#!/usr/bin/env python
"""Run ledger & regression sentinel CLI (docs/OBSERVABILITY.md "Run
ledger & regression sentinel").

The read side of the telemetry layer: ingest the committed bench
history (``BENCH_r*.json`` / ``BENCH_MEASURED_r*.json``) and any
``*.manifest.json`` run manifests into typed per-run rollups
(telemetry/ledger.py), then

* **report** (default) — the r01→rNN per-row trajectory, the latest
  rollup per row, and every sentinel finding vs the committed baseline
  (``tools/obs_baseline.json``), staleness flagged with the queued
  re-measurement command attached;
* ``--scan DIR`` — also ingest the run manifests under ``DIR`` and
  anomaly-scan their artifacts (step-time spikes, MFU cliffs, goodput
  gaps, SLO-burn spikes — each cross-linked to the covering trace span
  and the latest flight bundle);
* ``--gate`` — exit 1 when any finding is ``regressed`` and its
  fingerprint is not suppressed in the baseline (the PR gate; run from
  tier-1 by tests/test_obs_ledger.py);
* ``--drift`` — join the planner's evidence blocks (planner/audit.py)
  with measured rollups into plan-vs-actual drift ratios (ROADMAP
  item 3's calibration input);
* ``--write-baseline`` — re-pin the baseline to the current rollups
  (suppress list and comment are preserved).

Verdict vocabulary (frozen in telemetry/ledger.py, linted by
tools/telemetry_check.py): ``improved`` / ``flat`` / ``regressed`` /
``new`` / ``missing`` / ``stale``.  Only ``regressed`` gates.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

DEFAULT_BASELINE = os.path.join(REPO, "tools", "obs_baseline.json")


def _ledger():
    from deepspeed_tpu.telemetry import ledger

    return ledger


def collect_rollups(scan_dir: Optional[str],
                    with_history: bool = True) -> List[Dict[str, Any]]:
    """History rollups (committed BENCH files) + one rollup per run
    manifest under ``scan_dir``."""
    led = _ledger()
    rollups: List[Dict[str, Any]] = []
    if with_history:
        rollups.extend(led.load_bench_history(REPO))
    for path in sorted(glob.glob(
            os.path.join(scan_dir or "", "*.manifest.json"))):
        try:
            rollups.append(led.rollup_from_manifest(path))
        except Exception as e:  # noqa: BLE001 — one bad manifest ≠ no report
            print(f"obs_report: skipping unreadable manifest {path}: {e}",
                  file=sys.stderr)
    return rollups


def scan_anomalies(scan_dir: str) -> List[Dict[str, Any]]:
    led = _ledger()
    out: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(scan_dir,
                                              "*.manifest.json"))):
        try:
            out.extend(led.scan_manifest(path))
        except Exception as e:  # noqa: BLE001
            print(f"obs_report: anomaly scan failed for {path}: {e}",
                  file=sys.stderr)
    return out


def drift_report(rollups: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Plan-vs-actual: the planner's evidence for each audit row joined
    with that row's latest measured rollup.  Import-guarded — a broken
    planner must not take the sentinel down with it."""
    led = _ledger()
    try:
        from deepspeed_tpu.planner.audit import PLAN_AUDIT_ROWS, plan_for_row
    except Exception as e:  # noqa: BLE001
        print(f"obs_report: planner unavailable, no drift report ({e})",
              file=sys.stderr)
        return []
    latest = led.latest_rollups(rollups)
    # manifest rollups carry the actual-side signals (step-time p50,
    # HBM watermark, comm census) that summary-only history rows lack —
    # prefer them per row
    measured = led.latest_rollups(
        [r for r in rollups if r.get("source") == "manifest"])
    out: List[Dict[str, Any]] = []
    for name in PLAN_AUDIT_ROWS:
        rollup = measured.get(name) or latest.get(name)
        if rollup is None:
            continue
        plan = plan_for_row(name)
        if not plan.ranked:
            continue
        out.extend(led.plan_drift(rollup, plan.ranked[0].evidence))
    return out


def _trend(rollups: List[Dict[str, Any]]) -> Dict[str, Dict[int, Any]]:
    """{row: {round: value}} for the history rollups (trajectory view)."""
    out: Dict[str, Dict[int, Any]] = {}
    for r in rollups:
        if r.get("source") != "chip" or r.get("round") is None:
            continue
        cell = "ERR" if r.get("error") else r.get("value")
        out.setdefault(r["row"], {})[int(r["round"])] = cell
    return out


def build_report(args) -> Dict[str, Any]:
    led = _ledger()
    rollups = collect_rollups(args.scan, with_history=not args.no_history)
    baseline = led.load_baseline(args.baseline)
    requeue = led.attach_requeue_cmds(rollups, led.collect_queued_cmds(REPO))
    findings = led.diff_rollups(rollups, baseline, requeue)
    gate = led.gate_findings(findings, baseline.get("suppress", ()))
    report: Dict[str, Any] = {
        "baseline": args.baseline,
        "rollups": len(rollups),
        "rows": sorted({r["row"] for r in rollups}),
        "trend": _trend(rollups),
        "latest": {k: v for k, v in sorted(
            led.latest_rollups(rollups).items())},
        "stale_rows": requeue,
        "findings": findings,
        "gate_failures": gate,
        "anomalies": scan_anomalies(args.scan) if args.scan else [],
        "drift": drift_report(rollups) if args.drift else [],
    }
    return report


def _fmt_val(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def print_report(report: Dict[str, Any]) -> None:
    trend = report["trend"]
    rounds = sorted({rnd for cells in trend.values() for rnd in cells})
    if rounds:
        print("== trajectory (primary value per row per round) ==")
        head = "row".ljust(26) + " ".join(f"r{r:02d}".rjust(9)
                                          for r in rounds)
        print(head)
        for row in sorted(trend):
            cells = trend[row]
            print(row.ljust(26) + " ".join(
                _fmt_val(cells.get(r)).rjust(9) for r in rounds))
    print(f"\n== rollups: {report['rollups']} across "
          f"{len(report['rows'])} rows ==")
    if report["stale_rows"]:
        print("\n== stale rows (carried forward; re-measure with) ==")
        for row, cmd in sorted(report["stale_rows"].items()):
            print(f"  {row}: {cmd}")
    counts: Dict[str, int] = {}
    for f in report["findings"]:
        counts[f["verdict"]] = counts.get(f["verdict"], 0) + 1
    print("\n== sentinel vs " + os.path.relpath(report["baseline"], REPO)
          + " ==")
    print("  " + (", ".join(f"{k}: {v}" for k, v in sorted(counts.items()))
                  or "no findings"))
    for f in report["findings"]:
        if f["verdict"] in ("regressed", "improved", "missing"):
            print(f"  [{f['verdict']}] {f['row']}.{f['metric']}: "
                  f"{_fmt_val(f['baseline'])} -> {_fmt_val(f['current'])}"
                  f" (fp {f['fingerprint']})")
    if report["anomalies"]:
        print(f"\n== anomalies ({len(report['anomalies'])}) ==")
        for a in report["anomalies"]:
            where = f" tier={a['tier']}" if a.get("tier") else ""
            span = a.get("trace_span") or {}
            link = f" span={span.get('name')}" if span else ""
            print(f"  [{a['kind']}] step {a['step']}{where}: "
                  f"{_fmt_val(a['value'])} vs {_fmt_val(a['threshold'])}"
                  f"{link} (run {a['run_id']})")
    if report["drift"]:
        print(f"\n== plan-vs-actual drift ({len(report['drift'])}) ==")
        for d in report["drift"]:
            print(f"  {d['row']}.{d['metric']}: predicted "
                  f"{_fmt_val(d['predicted'])} actual "
                  f"{_fmt_val(d['actual'])} ratio {d['ratio']}")
    if report["gate_failures"]:
        print(f"\nGATE: {len(report['gate_failures'])} unbaselined "
              f"regression(s)")
        for f in report["gate_failures"]:
            print(f"  {f['row']}.{f['metric']} fp {f['fingerprint']}")
    else:
        print("\nGATE: clean")


def write_baseline(args, report: Dict[str, Any]) -> None:
    led = _ledger()
    old = led.load_baseline(args.baseline)
    rollups = collect_rollups(args.scan, with_history=not args.no_history)
    rows: Dict[str, Dict[str, float]] = {}
    smoke_rows: Dict[str, Dict[str, float]] = {}
    # partition before taking latest — a chip history row must not
    # shadow the smoke run of the same name (ledger.diff_rollups does
    # the same split when comparing)
    for smoke_flag, dest in ((False, rows), (True, smoke_rows)):
        subset = [r for r in rollups
                  if bool(r.get("smoke")) == smoke_flag]
        for row, rollup in led.latest_rollups(subset).items():
            flat = led.flatten_metrics(rollup)
            if flat:
                dest[row] = flat
    doc = {
        "comment": old.get("comment",
                           "Pinned by tools/obs_report.py --write-baseline; "
                           "rows = chip history, smoke_rows = deterministic "
                           "smoke metrics only, suppress = acknowledged "
                           "finding fingerprints."),
        "rows": rows,
        "smoke_rows": smoke_rows or old.get("smoke_rows", {}),
        "suppress": sorted(old.get("suppress", [])),
    }
    tmp = f"{args.baseline}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, args.baseline)
    print(f"obs_report: wrote {args.baseline} "
          f"({len(rows)} rows, {len(doc['smoke_rows'])} smoke rows)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scan", metavar="DIR", default=None,
                    help="ingest + anomaly-scan run manifests under DIR")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON (default tools/obs_baseline.json)")
    ap.add_argument("--gate", action="store_true",
                    help="exit 1 on unbaselined regressions")
    ap.add_argument("--drift", action="store_true",
                    help="plan-vs-actual drift report (planner join)")
    ap.add_argument("--no-history", action="store_true",
                    help="skip the committed BENCH_r* history")
    ap.add_argument("--write-baseline", action="store_true",
                    help="re-pin the baseline to the current rollups")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print the full report as JSON")
    args = ap.parse_args(argv)

    report = build_report(args)
    if args.write_baseline:
        write_baseline(args, report)
        return 0
    if args.as_json:
        print(json.dumps(report, indent=1, sort_keys=True, default=float))
    else:
        print_report(report)
    if args.gate and report["gate_failures"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
