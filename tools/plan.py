#!/usr/bin/env python
"""dstpu-plan — parallelism plan compiler CLI (docs/PLANNER.md).

Thin launcher for :mod:`deepspeed_tpu.planner.cli`::

    python tools/plan.py --model gpt2-6.7b --chips 1 --hbm 16GiB \
        --host-ram 64GiB --nvme --seq 512 --json plan.json
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from deepspeed_tpu.planner.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
