"""On-chip micro-benchmarks for the Pallas device kernels vs their XLA/jnp
equivalents: fused AdamW step and blockwise int8 quantize.  Each variant
iterates K times INSIDE one jit (lax.scan) so a single dispatch amortizes
the axon-tunnel round-trip — timing eager per-call dispatch swamps the
kernel (measured: ~55 ms/dispatch vs ~12 ms of real memory traffic).
The docstrings in ops/pallas/{fused_optimizer,quantize}.py cite these
numbers.  Not part of the suite."""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax

ITERS = 30


def timeit(f, *args):
    """f must iterate ITERS times inside one jit AND return a scalar —
    fetching any full-size array ships it through the axon tunnel and the
    download (~25 ms per 100 MB) swamps the kernel time."""
    r = f(*args)
    assert getattr(r, "ndim", 0) == 0, "bench fns must reduce to a scalar"
    float(np.asarray(r))
    t0 = time.perf_counter()
    float(np.asarray(f(*args)))
    return (time.perf_counter() - t0) / ITERS


def bench_adamw():
    from deepspeed_tpu.runtime.optimizers import build_optimizer

    rng = np.random.default_rng(0)
    shapes = {"wte": (50257, 1024), "h": (24, 1024, 4096),
              "h2": (24, 4096, 1024), "qkv": (24, 1024, 3072),
              "ln": (48, 1024)}
    params = {k: jnp.asarray(rng.standard_normal(s), jnp.float32)
              for k, s in shapes.items()}
    grads = {k: jnp.asarray(rng.standard_normal(s), jnp.float32)
             for k, s in shapes.items()}
    n = sum(int(np.prod(s)) for s in shapes.values())
    bytes_moved = n * 4 * 7  # read p,g,m,v; write p,m,v

    for label, cfg in [("optax", {}), ("pallas", {"pallas_fused": True})]:
        opt = build_optimizer("adamw", dict({"weight_decay": 0.01}, **cfg))
        state = opt.init(params)

        @jax.jit
        def run(g, s, p):
            def body(carry, _):
                p_, s_ = carry
                p2, s2 = opt.update(g, s_, p_, 1e-4)
                return (p2, s2), ()

            (p, s), _ = lax.scan(body, (p, s), None, length=ITERS)
            return sum(jnp.sum(x) for x in jax.tree.leaves(p))

        dt = timeit(run, grads, state, params)
        print(f"adamw/{label}: {dt*1e3:.2f} ms/step  "
              f"({bytes_moved/dt/1e9:.0f} GB/s effective, {n/1e6:.0f}M "
              f"params)", flush=True)


def bench_quantize():
    from deepspeed_tpu.ops import quantizer as qz

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((8192, 8192)), jnp.bfloat16)
    nbytes = x.size * 2 + x.size + x.size // 256 * 4

    for label, backend in [("jnp", "jnp"), ("pallas", "pallas")]:

        @jax.jit
        def roundtrip(t):
            # chain the round-trips so scan cannot elide iterations
            def body(cur, _):
                q, s, _ = qz.quantize_blockwise(cur, 8, 256, backend=backend)
                return qz.dequantize_blockwise(
                    q, s, dtype=jnp.bfloat16, backend=backend), ()

            out, _ = lax.scan(body, t, None, length=ITERS)
            return jnp.sum(out.astype(jnp.float32))

        dt = timeit(roundtrip, x)
        print(f"quant+dequant/{label}: {dt*1e3:.2f} ms/iter  "
              f"({2*nbytes/dt/1e9:.0f} GB/s effective, {x.size/1e6:.0f}M "
              f"elems)", flush=True)

        @jax.jit
        def fq(t):
            def body(cur, _):
                return qz.fake_quantize(cur, 8, 256, backend=backend), ()

            out, _ = lax.scan(body, t, None, length=ITERS)
            return jnp.sum(out.astype(jnp.float32))

        dt = timeit(fq, x)
        print(f"fake_quantize/{label}: {dt*1e3:.2f} ms/iter", flush=True)


if __name__ == "__main__":
    print(f"backend: {jax.default_backend()}  devices: {jax.devices()}")
    bench_adamw()
    bench_quantize()
