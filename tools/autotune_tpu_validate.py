"""Validate the autotuner's trial ordering on real TPU hardware.

The round-3 verdict flagged that autotuner trials had only ever executed
on the virtual CPU mesh, so the throughput ordering it optimizes was
never checked against the chip.  This tool runs a grid sweep
(micro-batch × ZeRO stage, gpt2-125m @ seq 512) with the SAME trial
machinery (crash-isolated subprocesses → deepspeed_tpu.autotuning.
trial_runner) on the live TPU backend, then reports:

* the measured throughput ranking,
* whether the model-based mode's predicted first choice (largest
  micro-batch, highest stage) is the measured winner or within 10%.

Writes ``AUTOTUNE_TPU.json`` at the repo root for the record.
Not part of the suite (needs the chip).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax

    from deepspeed_tpu.autotuning.autotuner import Autotuner
    from deepspeed_tpu.models import get_model_config

    assert jax.default_backend() != "cpu", "needs the TPU backend"
    model = get_model_config("gpt2-125m", max_seq_len=512)
    base = {
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "steps_per_print": 10_000,
        "activation_checkpointing": {"remat_policy": "dots_flash_saveable"},
    }
    tuner = Autotuner(model, base, seq_len=512, mode="grid",
                      steps_per_trial=4, max_trials=12,
                      trial_timeout=420.0)
    best, results = tuner.tune(patience=100)

    rows = sorted((r for r in results), key=lambda r: -r.throughput)
    report = {"device": str(jax.devices()[0]),
              "space": "grid micro_batch x zero_stage, gpt2-125m seq512",
              "results": [
                  {"cand": r.config,
                   "tokens_per_sec": round(r.throughput * 512, 1),
                   # failed trials carry inf — not valid strict JSON
                   "step_seconds": None if r.step_seconds == float("inf")
                   else round(r.step_seconds, 4),
                   "error": r.error}
                  for r in rows]}
    # model-based prediction = head of the model_based ordering
    pred = Autotuner(model, base, seq_len=512, mode="model_based",
                     max_trials=1)._space()
    report["model_based_first_choice"] = pred[0] if pred else None
    if rows and pred:
        measured_best = report["results"][0]["cand"]
        within = [r for r in report["results"]
                  if r["cand"] == pred[0] and r["tokens_per_sec"] >=
                  0.9 * report["results"][0]["tokens_per_sec"]]
        report["prediction_is_winner"] = measured_best == pred[0]
        report["prediction_within_10pct"] = bool(within)
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "AUTOTUNE_TPU.json")
    with open(out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report, indent=1))


if __name__ == "__main__":
    main()
