#!/usr/bin/env python3
"""Minimal end-to-end training example (the DeepSpeedExamples analog).

Run single-host::

    python examples/train_lm.py --model llama-tiny \
        --deepspeed_config examples/ds_config_zero3_bf16.json --steps 50

or through the launcher (multi-process/multi-host)::

    bin/dstpu --num_nodes 1 examples/train_lm.py --deepspeed_config ...
"""

import argparse
import sys

import numpy as np

import deepspeed_tpu as ds
from deepspeed_tpu.models import get_model_config


def synthetic_batches(vocab, rows, seq, steps, seed=0):
    rng = np.random.default_rng(seed)
    for _ in range(steps):
        ids = rng.integers(0, vocab, size=(rows, seq + 1), dtype=np.int32)
        yield {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="gpt2-tiny")
    ap.add_argument("--deepspeed_config", "--config", dest="config",
                    default=None)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--save_dir", default=None)
    ap.add_argument("--local_rank", type=int, default=-1)  # launcher parity
    args = ap.parse_args(argv)

    model = get_model_config(args.model)
    config = args.config or {
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    rows = engine.train_batch_size_value
    for step, batch in enumerate(
            synthetic_batches(model.vocab_size, rows, args.seq, args.steps)):
        loss = engine.train_batch(batch)
        if step % 10 == 0:
            print(f"step {step}: loss {float(np.asarray(loss)):.4f}")
    if args.save_dir:
        engine.save_checkpoint(args.save_dir)
    print(f"done: final loss {float(np.asarray(loss)):.4f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
