"""Model families: OPT, Mistral (sliding window), Qwen2 (qkv bias),
Falcon (MQA + parallel block), Phi (partial rotary), Bloom (ALiBi),
GPT-J (interleaved rotary), GPT-NeoX, GPT-Neo (alternating local
attention), BERT/DistilBERT (encoders).

Mirrors the reference's per-arch inference/v2 model implementations
(inference/v2/model_implementations/) exercised through training and the
ragged inference engine."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import get_model_config, init_params, list_models
from deepspeed_tpu.models import transformer as tf

FAMILIES = ["opt-tiny", "mistral-tiny", "qwen2-tiny", "falcon-tiny",
            "phi-tiny", "bloom-tiny", "gptj-tiny", "gptneox-tiny",
            "gptneo-tiny", "bert-tiny", "distilbert-tiny"]


def _reset_topo():
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None


def test_registry_has_families():
    names = list_models()
    for big in ["opt-125m", "opt-1.3b", "mistral-7b", "qwen2-7b",
                "falcon-7b", "phi-2"]:
        assert big in names


@pytest.mark.parametrize("name", FAMILIES)
def test_forward_shapes_and_finite(name):
    cfg = get_model_config(name).replace(dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(2, 16)), jnp.int32)
    logits = tf.forward(params, ids, cfg)
    if isinstance(logits, tuple):
        logits = logits[0]
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits, np.float32)).all()


def test_family_param_structure():
    opt = get_model_config("opt-tiny")
    p = init_params(opt, jax.random.PRNGKey(0))
    assert "positions" in p["embed"]  # learned positions
    assert "bq" in p["layers"]["attn"] and "bo" in p["layers"]["attn"]
    qwen = get_model_config("qwen2-tiny")
    p = init_params(qwen, jax.random.PRNGKey(0))
    assert "bq" in p["layers"]["attn"]      # qkv bias
    assert "bo" not in p["layers"]["attn"]  # but no out-proj bias
    falcon = get_model_config("falcon-tiny")
    assert falcon.kv_heads == 1  # multi-query
    p = init_params(falcon, jax.random.PRNGKey(0))
    assert "bq" not in p["layers"]["attn"]


def test_sliding_window_masks_far_keys():
    cfg = get_model_config("mistral-tiny").replace(
        dtype=jnp.float32, sliding_window=8, num_layers=1)
    params = init_params(cfg, jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(1, 64)), jnp.int32)
    base = tf.forward(params, ids, cfg)
    # perturb a token far outside the window of the last position
    ids2 = ids.at[0, 0].set((ids[0, 0] + 1) % cfg.vocab_size)
    out2 = tf.forward(params, ids2, cfg)
    # last position (63) sees keys 56..63 only → logits unchanged there
    np.testing.assert_allclose(np.asarray(base[0, -1]),
                               np.asarray(out2[0, -1]), atol=1e-5)
    # but an in-window position is affected
    assert np.abs(np.asarray(base[0, 0]) - np.asarray(out2[0, 0])).max() > 1e-4


def test_partial_rotary_rotates_prefix_only():
    cfg = get_model_config("phi-tiny").replace(dtype=jnp.float32)
    d = cfg.dim_per_head
    rot_d = max(2, int(d * cfg.rotary_pct) // 2 * 2)
    q = jnp.ones((1, 4, 2, d), jnp.float32)
    k = jnp.ones((1, 4, 2, d), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(4)[None], (1, 4))
    q2, _ = tf._rope(q, k, pos, cfg)
    # pass-through tail unchanged; rotated prefix changed for pos > 0
    np.testing.assert_allclose(np.asarray(q2[..., rot_d:]), 1.0, atol=1e-6)
    assert np.abs(np.asarray(q2[0, 1:, :, :rot_d]) - 1.0).max() > 1e-3


@pytest.mark.parametrize("name", ["opt-tiny", "falcon-tiny"])
def test_families_train(name):
    model = get_model_config(name)
    cfg = {"train_micro_batch_size_per_gpu": 4,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW", "params": {"lr": 5e-3}},
           "mesh": {"data": 1}}
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(4, 17), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
    losses = [float(np.asarray(engine.train_batch(batch))) for _ in range(8)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]  # learns the fixed batch
    _reset_topo()


@pytest.mark.parametrize("name", ["mistral-tiny", "phi-tiny"])
def test_families_ragged_inference(name):
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2

    model = get_model_config(name)
    eng = InferenceEngineV2(model, dtype="float32", max_context=256,
                            memory_config={"num_blocks": 64, "block_size": 16})
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, model.vocab_size, size=(6,)).tolist()
    out = eng.generate([prompt], max_new_tokens=4)
    assert len(out[0]) == 4  # generate returns the new tokens
    assert all(0 <= t < model.vocab_size for t in out[0])
    _reset_topo()


def test_gptneo_alt_window_trains():
    """GPT-Neo's alternating global/local attention trains through the
    paired grouped scan (static per-member window)."""
    import deepspeed_tpu as ds

    model = get_model_config("gptneo-tiny")
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config, seed=3)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(16, 33), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    losses = [float(np.asarray(engine.train_batch(batch)))
              for _ in range(4)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0], losses
    _reset_topo()
