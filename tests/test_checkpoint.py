"""Checkpoint tests: universal format, elasticity across mesh shapes, fp32
consolidation, orbax sharded/async engine (ref test model:
tests/unit/checkpoint/ incl. test_universal_checkpoint.py)."""

import os
import pickle

import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.checkpoint.universal import ds_to_universal, load_universal, zero_to_fp32
from deepspeed_tpu.models import get_model_config
from tests.conftest import make_lm_batch


def _cfg(mesh, **over):
    cfg = {
        "train_batch_size": 8,
        "train_micro_batch_size_per_gpu": 8 // (mesh.get("data", 1) * mesh.get("expert", 1)),
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "steps_per_print": 1000,
        "mesh": mesh,
    }
    cfg.update(over)
    return cfg


def _mk_engine(model, cfg, seed=3):
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None
    engine, _, _, _ = ds.initialize(model=model, config=cfg, seed=seed)
    return engine


def _train(engine, batches):
    return [float(np.asarray(engine.train_batch(b))) for b in batches]


@pytest.fixture
def trained(tmp_path):
    model = get_model_config("gpt2-tiny")
    engine = _mk_engine(model, _cfg({"data": 8}))
    rng = np.random.default_rng(0)
    batch = make_lm_batch(rng, 8, 16, model.vocab_size)
    _train(engine, [batch] * 3)
    engine.save_checkpoint(str(tmp_path), tag="ck")
    return model, engine, batch, str(tmp_path)


def test_universal_elastic_reload(trained):
    """Save under data:8, reload universally under data:4 x tensor:2 — the
    world-size elasticity the reference needs UCP for."""
    model, engine, batch, ckdir = trained
    udir = ds_to_universal(ckdir, tag="ck")
    assert os.path.exists(os.path.join(udir, "meta.json"))

    engine2 = _mk_engine(model, _cfg({"data": 4, "tensor": 2}), seed=99)
    load_universal(engine2, udir)
    assert engine2.global_steps == 3
    # identical continuation numerics despite resharding
    cont_a = _train(engine, [batch] * 2)
    cont_b = _train(engine2, [batch] * 2)
    np.testing.assert_allclose(cont_a, cont_b, rtol=2e-4, atol=2e-4)


def test_universal_via_config_flag(trained):
    model, _, _, ckdir = trained
    udir = ds_to_universal(ckdir, tag="ck")
    cfg = _cfg({"data": 2, "seq": 2}, load_universal_checkpoint=True)
    engine2 = _mk_engine(model, cfg, seed=11)
    engine2.load_checkpoint(udir)
    assert engine2.global_steps == 3


def test_zero_to_fp32(trained, tmp_path):
    model, engine, _, ckdir = trained
    out = zero_to_fp32(ckdir, str(tmp_path / "fp32.pkl"), tag="ck")
    with open(out, "rb") as f:
        flat = pickle.load(f)
    assert all(v.dtype == np.float32 for v in flat.values())
    assert "embed/tokens" in flat
    assert flat["embed/tokens"].shape == (model.vocab_size, model.hidden_size)


def test_universal_shape_mismatch_raises(trained):
    model, _, _, ckdir = trained
    udir = ds_to_universal(ckdir, tag="ck")
    other = get_model_config("gpt2-tiny", hidden_size=64, num_heads=2)
    engine2 = _mk_engine(other, _cfg({"data": 8}), seed=1)
    with pytest.raises(ValueError, match="shape mismatch"):
        load_universal(engine2, udir)


def test_orbax_engine_roundtrip(tmp_path):
    model = get_model_config("gpt2-tiny")
    cfg = _cfg({"data": 8}, checkpoint={"writer": {"type": "orbax"}})
    engine = _mk_engine(model, cfg)
    rng = np.random.default_rng(0)
    batch = make_lm_batch(rng, 8, 16, model.vocab_size)
    a = _train(engine, [batch] * 3)
    engine.save_checkpoint(str(tmp_path), tag="ob")

    engine2 = _mk_engine(model, cfg, seed=77)
    engine2.load_checkpoint(str(tmp_path), tag="ob")
    assert engine2.global_steps == 3
    cont_a = _train(engine, [batch] * 2)
    cont_b = _train(engine2, [batch] * 2)
    np.testing.assert_allclose(cont_a, cont_b, rtol=1e-5, atol=1e-5)


def test_orbax_async_save(tmp_path):
    model = get_model_config("gpt2-tiny")
    cfg = _cfg({"data": 8}, checkpoint={"writer": {"type": "orbax"}, "async_save": True})
    engine = _mk_engine(model, cfg)
    rng = np.random.default_rng(0)
    batch = make_lm_batch(rng, 8, 16, model.vocab_size)
    _train(engine, [batch] * 2)
    engine.save_checkpoint(str(tmp_path), tag="as")
    # training continues while the save commits in the background
    _train(engine, [batch] * 2)
    engine.checkpoint_engine.wait()
    engine2 = _mk_engine(model, cfg, seed=5)
    engine2.load_checkpoint(str(tmp_path), tag="as")
    assert engine2.global_steps == 2
