"""Parity tests for the Pallas fused optimizer steps
(deepspeed_tpu/ops/pallas/fused_optimizer.py) against the default optax
chain, run through the Pallas interpreter on CPU.  Ref kernel family:
csrc/adam/multi_tensor_adam.cu, csrc/lion (SURVEY §2.4 [NATIVE])."""

import importlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

fo = importlib.import_module("deepspeed_tpu.ops.pallas.fused_optimizer")
from deepspeed_tpu.runtime.optimizers import build_optimizer


@pytest.fixture(autouse=True)
def _interpret_mode():
    old = fo.INTERPRET
    fo.INTERPRET = True
    yield
    fo.INTERPRET = old


def _tree(seed=0):
    rng = np.random.default_rng(seed)
    # one servable 2-D leaf, one servable flat leaf, one unservable (odd)
    return {
        "w": jnp.asarray(rng.standard_normal((32, 256)), jnp.float32),
        "emb": jnp.asarray(rng.standard_normal((2048,)), jnp.float32),
        "bias": jnp.asarray(rng.standard_normal((7,)), jnp.float32),
    }


def _grads(seed=1):
    rng = np.random.default_rng(seed)
    return jax.tree.map(
        lambda p: jnp.asarray(rng.standard_normal(p.shape), jnp.float32),
        _tree())


@pytest.mark.parametrize("wd", [0.01, 0.0])
def test_fused_adamw_matches_optax(wd):
    cfg = {"betas": (0.9, 0.999), "eps": 1e-8, "weight_decay": wd}
    ref = build_optimizer("adamw", dict(cfg))
    fused = build_optimizer("adamw", dict(cfg, pallas_fused=True))
    assert fused.name == "fused_adamw"
    p_r, p_f = _tree(), _tree()
    s_r, s_f = ref.init(p_r), fused.init(p_f)
    for step in range(3):
        g = _grads(step)
        p_r, s_r = ref.update(g, s_r, p_r, 1e-3)
        p_f, s_f = fused.update(g, s_f, p_f, 1e-3)
    for k in p_r:
        np.testing.assert_allclose(np.asarray(p_f[k]), np.asarray(p_r[k]),
                                   rtol=2e-6, atol=2e-7, err_msg=k)
    # state trees are interchangeable (same structure, same values)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-6, atol=2e-7), s_f, s_r)


def test_fused_lion_matches_optax():
    cfg = {"betas": (0.9, 0.99), "weight_decay": 0.1}
    ref = build_optimizer("lion", dict(cfg))
    fused = build_optimizer("lion", dict(cfg, pallas_fused=True))
    p_r, p_f = _tree(), _tree()
    s_r, s_f = ref.init(p_r), fused.init(p_f)
    for step in range(3):
        g = _grads(10 + step)
        p_r, s_r = ref.update(g, s_r, p_r, 3e-4)
        p_f, s_f = fused.update(g, s_f, p_f, 3e-4)
    for k in p_r:
        np.testing.assert_allclose(np.asarray(p_f[k]), np.asarray(p_r[k]),
                                   rtol=2e-6, atol=2e-7, err_msg=k)


def test_fused_adamw_checkpoint_interchange():
    """A state produced by the optax path resumes under the fused path."""
    cfg = {"weight_decay": 0.01}
    ref = build_optimizer("adamw", dict(cfg))
    fused = build_optimizer("adamw", dict(cfg, pallas_fused=True))
    p = _tree()
    s = ref.init(p)
    p1, s1 = ref.update(_grads(0), s, p, 1e-3)
    # hand optax-produced state to the fused path
    p2_f, s2_f = fused.update(_grads(1), s1, p1, 1e-3)
    p2_r, s2_r = ref.update(_grads(1), s1, p1, 1e-3)
    for k in p2_r:
        np.testing.assert_allclose(np.asarray(p2_f[k]), np.asarray(p2_r[k]),
                                   rtol=2e-6, atol=2e-7)


def test_sharded_params_downgrades_pallas_fused():
    """With sharded params/opt-state the fused kernel path is refused at
    build time (a pallas_call is unpartitionable under GSPMD — it would
    replicate p/g/m/v per leaf, defeating ZeRO partitioning)."""
    opt = build_optimizer("adamw", {"pallas_fused": True},
                          sharded_params=True)
    assert opt.name == "adamw"  # not fused_adamw
    opt = build_optimizer("lion", {"pallas_fused": True},
                          sharded_params=True)
    assert opt.name == "lion"


@pytest.mark.parametrize("opt", ["adamw", "lion"])
def test_fused_state_dtype_stable_nonfp32(opt):
    """Non-fp32 leaves never hit the Pallas kernel (its fp32 out_shape
    aliases onto the param-dtype mu/nu); the jnp fallback computes in the
    state dtype like the optax chain, so values track the optax path and
    the state dtype stays stable — checkpoints stay interchangeable."""
    cfg = {"weight_decay": 0.01}
    fused = build_optimizer(opt, dict(cfg, pallas_fused=True))
    ref = build_optimizer(opt, dict(cfg))
    to_bf16 = lambda t: jax.tree.map(lambda x: x.astype(jnp.bfloat16), t)
    p_f, p_r = to_bf16(_tree()), to_bf16(_tree())
    s_f, s_r = fused.init(p_f), ref.init(p_r)
    for step in range(3):
        g = to_bf16(_grads(step))
        p_f, s_f = fused.update(g, s_f, p_f, 1e-3)
        p_r, s_r = ref.update(g, s_r, p_r, 1e-3)
    for leaf in jax.tree.leaves(s_f[0].mu) + jax.tree.leaves(p_f):
        assert leaf.dtype == jnp.bfloat16, leaf.dtype
    # trajectory parity with the optax chain in bf16 (loose tolerance:
    # associativity of the fused expression differs slightly)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a, np.float32), np.asarray(b, np.float32),
        rtol=0.05, atol=1e-3), p_f, p_r)


def test_engine_trains_with_pallas_fused_zero1():
    """Under ZeRO-1 (sharded optimizer state on the 8-device mesh) the
    fused path's per-leaf routing must fall back to the jnp math (a
    pallas_call would not partition under GSPMD) and train losslessly —
    same numerics contract as the optax default."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.parallel import topology

    model = get_model_config("gpt2-tiny")
    losses = {}
    for label, params in (("fused", {"lr": 1e-3, "pallas_fused": True}),
                          ("optax", {"lr": 1e-3})):
        config = {
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "AdamW", "params": dict(params)},
            "zero_optimization": {"stage": 1},
            "steps_per_print": 10_000,
        }
        engine, _, _, _ = ds.initialize(model=model, config=config, seed=7)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, model.vocab_size, size=(16, 33),
                           dtype=np.int32)
        batch = {"input_ids": ids[:, :-1],
                 "labels": ids[:, 1:].astype(np.int32)}
        losses[label] = [float(np.asarray(engine.train_batch(batch)))
                         for _ in range(4)]
        topology._GLOBAL_TOPOLOGY = None
    np.testing.assert_allclose(losses["fused"], losses["optax"],
                               rtol=1e-5, atol=1e-6)
    assert losses["fused"][-1] < losses["fused"][0]


def test_engine_trains_with_pallas_fused():
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.parallel import topology

    model = get_model_config("gpt2-tiny")
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW",
                      "params": {"lr": 1e-3, "pallas_fused": True}},
        "zero_optimization": {"stage": 0},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    # conftest runs 8 virtual devices → dp=8, so a full batch is 2*8 rows
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(16, 33), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    losses = [float(np.asarray(engine.train_batch(batch))) for _ in range(5)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
    topology._GLOBAL_TOPOLOGY = None
