"""Unified telemetry layer (deepspeed_tpu/telemetry/): StepRecord JSONL,
shared registry primitives, Prometheus export, auto-capture overlap
reports, and the satellite fixes that feed them (timer reset semantics,
comms volume clamp, flops-profiler degradation)."""

import json
import os
import time
import types

import numpy as np
import pytest

from deepspeed_tpu.telemetry import (EXPORT_TAGS, MetricsRegistry,
                                     StepRecord, Telemetry,
                                     build_capture_report,
                                     events_from_record, read_jsonl,
                                     render_prometheus)
from deepspeed_tpu.telemetry.registry import Counter, Gauge, Histogram


# ----------------------------------------------------------------------
# registry primitives
# ----------------------------------------------------------------------
def test_registry_get_or_create_shares_instances():
    reg = MetricsRegistry()
    c1 = reg.counter("x_total", "help")
    c2 = reg.counter("x_total")
    assert c1 is c2
    with pytest.raises(TypeError):
        reg.gauge("x_total")
    with pytest.raises(ValueError):
        reg.counter("bad name")
    with pytest.raises(ValueError):
        c1.inc(-1)


def test_histogram_percentiles_match_numpy():
    reg = MetricsRegistry()
    h = reg.histogram("lat_seconds")
    xs = list(range(1, 101))
    for x in xs:
        h.observe(float(x))
    snap = h.snapshot()
    assert snap["count"] == 100
    assert snap["p50"] == pytest.approx(np.percentile(xs, 50))
    assert snap["p95"] == pytest.approx(np.percentile(xs, 95))
    assert snap["p99"] == pytest.approx(np.percentile(xs, 99))
    assert snap["mean"] == pytest.approx(np.mean(xs))
    # empty histogram snapshots to zeros, not NaN/crash
    empty = reg.histogram("empty_seconds").snapshot()
    assert empty == {"p50": 0.0, "p95": 0.0, "p99": 0.0,
                     "mean": 0.0, "count": 0}


def test_histogram_window_bounds_memory_but_count_is_lifetime():
    h = Histogram("h", window=4)
    for x in (1, 2, 3, 4, 100, 100, 100, 100):
        h.observe(x)
    snap = h.snapshot()
    assert snap["count"] == 8          # lifetime
    assert snap["p50"] == 100          # window holds only the last 4
    assert h.lifetime() == (8, 410.0)


def test_histogram_time_window_idle_p95_decays(monkeypatch):
    """max_age_s > 0: an idle histogram's percentiles fall back to zero
    once the last burst ages out — count stays lifetime (regression for
    the fleet sampler: an idle tier must not hold its last-burst p95)."""
    import deepspeed_tpu.telemetry.registry as reg_mod

    clock = {"t": 1000.0}
    monkeypatch.setattr(reg_mod.time, "monotonic", lambda: clock["t"])
    h = Histogram("h", max_age_s=30.0)
    for x in (5.0, 7.0, 9.0):
        h.observe(x)
    snap = h.snapshot()
    assert snap["count"] == 3 and snap["p95"] > 0.0
    clock["t"] += 31.0                       # burst ages out
    snap = h.snapshot()
    assert snap == {"p50": 0.0, "p95": 0.0, "p99": 0.0,
                    "mean": 0.0, "count": 3}
    h.observe(2.0)                           # fresh sample re-populates
    assert h.snapshot()["p95"] == 2.0
    assert h.lifetime() == (4, 23.0)
    # default (max_age_s=0) keeps the historical lifetime behavior
    h0 = Histogram("h0")
    h0.observe(5.0)
    clock["t"] += 1e6
    assert h0.snapshot()["p95"] == 5.0


def test_prometheus_rendering():
    reg = MetricsRegistry()
    reg.counter("steps_total", "steps").inc(3)
    reg.gauge("mfu").set(0.42)
    h = reg.histogram("lat_seconds")
    h.observe(1.0)
    h.observe(3.0)
    text = render_prometheus(reg)
    assert "# TYPE steps_total counter" in text
    assert "steps_total 3" in text
    assert "# TYPE mfu gauge" in text
    assert "mfu 0.42" in text
    assert "# TYPE lat_seconds summary" in text
    assert 'lat_seconds{quantile="0.5"}' in text
    assert "lat_seconds_count 2" in text
    assert "lat_seconds_sum 4" in text


# ----------------------------------------------------------------------
# StepRecord
# ----------------------------------------------------------------------
def test_step_record_derived_fields_and_sorted_json():
    rec = StepRecord(step=5, wall_time_s=0.5, tokens=1000,
                     flops_per_step=1e9, peak_flops_per_sec=1e12)
    assert rec.tokens_per_sec == pytest.approx(2000.0)
    assert rec.achieved_flops_per_sec == pytest.approx(2e9)
    assert 0.0 < rec.mfu <= 1.0
    d = json.loads(rec.to_json())
    assert d["schema"] == 3
    assert list(d.keys()) == sorted(d.keys())
    # mfu clamps at 1.0 even when "achieved" exceeds the peak estimate
    hot = StepRecord(step=1, wall_time_s=0.1, tokens=1,
                     flops_per_step=1e13, peak_flops_per_sec=1e12)
    assert hot.mfu == 1.0


def test_events_from_record_covers_export_tags():
    rec = StepRecord(step=2, wall_time_s=0.1, tokens=10,
                     flops_per_step=1e6, peak_flops_per_sec=1e12,
                     loss=1.5, grad_norm=0.3, lr=1e-3, loss_scale=1.0,
                     hbm={"device_0": {"bytes_in_use": 10,
                                       "peak_bytes_in_use": 20}},
                     comm={"all_reduce": {"count": 2, "bytes": 256}})
    events = events_from_record(rec)
    tags = {t for t, _, _ in events}
    assert tags == set(EXPORT_TAGS)
    by_tag = {t: v for t, v, _ in events}
    assert by_tag["telemetry/hbm_bytes_in_use"] == 10
    assert by_tag["telemetry/comm_bytes_total"] == 256
    assert all(s == 2 for _, _, s in events)


def test_telemetry_hub_jsonl_and_serving_record(tmp_path):
    from deepspeed_tpu.runtime.config import TelemetryConfig

    path = str(tmp_path / "steps.jsonl")
    tel = Telemetry(TelemetryConfig(enabled=True, jsonl_path=path))
    tel.set_flops(1e9, "analytic")
    tel.record_train_step(step=1, wall_time_s=0.25, tokens=512, loss=2.0,
                          skipped=False)
    tel.record_train_step(step=2, wall_time_s=0.25, tokens=512, loss=2.0,
                          skipped=True)
    tel.record_serving_step(3, {"tokens_out": 7, "tokens_per_sec": 14.0,
                                "ttft": {"p50": 0.1}})
    tel.close()
    recs = read_jsonl(path)
    assert [r["kind"] for r in recs] == ["train", "train", "serving"]
    assert recs[0]["goodput"] == 1.0
    assert recs[1]["goodput"] == 0.5 and recs[1]["skipped"] is True
    assert recs[2]["serving"]["ttft_p50"] == 0.1
    assert recs[2]["tokens"] == 7
    # registry reflects the same run
    assert tel.registry.get("telemetry_steps_total").value == 2
    assert tel.registry.get("telemetry_skipped_steps_total").value == 1


def test_should_record_interval_with_capture_override(tmp_path):
    from deepspeed_tpu.runtime.config import TelemetryConfig

    tel = Telemetry(TelemetryConfig(enabled=True, interval_steps=3))
    assert [s for s in range(1, 8) if tel.should_record(s)] == [3, 6]
    # a regression-triggered capture needs every step's wall time, so it
    # overrides the thinning
    tel2 = Telemetry(TelemetryConfig(
        enabled=True, interval_steps=5,
        capture={"enabled": True, "regression_factor": 2.0,
                 "output_dir": str(tmp_path)}))
    assert all(tel2.should_record(s) for s in range(1, 8))


def test_capture_override_ends_with_exhausted_budget(tmp_path):
    """Once the capture budget is spent, the regression override must
    stop defeating interval thinning (every later step would otherwise
    pay the hard sync + export forever)."""
    from deepspeed_tpu.runtime.config import TelemetryConfig

    tel = Telemetry(TelemetryConfig(
        enabled=True, interval_steps=4,
        capture={"enabled": True, "regression_factor": 2.0,
                 "budget": 1, "output_dir": str(tmp_path)}))
    assert tel.should_record(1)           # budget left → every step
    assert not tel.is_full_record_step(1)  # ...but observe-only
    assert tel.is_full_record_step(4)
    tel.capture.budget_left = 0
    assert not tel.should_record(1)       # thinning applies again
    assert tel.should_record(4)


def test_engine_comm_delta_excludes_prior_traffic():
    """StepRecord.comm must be the delta vs the engine's construction
    baseline, not the process-global cumulative totals."""
    from deepspeed_tpu.utils.comms_logging import get_comms_logger

    cl = get_comms_logger()
    was_enabled = cl.enabled
    cl.enabled = True
    try:
        cl.record("all_reduce", np.zeros((4,), np.float32), "data")
        # fake just the attributes _comm_delta reads
        from deepspeed_tpu.runtime.engine import DeepSpeedEngine

        eng = types.SimpleNamespace(_comms_baseline=cl.totals())
        assert DeepSpeedEngine._comm_delta(eng) == {}
        cl.record("all_reduce", np.zeros((8,), np.float32), "data")
        delta = DeepSpeedEngine._comm_delta(eng)
        assert delta == {"all_reduce": {"count": 1, "bytes": 32}}
    finally:
        cl.enabled = was_enabled


def test_stale_record_not_cross_checked_against_capture(tmp_path):
    """With interval-thinned telemetry the last record can predate the
    capture window — the report must omit the MFU cross-check rather
    than pair the trace with the wrong step."""
    from deepspeed_tpu.runtime.config import TelemetryCaptureConfig
    from deepspeed_tpu.telemetry.capture import AutoCapture

    cap = AutoCapture(TelemetryCaptureConfig(
        enabled=True, num_steps=1, output_dir=str(tmp_path)),
        telemetry=types.SimpleNamespace(last_record=StepRecord(step=10)))
    cap._armed_at = 15
    path = cap._write_report(str(tmp_path / "empty"))
    with open(path) as f:
        rep = json.load(f)
    assert "mfu_cross_check" not in rep
    assert "no StepRecord inside the capture window" in rep["note"]
    # an in-window record IS cross-checked, stamped with its step
    cap2 = AutoCapture(TelemetryCaptureConfig(
        enabled=True, num_steps=1, output_dir=str(tmp_path)),
        telemetry=types.SimpleNamespace(last_record=StepRecord(step=15)))
    cap2._armed_at = 15
    with open(cap2._write_report(str(tmp_path / "empty2"))) as f:
        rep2 = json.load(f)
    assert rep2["mfu_cross_check"]["record_step"] == 15


def test_record_train_step_feeds_capture_regression_window(tmp_path):
    """The hub is the single feed point for the trigger's trailing
    step-time window — a regression seen only via record_train_step
    must arm it (the engine passes no wall time to on_step_end)."""
    from deepspeed_tpu.runtime.config import TelemetryConfig

    tel = Telemetry(TelemetryConfig(
        enabled=True,
        capture={"enabled": True, "regression_factor": 2.0,
                 "budget": 1, "output_dir": str(tmp_path)}))
    for i in range(12):
        tel.record_train_step(step=i + 1, wall_time_s=0.1, tokens=1)
    assert not tel.capture._regressed()
    tel.record_train_step(step=13, wall_time_s=5.0, tokens=1)
    tel.record_train_step(step=14, wall_time_s=5.0, tokens=1)
    assert tel.capture._regressed()


def test_serving_metrics_import_stays_jax_free():
    """PR-2 invariant: serving/ itself uses no jax (the parent package
    __init__ pulls jax regardless — the invariant is about the serving
    and telemetry module code, so the jax-0.4.37 compat surface stays
    moot there).  The shared-registry refactor must therefore never load
    telemetry.capture (the only jax-tainted telemetry module; it imports
    utils.trace) as a side effect of importing serving metrics."""
    import subprocess
    import sys as _sys

    code = (
        "import deepspeed_tpu.serving.metrics, sys; "
        "assert 'deepspeed_tpu.telemetry.capture' not in sys.modules; "
        "assert 'deepspeed_tpu.utils.trace' not in sys.modules; "
        "src = open(deepspeed_tpu.serving.metrics.__file__).read(); "
        "assert 'import jax' not in src; print('ok')")
    proc = subprocess.run([_sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "ok" in proc.stdout


# ----------------------------------------------------------------------
# satellite: Timer.elapsed(reset=True) on a running timer
# ----------------------------------------------------------------------
def test_timer_elapsed_reset_preserves_running_interval():
    from deepspeed_tpu.utils.timer import Timer

    t = Timer("t")
    t.start()
    time.sleep(0.02)
    first = t.elapsed(reset=True)
    assert first >= 0.015
    # regression: reset used to clear `started`, killing the in-flight
    # interval — the timer must still be running with a rebased start
    assert t.started
    time.sleep(0.02)
    t.stop()
    second = t.elapsed(reset=True)
    assert second >= 0.015
    # the pre-reset interval must NOT be double counted into the second
    assert second < first + 0.25


def test_timer_elapsed_reset_idle_still_clears():
    from deepspeed_tpu.utils.timer import Timer

    t = Timer("t")
    t.start()
    time.sleep(0.01)
    t.stop()
    assert t.elapsed(reset=True) > 0
    assert not t.started
    assert t.elapsed(reset=False) == 0.0


# ----------------------------------------------------------------------
# satellite: comms volume clamp + totals
# ----------------------------------------------------------------------
def test_calc_bw_log_single_device_clamps():
    from deepspeed_tpu.utils.comms_logging import calc_bw_log

    # n=1: ring factor 2(n-1)/n collapses to 0 — clamped to bus == alg
    r = calc_bw_log("all_reduce", 1 << 20, 1e-3, 1)
    assert r["algbw_gbps"] > 0
    assert r["busbw_gbps"] == pytest.approx(r["algbw_gbps"])
    # degenerate n<=0 must not divide by zero / go negative
    r0 = calc_bw_log("all_gather", 1 << 20, 1e-3, 0)
    assert r0["busbw_gbps"] == pytest.approx(r0["algbw_gbps"])
    # the multi-device formulas are untouched
    r4 = calc_bw_log("all_reduce", 1 << 20, 1e-3, 4)
    assert r4["busbw_gbps"] == pytest.approx(r4["algbw_gbps"] * 1.5)


def test_comms_logger_totals_per_op():
    from deepspeed_tpu.utils.comms_logging import CommsLogger

    cl = CommsLogger(enabled=True)
    a = np.zeros((4, 4), np.float32)     # 64 B
    b = np.zeros((8,), np.float32)       # 32 B
    cl.record("all_reduce", a, "data")
    cl.record("all_reduce", a, "data")
    cl.record("all_reduce", b, "data")
    cl.record("all_gather", b, "data")
    tot = cl.totals()
    assert tot["all_reduce"] == {"count": 3, "bytes": 160}
    assert tot["all_gather"] == {"count": 1, "bytes": 32}
    cl.log_summary()                      # TOTAL rows must not crash
    cl.reset()
    assert cl.totals() == {}


# ----------------------------------------------------------------------
# satellite: flops profiler degradation + analytic formula
# ----------------------------------------------------------------------
class _FakeCompiled:
    def __init__(self, ca, mem="raise"):
        self._ca, self._mem = ca, mem

    def cost_analysis(self):
        return self._ca

    def memory_analysis(self):
        if self._mem == "raise":
            raise RuntimeError("backend has no memory analysis")
        return self._mem


class _FakeJit:
    def __init__(self, compiled):
        self._compiled = compiled

    def lower(self, *a, **kw):
        return types.SimpleNamespace(compile=lambda: self._compiled)


def test_profile_compiled_degrades_gracefully():
    from deepspeed_tpu.profiling.flops_profiler import profile_compiled

    # list-shaped cost_analysis (one dict per computation)
    out = profile_compiled(_FakeJit(_FakeCompiled([{"flops": 5.0}])))
    assert out == {"flops": 5.0}
    # empty list / missing keys / raising memory_analysis → empty result
    assert profile_compiled(_FakeJit(_FakeCompiled([]))) == {}
    assert profile_compiled(_FakeJit(_FakeCompiled({}))) == {}
    out = profile_compiled(_FakeJit(_FakeCompiled(
        {"bytes accessed": 3.0}, mem=None)))
    assert out == {"bytes_accessed": 3.0}
    # memory_analysis present → summed peak
    mem = types.SimpleNamespace(temp_size_in_bytes=10,
                                argument_size_in_bytes=20,
                                output_size_in_bytes=30)
    out = profile_compiled(_FakeJit(_FakeCompiled({"flops": 1.0},
                                                  mem=mem)))
    assert out["peak_memory_bytes"] == 60.0


def test_analytic_model_profile_hand_computed():
    from deepspeed_tpu.profiling.flops_profiler import get_model_profile

    cfg = types.SimpleNamespace(
        hidden_size=4, num_heads=2, kv_heads=2, dim_per_head=2,
        intermediate_size=8, activation="gelu", num_layers=1,
        vocab_size=10, norm="layernorm", num_experts=0)
    prof = get_model_profile(cfg, batch_size=1, seq_len=3,
                             include_backward=False)
    # hand computation: qkv 288 + scores 144 + attn_out 96 + mlp 384
    # = 912/layer; logits 240 → fwd 1152
    assert prof["fwd_flops"] == 1152.0
    assert prof["breakdown_per_layer"]["attention_qkv"] == 288.0
    assert prof["breakdown_per_layer"]["mlp"] == 384.0
    assert prof["logits_flops"] == 240.0
    full = get_model_profile(cfg, 1, 3, include_backward=True)
    assert full["total_flops_per_step"] == pytest.approx(3 * 1152.0)
    recomp = get_model_profile(cfg, 1, 3, include_backward=True,
                               recompute_fwd_factor=1.0)
    assert recomp["total_flops_per_step"] == pytest.approx(4 * 1152.0)


# ----------------------------------------------------------------------
# capture reports
# ----------------------------------------------------------------------
def test_capture_report_empty_dir(tmp_path):
    rep = build_capture_report(str(tmp_path))
    assert rep["overlap_fraction"] == 0.0
    assert "no xplane files" in rep["note"]


def test_capture_report_synthetic_device_plane(tmp_path):
    xplane_pb2 = pytest.importorskip(
        "tensorflow.tsl.profiler.protobuf.xplane_pb2")

    xs = xplane_pb2.XSpace()
    plane = xs.planes.add(name="/device:TPU:0")
    for mid, n in {1: "fusion.42", 2: "all-reduce.7", 3: "dot.3"}.items():
        plane.event_metadata[mid].name = n
    line = plane.lines.add(timestamp_ns=0)
    ms = 10 ** 9  # ps per ms — report times must survive ms rounding
    line.events.add(metadata_id=1, offset_ps=0, duration_ps=1 * ms)
    line.events.add(metadata_id=2, offset_ps=ms // 2, duration_ps=1 * ms)
    line.events.add(metadata_id=3, offset_ps=2 * ms, duration_ps=ms // 2)
    (tmp_path / "t.xplane.pb").write_bytes(xs.SerializeToString())

    rec = StepRecord(step=3, wall_time_s=0.1, tokens=10,
                     flops_per_step=1e6, peak_flops_per_sec=1e12,
                     flops_source="analytic")
    rep = build_capture_report(str(tmp_path), step_record=rec)
    assert rep["overlap_fraction"] == 0.5
    names = [o["name"] for o in rep["top_ops"]]
    assert "all-reduce.7" in names and "fusion.42" in names
    cc = rep["mfu_cross_check"]
    assert cc["analytic_mfu"] == rec.mfu
    assert cc["capture_collective_ms"] > 0


def test_autocapture_regression_trigger_and_budget(tmp_path):
    from deepspeed_tpu.runtime.config import TelemetryCaptureConfig
    from deepspeed_tpu.telemetry.capture import AutoCapture

    cfg = TelemetryCaptureConfig(enabled=True, regression_factor=2.0,
                                 budget=1, window=16,
                                 output_dir=str(tmp_path))
    cap = AutoCapture(cfg)
    for _ in range(12):
        cap.observe_step_time(0.1)
    assert not cap._regressed()          # flat distribution
    cap.observe_step_time(1.0)           # p95 now 10× the median
    cap.observe_step_time(1.0)
    assert cap._regressed()
    # below the minimum sample count the trigger must stay quiet
    cold = AutoCapture(cfg)
    cold.observe_step_time(9.0)
    assert not cold._regressed()
    # factor 0 disables the trigger entirely
    off = AutoCapture(TelemetryCaptureConfig(
        enabled=True, regression_factor=0.0, output_dir=str(tmp_path)))
    for _ in range(20):
        off.observe_step_time(0.1)
    off.observe_step_time(50.0)
    assert not off._regressed()


# ----------------------------------------------------------------------
# serving metrics now run on the shared registry
# ----------------------------------------------------------------------
def test_serving_metrics_use_shared_registry_histograms():
    import deepspeed_tpu.serving.metrics as sm

    # the private window implementation is gone
    assert not hasattr(sm, "_percentiles")
    reg = MetricsRegistry()
    m = sm.ServingMetrics(registry=reg)
    for v in (0.1, 0.2, 0.3):
        m.record_first_token(v)
    m.record_admit(0.05)
    m.record_tokens(5)
    m.record_finish("completed", 3, first_token_at=1.0, finished_at=1.4)
    # the registry object IS the serving histogram
    h = reg.get("serving_ttft_seconds")
    assert isinstance(h, Histogram)
    snap = m.snapshot()
    assert snap["ttft"] == h.snapshot()
    assert snap["ttft"]["count"] == 3
    assert snap["ttft"]["p50"] == pytest.approx(0.2)
    assert snap["tpot"]["p50"] == pytest.approx(0.2)  # (1.4-1.0)/(3-1)
    assert snap["completed"] == 1 and snap["tokens_out"] == 5
    assert reg.get("serving_completed_total").value == 1
    # monitor-event flattening unchanged
    tags = {t for t, _, _ in m.events(7)}
    assert {"serving/ttft_p50", "serving/tpot_p95",
            "serving/tokens_out"} <= tags


def test_serving_metrics_counters_gauges():
    from deepspeed_tpu.serving.metrics import ServingMetrics

    m = ServingMetrics()
    m.record_submit()
    m.record_reject()
    m.record_preemption()
    m.record_step()
    m.set_gauges(queue_depth=3, active=2, kv_utilization=0.5)
    assert (m.submitted, m.rejected, m.preemptions, m.steps) == (1, 1, 1, 1)
    assert (m.queue_depth, m.active_requests) == (3, 2)
    assert m.kv_utilization == 0.5
    with pytest.raises(ValueError):
        m.record_finish("exploded", 1, None, 0.0)


# ----------------------------------------------------------------------
# the telemetry_check lint runs as a normal tier-1 test
# ----------------------------------------------------------------------
def test_telemetry_check_lint_passes():
    import importlib.util

    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "telemetry_check.py")
    spec = importlib.util.spec_from_file_location("telemetry_check", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.run_all() == []


def test_bench_backlog_queue_is_runnable():
    """Every queued measurement command in BENCH_MEASURED_r07+.json must
    still parse against the current bench.py flags, row names, tool
    scripts, and model registry — a renamed row or retired flag rots the
    queue silently otherwise (tools/bench_backlog.py)."""
    import importlib.util

    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "bench_backlog.py")
    spec = importlib.util.spec_from_file_location("bench_backlog", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.run_all() == []


# ----------------------------------------------------------------------
# acceptance: 3-step CPU train run with telemetry + forced capture
# ----------------------------------------------------------------------
def test_train_run_emits_step_records_and_capture_report(tmp_path):
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config

    jsonl = str(tmp_path / "steps.jsonl")
    prom = str(tmp_path / "metrics.prom")
    cap_dir = str(tmp_path / "captures")
    model = get_model_config("gpt2-tiny")
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "steps_per_print": 10_000,
        "telemetry": {
            "enabled": True, "jsonl_path": jsonl,
            "prometheus_path": prom,
            "capture": {"enabled": True, "capture_step": 2,
                        "num_steps": 1, "budget": 1,
                        "output_dir": cap_dir},
        },
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(8, 33), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    for _ in range(3):
        loss = engine.train_batch(batch)
    assert np.isfinite(float(np.asarray(loss)))
    engine.destroy()

    recs = read_jsonl(jsonl)
    assert len(recs) == 3
    for i, r in enumerate(recs):
        assert r["schema"] == 3 and r["kind"] == "train"
        assert r["step"] == i + 1
        assert r["tokens"] == 8 * 32
        assert r["tokens_per_sec"] > 0
        assert 0.0 < r["mfu"] <= 1.0
        assert r["flops_source"] in ("measured", "analytic")
        hbm0 = r["hbm"]["device_0"]
        assert hbm0["bytes_in_use"] > 0
        assert hbm0["peak_bytes_in_use"] >= hbm0["bytes_in_use"] > 0
        assert r["goodput"] == 1.0 and r["skipped"] is False
        assert r["loss"] is not None and np.isfinite(r["loss"])
        # serialized lines are key-sorted (schema lint contract)
        assert list(r.keys()) == sorted(r.keys())

    # the forced capture window produced a persisted overlap report
    report_path = os.path.join(cap_dir, "capture_step2", "report.json")
    assert os.path.exists(report_path), os.listdir(cap_dir)
    with open(report_path) as f:
        rep = json.load(f)
    assert 0.0 <= rep["overlap_fraction"] <= 1.0
    assert rep["armed_at_step"] == 2
    assert "mfu_cross_check" in rep
    assert rep["mfu_cross_check"]["analytic_mfu"] > 0

    # prometheus exposition carries the shared metrics
    with open(prom) as f:
        text = f.read()
    assert "telemetry_steps_total 3" in text
    assert "telemetry_step_time_seconds" in text
