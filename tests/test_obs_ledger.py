"""Run ledger & regression sentinel (telemetry/ledger.py +
tools/obs_report.py; docs/OBSERVABILITY.md "Run ledger & regression
sentinel").

Covers the tier-1 acceptance set:

* backfill — every committed BENCH_r*/BENCH_MEASURED_r*.json parses
  into rollups, the trajectory spans r01→r18, and the r04-carried rows
  come out ``stale`` with a runnable requeue command attached;
* planted regressions — an MFU cliff, a TTFT-p95 regression, a goodput
  gap, and an SLO-burn spike are each detected with the right verdict /
  anomaly kind, and the planted-regression gate exits 1;
* jittered-in-band series produce ZERO findings (no false positives);
* the real gate: ``obs_report --gate`` on in-session smoke artifacts
  (written through the real Telemetry + write_manifest path) against
  the committed ``tools/obs_baseline.json`` is clean.
"""

import importlib.util
import json
import os

import pytest

from deepspeed_tpu.telemetry import ledger

REPO = os.path.join(os.path.dirname(__file__), "..")


def _load_tool(name):
    path = os.path.join(REPO, "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _train_records(n, wall_s=0.1, mfu=0.5, goodput=1.0):
    return [{"kind": "train", "step": i + 1, "wall_time_s": wall_s,
             "mfu": mfu, "goodput": goodput, "tokens_per_sec": 1000.0}
            for i in range(n)]


# ----------------------------------------------------------------------
# backfill: the committed history parses, end to end
# ----------------------------------------------------------------------
def test_backfill_parses_all_committed_bench_files():
    rollups = ledger.load_bench_history(REPO)
    assert len(rollups) >= 70
    rounds = {r["round"] for r in rollups if r["round"] is not None}
    assert min(rounds) == 1 and max(rounds) >= 18
    rows = {r["row"] for r in rollups}
    assert {"gpt2_350m", "llama8b_class_zero3", "longseq_flash",
            "peak_params", "v2_decode"} <= rows
    for r in rollups:
        assert tuple(sorted(r)) == ledger.ROLLUP_KEYS
        assert tuple(sorted(r["train"])) == ledger.ROLLUP_TRAIN_KEYS
        assert tuple(sorted(r["serve"])) == ledger.ROLLUP_SERVE_KEYS


def test_backfill_flags_carried_rows_stale_with_requeue_cmds():
    rollups = ledger.load_bench_history(REPO)
    stale = {r["row"] for r in rollups if r["stale"]}
    assert stale == {"gpt2_350m", "llama8b_class_zero3", "longseq_flash",
                     "peak_params", "v2_decode"}
    # nothing measured at or before r04 is stale
    for r in rollups:
        if r["round"] is not None and r["round"] <= ledger.LAST_MEASURED_ROUND:
            assert not r["stale"]
    requeue = ledger.attach_requeue_cmds(
        rollups, ledger.collect_queued_cmds(REPO))
    assert set(requeue) == stale
    for row, cmd in requeue.items():
        assert f"--row {row}" in cmd or "--peak-entry" in cmd


def test_queued_cmd_row_names_are_clean():
    # the for-loop wrapped queue entries must not leak shell punctuation
    # into row names ("peak_params;" would silently duplicate the key)
    for name in ledger.collect_queued_cmds(REPO):
        assert name == name.strip(";&|")
    loop = ("for CB in 1 2; do DSTPU_CHUNK_BYTES=$CB "
            "python bench.py --row peak_params; done")
    assert ledger._row_name_from_cmd(loop) == "peak_params"


# ----------------------------------------------------------------------
# sentinel verdicts: planted regression / improvement / stale / new
# ----------------------------------------------------------------------
def test_planted_ttft_p95_regression_detected_and_gates():
    rollup = ledger.rollup_from_bench_row(
        {"metric": "serve_load_sim", "value": 900.0, "unit": "tokens/s",
         "ttft_p95_ms": 400.0}, round_no=19)
    baseline = {"rows": {"serve_load": {"serve.ttft_p95_ms": 100.0,
                                        "serve.tokens_per_sec": 1000.0}},
                "smoke_rows": {}, "suppress": []}
    findings = ledger.diff_rollups([rollup], baseline)
    by_metric = {f["metric"]: f for f in findings}
    assert by_metric["serve.ttft_p95_ms"]["verdict"] == "regressed"
    assert by_metric["serve.tokens_per_sec"]["verdict"] == "flat"
    gate = ledger.gate_findings(findings, baseline["suppress"])
    assert [f["metric"] for f in gate] == ["serve.ttft_p95_ms"]
    # fingerprint suppression clears the gate without touching verdicts
    fp = by_metric["serve.ttft_p95_ms"]["fingerprint"]
    assert ledger.gate_findings(findings, [fp]) == []
    assert fp == ledger.fingerprint("serve_load", "serve.ttft_p95_ms",
                                    "regressed")


def test_stale_and_new_and_missing_never_gate():
    rollup = ledger.rollup_from_bench_row(
        {"metric": "gpt2_350m_train", "value": 1000.0,
         "unit": "tokens/s", "mfu": 0.4}, round_no=19)
    rollup["stale"] = True
    baseline = {"rows": {"gpt2_350m": {"value": 1000.0,
                                       "train.goodput": 1.0}},
                "smoke_rows": {}, "suppress": []}
    requeue = {"gpt2_350m": "python bench.py --row gpt2_350m"}
    findings = ledger.diff_rollups([rollup], baseline, requeue)
    by_metric = {f["metric"]: f for f in findings}
    assert by_metric["value"]["verdict"] == "stale"
    assert by_metric["value"]["requeue_cmd"] == requeue["gpt2_350m"]
    assert by_metric["train.mfu"]["verdict"] == "new"
    assert by_metric["train.goodput"]["verdict"] == "missing"
    assert ledger.gate_findings(findings) == []


def test_smoke_rollup_diffs_smoke_rows_not_chip_rows():
    chip = ledger.rollup_from_bench_row(
        {"metric": "gpt2_350m_train", "value": 1000.0,
         "unit": "tokens/s"}, round_no=4)
    smoke = ledger.rollup_from_bench_row(
        {"metric": "gpt2_350m_train", "goodput": 0.5}, round_no=None,
        source="manifest")
    smoke["smoke"] = True
    baseline = {"rows": {"gpt2_350m": {"value": 1000.0}},
                "smoke_rows": {"gpt2_350m": {"train.goodput": 1.0}},
                "suppress": []}
    findings = ledger.diff_rollups([chip, smoke], baseline)
    verdicts = {(f["row"], f["metric"]): f["verdict"] for f in findings}
    # the chip row must not shadow the smoke run of the same name
    assert verdicts[("gpt2_350m", "value")] == "flat"
    assert verdicts[("gpt2_350m", "train.goodput")] == "regressed"


# ----------------------------------------------------------------------
# in-run anomaly scan: planted anomalies + jittered-in-band clean run
# ----------------------------------------------------------------------
def test_planted_step_time_spike_and_mfu_cliff_detected():
    records = _train_records(12)
    records.append({"kind": "train", "step": 13, "wall_time_s": 0.5,
                    "mfu": 0.1, "goodput": 1.0})
    trace = [{"ph": "X", "name": "train.step", "ts": 1, "dur": 2,
              "args": {"step": 13, "trace_id": "t-13"}}]
    anomalies = ledger.scan_run(records, trace_events=trace,
                                run_id="run-x")
    kinds = {a["kind"] for a in anomalies}
    assert kinds == {"step_time_spike", "mfu_cliff"}
    for a in anomalies:
        assert tuple(sorted(a)) == ledger.ANOMALY_KEYS
        assert a["step"] == 13 and a["run_id"] == "run-x"
        # cross-linked to the covering trace span
        assert a["trace_span"]["name"] == "train.step"
        assert a["trace_span"]["trace_id"] == "t-13"


def test_planted_goodput_gap_detected():
    records = _train_records(10)
    records.append({"kind": "train", "step": 11, "wall_time_s": 0.1,
                    "mfu": 0.5, "goodput": 0.8})
    anomalies = ledger.scan_run(records)
    gaps = [a for a in anomalies if a["kind"] == "goodput_gap"]
    assert len(gaps) == 1
    assert gaps[0]["step"] == 11
    assert gaps[0]["value"] == pytest.approx(0.8)
    assert gaps[0]["threshold"] == pytest.approx(1.0)


def test_recovery_record_is_a_goodput_gap():
    records = _train_records(5)
    records.append({"kind": "recovery", "step": 6, "wall_time_s": 42.0,
                    "goodput": 0.9})
    anomalies = ledger.scan_run(records)
    assert [a["kind"] for a in anomalies] == ["goodput_gap"]


def test_planted_slo_burn_spike_detected_per_tier():
    fleet = ([{"tier": "decode", "slo_violation": 0} for _ in range(5)]
             + [{"tier": "decode", "slo_violation": 1}]
             + [{"tier": "prefill", "slo_violation": 0}
                for _ in range(6)])
    anomalies = ledger.scan_run([], fleet_rows=fleet, objective=0.99)
    burns = [a for a in anomalies if a["kind"] == "slo_burn_spike"]
    assert len(burns) == 1 and burns[0]["tier"] == "decode"
    assert burns[0]["value"] >= 1.0


def test_jittered_in_band_run_has_zero_findings():
    # ±20% step-time jitter, mild MFU wobble, monotone goodput, no SLO
    # violations: the scan and the sentinel must both stay silent
    jitter = [0.10, 0.12, 0.09, 0.11, 0.10, 0.08, 0.12, 0.11,
              0.09, 0.10, 0.11, 0.12, 0.10, 0.09, 0.11, 0.10]
    records = [{"kind": "train", "step": i + 1, "wall_time_s": w,
                "mfu": 0.5 + 0.02 * (i % 3), "goodput": 1.0,
                "tokens_per_sec": 1000.0 + 10 * (i % 5)}
               for i, w in enumerate(jitter)]
    fleet = [{"tier": "decode", "slo_violation": 0} for _ in range(30)]
    assert ledger.scan_run(records, fleet_rows=fleet) == []

    rollup = ledger.rollup_from_bench_row(
        {"metric": "gpt2_350m_train", "value": 1020.0,
         "unit": "tokens/s", "mfu": 0.51}, round_no=19)
    baseline = {"rows": {"gpt2_350m": {"value": 1000.0,
                                       "train.mfu": 0.50,
                                       "train.tokens_per_sec": 1000.0}},
                "smoke_rows": {}, "suppress": []}
    findings = ledger.diff_rollups([rollup], baseline)
    assert {f["verdict"] for f in findings} == {"flat"}
    assert ledger.gate_findings(findings) == []


# ----------------------------------------------------------------------
# manifest round-trip + obs_report CLI (trend, gate both ways)
# ----------------------------------------------------------------------
def _write_run(tmp_path, name, *, smoke=True, skipped=0, steps=4):
    """Write telemetry artifacts through the REAL write path (Telemetry
    + write_manifest) and return the manifest path.  ``skipped`` plants
    that many overflow-skipped trailing steps, dragging cumulative
    goodput below 1.0."""
    from deepspeed_tpu.runtime.config import TelemetryConfig
    from deepspeed_tpu.telemetry import Telemetry

    jsonl = str(tmp_path / f"{name}.jsonl")
    run_id = ledger.new_run_id(name)
    tel = Telemetry(TelemetryConfig(enabled=True, jsonl_path=jsonl,
                                    run_id=run_id))
    for s in range(1, steps + 1):
        tel.record_train_step(step=s, wall_time_s=0.1, tokens=128,
                              skipped=(s > steps - skipped))
    tel.close()
    return ledger.write_manifest(
        str(tmp_path / f"{name}.manifest.json"), name, run_id,
        {"telemetry_jsonl": jsonl}, smoke=smoke)


def test_manifest_roundtrip_rollup_and_run_id(tmp_path):
    path = _write_run(tmp_path, "gpt2_350m")
    manifest = json.load(open(path))
    assert tuple(sorted(manifest)) == ledger.MANIFEST_KEYS
    sv = manifest["schema_versions"]
    assert sv["ledger"] == ledger.LEDGER_SCHEMA
    assert sv["step_record"] == 3 and sv["tier_snapshot"] == 2
    r = ledger.rollup_from_manifest(path)
    assert r["row"] == "gpt2_350m" and r["smoke"] and r["source"] == "manifest"
    assert r["run_id"] == manifest["run_id"] != ""
    assert r["train"]["goodput"] == 1.0
    assert r["train"]["step_time_p50_ms"] == pytest.approx(100.0, rel=0.01)
    # the run_id is stamped on every record too
    for rec in (json.loads(line) for line in open(
            str(tmp_path / "gpt2_350m.jsonl"))):
        assert rec["run_id"] == manifest["run_id"]
        assert rec["schema"] == 3


def test_obs_report_gate_clean_on_smoke_run_vs_committed_baseline(
        tmp_path, capsys):
    """The tier-1 gate: a fresh in-session smoke run diffed against the
    committed tools/obs_baseline.json must be clean, and the trend must
    span the full committed history r01→r18."""
    _write_run(tmp_path, "gpt2_350m")
    obs = _load_tool("obs_report")
    rc = obs.main(["--scan", str(tmp_path), "--gate"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "GATE: clean" in out
    assert "r01" in out and "r18" in out       # trajectory renders
    assert "stale rows" in out                 # requeue worklist renders


def test_obs_report_gate_exits_1_on_planted_regression_set(
        tmp_path, capsys):
    path = _write_run(tmp_path, "gpt2_350m", skipped=2)
    # two skipped steps drop cumulative goodput to 0.5, below the
    # baselined 1.0 (tolerance 2%) -> regressed -> gate trips
    assert ledger.rollup_from_manifest(path)["train"]["goodput"] < 0.98
    obs = _load_tool("obs_report")
    rc = obs.main(["--scan", str(tmp_path), "--gate", "--no-history"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "GATE: 1 unbaselined regression(s)" in out
    assert "gpt2_350m.train.goodput" in out
