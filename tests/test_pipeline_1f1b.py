"""1F1B pipeline schedule: table invariants, grad parity vs the GPipe
scan, and the O(pp) live-activation bound (ref runtime/pipe/schedule.py:189
TrainSchedule)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.parallel.pipeline import _make_1f1b_schedule
from deepspeed_tpu.parallel.topology import MeshTopology, set_topology


@pytest.mark.parametrize("pp,m", [(2, 2), (2, 8), (4, 4), (4, 8), (3, 5)])
def test_schedule_invariants(pp, m):
    wt, wm = _make_1f1b_schedule(pp, m)
    T = wt.shape[0]
    f_tick = {}
    b_tick = {}
    in_flight = np.zeros(pp, int)
    max_flight = np.zeros(pp, int)
    for t in range(T):
        for s in range(pp):
            if wt[t, s] == 1:
                o = wm[t, s]
                assert (s, o) not in f_tick, "duplicate forward"
                if s > 0:  # activation must have arrived (strictly earlier)
                    assert f_tick[(s - 1, o)] < t
                f_tick[(s, o)] = t
                in_flight[s] += 1
                max_flight[s] = max(max_flight[s], in_flight[s])
            elif wt[t, s] == 2:
                o = wm[t, s]
                assert (s, o) not in b_tick, "duplicate backward"
                assert (s, o) in f_tick and f_tick[(s, o)] < t or s == pp - 1
                if s == pp - 1:
                    assert f_tick[(s, o)] < t
                else:
                    assert b_tick[(s + 1, o)] < t
                b_tick[(s, o)] = t
                in_flight[s] -= 1
    # every (stage, microbatch) ran exactly one F and one B
    assert len(f_tick) == pp * m and len(b_tick) == pp * m
    # the defining 1F1B property: bounded stash
    assert max_flight.max() <= pp
    # utilisation sanity: ticks close to the ideal 2m + 2(pp-1)
    assert T <= 2 * m + 4 * pp


def _loss_and_grads(schedule, n_micro=8, pp=2):
    from deepspeed_tpu.models import init_params
    from deepspeed_tpu.models import transformer as tr
    from deepspeed_tpu.models.registry import TransformerConfig

    cfg = TransformerConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=4,
        num_heads=2, num_kv_heads=2, max_seq_len=16, arch="llama",
        norm="rmsnorm", activation="swiglu", use_rope=True,
        tie_embeddings=True, dtype=jnp.float32,
        pipeline_schedule=schedule, pipeline_microbatches=n_micro)
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 64, (8, 16)), jnp.int32)
    batch = {"input_ids": ids, "labels": ids}

    topo = MeshTopology({"pipe": pp, "data": 8 // pp})
    set_topology(topo)
    try:
        loss, grads = jax.jit(jax.value_and_grad(
            lambda p: tr.loss_fn(p, batch, cfg)))(params, )
    finally:
        set_topology(None)
    return float(loss), grads


@pytest.mark.parametrize("pp", [2, 4])
def test_1f1b_matches_gpipe_grads(pp):
    """pp=4 exercises true middle stages: multi-hop cotangent relay,
    left/right clip gating, and arr slot reuse over a >2 ring."""
    l1, g1 = _loss_and_grads("1f1b", pp=pp)
    l2, g2 = _loss_and_grads("gpipe", pp=pp)
    np.testing.assert_allclose(l1, l2, rtol=1e-5)
    flat1 = jax.tree_util.tree_leaves(g1)
    flat2 = jax.tree_util.tree_leaves(g2)
    for a, b in zip(flat1, flat2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_1f1b_live_activation_bound():
    """The compiled 1F1B step's temporary memory must not grow with
    n_micro (O(pp) stash), unlike the AD-differentiated GPipe scan whose
    residual stash is O(n_micro)."""
    from deepspeed_tpu.models import init_params
    from deepspeed_tpu.models import transformer as tr
    from deepspeed_tpu.models.registry import TransformerConfig

    def temp_bytes(schedule, n_micro):
        cfg = TransformerConfig(
            vocab_size=64, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=2, num_kv_heads=2, max_seq_len=64,
            arch="llama", norm="rmsnorm", activation="swiglu", use_rope=True,
            tie_embeddings=True, dtype=jnp.float32,
            pipeline_schedule=schedule, pipeline_microbatches=n_micro,
            remat_policy="none")
        params = init_params(cfg, jax.random.PRNGKey(0))
        ids = jnp.zeros((n_micro, 64), jnp.int32)
        batch = {"input_ids": ids, "labels": ids}
        topo = MeshTopology({"pipe": 2, "data": 1})
        set_topology(topo)
        try:
            compiled = jax.jit(jax.grad(
                lambda p: tr.loss_fn(p, batch, cfg))).lower(params).compile()
            mem = compiled.memory_analysis()
            return mem.temp_size_in_bytes
        finally:
            set_topology(None)

    # per-microbatch work is constant (mb=1); only the stash should differ.
    small = temp_bytes("1f1b", 4)
    big = temp_bytes("1f1b", 16)
    # O(pp) bound: with the embedding inside the pipelined region the
    # input cotangent folds into O(vocab·H) embed grads per tick — no
    # O(n_micro) dx stash — so 4x more microbatches is near-flat (the
    # only O(B) growth left is the int32 ids/labels themselves)
    assert big < small * 1.15, (small, big)
    gpipe_big = temp_bytes("gpipe", 16)
    assert big < gpipe_big, (big, gpipe_big)


def test_pipe_sharded_init_matches_eager_init():
    """Regression: jitting init straight into P(pipe) stacked-layer
    out_shardings on a mesh with an unused data axis returned the
    pipe-sharded leaves scaled by the data-axis size (4x at data=4 on
    jax 0.4.37) — a silently-hot init that trained ~2x slower.  The
    engine now materializes unsharded and device_puts; a pipe-mesh
    engine's params must be bit-identical to the eager init."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.models import transformer as tf

    model = get_model_config("gpt2-tiny")
    config = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "mesh": {"pipe": 2, "data": 4},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config, seed=11)
    # eager run of the engine's own init fn — no jit, no shardings, so
    # XLA partitioning cannot touch the drawn values
    ref = engine._init_fn(jax.random.PRNGKey(11))
    got = jax.tree.map(lambda a: np.asarray(a, np.float32), engine.params)
    ref = jax.tree.map(lambda a: np.asarray(a, np.float32), ref)
    flat_got = jax.tree_util.tree_leaves_with_path(got)
    flat_ref = jax.tree_util.tree_leaves_with_path(ref)
    assert len(flat_got) == len(flat_ref)
    for (path, a), (_, b) in zip(flat_got, flat_ref):
        # allclose, not array_equal: eager-vs-jit rng lowering may differ
        # in the last ulp — the bug being regressed is a 4x SCALE, which
        # no tolerance this tight lets through
        np.testing.assert_allclose(
            a, b, rtol=1e-5, atol=1e-7,
            err_msg=f"init drifted at {jax.tree_util.keystr(path)}")
