"""AutoTP automatic tensor-parallel sharding + Domino comm-hiding layer.

Ref test model: tests/unit/model_parallelism/ (AutoTP policies) and the
Domino blog's parity claim (split-batch == full-batch numerics).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu as ds
from deepspeed_tpu.models import get_model_config
from deepspeed_tpu.models import transformer as tf
from deepspeed_tpu.module_inject import (AutoTP, column_parallel_linear,
                                         row_parallel_linear, tp_model_init,
                                         vocab_parallel_logits)
from deepspeed_tpu.parallel.topology import MeshTopology, set_topology
from deepspeed_tpu.runtime.domino import domino_forward, domino_transformer_layer
from deepspeed_tpu.utils.jax_compat import shard_map


# ----------------------------------------------------------------------
# AutoTP classification
# ----------------------------------------------------------------------
@pytest.fixture
def tp_topo():
    topo = MeshTopology({"tensor": 4, "data": 2})
    set_topology(topo)
    return topo


def test_autotp_classifies_hf_style_names(tp_topo):
    tp = AutoTP(tp_topo)
    # row parallel: output projections (need allreduce)
    assert tp.classify("model/layers/0/self_attn/o_proj", (64, 64)) == "row"
    assert tp.classify("model/layers/0/mlp/down_proj", (256, 64)) == "row"
    assert tp.classify("transformer/h/0/mlp/dense_4h_to_h", (256, 64)) == "row"
    assert tp.classify("transformer/h/0/attn/c_proj", (64, 64)) == "row"
    # column parallel
    assert tp.classify("model/layers/0/self_attn/q_proj", (64, 64)) == "column"
    assert tp.classify("model/layers/0/mlp/gate_proj", (64, 256)) == "column"
    assert tp.classify("transformer/h/0/attn/c_attn", (64, 192)) == "column"
    # our model zoo paths
    assert tp.classify("layers/attn/wo", (3, 64, 64)) == "row"
    assert tp.classify("layers/attn/wq", (3, 64, 64)) == "column"
    assert tp.classify("layers/mlp/wi", (3, 64, 256)) == "column"
    # embeddings / norms
    assert tp.classify("embed/tokens", (512, 64)) == "embedding"
    assert tp.classify("layers/ln1/scale", (64,)) == "replicate"


def test_autotp_specs_shard_correct_dims(tp_topo):
    tp = AutoTP(tp_topo)
    assert tp.spec_for("layers/attn/wq", (3, 64, 128)) == P(None, None, "tensor")
    assert tp.spec_for("layers/attn/wo", (3, 128, 64)) == P(None, "tensor", None)
    assert tp.spec_for("embed/tokens", (512, 64)) == P("tensor", None)
    # indivisible → replicated with warning
    assert tp.spec_for("layers/attn/wq", (3, 64, 130)) == P(None, None, None)


def test_tp_model_init_shards_params(tp_topo):
    model = get_model_config("gpt2-tiny", num_layers=2)
    params = tf.init_params(model, jax.random.PRNGKey(0))
    sharded = tp_model_init(params, tp_topo)
    wq = sharded["layers"]["attn"]["wq"]
    assert wq.sharding.spec == P(None, None, "tensor")
    wo = sharded["layers"]["attn"]["wo"]
    assert wo.sharding.spec == P(None, "tensor", None)


def test_package_level_tp_model_init():
    model = get_model_config("gpt2-tiny", num_layers=1)
    params = tf.init_params(model, jax.random.PRNGKey(0))
    sharded = ds.tp_model_init(params, tp_size=4)
    wq = sharded["layers"]["attn"]["wq"]
    assert "tensor" in str(wq.sharding.spec)


# ----------------------------------------------------------------------
# Parallel linear functions: sharded == dense reference
# ----------------------------------------------------------------------
def test_column_then_row_matches_dense(rng):
    topo = MeshTopology({"tensor": 8})
    x = jnp.asarray(rng.normal(size=(4, 32)).astype(np.float32))
    w1 = jnp.asarray(rng.normal(size=(32, 64)).astype(np.float32))
    w2 = jnp.asarray(rng.normal(size=(64, 32)).astype(np.float32))
    b2 = jnp.asarray(rng.normal(size=(32,)).astype(np.float32))

    def block(x, w1s, w2s, b2):
        h = column_parallel_linear(x, w1s)          # [4, 64/8] local
        return row_parallel_linear(h, w2s, b2)      # psum over tensor

    out = jax.jit(shard_map(
        block, mesh=topo.mesh,
        in_specs=(P(), P(None, "tensor"), P("tensor", None), P()),
        out_specs=P()))(x, w1, w2, b2)
    expect = (x @ w1) @ w2 + b2
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               rtol=2e-4, atol=2e-4)


def test_vocab_parallel_logits_matches_dense(rng):
    topo = MeshTopology({"tensor": 8})
    x = jnp.asarray(rng.normal(size=(4, 32)).astype(np.float32))
    emb = jnp.asarray(rng.normal(size=(64, 32)).astype(np.float32))

    out = jax.jit(shard_map(
        lambda x, e: vocab_parallel_logits(x, e),
        mesh=topo.mesh, in_specs=(P(), P("tensor", None)), out_specs=P(),
        check_vma=False))(x, emb)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x @ emb.T),
                               rtol=2e-4, atol=2e-4)


# ----------------------------------------------------------------------
# Domino
# ----------------------------------------------------------------------
def test_domino_layer_matches_plain(rng):
    cfg = get_model_config("gpt2-tiny", num_layers=1).replace(dtype=jnp.float32)
    set_topology(MeshTopology({"data": 1}))
    params = tf.init_params(cfg, jax.random.PRNGKey(0))
    lp = jax.tree.map(lambda x: x[0], params["layers"])
    x = jnp.asarray(rng.normal(size=(4, 16, cfg.hidden_size)).astype(np.float32))
    pos = jnp.broadcast_to(jnp.arange(16, dtype=jnp.int32)[None], (4, 16))

    ref, _ = tf.transformer_layer(x, lp, pos, cfg)
    got, _ = domino_transformer_layer(x, lp, pos, cfg, n_chunks=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_domino_forward_matches_plain_on_tp_mesh(rng):
    """Domino full forward under a TP mesh == plain forward (numerics),
    with independent per-chunk chains for the scheduler to overlap."""
    cfg = get_model_config("gpt2-tiny", num_layers=2).replace(dtype=jnp.float32)
    topo = MeshTopology({"tensor": 4, "data": 2})
    set_topology(topo)
    from deepspeed_tpu.parallel.sharding import ShardingRules

    rules = ShardingRules(topo, zero_stage=0)
    params = jax.jit(lambda k: tf.init_params(cfg, k),
                     out_shardings=rules.tree_shardings(
                         jax.eval_shape(lambda k: tf.init_params(cfg, k),
                                        jax.random.PRNGKey(0))))(jax.random.PRNGKey(0))
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(4, 16)).astype(np.int32))

    ref = jax.jit(lambda p, i: tf.forward(p, i, cfg))(params, ids)
    got = jax.jit(lambda p, i: domino_forward(p, i, cfg, n_chunks=2))(params, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_domino_rejects_indivisible_batch(rng):
    cfg = get_model_config("gpt2-tiny", num_layers=1).replace(dtype=jnp.float32)
    set_topology(MeshTopology({"data": 1}))
    params = tf.init_params(cfg, jax.random.PRNGKey(0))
    ids = jnp.zeros((3, 8), jnp.int32)
    with pytest.raises(ValueError, match="divisible"):
        domino_forward(params, ids, cfg, n_chunks=2)


def test_domino_chunk_collectives_stay_independent():
    """Compile-level overlap evidence (ref VERDICT r3 Weak #3): domino's
    per-chunk TP psums must survive compilation as SEPARATE all-reduce ops
    on chunk-shaped operands with distinct channel ids — not merged into
    one full-batch (or tuple-combined) collective.  Merged collectives
    would serialize the chunks and kill the latency-hiding overlap that is
    domino's entire point (ref runtime/domino/transformer.py async
    double-buffering)."""
    import re

    cfg = get_model_config("gpt2-tiny", num_layers=2).replace(dtype=jnp.float32)
    topo = MeshTopology({"tensor": 2, "data": 1})
    set_topology(topo)
    try:
        from deepspeed_tpu.parallel.sharding import ShardingRules

        rules = ShardingRules(topo, zero_stage=0)
        params = jax.jit(lambda k: tf.init_params(cfg, k),
                         out_shardings=rules.tree_shardings(
                             jax.eval_shape(lambda k: tf.init_params(cfg, k),
                                            jax.random.PRNGKey(0))))(
            jax.random.PRNGKey(0))
        b, s = 4, 32
        ids = jnp.zeros((b, s), jnp.int32)

        def ars(fn):
            hlo = jax.jit(fn).lower(params, ids).compile().as_text()
            out = []
            for line in hlo.splitlines():
                m = re.search(r"=\s*(\(?)f32\[(\d+),(\d+),(\d+)\][^=]*"
                              r"all-reduce\(.*channel_id=(\d+)", line)
                if m:
                    out.append((m.group(1) == "(",  # tuple-combined?
                                int(m.group(2)),     # leading (batch) dim
                                int(m.group(5))))    # channel id
            return out

        plain = ars(lambda p, i: tf.forward(p, i, cfg))
        dom = ars(lambda p, i: domino_forward(p, i, cfg, n_chunks=2))

        # plain: the scanned layer body carries 2 full-batch TP psums
        # (attention-out + mlp-down row-parallel reductions)
        plain_layer = [a for a in plain if a[1] == b and not a[0]]
        assert len(plain_layer) >= 2, plain
        # domino: the scanned body carries one psum PER CHUNK per
        # projection — chunk-shaped, non-tuple, each on its own channel.
        # If XLA's combiner had merged the chunks (one tuple/full-batch
        # op), the chains would serialize and overlap would be impossible.
        dom_layer = [a for a in dom if a[1] == b // 2 and not a[0]]
        assert len(dom_layer) >= 2 * 2, dom
        channels = [c for _, _, c in dom_layer]
        assert len(set(channels)) == len(channels), dom_layer
    finally:
        set_topology(None)
