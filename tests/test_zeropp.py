"""ZeRO++ (qwZ/hpZ) + MiCS hierarchical sharding.

Ref test model: tests/unit/runtime/zero/test_zeropp.py (config sweep +
convergence).  Shardings are asserted structurally (which mesh axes carry
each state) and convergence is checked by training on a fixed batch.
"""

import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import get_model_config
from deepspeed_tpu.parallel.topology import factor_data_axis, resolve_mesh_sizes
from tests.conftest import make_lm_batch


def _axes_of(shardings):
    """Set of mesh axis names appearing in a sharding pytree."""
    import jax

    axes = set()
    for s in jax.tree.leaves(shardings):
        for part in s.spec:
            if part is None:
                continue
            for ax in (part if isinstance(part, tuple) else (part,)):
                axes.add(ax)
    return axes


def test_factor_data_axis():
    sizes = resolve_mesh_sizes({"data": 8}, 8)
    out = factor_data_axis(sizes, 4)
    assert out["data"] == 2 and out["subdata"] == 4
    with pytest.raises(ValueError):
        factor_data_axis(sizes, 3)


def _make_engine(zero_extra, mesh=None):
    model = get_model_config("gpt2-tiny", num_layers=2)
    # threshold 0: the tiny model's params would all be persistent under
    # the reference-default param_persistence_threshold (1e5 elements),
    # hiding the sharding structure these tests pin
    cfg = {"train_micro_batch_size_per_gpu": 1, "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": 3,
                                 "param_persistence_threshold": 0,
                                 **zero_extra}}
    if mesh:
        cfg["mesh"] = mesh
    engine, *_ = ds.initialize(model=model, config=cfg)
    return engine, model


def test_hpz_params_shard_inner_state_shards_full(rng):
    """hpZ: params over the inner (subdata) factor only; optimizer state
    over the full ZeRO world (ref zero_hpz_partition_size semantics)."""
    engine, model = _make_engine({"zero_hpz_partition_size": 2},
                                 mesh={"data": 8})
    assert engine.topology.sizes["data"] == 4
    assert engine.topology.sizes["subdata"] == 2
    p_axes = _axes_of(engine.param_shardings)
    assert "subdata" in p_axes and "data" not in p_axes
    o_axes = _axes_of(engine.opt_shardings)
    assert "data" in o_axes and "subdata" in o_axes
    batch = make_lm_batch(rng, 8, 16, model.vocab_size)
    l0 = float(np.asarray(engine.train_batch(batch)))
    for _ in range(4):
        loss = engine.train_batch(batch)
    assert float(np.asarray(loss)) < l0


def test_mics_everything_shards_within_subgroup(rng):
    """MiCS: params AND optimizer state shard only within the sub-group;
    across sub-groups it is replication (ref MiCS_Init, mics.py:63)."""
    engine, model = _make_engine({"mics_shard_size": 4}, mesh={"data": 8})
    assert engine.topology.sizes == {**engine.topology.sizes,
                                     "data": 2, "subdata": 4}
    p_axes = _axes_of(engine.param_shardings)
    o_axes = _axes_of(engine.opt_shardings)
    assert "subdata" in p_axes and "data" not in p_axes
    assert "subdata" in o_axes and "data" not in o_axes
    batch = make_lm_batch(rng, 8, 16, model.vocab_size)
    l0 = float(np.asarray(engine.train_batch(batch)))
    for _ in range(4):
        loss = engine.train_batch(batch)
    assert float(np.asarray(loss)) < l0


def test_qwz_trains_close_to_exact(rng):
    """qwZ int8 weight gather: training converges and tracks the exact run
    (straight-through grads; int8 error is small at init scale)."""
    model = get_model_config("gpt2-tiny", num_layers=2)
    batch = make_lm_batch(rng, 8, 16, model.vocab_size)

    losses = {}
    for qwz in (False, True):
        cfg = {"train_micro_batch_size_per_gpu": 2,
               "gradient_accumulation_steps": 1,
               "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
               "zero_optimization": {"stage": 3, "zero_quantized_weights": qwz},
               "mesh": {"data": 4}}
        engine, *_ = ds.initialize(model=model, config=cfg, seed=0)
        cur = [float(np.asarray(engine.train_batch(batch))) for _ in range(5)]
        losses[qwz] = cur
    assert losses[True][-1] < losses[True][0]          # converges
    # int8 blockwise weight error keeps the loss curves close
    assert abs(losses[True][0] - losses[False][0]) / losses[False][0] < 0.05


def test_hpz_with_quantized_weights_combo(rng):
    """The headline ZeRO++ config: hpZ + qwZ together."""
    engine, model = _make_engine({"zero_hpz_partition_size": 2,
                                  "zero_quantized_weights": True},
                                 mesh={"data": 4})
    batch = make_lm_batch(rng, 4, 16, model.vocab_size)
    l0 = float(np.asarray(engine.train_batch(batch)))
    for _ in range(4):
        loss = engine.train_batch(batch)
    assert float(np.asarray(loss)) < l0
