"""Self-healing elastic training (deepspeed_tpu/resilience/): the
partition oracle as THE spec source, universal-checkpoint resharding
across mesh shapes, crash-atomic commits, escalating group stop, the
watchdog→agent→resume supervisor loop, and live serving grow/shrink.
See docs/ELASTICITY.md; ISSUE 13 acceptance tests live here."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.checkpoint.universal import (COMMIT_MARKER, ds_to_universal,
                                                load_universal,
                                                resolve_universal_dir)
from deepspeed_tpu.models import get_model_config
from deepspeed_tpu.resilience.oracle import PartitionOracle, plan_mesh
from tests.conftest import make_lm_batch


def _cfg(mesh, stage=2, **over):
    dp = mesh.get("data", 1) * mesh.get("subdata", 1) * mesh.get("expert", 1)
    cfg = {
        "train_batch_size": 8,
        "train_micro_batch_size_per_gpu": max(1, 8 // dp),
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage},
        "steps_per_print": 1000,
        "mesh": mesh,
    }
    cfg.update(over)
    return cfg


def _mk_engine(model, cfg, seed=3, topology=None):
    from deepspeed_tpu.parallel import topology as topo_mod
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    topo_mod._GLOBAL_TOPOLOGY = None
    if topology is not None:
        return DeepSpeedEngine(model=model, config=cfg, topology=topology,
                               seed=seed)
    engine, _, _, _ = ds.initialize(model=model, config=cfg, seed=seed)
    return engine


def _train(engine, batches):
    return [float(np.asarray(engine.train_batch(b))) for b in batches]


# ---------------------------------------------------------------------------
# PartitionOracle: the ONE spec source
# ---------------------------------------------------------------------------

def test_oracle_is_the_single_source():
    """Engine init, the serving engine, and the historical ShardingRules
    name all resolve to the SAME class/instance — no per-site spec
    derivation survives."""
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.parallel.sharding import ShardingRules

    assert ShardingRules is PartitionOracle  # alias, not a second impl

    model = get_model_config("gpt2-tiny")
    engine = _mk_engine(model, _cfg({"data": 8}, stage=3))
    assert isinstance(engine.oracle, PartitionOracle)
    assert engine.rules is engine.oracle
    # from_config derives identically to what the engine uses
    twin = PartitionOracle.from_config(engine.topology, engine.config)
    shape = (model.num_layers, model.hidden_size,
             model.num_heads * (model.hidden_size // model.num_heads))
    assert engine.oracle.spec_for("layers/attn/wq", shape) \
        == twin.spec_for("layers/attn/wq", shape)

    from deepspeed_tpu.parallel import topology as topo_mod

    topo_mod._GLOBAL_TOPOLOGY = None
    eng2 = InferenceEngineV2(model, {"memory_config": {"num_blocks": 8,
                                                       "block_size": 4},
                                     "max_context": 64})
    assert isinstance(eng2.oracle, PartitionOracle)
    assert eng2.rules is eng2.oracle


def test_oracle_flat_specs_match_tree_specs():
    """flat_specs on a {path: shape} manifest (the checkpoint view) must
    agree exactly with tree_specs on the pytree (the engine view) — the
    property that makes a flat checkpoint land wherever the engine would
    have put the leaf."""
    import jax

    from deepspeed_tpu.models import transformer as tf_model
    from deepspeed_tpu.parallel.topology import MeshTopology
    from deepspeed_tpu.resilience.oracle import path_str

    model = get_model_config("gpt2-tiny")
    topo = MeshTopology({"data": 4, "tensor": 2})
    oracle = PartitionOracle(topo, zero_stage=3)
    shapes = jax.eval_shape(lambda r: tf_model.init_params(model, r),
                            jax.random.PRNGKey(0))
    tree = oracle.tree_specs(shapes)
    flat_tree = {path_str(p): s for p, s in
                 jax.tree_util.tree_flatten_with_path(
                     tree, is_leaf=lambda x: not isinstance(x, dict))[0]}
    manifest = {path_str(p): tuple(l.shape) for p, l in
                jax.tree_util.tree_flatten_with_path(shapes)[0]}
    flat = oracle.flat_specs(manifest)
    assert set(flat) == set(flat_tree)
    for k in flat:
        assert flat[k] == flat_tree[k], k
    # at least one leaf actually shards over each axis class
    assert any("tensor" in str(s) for s in flat.values())
    assert any("data" in str(s) for s in flat.values())


def test_plan_mesh_keeps_divisible_axes_and_sheds_outermost_first():
    assert plan_mesh(8, {"tensor": 2})["tensor"] == 2
    assert plan_mesh(8, {"tensor": 2})["data"] == 4
    # tensor no longer divides 6 → folded into data
    p6 = plan_mesh(6, {"tensor": 4})
    assert p6["tensor"] == 1 and p6["data"] == 6
    # pipe shed before tensor (outermost-first)
    p = plan_mesh(6, {"pipe": 4, "tensor": 2})
    assert p["pipe"] == 1 and p["tensor"] == 2 and p["data"] == 3
    # pure shrink
    assert plan_mesh(3, {"data": 8})["data"] == 3
    with pytest.raises(ValueError):
        plan_mesh(0)


# ---------------------------------------------------------------------------
# Universal-checkpoint resharding matrix (save 2×4 → load 4×2 / 8×1 / 6)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def saved_2x4(tmp_path_factory):
    ckdir = str(tmp_path_factory.mktemp("u24"))
    model = get_model_config("gpt2-tiny")
    engine = _mk_engine(model, _cfg({"data": 2, "tensor": 4}))
    rng = np.random.default_rng(0)
    batch = make_lm_batch(rng, 8, 16, model.vocab_size)
    losses = _train(engine, [batch] * 3)
    engine.save_checkpoint(ckdir, tag="ck")
    udir = ds_to_universal(ckdir, tag="ck")
    flat = {}
    import jax

    from deepspeed_tpu.resilience.oracle import path_str

    for p, leaf in jax.tree_util.tree_flatten_with_path(engine.params)[0]:
        flat[path_str(p)] = np.asarray(leaf)
    cont = _train(engine, [batch] * 2)  # unkilled continuation reference
    return model, ckdir, udir, batch, flat, losses, cont


@pytest.mark.parametrize("mesh", [{"data": 4, "tensor": 2}, {"data": 8}])
def test_universal_reshard_matrix(saved_2x4, mesh):
    """Save on data2×tensor4, load on a different factorization: every
    param leaf BITWISE equal to the source, and the N-step loss curve
    continues exactly like the unkilled engine's."""
    import jax

    from deepspeed_tpu.resilience.oracle import path_str

    model, ckdir, udir, batch, flat, _, cont = saved_2x4
    engine2 = _mk_engine(model, _cfg(mesh), seed=99)
    load_universal(engine2, udir)
    assert engine2.global_steps == 3
    for p, leaf in jax.tree_util.tree_flatten_with_path(engine2.params)[0]:
        np.testing.assert_array_equal(np.asarray(leaf), flat[path_str(p)],
                                      err_msg=path_str(p))
    cont_b = _train(engine2, [batch] * 2)
    np.testing.assert_allclose(cont, cont_b, rtol=2e-4, atol=2e-4)
    engine2.destroy()


def test_universal_reshard_shrunk_world(saved_2x4):
    """The elastic-resume case proper: the 8-device world shrank to 6
    (a host died); the oracle reshards the same checkpoint onto the
    survivors' mesh."""
    import jax

    from deepspeed_tpu.parallel.topology import MeshTopology
    from deepspeed_tpu.resilience.oracle import path_str

    model, ckdir, udir, batch, flat, _, cont = saved_2x4
    n_dev = len(jax.devices())
    shrunk = n_dev - 2
    mesh = plan_mesh(shrunk, {"tensor": 4})  # tensor4 no longer fits → data
    assert mesh["data"] == shrunk and mesh["tensor"] == 1
    topo = MeshTopology({"data": shrunk}, devices=jax.devices()[:shrunk])
    cfg = _cfg({"data": shrunk})
    cfg["train_batch_size"] = 8 * shrunk          # divisible by dp=6
    cfg["train_micro_batch_size_per_gpu"] = 8
    engine2 = _mk_engine(model, cfg, seed=17, topology=topo)
    load_universal(engine2, udir)
    for p, leaf in jax.tree_util.tree_flatten_with_path(engine2.params)[0]:
        np.testing.assert_array_equal(np.asarray(leaf), flat[path_str(p)],
                                      err_msg=path_str(p))
    assert engine2.global_steps == 3
    engine2.destroy()


def test_universal_dtype_validation_raises(saved_2x4, tmp_path):
    """A float leaf cannot silently land in an int template: same-kind
    cast validation trips BEFORE any engine state mutates."""
    model, ckdir, udir, *_ = saved_2x4
    from deepspeed_tpu.checkpoint.universal import _unflatten_like

    with pytest.raises(ValueError, match="dtype mismatch"):
        _unflatten_like({"x": np.zeros((2,), np.int32)},
                        {"x": np.ones((2,), np.float32)})
    # same-kind (f64→f32) casts fine
    out = _unflatten_like({"x": np.zeros((2,), np.float32)},
                          {"x": np.ones((2,), np.float64)})
    assert out["x"].dtype == np.float32


# ---------------------------------------------------------------------------
# Crash-atomic commit
# ---------------------------------------------------------------------------

def _fake_committed(root, tag, steps):
    udir = os.path.join(root, tag, "universal")
    os.makedirs(os.path.join(udir, "params"), exist_ok=True)
    os.makedirs(os.path.join(udir, "optimizer"), exist_ok=True)
    with open(os.path.join(udir, "meta.json"), "w") as f:
        json.dump({"global_steps": steps}, f)
    with open(os.path.join(udir, COMMIT_MARKER), "w") as f:
        f.write("{}")
    return udir


def test_resolve_skips_uncommitted_tags(tmp_path):
    """The exact state a worker killed mid-save leaves behind: `latest`
    points at a tag whose conversion never committed — resolve must fall
    back to the newest COMMITTED tag, not crash on the torn one."""
    root = str(tmp_path)
    good = _fake_committed(root, "step2", steps=2)
    _fake_committed(root, "step1", steps=1)
    # step3: save died mid-write — staging dir only, no final universal
    staging = os.path.join(root, "step3", "universal.tmp-12345")
    os.makedirs(os.path.join(staging, "params"))
    with open(os.path.join(staging, "meta.json"), "w") as f:
        json.dump({"global_steps": 3}, f)
    with open(os.path.join(root, "latest"), "w") as f:
        f.write("step3")

    assert resolve_universal_dir(root) == good  # newest committed wins

    # a torn final dir (marker missing — e.g. rsync'd partial) is skipped
    torn = os.path.join(root, "step4", "universal")
    os.makedirs(torn)
    with open(os.path.join(torn, "meta.json"), "w") as f:
        json.dump({"global_steps": 4}, f)
    with open(os.path.join(root, "latest"), "w") as f:
        f.write("step4")
    assert resolve_universal_dir(root) == good
    with pytest.raises(FileNotFoundError, match="uncommitted"):
        resolve_universal_dir(torn)


def test_mid_save_kill_leaves_previous_tag_resumable(tmp_path):
    """True mid-save kill: a subprocess converts a real checkpoint and is
    SIGKILLed inside the write; the final universal dir must not exist
    (staging protocol) and resolve must land on the earlier committed
    tag."""
    root = str(tmp_path)
    code = f"""
import os, sys
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import deepspeed_tpu as ds
from deepspeed_tpu.checkpoint.universal import ds_to_universal
from deepspeed_tpu.models import get_model_config
model = get_model_config("gpt2-tiny")
engine, _, _, _ = ds.initialize(model=model, config={{
    "train_micro_batch_size_per_gpu": 4,
    "optimizer": {{"type": "AdamW", "params": {{"lr": 1e-3}}}},
    "zero_optimization": {{"stage": 2}}, "steps_per_print": 1000}})
engine.save_checkpoint({root!r}, tag="a")
ds_to_universal({root!r}, tag="a")           # commits cleanly
engine.save_checkpoint({root!r}, tag="b")    # latest -> b
import deepspeed_tpu.checkpoint.universal as u
orig = u._save_flat
def dying(flat, out_root):
    orig(flat, out_root)
    os.kill(os.getpid(), 9)                  # die mid-conversion
u._save_flat = dying
ds_to_universal({root!r}, tag="b")
"""
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == -9, proc.stderr[-2000:]
    assert not os.path.exists(os.path.join(root, "b", "universal")), \
        "killed save must not publish a final universal dir"
    with open(os.path.join(root, "latest")) as f:
        assert f.read().strip() == "b"       # pointer names the torn tag
    resolved = resolve_universal_dir(root)   # ...and resolve skips it
    assert resolved == os.path.join(root, "a", "universal")


def test_orbax_latest_deferred_until_async_commit(tmp_path):
    """The orbax writer's crash-atomicity: an async save publishes
    meta.json + `latest` only at wait() — a process killed mid-stream
    leaves the previous pointer intact."""
    model = get_model_config("gpt2-tiny")
    cfg = _cfg({"data": 8}, checkpoint={"writer": {"type": "orbax"},
                                        "async_save": True})
    engine = _mk_engine(model, cfg)
    rng = np.random.default_rng(0)
    _train(engine, [make_lm_batch(rng, 8, 16, model.vocab_size)] * 1)
    engine.save_checkpoint(str(tmp_path), tag="t1")
    assert not os.path.exists(os.path.join(str(tmp_path), "latest")), \
        "latest must not exist before the async save commits"
    engine.checkpoint_engine.wait()
    with open(os.path.join(str(tmp_path), "latest")) as f:
        assert f.read().strip() == "t1"
    engine.destroy()


# ---------------------------------------------------------------------------
# Escalating group stop
# ---------------------------------------------------------------------------

def test_stop_group_escalates_sigterm_to_sigkill():
    """A wedged worker swallowing SIGTERM used to block restart forever
    (per-process serial 30 s waits, kill never awaited); now the group
    shares ONE deadline and stragglers are SIGKILLed."""
    from deepspeed_tpu.elasticity import stop_group

    code = ("import signal, time\n"
            "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
            "print('armed', flush=True)\n"
            "time.sleep(600)\n")
    procs = [subprocess.Popen([sys.executable, "-c", code],
                              stdout=subprocess.PIPE, text=True)
             for _ in range(2)]
    for p in procs:
        assert p.stdout.readline().strip() == "armed"  # handler installed
    t0 = time.monotonic()
    stop_group(procs, stop_timeout_s=1.0)
    elapsed = time.monotonic() - t0
    assert all(p.poll() is not None for p in procs)
    assert any(p.returncode == -signal.SIGKILL for p in procs)
    assert elapsed < 15.0, elapsed


def test_stop_group_graceful_workers_not_killed():
    procs = [subprocess.Popen([sys.executable, "-c",
                               "import time; time.sleep(600)"])]
    from deepspeed_tpu.elasticity import stop_group

    stop_group(procs, stop_timeout_s=10.0)
    assert procs[0].returncode == -signal.SIGTERM  # TERM sufficed


# ---------------------------------------------------------------------------
# Supervisor: watchdog → agent → resume (the chaos e2e)
# ---------------------------------------------------------------------------

def _mk_telemetry(tmpdir):
    from deepspeed_tpu.runtime.config import TelemetryConfig
    from deepspeed_tpu.telemetry import Telemetry

    return Telemetry(TelemetryConfig(
        enabled=True,
        jsonl_path=os.path.join(tmpdir, "steps.jsonl"),
        tracing={"enabled": True,
                 "trace_path": os.path.join(tmpdir, "t.trace.json")},
        flight={"enabled": True, "output_dir": os.path.join(tmpdir,
                                                            "flight")}))


def test_supervisor_chaos_crash_resize_resume(tmp_path):
    """THE acceptance e2e: a worker killed mid-run → flight bundle →
    group stopped → mesh re-planned SMALLER (its host is gone) →
    restarted → universal resume through the oracle → loss curve lands
    on the unkilled reference — with the outage measured as recovery.*
    spans and a goodput-gap StepRecord."""
    from deepspeed_tpu.resilience.supervisor import (RecoverySupervisor,
                                                     loss_curve)

    total, die_at = 5, 2
    wenv = {"DSTPU_SEQ": "16", "DSTPU_BATCH": "8"}

    ref_dir = str(tmp_path / "ref")
    ref = RecoverySupervisor(
        ref_dir, hosts_fn=lambda: ["h0", "h1"], devices_per_host=2,
        total_steps=total, deadline_s=60.0, poll_s=0.2,
        worker_env=dict(wenv)).run()
    assert ref.returncode == 0 and ref.recoveries == 0
    ref_losses = loss_curve(ref.progress_path)
    assert sorted(ref_losses) == list(range(1, total + 1))

    chaos_dir = str(tmp_path / "chaos")
    os.makedirs(chaos_dir)
    sentinel = os.path.join(chaos_dir, ".chaos_fired")
    tel = _mk_telemetry(chaos_dir)
    sup = RecoverySupervisor(
        chaos_dir,
        # the dying worker arms the sentinel first: host h1 dies with it
        hosts_fn=lambda: ["h0"] if os.path.exists(sentinel)
        else ["h0", "h1"],
        devices_per_host=2, total_steps=total, deadline_s=60.0,
        poll_s=0.2, stop_timeout_s=10.0, resume_deadline_s=240.0,
        telemetry=tel,
        worker_env={**wenv, "DSTPU_CHAOS": json.dumps({"die_at": die_at})})
    res = sup.run()

    # recovered, once, onto a SHRUNK mesh
    assert res.returncode == 0 and res.recoveries == 1
    assert res.outages[0]["resized"] and res.mesh == {"data": 2}
    states = [e.state for e in res.events]
    for s in ("detected", "dumped", "stopped", "replanned", "restarted",
              "resumed"):
        assert s in states, (s, states)
    assert states.index("detected") < states.index("stopped") \
        < states.index("restarted") < states.index("resumed")

    # flight bundle on disk with the frozen `recovery` reason
    bundle = res.outages[0]["bundle"]
    with open(os.path.join(bundle, "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["reason"] == "recovery"
    assert os.path.exists(os.path.join(bundle, "stacks.txt"))

    # loss continuity: every step of the resumed curve matches the
    # unkilled run (the recomputed crash step included)
    curve = loss_curve(res.progress_path)
    assert sorted(curve) == list(range(1, total + 1))
    for s in range(1, total + 1):
        assert abs(curve[s] - ref_losses[s]) < 2e-3, (s, curve[s],
                                                      ref_losses[s])

    # goodput-gap StepRecord: kind=recovery, skipped, outage priced in
    rec = tel.last_record
    assert rec is not None and rec.kind == "recovery"
    assert rec.skipped and rec.wall_time_s > 0
    assert rec.wall_time_s == pytest.approx(res.outages[0]["outage_s"],
                                            rel=0.2)

    # recovery.* spans/instants in the trace
    events = {e["name"] for e in tel.tracer.snapshot()}
    assert {"recovery.outage", "recovery.detected", "recovery.replan",
            "recovery.restart", "recovery.resumed"} <= events
    tel.close()


def test_supervisor_hang_watchdog_recovery(tmp_path):
    """Detection channel 2: the worker stops heartbeating (wedged, TERM
    ignored) — the supervisor's Watchdog fires, escalation clears the
    worker, and the run still completes."""
    from deepspeed_tpu.resilience.supervisor import RecoverySupervisor

    d = str(tmp_path / "hang")
    sup = RecoverySupervisor(
        d, hosts_fn=lambda: ["h0"], devices_per_host=1, total_steps=3,
        deadline_s=6.0, poll_s=0.2, stop_timeout_s=2.0,
        resume_deadline_s=240.0,
        worker_env={"DSTPU_SEQ": "16", "DSTPU_BATCH": "4",
                    "DSTPU_CHAOS": json.dumps({"hang_at": 1,
                                               "ignore_term": True})})
    res = sup.run()
    assert res.returncode == 0 and res.recoveries >= 1
    assert res.outages[0]["reason"] == "hang"


def test_supervisor_max_recoveries_budget(tmp_path):
    """A worker that dies instantly every time must exhaust the budget
    and fail LOUDLY, not loop forever."""
    from deepspeed_tpu.resilience.supervisor import (RecoveryFailed,
                                                     RecoverySupervisor)

    sup = RecoverySupervisor(
        str(tmp_path / "doom"), hosts_fn=lambda: ["h0"],
        devices_per_host=1, total_steps=3, deadline_s=30.0, poll_s=0.1,
        stop_timeout_s=2.0, resume_deadline_s=30.0, max_recoveries=1,
        worker_cmd=[sys.executable, "-c", "import sys; sys.exit(3)"])
    with pytest.raises(RecoveryFailed, match="budget"):
        sup.run()
    assert [e.state for e in sup.events].count("restarted") == 1
    assert sup.events[-1].state == "failed"


def test_record_recovery_goodput_gap():
    """Telemetry.record_recovery: one outage = one skipped step in the
    cumulative goodput, schema-stable JSONL."""
    from deepspeed_tpu.runtime.config import TelemetryConfig
    from deepspeed_tpu.telemetry import Telemetry

    tel = Telemetry(TelemetryConfig(enabled=True))
    for s in range(1, 4):
        tel.record_train_step(step=s, wall_time_s=0.1, tokens=128)
    rec = tel.record_recovery(step=3, outage_s=42.5)
    assert rec.kind == "recovery" and rec.skipped
    assert rec.wall_time_s == 42.5
    assert rec.goodput == pytest.approx(3 / 4)
    d = json.loads(rec.to_json())
    assert list(d) == sorted(d) and d["schema"] == 3


# ---------------------------------------------------------------------------
# Serving: live grow / shrink / respawn through the same oracle
# ---------------------------------------------------------------------------

def test_replicaset_grow_shrink_respawn_live():
    from deepspeed_tpu.serving import ReplicaSet, Router, SamplingParams

    model = get_model_config("llama-tiny")
    eng_cfg = {"dtype": "float32",
               "memory_config": {"num_blocks": 32, "block_size": 4},
               "max_context": 64}
    # per-replica slices of 2 over 8 devices: room to grow to 4
    rs = ReplicaSet.build(model, 2, eng_cfg, {}, seed=0,
                          devices_per_replica=2)
    router = Router(rs).start()
    try:
        rng = np.random.default_rng(5)
        prompts = [rng.integers(1, model.vocab_size, size=8).tolist()
                   for _ in range(6)]
        expected = router.generate(prompts, max_new_tokens=8)

        # GROW: new replica on the next free slice, serving immediately
        r2 = rs.grow()
        assert len(rs) == 3 and r2.index == 2 and r2.alive
        out = r2.server.generate([prompts[0]], max_new_tokens=8)
        assert out[0] == expected[0]        # bit-identical weights
        # router dispatches to it and the per-replica counter appears
        for i, p in enumerate(prompts):
            router.submit(p, SamplingParams(max_new_tokens=4),
                          session=f"s{i}")
        time.sleep(0.1)
        snap = router.snapshot()
        assert "r2" in snap["routed"]

        # SHRINK: victim's slice frees; survivors keep serving
        rs.shrink(2)
        assert len(rs) == 2
        assert router.generate([prompts[1]],
                               max_new_tokens=8)[0] == expected[1]

        # RESPAWN: kill r0 mid-stream → fail-over covers the request,
        # then the replica re-grows on its own slice and serves again
        s = router.submit(prompts[2], SamplingParams(max_new_tokens=24))
        it = iter(s)
        got = [next(it)]                    # demonstrably mid-stream
        rs[0].kill()
        for tok in it:
            got.append(tok)
        full = router.generate([prompts[2]], max_new_tokens=24)[0]
        assert got == full                  # bit-identical across the kill
        fresh = rs.respawn(0)
        assert fresh.alive and rs[0] is fresh
        out = fresh.server.generate([prompts[3]], max_new_tokens=8)
        assert out[0] == expected[3]
    finally:
        router.stop(timeout=60.0)


def test_replicaset_respawn_requires_dead_replica():
    from deepspeed_tpu.serving import ReplicaSet

    model = get_model_config("llama-tiny")
    eng_cfg = {"dtype": "float32",
               "memory_config": {"num_blocks": 16, "block_size": 4},
               "max_context": 32}
    rs = ReplicaSet.build(model, 2, eng_cfg, {}, seed=0).start()
    try:
        with pytest.raises(RuntimeError, match="alive"):
            rs.respawn(0)
        with pytest.raises(ValueError, match="last replica"):
            rs.shrink(0)
            rs.shrink(1)
    finally:
        rs.stop(drain=False, timeout=30.0)
