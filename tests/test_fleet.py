"""Fleet observability plane (serving/fleet.py + telemetry/slo.py):
SLO specs and ledgers, frozen-schema TierSnapshot sampling — including
under live ``grow()/shrink()/respawn()`` — and the stitched cross-tier
disagg trace: prefill leg, KV handoff, and decode leg chained under ONE
caller-visible trace_id.
"""

import json
import threading
import time

import numpy as np
import pytest

from deepspeed_tpu.models import get_model_config
from deepspeed_tpu.runtime.config import TelemetryConfig
from deepspeed_tpu.serving import (REQUEST_TIMELINE_KEYS,
                                   TIER_SNAPSHOT_KEYS,
                                   TIER_SNAPSHOT_SCHEMA, DisaggRouter,
                                   FleetSampler, ReplicaSet, Router,
                                   SamplingParams, ServingMetrics)
from deepspeed_tpu.telemetry import Telemetry
from deepspeed_tpu.telemetry.slo import (SLO_BLOCK_KEYS, SLO_LEDGER_KEYS,
                                         SLO_SCENARIO_KEYS, SLOLedger,
                                         SLOSpec)

ENG_CFG = {"dtype": "float32",
           "memory_config": {"num_blocks": 64, "block_size": 4},
           "max_context": 64}

DISAGG = {"enabled": True, "prefill_replicas": 1, "decode_replicas": 1,
          "speculative": {"enabled": True, "draft_model": "llama-tiny",
                          "spec_k": 3}}


def _model(layers=1):
    return get_model_config("llama-tiny", num_layers=layers)


def _prompts(model, sizes, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, model.vocab_size, size=n).tolist()
            for n in sizes]


# ---------------------------------------------------------------------------
# SLOSpec / SLOLedger (pure stdlib — no serving stack)
# ---------------------------------------------------------------------------

def test_slo_spec_targets_overrides_and_validation():
    spec = SLOSpec({"enabled": True, "ttft_p95_ms": 100.0,
                    "tpot_p95_ms": 10.0,
                    "scenario_overrides": {
                        "long_prompt_short_decode": {"ttft_p95_ms": 200.0}}})
    assert spec.enabled and spec.objective == 0.99
    assert spec.targets_for() == {"ttft_p95_ms": 100.0,
                                  "tpot_p95_ms": 10.0,
                                  "queue_wait_p95_ms": 0.0}
    # override is partial: unnamed targets keep the base value
    assert spec.targets_for("long_prompt_short_decode") == {
        "ttft_p95_ms": 200.0, "tpot_p95_ms": 10.0,
        "queue_wait_p95_ms": 0.0}
    assert spec.targets_for("unknown_mix") == spec.targets_for()
    with pytest.raises(ValueError, match="objective"):
        SLOSpec({"objective": 1.5})
    with pytest.raises(ValueError, match="must be >= 0"):
        SLOSpec({"ttft_p95_ms": -1})
    with pytest.raises(ValueError, match="unknown"):
        SLOSpec({"scenario_overrides": {"burst": {"ttft_p50_ms": 5}}})


def test_slo_evaluate_frozen_block_and_per_scenario_attainment():
    spec = SLOSpec({"enabled": True, "ttft_p95_ms": 100.0,
                    "tpot_p95_ms": 10.0, "objective": 0.9,
                    "scenario_overrides": {"long": {"ttft_p95_ms": 500.0}}})
    reqs = (
        # chat: 3 good, 1 TTFT violation, 1 TPOT violation
        [{"scenario": "chat", "ttft_ms": 50.0, "tpot_ms": 5.0}] * 3
        + [{"scenario": "chat", "ttft_ms": 150.0, "tpot_ms": 5.0},
           {"scenario": "chat", "ttft_ms": 50.0, "tpot_ms": 20.0},
           # long: 300 ms TTFT violates the base target but NOT the
           # scenario override — must count as attained
           {"scenario": "long", "ttft_ms": 300.0, "tpot_ms": 5.0},
           # one-token request: no TPOT measurement ⇒ attained
           {"scenario": "long", "ttft_ms": 50.0, "tpot_ms": None}])
    block = spec.evaluate(reqs)
    assert tuple(sorted(block)) == SLO_BLOCK_KEYS
    assert block["violations"] == 2
    assert block["attainment"] == round(1 - 2 / 7, 3)
    # burn: 2 violations over the (1-0.9)*7 = 0.7 allowed
    assert block["error_budget_burn"] == round(2 / 0.7, 3)
    assert set(block["by_scenario"]) == {"chat", "long"}
    for entry in block["by_scenario"].values():
        assert tuple(sorted(entry)) == SLO_SCENARIO_KEYS
    chat = block["by_scenario"]["chat"]
    assert (chat["n"], chat["violations"]) == (5, 2)
    assert chat["ttft_attainment"] == round(1 - 1 / 5, 3)
    assert chat["tpot_attainment"] == round(1 - 1 / 5, 3)
    long_ = block["by_scenario"]["long"]
    assert (long_["n"], long_["violations"]) == (2, 0)
    # zero-budget objective exports a finite burn, never Infinity
    tight = SLOSpec({"enabled": True, "ttft_p95_ms": 1.0,
                     "objective": 1.0})
    burn = tight.evaluate([{"scenario": "x", "ttft_ms": 99.0}])
    assert burn["error_budget_burn"] == 999.0
    assert json.loads(json.dumps(burn))   # JSON-safe throughout


def test_slo_ledger_streaming_per_tier():
    spec = SLOSpec({"enabled": True, "ttft_p95_ms": 100.0})
    ledger = SLOLedger(spec)
    assert ledger.observe("decode", 50.0, 0.0, 0.0) is False
    assert ledger.observe("decode", 150.0, 0.0, 0.0) is True
    assert ledger.observe("prefill", 10.0, 0.0, 0.0) is False
    snap = ledger.snapshot()
    assert set(snap) == {"decode", "prefill"}
    for row in snap.values():
        assert tuple(sorted(row)) == SLO_LEDGER_KEYS
    # 1 violation over the (1-0.99)*2 = 0.02 ticks the budget allows
    assert snap["decode"] == {"ticks": 2, "violations": 1,
                              "attainment": 0.5,
                              "error_budget_burn": 50.0}
    assert snap["prefill"]["attainment"] == 1.0


def test_serving_slo_config_block_round_trips():
    from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                              DeepSpeedConfigError)

    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "serving": {"n_replicas": 1, "metrics_window_s": 30.0,
                    "slo": {"enabled": True, "ttft_p95_ms": 2000.0,
                            "objective": 0.95,
                            "scenario_overrides": {
                                "burst": {"tpot_p95_ms": 50.0}}}},
    })
    assert cfg.serving.slo.enabled
    assert cfg.serving.server_config()["metrics_window_s"] == 30.0
    spec = SLOSpec(cfg.serving.slo_config())
    assert spec.objective == 0.95
    assert spec.targets_for("burst")["tpot_p95_ms"] == 50.0
    for bad in ({"slo": {"objective": 0.0}},
                {"slo": {"ttft_p95_ms": -5}},
                {"slo": {"scenario_overrides": {"b": {"nope": 1}}}},
                {"metrics_window_s": -1}):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                             "serving": {"n_replicas": 1, **bad}})


# ---------------------------------------------------------------------------
# FleetSampler over a fake fleet (schema, pooling, rates, liveness)
# ---------------------------------------------------------------------------

class _FakeEngine:
    free_blocks = 10


class _FakeServer:
    def __init__(self, metrics):
        self.metrics = metrics
        self.admission = [None] * 2      # len() == queue depth
        self._active = {1: None}         # len() == running
        self.prefix_cache = None


class _FakeReplica:
    def __init__(self, tier, window_s=0.0):
        self.tier = tier
        self.alive = True
        self.engine = _FakeEngine()
        self.server = _FakeServer(ServingMetrics(window_s=window_s))
        self.kv_headroom = 0.75


def test_fleet_sampler_schema_pooling_rates_and_jsonl(tmp_path):
    a, b = _FakeReplica("decode"), _FakeReplica("decode")
    p = _FakeReplica("prefill")
    jsonl = str(tmp_path / "fleet.jsonl")
    sampler = FleetSampler([a, b, p], cadence_s=0.01, jsonl_path=jsonl)
    # pooled percentiles: b's slow outlier must dominate the tier p95
    # even though a holds most of the samples (never average p95s)
    for _ in range(9):
        a.server.metrics.record_first_token(0.010)
    b.server.metrics.record_first_token(0.200)
    a.server.metrics.record_tokens(30)
    a.server.metrics.record_spec_round(proposed=10, accepted=8)
    snap1 = sampler.sample_once()
    assert set(snap1) == {"decode", "prefill"}
    for tier, row in snap1.items():
        assert tuple(sorted(row)) == TIER_SNAPSHOT_KEYS
        assert row["schema"] == TIER_SNAPSHOT_SCHEMA
        assert row["tier"] == tier
    d = snap1["decode"]
    assert d["replicas_alive"] == 2
    assert d["queue_depth"] == 4 and d["running"] == 2
    assert d["evictable_headroom_blocks"] == 20
    assert d["kv_utilization"] == 0.25
    assert d["ttft_p95_ms"] > 100.0          # pooled, not averaged
    assert d["spec_accept_rate"] == 0.8
    assert d["tokens_per_sec"] == 0.0        # no previous tick yet
    # rates are deltas over the tick gap
    a.server.metrics.record_tokens(50)
    time.sleep(0.02)
    snap2 = sampler.sample_once()
    assert snap2["decode"]["tokens_per_sec"] > 0
    assert snap2["prefill"]["tokens_per_sec"] == 0.0
    assert snap2["decode"]["tick"] == 2
    # standalone registry hosts the per-tier gauges
    names = {m.name for m in sampler.registry.collect()}
    assert "fleet_decode_ttft_p95_ms" in names
    assert "fleet_prefill_queue_depth" in names
    # JSONL: one sorted-key line per tier per tick, schema-stamped
    with open(jsonl) as f:
        lines = [json.loads(ln) for ln in f]
    assert len(lines) == 4
    for row in lines:
        assert tuple(sorted(row)) == TIER_SNAPSHOT_KEYS
    assert sampler.history()[-1]["tick"] == 2
    assert sampler.latest() == snap2


def test_fleet_sampler_dead_replica_drops_within_one_tick():
    a, b = _FakeReplica("decode"), _FakeReplica("decode")
    sampler = FleetSampler([a, b], cadence_s=0.01)
    assert sampler.sample_once()["decode"]["replicas_alive"] == 2
    b.alive = False
    assert sampler.sample_once()["decode"]["replicas_alive"] == 1
    a.alive = False                      # whole tier dark: no row at all
    assert sampler.sample_once() == {}
    assert sampler.latest() == {}
    # a dark tier's gauges are zeroed, not left at last-known-good — a
    # registry consumer must not keep seeing a healthy-looking dead tier
    g = sampler.registry.get("fleet_decode_replicas_alive")
    assert g is not None and g.value == 0.0
    assert sampler.registry.get("fleet_decode_queue_depth").value == 0.0
    b.alive = True                       # revival re-enters cleanly
    snap = sampler.sample_once()
    assert snap["decode"]["replicas_alive"] == 1
    assert snap["decode"]["tokens_per_sec"] == 0.0   # rates restarted


def test_fleet_sampler_manual_tick_safe_against_cadence_thread():
    # a manual sample_once() (bench tail tick) may overlap the cadence
    # thread; whole ticks are serialised, so ring rows never interleave
    # across ticks and rates never pair one tick's clock with another's
    # counters
    a, b = _FakeReplica("decode"), _FakeReplica("prefill")
    with FleetSampler([a, b], cadence_s=0.001) as sampler:
        for _ in range(50):
            a.server.metrics.record_tokens(5)
            out = sampler.sample_once()
            assert set(out) == {"decode", "prefill"}
            for row in out.values():
                assert tuple(sorted(row)) == TIER_SNAPSHOT_KEYS
                assert row["tokens_per_sec"] >= 0.0
    hist = sampler.history()
    ticks = [r["tick"] for r in hist]
    assert ticks == sorted(ticks)        # ticks appended atomically
    # within a tick the two tier rows are adjacent, never split by
    # another tick's rows
    for i in range(0, len(hist) - 1):
        if ticks[i] == ticks[i + 1]:
            assert {hist[i]["tier"], hist[i + 1]["tier"]} == \
                {"decode", "prefill"}


def test_fleet_sampler_slo_ledger_and_violation_flag():
    rep = _FakeReplica("decode")
    spec = SLOSpec({"enabled": True, "ttft_p95_ms": 50.0})
    sampler = FleetSampler([rep], slo=spec, cadence_s=0.01)
    rep.server.metrics.record_first_token(0.010)
    assert sampler.sample_once()["decode"]["slo_violation"] == 0
    rep.server.metrics.record_first_token(0.500)
    assert sampler.sample_once()["decode"]["slo_violation"] == 1
    ledger = sampler.slo_snapshot()
    assert tuple(sorted(ledger["decode"])) == SLO_LEDGER_KEYS
    assert ledger["decode"]["ticks"] == 2
    assert ledger["decode"]["violations"] == 1
    # a disabled spec means no ledger at all
    off = FleetSampler([rep], slo=SLOSpec({"enabled": False,
                                           "ttft_p95_ms": 50.0}))
    off.sample_once()
    assert off.slo_snapshot() == {}


def test_fleet_sampler_cadence_thread_and_validation():
    rep = _FakeReplica("unified")
    with pytest.raises(ValueError, match="cadence_s"):
        FleetSampler([rep], cadence_s=0.0)
    with FleetSampler([rep], cadence_s=0.01) as sampler:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not sampler.latest():
            time.sleep(0.01)
        assert sampler.latest()["unified"]["replicas_alive"] == 1
        with pytest.raises(RuntimeError, match="already started"):
            sampler.start()
    assert sampler._thread is None       # stopped on exit


# ---------------------------------------------------------------------------
# live fleet: sampling across grow / shrink / kill / respawn
# ---------------------------------------------------------------------------

def test_fleet_sampler_live_grow_shrink_respawn():
    model = _model()
    eng_cfg = {"dtype": "float32",
               "memory_config": {"num_blocks": 32, "block_size": 4},
               "max_context": 64}
    rs = ReplicaSet.build(model, 2, eng_cfg,
                          {"metrics_window_s": 60.0}, seed=0,
                          devices_per_replica=2)
    router = Router(rs).start()
    sampler = FleetSampler(rs, router=router, cadence_s=0.02).start()
    try:
        prompts = _prompts(model, [8] * 4, seed=7)
        router.generate(prompts, max_new_tokens=6)
        snap = sampler.sample_once()
        assert snap["unified"]["replicas_alive"] == 2
        assert tuple(sorted(snap["unified"])) == TIER_SNAPSHOT_KEYS
        assert snap["unified"]["ttft_p95_ms"] > 0.0

        rs.grow()                        # r2 joins on the next free slice
        assert sampler.sample_once()["unified"]["replicas_alive"] == 3
        rs.shrink(2)
        assert sampler.sample_once()["unified"]["replicas_alive"] == 2

        rs[0].kill()                     # dead drops within ONE tick
        assert sampler.sample_once()["unified"]["replicas_alive"] == 1
        rs.respawn(0)                    # ...and re-enters the rollup
        snap = sampler.sample_once()
        assert snap["unified"]["replicas_alive"] == 2
        assert tuple(sorted(snap["unified"])) == TIER_SNAPSHOT_KEYS
        # survivors still serve while the cadence thread keeps ticking
        out = router.generate([prompts[0]], max_new_tokens=6)
        assert len(out[0]) == 6
    finally:
        sampler.stop()
        router.stop(timeout=60.0)


# ---------------------------------------------------------------------------
# acceptance: stitched cross-tier trace under ONE trace_id + timeline
# ---------------------------------------------------------------------------

def test_disagg_trace_stitches_tiers_under_one_trace_id(tmp_path):
    trace_path = str(tmp_path / "disagg.trace.json")
    tel = Telemetry(TelemetryConfig(
        enabled=True, tracing={"enabled": True,
                               "trace_path": trace_path}))
    model = _model()
    rs = ReplicaSet.build(model, 2, ENG_CFG, seed=0, disagg=DISAGG)
    router = DisaggRouter(rs, telemetry=tel).start()
    try:
        prompt = _prompts(model, [9], seed=3)[0]
        stream = router.submit(prompt, SamplingParams(max_new_tokens=8))
        toks = [t for t in stream]
        assert len(toks) == 8
        trace_id = stream.trace_id
        assert trace_id
        # the flat per-request timeline mirrors the same trace_id
        tl = stream.timeline
        assert tl is not None
        assert tuple(sorted(tl)) == REQUEST_TIMELINE_KEYS
        assert tl["trace_id"] == trace_id
        assert tl["prefill_ms"] > 0 and tl["decode_ms"] > 0
        assert tl["handoff_bytes"] > 0 and tl["failovers"] == 0
        assert tl["total_ms"] >= tl["prefill_ms"]
        assert router.timelines()[-1] == tl
    finally:
        router.stop()
    tel.close()                          # exports the Chrome trace

    from tools.telemetry_check import validate_chrome_trace
    assert validate_chrome_trace(trace_path) == []
    with open(trace_path) as f:
        events = [e for e in json.load(f)["traceEvents"]
                  if e["ph"] in ("X", "i")]
    mine = [e for e in events if e["args"].get("trace_id") == trace_id]
    names = {e["name"] for e in mine}
    # prefill leg, KV handoff, and decode leg all chained under the ONE
    # caller-visible trace_id
    for want in ("router.request", "router.leg", "serve.request",
                 "serve.prefill", "serve.handoff", "serve.decode"):
        assert want in names, (want, sorted(names))
    # exactly one root; every serve.request (one per tier leg) is
    # parented under it through its router.leg
    roots = [e for e in mine if e["name"] == "router.request"]
    assert len(roots) == 1
    root_span = roots[0]["args"]["span_id"]
    leg_parents = {e["args"]["parent_id"] for e in mine
                   if e["name"] == "router.leg"}
    assert leg_parents == {root_span}
    # both tiers ran a serve.request under this trace
    serve_reqs = [e for e in mine if e["name"] == "serve.request"]
    assert len(serve_reqs) == 2
