"""Test harness: 8 virtual CPU devices.

TPU translation of the reference's distributed-without-a-cluster fixture
(tests/unit/common.py DistributedExec): instead of spawning N processes with
a file store, we run single-process JAX with
``--xla_force_host_platform_device_count=8`` so every mesh shape up to 8
"chips" is exercised for real (collectives included) on a GPU/TPU-less CI
machine — the same role the CPU accelerator plays for the reference.
"""

import os

# Must be set before the CPU backend initializes (backends are lazy, so
# setting it at conftest import is early enough even though sitecustomize
# may have imported jax already).  Optimization level 0: the CPU mesh
# exists to check numerics and collective structure, not codegen quality —
# skipping XLA:CPU's heavy optimization passes cuts suite compile time
# ~30% with identical results (measured on test_engine: 115s → 80s).
for _flag in ("--xla_force_host_platform_device_count=8",
              "--xla_backend_optimization_level=0"):
    if _flag not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " " + _flag
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

# The axon TPU plugin pins jax_platforms via jax.config at sitecustomize
# time; env vars alone cannot override it — force CPU through the config.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_global_topology():
    """Each test builds its own mesh; clear the global between tests."""
    yield
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def make_lm_batch(rng, batch: int, seq: int, vocab: int):
    ids = rng.integers(0, vocab, size=(batch, seq + 1), dtype=np.int32)
    return {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
