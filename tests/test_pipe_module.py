"""PipelineModule/LayerSpec partitioning API (ref runtime/pipe/module.py +
partition helpers in runtime/utils.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.parallel.pipe_module import (LayerSpec, PipelineModule,
                                                TiedLayerSpec,
                                                partition_balanced,
                                                partition_uniform)


def test_partition_uniform():
    assert partition_uniform(8, 4) == [0, 2, 4, 6, 8]
    assert partition_uniform(7, 3) == [0, 3, 5, 7]  # remainder up front


def test_partition_balanced_bottleneck():
    # one huge layer should sit alone in its stage
    weights = [1, 1, 100, 1, 1, 1]
    parts = partition_balanced(weights, 3)
    assert parts[0] == 0 and parts[-1] == 6
    stage_sums = [sum(weights[parts[i]:parts[i + 1]]) for i in range(3)]
    assert max(stage_sums) == 100  # optimal bottleneck
    # monotone boundaries
    assert all(a <= b for a, b in zip(parts, parts[1:]))


def test_partition_balanced_uniform_case():
    parts = partition_balanced([1] * 8, 4)
    assert parts == [0, 2, 4, 6, 8]


def _linear_init(key, n_in, n_out):
    return {"w": jax.random.normal(key, (n_in, n_out)) * 0.1}


def _linear_apply(p, x):
    return jnp.tanh(x @ p["w"])


def test_pipeline_module_parameters_partition():
    specs = [LayerSpec(_linear_apply, _linear_init, 8, 8) for _ in range(4)]
    specs += [LayerSpec(_linear_apply, _linear_init, 8, 64)]  # heavy
    pm = PipelineModule(specs, num_stages=2, partition_method="parameters")
    assert pm.parts[0] == 0 and pm.parts[-1] == 5
    # the heavy layer's stage should not also hold all light layers
    heavy_stage = pm.stage_of(4)
    assert len(pm.stage_layers(heavy_stage)) < 5
    x = jnp.ones((2, 8))
    out = pm(pm.params, x)
    assert out.shape == (2, 8) or out.shape == (2, 64)
    # forward_stage composition == full forward
    y = x
    for s in range(pm.num_stages):
        y = pm.forward_stage(pm.params, y, s)
    np.testing.assert_allclose(np.asarray(y), np.asarray(out), atol=1e-6)


def test_pipeline_module_type_partition_and_errors():
    def embed_apply(p, x):
        return x

    specs = [LayerSpec(embed_apply, _linear_init, 4, 4),
             LayerSpec(_linear_apply, _linear_init, 4, 4),
             LayerSpec(_linear_apply, _linear_init, 4, 4)]
    pm = PipelineModule(specs, num_stages=2,
                        partition_method="type:linear_apply")
    assert pm.parts[-1] == 3
    with pytest.raises(ValueError):
        PipelineModule(specs, num_stages=2, partition_method="type:nomatch")
    with pytest.raises(ValueError):
        PipelineModule(specs, num_stages=2, partition_method="bogus")


def test_tied_layer_spec_shares_params():
    specs = [TiedLayerSpec("embed", _linear_apply, _linear_init, 4, 4),
             LayerSpec(_linear_apply, _linear_init, 4, 4),
             TiedLayerSpec("embed", _linear_apply, _linear_init, 4, 4)]
    pm = PipelineModule(specs, num_stages=1, partition_method="uniform")
    assert "embed" in pm.params and len(pm.tied_comms["embed"]) == 2
    # exactly one param entry for the tied pair + one untied layer
    assert len(pm.params) == 2


def test_offload_dots_remat_policy():
    from deepspeed_tpu.models import get_model_config, init_params
    from deepspeed_tpu.models import transformer as tf

    cfg = get_model_config("gpt2-tiny").replace(dtype=jnp.float32,
                                                remat_policy="offload_dots")
    params = init_params(cfg, jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(2, 8)), jnp.int32)
    # forward+grad under the offload policy compiles and is finite
    g = jax.grad(lambda p: tf.loss_fn(
        p, {"input_ids": ids, "labels": ids}, cfg))(params)
    gn = float(jnp.sqrt(sum((x.astype(jnp.float32) ** 2).sum()
                            for x in jax.tree.leaves(g))))
    assert np.isfinite(gn) and gn > 0
