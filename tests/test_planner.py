"""Parallelism plan compiler (deepspeed_tpu/planner; docs/PLANNER.md).

Three families:

1. Regression gate — for every bench row with a pinned known-good
   config (bench.PINNED_ROW_CONFIGS), the planner's row-mirroring query
   must rank that config in its TOP-3; the 6.7B chunked-offload ladder
   rung and a MoE expert-parallel placement must be proposed
   sight-unseen.
2. Cost-model properties — step time monotone in wire bytes at fixed
   overlap; overlap credit never exceeds the comm it hides; the
   anchored-vs-extrapolated census agrees within the frozen
   ANCHOR_TOLERANCE on a real lowered audit target.
3. Plumbing — fragment round-trip through runtime.config.load_plan,
   memory-model comm residual (error-feedback) pricing, Autotuner
   planner-mode seeding, and the CLI.
"""

import json
import os
import sys

import pytest

from deepspeed_tpu.planner import (ANCHOR_TOLERANCE, PLAN_EVIDENCE_KEYS,
                                   Candidate, FleetSpec, ModelSpec, Plan,
                                   analytic_census, anchor_ratios,
                                   apply_anchors, compile_plan,
                                   plan_rank_of, seed_candidates,
                                   step_time)
from deepspeed_tpu.planner.audit import PLAN_AUDIT_ROWS, plan_for_row

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _pinned():
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    import bench

    return bench.PINNED_ROW_CONFIGS


# ---------------------------------------------------------------------
# 1. regression gate: known-good configs rank top-3
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def row_plans():
    return {name: plan_for_row(name) for name in PLAN_AUDIT_ROWS}


@pytest.mark.parametrize("name", PLAN_AUDIT_ROWS)
def test_known_good_ranks_top3(row_plans, name):
    plan = row_plans[name]
    rank = plan_rank_of(plan, _pinned()[name])
    assert rank is not None and rank <= 3, \
        (name, rank, [r.candidate for r in plan.ranked[:5]])


def test_ranked_entries_carry_frozen_evidence(row_plans):
    want = tuple(sorted(PLAN_EVIDENCE_KEYS))
    for name, plan in row_plans.items():
        assert plan.ranked, name
        for entry in plan.ranked:
            assert tuple(sorted(entry.evidence)) == want, name
            assert entry.evidence["predicted_peak_bytes"] > 0
            assert entry.evidence["predicted_step_ms"] > 0


@pytest.fixture(scope="module")
def plan_67b():
    model = ModelSpec.from_name("gpt2-6.7b", seq_len=512)
    fleet = FleetSpec(chips=1, hbm_bytes=16 << 30, host_bytes=64 << 30,
                      nvme=True)
    return compile_plan(model, fleet, max_micro_batch=4)


def test_67b_chunked_proposed_sight_unseen(plan_67b):
    """The peak_params acceptance rung: on a 1-chip 16GiB fleet with a
    64GiB host and NVMe, the planner must propose the chunked-offload
    config the r16 ladder pinned — without ever having run it."""
    rank = plan_rank_of(plan_67b, _pinned()["gpt2_6_7b_chunked"])
    assert rank is not None and rank <= 3, \
        (rank, [r.candidate for r in plan_67b.ranked])


def test_67b_losers_keep_pruning_reasons(plan_67b):
    """Device-resident tiers CANNOT hold 6.7B of optimizer state — the
    plan must say so with the dominant class and the shortfall."""
    assert plan_67b.pruned
    device_losers = [p for p in plan_67b.pruned
                     if "off:" not in p["candidate"]]
    assert device_losers
    for row in device_losers:
        assert row["reason"]
        assert row["dominant_class"]
        assert row["shortfall_bytes"] > 0
        assert row["predicted_peak_bytes"] > 16 << 30


def test_moe_expert_parallel_proposed_sight_unseen():
    """moe-1b-ep8 on 8 chips: an expert:8 placement must appear in the
    top-3 — the planner prices the all-to-all dispatch and the
    expert-sharded param win with no MoE bench row to copy from."""
    model = ModelSpec.from_name("moe-1b-ep8", seq_len=512)
    plan = compile_plan(model, FleetSpec(chips=8), max_micro_batch=8)
    assert plan.ranked
    top_meshes = [r.config.get("mesh") or {} for r in plan.ranked[:3]]
    assert any(m.get("expert") == 8 for m in top_meshes), top_meshes


# ---------------------------------------------------------------------
# 2. cost-model properties
# ---------------------------------------------------------------------

def _gpt2_350m_spec():
    return ModelSpec.from_name("gpt2-350m", seq_len=1024)


def test_step_time_monotone_in_wire_bytes():
    """At fixed overlap decisions, more bytes on the wire can never make
    the modeled step faster."""
    model = _gpt2_350m_spec()
    fleet = FleetSpec(chips=8)
    cand = Candidate(mesh={"data": 8}, zero_stage=2, micro_batch=4)
    census = analytic_census(model, cand, gas=2, fleet=fleet)
    assert census, "expected DP collectives in the census"
    prev = None
    for scale in (0.5, 1.0, 2.0, 8.0, 64.0):
        scaled = {k: {**r, "wire_bytes": int(r["wire_bytes"] * scale)}
                  for k, r in census.items()}
        t = step_time(model, cand, fleet, gas=2, census=scaled)
        if prev is not None:
            assert t["step_seconds"] >= prev - 1e-12, scale
        prev = t["step_seconds"]


def test_overlap_credit_never_exceeds_comm():
    """The credit hides comm behind compute — it can never exceed the
    comm there is, nor drive exposed comm negative."""
    model = _gpt2_350m_spec()
    fleet = FleetSpec(chips=8)
    for cand in (
        Candidate(mesh={"data": 8}, zero_stage=1, micro_batch=2,
                  step_schedule={"weight_update": "decomposed",
                                 "fused_reduce_scatter": True}),
        Candidate(mesh={"data": 8}, zero_stage=3, micro_batch=2,
                  step_schedule={"gather_prefetch_depth": 2,
                                 "fused_gather_matmul": True}),
        Candidate(mesh={"data": 2, "seq": 4}, zero_stage=2, micro_batch=2,
                  step_schedule={"ring_interleave": 2}),
    ):
        census = analytic_census(model, cand, gas=1, fleet=fleet)
        t = step_time(model, cand, fleet, gas=1, census=census)
        assert t["overlap_credit_seconds"] <= t["comm_seconds"] + 1e-12
        assert t["exposed_comm_seconds"] >= -1e-12
        assert t["exposed_comm_seconds"] + t["overlap_credit_seconds"] \
            == pytest.approx(t["comm_seconds"])


def test_anchored_census_within_frozen_tolerance():
    """Anchor/extrapolate protocol: the analytic census of the
    train_zero1 audit target's exact shape must agree with the REAL
    lowered census within ANCHOR_TOLERANCE (docs/PLANNER.md)."""
    from deepspeed_tpu.analysis.targets import run_target_audits
    from deepspeed_tpu.models import get_model_config

    rep, _ = run_target_audits("train_zero1", memory=False)
    measured = rep.census_summary()
    cfg = get_model_config("gpt2-tiny", max_seq_len=64)
    model = ModelSpec.from_name("gpt2-tiny", seq_len=64, max_seq_len=64)
    assert model.config.hidden_size == cfg.hidden_size
    cand = Candidate(mesh={"data": 8}, zero_stage=1, micro_batch=1)
    ratios = anchor_ratios(measured, model, cand, gas=2)
    assert "all-reduce" in ratios, (measured.keys(), ratios)
    for kind, ratio in ratios.items():
        assert 1.0 / ANCHOR_TOLERANCE <= ratio <= ANCHOR_TOLERANCE, \
            (kind, ratio)
    # anchored rows are marked, un-anchored rows stay extrapolated
    census = analytic_census(model, cand, gas=2)
    anchored = apply_anchors(census, ratios)
    assert anchored["all-reduce"]["mode"] == "anchored"


def test_anchors_flow_into_plan_evidence():
    model = _gpt2_350m_spec()
    plan = compile_plan(model, FleetSpec(chips=8), stages=(1,),
                        enable_quant=False, enable_offload=False,
                        max_micro_batch=4, anchors={"all-reduce": 1.5})
    assert plan.ranked
    top = plan.ranked[0].evidence
    assert top["census_mode"] in ("anchored", "mixed")
    assert top["census"]["all-reduce"]["mode"] == "anchored"


# ---------------------------------------------------------------------
# 3a. memory model: comm-quantization error-feedback residual
# ---------------------------------------------------------------------

def test_memory_breakdown_has_comm_class():
    from deepspeed_tpu.autotuning.autotuner import (ModelInfo,
                                                    estimate_memory_breakdown)

    info = ModelInfo(num_params=100_000_000, hidden_size=1024,
                     num_layers=24, vocab_size=50257)
    base = estimate_memory_breakdown(info, zero_stage=1, dp_size=8,
                                     micro_batch=1, seq_len=1024)
    quant = estimate_memory_breakdown(info, zero_stage=1, dp_size=8,
                                      micro_batch=1, seq_len=1024,
                                      comm_quant=True)
    assert base["comm"] == 0
    # fp32 EF residual: one padded row per device ≈ 4 B/param
    assert quant["comm"] >= 4 * info.num_params
    # not eligible: stage 3 regathers, nothing replicated to feed back
    z3 = estimate_memory_breakdown(info, zero_stage=3, dp_size=8,
                                   micro_batch=1, seq_len=1024,
                                   comm_quant=True)
    assert z3["comm"] == 0


def test_comm_residual_flips_fit_verdict():
    """The regression the satellite fixes: a quantized-DP config whose
    EF residual is the difference between fitting and OOM must now be
    rejected by predict_fit."""
    from deepspeed_tpu.autotuning.autotuner import ModelInfo, predict_fit

    info = ModelInfo(num_params=400_000_000, hidden_size=1024,
                     num_layers=24, vocab_size=50257)
    kwargs = dict(zero_stage=1, dp_size=8, micro_batch=1, seq_len=1024)
    base = predict_fit(info, hbm_bytes=1 << 62, **kwargs)
    # budget: just above the un-quantized peak, well below peak + 4B/p
    budget = base["predicted_peak_bytes"] + (1 << 20)
    assert predict_fit(info, hbm_bytes=budget, **kwargs)["predicted_fit"]
    quant = predict_fit(info, hbm_bytes=budget, comm_quant=True, **kwargs)
    assert not quant["predicted_fit"]
    assert quant["dominant_class"] == "comm"


# ---------------------------------------------------------------------
# 3b. plan round-trip + seeding + CLI
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def small_plan():
    model = ModelSpec.from_name("gpt2-350m", seq_len=1024)
    return compile_plan(model, FleetSpec(chips=8), enable_quant=False,
                        max_micro_batch=8, top=5)


def test_plan_roundtrip_through_load_plan(tmp_path, small_plan):
    from deepspeed_tpu.planner import save_plan
    from deepspeed_tpu.runtime.config import (DeepSpeedConfigError,
                                              load_plan)

    path = str(tmp_path / "plan.json")
    save_plan(small_plan, path)
    cfg = load_plan(path, world_size=8)
    assert cfg.train_micro_batch_size_per_gpu == \
        small_plan.ranked[0].config["train_micro_batch_size_per_gpu"]
    # rank selection + bare-fragment mode + failure mode
    cfg2 = load_plan(small_plan.ranked[1].config, world_size=8)
    assert cfg2.zero_config.stage == \
        small_plan.ranked[1].config["zero_optimization"]["stage"]
    with pytest.raises(DeepSpeedConfigError):
        load_plan(path, world_size=8, rank=99)
    # Plan serialization round-trips losslessly
    again = Plan.from_dict(json.loads(json.dumps(small_plan.to_dict())))
    assert again.to_dict() == small_plan.to_dict()


def test_seed_candidates_feed_autotuner_space():
    from deepspeed_tpu.autotuning.autotuner import Autotuner
    from deepspeed_tpu.models import get_model_config

    cfg = get_model_config("gpt2-tiny", max_seq_len=64)
    cands = seed_candidates(cfg, seq_len=64, chips=8,
                            hbm_bytes=16 << 30, top=4)
    assert cands
    for c in cands:
        assert set(c) >= {"zero_stage", "micro_batch", "mesh",
                          "est_bytes"}
    tuner = Autotuner(cfg, {"optimizer": {"type": "AdamW",
                                          "params": {"lr": 1e-4}}},
                      seq_len=64, mode="planner", max_trials=4,
                      n_devices=8)
    space = tuner._space()
    assert 0 < len(space) <= 4
    # the trial config applies the candidate's override blocks
    trial = tuner._trial_config(space[0])
    assert trial["zero_optimization"]["stage"] == space[0]["zero_stage"]


def test_cli_writes_valid_plan_json(tmp_path, capsys):
    from deepspeed_tpu.planner.cli import main

    out = str(tmp_path / "plan.json")
    rc = main(["--model", "gpt2-350m", "--chips", "8", "--top", "3",
               "--no-quant", "--max-micro-batch", "4",
               "--calibration", "none", "--json", out])
    assert rc == 0
    text = capsys.readouterr().out
    assert "tok/s/chip" in text
    data = json.load(open(out))
    assert data["ranked"]
    from deepspeed_tpu.runtime.config import load_plan
    load_plan(out, world_size=8)


def test_cli_no_fit_exits_nonzero(tmp_path):
    from deepspeed_tpu.planner.cli import main

    # 6.7B on one 16GiB chip with no host and no NVMe: nothing fits
    rc = main(["--model", "gpt2-6.7b", "--chips", "1", "--seq", "512",
               "--no-offload", "--calibration", "none"])
    assert rc == 1


# ---------------------------------------------------------------------
# 3c. bench plumbing: resolved_config blobs + plan_validate row
# ---------------------------------------------------------------------

def test_bench_resolved_config_blob_shape():
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    import bench

    blob = bench._resolved_config({
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 2,
        "mesh": {"data": 8},
        "zero_optimization": {
            "stage": 3,
            "offload_optimizer": {"device": "nvme", "nvme_path": "/x",
                                  "working_set_bytes": 1 << 30,
                                  "chunk_bytes": 64 << 20}},
        "comm_quantization": {"enabled": True, "grad_reduce": "int8"},
    })
    assert blob["mesh"] == {"data": 8}
    assert blob["zero_optimization"]["stage"] == 3
    # offload block keeps the planner-relevant keys, drops paths
    oo = blob["zero_optimization"]["offload_optimizer"]
    assert oo == {"device": "nvme", "working_set_bytes": 1 << 30,
                  "chunk_bytes": 64 << 20}
    assert json.loads(json.dumps(blob)) == blob
    # the blob is fragment-shaped: plan_rank_of consumes it directly
    from deepspeed_tpu.planner.rank import _frag_key
    assert _frag_key(blob, 8)[3] == "nvme_chunked"


def test_bench_registers_plan_validate_row():
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    import bench

    assert "plan_validate" in bench._ROWS
    assert set(bench.PINNED_ROW_CONFIGS) >= set(PLAN_AUDIT_ROWS)
