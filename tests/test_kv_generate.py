"""KV-cached generation shared by v1 InferenceEngine and the hybrid engine.

Ref: deepspeed/runtime/hybrid_engine.py:30 (the reference re-wires ZeRO-3
weights into kernel-injected inference containers so RLHF rollouts are
KV-cached) and inference/engine.py:40 (v1 generate).  Asserts (a) token
parity with InferenceEngineV2's paged greedy path, and (b) per-emitted-token
compiled cost is O(S) — one paged decode step — not the O(S²) full
recompute of a naive loop.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import get_model_config
from deepspeed_tpu.models import transformer as tf_model


def _reset_topo():
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None


def _flops(compiled) -> float:
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    return float(ca["flops"])


@pytest.mark.parametrize("name", ["llama-tiny", "gpt2-tiny"])
def test_v1_generate_matches_v2_greedy(name):
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2

    model = get_model_config(name)
    eng1 = InferenceEngine(model, dtype="float32", seed=0)
    _reset_topo()
    v2 = InferenceEngineV2(model, {"dtype": "float32"},
                           model_params=eng1.params)
    rng = np.random.default_rng(7)
    prompts = rng.integers(1, model.vocab_size, size=(2, 6), dtype=np.int32)
    out1 = eng1.generate(prompts, max_new_tokens=8)
    assert out1.shape == (2, 14)
    out2 = v2.generate([list(map(int, p)) for p in prompts],
                       max_new_tokens=8)
    assert out1[:, 6:].tolist() == [list(map(int, o)) for o in out2]
    _reset_topo()


def test_hybrid_generate_matches_v2_greedy_on_live_weights():
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedHybridEngine

    model = get_model_config("gpt2-tiny")
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
           "mesh": {"data": 1}}
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    he = DeepSpeedHybridEngine(engine)
    rng = np.random.default_rng(3)
    ids = rng.integers(0, model.vocab_size, size=(2, 9), dtype=np.int32)
    he.train_batch({"input_ids": ids[:, :-1], "labels": ids[:, 1:]})

    he.eval()
    prompts = rng.integers(1, model.vocab_size, size=(2, 5), dtype=np.int32)
    out = he.generate(prompts, max_new_tokens=6)
    assert out.shape == (2, 11)
    _reset_topo()
    # v2 over the SAME live training arrays must agree token-for-token
    # (training params are fp32 by default here, matching dtype float32)
    v2 = InferenceEngineV2(model, {"dtype": "float32"},
                           model_params=engine.params)
    out2 = v2.generate([list(map(int, p)) for p in prompts],
                       max_new_tokens=6)
    assert out[:, 5:].tolist() == [list(map(int, o)) for o in out2]
    stats = he.stats()
    assert stats["generated_tokens"] == 12
    _reset_topo()


def test_decode_step_cost_is_o_s_not_o_s2():
    """The naive loop pays a full forward (O(S·model)) per emitted token;
    the paged decode step must cost a small fraction of that — i.e. the
    rollout is O(S) per token (ref VERDICT r3 Missing #2 done-criterion)."""
    from deepspeed_tpu.inference.kv_generate import KVCachedGenerator

    s = 1024
    cfg = get_model_config("gpt2-tiny", max_seq_len=2048, dtype=jnp.float32)
    params = jax.jit(lambda k: tf_model.init_params(cfg, k))(
        jax.random.PRNGKey(0))

    full = jax.jit(lambda p, i: tf_model.forward(p, i, cfg))
    ids = np.zeros((1, s), np.int32)
    full_flops = _flops(full.lower(params, ids).compile())

    gen = KVCachedGenerator(cfg, block_size=64)
    nb = -(-(s + 4) // 64)
    cache = jnp.zeros((cfg.num_layers, cfg.kv_heads, nb * 64,
                       cfg.dim_per_head), cfg.dtype)
    tables = jnp.arange(nb, dtype=jnp.int32)[None, :]
    lowered = gen._decode.lower(
        params, cache, cache, jnp.zeros((1,), jnp.int32),
        jnp.full((1,), s, jnp.int32), jnp.ones((1,), bool), tables,
        jax.random.PRNGKey(0), jnp.float32(1.0), n_steps=1, greedy=True)
    step_flops = _flops(lowered.compile())
    # one decode step at context S must be far below one full forward at S
    assert step_flops * 5 < full_flops, (step_flops, full_flops)


def test_top_k_top_p_sampling():
    """FastGen-style logit processing (ref v2 samplers): top-k restricts
    every sampled token to the k most likely; top-p to the smallest
    nucleus reaching the mass; both on device in prefill AND decode."""
    from deepspeed_tpu.inference.v2.model import sample_tokens

    rng = np.random.default_rng(11)
    logits = jnp.asarray(rng.standard_normal((64, 128)) * 3, jnp.float32)
    key = jax.random.PRNGKey(0)
    # top-k: every sample must be among each row's top-5 logits
    toks = sample_tokens(logits, key, jnp.float32(1.0), greedy=False,
                         top_k=5)
    top5 = np.asarray(jax.lax.top_k(logits, 5)[1])
    assert all(int(t) in top5[i] for i, t in enumerate(np.asarray(toks)))
    # top-p=tiny: collapses to argmax
    toks_p = sample_tokens(logits, key, jnp.float32(1.0), greedy=False,
                           top_p=1e-6)
    np.testing.assert_array_equal(np.asarray(toks_p),
                                  np.asarray(jnp.argmax(logits, -1)))
    # engine-level: v1 generate with top_k=1 must equal greedy
    from deepspeed_tpu.inference.engine import InferenceEngine

    model = get_model_config("gpt2-tiny")
    eng = InferenceEngine(model, dtype="float32", seed=0)
    prompts = rng.integers(1, model.vocab_size, size=(2, 5), dtype=np.int32)
    g = eng.generate(prompts, max_new_tokens=6)
    k1 = eng.generate(prompts, max_new_tokens=6, temperature=0.7, top_k=1)
    np.testing.assert_array_equal(g, k1)
    _reset_topo()
