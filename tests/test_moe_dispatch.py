"""MoE dispatch formulations: sorted-vs-einsum parity, FCFS capacity drop
order, and the explicit expert-parallel shard_map + all_to_all path.
Ref test model: tests/unit/moe in the reference suite."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.moe import sharded_moe as sm


class Cfg:
    def __init__(self, top_k=2, capacity_factor=1.25, moe_dispatch="auto"):
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.moe_dispatch = moe_dispatch


def _params(key, e, h, f, dtype=jnp.float32, swiglu=True):
    ks = jax.random.split(key, 4)
    p = {
        "router": jax.random.normal(ks[0], (h, e), dtype) * 0.2,
        "wi": jax.random.normal(ks[1], (e, h, f), dtype) * 0.1,
        "wo": jax.random.normal(ks[2], (e, f, h), dtype) * 0.1,
    }
    if swiglu:
        p["wg"] = jax.random.normal(ks[3], (e, h, f), dtype) * 0.1
    return p


@pytest.mark.parametrize("k,cf", [(1, 1.5), (2, 1.25), (2, 0.5), (4, 1.0)])
def test_sorted_matches_einsum(k, cf):
    """Both dispatch formulations produce identical outputs — including
    when capacity drops tokens (cf=0.5 forces heavy overflow)."""
    key = jax.random.PRNGKey(0)
    b, s, h, f, e = 2, 16, 32, 64, 4
    x = jax.random.normal(jax.random.PRNGKey(1), (b, s, h), jnp.float32)
    p = _params(key, e, h, f)
    out_e, aux_e = sm.moe_forward(x, p, Cfg(k, cf, "einsum"))
    out_s, aux_s = sm.moe_forward(x, p, Cfg(k, cf, "sorted"))
    np.testing.assert_allclose(np.asarray(out_e), np.asarray(out_s),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux_e), float(aux_s), rtol=1e-5)


def test_sorted_grads_match_einsum():
    key = jax.random.PRNGKey(2)
    b, s, h, f, e = 2, 8, 16, 32, 4
    x = jax.random.normal(jax.random.PRNGKey(3), (b, s, h), jnp.float32)
    p = _params(key, e, h, f)

    def loss(p, mode):
        out, aux = sm.moe_forward(x, p, Cfg(2, 1.25, mode))
        return jnp.sum(out ** 2) + 0.01 * aux

    g_e = jax.grad(loss)(p, "einsum")
    g_s = jax.grad(loss)(p, "sorted")
    for kk in g_e:
        np.testing.assert_allclose(np.asarray(g_e[kk]), np.asarray(g_s[kk]),
                                   rtol=1e-4, atol=1e-5)


def test_capacity_overflow_drop_order():
    """When an expert overflows, the sorted path drops the same entries as
    the iterative einsum path: later tokens first, and a token's 2nd
    choice never displaces another token's 1st choice."""
    t, e, k = 8, 2, 2
    # every token's first choice is expert 0 → capacity c = 1.25*2*8/2 = 10
    # with cf small enough to overflow: choose cf so c = 4
    logits = jnp.stack([jnp.linspace(5.0, 6.0, t),
                        jnp.linspace(1.0, 0.0, t)], axis=1)
    cf = 0.5  # c = 0.5 * 2 * 8 / 2 = 4
    l_e, combine, dispatch = sm.top_k_gating(logits, k, cf)
    l_s, slot, gate, c = sm.top_k_gating_sorted(logits, k, cf)
    assert c == 4
    # einsum path: dispatch [T, E, C] — first 4 tokens hold expert 0
    kept_e = np.asarray(dispatch.sum(axis=(1, 2)))
    # sorted path: slot < e*c means kept; reshape to [k, T]
    slot_kt = np.asarray(slot).reshape(k, t)
    kept_s = (slot_kt < e * c).sum(axis=0)
    np.testing.assert_array_equal(kept_e, kept_s)
    # expert 0 (everyone's 1st choice) keeps tokens 0..3 exactly
    assert np.array_equal(slot_kt[0] < c, np.arange(t) < 4)
    np.testing.assert_allclose(float(l_e), float(l_s), rtol=1e-6)


def test_auto_threshold_selects_sorted(monkeypatch):
    calls = {}
    orig = sm._dispatch_combine_sorted

    def spy(*a, **kw):
        calls["sorted"] = True
        return orig(*a, **kw)

    monkeypatch.setitem(sm._DISPATCHERS, "sorted", spy)
    monkeypatch.setattr(sm, "_SORT_DISPATCH_THRESHOLD", 1)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 16), jnp.float32)
    p = _params(jax.random.PRNGKey(1), 4, 16, 32)
    sm.moe_forward(x, p, Cfg(2, 1.25, "auto"))
    assert calls.get("sorted")


@pytest.mark.parametrize("mode", ["einsum", "sorted"])
def test_ep_path_matches_single_group(mode):
    """moe_forward_ep over a {data:2, expert:2, tensor:2} mesh must agree
    with the single-group formulation on the same global batch, when no
    tokens are dropped (per-shard capacity partitions the global one;
    drop *order* differs only across shard boundaries)."""
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology

    topo = MeshTopology({"data": 2, "expert": 2, "tensor": 2})
    set_topology(topo)
    try:
        b, s, h, f, e = 4, 8, 32, 64, 4
        cfg = Cfg(2, 8.0, mode)  # generous capacity: nothing dropped
        x = jax.random.normal(jax.random.PRNGKey(7), (b, s, h), jnp.float32)
        p = _params(jax.random.PRNGKey(8), e, h, f)
        out_ref, aux_ref = sm.moe_forward(x, p, cfg)
        out_ep, aux_ep = jax.jit(
            lambda x, p: sm.moe_forward_ep(x, p, cfg, topo))(x, p)
        np.testing.assert_allclose(np.asarray(out_ref), np.asarray(out_ep),
                                   rtol=2e-5, atol=2e-5)
        # aux: per-shard mean of local stats vs global stats — equal when
        # shards see identical token counts and the router is shared
        assert np.isfinite(float(aux_ep))
        # noisy gating through the EP shard_map (per-shard fold_in key):
        # compiles, deterministic per key, finite
        cfg.moe_noisy_gate_policy = "RSample"
        nk = jax.random.PRNGKey(11)
        n1, _ = jax.jit(lambda x, p: sm.moe_forward_ep(
            x, p, cfg, topo, noise_key=nk))(x, p)
        n2, _ = jax.jit(lambda x, p: sm.moe_forward_ep(
            x, p, cfg, topo, noise_key=nk))(x, p)
        np.testing.assert_array_equal(np.asarray(n1), np.asarray(n2))
        assert np.isfinite(np.asarray(n1)).all()
        cfg.moe_noisy_gate_policy = None
    finally:
        set_topology(None)


@pytest.mark.parametrize("n_layers", [4, 3])
def test_full_model_train_grad_moe_freq2_ep(n_layers):
    """Regression: jax.grad through the full model with moe_layer_freq=2 on
    an expert mesh used to abort XLA compilation (shard_map collective under
    the scan's lax.cond, and a bf16 all-reduce from the replicated router's
    backward).  The grouped scan makes MoE placement static — including the
    unrolled tail when num_layers is not a multiple of the frequency — so
    the EP path must compile and produce finite grads."""
    from deepspeed_tpu.models import transformer as tr
    from deepspeed_tpu.models.registry import TransformerConfig
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology

    topo = MeshTopology({"data": 4, "expert": 2})
    set_topology(topo)
    try:
        cfg = TransformerConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            num_layers=n_layers, num_heads=2, num_kv_heads=2, max_seq_len=32,
            arch="llama", norm="rmsnorm", activation="swiglu", use_rope=True,
            tie_embeddings=False, num_experts=4, top_k=2, moe_layer_freq=2)
        from deepspeed_tpu.models import init_params

        params = init_params(cfg, jax.random.PRNGKey(0))
        ids = jnp.asarray(
            np.random.default_rng(0).integers(0, 128, size=(8, 16)), jnp.int32)

        def loss(params):
            out = tr.forward(params, ids, cfg)
            logits, aux = out if isinstance(out, tuple) else (out, 0.0)
            return jnp.mean(logits.astype(jnp.float32) ** 2) + 0.01 * aux

        g = jax.jit(jax.grad(loss))(params)
        leaves = jax.tree_util.tree_leaves(g)
        assert all(np.all(np.isfinite(np.asarray(l))) for l in leaves)
    finally:
        set_topology(None)


def test_ep_path_grads_finite():
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology

    topo = MeshTopology({"data": 2, "expert": 2})
    set_topology(topo)
    try:
        b, s, h, f, e = 4, 4, 16, 32, 4
        cfg = Cfg(2, 2.0, "sorted")
        x = jax.random.normal(jax.random.PRNGKey(9), (b, s, h), jnp.float32)
        p = _params(jax.random.PRNGKey(10), e, h, f)

        def loss(p, x):
            out, aux = sm.moe_forward_ep(x, p, cfg, topo)
            return jnp.sum(out ** 2) + 0.01 * aux

        g = jax.jit(jax.grad(loss))(p, x)
        for kk, v in g.items():
            assert np.all(np.isfinite(np.asarray(v))), kk
            assert float(jnp.abs(v).sum()) > 0, kk
    finally:
        set_topology(None)


@pytest.mark.parametrize("dispatch", ["einsum", "sorted"])
def test_noisy_gate_policies(dispatch):
    """Reference noisy_gate_policy (sharded_moe.py:193-202): RSample
    perturbs expert CHOICE only (gates from clean probs), Jitter perturbs
    the router input; both require a threaded key and are exact no-ops
    without one (eval determinism).  Covers both dispatch formulations'
    select_logits branches."""
    from deepspeed_tpu.moe.sharded_moe import moe_forward

    class NCfg(Cfg):
        def __init__(self, policy, **kw):
            super().__init__(**kw)
            self.moe_noisy_gate_policy = policy

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 8), jnp.float32)
    p = _params(jax.random.PRNGKey(2), e=4, h=8, f=16)

    base, _ = moe_forward(x, p, NCfg(None, moe_dispatch=dispatch))
    for policy in ("RSample", "Jitter"):
        cfg = NCfg(policy, moe_dispatch=dispatch)
        # no key → identical to the clean path even with the policy set
        off, _ = moe_forward(x, p, cfg)
        np.testing.assert_array_equal(np.asarray(off), np.asarray(base))
        # keyed → deterministic per key, different across keys, finite
        n1, _ = moe_forward(x, p, cfg, noise_key=key)
        n1b, _ = moe_forward(x, p, cfg, noise_key=key)
        n2, _ = moe_forward(x, p, cfg, noise_key=jax.random.PRNGKey(9))
        np.testing.assert_array_equal(np.asarray(n1), np.asarray(n1b))
        assert np.isfinite(np.asarray(n1)).all()
        assert not np.array_equal(np.asarray(n1), np.asarray(n2))

    with pytest.raises(ValueError, match="noisy_gate_policy"):
        moe_forward(x, p, NCfg("bogus"), noise_key=key)


def test_rsample_einsum_sorted_agree():
    """Both dispatch formulations make the SAME noisy choices from the
    same select logits (shared gumbel key) and combine identically."""
    from deepspeed_tpu.moe.sharded_moe import moe_forward

    class NCfg(Cfg):
        def __init__(self, policy, **kw):
            super().__init__(**kw)
            self.moe_noisy_gate_policy = policy

    key = jax.random.PRNGKey(6)
    x = jax.random.normal(jax.random.PRNGKey(7), (2, 16, 8), jnp.float32)
    p = _params(jax.random.PRNGKey(8), e=4, h=8, f=16)
    a, _ = moe_forward(x, p, NCfg("RSample", moe_dispatch="einsum"),
                       noise_key=key)
    b, _ = moe_forward(x, p, NCfg("RSample", moe_dispatch="sorted"),
                       noise_key=key)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-6)


def test_rsample_keeps_clean_gate_values():
    """RSample changes which experts are chosen, never the probability
    mass used as combine weights: every nonzero combine weight must equal
    the clean softmax prob of that (token, expert) pair."""
    from deepspeed_tpu.moe.sharded_moe import top_k_gating

    logits = jax.random.normal(jax.random.PRNGKey(3), (32, 8), jnp.float32)
    noisy = logits + jax.random.gumbel(jax.random.PRNGKey(4), logits.shape)
    _, combine, dispatch = top_k_gating(logits, k=1, capacity_factor=4.0,
                                        select_logits=noisy)
    probs = np.asarray(jax.nn.softmax(logits, axis=-1))
    comb = np.asarray(combine).sum(axis=2)  # [T, E]
    nz = comb > 0
    t_idx, e_idx = np.nonzero(nz)
    np.testing.assert_allclose(comb[nz], probs[t_idx, e_idx], rtol=1e-5)
    # and the choices really differ from the clean argmax somewhere
    clean_choice = probs.argmax(-1)
    noisy_choice = np.asarray(noisy).argmax(-1)
    assert (clean_choice != noisy_choice).any()


def _residual_params(key, e, h, f):
    ks = jax.random.split(key, 5)
    p = _params(ks[0], e, h, f)
    p["residual"] = {
        "wi": jax.random.normal(ks[1], (h, f), jnp.float32) * 0.1,
        "wg": jax.random.normal(ks[2], (h, f), jnp.float32) * 0.1,
        "wo": jax.random.normal(ks[3], (f, h), jnp.float32) * 0.1,
    }
    p["coef_w"] = jax.random.normal(ks[4], (h, 2), jnp.float32) * 0.2
    p["coef_b"] = jnp.zeros((2,), jnp.float32)
    return p


def test_residual_moe_semantics():
    """PR-MoE (ref moe/layer.py:124-135): output = routed·c0 + mlp·c1 with
    c = softmax(x @ coef) — verified against a hand computation from the
    plain (non-residual) routed output."""
    b, s, h, f, e = 2, 8, 32, 64, 4
    x = jax.random.normal(jax.random.PRNGKey(0), (b, s, h), jnp.float32)
    p = _residual_params(jax.random.PRNGKey(1), e, h, f)
    routed, aux0 = sm.moe_forward(
        x, {k: v for k, v in p.items()
            if k not in ("residual", "coef_w", "coef_b")}, Cfg(2, 4.0))
    out, aux = sm.moe_forward(x, p, Cfg(2, 4.0))
    tok = x.reshape(-1, h)
    rp = p["residual"]
    mlp = (jax.nn.silu(tok @ rp["wg"]) * (tok @ rp["wi"])) @ rp["wo"]
    coef = jax.nn.softmax(tok @ p["coef_w"] + p["coef_b"], axis=-1)
    want = (routed.reshape(-1, h) * coef[:, 0:1]
            + mlp * coef[:, 1:2]).reshape(b, s, h)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux), float(aux0), rtol=1e-6)


def test_residual_moe_ep_matches_single_group():
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology

    topo = MeshTopology({"data": 2, "expert": 2})
    set_topology(topo)
    try:
        b, s, h, f, e = 4, 8, 32, 64, 4
        cfg = Cfg(2, 8.0)
        x = jax.random.normal(jax.random.PRNGKey(2), (b, s, h), jnp.float32)
        p = _residual_params(jax.random.PRNGKey(3), e, h, f)
        out_ref, _ = sm.moe_forward(x, p, cfg)
        out_ep, _ = jax.jit(
            lambda x, p: sm.moe_forward_ep(x, p, cfg, topo))(x, p)
        np.testing.assert_allclose(np.asarray(out_ref), np.asarray(out_ep),
                                   rtol=2e-5, atol=2e-5)
    finally:
        set_topology(None)


def test_residual_moe_full_model_trains():
    """moe_use_residual through the engine: params carry the residual
    branch + coefficient head, and the model trains on the expert mesh."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.parallel import topology

    model = get_model_config("mixtral-tiny", moe_use_residual=True)
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "mesh": {"data": 4, "expert": 2},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config, seed=5)
    layer_moe = engine.params["layers"]["moe"]
    assert "residual" in layer_moe and "coef_w" in layer_moe
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(16, 33), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    losses = [float(np.asarray(engine.train_batch(batch))) for _ in range(4)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0], losses
    topology._GLOBAL_TOPOLOGY = None
