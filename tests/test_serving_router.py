"""Multi-replica serving tier: router, replicas, paged prefix cache.

Correctness oracle, same as test_serving: everything the routed path
produces under greedy sampling must be BIT-IDENTICAL to a single
engine's one-shot ``generate()`` with the same weights — across replica
choice, fail-over re-dispatch, prefix-cache adoption, and
preemption-then-re-adoption.  The shared-page safety tests pin the
refcount invariant: no page is ever freed (or handed to a new owner)
while another live sequence still reads it.
"""

import threading
import time

import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import build_engine
from deepspeed_tpu.inference.v2.ragged import BlockedAllocator
from deepspeed_tpu.models import get_model_config
from deepspeed_tpu.serving import (InferenceServer, PrefixCache,
                                   PrefixCacheConfig, ReplicaSet, Router,
                                   SamplingParams)

ENG_CFG = {"dtype": "float32",
           "memory_config": {"num_blocks": 64, "block_size": 4},
           "max_context": 64}


def _model():
    return get_model_config("llama-tiny", num_layers=1)


def _prompts(model, sizes, seed=0, shared=0):
    rng = np.random.default_rng(seed)
    head = rng.integers(1, model.vocab_size, size=shared).tolist()
    return [head + rng.integers(1, model.vocab_size,
                                size=n - shared).tolist()
            for n in sizes]


# ---------------------------------------------------------------------------
# allocator refcounts (the invariant everything above rests on)
# ---------------------------------------------------------------------------

def test_allocator_refcount_shared_pages():
    al = BlockedAllocator(8)
    blocks = al.allocate(3)
    assert all(al.refcount(b) == 1 for b in blocks)
    al.acquire(blocks[:2])                 # a second owner (prefix cache)
    al.free(blocks)                        # first owner releases all 3
    # shared pages survive at refcount 1; the unshared one is free again
    assert al.refcount(blocks[0]) == 1 and al.refcount(blocks[1]) == 1
    assert al.refcount(blocks[2]) == 0
    assert al.free_blocks == 5
    # a freed page cannot be re-released or re-acquired
    with pytest.raises(ValueError):
        al.free([blocks[2]])
    with pytest.raises(ValueError):
        al.acquire([blocks[2]])
    al.free(blocks[:2])                    # last owner: back on free list
    assert al.free_blocks == 7
    with pytest.raises(ValueError):        # double free still rejected
        al.free([blocks[0]])


def test_prefix_cache_adopt_insert_evict_refcounts():
    """Eviction can never free a page a live sequence shares (rc >= 2);
    LRU evicts leaves first and exposed parents after."""
    al = BlockedAllocator(16)
    pc = PrefixCache(PrefixCacheConfig(enabled=True), al, block_size=4)
    donor = al.allocate(3)                 # 8 prompt tokens + 1 decode page
    tokens = list(range(100, 112))         # 12 tokens -> 3 blocks, 2 full+1
    assert pc.insert(tokens[:9], donor) == 2   # 9 prefilled -> 2 full blocks
    al.free(donor)                         # donor flushes
    assert al.refcount(donor[0]) == 1 and al.refcount(donor[1]) == 1
    assert al.refcount(donor[2]) == 0      # partial block was never cached

    adopted, n = pc.adopt(tokens)          # new request, same prefix
    assert adopted == donor[:2] and n == 8
    assert al.refcount(donor[0]) == 2
    # eviction under pressure must skip shared pages entirely
    assert pc.evict(10) == 0
    assert al.refcount(donor[0]) == 2 and al.refcount(donor[1]) == 2
    pc.release(adopted)                    # adopter flushed
    # now reclaimable: leaf (block 1) goes first, exposing block 0
    assert pc.evict(1) == 1
    assert al.refcount(donor[1]) == 0 and al.refcount(donor[0]) == 1
    assert pc.evict(1) == 1
    assert al.free_blocks == 15
    assert pc.cached_blocks == 0


def test_prefix_cache_adoption_reserves_one_prefill_token():
    """A prompt fully covered by the cache still prefills >= 1 token
    (the sampling step needs a real row)."""
    al = BlockedAllocator(16)
    pc = PrefixCache(PrefixCacheConfig(enabled=True), al, block_size=4)
    blocks = al.allocate(2)
    tokens = list(range(8))
    pc.insert(tokens, blocks)              # both blocks cached
    adopted, n = pc.adopt(tokens)          # SAME 8 tokens: cap at 1 block
    assert n == 4 and len(adopted) == 1
    pc.release(adopted)


# ---------------------------------------------------------------------------
# single-server prefix cache behavior
# ---------------------------------------------------------------------------

def test_warm_request_skips_shared_prefill_bit_identical():
    """Acceptance: a warm shared-system-prompt request skips >= the
    shared blocks of prefill (prefill_tokens_saved) and greedy output
    stays bit-identical to the cold path."""
    model = _model()
    shared = 16                            # 4 full blocks at bs=4
    prompts = _prompts(model, [22, 23], seed=5, shared=shared)
    ref_eng = build_engine(model, dict(ENG_CFG), seed=0)
    ref = ref_eng.generate(prompts, max_new_tokens=6)

    eng = build_engine(model, dict(ENG_CFG), seed=0)
    srv = InferenceServer(eng, {"prefix_cache": {"enabled": True}}).start()
    try:
        cold = srv.submit(prompts[0], SamplingParams(max_new_tokens=6))
        assert cold.result(timeout=120) == ref[0]
        warm = srv.submit(prompts[1], SamplingParams(max_new_tokens=6))
        assert warm.result(timeout=120) == ref[1]
        snap = srv.metrics.snapshot()
        assert snap["prefix_hits"] == 1 and snap["prefix_misses"] == 1
        assert snap["prefill_tokens_saved"] >= shared
    finally:
        srv.stop()
    # stop() clears the cache: the pool returns whole to the engine
    assert eng.free_blocks == eng.cfg.num_blocks - 1


def test_preempted_victim_readopts_prefix_bit_identical():
    """Satellite: recompute-preempted victims re-adopt their cached
    prefix on re-admission (prefix_hits exceed the admission count) and
    outputs stay bit-identical through preemption + re-adoption."""
    n_req, new, shared = 8, 12, 8
    model = _model()
    cfg = {"dtype": "float32",
           "state_manager": {"max_tracked_sequences": 8,
                             "max_ragged_batch_size": 32},
           "memory_config": {"num_blocks": 28, "block_size": 4},
           "max_context": 32}
    prompts = _prompts(model, [12] * n_req, seed=7, shared=shared)
    ref_eng = build_engine(model, dict(cfg), seed=0)
    ref = ref_eng.generate(prompts, max_new_tokens=new)

    eng = build_engine(model, dict(cfg), seed=0)
    srv = InferenceServer(eng, {"prefix_cache": {"enabled": True}}).start()
    try:
        streams = [srv.submit(p, SamplingParams(max_new_tokens=new))
                   for p in prompts]
        outs = [s.result(timeout=300) for s in streams]
        snap = srv.metrics.snapshot()
    finally:
        srv.stop()
    assert outs == ref                     # bit-identical through it all
    assert snap["preemptions"] >= 1        # the tight pool really preempted
    # every re-admission of a preempted victim re-adopts its prefix, so
    # hits exceed what first admissions alone could produce
    assert snap["prefix_hits"] > 0
    assert (snap["prefix_hits"] + snap["prefix_misses"]
            == snap["admitted"] + snap["preemptions"])
    assert eng.free_blocks == eng.cfg.num_blocks - 1


def test_eviction_under_admission_pressure_frees_cache_first():
    """When the watermark blocks admission, idle cache pages are evicted
    before anyone waits — and the engine keeps its page-safety (the
    refcounting allocator raises on any double-free, so a clean run IS
    the invariant check)."""
    model = _model()
    cfg = {"dtype": "float32",
           "state_manager": {"max_tracked_sequences": 4,
                             "max_ragged_batch_size": 32},
           "memory_config": {"num_blocks": 20, "block_size": 4},
           "max_context": 64}
    eng = build_engine(model, dict(cfg), seed=0)
    # kv_high_watermark 0.5: a 19-block pool must keep 9 free at
    # admission, so the 12-block request below cannot admit until the
    # cache's idle pages are reclaimed
    srv = InferenceServer(eng, {
        "prefix_cache": {"enabled": True},
        "admission": {"kv_high_watermark": 0.5}}).start()
    try:
        # fill the cache: a long prompt whose pages go idle after finish
        a = _prompts(model, [16], seed=1)[0]
        srv.submit(a, SamplingParams(max_new_tokens=2)).result(timeout=120)
        time.sleep(0.05)                   # let gauges settle
        cached = srv.metrics.snapshot()["prefix_cached_blocks"]
        assert cached >= 4                 # 16 tokens = 4 full blocks held
        # now a big unrelated request that needs those pages back
        b = _prompts(model, [40], seed=2)[0]
        out = srv.submit(b, SamplingParams(max_new_tokens=8))
        res = out.result(timeout=120)
        assert len(res) == 8
    finally:
        srv.stop()
    assert eng.free_blocks == eng.cfg.num_blocks - 1


# ---------------------------------------------------------------------------
# router + replicas
# ---------------------------------------------------------------------------

def test_router_e2e_failover_streamed_sticky():
    """Acceptance: router over 2 replicas, concurrent streamed requests
    land sticky (each pumped from one replica), one replica killed
    mid-run -> its in-flight requests fail over and FINISH, outputs
    bit-identical to one-shot generate()."""
    model = _model()
    n_req, new = 4, 24
    prompts = _prompts(model, [8] * n_req, seed=3)
    ref_eng = build_engine(model, dict(ENG_CFG), seed=0)
    ref = ref_eng.generate(prompts, max_new_tokens=new)

    rs = ReplicaSet.build(model, 2, ENG_CFG, seed=0)
    router = Router(rs).start()
    outs = {}

    def consume(i, stream):
        outs[i] = [tok for tok in stream]  # incremental iterator

    streams = [router.submit(p, SamplingParams(max_new_tokens=new))
               for p in prompts]
    threads = [threading.Thread(target=consume, args=(i, s))
               for i, s in enumerate(streams)]
    for t in threads:
        t.start()
    # wait until BOTH replicas hold active work AND every stream has
    # tokens flowing (so the kill is demonstrably mid-stream), then
    # kill r0
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if (all(len(r.server._active) > 0 for r in rs)
                and all(len(s.tokens) >= 2 for s in streams)):
            break
        time.sleep(0.01)
    assert all(len(r.server._active) > 0 for r in rs), \
        "both replicas should be serving before the kill"
    assert all(len(s.tokens) >= 2 for s in streams), \
        "every request should be streaming before the kill"
    rs[0].kill()
    for t in threads:
        t.join(timeout=300)
    assert not any(t.is_alive() for t in threads)
    snap = router.snapshot()
    router.stop()

    assert [outs[i] for i in range(n_req)] == ref   # bit-identical
    # sticky dispatch spread the streams over BOTH replicas, and the
    # pre-kill wait proved every one of them was mid-stream
    assert snap["routed"]["r0"] > 0 and snap["routed"]["r1"] > 0
    assert snap["failovers"] >= 1                   # r0's work moved
    assert snap["replicas_alive"] == 1


def test_router_sticky_sessions_warm_prefix():
    """Session affinity pins requests to one replica, so its local
    prefix cache serves the session's shared prompt."""
    model = _model()
    shared = 16
    prompts = _prompts(model, [22, 23, 24], seed=9, shared=shared)
    rs = ReplicaSet.build(model, 2, ENG_CFG,
                          {"prefix_cache": {"enabled": True}}, seed=0)
    router = Router(rs).start()
    try:
        before = [router.metrics.routed(i) for i in range(2)]
        for p in prompts:
            router.submit(p, SamplingParams(max_new_tokens=4),
                          session="user-1").result(timeout=120)
        delta = [router.metrics.routed(i) - before[i] for i in range(2)]
        assert sorted(delta) == [0, 3]     # all three on ONE replica
        agg = router.snapshot()["aggregate"]
        assert agg["prefix_hits"] >= 2     # warm after the first
        assert agg["prefill_tokens_saved"] >= 2 * shared
    finally:
        router.stop()


def test_router_spreads_load_and_aggregates():
    model = _model()
    prompts = _prompts(model, [8] * 6, seed=4)
    ref_eng = build_engine(model, dict(ENG_CFG), seed=0)
    ref = ref_eng.generate(prompts, max_new_tokens=6)
    rs = ReplicaSet.build(model, 2, ENG_CFG, seed=0)
    router = Router(rs).start()
    try:
        outs = router.generate(prompts, max_new_tokens=6)
        snap = router.snapshot()
    finally:
        router.stop()
    assert outs == ref
    assert snap["routed"]["r0"] > 0 and snap["routed"]["r1"] > 0
    assert snap["aggregate"]["tokens_out"] == 6 * 6
    assert snap["failovers"] == 0


def test_router_cancel_reaches_current_replica():
    model = _model()
    rs = ReplicaSet.build(model, 2, ENG_CFG, seed=0)
    router = Router(rs).start()
    try:
        p = _prompts(model, [8], seed=6)[0]
        stream = router.submit(p, SamplingParams(max_new_tokens=40))
        it = iter(stream)
        next(it)                           # first token proves it's live
        stream.cancel()
        from deepspeed_tpu.serving import RequestCancelled
        with pytest.raises(RequestCancelled):
            stream.result(timeout=120)
    finally:
        router.stop()


def test_serving_config_block():
    from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                              DeepSpeedConfigError)

    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "serving": {"n_replicas": 2,
                    "router": {"queue_weight": 0.1, "max_failovers": 3},
                    "prefix_cache": {"enabled": True, "max_blocks": 128}},
    })
    assert cfg.serving.n_replicas == 2
    assert cfg.serving.router.max_failovers == 3
    assert cfg.serving.prefix_cache.enabled
    # the round-trip dicts feed the serving classes directly
    assert cfg.serving.server_config()["prefix_cache"]["max_blocks"] == 128
    assert cfg.serving.router_config()["queue_weight"] == 0.1
    for bad in ({"n_replicas": 0},
                {"router": {"queue_weight": -1}},
                {"prefix_cache": {"min_prefix_blocks": 0}}):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                             "serving": bad})


def test_router_invalid_request_rejected_cleanly():
    """Per-request validation errors from the replica server (plain
    ValueError) propagate through Router.submit AND close the books:
    rejected counter matches, no pump/inflight leak."""
    model = _model()
    rs = ReplicaSet.build(model, 2, ENG_CFG, seed=0)
    router = Router(rs).start()
    try:
        with pytest.raises(ValueError):
            router.submit([], SamplingParams(max_new_tokens=4))
        with pytest.raises(ValueError):
            router.submit([1, 2, 3], SamplingParams(top_p=0.0))
        snap = router.snapshot()
        assert snap["requests"] == 2 and snap["rejected"] == 2
        assert sum(snap["routed"].values()) == 0
        # a valid request still works afterwards
        p = _prompts(model, [6], seed=8)[0]
        out = router.submit(p, SamplingParams(max_new_tokens=3))
        assert len(out.result(timeout=120)) == 3
    finally:
        router.stop()


def test_router_mask_cooldown_backoff_then_recovery():
    """Fail-over hygiene (chaos PR satellite): a replica that fails
    mask_after_failures legs in a row is masked out of dispatch for
    mask_cooldown_s; the failed legs retry elsewhere after a bounded
    backoff and stay bit-identical; once the replica is respawned and
    the cooldown lapses, dispatch uses it again."""
    model = _model()
    n_req, new = 4, 24
    prompts = _prompts(model, [8] * n_req, seed=11)
    ref_eng = build_engine(model, dict(ENG_CFG), seed=0)
    ref = ref_eng.generate(prompts, max_new_tokens=new)
    ref_short = ref_eng.generate(prompts, max_new_tokens=4)

    rs = ReplicaSet.build(model, 2, ENG_CFG, seed=0)
    router = Router(rs, {"mask_after_failures": 2, "mask_cooldown_s": 2.0,
                         "backoff_base_s": 0.01,
                         "backoff_cap_s": 0.05}).start()
    try:
        streams = [router.submit(p, SamplingParams(max_new_tokens=new))
                   for p in prompts]
        # wait until r1 demonstrably owns >= mask_after_failures legs
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if (len(rs[1].server._active) >= 2
                    and all(len(s.tokens) >= 2 for s in streams)):
                break
            time.sleep(0.01)
        assert len(rs[1].server._active) >= 2, \
            "r1 should hold two in-flight legs before the kill"
        rs[1].kill()
        # every leg finishes on the survivor, outputs untouched
        outs = [s.result(timeout=300) for s in streams]
        assert outs == ref
        snap = router.snapshot()
        assert snap["failovers"] >= 2
        # two consecutive leg failures crossed the mask threshold
        assert router.masked_indices() == {1}

        rs.respawn(1)
        # the cooldown mask expires on its own (no operator unmask)
        deadline = time.monotonic() + 10
        while router.masked_indices() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert router.masked_indices() == set()
        # dispatch trusts the recovered replica again — and correctness
        # still holds through the respawn
        outs = [router.submit(p, SamplingParams(max_new_tokens=4))
                for p in prompts]
        assert [s.result(timeout=300) for s in outs] == ref_short
        assert rs[1].server.metrics.snapshot()["submitted"] >= 1, \
            "recovered replica should serve again after the cooldown"
    finally:
        router.stop()
