"""v2 module registry + heuristics (ref inference/v2/modules/
module_registry.py + heuristics.py): named implementations, auto
resolution by hardware/shape, engine config overrides."""

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import model as v2_model  # registers impls
from deepspeed_tpu.inference.v2.modules import (available, module_overrides,
                                                register_module, resolve)


def test_builtin_attention_impls_registered():
    names = available("attention")
    assert "paged_pallas" in names and "paged_xla" in names


def test_auto_resolution_by_context():
    # CPU / no tables → xla fallback
    impl = resolve("attention", "auto", block_size=16, head_dim=64,
                   on_tpu=False, has_tables=False)
    assert impl is v2_model._attn_impl_xla
    # TPU-shaped context with servable geometry → pallas
    impl = resolve("attention", "auto", block_size=16, head_dim=64,
                   on_tpu=True, has_tables=True)
    assert impl is v2_model._attn_impl_pallas


def test_explicit_name_and_errors():
    assert resolve("attention", "paged_xla") is v2_model._attn_impl_xla
    with pytest.raises(KeyError, match="unknown attention"):
        resolve("attention", "nope")
    with pytest.raises(KeyError, match="no implementations"):
        resolve("rotary", "auto")


def test_custom_registration_and_priority():
    calls = []

    @register_module("testkind", "special",
                     default_for=lambda fast=False, **_: fast)
    def special():
        calls.append("special")

    @register_module("testkind", "plain")
    def plain():
        calls.append("plain")

    resolve("testkind", "auto", fast=True)()
    resolve("testkind", "auto", fast=False)()
    assert calls == ["special", "plain"]


def test_engine_override_reaches_model_config():
    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.models import get_model_config

    model = get_model_config("llama-tiny")
    eng = InferenceEngineV2(model, {"modules": {"attention": "paged_xla"}})
    assert dict(eng.model_config.v2_modules)["attention"] == "paged_xla"
    # generation still works through the pinned implementation
    out = eng.generate([[1, 2, 3]], max_new_tokens=4)
    assert len(out[0]) == 4
    assert module_overrides({"modules": {"attention": "paged_xla"}}) == {
        "attention": "paged_xla"}
    assert module_overrides({}) == {}
