"""Sparse attention configs/kernel + model features (PLD, eigenvalue,
tiled linear, sparse tensors).

Mirrors reference coverage: tests/unit/ops/sparse_attention/, runtime
feature tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import (BigBirdSparsityConfig,
                                                BSLongformerSparsityConfig,
                                                DenseSparsityConfig,
                                                FixedSparsityConfig,
                                                VariableSparsityConfig,
                                                layout_to_token_mask,
                                                sparse_attention)
from deepspeed_tpu.runtime.model_features import (Eigenvalue,
                                                  ProgressiveLayerDrop,
                                                  SparseTensor, layer_drop,
                                                  tiled_linear)


def _qkv(b=1, s=64, h=2, d=8, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
                 for _ in range(3))


def test_layouts_shapes_and_coverage():
    for cfg in [FixedSparsityConfig(2, block=8, num_local_blocks=2),
                BSLongformerSparsityConfig(2, block=8),
                BigBirdSparsityConfig(2, block=8),
                VariableSparsityConfig(2, block=8, local_window_blocks=[2, 4])]:
        layout = cfg.make_layout(64)
        assert layout.shape == (2, 8, 8)
        assert layout.sum() > 0
        # every query block attends at least one key block
        assert (layout.sum(-1) > 0).all()
    with pytest.raises(ValueError):
        FixedSparsityConfig(2, block=16).make_layout(40)


def test_longformer_window_and_global():
    cfg = BSLongformerSparsityConfig(1, block=8, num_sliding_window_blocks=3,
                                     global_block_indices=[0])
    lay = cfg.make_layout(64)[0]
    assert lay[0].all() and lay[:, 0].all()  # global row+col
    assert lay[4, 3] and lay[4, 4] and lay[4, 5]  # window
    assert not lay[4, 6]  # outside window, not global


def test_dense_config_matches_full_attention():
    q, k, v = _qkv()
    cfg = DenseSparsityConfig(2, block=8)
    out = sparse_attention(q, k, v, cfg)
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k)
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_sparse_attention_respects_mask():
    q, k, v = _qkv(s=32)
    cfg = BSLongformerSparsityConfig(2, block=8, num_sliding_window_blocks=1,
                                     global_block_indices=[])
    out = sparse_attention(q, k, v, cfg, causal=True)
    # block-diagonal layout + causal: token 8 only sees keys 8..8 in its
    # block → changing key 0 must not affect query 8's output
    k2 = k.at[:, 0].set(k[:, 0] + 100.0)
    out2 = sparse_attention(q, k2, v, cfg, causal=True)
    np.testing.assert_allclose(np.asarray(out[:, 8:16]),
                               np.asarray(out2[:, 8:16]), atol=1e-6)


def test_causal_sparse_attention():
    q, k, v = _qkv(s=32)
    cfg = DenseSparsityConfig(2, block=8)
    out = sparse_attention(q, k, v, cfg, causal=True)
    # first token attends only itself → output == v[0]
    np.testing.assert_allclose(np.asarray(out[:, 0]), np.asarray(v[:, 0]),
                               atol=2e-5)


def test_layout_to_token_mask():
    lay = np.zeros((1, 2, 2), np.int64)
    lay[0, 1, 0] = 1
    m = layout_to_token_mask(lay, 4)
    assert m.shape == (1, 8, 8)
    assert bool(m[0, 5, 2]) and not bool(m[0, 1, 1])


# ----------------------------------------------------------------------
def test_progressive_layer_drop_schedule():
    pld = ProgressiveLayerDrop(theta=0.5, gamma=0.01)
    assert pld.get_theta(0) == pytest.approx(1.0)
    assert pld.update_state(10**6) == pytest.approx(0.5, abs=1e-3)
    ths = [pld.get_theta(s) for s in range(0, 1000, 100)]
    assert all(a >= b for a, b in zip(ths, ths[1:]))  # monotone decay
    assert pld.get_state()["pld_theta"] == pld.current_theta


def test_layer_drop_keep_and_skip():
    f = lambda x: x * 2.0  # noqa: E731
    x = jnp.ones((2, 4))
    kept = layer_drop(f, x, keep_prob=1.0, key=jax.random.PRNGKey(0))
    np.testing.assert_allclose(np.asarray(kept), 2.0)
    skipped = layer_drop(f, x, keep_prob=0.0, key=jax.random.PRNGKey(0),
                         layer_idx=1, num_layers=1)
    np.testing.assert_allclose(np.asarray(skipped), 1.0)  # identity


def test_eigenvalue_quadratic():
    # loss = 0.5 x^T A x with known top eigenvalue
    a = np.diag([4.0, 1.0, 0.5]).astype(np.float32)
    A = jnp.asarray(a)

    def loss(p):
        x = p["x"]
        return 0.5 * x @ A @ x

    eig = Eigenvalue(max_iter=50, tol=1e-6)
    out = eig.compute(loss, {"x": jnp.ones((3,), jnp.float32)},
                      jax.random.PRNGKey(0))
    assert out["__global__"] == pytest.approx(4.0, rel=1e-2)


def test_tiled_linear_matches():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((4, 12)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((12, 8)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((8,)), jnp.float32)
    out = tiled_linear(x, w, b, in_splits=3, out_splits=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x @ w + b),
                               atol=1e-5)
    act = tiled_linear(x, w, b, in_splits=2, out_splits=4,
                       activation=jax.nn.relu)
    np.testing.assert_allclose(np.asarray(act),
                               np.asarray(jax.nn.relu(x @ w + b)), atol=1e-5)
    with pytest.raises(ValueError):
        tiled_linear(x, w, None, in_splits=5)


def test_sparse_tensor_roundtrip_and_add():
    dense = jnp.zeros((6, 3)).at[1].set(2.0).at[4].set(-1.0)
    st = SparseTensor.from_dense(dense)
    assert st.indices.shape[0] == 2
    np.testing.assert_allclose(np.asarray(st.to_dense()), np.asarray(dense))
    both = SparseTensor.add(st, st)
    np.testing.assert_allclose(np.asarray(both.to_dense()),
                               np.asarray(dense * 2))
    assert st.sparse_size() < dense.size


# ---------------------------------------------------------------------------
# Sparse gradients (ref runtime/sparse_tensor.py + engine.py:145 sparse
# bucket): COO semantics + engine trajectory parity vs dense gradients.
# ---------------------------------------------------------------------------
def test_sparse_tensor_coo_semantics():
    from deepspeed_tpu.runtime.sparse import SparseTensor

    dense = jnp.arange(20, dtype=jnp.float32).reshape(5, 4)
    st = SparseTensor.from_dense_rows(dense, jnp.array([1, 3], jnp.int32))
    out = np.asarray(st.to_dense())
    np.testing.assert_array_equal(out[1], np.asarray(dense[1]))
    np.testing.assert_array_equal(out[3], np.asarray(dense[3]))
    assert out[0].sum() == 0 and out[2].sum() == 0 and out[4].sum() == 0
    # duplicate indices sum (scatter-add semantics)
    st2 = SparseTensor(jnp.array([2, 2], jnp.int32),
                       jnp.ones((2, 4), jnp.float32), (5, 4))
    np.testing.assert_array_equal(np.asarray(st2.to_dense())[2],
                                  np.full(4, 2.0))
    # add concatenates; add_into accumulates into an existing buffer
    both = st.add(st2)
    np.testing.assert_array_equal(np.asarray(both.to_dense()),
                                  np.asarray(st.to_dense() + st2.to_dense()))
    acc = both.add_into(jnp.ones((5, 4), jnp.float32))
    np.testing.assert_array_equal(
        np.asarray(acc), np.asarray(st.to_dense() + st2.to_dense() + 1.0))
    assert both.sparse_size() == 4 * 4 + 4    # 4 rows of 4 + 4 indices
    assert both.dense_size() == 20
    # pytree roundtrip (must survive jit boundaries)
    leaves, treedef = jax.tree_util.tree_flatten(st)
    st3 = jax.tree_util.tree_unflatten(treedef, leaves)
    assert st3.dense_shape == st.dense_shape


def _sparse_losses(mesh, sparse, n=4):
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.parallel import topology
    from tests.conftest import make_lm_batch

    model = get_model_config("llama-tiny")  # untied embeddings
    assert not model.tie_embeddings
    dp = 1
    for ax in ("data", "expert"):
        dp *= mesh.get(ax, 1)
    cfg = {
        "train_batch_size": 8,
        "train_micro_batch_size_per_gpu": 8 // dp,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "gradient_clipping": 1.0,
        "steps_per_print": 1000,
        "mesh": mesh,
        "sparse_gradients": sparse,
    }
    engine, _, _, _ = ds.initialize(model=model, config=cfg, seed=13)
    rng = np.random.default_rng(5)
    batch = make_lm_batch(rng, 8, 32, model.vocab_size)
    out = [float(np.asarray(engine.train_batch(batch))) for _ in range(n)]
    topology._GLOBAL_TOPOLOGY = None
    return out


def test_sparse_gradients_match_dense_dp1():
    dense = _sparse_losses({"data": 1}, sparse=False)
    sparse = _sparse_losses({"data": 1}, sparse=True)
    np.testing.assert_allclose(dense, sparse, rtol=1e-5, atol=1e-6)
    assert sparse[-1] < sparse[0]


def test_sparse_gradients_match_dense_dp4():
    """The sparse (ids, values) all_gather reduction must reproduce the
    dense psum trajectory on a real dp mesh."""
    dense = _sparse_losses({"data": 4}, sparse=False)
    sparse = _sparse_losses({"data": 4}, sparse=True)
    np.testing.assert_allclose(dense, sparse, rtol=1e-5, atol=1e-6)
    assert sparse[-1] < sparse[0]


def test_sparse_gradients_tied_embeddings_falls_back():
    """gpt2 ties embeddings: the engine must warn + use dense gradients,
    not crash or silently drop the lm_head grad."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.parallel import topology
    from tests.conftest import make_lm_batch

    model = get_model_config("gpt2-tiny")
    assert model.tie_embeddings
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "steps_per_print": 1000,
        "mesh": {"data": 1},
        "sparse_gradients": True,
    }
    engine, _, _, _ = ds.initialize(model=model, config=cfg, seed=13)
    rng = np.random.default_rng(6)
    batch = make_lm_batch(rng, 4, 32, model.vocab_size)
    losses = [float(np.asarray(engine.train_batch(batch))) for _ in range(3)]
    topology._GLOBAL_TOPOLOGY = None
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]
