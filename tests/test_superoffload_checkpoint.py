"""SuperOffload × checkpoint-writer matrix (ADVICE r3 medium finding).

The host optimizer owns the fp32 masters/moments when
``offload_optimizer.super_offload`` is set (engine.opt_state is None), so
every writer must either round-trip ``_super_opt.state_dict()`` (pickle,
fast, decoupled) or refuse loudly (orbax) — and a weights-only resume must
re-seed the masters or the next step's push_params reverts the load.
"""

import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import get_model_config
from tests.conftest import make_lm_batch


def _reset_topo():
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None


def _so_engine(writer=None, seed=19):
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "gradient_clipping": 0.0,
        "steps_per_print": 1000,
        "mesh": {"data": 1},
        "zero_optimization": {
            "offload_optimizer": {"device": "cpu", "super_offload": True}},
    }
    if writer:
        cfg["checkpoint"] = {"writer": {"type": writer}}
    model = get_model_config("gpt2-tiny")
    engine, _, _, _ = ds.initialize(model=model, config=cfg, seed=seed)
    return engine, model


def _params_flat(engine):
    import jax

    return np.concatenate([np.asarray(x, np.float32).ravel()
                           for x in jax.tree.leaves(engine.params)])


@pytest.mark.parametrize("writer", ["fast", "decoupled"])
def test_fast_writer_roundtrips_superoffload(tmp_path, writer):
    rng = np.random.default_rng(31)
    batch = make_lm_batch(rng, 4, 32, 512)
    engine, model = _so_engine(writer)
    for _ in range(2):
        engine.train_batch(batch)
    engine.save_checkpoint(str(tmp_path), tag="so")
    ce = engine.checkpoint_engine
    if hasattr(ce, "wait"):
        ce.wait()
    master_ref = [m.copy() for m in engine._super_opt._master]
    step_ref = engine._super_opt.step_count
    after_save = _params_flat(engine)
    _reset_topo()

    engine2, _ = _so_engine(writer, seed=77)  # different init
    engine2.load_checkpoint(str(tmp_path), tag="so")
    assert engine2._super_opt.step_count == step_ref
    for a, b in zip(engine2._super_opt._master, master_ref):
        np.testing.assert_allclose(a, b, atol=0)
    np.testing.assert_allclose(_params_flat(engine2), after_save, atol=1e-6)
    # the restore must SURVIVE a train step (push_params reads masters) —
    # both engines stepping on the same batch must stay in lockstep
    l1 = float(np.asarray(engine.train_batch(batch)))
    l2 = float(np.asarray(engine2.train_batch(batch)))
    assert abs(l1 - l2) < 1e-5, (l1, l2)
    np.testing.assert_allclose(_params_flat(engine2), _params_flat(engine),
                               atol=1e-6)
    _reset_topo()


def test_weights_only_resume_reseeds_masters(tmp_path):
    rng = np.random.default_rng(32)
    batch = make_lm_batch(rng, 4, 32, 512)
    engine, _ = _so_engine("fast")
    for _ in range(2):
        engine.train_batch(batch)
    engine.save_checkpoint(str(tmp_path), tag="w")
    saved = _params_flat(engine)
    _reset_topo()

    engine2, _ = _so_engine("fast", seed=77)
    engine2.load_checkpoint(str(tmp_path), tag="w",
                            load_optimizer_states=False)
    np.testing.assert_allclose(_params_flat(engine2), saved, atol=1e-6)
    assert engine2._super_opt.step_count == 0  # fresh moments
    # the loaded weights must survive the next step (masters re-seeded)
    engine2.train_batch(batch)
    moved = _params_flat(engine2)
    # params changed by ~lr, not reverted to the seed-77 random init
    assert np.abs(moved - saved).max() < 0.1, "weights reverted on step"
    _reset_topo()


def test_orbax_writer_refuses_superoffload(tmp_path):
    from deepspeed_tpu.runtime.config import DeepSpeedConfigError

    engine, _ = _so_engine("orbax")
    with pytest.raises(DeepSpeedConfigError, match="super_offload"):
        engine.save_checkpoint(str(tmp_path), tag="x")
    _reset_topo()
