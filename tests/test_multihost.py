"""Multi-host correctness: 2 real ``jax.distributed`` CPU processes train,
checkpoint, barrier, and convert to a universal checkpoint; a separate
1-process run reloads it at the different world size.

This is the analog of the reference's ``DistributedExec`` harness
(``tests/unit/common.py:134``, file-store rendezvous at ``:331``) with the
rendezvous replaced by a jax.distributed coordinator, and of
``checkpoint/ds_to_universal.py:112`` elasticity coverage.

Each worker runs in a fresh subprocess (its own JAX runtime): 2 processes
x 2 local CPU devices = a 4-device global mesh, dp=4.
"""

import json
import os
import pickle
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import os, sys, json, pickle
    import numpy as np

    rank = int(sys.argv[1]); world = int(sys.argv[2])
    port = sys.argv[3]; out_dir = sys.argv[4]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["DSTPU_COORDINATOR"] = f"localhost:{port}"
    os.environ["DSTPU_NUM_PROCS"] = str(world)
    os.environ["DSTPU_PROC_ID"] = str(rank)
    sys.path.insert(0, os.environ["DSTPU_TEST_REPO"])

    import jax
    jax.config.update("jax_platforms", "cpu")  # axon plugin pins platforms
    import deepspeed_tpu as ds
    from deepspeed_tpu.comm import comm
    from deepspeed_tpu.models import get_model_config

    topo = comm.init_distributed(mesh_sizes={"data": 4})
    assert jax.process_count() == world, jax.process_count()
    assert len(jax.devices()) == 4, jax.devices()
    assert comm.get_world_size() == 4  # world = devices (2 procs x 2 local)
    assert comm.get_rank() == rank     # host-level rank = process index
    comm.barrier()

    # host-object collectives across REAL processes (ref
    # dist.all_gather_object/broadcast_object_list, comm.py:247/:229)
    gathered = comm.all_gather_object({"rank": rank, "tag": "x" * (rank + 1)})
    assert gathered == [{"rank": 0, "tag": "x"}, {"rank": 1, "tag": "xx"}], gathered
    objs = [f"from-{rank}", rank * 10]
    comm.broadcast_object_list(objs, src=1)
    assert objs == ["from-1", 10], objs
    # src is a GLOBAL rank (reference semantics): with the reversed group
    # (1, 0), src=1 must still pick process 1's payload, not index 1.
    objs = [f"from-{rank}"]
    comm.broadcast_object_list(objs, src=1, group=(1, 0))
    assert objs == ["from-1"], objs
    try:
        comm.broadcast_object_list([0], src=5, group=(1, 0))
        raise AssertionError("src outside group must raise")
    except ValueError:
        pass
    comm.monitored_barrier(timeout=60.0)

    model = get_model_config("gpt2-tiny")
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "steps_per_print": 1000,
        "mesh": {"data": 4},
        "checkpoint": {"writer": {"type": "fast"}},
    }
    engine, _, _, _ = ds.initialize(model=model, config=cfg, seed=17)
    rng = np.random.default_rng(0)  # identical data on both processes
    ids = rng.integers(0, model.vocab_size, size=(8, 33), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    losses = [float(np.asarray(engine.train_batch(batch))) for _ in range(3)]
    assert all(np.isfinite(losses)), losses

    # ---- fast engine save: per-process files, rank-0 commit --------------
    engine.save_checkpoint(out_dir, tag="t1")
    comm.barrier()

    # perturb, then reload and check the roundtrip restores training state
    before = np.asarray(
        jax.experimental.multihost_utils.process_allgather(
            engine.params["embed"]["tokens"] if isinstance(engine.params["embed"], dict) else engine.params["embed"], tiled=True))
    engine.params = jax.tree.map(lambda x: x * 0, engine.params)
    engine.load_checkpoint(out_dir, tag="t1")
    after = np.asarray(
        jax.experimental.multihost_utils.process_allgather(
            engine.params["embed"]["tokens"] if isinstance(engine.params["embed"], dict) else engine.params["embed"], tiled=True))
    np.testing.assert_array_equal(before, after)
    loss_after = float(np.asarray(engine.train_batch(batch)))
    assert np.isfinite(loss_after)

    # ---- pickle engine save (per-process mp_rank files) + universal ------
    from deepspeed_tpu.checkpoint.engine import save_checkpoint
    from deepspeed_tpu.checkpoint.universal import ds_to_universal
    pik_dir = os.path.join(out_dir, "pickle_ckpt")
    save_checkpoint(engine, pik_dir, tag="u1")
    comm.barrier()
    uni = ds_to_universal(pik_dir, tag="u1")
    comm.barrier()

    if rank == 0:
        # snapshot of the weights the u1/universal checkpoint contains
        final = np.asarray(
            jax.experimental.multihost_utils.process_allgather(
                engine.params["embed"]["tokens"], tiled=True))
        with open(os.path.join(out_dir, "result.json"), "w") as f:
            json.dump({"losses": losses, "loss_after": loss_after,
                        "universal_dir": uni}, f)
        np.save(os.path.join(out_dir, "final_wte.npy"), final)
    comm.barrier()
    print(f"worker {rank} OK", flush=True)
""")

RELOADER = textwrap.dedent("""
    import os, sys, json
    import numpy as np

    out_dir = sys.argv[1]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ.pop("DSTPU_COORDINATOR", None)
    os.environ.pop("DSTPU_NUM_PROCS", None)
    sys.path.insert(0, os.environ["DSTPU_TEST_REPO"])

    import jax
    jax.config.update("jax_platforms", "cpu")  # axon plugin pins platforms
    import deepspeed_tpu as ds
    from deepspeed_tpu.checkpoint.universal import (load_universal,
                                                    resolve_universal_dir)
    from deepspeed_tpu.models import get_model_config

    with open(os.path.join(out_dir, "result.json")) as f:
        res = json.load(f)

    # DIFFERENT topology than the save: 1 process, dp=2 x tp=2 over 4 devices
    model = get_model_config("gpt2-tiny")
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "steps_per_print": 1000,
        "mesh": {"data": 2, "tensor": 2},
    }
    engine, _, _, _ = ds.initialize(model=model, config=cfg, seed=99)
    load_universal(engine, resolve_universal_dir(res["universal_dir"]))

    saved = np.load(os.path.join(out_dir, "final_wte.npy"))
    np.testing.assert_array_equal(np.asarray(engine.params["embed"]["tokens"] if isinstance(engine.params["embed"], dict) else engine.params["embed"]), saved)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(8, 33), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    loss = float(np.asarray(engine.train_batch(batch)))
    assert np.isfinite(loss)
    print(f"reloader OK loss={loss}", flush=True)
""")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_workers(script: str, args_per_proc, timeout=420):
    # log to files, not pipes: a full pipe buffer on one worker while the
    # harness blocks on another would deadlock the collective they share
    import tempfile

    procs, files = [], []
    for i, args in enumerate(args_per_proc):
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(("DSTPU_", "XLA_", "JAX_"))}
        env["DSTPU_TEST_REPO"] = REPO
        f = tempfile.NamedTemporaryFile("w+", suffix=f"_w{i}.log", delete=False)
        files.append(f)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script, *map(str, args)],
            stdout=f, stderr=subprocess.STDOUT, env=env))
    outs = []
    for p, f in zip(procs, files):
        try:
            p.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
        f.flush()
        f.seek(0)
        outs.append(f.read())
        f.close()
        os.unlink(f.name)
    return procs, outs


@pytest.mark.slow
def test_two_process_train_checkpoint_universal(tmp_path):
    """2 jax.distributed processes: init, barrier, train dp=4, fast-engine
    save/load roundtrip, pickle save, universal conversion."""
    port = _free_port()
    out = str(tmp_path)
    procs, logs = _run_workers(
        WORKER, [(r, 2, port, out) for r in range(2)])
    for p, log in zip(procs, logs):
        assert p.returncode == 0, f"worker failed:\n{log[-3000:]}"

    # per-process fast-engine files exist (no clobbering)
    d = os.path.join(out, "t1")
    assert os.path.exists(os.path.join(d, "model_states_p000.bin"))
    assert os.path.exists(os.path.join(d, "model_states_p001.bin"))
    assert os.path.exists(os.path.join(d, "meta.json"))
    with open(os.path.join(d, "meta.json")) as f:
        assert json.load(f)["process_count"] == 2
    # per-process pickle files exist
    pd = os.path.join(out, "pickle_ckpt", "u1")
    assert os.path.exists(os.path.join(pd, "mp_rank_00_model_states.pt"))
    assert os.path.exists(os.path.join(pd, "mp_rank_01_model_states.pt"))

    # both processes trained identical losses (same data, dp replicas agree)
    with open(os.path.join(out, "result.json")) as f:
        res = json.load(f)
    assert res["losses"][-1] < res["losses"][0]

    # ---- elasticity: reload the universal ckpt at world_size=1, tp=2 -----
    procs, logs = _run_workers(RELOADER, [(out,)])
    assert procs[0].returncode == 0, f"reloader failed:\n{logs[0][-3000:]}"
    assert "reloader OK" in logs[0]
