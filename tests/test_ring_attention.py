"""Ring attention (sequence/ring.py): K/V blocks rotating the "seq" mesh
ring with online softmax — the context-parallel alternative to Ulysses
(no heads % sp requirement).  Parity against full attention, gradients,
and engine training on a seq mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.parallel.topology import MeshTopology, set_topology
from deepspeed_tpu.sequence.ring import ring_attention


def _ref_attention(q, k, v, causal=True, window=None):
    s_len = q.shape[1]
    nh, nkv = q.shape[2], k.shape[2]
    if nkv != nh:
        k = jnp.repeat(k, nh // nkv, axis=2)
        v = jnp.repeat(v, nh // nkv, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / np.sqrt(q.shape[-1])
    pos = jnp.arange(s_len)
    valid = jnp.ones((s_len, s_len), bool)
    if causal:
        valid = pos[:, None] >= pos[None, :]
    if window is not None:
        valid &= (pos[:, None] - pos[None, :]) < window
    s = jnp.where(valid[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))


@pytest.fixture
def seq_topo():
    topo = MeshTopology({"seq": 4, "data": 2})
    set_topology(topo)
    yield topo
    set_topology(None)


@pytest.mark.parametrize("causal,window,nkv", [
    (True, None, 4),     # causal MHA
    (False, None, 4),    # bidirectional
    (True, 8, 4),        # sliding window
    (True, None, 1),     # MQA: 1 KV head on a 4-way seq ring (K/V
                         # travel and attend ungrouped at nkv=1)
])
def test_ring_matches_full_attention(seq_topo, causal, window, nkv):
    rng = np.random.default_rng(0)
    b, s, nh, d = 2, 32, 4, 16
    q = jnp.asarray(rng.standard_normal((b, s, nh, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, nkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, nkv, d)), jnp.float32)
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, seq_topo, causal=causal, window=window))(q, k, v)
    ref = _ref_attention(q, k, v, causal=causal, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_sp_exceeds_query_heads():
    """seq ring LARGER than the query-head count — the regime Ulysses
    cannot shard at all (heads % sp fails): ring must still match full
    attention exactly."""
    topo = MeshTopology({"seq": 8})
    set_topology(topo)
    try:
        rng = np.random.default_rng(3)
        b, s, nh, nkv, d = 2, 32, 4, 2, 16
        q = jnp.asarray(rng.standard_normal((b, s, nh, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((b, s, nkv, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((b, s, nkv, d)), jnp.float32)
        out = jax.jit(lambda q, k, v: ring_attention(q, k, v, topo))(q, k, v)
        ref = _ref_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
    finally:
        set_topology(None)


def test_ring_grads_match_reference(seq_topo):
    rng = np.random.default_rng(1)
    b, s, nh, d = 2, 16, 2, 8
    q = jnp.asarray(rng.standard_normal((b, s, nh, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, nh, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, nh, d)), jnp.float32)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, seq_topo) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref_attention(q, k, v) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, r in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=5e-5, atol=5e-5)


def test_ring_engine_training_matches_ulysses():
    """llama-tiny on a seq=4 mesh: ring and Ulysses are the same math in
    a different order — losses must track closely, and ring must train."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.parallel import topology

    losses = {}
    try:
        for impl in ("ring", "ulysses"):
            model = get_model_config("llama-tiny", seq_impl=impl,
                                     attn_impl="xla")
            config = {
                "train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "mesh": {"seq": 4, "data": 2},
                "steps_per_print": 10_000,
            }
            engine, _, _, _ = ds.initialize(model=model, config=config,
                                            seed=7)
            rng = np.random.default_rng(0)
            ids = rng.integers(0, model.vocab_size, size=(8, 33),
                               dtype=np.int32)
            batch = {"input_ids": ids[:, :-1],
                     "labels": ids[:, 1:].astype(np.int32)}
            losses[impl] = [float(np.asarray(engine.train_batch(batch)))
                            for _ in range(4)]
            assert losses[impl][-1] < losses[impl][0], (impl, losses[impl])
            topology.set_topology(None)
    finally:
        topology.set_topology(None)
    np.testing.assert_allclose(losses["ring"], losses["ulysses"],
                               rtol=5e-3, atol=5e-3)


def test_ring_collectives_are_ppermute(seq_topo):
    """The compiled ring must move K/V with collective-permute edges (the
    nearest-neighbour ICI pattern), not all-to-all or all-gather."""
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((2, 32, 4, 16)), jnp.float32)
    hlo = jax.jit(lambda q: ring_attention(q, q, q, seq_topo)).lower(
        q).compile().as_text()
    assert "collective-permute" in hlo
    assert "all-to-all" not in hlo


# ----------------------------------------------------------------------
# Perf-grade ring: Pallas flash inner block, striped placement, entry
# asserts, and remat/ZeRO-2 composition.
# ----------------------------------------------------------------------
import importlib  # noqa: E402

from deepspeed_tpu.sequence.ring import (ring_position_map,  # noqa: E402
                                         stripe_sequence, unstripe_sequence)

fm = importlib.import_module("deepspeed_tpu.ops.pallas.flash_mha")


@pytest.fixture
def flash_interpret():
    """Route the ring's inner block through the Pallas carry kernel under
    the interpreter so the KERNEL's numerics are what the CPU mesh
    checks."""
    old = fm.INTERPRET
    fm.INTERPRET = True
    yield
    fm.INTERPRET = old


@pytest.mark.parametrize("causal,window,nkv", [
    (True, None, 4),     # causal MHA
    (False, None, 4),    # bidirectional
    (True, 8, 4),        # sliding window
    (True, None, 1),     # MQA
])
def test_ring_flash_kernel_parity(seq_topo, flash_interpret, causal,
                                  window, nkv):
    """Interpret-mode parity: each hop runs ONE fused flash pass
    (flash_carry_block) and the assembled ring output must match dense
    reference attention exactly."""
    from deepspeed_tpu.sequence import ring as ring_mod

    assert ring_mod._kernel_enabled()  # the fixture routes to the kernel
    rng = np.random.default_rng(5)
    b, s, nh, d = 2, 32, 4, 16
    q = jnp.asarray(rng.standard_normal((b, s, nh, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, nkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, nkv, d)), jnp.float32)
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, seq_topo, causal=causal, window=window))(q, k, v)
    ref = _ref_attention(q, k, v, causal=causal, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_flash_kernel_grads(seq_topo, flash_interpret):
    """Gradients through the flash-kernel forward + hand-written ring
    backward must match the dense reference."""
    rng = np.random.default_rng(6)
    q = jnp.asarray(rng.standard_normal((2, 16, 2, 8)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 16, 2, 8)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 16, 2, 8)), jnp.float32)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, seq_topo) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref_attention(q, k, v) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, r in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=5e-5, atol=5e-5)


def test_stripe_roundtrip_and_position_map():
    x = np.arange(2 * 16 * 3).reshape(2, 16, 3)
    y = stripe_sequence(x, 4)
    assert not np.array_equal(x, y)
    np.testing.assert_array_equal(unstripe_sequence(y, 4), x)
    # slot j of shard r holds token pos_map[r*s_l + j]
    pos = np.asarray(ring_position_map(16, 4, "striped"))
    s_l = 4
    for r in range(4):
        for j in range(s_l):
            np.testing.assert_array_equal(y[:, r * s_l + j],
                                          x[:, pos[r * s_l + j]])
    np.testing.assert_array_equal(
        np.asarray(ring_position_map(16, 4, "contiguous")), np.arange(16))


@pytest.mark.parametrize("use_flash", [False, True])
@pytest.mark.parametrize("nkv", [4, 2])
def test_ring_striped_matches_full_attention(seq_topo, use_flash, nkv):
    """Striped placement (causal load balancing): stripe the inputs,
    run the ring, unstripe the output — must equal dense reference
    attention in natural order, on both inner-block paths."""
    old = fm.INTERPRET
    fm.INTERPRET = use_flash
    try:
        rng = np.random.default_rng(7)
        b, s, nh, d = 2, 32, 4, 16
        sp = 4
        q = jnp.asarray(rng.standard_normal((b, s, nh, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((b, s, nkv, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((b, s, nkv, d)), jnp.float32)
        qs, ks, vs = (stripe_sequence(x, sp) for x in (q, k, v))
        out = jax.jit(lambda a, b_, c: ring_attention(
            a, b_, c, seq_topo, causal=True, placement="striped"))(qs, ks, vs)
        out = unstripe_sequence(np.asarray(out), sp)
        ref = _ref_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
    finally:
        fm.INTERPRET = old


def test_ring_striped_grads(seq_topo):
    """Striped-placement gradients: unstripe(grad(striped)) must equal
    the dense reference gradient."""
    rng = np.random.default_rng(8)
    sp = 4
    q = jnp.asarray(rng.standard_normal((2, 16, 2, 8)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 16, 2, 8)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 16, 2, 8)), jnp.float32)

    def loss_striped(q, k, v):
        return jnp.sum(ring_attention(q, k, v, seq_topo,
                                      placement="striped") ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref_attention(q, k, v) ** 2)

    g_s = jax.jit(jax.grad(loss_striped, argnums=(0, 1, 2)))(
        stripe_sequence(q, sp), stripe_sequence(k, sp),
        stripe_sequence(v, sp))
    g_r = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, r in zip(g_s, g_r):
        np.testing.assert_allclose(unstripe_sequence(np.asarray(a), sp),
                                   np.asarray(r), rtol=5e-5, atol=5e-5)


def test_ring_entry_asserts(seq_topo):
    """Loud failures instead of silent truncation/one-sided bands."""
    q = jnp.zeros((2, 32, 4, 16), jnp.float32)
    k3 = jnp.zeros((2, 32, 3, 16), jnp.float32)
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention(q, k3, k3, seq_topo)
    with pytest.raises(ValueError, match="causal"):
        ring_attention(q, q, q, seq_topo, causal=False, window=8)
    with pytest.raises(ValueError, match="window must be positive"):
        ring_attention(q, q, q, seq_topo, window=0)
    with pytest.raises(ValueError, match="placement"):
        ring_attention(q, q, q, seq_topo, placement="zigzagish")


def test_ring_backward_skips_forward_rerun_when_residuals_saved(seq_topo):
    """The ring tags its saved (o, lse) as flash_out/flash_lse.  Under a
    remat policy that KEEPS those names the backward must not re-run the
    forward's ppermute chain — strictly fewer collective-permutes than
    under nothing_saveable (which legitimately replays the ring)."""
    rng = np.random.default_rng(9)
    q = jnp.asarray(rng.standard_normal((2, 32, 4, 16)), jnp.float32)

    def counts(policy):
        def f(q, k, v):
            return jnp.sum(jax.checkpoint(
                lambda a, b, c: ring_attention(a, b, c, seq_topo),
                policy=policy)(q, k, v) ** 2)

        hlo = jax.jit(jax.grad(f, argnums=(0, 1, 2))).lower(
            q, q, q).compile().as_text()
        return hlo.count("collective-permute(")

    saved = counts(jax.checkpoint_policies.save_only_these_names(
        "flash_out", "flash_lse"))
    replayed = counts(jax.checkpoint_policies.nothing_saveable)
    assert saved < replayed, (saved, replayed)


def test_ring_zero2_train_step_hlo_and_policy():
    """ZeRO-2 × ring on a data×seq mesh: the engine upgrades the remat
    policy to flash_saveable (saving the ring's (o, lse)), the compiled
    train step moves K/V only with collective-permute (no all-to-all),
    and training takes real steps."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.parallel import topology

    try:
        model = get_model_config("llama-tiny", seq_impl="ring",
                                 attn_impl="xla")
        config = {
            "train_micro_batch_size_per_gpu": 4,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "mesh": {"seq": 4, "data": 2},
            "zero_optimization": {"stage": 2},
            "steps_per_print": 10_000,
        }
        engine, _, _, _ = ds.initialize(model=model, config=config, seed=7)
        assert engine.model_config.remat_policy == "flash_saveable"

        rng = np.random.default_rng(0)
        ids = rng.integers(0, model.vocab_size, size=(8, 33), dtype=np.int32)
        batch = {"input_ids": ids[:, :-1],
                 "labels": ids[:, 1:].astype(np.int32)}
        batch_stack = engine._put_batch(
            engine._stack_micro_batches(batch), stacked=True)
        hlo = engine._train_step_jit.lower(
            engine.params, engine.opt_state, engine.loss_scale_state,
            batch_stack, jnp.float32(1e-3)).compile().as_text()
        assert "collective-permute" in hlo
        # no all-to-all may originate from the attention path: K/V must
        # move as nearest-neighbour ring traffic.  (ZeRO-2's tiny
        # param-shaped grad reshards may legitimately lower to all-to-all
        # — filter by source metadata.)
        for line in hlo.splitlines():
            if "all-to-all" in line:
                assert "ring.py" not in line and "_attn_block" not in line \
                    and "sequence/layer.py" not in line, line

        losses = [float(np.asarray(engine.train_batch(batch)))
                  for _ in range(3)]
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0], losses
    finally:
        topology.set_topology(None)


# ----------------------------------------------------------------------
# Fused ring backward (offset-aware dq/dkv flash kernels): grad-parity
# matrix on the 2x4 CPU mesh — interpreter-mode Pallas vs the XLA einsum
# fallback, both asserted against a single-device flash reference.
# Axes: causal x windowed x striped placement x GQA.
# ----------------------------------------------------------------------
@pytest.mark.parametrize("placement,causal,window,nkv", [
    ("contiguous", True, None, 4),    # causal MHA
    ("contiguous", True, 8, 2),       # sliding window + GQA
    ("contiguous", False, None, 4),   # bidirectional
    ("striped", True, None, 2),       # striped causal + GQA
    ("striped", True, 8, 4),          # striped + window
    ("striped", False, None, 2),      # striped bidirectional + GQA
])
def test_ring_fused_bwd_parity_matrix(seq_topo, placement, causal, window,
                                      nkv):
    """The fused Pallas ring backward must match BOTH the XLA einsum
    backward (same ring, kernel gate off) and the single-device flash
    reference (fm.flash_mha grads) on every placement/mask/GQA combo."""
    rng = np.random.default_rng(11)
    b, s, nh, d = 2, 32, 4, 16
    sp = 4
    q = jnp.asarray(rng.standard_normal((b, s, nh, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, nkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, nkv, d)), jnp.float32)
    if placement == "striped":
        qr, kr, vr = (stripe_sequence(x, sp) for x in (q, k, v))
    else:
        qr, kr, vr = q, k, v

    def ring_loss(q, k, v):
        return jnp.sum(ring_attention(q, k, v, seq_topo, causal=causal,
                                      window=window,
                                      placement=placement) ** 2)

    grad_ring = jax.grad(ring_loss, argnums=(0, 1, 2))
    old = fm.INTERPRET
    try:
        fm.INTERPRET = True       # fused Pallas backward (interpreter)
        from deepspeed_tpu.sequence import ring as ring_mod

        assert ring_mod._kernel_enabled()
        g_fused = jax.jit(grad_ring)(qr, kr, vr)
        # single-device flash reference, same interpreted kernels
        g_ref = jax.grad(
            lambda q, k, v: jnp.sum(fm.flash_mha(
                q.swapaxes(1, 2), k.swapaxes(1, 2), v.swapaxes(1, 2),
                causal, None, window).swapaxes(1, 2) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        fm.INTERPRET = False      # XLA einsum fallback backward
        g_xla = jax.jit(grad_ring)(qr, kr, vr)
    finally:
        fm.INTERPRET = old
    for a, x, r in zip(g_fused, g_xla, g_ref):
        a = np.asarray(a)
        x = np.asarray(x)
        if placement == "striped":
            a = unstripe_sequence(a, sp)
            x = unstripe_sequence(x, sp)
        np.testing.assert_allclose(a, np.asarray(r), rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(x, np.asarray(r), rtol=2e-4, atol=2e-4)


def test_ring_engine_striped_matches_contiguous():
    """Engine-level striped placement: host-side stripe of ids/labels +
    stripe-aware positions is a pure reordering of the same math — the
    training loss trajectory must track the contiguous ring closely."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.parallel import topology

    losses = {}
    try:
        for placement in ("contiguous", "striped"):
            model = get_model_config("llama-tiny", seq_impl="ring",
                                     ring_placement=placement,
                                     attn_impl="xla")
            config = {
                "train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "mesh": {"seq": 4, "data": 2},
                "steps_per_print": 10_000,
            }
            engine, _, _, _ = ds.initialize(model=model, config=config,
                                            seed=7)
            rng = np.random.default_rng(0)
            ids = rng.integers(0, model.vocab_size, size=(8, 33),
                               dtype=np.int32)
            batch = {"input_ids": ids[:, :-1],
                     "labels": ids[:, 1:].astype(np.int32)}
            losses[placement] = [float(np.asarray(engine.train_batch(batch)))
                                 for _ in range(4)]
            assert losses[placement][-1] < losses[placement][0], losses
            topology.set_topology(None)
    finally:
        topology.set_topology(None)
    np.testing.assert_allclose(losses["striped"], losses["contiguous"],
                               rtol=5e-3, atol=5e-3)