"""Ring attention (sequence/ring.py): K/V blocks rotating the "seq" mesh
ring with online softmax — the context-parallel alternative to Ulysses
(no heads % sp requirement).  Parity against full attention, gradients,
and engine training on a seq mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.parallel.topology import MeshTopology, set_topology
from deepspeed_tpu.sequence.ring import ring_attention


def _ref_attention(q, k, v, causal=True, window=None):
    s_len = q.shape[1]
    nh, nkv = q.shape[2], k.shape[2]
    if nkv != nh:
        k = jnp.repeat(k, nh // nkv, axis=2)
        v = jnp.repeat(v, nh // nkv, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / np.sqrt(q.shape[-1])
    pos = jnp.arange(s_len)
    valid = jnp.ones((s_len, s_len), bool)
    if causal:
        valid = pos[:, None] >= pos[None, :]
    if window is not None:
        valid &= (pos[:, None] - pos[None, :]) < window
    s = jnp.where(valid[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))


@pytest.fixture
def seq_topo():
    topo = MeshTopology({"seq": 4, "data": 2})
    set_topology(topo)
    yield topo
    set_topology(None)


@pytest.mark.parametrize("causal,window,nkv", [
    (True, None, 4),     # causal MHA
    (False, None, 4),    # bidirectional
    (True, 8, 4),        # sliding window
    (True, None, 1),     # MQA: 1 KV head on a 4-way seq ring (K/V
                         # travel and attend ungrouped at nkv=1)
])
def test_ring_matches_full_attention(seq_topo, causal, window, nkv):
    rng = np.random.default_rng(0)
    b, s, nh, d = 2, 32, 4, 16
    q = jnp.asarray(rng.standard_normal((b, s, nh, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, nkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, nkv, d)), jnp.float32)
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, seq_topo, causal=causal, window=window))(q, k, v)
    ref = _ref_attention(q, k, v, causal=causal, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_sp_exceeds_query_heads():
    """seq ring LARGER than the query-head count — the regime Ulysses
    cannot shard at all (heads % sp fails): ring must still match full
    attention exactly."""
    topo = MeshTopology({"seq": 8})
    set_topology(topo)
    try:
        rng = np.random.default_rng(3)
        b, s, nh, nkv, d = 2, 32, 4, 2, 16
        q = jnp.asarray(rng.standard_normal((b, s, nh, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((b, s, nkv, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((b, s, nkv, d)), jnp.float32)
        out = jax.jit(lambda q, k, v: ring_attention(q, k, v, topo))(q, k, v)
        ref = _ref_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
    finally:
        set_topology(None)


def test_ring_grads_match_reference(seq_topo):
    rng = np.random.default_rng(1)
    b, s, nh, d = 2, 16, 2, 8
    q = jnp.asarray(rng.standard_normal((b, s, nh, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, nh, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, nh, d)), jnp.float32)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, seq_topo) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref_attention(q, k, v) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, r in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=5e-5, atol=5e-5)


def test_ring_engine_training_matches_ulysses():
    """llama-tiny on a seq=4 mesh: ring and Ulysses are the same math in
    a different order — losses must track closely, and ring must train."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.parallel import topology

    losses = {}
    try:
        for impl in ("ring", "ulysses"):
            model = get_model_config("llama-tiny", seq_impl=impl,
                                     attn_impl="xla")
            config = {
                "train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "mesh": {"seq": 4, "data": 2},
                "steps_per_print": 10_000,
            }
            engine, _, _, _ = ds.initialize(model=model, config=config,
                                            seed=7)
            rng = np.random.default_rng(0)
            ids = rng.integers(0, model.vocab_size, size=(8, 33),
                               dtype=np.int32)
            batch = {"input_ids": ids[:, :-1],
                     "labels": ids[:, 1:].astype(np.int32)}
            losses[impl] = [float(np.asarray(engine.train_batch(batch)))
                            for _ in range(4)]
            assert losses[impl][-1] < losses[impl][0], (impl, losses[impl])
            topology.set_topology(None)
    finally:
        topology.set_topology(None)
    np.testing.assert_allclose(losses["ring"], losses["ulysses"],
                               rtol=5e-3, atol=5e-3)


def test_ring_collectives_are_ppermute(seq_topo):
    """The compiled ring must move K/V with collective-permute edges (the
    nearest-neighbour ICI pattern), not all-to-all or all-gather."""
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((2, 32, 4, 16)), jnp.float32)
    hlo = jax.jit(lambda q: ring_attention(q, q, q, seq_topo)).lower(
        q).compile().as_text()
    assert "collective-permute" in hlo
    assert "all-to-all" not in hlo