"""ZenFlow async-host-step stress test.

The overlap contract (runtime/zenflow.py:15-18): the cold host Adam runs
on a worker thread, producing a *pending delta* that lands at the start
of a later step; ``wait()`` joins the worker before ANY read of shared
state.  The invariant under test: with identical gradient streams, the
``overlap=True`` trajectory is bit-identical to ``overlap=False`` — no
delta may be lost, doubled, or torn regardless of thread timing.

Stressors: many steps (enough cold cycles for a lost delta to compound
visibly), randomized worker latency (monkeypatched sleep inside
``_cold_update`` widens the race window beyond what tiny shapes give),
and mid-run ``state_dict``/``load_state_dict`` round-trips at arbitrary
points relative to in-flight workers.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.zenflow import ZenFlowOptimizer


def _params():
    k = jax.random.split(jax.random.PRNGKey(7), 3)
    return {
        "w1": jax.random.normal(k[0], (16, 32), jnp.float32),
        "w2": jax.random.normal(k[1], (32, 8), jnp.float32),
        "b": jax.random.normal(k[2], (8,), jnp.float32),
    }


def _grad_stream(n, params):
    keys = jax.random.split(jax.random.PRNGKey(11), n)
    return [jax.tree.map(
        lambda p, i=i: jax.random.normal(
            jax.random.fold_in(keys[i], hash(p.shape) % 997), p.shape,
            jnp.float32), params) for i in range(n)]


def _run(overlap, n_steps, latency=None, checkpoint_at=()):
    params = _params()
    opt = ZenFlowOptimizer(params, lr=0.02, topk_ratio=0.25,
                           update_interval=3, overlap=overlap)
    if latency is not None:
        orig = opt._cold_update

        def slow_cold(n):
            time.sleep(latency())
            orig(n)

        opt._cold_update = slow_cold
    saved = None
    for i, g in enumerate(_grad_stream(n_steps, params)):
        if i in checkpoint_at:
            # snapshot possibly WHILE a worker is in flight, restore into
            # a fresh optimizer, and continue from the snapshot
            saved = (jax.tree.map(np.asarray, params), opt.state_dict())
            params = jax.tree.map(jnp.asarray, saved[0])
            opt2 = ZenFlowOptimizer(params, lr=0.02, topk_ratio=0.25,
                                    update_interval=3, overlap=overlap)
            if latency is not None:
                orig2 = opt2._cold_update

                def slow_cold2(n, _o=opt2):
                    time.sleep(latency())
                    ZenFlowOptimizer._cold_update(_o, n)

                opt2._cold_update = slow_cold2
            opt2.load_state_dict(saved[1])
            opt = opt2
        params = opt.step(params, g)
    params = opt.flush(params)
    return jax.tree.map(np.asarray, params)


def test_overlap_matches_serial_many_cycles():
    """60 steps / 20 cold cycles: one lost or doubled pending delta would
    diverge the trees."""
    serial = _run(False, 60)
    overlapped = _run(True, 60)
    jax.tree.map(np.testing.assert_array_equal, serial, overlapped)


def test_overlap_matches_serial_with_jittered_latency():
    """Randomized host-step latency (0–15 ms) shifts worker completion
    past step boundaries in both directions."""
    rng = np.random.default_rng(3)
    serial = _run(False, 45)
    overlapped = _run(True, 45, latency=lambda: float(rng.uniform(0, 0.015)))
    jax.tree.map(np.testing.assert_array_equal, serial, overlapped)


@pytest.mark.parametrize("ckpt_step", [4, 5, 17])
def test_checkpoint_mid_flight_preserves_trajectory(ckpt_step):
    """state_dict/load_state_dict at arbitrary phase (incl. right after a
    worker launch at steps ≡ 0 mod 3, and mid-accumulation) must continue
    the exact serial trajectory."""
    serial = _run(False, 30)
    resumed = _run(True, 30, latency=lambda: 0.01,
                   checkpoint_at=(ckpt_step,))
    jax.tree.map(np.testing.assert_array_equal, serial, resumed)


def test_no_concurrent_mutation_window():
    """Instrument the worker with an in-critical-section flag: step() must
    never touch shared host state while the worker is inside
    _cold_update (wait() must have joined it first)."""
    params = _params()
    opt = ZenFlowOptimizer(params, lr=0.02, topk_ratio=0.25,
                           update_interval=2, overlap=True)
    in_cold = threading.Event()
    violations = []
    orig = opt._cold_update

    def guarded_cold(n):
        in_cold.set()
        time.sleep(0.02)
        orig(n)
        in_cold.clear()

    opt._cold_update = guarded_cold
    orig_step = opt.step

    def guarded_step(params, grads):
        opt.wait()
        if in_cold.is_set():
            violations.append("step entered while cold update running")
        return orig_step(params, grads)

    for g in _grad_stream(20, params):
        params = guarded_step(params, g)
    opt.flush(params)
    assert not violations, violations
