"""Parity tests for the repo-owned Pallas paged (block-table) decode
attention kernel (deepspeed_tpu/ops/pallas/paged_attention.py) run through
the Pallas interpreter on the CPU mesh, against the XLA gather fallback it
replaces on TPU. Ref kernel family: inference/v2/kernels/ragged_ops
(blocked flash over a KV block table) in the reference suite."""

import importlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pm = importlib.import_module("deepspeed_tpu.ops.pallas.paged_attention")


@pytest.fixture(autouse=True)
def _interpret_mode():
    old = pm.INTERPRET
    pm.INTERPRET = True
    yield
    pm.INTERPRET = old


def _decode_fn(*args, **kw):
    # bypass the jit wrapper so the INTERPRET toggle is honoured regardless
    # of any cached trace from a previous test
    return pm.paged_decode_attention.__wrapped__(*args, **kw)


def _ref_paged(q, k_pages, v_pages, pages, pos, clen, bs, scale):
    """Gather-based reference: materialises each token's [C, d] context."""
    t, nh, d = q.shape
    nkv = k_pages.shape[0]
    g = nh // nkv
    nb = pages.shape[1]
    c_idx = jnp.arange(nb * bs)
    rows = pages[:, c_idx // bs] * bs + (c_idx % bs)[None, :]      # [T, C]
    k_ctx = k_pages[:, rows].astype(jnp.float32)                   # [nkv,T,C,d]
    v_ctx = v_pages[:, rows].astype(jnp.float32)
    qg = q.reshape(t, nkv, g, d).astype(jnp.float32)
    s = jnp.einsum("tkgd,ktcd->tkgc", qg, k_ctx) * scale
    valid = (c_idx[None, :] <= pos[:, None]) & (c_idx[None, :] < clen[:, None])
    s = jnp.where(valid[:, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("tkgc,ktcd->tkgd", p, v_ctx)
    return out.reshape(t, nh, d)


def _make_case(key, t, nh, nkv, d, n_pages, nb, bs, poison=False):
    """Random tokens with ragged context lengths over a shared page pool.

    Each token gets `nb` block-table slots; slots beyond its context point
    at page 0 (shared garbage, like a real allocator's freed pages)."""
    ks = jax.random.split(key, 4)
    q = jax.random.normal(ks[0], (t, nh, d), jnp.bfloat16)
    P = n_pages * bs
    k_pages = jax.random.normal(ks[1], (nkv, P, d), jnp.bfloat16)
    v_pages = jax.random.normal(ks[2], (nkv, P, d), jnp.bfloat16)
    # ragged context lengths in [1, nb*bs]
    clen = jax.random.randint(ks[3], (t,), 1, nb * bs + 1)
    pos = clen - 1                                   # decode: last position
    # distinct pages per token where possible, wrapping over the pool;
    # table entries past the context are garbage (page 0)
    tbl = (np.arange(t)[:, None] * nb + np.arange(nb)[None, :]) % n_pages
    used = (np.asarray(clen)[:, None] > np.arange(nb)[None, :] * bs)
    tbl = np.where(used, tbl, 0)
    if poison:
        # huge finite values in page 0 must never leak through the masks
        k_pages = k_pages.at[:, :bs].set(1e3)
        v_pages = v_pages.at[:, :bs].set(1e3)
        tbl = np.where(used, tbl + 1, 0)             # keep page 0 pure garbage
        tbl = np.minimum(tbl, n_pages - 1)
    return q, k_pages, v_pages, jnp.asarray(tbl, jnp.int32), pos, clen


CASES = [
    # t, nh, nkv, d, n_pages, nb, bs
    (4, 4, 4, 64, 8, 2, 16),       # MHA, multi-page
    (5, 8, 2, 64, 16, 3, 16),      # GQA 4x, 3 pages
    (3, 4, 1, 64, 8, 2, 32),       # MQA, wider pages
    (2, 4, 2, 128, 8, 2, 8),       # d=128, minimal block size
]


@pytest.mark.parametrize("t,nh,nkv,d,n_pages,nb,bs", CASES)
def test_paged_parity(t, nh, nkv, d, n_pages, nb, bs):
    q, kp, vp, tbl, pos, clen = _make_case(
        jax.random.PRNGKey(0), t, nh, nkv, d, n_pages, nb, bs)
    scale = 1.0 / np.sqrt(d)
    out = _decode_fn(q, kp, vp, tbl, pos, clen, block_size=bs, sm_scale=scale)
    ref = _ref_paged(q, kp, vp, tbl, pos, clen, bs, scale)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref)))
    assert err < 0.05, err


def test_garbage_page_masking():
    """Block-table slots past a token's context point at a poison page of
    huge values; output must still match the masked reference."""
    q, kp, vp, tbl, pos, clen = _make_case(
        jax.random.PRNGKey(1), 5, 8, 2, 64, 16, 3, 16, poison=True)
    scale = 1.0 / np.sqrt(64)
    out = _decode_fn(q, kp, vp, tbl, pos, clen, block_size=16, sm_scale=scale)
    ref = _ref_paged(q, kp, vp, tbl, pos, clen, 16, scale)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref)))
    assert err < 0.05, err
    assert bool(jnp.all(jnp.isfinite(out.astype(jnp.float32))))


def test_mid_sequence_positions():
    """pos < clen - 1 (e.g. SplitFuse chunked prefill): the causal frontier,
    not the context length, must bound attention."""
    t, nh, nkv, d, bs, nb = 4, 4, 2, 64, 16, 2
    q, kp, vp, tbl, _, _ = _make_case(
        jax.random.PRNGKey(2), t, nh, nkv, d, 8, nb, bs)
    clen = jnp.full((t,), nb * bs, jnp.int32)
    pos = jnp.asarray([0, 7, 16, nb * bs - 1], jnp.int32)
    scale = 1.0 / np.sqrt(d)
    out = _decode_fn(q, kp, vp, tbl, pos, clen, block_size=bs, sm_scale=scale)
    ref = _ref_paged(q, kp, vp, tbl, pos, clen, bs, scale)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref)))
    assert err < 0.05, err


def test_dispatch_uses_pallas_kernel(monkeypatch):
    """inference v2's _paged_attention routes through the repo kernel when
    block tables are available on TPU, and the kernel output matches the
    XLA gather path it replaces."""
    from deepspeed_tpu.inference.v2 import model as m2
    from deepspeed_tpu.models.transformer import TransformerConfig

    monkeypatch.setattr(m2, "_on_tpu", lambda: True)
    calls = {"n": 0}
    real = pm.paged_decode_attention

    def counting(*a, **kw):
        calls["n"] += 1
        return real.__wrapped__(*a, **kw)

    # model.py binds the kernel at import — patch the consumer's name
    monkeypatch.setattr(m2, "paged_decode_attention", counting)

    t, nh, nkv, d, bs, nb = 3, 8, 2, 64, 16, 2
    q, kp, vp, tbl, pos, clen = _make_case(
        jax.random.PRNGKey(3), t, nh, nkv, d, 8, nb, bs)
    cfg = TransformerConfig(num_heads=nh, num_kv_heads=nkv,
                            hidden_size=nh * d, use_rope=True, arch="llama")
    # gather_idx for the XLA path: flat page-row index of each ctx position
    c_idx = jnp.arange(nb * bs)
    gather_idx = tbl[:, c_idx // bs] * bs + (c_idx % bs)[None, :]
    token_slot = jnp.arange(t, dtype=jnp.int32)
    out = m2._paged_attention(q, kp, vp, gather_idx, pos, clen, cfg,
                              block_tables=tbl, token_slot=token_slot,
                              block_size=bs)
    assert calls["n"] == 1, "Pallas paged kernel was not dispatched"
    ref = m2._paged_attention_xla(q, kp, vp, gather_idx, pos, clen, cfg)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    assert err < 0.05, err


def test_paged_sliding_window_parity():
    """Mistral sliding-window masking in the paged kernel (pages wholly
    before the window are grid-skipped; partial pages masked per-row)."""
    t, nh, nkv, d, n_pages, nb, bs, window = 5, 4, 2, 64, 16, 4, 16, 24
    q, kp, vp, tbl, pos, clen = _make_case(
        jax.random.PRNGKey(5), t, nh, nkv, d, n_pages, nb, bs)
    scale = 1.0 / np.sqrt(d)
    out = _decode_fn(q, kp, vp, tbl, pos, clen, block_size=bs,
                     sm_scale=scale, window=window)

    # reference with window mask
    nbk = tbl.shape[1]
    c_idx = jnp.arange(nbk * bs)
    rows = tbl[:, c_idx // bs] * bs + (c_idx % bs)[None, :]
    k_ctx = kp[:, rows].astype(jnp.float32)
    v_ctx = vp[:, rows].astype(jnp.float32)
    g = nh // nkv
    qg = q.reshape(t, nkv, g, d).astype(jnp.float32)
    s = jnp.einsum("tkgd,ktcd->tkgc", qg, k_ctx) * scale
    valid = ((c_idx[None, :] <= pos[:, None])
             & (c_idx[None, :] < clen[:, None])
             & (pos[:, None] - c_idx[None, :] < window))
    s = jnp.where(valid[:, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    ref = jnp.einsum("tkgc,ktcd->tkgd", p, v_ctx).reshape(t, nh, d)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref)))
    assert err < 0.05, err


@pytest.mark.parametrize("t,nh,nkv,d,n_pages,nb,bs", CASES)
def test_paged_quantized_parity(t, nh, nkv, d, n_pages, nb, bs):
    """Int8-KV kernel variant: quantize the page pools per (head, row),
    run the quantized kernel, and compare against the float reference on
    the DEQUANTIZED pools (exact math parity) and against the original
    float pools (small quantization error)."""
    q, kp, vp, tbl, pos, clen = _make_case(
        jax.random.PRNGKey(1), t, nh, nkv, d, n_pages, nb, bs)
    scale = 1.0 / np.sqrt(d)

    def quantize(p):
        pf = p.astype(jnp.float32)
        s = jnp.maximum(jnp.max(jnp.abs(pf), axis=-1), 1e-8) / 127.0
        q8 = jnp.clip(jnp.round(pf / s[..., None]), -127, 127)
        return q8.astype(jnp.int8), s

    kq, ks = quantize(kp)
    vq, vs = quantize(vp)
    out = _decode_fn(q, kq, vq, tbl, pos, clen, block_size=bs,
                     sm_scale=scale, k_scales=ks, v_scales=vs)
    deq = lambda q8, s: (q8.astype(jnp.float32) * s[..., None]).astype(jnp.bfloat16)
    ref_exact = _ref_paged(q, deq(kq, ks), deq(vq, vs), tbl, pos, clen, bs,
                           scale)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref_exact)))
    assert err < 0.05, err
    ref_float = _ref_paged(q, kp, vp, tbl, pos, clen, bs, scale)
    qerr = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref_float)))
    assert qerr < 0.15, qerr  # int8 per-row quantization noise bound
