"""safe_get/set accessors for ZeRO-sharded state
(deepspeed_tpu/utils/tensor_fragment.py; ref utils/tensor_fragment.py:134+
and its Local API)."""

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import get_model_config
from deepspeed_tpu.parallel import topology


@pytest.fixture
def zero3_engine():
    model = get_model_config("gpt2-tiny")
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        # threshold 0: even tiny params shard, so the accessors are
        # exercised against genuinely partitioned leaves
        "zero_optimization": {"stage": 3,
                              "param_persistence_threshold": 0},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config, seed=5)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(16, 33), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    engine.train_batch(batch)  # populate optimizer state
    yield engine, batch
    topology._GLOBAL_TOPOLOGY = None


def test_get_full_param_assembles_sharded(zero3_engine):
    engine, _ = zero3_engine
    w = ds.safe_get_full_fp32_param(engine, "layers/attn/wq")
    mc = engine.model_config
    assert w.shape == (mc.num_layers, mc.hidden_size,
                       mc.num_heads * mc.dim_per_head)
    assert np.isfinite(w).all() and np.abs(w).sum() > 0
    with pytest.raises(KeyError, match="no param"):
        ds.safe_get_full_fp32_param(engine, "layers/attn/nope")


def test_set_full_param_roundtrips_and_trains(zero3_engine):
    engine, batch = zero3_engine
    w = ds.safe_get_full_fp32_param(engine, "embed/tokens")
    ds.safe_set_full_fp32_param(engine, "embed/tokens", w * 0.5)
    w2 = ds.safe_get_full_fp32_param(engine, "embed/tokens")
    np.testing.assert_allclose(w2, w * 0.5, rtol=1e-6)
    # sharding preserved → the engine still trains
    loss = float(np.asarray(engine.train_batch(batch)))
    assert np.isfinite(loss)


def test_optimizer_state_by_torch_key(zero3_engine):
    engine, batch = zero3_engine
    m = ds.safe_get_full_optimizer_state(engine, "embed/tokens", "exp_avg")
    v = ds.safe_get_full_optimizer_state(engine, "embed/tokens",
                                         "exp_avg_sq")
    assert m.shape == v.shape and (v >= 0).all()
    assert np.abs(m).sum() > 0  # one step taken in the fixture
    # set: zero the second moment and confirm the write landed sharded
    ds.safe_set_full_optimizer_state(engine, "embed/tokens",
                                     np.zeros_like(v), "exp_avg_sq")
    v2 = ds.safe_get_full_optimizer_state(engine, "embed/tokens",
                                          "exp_avg_sq")
    assert np.abs(v2).sum() == 0
    loss = float(np.asarray(engine.train_batch(batch)))
    assert np.isfinite(loss)
    with pytest.raises(KeyError, match="unknown optimizer state key"):
        ds.safe_get_full_optimizer_state(engine, "embed/tokens", "bogus")


def test_grad_accessor_on_trio_path():
    model = get_model_config("gpt2-tiny")
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config, seed=6)
    rng = np.random.default_rng(1)
    ids = rng.integers(0, model.vocab_size, size=(8, 33), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    assert ds.safe_get_full_grad(engine, "embed/tokens") is None
    loss = engine.forward(batch)
    engine.backward(loss)
    g = ds.safe_get_full_grad(engine, "embed/tokens")
    assert g is not None and np.abs(g).sum() > 0
    topology._GLOBAL_TOPOLOGY = None


def test_local_shard_accessors(zero3_engine):
    engine, _ = zero3_engine
    from deepspeed_tpu.utils.tensor_fragment import _find_leaf

    leaf = _find_leaf(engine.params, "layers/mlp/wi")
    assert any(ax is not None for ax in leaf.sharding.spec), \
        "fixture should shard this leaf (threshold 0)"
    full = ds.safe_get_full_fp32_param(engine, "layers/mlp/wi")
    local = ds.safe_get_local_fp32_param(engine, "layers/mlp/wi")
    # single process holding all 8 distinct shards: stacked = full size
    assert local.size == full.size
    assert local.shape[0] == 8  # one stacked entry per device shard
    m_local = ds.safe_get_local_optimizer_state(engine, "layers/mlp/wi",
                                                "exp_avg")
    assert m_local.size == full.size


def test_replicated_leaf_local_is_single_copy(zero3_engine):
    engine, _ = zero3_engine
    # final_norm/scale is 1-D tiny; under threshold 0 it may shard — use
    # a replicated leaf by construction: fetch full and compare shapes
    from deepspeed_tpu.utils.tensor_fragment import _find_leaf, _local_shard

    leaf = _find_leaf(engine.params, "final_norm/scale")
    local = _local_shard(leaf)
    if not any(ax is not None for ax in leaf.sharding.spec):
        # replicated: ONE copy, not one per device
        assert local.shape == leaf.shape


def test_fp16_grad_accessor_unscales():
    """Under fp16 dynamic loss scaling the buffer holds SCALED grads;
    the accessor must divide the scale out."""
    model = get_model_config("gpt2-tiny")
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "fp16": {"enabled": True, "initial_scale_power": 8},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config, seed=8)
    rng = np.random.default_rng(2)
    ids = rng.integers(0, model.vocab_size, size=(8, 33), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    loss = engine.forward(batch)
    engine.backward(loss)
    scale = float(np.asarray(engine.loss_scale_state["scale"]))
    assert scale == 2.0 ** 8
    g = ds.safe_get_full_grad(engine, "embed/tokens")
    raw = np.asarray(engine._grad_buffer["embed"]["tokens"], np.float32)
    np.testing.assert_allclose(g, raw / scale, rtol=1e-6)
    # unscaled grads of a ~6.2-loss CE on a tiny model are O(1e-3..1),
    # not O(scale)
    assert np.abs(g).max() < 50.0
    topology._GLOBAL_TOPOLOGY = None


def test_param_stream_state_routing():
    """The split {'stream','resident'} optimizer state of the param-
    streaming engine routes layer paths to the stream subtree (and
    set only rewrites that subtree)."""
    model = get_model_config("gpt2-tiny")
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3,
                              "offload_param": {"device": "cpu"},
                              "offload_optimizer": {"device": "cpu"}},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config, seed=9)
    rng = np.random.default_rng(3)
    ids = rng.integers(0, model.vocab_size, size=(8, 33), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    engine.train_batch(batch)
    m_layer = ds.safe_get_full_optimizer_state(engine, "layers/attn/wq",
                                               "exp_avg")
    assert np.abs(m_layer).sum() > 0
    m_res = ds.safe_get_full_optimizer_state(engine, "embed/tokens",
                                             "exp_avg")
    ds.safe_set_full_optimizer_state(engine, "layers/attn/wq",
                                     np.zeros_like(m_layer), "exp_avg")
    assert np.abs(ds.safe_get_full_optimizer_state(
        engine, "layers/attn/wq", "exp_avg")).sum() == 0
    # resident subtree untouched by the stream write
    np.testing.assert_array_equal(
        ds.safe_get_full_optimizer_state(engine, "embed/tokens", "exp_avg"),
        m_res)
    # the engine still steps after the surgical write
    loss = float(np.asarray(engine.train_batch(batch)))
    assert np.isfinite(loss)
    topology._GLOBAL_TOPOLOGY = None
