"""Dropout (GPT-2/BERT-class training): engine-threaded PRNG keys, off at
eval/serve, bitwise-consistent under rematerialisation — the property the
reference's CudaRNGStatesTracker (activation_checkpointing/
checkpointing.py:124) exists to enforce."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models import get_model_config
from deepspeed_tpu.models import transformer as tf


def _batch(cfg, b=2, s=32, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, cfg.vocab_size, size=(b, s + 1), dtype=np.int32)
    return {"input_ids": jnp.asarray(ids[:, :-1]),
            "labels": jnp.asarray(ids[:, 1:])}


def test_dropout_changes_loss_and_is_keyed():
    cfg = get_model_config("gpt2-tiny", dropout=0.2)
    params = tf.init_params(cfg, jax.random.PRNGKey(0))
    batch = _batch(cfg)
    base = float(np.asarray(tf.loss_fn(params, batch, cfg)))
    k1 = dict(batch, dropout_key=jax.random.PRNGKey(1))
    k2 = dict(batch, dropout_key=jax.random.PRNGKey(2))
    l1 = float(np.asarray(tf.loss_fn(params, k1, cfg)))
    l1b = float(np.asarray(tf.loss_fn(params, k1, cfg)))
    l2 = float(np.asarray(tf.loss_fn(params, k2, cfg)))
    assert np.isfinite([base, l1, l2]).all()
    assert l1 == l1b                       # same key → deterministic
    assert l1 != base and l1 != l2         # dropout live, key-dependent


def test_no_key_means_identity_even_with_rate_set():
    cfg = get_model_config("gpt2-tiny", dropout=0.5)
    params = tf.init_params(cfg, jax.random.PRNGKey(0))
    batch = _batch(cfg)
    cfg0 = cfg.replace(dropout=0.0)
    np.testing.assert_array_equal(
        np.asarray(tf.forward(params, batch["input_ids"], cfg)),
        np.asarray(tf.forward(params, batch["input_ids"], cfg0)))


def test_dropout_grads_consistent_under_remat():
    """Explicit keys make the remat recompute replay identical masks: the
    grads under full rematerialisation equal the no-remat grads."""
    cfg = get_model_config("gpt2-tiny", dropout=0.3, attn_impl="xla")
    params = tf.init_params(cfg, jax.random.PRNGKey(0))
    batch = dict(_batch(cfg), dropout_key=jax.random.PRNGKey(5))

    g_remat = jax.grad(lambda p: tf.loss_fn(
        p, batch, cfg.replace(remat_policy="nothing_saveable")))(params)
    g_plain = jax.grad(lambda p: tf.loss_fn(
        p, batch, cfg.replace(remat_policy="none")))(params)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        g_remat, g_plain)


def test_engine_trains_with_dropout_and_eval_is_deterministic():
    import deepspeed_tpu as ds
    from deepspeed_tpu.parallel import topology

    model = get_model_config("gpt2-tiny", dropout=0.1)
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config, seed=3)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(32, 33), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    losses = [float(np.asarray(engine.train_batch(batch)))
              for _ in range(5)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
    # training must not leak a dropout_key into the caller's batch dict
    assert "dropout_key" not in batch
    # eval through the model surface with the trained params: no key →
    # dropout off → bitwise deterministic
    e1 = np.asarray(tf.forward(engine.params, batch["input_ids"][:4],
                               engine.model_config))
    e2 = np.asarray(tf.forward(engine.params, batch["input_ids"][:4],
                               engine.model_config))
    np.testing.assert_array_equal(e1, e2)
    topology._GLOBAL_TOPOLOGY = None


def test_dropout_trio_forward_applies_key():
    """The forward/backward/step trio threads a per-micro key too (the
    r04 review caught it silently skipping dropout)."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.parallel import topology

    losses = {}
    for label, rate in (("drop", 0.5), ("nodrop", 0.0)):
        model = get_model_config("gpt2-tiny", dropout=rate)
        config = {
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "steps_per_print": 10_000,
        }
        engine, _, _, _ = ds.initialize(model=model, config=config, seed=3)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, model.vocab_size, size=(8, 33), dtype=np.int32)
        batch = {"input_ids": ids[:, :-1],
                 "labels": ids[:, 1:].astype(np.int32)}
        losses[label] = float(np.asarray(engine.forward(batch)))
        topology._GLOBAL_TOPOLOGY = None
    # same params/seed/data: a live 0.5 dropout must move the loss
    assert losses["drop"] != losses["nodrop"]


def test_dropout_trains_under_pipeline_parallelism():
    """Dropout + PP is an ordinary reference combination (every GPT-2
    pipeline run, ref runtime/pipe/engine.py:337): the per-microbatch key
    rides the 1F1B extras, so training works and the rate moves the loss;
    eval (no key) stays deterministic."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.parallel import topology

    losses = {}
    for label, rate in (("drop", 0.5), ("nodrop", 0.0)):
        model = get_model_config("gpt2-tiny", dropout=rate)
        config = {
            "train_micro_batch_size_per_gpu": 4,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "mesh": {"pipe": 2, "data": 4},
            "steps_per_print": 10_000,
        }
        engine, _, _, _ = ds.initialize(model=model, config=config, seed=3)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, model.vocab_size, size=(16, 33),
                           dtype=np.int32)
        batch = {"input_ids": ids[:, :-1],
                 "labels": ids[:, 1:].astype(np.int32)}
        steps = [float(np.asarray(engine.train_batch(batch)))
                 for _ in range(3)]
        assert np.isfinite(steps).all(), (label, steps)
        assert steps[-1] < steps[0], (label, steps)
        losses[label] = steps[0]
        if rate > 0:
            # eval path (no key): dropout off → deterministic.  PP forward
            # runs under jit (partial-manual shard_map needs it).
            fwd = jax.jit(lambda p, i: tf.forward(p, i, engine.model_config))
            e1 = np.asarray(fwd(engine.params, batch["input_ids"][:4]))
            e2 = np.asarray(fwd(engine.params, batch["input_ids"][:4]))
            np.testing.assert_array_equal(e1, e2)
        topology._GLOBAL_TOPOLOGY = None
    # same params/seed/data: a live 0.5 dropout must move the first loss
    assert losses["drop"] != losses["nodrop"]


def test_dropout_pipeline_grads_match_masks_deterministically():
    """Same key → identical 1F1B loss twice (mask replay is stable across
    the schedule's forward and backward ticks)."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.parallel import topology

    model = get_model_config("gpt2-tiny", dropout=0.3)
    config = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "SGD", "params": {"lr": 0.0}},
        "mesh": {"pipe": 2, "data": 4},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config, seed=11)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(16, 33), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    # lr=0 → params frozen; global_steps advances, so pin it to replay the
    # exact same step key
    l1 = float(np.asarray(engine.train_batch(batch)))
    engine.global_steps = 0
    l2 = float(np.asarray(engine.train_batch(batch)))
    assert l1 == l2
    topology._GLOBAL_TOPOLOGY = None


def test_dropout_pipeline_primal_matches_differentiated_loss():
    """The loss-only (custom_vjp primal, GPipe) path and the 1F1B
    differentiated forward draw identical dropout masks — same per-
    microbatch key slicing in both schedules."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.parallel import topology

    model = get_model_config("gpt2-tiny", dropout=0.3)
    config = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "mesh": {"pipe": 2, "data": 4},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config, seed=5)
    cfg = engine.model_config
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(16, 33), dtype=np.int32)
    batch = {"input_ids": jnp.asarray(ids[:, :-1]),
             "labels": jnp.asarray(ids[:, 1:].astype(np.int32)),
             "dropout_key": jax.random.PRNGKey(42)}
    loss_only = float(np.asarray(jax.jit(
        lambda p: tf.loss_fn(p, batch, cfg))(engine.params)))
    loss_diff, _ = jax.jit(jax.value_and_grad(
        lambda p: tf.loss_fn(p, batch, cfg)))(engine.params)
    np.testing.assert_allclose(loss_only, float(np.asarray(loss_diff)),
                               rtol=1e-5, atol=1e-6)
    topology._GLOBAL_TOPOLOGY = None


def test_rng_tracker_parity_surface():
    """Megatron-style named RNG streams (ref CudaRNGStatesTracker)."""
    from deepspeed_tpu.checkpointing import (get_cuda_rng_tracker,
                                             model_parallel_rng_seed)

    model_parallel_rng_seed(123, tp_rank=0)
    t = get_cuda_rng_tracker()
    k1 = t.fork()
    k2 = t.fork()
    assert not np.array_equal(np.asarray(k1), np.asarray(k2))  # advances
    # same seed replays the same stream
    model_parallel_rng_seed(123, tp_rank=0)
    np.testing.assert_array_equal(np.asarray(t.fork()), np.asarray(k1))
    # different tp rank → different model-parallel stream, same default
    model_parallel_rng_seed(123, tp_rank=1)
    assert not np.array_equal(np.asarray(t.fork()), np.asarray(k1))
    st = t.get_states()
    t.fork("default")
    t.set_states(st)  # restore round-trip
    # reference context-manager idiom ports unchanged
    with t.fork() as key:
        assert np.asarray(key).shape == (2,)
    import pytest as _pytest

    with _pytest.raises(KeyError):
        t.fork("nope")
