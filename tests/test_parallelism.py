"""Parallelism strategy tests: Ulysses SP, pipeline, TP — each is validated
by numeric parity against a pure-DP run of the identical model (parallelism
must be a layout change, not a numerics change)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import get_model_config
from deepspeed_tpu.parallel.topology import MeshTopology, set_topology
from tests.conftest import make_lm_batch


def _cfg(mesh, **over):
    cfg = {
        "train_batch_size": 8,
        "train_micro_batch_size_per_gpu": 8 // (mesh.get("data", 1) * mesh.get("expert", 1)),
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "gradient_clipping": 1.0,
        "steps_per_print": 1000,
        "mesh": mesh,
    }
    cfg.update(over)
    return cfg


def _losses(model, cfg, batches, seed=7):
    engine, _, _, _ = ds.initialize(model=model, config=cfg, seed=seed)
    out = [float(np.asarray(engine.train_batch(b))) for b in batches]
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None
    return out


def _batches(model, n=4, batch=8, seq=32):
    rng = np.random.default_rng(0)
    b = make_lm_batch(rng, batch, seq, model.vocab_size)
    return [b] * n


def test_ulysses_matches_dp():
    model = get_model_config("llama-tiny")
    batches = _batches(model)
    ref = _losses(model, _cfg({"data": 8}), batches)
    sp = _losses(model, _cfg({"data": 4, "seq": 2}), batches)
    assert sp[-1] < sp[0]
    np.testing.assert_allclose(ref, sp, rtol=2e-4, atol=2e-4)


def test_ulysses_emits_all_to_all():
    """The seq↔head resharding must compile to all-to-all (Ulysses), not
    plain all-gathers of the whole sequence."""
    from deepspeed_tpu.models import transformer as tf_model

    model = get_model_config("llama-tiny", dtype=jnp.float32)
    topo = MeshTopology({"data": 2, "seq": 4})
    set_topology(topo)
    params = tf_model.init_params(model, jax.random.PRNGKey(0))
    ids = jnp.zeros((2, 64), jnp.int32)

    lowered = jax.jit(lambda p, i: tf_model.forward(p, i, model)).lower(params, ids)
    hlo = lowered.compile().as_text()
    assert "all-to-all" in hlo, "Ulysses resharding did not lower to all-to-all"


def test_pipeline_matches_dp():
    model = get_model_config("gpt2-tiny")  # 2 layers → 2 stages
    batches = _batches(model)
    ref = _losses(model, _cfg({"data": 8}), batches)
    pp = _losses(model, _cfg({"pipe": 2, "data": 4}), batches)
    assert pp[-1] < pp[0]
    np.testing.assert_allclose(ref, pp, rtol=5e-4, atol=5e-4)


def test_pipeline_with_zero1():
    model = get_model_config("gpt2-tiny")
    batches = _batches(model)
    losses = _losses(model, _cfg({"pipe": 2, "data": 2, "tensor": 2},
                                 zero_optimization={"stage": 1}), batches)
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]


@pytest.mark.xfail(
    reason="jax 0.4.37: transposing the GPipe shard_map with a NESTED "
    "expert shard_map inside layer_fn trips shard_map._SpecError on the "
    "replicated aux out-spec even with check_rep=False "
    "(parallel/pipeline.py spmd_pipeline; the 1F1B custom-VJP path and "
    "moe-without-pipe both differentiate fine — see "
    "test_pipeline_moe_engine_train). Revisit at the next jax bump.",
    strict=False)
def test_pipeline_moe_forward_parity():
    """MoE + pipeline (ref groups.py:384 EP+PP composition): the pipelined
    forward must match the unpartitioned model per token (generous capacity
    so no tokens drop; fp32 so the comparison is tight)."""
    from deepspeed_tpu.models import init_params
    from deepspeed_tpu.models import transformer as tf_model
    from deepspeed_tpu.models.registry import TransformerConfig

    cfg = TransformerConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=4,
        num_heads=2, num_kv_heads=2, max_seq_len=32, arch="llama",
        norm="rmsnorm", activation="swiglu", use_rope=True,
        tie_embeddings=False, num_experts=4, top_k=2, moe_layer_freq=2,
        capacity_factor=8.0, dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 128, (8, 16)),
                      jnp.int32)
    set_topology(None)
    ref_logits, _ = tf_model.forward(params, ids, cfg)

    topo = MeshTopology({"pipe": 2, "data": 2, "expert": 2})
    set_topology(topo)
    try:
        out, aux = jax.jit(lambda p, i: tf_model.forward(p, i, cfg))(params, ids)
        rel = float(jnp.linalg.norm((out - ref_logits).ravel())
                    / jnp.linalg.norm(ref_logits.ravel()))
        assert rel < 1e-5, rel
        assert np.isfinite(float(aux))

        # backward through pipe + nested expert shard_map compiles + finite
        def loss(p):
            logits, aux = tf_model.forward(p, ids, cfg)
            return jnp.mean(logits ** 2) + 0.01 * aux

        g = jax.jit(jax.grad(loss))(params)
        assert all(np.all(np.isfinite(np.asarray(l)))
                   for l in jax.tree_util.tree_leaves(g))
    finally:
        set_topology(None)


def test_pipeline_moe_engine_train():
    """MoE model trains under {pipe, data, expert} through the engine."""
    model = get_model_config("mixtral-tiny", num_layers=2)
    batches = _batches(model)
    losses = _losses(model, _cfg({"pipe": 2, "data": 2, "expert": 2}),
                     batches)
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]


def test_distributed_attention_wrapper():
    """Explicit shard_map DistributedAttention == local attention result."""
    from deepspeed_tpu.sequence.layer import DistributedAttention
    from deepspeed_tpu.ops.flash_attention import _xla_attention

    topo = MeshTopology({"data": 2, "seq": 4})
    set_topology(topo)
    import math

    def local_attn(q, k, v):
        return _xla_attention(q, k, v, True, 1.0 / math.sqrt(q.shape[-1]))

    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (2, 32, 8, 16))
    k = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 8, 16))
    v = jax.random.normal(jax.random.PRNGKey(2), (2, 32, 8, 16))
    dist_attn = DistributedAttention(local_attn, topo)
    out = dist_attn(q, k, v)
    expected = local_attn(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-5)


def test_ulysses_composes_with_tensor_parallel():
    """tp×sp composition: heads shard jointly over (tensor, seq)
    (sequence/layer.py ulysses_qkv_constraint) — must reproduce the pure-DP
    trajectory, and must not trip the SPMD partitioner."""
    model = get_model_config("llama-tiny")  # 4 heads = tp2 * sp2
    batches = _batches(model)
    dp = _losses(model, _cfg({"data": 8}), batches)
    mix = _losses(model, _cfg({"data": 2, "tensor": 2, "seq": 2}), batches)
    np.testing.assert_allclose(dp, mix, rtol=2e-4, atol=2e-4)
    assert mix[-1] < mix[0]
