"""MoE serving (ref VERDICT r3 Missing #6): InferenceEngineV2 with
expert parallelism — EP all_to_all inside the ragged step, token parity
with the single-group path, and the mixtral/qwen2moe model zoo entries.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models import get_model_config
from deepspeed_tpu.models import transformer as tf_model


def _reset_topo():
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None


@pytest.mark.parametrize("name", ["mixtral-tiny", "qwen2moe-tiny"])
def test_v2_ep_serving_matches_single_group(name):
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2

    # ample capacity: with token drops, per-shard (EP) and global capacity
    # budgets legitimately differ — parity is exact only dropless
    model = get_model_config(name, capacity_factor=16.0)
    eng1 = InferenceEngineV2(model, {"dtype": "float32"})
    params = eng1.params
    rng = np.random.default_rng(5)
    prompts = [list(map(int, rng.integers(1, model.vocab_size, size=(6,))))
               for _ in range(2)]
    out1 = eng1.generate(prompts, max_new_tokens=6)
    _reset_topo()

    eng2 = InferenceEngineV2(model, {"dtype": "float32",
                                     "expert_parallel": {"ep_size": 2}},
                             model_params=params)
    assert eng2.topology.ep_size == 2
    out2 = eng2.generate(prompts, max_new_tokens=6)
    assert out1 == out2, (out1, out2)
    _reset_topo()


def test_ep_ragged_step_compiles_all_to_all():
    """The expert-parallel ragged decode must carry the explicit expert
    all_to_all dispatch (ref moe/sharded_moe.py:96 _AllToAll)."""
    from deepspeed_tpu.inference.v2.model import ragged_forward
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology

    model = get_model_config("mixtral-tiny", dtype=jnp.float32)
    topo = MeshTopology({"expert": 2})
    set_topology(topo)
    try:
        params = jax.jit(lambda k: tf_model.init_params(model, k))(
            jax.random.PRNGKey(0))
        bs, t, nb = 16, 8, 4
        cache = jnp.zeros((model.num_layers, model.kv_heads, nb * bs,
                           model.dim_per_head), jnp.float32)
        tables = jnp.arange(nb, dtype=jnp.int32).reshape(2, 2)
        args = (params, cache, cache + 0, jnp.zeros((t,), jnp.int32),
                jnp.zeros((t,), jnp.int32),
                jnp.arange(t, dtype=jnp.int32) % 4,
                jnp.arange(t, dtype=jnp.int32),
                tables, jnp.full((2,), 4, jnp.int32),
                jnp.zeros((2,), jnp.int32))
        import functools

        hlo = jax.jit(functools.partial(ragged_forward, cfg=model,
                                        block_size=bs)).lower(
            *args).compile().as_text()
        assert "all-to-all" in hlo, "EP dispatch missing from ragged step"
    finally:
        set_topology(None)
        _reset_topo()


def test_shared_expert_moe_trains():
    """qwen2moe-style shared-expert model trains end-to-end."""
    import deepspeed_tpu as ds

    model = get_model_config("qwen2moe-tiny")
    cfg = {"train_micro_batch_size_per_gpu": 4,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
           "mesh": {"data": 2, "expert": 2}}
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(16, 33), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    losses = [float(np.asarray(engine.train_batch(batch)))
              for _ in range(5)]
    assert losses[-1] < losses[0] - 0.5, losses
    _reset_topo()


def test_universal_reshard_moe_shared_expert(tmp_path):
    """UCP elasticity for the MoE tree shapes this round added (no dense
    mlp on freq-1 stacks, shared expert + gate): save under data:4 x
    expert:2, reload universally under data:2 x expert:4 with identical
    continuation numerics."""
    import os

    import deepspeed_tpu as ds
    from deepspeed_tpu.checkpoint.universal import (ds_to_universal,
                                                    load_universal)
    from tests.conftest import make_lm_batch

    # dropless capacity: per-group capacity budgets differ across mesh
    # shapes, so continuation parity is only exact without token drops
    model = get_model_config("qwen2moe-tiny", capacity_factor=16.0)

    def mk(mesh, seed):
        _reset_topo()
        cfg = {"train_micro_batch_size_per_gpu": 2,
               "gradient_accumulation_steps": 1,
               "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
               "zero_optimization": {"stage": 2},
               "steps_per_print": 1000, "mesh": mesh}
        engine, _, _, _ = ds.initialize(model=model, config=cfg, seed=seed)
        return engine

    rng = np.random.default_rng(4)
    batch = make_lm_batch(rng, 16, 16, model.vocab_size)
    e1 = mk({"data": 4, "expert": 2}, seed=3)
    for _ in range(2):
        e1.train_batch(batch)
    e1.save_checkpoint(str(tmp_path), tag="m")
    udir = ds_to_universal(str(tmp_path), tag="m")
    assert os.path.exists(os.path.join(udir, "meta.json"))

    e2 = mk({"data": 2, "expert": 4}, seed=77)
    load_universal(e2, udir)
    assert e2.global_steps == 2
    a = [float(np.asarray(e1.train_batch(batch))) for _ in range(2)]
    b = [float(np.asarray(e2.train_batch(batch))) for _ in range(2)]
    # fp32 reduction order differs across expert-group sizes (the EP
    # all_to_all sums in a different order); a real restore bug would be
    # O(1), not O(1e-3)
    np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-3)
    _reset_topo()
