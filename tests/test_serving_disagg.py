"""Disaggregated serving: prefill/decode tiers, KV handoff, speculation.

Correctness oracle, same as the router tests: everything the disagg
path produces under greedy sampling must be BIT-IDENTICAL to a single
engine's one-shot ``generate()`` with the same weights — across the
prefill→decode handoff (zero-copy and transfer paths), speculative
decoding (any accept pattern), mid-handoff replica kills, and
fail-over.  The refcount tests pin that handed-off pages release
cleanly on finish/cancel/fail-over — nothing leaks a pool block.
"""

import time

import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import build_engine
from deepspeed_tpu.inference.v2.ragged import BlockedAllocator
from deepspeed_tpu.models import get_model_config
from deepspeed_tpu.serving import (AdmissionController, DisaggRouter,
                                   PrefixCache, PrefixCacheConfig,
                                   ReplicaSet, RequestCancelled, Router,
                                   SamplingParams)

ENG_CFG = {"dtype": "float32",
           "memory_config": {"num_blocks": 64, "block_size": 4},
           "max_context": 64}

DISAGG = {"enabled": True, "prefill_replicas": 1, "decode_replicas": 1,
          "speculative": {"enabled": True, "draft_model": "llama-tiny",
                          "spec_k": 3}}


def _model(layers=1):
    return get_model_config("llama-tiny", num_layers=layers)


def _prompts(model, sizes, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, model.vocab_size, size=n).tolist()
            for n in sizes]


def _pool_whole(engine) -> bool:
    """Every page back on the free list (block 0 excluded)."""
    return engine.free_blocks == engine.cfg.num_blocks - 1


# ---------------------------------------------------------------------------
# engine-level: verify-k step + KV chain export/import
# ---------------------------------------------------------------------------

def test_verify_step_any_accept_pattern_is_greedy_bit_identical():
    model = _model()
    ref = build_engine(model, ENG_CFG, seed=0)
    prompt = _prompts(model, [9])[0]
    want = ref.generate([prompt], max_new_tokens=12)[0]

    eng = build_engine(model, ENG_CFG, seed=0)
    eng.admit(0, prompt)
    out = eng.step(temperature=0.0)
    emitted = [out[0]]
    eng.extend(0, out[0])
    # perfect proposals: all accepted + bonus
    acc = eng.verify_step({0: want[1:4]})[0]
    assert acc == want[1:5]
    emitted += acc
    # garbage proposals: zero accepted, bonus only — and the KV rows the
    # rejected tokens wrote must not poison later decoding
    acc = eng.verify_step({0: [0, 0]})[0]
    assert acc == [want[5]]
    emitted += acc
    # partially-correct proposals (first right, second wrong)
    acc = eng.verify_step({0: [want[6], 0, 0]})[0]
    assert acc == want[6:8]
    emitted += acc
    # empty proposal = plain greedy step through the verify surface
    acc = eng.verify_step({0: []})[0]
    assert acc == [want[8]]
    emitted += acc
    while len(emitted) < 12:
        o = eng.step(temperature=0.0)
        emitted.append(o[0])
        eng.extend(0, o[0])
    assert emitted == want


def test_verify_step_rejects_mid_prefill_sequence():
    model = _model()
    eng = build_engine(model, ENG_CFG, seed=0)
    eng.admit(0, _prompts(model, [9])[0])
    # no step has run: the prompt is still uncached (> 1 pending)
    with pytest.raises(ValueError, match="uncached"):
        eng.verify_step({0: [1, 2]})


def test_verify_step_bad_entry_leaves_batch_untouched():
    """All-or-nothing validation: a bad sequence in the batch must not
    leave EARLIER sequences carrying unverified draft tokens."""
    model = _model()
    eng = build_engine(model, ENG_CFG, seed=0)
    pa, pb = _prompts(model, [9, 7], seed=9)
    eng.admit(0, pa)
    t0 = eng.step(temperature=0.0)[0]
    eng.extend(0, t0)
    eng.admit(1, pb)                 # mid-prefill: uncached > 1
    before = list(eng.state_manager.get(0).tokens)
    with pytest.raises(ValueError, match="uncached"):
        eng.verify_step({0: [1, 2], 1: [3]})
    assert eng.state_manager.get(0).tokens == before


def test_spec_degrades_to_plain_step_when_actives_exceed_budget():
    """An active set wider than the ragged token budget cannot verify
    (even k=0 needs one row per sequence) — the serve loop must fall
    back to plain budget-split steps, bit-identically, instead of
    crashing the loop with an over-budget verify."""
    from deepspeed_tpu.serving import InferenceServer, SpeculativeDecoder

    model = _model()
    cfg = dict(ENG_CFG,
               state_manager={"max_tracked_sequences": 8,
                              "max_ragged_batch_size": 4})
    ref = build_engine(model, cfg, seed=0)
    prompts = _prompts(model, [5, 6, 7, 5, 6, 7], seed=10)
    want = [ref.generate([p], max_new_tokens=4)[0] for p in prompts]

    eng = build_engine(model, cfg, seed=0)
    draft = build_engine(model, cfg, seed=0)
    srv = InferenceServer(eng, spec_decoder=SpeculativeDecoder(
        eng, draft, spec_k=3)).start()
    try:
        streams = [srv.submit(p, SamplingParams(max_new_tokens=4,
                                                speculative=True))
                   for p in prompts]
        assert [s.result(timeout=300) for s in streams] == want
    finally:
        srv.stop()


def test_export_import_chain_decode_parity_and_release():
    model = _model()
    prompt = _prompts(model, [10], seed=2)[0]
    ref = build_engine(model, ENG_CFG, seed=0)
    want = ref.generate([prompt], max_new_tokens=8)[0]

    a = build_engine(model, ENG_CFG, seed=0)
    b = build_engine(model, ENG_CFG, seed=0)
    a.admit(7, prompt)
    t0 = a.step(temperature=0.0)[7]
    payload = a.export_kv_chain(7)
    a.extend(7, t0)
    a.flush(7)
    assert _pool_whole(a)
    assert payload["tokens"] == prompt[:8]      # full blocks only
    blocks, n_tok, moved = b.import_kv_chain(payload)
    assert n_tok == 8 and moved == payload["nbytes"] and len(blocks) == 2
    b.admit(9, prompt + [t0], cached_blocks=blocks, num_cached=n_tok)
    got = [t0]
    while len(got) < 8:
        o = b.step(temperature=0.0)
        if 9 in o:
            got.append(o[9])
            b.extend(9, o[9])
    assert got == want
    b.flush(9)
    assert _pool_whole(b)       # imported pages released with the seq


def test_import_rejects_geometry_mismatch():
    model = _model()
    prompt = _prompts(model, [10])[0]
    a = build_engine(model, ENG_CFG, seed=0)
    other = dict(ENG_CFG, memory_config={"num_blocks": 64,
                                         "block_size": 8})
    b = build_engine(model, other, seed=0)
    a.admit(0, prompt)
    a.step(temperature=0.0)
    payload = a.export_kv_chain(0)
    with pytest.raises(ValueError, match="geometry"):
        b.import_kv_chain(payload)
    assert _pool_whole(b)       # the refused import allocated nothing


# ---------------------------------------------------------------------------
# evictable headroom (the router/admission satellite)
# ---------------------------------------------------------------------------

def test_evictable_headroom_counts_cache_owned_leaves():
    al = BlockedAllocator(16)
    pc = PrefixCache(PrefixCacheConfig({"enabled": True}), al,
                     block_size=4)
    blocks = al.allocate(3)
    pc.insert(list(range(12)), blocks)   # 3 full cache-owned blocks
    al.free(blocks)                      # donor flushes: cache sole owner

    class _Eng:
        free_blocks = al.free_blocks
    assert al.free_blocks == 12
    # the whole chain is solely-cache-owned: eviction reaches all 3
    # (leaf-first across passes), so all 3 are headroom-in-waiting
    assert pc.evictable_count(max_age_s=0) == 3
    assert AdmissionController.evictable_headroom(_Eng, pc) == 15
    assert AdmissionController.evictable_headroom(_Eng, None) == 12
    # a live sequence adopting the first 2 blocks pins them (and the
    # interior entries above), but the unshared leaf below stays
    # reachable only through the cache — it alone remains evictable
    al.acquire(blocks[:2])
    assert pc.evictable_count(max_age_s=0) == 1
    al.free(blocks[:2])
    assert pc.evictable_count(max_age_s=0) == 3


def test_cache_warm_replica_still_wins_dispatch():
    """Regression for the headroom satellite: a replica whose pool is
    full of solely-cache-owned (evictable) pages must score like a cold
    one — under the old free-list-only score the router would spill
    AWAY from the warm cache."""
    model = _model()
    rs = ReplicaSet.build(model, 2, ENG_CFG,
                          {"prefix_cache": {"enabled": True}}, seed=0)
    router = Router(rs).start()
    try:
        # warm r1 through a sticky session: a long shared prompt leaves
        # its full blocks cache-owned after the request finishes
        warm = _prompts(model, [33], seed=5)[0]
        router.submit(warm, SamplingParams(max_new_tokens=2),
                      session="warm").result(timeout=120)
        deadline = time.monotonic() + 10
        while (rs[1].server.prefix_cache is None
               or rs[1].engine.free_blocks == rs[0].engine.free_blocks) \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        warm_rep = next(r for r in rs
                        if r.server.prefix_cache.cached_blocks > 0)
        cold_rep = next(r for r in rs if r is not warm_rep)
        # raw free list differs...
        assert warm_rep.engine.free_blocks < cold_rep.engine.free_blocks
        # ...but evictable-aware headroom (and hence the score) does not
        assert warm_rep.dispatch_headroom == cold_rep.dispatch_headroom
        assert router._score(warm_rep) == router._score(cold_rep)
        # one queued request on the cold replica and the warm one WINS
        with router._lock:
            router._inflight[cold_rep.index] = \
                router._inflight.get(cold_rep.index, 0) + 1
        assert router._choose() is warm_rep
    finally:
        router.stop()


# ---------------------------------------------------------------------------
# disagg end-to-end
# ---------------------------------------------------------------------------

def test_disagg_e2e_bit_identical_with_zero_copy_second_wave():
    model = _model(layers=2)
    prompts = _prompts(model, [9, 13, 6], seed=3)
    ref = build_engine(model, ENG_CFG, seed=0)
    want = [ref.generate([p], max_new_tokens=8)[0] for p in prompts]

    rs = ReplicaSet.build(model, 2, ENG_CFG,
                          {"prefix_cache": {"enabled": True}}, seed=0,
                          disagg=DISAGG)
    assert [r.tier for r in rs] == ["prefill", "decode"]
    router = DisaggRouter(rs).start()
    try:
        streams = [router.submit(p, SamplingParams(max_new_tokens=8,
                                                   speculative=True))
                   for p in prompts]
        outs = [s.result(timeout=300) for s in streams]
        assert outs == want
        # every request paid one handoff; the first wave moved bytes
        assert all(s.handoff_ms is not None for s in streams)
        assert all(s.handoff_bytes > 0 for s in streams)
        # second wave: the decode replica's prefix cache holds the
        # chains → adoption is a pure ref acquire, zero bytes move
        streams2 = [router.submit(p, SamplingParams(max_new_tokens=8,
                                                    speculative=True))
                    for p in prompts]
        assert [s.result(timeout=300) for s in streams2] == want
        assert all(s.handoff_bytes == 0 for s in streams2)
        snap = router.snapshot()
        assert snap["handoffs"] == 6
        dec = rs[1].server.metrics.snapshot()
        assert dec["handoffs_in"] == 6 and dec["spec_rounds"] > 0
        pre = rs[0].server.metrics.snapshot()
        assert pre["handoffs_out"] == 6
    finally:
        router.stop()
    # refcounts: stop() cleared the caches, every pool returns whole
    for r in rs:
        assert _pool_whole(r.engine), r.name


def test_disagg_cancel_releases_adopted_chain():
    model = _model(layers=2)
    rs = ReplicaSet.build(model, 2, ENG_CFG,
                          {"prefix_cache": {"enabled": True}}, seed=0,
                          disagg=DISAGG)
    router = DisaggRouter(rs).start()
    try:
        prompt = _prompts(model, [11], seed=4)[0]
        # an impossible request fails at SUBMIT (the 1-token prefill leg
        # must not hide the per-sequence cap until mid-decode)
        with pytest.raises(ValueError, match="KV blocks"):
            router.submit(prompt, SamplingParams(max_new_tokens=64))
        s = router.submit(prompt, SamplingParams(max_new_tokens=48))
        for _tok in s:      # let the handoff land, then cancel mid-decode
            break
        s.cancel()
        with pytest.raises(RequestCancelled):
            s.result(timeout=120)
    finally:
        router.stop()
    for r in rs:
        assert _pool_whole(r.engine), r.name


def test_disagg_mid_handoff_kill_reruns_prefill_on_survivor():
    """Kill the decode replica with adopted chains in flight: the leg
    fails over and the survivor (the prefill replica, as the last-resort
    stand-in) re-runs prefill — output bit-identical, nothing leaks."""
    model = _model(layers=2)
    prompts = _prompts(model, [9, 12], seed=6)
    ref = build_engine(model, ENG_CFG, seed=0)
    want = [ref.generate([p], max_new_tokens=10)[0] for p in prompts]

    rs = ReplicaSet.build(model, 2, ENG_CFG,
                          {"prefix_cache": {"enabled": True}}, seed=0,
                          disagg=DISAGG)
    router = DisaggRouter(rs).start()
    try:
        streams = [router.submit(p, SamplingParams(max_new_tokens=10))
                   for p in prompts]
        # wait until decode legs stream, then kill the decode replica
        for s in streams:
            for _tok in s:
                break
        rs[1].kill()
        outs = [s.result(timeout=300) for s in streams]
        assert outs == want
        assert router.metrics.failovers >= 1
    finally:
        router.stop()
    assert _pool_whole(rs[0].engine)


def test_disagg_prefill_tier_down_falls_back():
    """A dead prefill tier must not strand requests: the decode replica
    serves the prefill leg (and its own decode leg) bit-identically."""
    model = _model(layers=2)
    prompt = _prompts(model, [10], seed=7)[0]
    ref = build_engine(model, ENG_CFG, seed=0)
    want = ref.generate([prompt], max_new_tokens=8)[0]

    rs = ReplicaSet.build(model, 2, ENG_CFG,
                          {"prefix_cache": {"enabled": True}}, seed=0,
                          disagg=DISAGG)
    router = DisaggRouter(rs).start()
    try:
        rs[0].kill()    # the whole prefill tier
        s = router.submit(prompt, SamplingParams(max_new_tokens=8,
                                                 speculative=True))
        assert s.result(timeout=300) == want
    finally:
        router.stop()


def test_spec_parity_across_prefix_hits_and_failover():
    """The acceptance test: greedy output with `speculative` enabled is
    bit-identical to greedy without it — across prefix-cache hits (the
    second submit adopts cached pages) and a forced mid-stream
    fail-over of the decode replica."""
    model = _model(layers=2)
    prompt = _prompts(model, [14], seed=8)[0]
    ref = build_engine(model, ENG_CFG, seed=0)
    want = ref.generate([prompt], max_new_tokens=16)[0]

    disagg = {"enabled": True, "prefill_replicas": 1,
              "decode_replicas": 2,
              "speculative": {"enabled": True,
                              "draft_model": "llama-tiny", "spec_k": 3}}
    rs = ReplicaSet.build(model, 3, ENG_CFG,
                          {"prefix_cache": {"enabled": True}}, seed=0,
                          disagg=disagg)
    router = DisaggRouter(rs).start()
    try:
        # plain greedy, then speculative on a prefix-cache-warm fleet
        assert router.submit(
            prompt, SamplingParams(max_new_tokens=16)).result(
                timeout=300) == want
        s = router.submit(prompt, SamplingParams(max_new_tokens=16,
                                                 speculative=True))
        assert s.result(timeout=300) == want
        assert s.handoff_bytes == 0     # cache hit: zero-copy adoption
        # forced mid-stream fail-over with speculation on
        s = router.submit(prompt, SamplingParams(max_new_tokens=16,
                                                 speculative=True))
        it = iter(s)
        next(it)            # first token: the prefill leg completed
        # wait for the decode leg to own the stream, then kill its host
        deadline = time.monotonic() + 30
        owner = None
        while owner is None and time.monotonic() < deadline:
            owner = next((r for r in rs
                          if r.tier == "decode" and r.server._active),
                         None)
            if owner is None:
                time.sleep(0.02)
        assert owner is not None, "decode leg never started"
        owner.kill()
        assert s.result(timeout=300) == want
    finally:
        router.stop()


# ---------------------------------------------------------------------------
# config hygiene
# ---------------------------------------------------------------------------

def test_disagg_config_roundtrip_and_rejection():
    from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                              DeepSpeedConfigError)

    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "serving": {"n_replicas": 3,
                    "disagg": {"enabled": True, "prefill_replicas": 1,
                               "decode_replicas": 2,
                               "speculative": {"enabled": True,
                                               "draft_model": "llama-tiny",
                                               "spec_k": 5}}},
    })
    d = cfg.serving.disagg_config()
    assert d["prefill_replicas"] == 1 and d["decode_replicas"] == 2
    assert d["speculative"]["spec_k"] == 5
    # the dict feeds ReplicaSet.build(disagg=...) directly
    from deepspeed_tpu.serving import DisaggConfig
    parsed = DisaggConfig(d)
    assert parsed.n_replicas == 3 and parsed.tier_of(0) == "prefill"
    assert parsed.tier_of(2) == "decode"
    for bad in ({"disagg": {"enabled": True, "prefill_replicas": 2,
                            "decode_replicas": 2}},       # 4 != n_replicas
                {"disagg": {"enabled": True, "prefill_replicas": 0,
                            "decode_replicas": 3}},
                {"disagg": {"speculative": {"spec_k": 0}}},
                {"disagg": {"speculative": {"enabled": True}}}):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                             "serving": {"n_replicas": 3, **bad}})


def test_sampling_params_speculative_field():
    import dataclasses

    p = SamplingParams(max_new_tokens=4, speculative=True)
    assert p.speculative and p.greedy
    p2 = dataclasses.replace(p, max_new_tokens=2)
    assert p2.speculative       # survives the router's leg re-shaping


def test_build_rejects_tiers_that_dont_fit_devices():
    model = _model()
    with pytest.raises(ValueError, match="prefill"):
        ReplicaSet.build(
            model, 9, ENG_CFG, seed=0,
            disagg={"enabled": True, "prefill_replicas": 4,
                    "decode_replicas": 5})
    with pytest.raises(ValueError, match="must sum"):
        ReplicaSet.build(
            model, 2, ENG_CFG, seed=0,
            disagg={"enabled": True, "prefill_replicas": 2,
                    "decode_replicas": 2})


# ---------------------------------------------------------------------------
# deadline propagation + kill-between-legs (chaos PR satellites)
# ---------------------------------------------------------------------------

def test_disagg_deadline_between_legs_typed_no_hang():
    """A deadline that dies mid-prefill (the engine step outlives it, so
    no queue sweep can catch it) surfaces as typed DeadlineExceeded from
    the between-legs guard — never a hang, never a decode admission that
    could only expire in queue — and the dropped un-adopted payload
    leaks nothing."""
    from deepspeed_tpu.resilience.chaos import FaultPlan, attach_chaos
    from deepspeed_tpu.serving import DeadlineExceeded

    model = _model(layers=2)
    prompt = _prompts(model, [10], seed=21)[0]
    rs = ReplicaSet.build(model, 2, ENG_CFG,
                          {"prefix_cache": {"enabled": True}}, seed=0,
                          disagg=DISAGG)
    router = DisaggRouter(rs).start()
    try:
        # the prefill engine step takes >= 400ms, every time
        attach_chaos(rs, FaultPlan([
            {"kind": "slow_replica", "target": "r0", "at": 0.0,
             "duration_s": 120.0, "point": "engine.step",
             "params": {"delay_ms": 400.0}}]))
        t0 = time.monotonic()
        s = router.submit(prompt, SamplingParams(max_new_tokens=8),
                          deadline_s=0.2)
        with pytest.raises(DeadlineExceeded):
            s.result(timeout=120)       # a hang would raise TimeoutError
        assert time.monotonic() - t0 < 60
    finally:
        router.stop()
    for r in rs:
        assert _pool_whole(r.engine), r.name


def test_disagg_deadline_mid_decode_releases_adopted_chain():
    """Expiry AFTER the handoff landed: the decode leg dies mid-decode
    with typed DeadlineExceeded and the adopted chain's pages all go
    back to the pool (same refcount bar as the cancel test)."""
    from deepspeed_tpu.resilience.chaos import FaultPlan, attach_chaos
    from deepspeed_tpu.serving import DeadlineExceeded

    model = _model(layers=2)
    prompts = _prompts(model, [9, 11], seed=22)
    rs = ReplicaSet.build(model, 2, ENG_CFG,
                          {"prefix_cache": {"enabled": True}}, seed=0,
                          disagg=DISAGG)
    router = DisaggRouter(rs).start()
    try:
        # warm both tiers so compile cost can't eat the deadline budget
        assert len(router.submit(
            prompts[0], SamplingParams(max_new_tokens=4)).result(
                timeout=300)) == 4
        # now every decode step costs >= 200ms: 48 tokens can't finish
        # inside a 2s budget, so expiry lands mid-decode
        attach_chaos(rs, FaultPlan([
            {"kind": "slow_replica", "target": "r1", "at": 0.0,
             "duration_s": 120.0, "point": "engine.step",
             "params": {"delay_ms": 200.0}}]))
        s = router.submit(prompts[1], SamplingParams(max_new_tokens=48),
                          deadline_s=2.0)
        got = []
        try:
            for tok in s:               # handoff landed: tokens flow...
                got.append(tok)
        except DeadlineExceeded:
            pass                        # ...then the deadline kills it
        assert 0 < len(got) < 48, \
            "expiry should land mid-decode, after the handoff"
        with pytest.raises(DeadlineExceeded):
            s.result(timeout=120)
    finally:
        router.stop()
    for r in rs:
        assert _pool_whole(r.engine), r.name


def test_disagg_kill_between_export_and_import_no_leak():
    """Kill the decode replica while an exported chain sits QUEUED on it
    (exported but not yet imported — the decode cap holds admission):
    the orphaned payload is dropped without touching any pool, both
    requests fail over to the survivor, outputs bit-identical, and the
    survivor's pool drains to whole."""
    model = _model(layers=2)
    prompts = _prompts(model, [9, 12], seed=23)
    ref = build_engine(model, ENG_CFG, seed=0)
    want = [ref.generate([prompts[0]], max_new_tokens=20)[0],
            ref.generate([prompts[1]], max_new_tokens=8)[0]]

    rs = ReplicaSet.build(model, 2, ENG_CFG,
                          {"prefix_cache": {"enabled": True}}, seed=0,
                          disagg=DISAGG)
    router = DisaggRouter(rs).start()
    try:
        # one decode slot: the filler takes it, the target's decode leg
        # must wait in r1's queue with its adopted-to-be payload
        rs[1].server.set_brownout("cap_decode")
        filler = router.submit(prompts[0],
                               SamplingParams(max_new_tokens=20))
        deadline = time.monotonic() + 120
        while (not rs[1].server._active
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert rs[1].server._active, "filler should be decoding on r1"
        target = router.submit(prompts[1],
                               SamplingParams(max_new_tokens=8))
        # target's prefill completed on r0, its decode leg (carrying the
        # exported chain) is queued behind the cap on r1
        while (len(rs[1].server.admission) < 1
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert len(rs[1].server.admission) == 1, \
            "target's decode leg should be queued (exported, unimported)"
        rs[1].kill()
        assert filler.result(timeout=300) == want[0]
        assert target.result(timeout=300) == want[1]
        assert router.metrics.failovers >= 2
    finally:
        router.stop()
    assert _pool_whole(rs[0].engine)
