"""Data efficiency: curriculum schedules, curriculum sampler, random-LTD,
variable batch/LR, and engine seqlen-curriculum integration.

Mirrors the reference's data-pipeline unit coverage
(tests/unit/runtime/test_data_efficiency.py style)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import get_model_config
from deepspeed_tpu.runtime.data_pipeline import (CurriculumScheduler,
                                                 DeepSpeedDataSampler,
                                                 RandomLTDScheduler,
                                                 batch_by_token_budget,
                                                 random_ltd_drop,
                                                 random_ltd_restore,
                                                 scale_lr_by_batch_size)
from deepspeed_tpu.runtime.data_pipeline.data_routing import (
    RandomLTDLayerWrapper, random_ltd_indices)


def test_curriculum_fixed_linear():
    cs = CurriculumScheduler({
        "curriculum_type": "seqlen", "min_difficulty": 8,
        "max_difficulty": 64, "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 100, "difficulty_step": 8}})
    assert cs.get_difficulty(0) == 8
    assert cs.get_difficulty(50) == 8 + (64 - 8) // 2 // 8 * 8  # rounded to 8s
    assert cs.get_difficulty(100) == 64
    assert cs.get_difficulty(10**6) == 64
    # monotone non-decreasing
    vals = [cs.get_difficulty(s) for s in range(0, 120, 5)]
    assert all(a <= b for a, b in zip(vals, vals[1:]))


def test_curriculum_fixed_root_and_discrete():
    root = CurriculumScheduler({
        "min_difficulty": 4, "max_difficulty": 100,
        "schedule_type": "fixed_root",
        "schedule_config": {"total_curriculum_step": 100, "difficulty_step": 1,
                            "root_degree": 2}})
    # sqrt schedule grows faster early than linear
    assert root.get_difficulty(25) == 4 + int((100 - 4) * 0.5)
    disc = CurriculumScheduler({
        "schedule_type": "fixed_discrete", "min_difficulty": 1,
        "max_difficulty": 3,
        "schedule_config": {"difficulty": [16, 32, 64], "max_step": [10, 20]}})
    assert disc.get_difficulty(5) == 16
    assert disc.get_difficulty(15) == 32
    assert disc.get_difficulty(25) == 64


def test_curriculum_validation():
    with pytest.raises(ValueError):
        CurriculumScheduler({"schedule_type": "fixed_linear"})
    with pytest.raises(ValueError):
        CurriculumScheduler({"schedule_type": "bogus"})


def test_sampler_plain_partitions_ranks():
    s0 = DeepSpeedDataSampler(32, batch_size=8, dp_rank=0, dp_size=2, seed=3)
    s1 = DeepSpeedDataSampler(32, batch_size=8, dp_rank=1, dp_size=2, seed=3)
    b0, b1 = list(s0), list(s1)
    assert len(b0) == len(b1) == 4
    seen = set()
    for x, y in zip(b0, b1):
        assert len(x) == len(y) == 4
        assert not (set(x) & set(y))  # disjoint rank slices
        seen |= set(x) | set(y)
    assert seen == set(range(32))  # every sample exactly once


def test_sampler_curriculum_filters_difficulty():
    # difficulties = seqlens 1..64; curriculum caps at 16 for first steps
    n = 64
    diffs = np.arange(1, n + 1)
    cs = CurriculumScheduler({
        "min_difficulty": 16, "max_difficulty": 64,
        "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 4, "difficulty_step": 16}})
    s = DeepSpeedDataSampler(n, batch_size=8, difficulties=diffs,
                             curriculum=cs, shuffle=True, seed=0)
    batches = list(s)
    # first batch: only samples with difficulty <= 16
    assert all(diffs[i] <= 16 for i in batches[0])
    # every sample seen at most once
    flat = [i for b in batches for i in b]
    assert len(flat) == len(set(flat))


def test_random_ltd_gather_scatter_roundtrip():
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 16, 4)),
                    jnp.float32)
    idx = random_ltd_indices(jax.random.PRNGKey(0), 16, 8, 2)
    assert idx.shape == (2, 8)
    # sorted, unique per row
    for r in np.asarray(idx):
        assert (np.diff(r) > 0).all()
    kept = random_ltd_drop(x, idx)
    assert kept.shape == (2, 8, 4)
    restored = random_ltd_restore(x, kept, idx)
    np.testing.assert_allclose(np.asarray(restored), np.asarray(x))  # identity


def test_random_ltd_layer_wrapper():
    w = jnp.asarray(np.random.default_rng(1).standard_normal((4, 4)), jnp.float32)
    layer = lambda x, pos: x @ w  # noqa: E731
    sched = RandomLTDScheduler(8, 16, total_steps=10, step_size=4)
    wrapper = RandomLTDLayerWrapper(layer, sched)
    x = jnp.asarray(np.random.default_rng(2).standard_normal((2, 16, 4)), jnp.float32)
    pos = jnp.tile(jnp.arange(16), (2, 1))
    y = wrapper(x, pos, jax.random.PRNGKey(1), kept=8)
    assert y.shape == x.shape
    # exactly 8 tokens per row transformed, the rest passed through
    changed = (np.abs(np.asarray(y - x)).sum(-1) > 1e-6).sum(axis=1)
    assert (changed <= 8).all()
    # kept >= seq → plain layer
    y_full = wrapper(x, pos, jax.random.PRNGKey(1), kept=16)
    np.testing.assert_allclose(np.asarray(y_full), np.asarray(x @ w), atol=1e-6)


def test_random_ltd_schedule():
    s = RandomLTDScheduler(64, 512, total_steps=100, step_size=64)
    assert s.get_seqlen(0) == 64
    assert s.get_seqlen(100) == 512
    assert s.get_seqlen(50) == (64 + (512 - 64) // 2) // 64 * 64


def test_batch_by_token_budget():
    seqlens = [10, 20, 30, 40, 50, 60]
    batches = batch_by_token_budget(seqlens, token_budget=100, shuffle_seed=-1)
    flat = sorted(i for b in batches for i in b)
    assert flat == list(range(6))
    for b in batches:
        rows = len(b)
        assert rows * max(seqlens[i] for i in b) <= 100
    with pytest.raises(ValueError):
        batch_by_token_budget([200], token_budget=100)


def test_scale_lr():
    assert scale_lr_by_batch_size(0.1, 64, 32, "linear") == pytest.approx(0.2)
    assert scale_lr_by_batch_size(0.1, 64, 16, "sqrt") == pytest.approx(0.2)
    assert scale_lr_by_batch_size(0.1, 64, 32, "none") == 0.1


def test_engine_curriculum_truncates_seqlen():
    model = get_model_config("gpt2-tiny")
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "mesh": {"data": 1},
        "data_efficiency": {
            "enabled": True,
            "data_sampling": {"curriculum_learning": {
                "enabled": True, "curriculum_type": "seqlen",
                "min_difficulty": 8, "max_difficulty": 16,
                "schedule_type": "fixed_linear",
                "schedule_config": {"total_curriculum_step": 2,
                                    "difficulty_step": 8}}}},
    }
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(2, 16), dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids}
    seen = []
    orig = engine._stack_micro_batches

    def spy(data):
        out = orig(data)
        seen.append(out["input_ids"].shape[-1])
        return out

    engine._stack_micro_batches = spy
    for _ in range(3):
        loss = engine.train_batch(batch)
    assert np.isfinite(float(np.asarray(loss)))
    assert seen[0] == 8 and seen[-1] == 16  # difficulty ramped 8 → 16
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None


def test_legacy_curriculum_key():
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "curriculum_learning": {"enabled": True, "curriculum_type": "seqlen",
                                "min_difficulty": 2, "max_difficulty": 4,
                                "schedule_type": "fixed_linear",
                                "schedule_config": {"total_curriculum_step": 2}}})
    assert cfg.data_efficiency.enabled
    assert cfg.data_efficiency.curriculum_config["min_difficulty"] == 2
