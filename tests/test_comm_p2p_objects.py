"""Round-4 comm façade additions: p2p send/recv (single-edge permute),
root collectives (reduce/gather/scatter), host-object collectives, and
group teardown.  Ref surface: deepspeed/comm/comm.py:369-425 (send/recv/
gather/scatter/monitored_barrier), :229/:247 (object collectives),
:177/:182 (destroy_process_group/new_group)."""

import jax
import jax.numpy as jnp
import numpy as np
from deepspeed_tpu.utils.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm import comm
from deepspeed_tpu.parallel.topology import DATA_AXIS, MeshTopology


def _topo():
    return MeshTopology({"data": 8})


def test_send_recv_edge():
    topo = _topo()
    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)

    def f(xs):
        return comm.send_recv(xs, src=2, dst=5, group=DATA_AXIS)

    out = shard_map(f, mesh=topo.mesh, in_specs=P(DATA_AXIS),
                    out_specs=P(DATA_AXIS))(x)
    out = np.asarray(out).reshape(-1)
    assert out[5] == 2.0 and out[2] == 0.0 and out.sum() == 2.0


def test_send_recv_aliases():
    topo = _topo()
    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)

    def f_send(xs):
        return comm.send(xs, dst=3, group=DATA_AXIS, src=1)

    def f_recv(xs):
        return comm.recv(xs, src=6, group=DATA_AXIS)  # dst defaults to 7

    s = np.asarray(shard_map(f_send, mesh=topo.mesh, in_specs=P(DATA_AXIS),
                             out_specs=P(DATA_AXIS))(x)).reshape(-1)
    r = np.asarray(shard_map(f_recv, mesh=topo.mesh, in_specs=P(DATA_AXIS),
                             out_specs=P(DATA_AXIS))(x)).reshape(-1)
    assert s[3] == 1.0 and s.sum() == 1.0
    assert r[7] == 6.0 and r.sum() == 6.0


def test_reduce_and_gather_spmd_supersets():
    topo = _topo()
    x = jnp.ones((8, 2), jnp.float32)

    def f(xs):
        return comm.reduce(xs, dst=0, group=DATA_AXIS)

    out = np.asarray(shard_map(f, mesh=topo.mesh, in_specs=P(DATA_AXIS),
                               out_specs=P(DATA_AXIS))(x))
    assert (out == 8.0).all()  # every rank holds the root's result

    def g(xs):
        return comm.gather(xs, dst=0, group=DATA_AXIS)

    out = shard_map(g, mesh=topo.mesh, in_specs=P(DATA_AXIS),
                    out_specs=P(None, DATA_AXIS))(
        jnp.arange(8, dtype=jnp.float32).reshape(8, 1))
    assert np.asarray(out).reshape(8, 8).shape == (8, 8)


def test_scatter_slices_root_tensor():
    topo = _topo()
    # every rank holds a [8] row; rank i should end with root's slice i
    rows = jnp.tile(jnp.arange(8, dtype=jnp.float32)[None, :] * 0, (8, 1))
    rows = rows.at[3].set(jnp.arange(8, dtype=jnp.float32))  # root = 3

    def f(xs):
        return comm.scatter(xs[0], src=3, group=DATA_AXIS)[None]

    out = shard_map(f, mesh=topo.mesh, in_specs=P(DATA_AXIS),
                    out_specs=P(DATA_AXIS))(rows)
    np.testing.assert_allclose(np.asarray(out).reshape(-1),
                               np.arange(8, dtype=np.float32))


def test_scatter_rejects_nondivisible_axis():
    import pytest

    topo = _topo()
    rows = jnp.ones((8, 10), jnp.float32)

    def f(xs):
        return comm.scatter(xs[0], src=0, group=DATA_AXIS)[None]

    with pytest.raises(ValueError, match="divide evenly"):
        shard_map(f, mesh=topo.mesh, in_specs=P(DATA_AXIS),
                  out_specs=P(DATA_AXIS))(rows)


def test_object_collectives_single_process_identity():
    objs = [{"a": 1}, "two"]
    comm.broadcast_object_list(objs, src=0)
    assert objs == [{"a": 1}, "two"]
    assert comm.all_gather_object({"rank": 0}) == [{"rank": 0}]


def test_monitored_barrier_and_new_group():
    comm.monitored_barrier(timeout=10.0)  # no straggler → silent
    assert comm.new_group([3, 1, 2]) == (1, 2, 3)
