"""Accelerator abstraction conformance (ref tests/unit/accelerator/)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.accelerator import (DeepSpeedAccelerator, get_accelerator,
                                       set_accelerator)
from deepspeed_tpu.accelerator.cpu_accelerator import CPU_Accelerator
from deepspeed_tpu.accelerator.real_accelerator import _probe_platform


@pytest.fixture(autouse=True)
def _fresh_accelerator():
    set_accelerator(None)
    yield
    set_accelerator(None)


def test_get_accelerator_probes_platform():
    acc = get_accelerator()
    assert isinstance(acc, DeepSpeedAccelerator)
    assert acc.device_name().split(":")[0] == _probe_platform()
    assert get_accelerator() is acc  # cached


def test_abstract_surface_complete():
    """Every abstract method is implemented on both backends."""
    import inspect

    for cls in (CPU_Accelerator,):
        acc = cls()
        for name, member in inspect.getmembers(DeepSpeedAccelerator):
            if getattr(member, "__isabstractmethod__", False):
                assert callable(getattr(acc, name)), name


def test_device_and_memory_api():
    acc = CPU_Accelerator()
    assert acc.device_count() >= 1
    assert acc.is_available()
    acc.set_device(0)
    assert acc.current_device() == 0
    assert acc.device(0) in jax.devices("cpu")
    stats = acc.memory_stats()
    assert stats.get("bytes_in_use", 0) > 0  # /proc RSS
    assert acc.total_memory() > 0
    assert 0 < acc.available_memory() <= acc.total_memory()


def test_rng_state_roundtrip():
    acc = CPU_Accelerator()
    acc.manual_seed(42)
    assert acc.initial_seed() == 42
    state = acc.get_rng_state()
    k1 = acc.next_key()
    acc.set_rng_state(state)
    k2 = acc.next_key()
    np.testing.assert_array_equal(np.asarray(k1), np.asarray(k2))


def test_dtype_support():
    acc = CPU_Accelerator()
    assert acc.is_bf16_supported()
    assert not acc.is_fp16_supported()
    assert jnp.bfloat16 in acc.supported_dtypes()
    assert acc.preferred_dtype() == jnp.bfloat16


def test_stream_event_nullops_and_sync():
    acc = CPU_Accelerator()
    s = acc.Stream()
    ev = acc.Event(enable_timing=True)
    ev.record()
    e2 = acc.Event(enable_timing=True)
    e2.record()
    assert ev.elapsed_time(e2) >= 0.0
    s.synchronize()
    acc.synchronize()
    with acc.stream(s):
        pass


def test_graph_capture_is_jit():
    acc = CPU_Accelerator()
    g = acc.create_graph()
    g.capture(lambda x: x * 2)
    out = acc.replay_graph(g, jnp.ones((4,)))
    np.testing.assert_allclose(np.asarray(out), 2 * np.ones((4,)))


def test_range_push_pop_no_crash():
    acc = CPU_Accelerator()
    acc.range_push("test-range")
    acc.range_pop()


def test_env_override(monkeypatch):
    monkeypatch.setenv("DS_ACCELERATOR", "cpu")
    acc = get_accelerator()
    assert isinstance(acc, CPU_Accelerator)
    assert acc.communication_backend_name() == "xla-cpu"
