"""Cross-feature integration: one engine stacking ZeRO-3 + dropout +
noisy-MoE gating + per-op autocast + gradient clipping + LR schedule +
checkpoint round-trip.  Features are individually tested elsewhere; this
pins their COMPOSITION (where hook-free designs usually rot)."""

import numpy as np

import deepspeed_tpu as ds
from deepspeed_tpu.models import get_model_config
from deepspeed_tpu.parallel import topology


def test_zero3_dropout_noisy_moe_autocast_composition(tmp_path):
    model = get_model_config("mixtral-tiny", dropout=0.1,
                             moe_noisy_gate_policy="RSample")
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "AdamW",
                      "params": {"lr": 1e-3, "weight_decay": 0.01}},
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_num_steps": 4}},
        "torch_autocast": {"enabled": True, "dtype": "bfloat16",
                           "fp32_ops": ["layernorm", "softmax", "rope",
                                        "router", "loss"]},
        "zero_optimization": {"stage": 3},
        "gradient_clipping": 1.0,
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config, seed=11)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(32, 33), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    losses = [float(np.asarray(engine.train_batch(batch)))
              for _ in range(8)]
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0] - 0.5, losses

    # checkpoint round-trip mid-composition: params AND the dropout/noise
    # stream stay consistent (loss continues from where it left off)
    engine.save_checkpoint(str(tmp_path), tag="ks")
    cont = float(np.asarray(engine.train_batch(batch)))
    engine.load_checkpoint(str(tmp_path), tag="ks")
    resumed = float(np.asarray(engine.train_batch(batch)))
    # same step counter + seed-derived keys → the resumed step must match
    # the continued step bit-for-bit
    assert resumed == cont, (resumed, cont)
    topology._GLOBAL_TOPOLOGY = None


def test_pipeline_dropout_clip_schedule_composition(tmp_path):
    """pipe=2 × data=4 with dropout + gradient clipping + LR schedule +
    checkpoint resume: the 1F1B keyed-dropout path composing with the
    rest of the training stack (ref: every GPT-2 pipeline run trains with
    dropout, runtime/pipe/engine.py:337)."""
    model = get_model_config("gpt2-tiny", dropout=0.1)
    config = {
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "AdamW",
                      "params": {"lr": 1e-3, "weight_decay": 0.01}},
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_num_steps": 4}},
        "mesh": {"pipe": 2, "data": 4},
        "gradient_clipping": 1.0,
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config, seed=11)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(32, 33), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    losses = [float(np.asarray(engine.train_batch(batch)))
              for _ in range(6)]
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0] - 0.5, losses

    engine.save_checkpoint(str(tmp_path), tag="pp")
    cont = float(np.asarray(engine.train_batch(batch)))
    engine.load_checkpoint(str(tmp_path), tag="pp")
    resumed = float(np.asarray(engine.train_batch(batch)))
    assert resumed == cont, (resumed, cont)
    topology._GLOBAL_TOPOLOGY = None
