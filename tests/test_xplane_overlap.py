"""XPlane overlap analysis (utils/xplane.py) — the measurement machinery
behind tools/domino_overlap.py (ref Domino claim,
blogs/deepspeed-domino/README.md:126)."""

import jax
import numpy as np
import pytest

pytest.importorskip("tensorflow")  # xplane proto ships with tensorflow

from deepspeed_tpu.utils import xplane  # noqa: E402


def test_overlap_fraction_math():
    # collective [0, 10) fully covered by compute [0, 20)
    assert xplane.overlap_fraction([(0, 10)], [(0, 20)]) == 1.0
    # half covered
    assert xplane.overlap_fraction([(0, 10)], [(5, 20)]) == 0.5
    # disjoint
    assert xplane.overlap_fraction([(0, 10)], [(10, 20)]) == 0.0
    # overlapping compute intervals must not double-count
    assert xplane.overlap_fraction([(0, 10)], [(0, 6), (4, 10)]) == 1.0
    # multiple collectives, partial coverage: [0,4) covered 4, [8,12) covered 2
    assert xplane.overlap_fraction([(0, 4), (8, 12)],
                                   [(0, 5), (9, 11)]) == 0.75
    # no collectives
    assert xplane.overlap_fraction([], [(0, 5)]) == 0.0


def test_cpu_capture_parses_and_reports_no_device_planes(tmp_path):
    """A CPU capture carries host events only — the analyzer must parse
    the file and say so, not crash (the TPU device planes are what the
    on-chip tool consumes)."""
    x = jax.numpy.ones((128, 128))
    f = jax.jit(lambda a: a @ a)
    f(x)
    jax.profiler.start_trace(str(tmp_path))
    float(np.asarray(f(x).sum()))
    jax.profiler.stop_trace()
    files = xplane.find_xplane_files(str(tmp_path))
    assert files, "capture produced no xplane file"
    xs = xplane.load_xspace(files[0])
    assert len(xs.planes) > 0
    res = xplane.analyze_logdir(str(tmp_path), device_substr="TPU")
    assert "error" in res and "device planes" in res["error"]


def test_synthetic_device_plane_analysis(tmp_path):
    """Build an XSpace with a fake TPU plane and check end-to-end
    classification + overlap accounting."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    xs = xplane_pb2.XSpace()
    plane = xs.planes.add(name="/device:TPU:0")
    names = {1: "fusion.42", 2: "all-reduce.7", 3: "dot.3", 4: "infeed"}
    for mid, n in names.items():
        plane.event_metadata[mid].name = n
    line = plane.lines.add(timestamp_ns=0)
    # compute fusion [0, 100); all-reduce [50, 150) → half hidden
    e = line.events.add(metadata_id=1, offset_ps=0, duration_ps=100)
    e = line.events.add(metadata_id=2, offset_ps=50, duration_ps=100)
    e = line.events.add(metadata_id=3, offset_ps=200, duration_ps=50)
    e = line.events.add(metadata_id=4, offset_ps=0, duration_ps=500)  # ignored
    del e
    path = tmp_path / "t.xplane.pb"
    path.write_bytes(xs.SerializeToString())
    res = xplane.analyze_logdir(str(tmp_path))
    dev = res["devices"]["/device:TPU:0"]
    assert dev["overlap_fraction"] == 0.5
    assert res["mean_overlap_fraction"] == 0.5
