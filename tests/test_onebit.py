"""1-bit optimizers (OnebitAdam / OnebitLamb / ZeroOneAdam).

Ref test model: tests/onebit/ + tests/unit/runtime/half_precision/onebit —
convergence of the compressed-momentum optimizers vs plain Adam on the
8-virtual-device DP mesh.
"""

import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import get_model_config
from tests.conftest import make_lm_batch


def _train(opt_type, rng, steps=8, freeze_step=3, **opt_params):
    model = get_model_config("gpt2-tiny", num_layers=2)
    cfg = {"train_micro_batch_size_per_gpu": 1, "gradient_accumulation_steps": 2,
           "optimizer": {"type": opt_type,
                         "params": {"lr": 1e-3, "freeze_step": freeze_step,
                                    **opt_params}},
           "mesh": {"data": 8}}
    engine, *_ = ds.initialize(model=model, config=cfg, seed=0)
    batch = make_lm_batch(rng, 16, 16, model.vocab_size)
    return [float(np.asarray(engine.train_batch(batch))) for _ in range(steps)], engine


@pytest.mark.parametrize("opt", ["OnebitAdam", "OnebitLamb", "ZeroOneAdam"])
def test_onebit_variants_converge(rng, opt):
    """Loss must keep dropping after the warmup→compression switch."""
    losses, engine = _train(opt, rng, steps=8, freeze_step=3)
    assert engine._onebit is not None  # compressed mode engaged
    assert losses[-1] < losses[0]
    # still learning during the compression stage
    assert losses[-1] < losses[3]


def test_onebit_tracks_exact_adam(rng):
    """1-bit Adam with error feedback stays close to uncompressed AdamW."""
    ob, _ = _train("OnebitAdam", rng, steps=8, freeze_step=4, weight_decay=0.0)
    ref, _ = _train("Adam", rng, steps=8, weight_decay=0.0)
    # identical during warmup steps is too strict (different update forms);
    # final losses must be in the same regime
    assert abs(ob[-1] - ref[-1]) / ref[-1] < 0.25, (ob, ref)


def test_onebit_state_is_per_rank_sharded(rng):
    _, engine = _train("OnebitAdam", rng, steps=1)
    st = engine._onebit_state
    world = engine.topology.dp_size
    assert st["worker_err"].shape[0] == world
    assert st["server_err"].shape[0] == world
    # error feedback actually fires once compression starts
    assert float(np.asarray(engine.loss_scale_state["scale"])) == 1.0


def test_onebit_single_device_falls_back(rng):
    """dp==1: no compression machinery; plain optimizer path."""
    model = get_model_config("gpt2-tiny", num_layers=1)
    cfg = {"train_micro_batch_size_per_gpu": 2, "gradient_accumulation_steps": 1,
           "optimizer": {"type": "OnebitAdam", "params": {"lr": 1e-3}},
           "mesh": {"data": 1}}
    engine, *_ = ds.initialize(model=model, config=cfg, seed=0)
    assert engine._onebit is None
    batch = make_lm_batch(rng, 2, 8, model.vocab_size)
    l0 = float(np.asarray(engine.train_batch(batch)))
    for _ in range(3):
        loss = engine.train_batch(batch)
    assert float(np.asarray(loss)) < l0


def test_qgz_compressed_dp_gradients_converge(rng):
    """zero_quantized_gradients without ZeRO-3: int8 hierarchical gradient
    reduction in the DP step (qgZ), with hpZ node factoring."""
    model = get_model_config("gpt2-tiny", num_layers=2)
    cfg = {"train_micro_batch_size_per_gpu": 2, "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": 1, "zero_quantized_gradients": True,
                                 "zero_hpz_partition_size": 2},
           "mesh": {"data": 8}}
    engine, *_ = ds.initialize(model=model, config=cfg, seed=0)
    assert engine._onebit is not None and engine._onebit.cfg.variant == "qgz"
    batch = make_lm_batch(rng, 16, 16, model.vocab_size)
    losses = [float(np.asarray(engine.train_batch(batch))) for _ in range(6)]
    assert losses[-1] < losses[0]

    # and it tracks the exact-gradient run closely (int8 error is tiny)
    ref, _ = _train("AdamW", rng, steps=6)
    assert abs(losses[-1] - ref[5]) / ref[5] < 0.1


def test_onebit_rejects_model_parallel_mesh(rng):
    from deepspeed_tpu.runtime.onebit import OnebitConfig, OnebitTrainStep
    from deepspeed_tpu.parallel.topology import MeshTopology

    topo = MeshTopology({"data": 4, "tensor": 2})
    with pytest.raises(ValueError, match="data-parallel"):
        OnebitTrainStep(topo, lambda p, b: 0.0, {"w": np.zeros((4,))},
                        OnebitConfig({}, "onebitadam"), gas=1)
