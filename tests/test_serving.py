"""Serving layer (MII analog): streams, admission, preemption, metrics.

The correctness oracle mirrors test_inference_v2: everything the async
serve loop produces under greedy sampling must be BIT-IDENTICAL to the
engine's one-shot ``generate()`` with the same weights — across thread
interleavings, admission waves, and KV-exhaustion preemptions.
"""

import threading
import time

import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import build_engine
from deepspeed_tpu.models import get_model_config
from deepspeed_tpu.serving import (DeadlineExceeded, InferenceServer,
                                   QueueFull, RequestCancelled,
                                   SamplingParams, ServingError,
                                   ServingMetrics)


def _tiny_engine(num_blocks=64, block_size=4, max_seqs=8, budget=16,
                 max_context=64, seed=0):
    model = get_model_config("llama-tiny", num_layers=1)
    eng = build_engine(
        model, {"dtype": "float32",
                "state_manager": {"max_tracked_sequences": max_seqs,
                                  "max_ragged_batch_size": budget},
                "memory_config": {"num_blocks": num_blocks,
                                  "block_size": block_size},
                "max_context": max_context}, seed=seed)
    return model, eng


def _prompts(model, sizes, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, model.vocab_size, size=n).tolist()
            for n in sizes]


def test_streaming_matches_generate_one_shot():
    """Iterated stream tokens == blocking result() == engine.generate()."""
    model, eng = _tiny_engine()
    prompts = _prompts(model, (5, 11, 3))
    ref = eng.generate(prompts, max_new_tokens=6)
    srv = InferenceServer(eng).start()
    try:
        streamed = {}

        def consume(i, stream):
            streamed[i] = [tok for tok in stream]  # incremental iterator

        streams = [srv.submit(p, SamplingParams(max_new_tokens=6))
                   for p in prompts]
        threads = [threading.Thread(target=consume, args=(i, s))
                   for i, s in enumerate(streams)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert [streamed[i] for i in range(3)] == ref
        assert [s.result(timeout=1) for s in streams] == ref
    finally:
        srv.stop()
    assert eng.free_blocks == eng.cfg.num_blocks - 1


def test_e2e_concurrent_streaming_preemption_parity():
    """The acceptance-criteria run: 8 threads submit concurrently, tokens
    stream incrementally (a first token lands before any other request
    finishes), a tiny KV pool forces ≥1 preemption that recovers, final
    outputs are bit-identical to one-shot greedy generate(), and the
    metrics snapshot shows nonzero TTFT/TPOT/preemption counters."""
    n_req, new = 8, 12
    # 23 usable blocks: eight 8-token prompts admit (2 blocks each) but
    # grow to ceil(20/4)=5 blocks → demand 40 > 23 → forced preemption
    model, eng = _tiny_engine(num_blocks=24, block_size=4, max_seqs=8,
                              budget=32, max_context=32)
    prompts = _prompts(model, [8] * n_req, seed=7)
    ref = eng.generate(prompts, max_new_tokens=new)
    assert eng.free_blocks == 23

    srv = InferenceServer(eng).start()
    outs = {}
    first_token_at = {}
    finished_at = {}

    def submit_and_consume(i):
        stream = srv.submit(prompts[i], SamplingParams(max_new_tokens=new))
        toks = []
        for tok in stream:
            if not toks:
                first_token_at[i] = time.monotonic()
            toks.append(tok)
        finished_at[i] = time.monotonic()
        outs[i] = toks

    try:
        threads = [threading.Thread(target=submit_and_consume, args=(i,))
                   for i in range(n_req)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads)
    finally:
        srv.stop()

    assert [outs[i] for i in range(n_req)] == ref  # bit-identical greedy
    # continuous batching: someone's first token precedes someone else's
    # completion (tokens interleave across requests, not one-at-a-time)
    assert any(first_token_at[a] < finished_at[b]
               for a in range(n_req) for b in range(n_req) if a != b)
    snap = srv.metrics.snapshot()
    assert snap["preemptions"] >= 1          # KV exhaustion recovered
    assert snap["completed"] == n_req
    assert snap["ttft"]["count"] == n_req and snap["ttft"]["p50"] > 0
    assert snap["tpot"]["count"] == n_req and snap["tpot"]["p50"] > 0
    assert snap["tokens_out"] == n_req * new
    assert eng.free_blocks == 23             # no leaked pages
    assert eng.state_manager.n_active == 0


def test_interleaved_prefill_decode_waves():
    """Submitters arrive while earlier requests are mid-decode: outputs
    still match one-shot generate() per prompt."""
    model, eng = _tiny_engine(max_seqs=4, budget=16)
    prompts = _prompts(model, (9, 4, 13, 6, 3, 11), seed=3)
    ref = eng.generate(prompts, max_new_tokens=5)
    srv = InferenceServer(eng).start()
    try:
        streams = []
        for i, p in enumerate(prompts):
            streams.append(srv.submit(p, SamplingParams(max_new_tokens=5)))
            time.sleep(0.05)  # arrivals interleave with running decode
        outs = [s.result(timeout=120) for s in streams]
    finally:
        srv.stop()
    assert outs == ref


def test_cancellation_mid_stream():
    model, eng = _tiny_engine()
    srv = InferenceServer(eng).start()
    try:
        [p] = _prompts(model, (6,))
        stream = srv.submit(p, SamplingParams(max_new_tokens=40))
        it = iter(stream)
        got = [next(it)]           # wait until it's demonstrably running
        stream.cancel()
        with pytest.raises(RequestCancelled):
            for tok in it:
                got.append(tok)
        with pytest.raises(RequestCancelled):
            stream.result(timeout=10)
        assert len(stream.tokens) >= len(got)  # delivered tokens readable
        deadline = time.monotonic() + 10
        while eng.state_manager.n_active and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.state_manager.n_active == 0  # slot + pages reclaimed
    finally:
        srv.stop()
    snap = srv.metrics.snapshot()
    assert snap["cancelled"] == 1


def test_cancel_while_queued():
    """Cancelling before admission: request leaves the queue unserved."""
    model, eng = _tiny_engine(max_seqs=1)
    srv = InferenceServer(eng)
    long, short = _prompts(model, (6, 4))
    s1 = srv.submit(long, SamplingParams(max_new_tokens=32))
    s2 = srv.submit(short, SamplingParams(max_new_tokens=4))
    s2.cancel()                    # cancelled while queued (server not up)
    srv.start()
    try:
        assert len(s1.result(timeout=120)) == 32
        with pytest.raises(RequestCancelled):
            s2.result(timeout=10)
        assert s2.tokens == []
    finally:
        srv.stop()


def test_deadline_expiry():
    model, eng = _tiny_engine()
    srv = InferenceServer(eng).start()
    try:
        [p] = _prompts(model, (5,))
        stream = srv.submit(p, SamplingParams(max_new_tokens=50),
                            deadline_s=0.3)
        with pytest.raises(DeadlineExceeded):
            stream.result(timeout=60)
        ok = srv.submit(p, SamplingParams(max_new_tokens=3))
        assert len(ok.result(timeout=60)) == 3   # server survives expiry
    finally:
        srv.stop()
    assert srv.metrics.snapshot()["expired"] == 1


def test_queue_full_reject_policy():
    model, eng = _tiny_engine()
    srv = InferenceServer(eng, {"admission": {"max_queue_size": 2}})
    [p] = _prompts(model, (4,))
    srv.submit(p), srv.submit(p)   # server not started: queue only fills
    with pytest.raises(QueueFull):
        srv.submit(p)
    assert srv.metrics.snapshot()["rejected"] == 1


def test_submit_validation():
    model, eng = _tiny_engine(num_blocks=8, block_size=4, max_context=16)
    srv = InferenceServer(eng)
    with pytest.raises(ValueError, match="empty prompt"):
        srv.submit([])
    with pytest.raises(ValueError, match="KV blocks"):
        srv.submit(list(range(1, 10)),
                   SamplingParams(max_new_tokens=4096))
    # degenerate sampling params fail at the API boundary — inside the
    # serve loop they would crash it and fail every in-flight request
    with pytest.raises(ValueError, match="top_p"):
        srv.submit([1, 2], SamplingParams(temperature=0.8, top_p=0.0))
    with pytest.raises(ValueError, match="top_k"):
        srv.submit([1, 2], SamplingParams(temperature=0.8, top_k=-1))


def test_heterogeneous_sampling_batch():
    """Greedy and nucleus requests coexist in one ragged batch; greedy
    outputs stay bit-identical to generate(), sampled outputs are valid
    and deterministic per seed."""
    model, eng = _tiny_engine()
    prompts = _prompts(model, (5, 7), seed=11)
    ref = eng.generate([prompts[0]], max_new_tokens=6)
    outs = {}
    for attempt in range(2):
        srv = InferenceServer(eng).start()
        try:
            g = srv.submit(prompts[0], SamplingParams(max_new_tokens=6))
            s = srv.submit(prompts[1], SamplingParams(
                max_new_tokens=6, temperature=0.8, top_p=0.9, top_k=50,
                seed=123))
            outs[attempt] = (g.result(timeout=120), s.result(timeout=120))
        finally:
            srv.stop()
        assert outs[attempt][0] == ref[0]
        assert all(0 <= t < model.vocab_size for t in outs[attempt][1])
    assert outs[0][1] == outs[1][1]  # same seed → same sampled tokens


def test_graceful_drain_vs_abort():
    model, eng = _tiny_engine()
    [p] = _prompts(model, (5,))
    srv = InferenceServer(eng).start()
    streams = [srv.submit(p, SamplingParams(max_new_tokens=8))
               for _ in range(3)]
    srv.stop(drain=True, timeout=120)         # drain: all complete
    assert all(len(s.result(timeout=1)) == 8 for s in streams)

    srv2 = InferenceServer(eng).start()
    streams2 = [srv2.submit(p, SamplingParams(max_new_tokens=50))
                for _ in range(3)]
    srv2.stop(drain=False, timeout=60)        # abort: all cancelled
    for s in streams2:
        with pytest.raises(RequestCancelled):
            s.result(timeout=1)
    assert eng.free_blocks == eng.cfg.num_blocks - 1
    with pytest.raises(RuntimeError, match="already stopped"):
        srv2.start()                          # no silent dead restarts


def test_priority_scheduling_order():
    """Higher-priority requests admitted from a contended queue first."""
    model, eng = _tiny_engine(max_seqs=8)
    sched = eng.scheduler
    mgr = eng.state_manager
    for uid, prio in ((1, 0), (2, 5), (3, 1)):
        mgr.open(uid, [1, 2, 3])
        sched.add(uid, priority=prio)
    order = [seq.uid for seq, _ in sched.next_schedule()]
    assert order == [2, 3, 1]
    for uid in (1, 2, 3):
        sched.retire(uid)
        mgr.flush(uid)
    # front=True (preempted requeue) beats FIFO within a priority class
    mgr.open(4, [7, 8])
    sched.add(4, priority=0)
    mgr.open(5, [9])
    sched.add(5, priority=0, front=True)
    order = [seq.uid for seq, _ in sched.next_schedule()]
    assert order == [5, 4]


def test_loop_crash_fails_streams_and_sheds_new_load(monkeypatch):
    """An engine failure must terminate every waiting stream with a typed
    error AND close the queue — a dead server accepting submits would
    park their result() calls forever."""
    model, eng = _tiny_engine()
    srv = InferenceServer(eng).start()
    [p] = _prompts(model, (4,))

    def boom(*a, **k):
        raise RuntimeError("injected engine failure")

    monkeypatch.setattr(eng, "step", boom)
    s = srv.submit(p, SamplingParams(max_new_tokens=4))
    with pytest.raises(ServingError, match="serve loop died"):
        s.result(timeout=60)
    with pytest.raises(QueueFull):     # admission closed by crash handler
        srv.submit(p)
    with pytest.raises(RuntimeError, match="serve loop died"):
        srv.stop()                     # surfaces the original failure


def test_stop_drain_fails_fast_on_dead_loop(monkeypatch):
    """A crashed loop must not make stop(drain=True) wait out the drain
    timeout: the crash handler can itself wedge on the broken engine
    (flush on inconsistent state), so stop() polls and raises the loop
    error as soon as it is recorded."""
    model, eng = _tiny_engine()
    srv = InferenceServer(eng).start()
    [p] = _prompts(model, (4,))
    release = threading.Event()

    def boom(*a, **k):
        raise RuntimeError("injected engine failure")

    def wedged_flush(uid):
        # the crash handler's flush hangs on the broken engine — exactly
        # the state stop() must not wait out
        release.wait(30)

    monkeypatch.setattr(eng, "step", boom)
    monkeypatch.setattr(eng, "flush", wedged_flush)
    srv.submit(p, SamplingParams(max_new_tokens=4))
    deadline = time.monotonic() + 10
    while srv._loop_error is None and time.monotonic() < deadline:
        time.sleep(0.01)
    assert srv._loop_error is not None
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="serve loop died"):
        srv.stop(drain=True, timeout=60)
    # fail-fast: seconds (the 1s handler grace), not the 60s drain wait
    assert time.monotonic() - t0 < 5.0
    release.set()


def test_metrics_monitor_export():
    """ServingMetrics events flow through a MonitorMaster-shaped sink."""
    class Sink:
        def __init__(self):
            self.events = []

        def write_events(self, evs):
            self.events.extend(evs)

    model, eng = _tiny_engine()
    [p] = _prompts(model, (4,))
    sink = Sink()
    srv = InferenceServer(eng, monitor=sink).start()
    try:
        srv.submit(p, SamplingParams(max_new_tokens=3)).result(timeout=120)
    finally:
        srv.stop()
    tags = {t for t, _v, _s in sink.events}
    assert {"serving/tokens_out", "serving/ttft_p50",
            "serving/tpot_p50", "serving/preemptions"} <= tags
    m = ServingMetrics()
    m.record_tokens(5)
    assert m.snapshot()["tokens_out"] == 5
