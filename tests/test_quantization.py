"""Quantization kernels + quantized/compressed collectives.

Ref test model: tests/unit/ops/quantizer/, tests/unit/comm/ — kernels are
checked against pure-numpy references; collectives run for real on the
8-virtual-device CPU mesh and are checked against exact (fp32) reductions.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm.compressed import (compressed_allreduce, pack_signs,
                                           unpack_signs)
from deepspeed_tpu.comm.coalesced_collectives import (all_gather_coalesced,
                                                      all_to_all_quant_reduce,
                                                      loco_quant_reduce,
                                                      reduce_scatter_coalesced,
                                                      tree_meta)
from deepspeed_tpu.ops.fp_quantizer import fp_dequantize, fp_fake_quantize, fp_quantize
from deepspeed_tpu.ops.quantizer import (dequantize_blockwise, fake_quantize,
                                         pack_int4, quantize_blockwise, unpack_int4)
from deepspeed_tpu.parallel.topology import MeshTopology
from deepspeed_tpu.utils.jax_compat import shard_map


# ----------------------------------------------------------------------
# Integer quantizer
# ----------------------------------------------------------------------
@pytest.mark.parametrize("num_bits,group", [(8, 64), (8, 0), (4, 32)])
def test_blockwise_roundtrip_error_bound(rng, num_bits, group):
    x = jnp.asarray(rng.normal(size=(4, 256)).astype(np.float32))
    q, s, z = quantize_blockwise(x, num_bits=num_bits, group_size=group)
    assert q.dtype == jnp.int8
    y = dequantize_blockwise(q, s, z, num_bits)
    # error bounded by half a quantization step per group
    gsz = group if group else 256
    step = np.asarray(jnp.max(jnp.abs(x.reshape(4, -1, gsz)), axis=-1)) / (
        2 ** (num_bits - 1) - 1)
    err = np.abs(np.asarray(x - y)).reshape(4, -1, gsz).max(-1)
    assert (err <= step * 0.5 + 1e-7).all()


def test_asymmetric_quantization_handles_offset(rng):
    x = jnp.asarray(rng.uniform(5.0, 6.0, size=(2, 128)).astype(np.float32))
    y_sym = fake_quantize(x, num_bits=4, group_size=128, symmetric=True)
    q, s, z = quantize_blockwise(x, num_bits=4, group_size=128, symmetric=False)
    y_asym = dequantize_blockwise(q, s, z, num_bits=4)
    # shifted distribution: asymmetric must be strictly better
    assert np.abs(np.asarray(x - y_asym)).max() < np.abs(np.asarray(x - y_sym)).max()


def test_int4_pack_unpack_roundtrip(rng):
    q = jnp.asarray(rng.integers(-8, 8, size=(3, 64)).astype(np.int8))
    np.testing.assert_array_equal(np.asarray(unpack_int4(pack_int4(q))), np.asarray(q))


def test_quantize_constant_group():
    x = jnp.zeros((1, 64))
    y = fake_quantize(x, 8, 64)
    np.testing.assert_array_equal(np.asarray(y), 0.0)


def test_quantize_under_jit(rng):
    x = jnp.asarray(rng.normal(size=(8, 128)).astype(np.float32))
    f = jax.jit(functools.partial(fake_quantize, num_bits=8, group_size=64))
    np.testing.assert_allclose(np.asarray(f(x)),
                               np.asarray(fake_quantize(x, 8, 64)), rtol=1e-6)


# ----------------------------------------------------------------------
# FP quantizer
# ----------------------------------------------------------------------
@pytest.mark.parametrize("fmt,tol", [("fp8_e4m3", 0.07), ("fp8_e5m2", 0.14),
                                     ("fp6_e3m2", 0.17), ("fp12_e4m7", 0.005)])
def test_fp_formats_error_vs_group_absmax(rng, fmt, tol):
    """Error bounded relative to each element's own magnitude for normals;
    globally bounded by a fraction of the group absmax (subnormal grid)."""
    x = jnp.asarray(rng.normal(size=(4, 256)).astype(np.float32))
    y = fp_fake_quantize(x, fmt, group_size=64)
    err = np.abs(np.asarray(x - y)).reshape(4, -1, 64)
    absmax = np.abs(np.asarray(x)).reshape(4, -1, 64).max(-1, keepdims=True)
    assert (err / absmax).max() < tol, f"{fmt}: {(err / absmax).max()}"


def test_fp8_uses_native_dtype(rng):
    x = jnp.asarray(rng.normal(size=(2, 64)).astype(np.float32))
    q, s = fp_quantize(x, "fp8_e4m3", group_size=0)
    assert q.dtype == jnp.float8_e4m3fn
    y = fp_dequantize(q, s, "fp8_e4m3")
    assert y.dtype == jnp.float32


def test_fp6_values_are_representable(rng):
    """Every fp6 output must have ≤2 mantissa bits and exponent in range."""
    x = jnp.asarray(rng.normal(size=(1, 512)).astype(np.float32) * 3)
    y = np.asarray(fp_fake_quantize(x, "fp6_e3m2", group_size=0))
    nz = y[y != 0]
    m, e = np.frexp(nz)
    # mantissa in {0.5, 0.625, 0.75, 0.875} → 2 fractional bits after the lead
    np.testing.assert_allclose((m * 8) % 1, 0, atol=1e-6)


# ----------------------------------------------------------------------
# Coalesced / quantized collectives on the 8-device mesh
# ----------------------------------------------------------------------
def _tree(rng, scale=1.0):
    return {"w": jnp.asarray(rng.normal(size=(16, 32)).astype(np.float32)) * scale,
            "b": jnp.asarray(rng.normal(size=(64,)).astype(np.float32))}


def test_reduce_scatter_coalesced_matches_psum(rng):
    topo = MeshTopology({"data": 8})
    world = 8
    grads = [_tree(rng) for _ in range(world)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *grads)

    def body(g):
        g = jax.tree.map(lambda x: x[0], g)
        shard, meta = reduce_scatter_coalesced(g, "data", world)
        return shard

    out = jax.jit(shard_map(body, mesh=topo.mesh,
                                in_specs=P("data"), out_specs=P("data")))(stacked)
    expect = jax.tree.map(lambda *xs: sum(xs), *grads)
    flat = np.concatenate([np.asarray(expect["b"]).ravel(),
                           np.asarray(expect["w"]).ravel()])
    np.testing.assert_allclose(np.asarray(out), flat, rtol=1e-5, atol=1e-5)


def test_reduce_scatter_then_gather_roundtrip(rng):
    topo = MeshTopology({"data": 8})
    world = 8
    grads = [_tree(rng) for _ in range(world)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *grads)
    shapes, dtypes = tree_meta(grads[0])

    def body(g):
        g = jax.tree.map(lambda x: x[0], g)
        shard, meta = reduce_scatter_coalesced(g, "data", world)
        full = all_gather_coalesced(shard, meta, shapes, dtypes, "data")
        return jax.tree.map(lambda x: x[None], full)

    out = jax.jit(shard_map(body, mesh=topo.mesh,
                                in_specs=P("data"),
                                out_specs=jax.tree.map(lambda _: P("data"), grads[0])))(stacked)
    expect = jax.tree.map(lambda *xs: sum(xs), *grads)
    for k in expect:
        np.testing.assert_allclose(np.asarray(out[k][0]), np.asarray(expect[k]),
                                   rtol=1e-5, atol=1e-5)


def test_qgz_two_level_quant_reduce_close_to_exact(rng):
    """qgZ over a 2×4 (outer×inner) factorised world ≈ exact mean."""
    topo = MeshTopology({"data": 2, "seq": 4})  # outer=data, inner=seq
    world = 8
    grads = [_tree(rng) for _ in range(world)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs).reshape((2, 4) + xs[0].shape),
                           *grads)

    def body(g):
        g = jax.tree.map(lambda x: x[0, 0], g)
        shard, meta = all_to_all_quant_reduce(g, "seq", "data", 4, 2,
                                              num_bits=8, group_size=64)
        return shard[None, None]

    out = jax.jit(shard_map(body, mesh=topo.mesh,
                                in_specs=P("data", "seq"),
                                out_specs=P("data", "seq")))(stacked)
    expect = jax.tree.map(lambda *xs: sum(xs) / world, *grads)
    flat = np.concatenate([np.asarray(expect["b"]).ravel(),
                           np.asarray(expect["w"]).ravel()])
    # shard layout: level-1 chunks the buffer over the INNER axis, level-2
    # over the outer — so the global order is (inner, outer)-major
    got = np.asarray(out).reshape(2, 4, -1).transpose(1, 0, 2).ravel()
    # int8 two-level: small relative error vs exact mean
    denom = np.abs(flat).max()
    assert np.abs(got - flat).max() / denom < 0.05


def test_loco_error_feedback_reduces_bias(rng):
    """LoCo: with error feedback, repeated reduction of the SAME gradient
    converges toward the exact mean (residual is re-injected)."""
    topo = MeshTopology({"data": 2, "seq": 4})
    world = 8
    grads = [_tree(rng) for _ in range(world)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs).reshape((2, 4) + xs[0].shape),
                           *grads)
    errs = jax.tree.map(lambda x: jnp.zeros_like(x), stacked)

    def body(g, e):
        g = jax.tree.map(lambda x: x[0, 0], g)
        e = jax.tree.map(lambda x: x[0, 0], e)
        shard, meta, new_err = loco_quant_reduce(g, e, "seq", "data", 4, 2,
                                                 num_bits=4, group_size=64)
        return shard[None, None], jax.tree.map(lambda x: x[None, None], new_err)

    step = jax.jit(shard_map(
        body, mesh=topo.mesh,
        in_specs=(P("data", "seq"), P("data", "seq")),
        out_specs=(P("data", "seq"), jax.tree.map(lambda _: P("data", "seq"), errs))))

    expect = jax.tree.map(lambda *xs: sum(xs) / world, *grads)
    flat = np.concatenate([np.asarray(expect["b"]).ravel(),
                           np.asarray(expect["w"]).ravel()])
    prev_err = None
    for i in range(3):
        out, errs = step(stacked, errs)
        cur = np.abs(np.asarray(out).reshape(-1) - flat).max()
        if prev_err is not None:
            assert cur <= prev_err * 1.5  # int4: error must not blow up
        prev_err = cur


# ----------------------------------------------------------------------
# 1-bit compressed allreduce
# ----------------------------------------------------------------------
def test_pack_unpack_signs(rng):
    bits = jnp.asarray(rng.integers(0, 2, size=(3, 64)).astype(np.uint8))
    np.testing.assert_array_equal(np.asarray(unpack_signs(pack_signs(bits))),
                                  np.asarray(bits))


def test_compressed_allreduce_error_feedback_convergence(rng):
    """Sign-compressed mean with error feedback: averaging the same vectors
    repeatedly drives the accumulated estimate to the true mean (the 1-bit
    Adam guarantee)."""
    topo = MeshTopology({"data": 8})
    world, n = 8, 1024
    xs = rng.normal(size=(world, n)).astype(np.float32)
    exact = xs.mean(0)

    def body(x, we, se):
        out, we2, se2 = compressed_allreduce(x[0], we[0], se[0], "data", world)
        return out[None], we2[None], se2[None]

    step = jax.jit(shard_map(body, mesh=topo.mesh,
                                 in_specs=(P("data"), P("data"), P("data")),
                                 out_specs=(P("data"), P("data"), P("data"))))
    we = jnp.zeros((world, n))
    se = jnp.zeros((world, n // world))
    x = jnp.asarray(xs)
    total = np.zeros(n)
    # error feedback: sum of compressed outputs ≈ sum of true means
    for i in range(6):
        out, we, se = step(x, we, se)
        total += np.asarray(out[0])
    avg_est = total / 6
    corr = np.corrcoef(avg_est, exact)[0, 1]
    assert corr > 0.9, corr


def test_compressed_allreduce_identical_inputs_exact():
    """All ranks hold the same vector → sign compression is exact in sign
    and the scale matches the L1 mean."""
    topo = MeshTopology({"data": 8})
    world, n = 8, 256
    v = np.sign(np.random.default_rng(1).normal(size=n)).astype(np.float32)

    def body(x, we, se):
        out, we2, se2 = compressed_allreduce(x[0], we[0], se[0], "data", world)
        return out[None], we2[None], se2[None]

    step = jax.jit(shard_map(body, mesh=topo.mesh,
                                 in_specs=(P("data"), P("data"), P("data")),
                                 out_specs=(P("data"), P("data"), P("data"))))
    x = jnp.asarray(np.tile(v, (world, 1)))
    out, _, _ = step(x, jnp.zeros((world, n)), jnp.zeros((world, n // world)))
    np.testing.assert_allclose(np.asarray(out[0]), v, rtol=1e-5)
