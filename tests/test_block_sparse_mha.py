"""Block-sparse Pallas attention: parity vs the dense-masked reference and
density-proportional tile liveness (ref VERDICT r3 Missing #3;
deepspeed/ops/sparse_attention/matmul.py block skipping)."""

import importlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops import sparse_attention as sa

# the package re-exports same-named functions over the submodules; import
# the modules themselves for INTERPRET toggling
bsm = importlib.import_module("deepspeed_tpu.ops.pallas.block_sparse_mha")
fm = importlib.import_module("deepspeed_tpu.ops.pallas.flash_mha")


@pytest.fixture(autouse=True)
def _interpret():
    old = fm.INTERPRET
    fm.INTERPRET = True
    yield
    fm.INTERPRET = old


def _qkv(rng, b=1, h=2, s=256, d=64, hkv=None):
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, hkv or h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, hkv or h, s, d)), jnp.float32)
    return q, k, v


def _dense_ref(q, k, v, layout, block, causal):
    """Dense-masked reference through ops/sparse_attention.py (BSHD)."""
    group = q.shape[1] // k.shape[1]
    kk = jnp.repeat(k, group, axis=1)
    vv = jnp.repeat(v, group, axis=1)

    class _Cfg(sa.SparsityConfig):
        def make_layout(self, seq_len):
            return np.asarray(layout)

    cfg = _Cfg(num_heads=q.shape[1], block=block)
    out = sa.sparse_attention(q.transpose(0, 2, 1, 3),
                              kk.transpose(0, 2, 1, 3),
                              vv.transpose(0, 2, 1, 3), cfg, causal=causal,
                              impl="xla")
    return out.transpose(0, 2, 1, 3)


@pytest.mark.parametrize("causal", [False, True])
def test_fixed_layout_parity(causal):
    rng = np.random.default_rng(0)
    s, block, h = 256, 64, 2
    q, k, v = _qkv(rng, h=h, s=s)
    cfg = sa.FixedSparsityConfig(num_heads=h, block=block,
                                 num_local_blocks=2, num_global_blocks=1)
    layout = cfg.make_layout(s)
    out = bsm.block_sparse_mha(q, k, v, layout, block, causal=causal)
    ref = _dense_ref(q, k, v, layout, block, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_gqa_and_grads_parity():
    rng = np.random.default_rng(1)
    s, block, h, hkv = 256, 128, 4, 2
    q, k, v = _qkv(rng, h=h, s=s, hkv=hkv)
    cfg = sa.BigBirdSparsityConfig(num_heads=h, block=block,
                                   num_random_blocks=1,
                                   num_sliding_window_blocks=1,
                                   num_global_blocks=1)
    layout = cfg.make_layout(s)

    def f_sparse(q, k, v):
        return (bsm.block_sparse_mha(q, k, v, layout, block,
                                     causal=True) ** 2).sum()

    def f_ref(q, k, v):
        return (_dense_ref(q, k, v, layout, block, True) ** 2).sum()

    g1 = jax.grad(f_sparse, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_tile_liveness_scales_with_density():
    """The pl.when predicate (mirrored by _tile_live) must track layout
    density: compute tiles ∝ live layout blocks, and the DMA-clamp table
    repeats indices on dead steps (skipped fetches)."""
    s, block = 2048, 128
    h = 1
    nb = s // block
    for frac in (0.1, 0.5, 1.0):
        layout = np.zeros((h, nb, nb), np.int64)
        rng = np.random.default_rng(int(frac * 10))
        live_blocks = int(frac * nb * nb)
        idx = rng.choice(nb * nb, size=live_blocks, replace=False)
        layout[0].flat[idx] = 1
        live = bsm._tile_live(layout, 128, 128, block, causal=False)
        assert live.sum() == live_blocks  # kernel tile == layout block here
        pick = bsm._kv_pick(live, inner_is_k=True)
        # dead steps reuse an index → fraction of changed indices ≈ density
        changes = (np.diff(pick[0], axis=1) != 0).sum() + live[:, :, 0].sum()
        assert changes <= live_blocks + nb
    # fully-dense layout: every tile live
    assert bsm._tile_live(np.ones((1, nb, nb), np.int64), 128, 128, block,
                          causal=False).all()


def test_dense_layout_matches_flash():
    """An all-ones layout must reproduce plain flash attention."""
    rng = np.random.default_rng(2)
    s, block, h = 256, 128, 2
    q, k, v = _qkv(rng, h=h, s=s)
    layout = np.ones((h, s // block, s // block), np.int64)
    out = bsm.block_sparse_mha(q, k, v, layout, block, causal=True)
    ref = fm.flash_mha(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_sparse_attention_auto_dispatches_to_pallas(monkeypatch):
    rng = np.random.default_rng(3)
    s, block, h = 256, 128, 2
    q = jnp.asarray(rng.standard_normal((1, s, h, 64)), jnp.float32)
    called = {}
    orig = bsm.block_sparse_mha

    def spy(*a, **kw):
        called["yes"] = True
        return orig(*a, **kw)

    monkeypatch.setattr(bsm, "block_sparse_mha", spy)
    cfg = sa.FixedSparsityConfig(num_heads=h, block=block,
                                 num_local_blocks=1, num_global_blocks=1)
    sa.sparse_attention(q, q, q, cfg, causal=True)
    assert called.get("yes"), "auto dispatch did not take the Pallas kernel"
