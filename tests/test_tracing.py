"""Distributed request/step tracing + flight recorder
(telemetry/tracing.py, telemetry/flight.py; docs/OBSERVABILITY.md
"Tracing & flight recorder").

Acceptance criteria covered here:
* serve ≥ 4 concurrent requests with tracing on → the exported Chrome
  trace parses, and each request's queue_wait/prefill/decode/request
  spans share its trace_id;
* a train run's ``train.step`` spans carry the matching StepRecord step
  ids;
* a forced serve-loop hang fires the watchdog within its deadline and
  the bundle carries all-thread stacks + a non-empty span ring;
* with tracing disabled the hot path returns the shared NULL_SPAN and
  retains no allocations.
"""

import gc
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from deepspeed_tpu.telemetry.flight import (FlightRecorder, Watchdog,
                                            dump_bundle)
from deepspeed_tpu.telemetry.tracing import (EVENT_NAMES, NULL_SPAN,
                                             SPAN_NAMES, Tracer)


# ----------------------------------------------------------------------
# tracer unit behavior
# ----------------------------------------------------------------------
def test_span_export_is_wellformed_chrome_trace(tmp_path):
    tr = Tracer(enabled=True)
    tid = tr.new_trace_id()
    root = tr.span("serve.request", tid).set(uid=1)
    with tr.span("serve.queue_wait", tid, root):
        pass
    tr.instant("serve.enqueue", tid, uid=1)
    root.end(outcome="completed")

    path = tr.export_chrome_trace(str(tmp_path / "t.trace.json"))
    with open(path) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    spans = [e for e in events if e["ph"] == "X"]
    instants = [e for e in events if e["ph"] == "i"]
    metas = [e for e in events if e["ph"] == "M"]
    assert {e["name"] for e in spans} == {"serve.request",
                                          "serve.queue_wait"}
    assert all(e["args"]["trace_id"] == tid for e in spans + instants)
    assert all(e["dur"] >= 0 and e["ts"] >= 0 for e in spans)
    # parent chain: queue_wait points at the request root span
    child = next(e for e in spans if e["name"] == "serve.queue_wait")
    root_ev = next(e for e in spans if e["name"] == "serve.request")
    assert child["args"]["parent_id"] == root_ev["args"]["span_id"]
    assert root_ev["args"]["outcome"] == "completed"
    # thread metadata rows name the emitting thread
    assert any(m["name"] == "process_name" for m in metas)
    assert any(m["name"] == "thread_name" for m in metas)
    # structural validation is the same check telemetry_check ships
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "telemetry_check", os.path.join(os.path.dirname(__file__), "..",
                                        "tools", "telemetry_check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.validate_chrome_trace(path) == []


def test_export_survives_non_json_span_args(tmp_path):
    """One exotic span arg (numpy scalar, object, ...) must not abort
    the whole export at shutdown — args degrade to repr(), same contract
    as flight.dump_bundle's ring.json."""
    tr = Tracer(enabled=True)
    tr.span("serve.step").set(shape=np.int64(4), obj=object()).end()

    path = tr.export_chrome_trace(str(tmp_path / "weird.trace.json"))
    with open(path) as f:
        trace = json.load(f)
    ev = next(e for e in trace["traceEvents"] if e["ph"] == "X")
    assert "4" in str(ev["args"]["shape"])  # repr'd numpy scalar
    assert "object" in ev["args"]["obj"]


def test_span_end_idempotent_and_bounded_buffer():
    tr = Tracer(enabled=True, max_events=8)
    sp = tr.span("serve.step")
    sp.end()
    sp.end()      # double-end (crash paths) must not duplicate
    assert len(tr.snapshot()) == 1
    for _ in range(20):
        tr.span("serve.step").end()
    assert len(tr.snapshot()) == 8      # bounded
    assert tr.dropped_events == 13      # 21 emitted, 8 kept


def test_disabled_tracer_fast_path_no_allocation():
    tr = Tracer(enabled=False)
    # identity: the disabled path returns the shared singleton
    assert tr.span("serve.step") is NULL_SPAN
    assert tr.span("train.step", "tid") is NULL_SPAN
    assert NULL_SPAN.set(a=1) is NULL_SPAN
    with tr.span("serve.step") as sp:
        assert sp is NULL_SPAN
    tr.instant("serve.enqueue", "tid", uid=1)
    assert tr.snapshot() == []

    # the serve-loop hot-path shape (span + end per step) retains nothing
    gc.collect()
    before = sys.getallocatedblocks()
    for _ in range(5000):
        s = tr.span("serve.step", "tid")
        s.end()
    gc.collect()
    after = sys.getallocatedblocks()
    assert after - before < 50, f"disabled tracer leaked {after - before}"
    assert tr.snapshot() == []


def test_summary_rollup():
    tr = Tracer(enabled=True)
    for _ in range(3):
        tr.span("serve.prefill").end()
    tr.span("serve.decode").end()
    s = tr.summary()
    assert s["serve.prefill"]["count"] == 3
    assert s["serve.decode"]["count"] == 1
    assert s["serve.prefill"]["total_ms"] >= 0.0


def test_span_track_named_for_creating_thread():
    """A span created on one thread but ended on another (submit() opens
    request spans the serve loop closes) renders on a track named for
    the *creating* thread."""
    tr = Tracer(enabled=True)
    sp = tr.span("serve.request")
    t = threading.Thread(target=sp.end, name="ds-serve-loop")
    t.start()
    t.join()
    trace = tr.chrome_trace()
    names = {e["tid"]: e["args"]["name"] for e in trace["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"}
    ev = next(e for e in trace["traceEvents"] if e["ph"] == "X")
    assert names[ev["tid"]] == threading.current_thread().name


# ----------------------------------------------------------------------
# flight recorder + watchdog
# ----------------------------------------------------------------------
def test_flight_ring_bounded_keeps_newest():
    ring = FlightRecorder(capacity=4)
    tr = Tracer(enabled=True, ring=ring)
    for i in range(10):
        tr.span("serve.step").set(i=i).end()
    events = ring.snapshot()
    assert len(events) == 4
    assert [e["args"]["i"] for e in events] == [6, 7, 8, 9]


def test_make_span_recorder_tracing_only_skips_ring():
    """The shared bootstrap factory: flight alone enables span recording;
    a tracing-only config gets NO ring — nothing reads it (dump paths
    are gated on flight.enabled), so the hot path skips the per-emit
    lock + append and the 2048-event retention."""
    from deepspeed_tpu.telemetry import make_span_recorder

    tr, ring = make_span_recorder(tracing_enabled=True,
                                  flight_enabled=False)
    assert tr.enabled and ring is None
    tr.span("serve.step").end()             # ring-less emit still records
    assert len(tr.snapshot()) == 1

    tr2, ring2 = make_span_recorder(tracing_enabled=False,
                                    flight_enabled=True, ring_size=4)
    assert tr2.enabled and ring2 is not None and ring2.capacity == 4
    tr2.span("serve.step").end()
    assert len(ring2) == 1

    tr3, ring3 = make_span_recorder(tracing_enabled=False,
                                    flight_enabled=False)
    assert not tr3.enabled and ring3 is None


def test_dump_bundle_contents(tmp_path):
    ring = FlightRecorder()
    tr = Tracer(enabled=True, ring=ring)
    tr.span("serve.step").end()
    bundle = dump_bundle(str(tmp_path), "manual", ring=ring,
                         error=RuntimeError("boom"))
    files = set(os.listdir(bundle))
    assert {"manifest.json", "stacks.txt", "ring.json"} <= files
    manifest = json.load(open(os.path.join(bundle, "manifest.json")))
    assert manifest["reason"] == "manual"
    assert "boom" in manifest["error"]
    assert manifest["ring_events"] == 1
    stacks = open(os.path.join(bundle, "stacks.txt")).read()
    assert "MainThread" in stacks
    assert "test_dump_bundle_contents" in stacks  # this frame captured
    ring_doc = json.load(open(os.path.join(bundle, "ring.json")))
    assert ring_doc["events"][0]["name"] == "serve.step"


def test_watchdog_fires_within_deadline_and_rearms(tmp_path):
    ring = FlightRecorder()
    tr = Tracer(enabled=True, ring=ring)
    tr.span("train.step").end()           # something for the ring
    fired = []
    wd = Watchdog("t", deadline_s=0.2, output_dir=str(tmp_path),
                  ring=ring, tracer=tr, poll_s=0.02,
                  on_fire=fired.append).start()
    try:
        # healthy phase: beat faster than the deadline → no fire
        for _ in range(10):
            wd.beat()
            time.sleep(0.03)
        assert wd.fire_count == 0
        # stall: stop beating → exactly one bundle, within ~deadline
        t0 = time.monotonic()
        deadline = t0 + 5.0
        while wd.fire_count == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert wd.fire_count == 1
        assert time.monotonic() - t0 < 2.0      # 0.2s deadline + slack
        time.sleep(0.3)
        assert wd.fire_count == 1               # one bundle per stall
        # recovery re-arms: a new stall fires again
        wd.beat()
        time.sleep(0.5)
        assert wd.fire_count == 2
    finally:
        wd.stop()
    assert len(fired) == wd.fire_count
    bundle = fired[0]
    stacks = open(os.path.join(bundle, "stacks.txt")).read()
    assert "MainThread" in stacks               # all-thread stacks
    ring_doc = json.load(open(os.path.join(bundle, "ring.json")))
    assert len(ring_doc["events"]) > 0          # non-empty span ring
    # the stall is also visible in the trace itself
    assert any(e["name"] == "watchdog.fire" for e in tr.snapshot())


def test_watchdog_restart_after_stop_still_fires(tmp_path):
    """A stop()ed watchdog can be re-armed: start() clears the stop
    event, else the fresh thread exits on its first wait() and
    monitoring dies silently while beat()/resume() appear to work."""
    wd = Watchdog("t", deadline_s=0.2, output_dir=str(tmp_path),
                  poll_s=0.02)
    wd.resume()
    wd.stop()
    wd.resume()                     # re-arm after stop()
    try:
        t0 = time.monotonic()
        while wd.fire_count == 0 and time.monotonic() - t0 < 5.0:
            time.sleep(0.01)
        assert wd.fire_count == 1   # restarted thread really monitors
    finally:
        wd.stop()


def test_admission_block_span_not_admitted_on_close():
    """A blocking offer() woken by close() is a rejection — its
    serve.admission_block span must not claim admitted=True."""
    from deepspeed_tpu.serving.admission import (AdmissionConfig,
                                                 AdmissionController)
    from deepspeed_tpu.serving.request import (GenerationRequest, QueueFull,
                                               ResponseStream,
                                               SamplingParams)

    ctl = AdmissionController(AdmissionConfig(max_queue_size=1,
                                              queue_policy="block"))
    tr = Tracer(enabled=True)
    ctl.tracer = tr

    def req(uid):
        return GenerationRequest(uid=uid, prompt=[1, 2],
                                 params=SamplingParams(max_new_tokens=2),
                                 stream=ResponseStream(uid),
                                 trace_id=tr.new_trace_id())

    ctl.offer(req(0))                      # fills the queue
    errs = []

    def blocked_offer():
        try:
            ctl.offer(req(1), timeout=10.0)
        except QueueFull as e:
            errs.append(e)

    t = threading.Thread(target=blocked_offer)
    t.start()
    time.sleep(0.15)                       # let it block on the full queue
    ctl.close()                            # wakes the waiter → rejection
    t.join(timeout=10)
    assert len(errs) == 1
    span = next(e for e in tr.snapshot()
                if e["name"] == "serve.admission_block")
    assert span["args"]["admitted"] is False


def test_watchdog_pause_suppresses_fire(tmp_path):
    """pause() silences stall detection (inter-step gaps are not hangs);
    resume() re-arms with a fresh deadline clock."""
    wd = Watchdog("t", deadline_s=0.1, output_dir=str(tmp_path),
                  poll_s=0.02)
    wd.resume()                   # starts the thread, armed
    try:
        wd.pause()
        time.sleep(0.4)           # way past the deadline while paused
        assert wd.fire_count == 0
        wd.resume()               # fresh clock: no instant fire either
        time.sleep(0.05)
        assert wd.fire_count == 0
        deadline = time.monotonic() + 5.0
        while wd.fire_count == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert wd.fire_count == 1  # unpaused stall still detected
    finally:
        wd.stop()


def test_flight_only_config_still_populates_ring(tmp_path):
    """flight.enabled without tracing.enabled must still record spans
    into the ring (an empty ring.json defeats the flight recorder), but
    must not export a trace file."""
    from deepspeed_tpu.runtime.config import TelemetryConfig
    from deepspeed_tpu.telemetry import Telemetry

    unwanted = str(tmp_path / "explicitly_disabled.trace.json")
    tel = Telemetry(TelemetryConfig(
        enabled=True,
        # trace_path under a DISABLED tracing block: the user said no
        # trace file — flight-only span recording must not write one
        tracing={"enabled": False, "trace_path": unwanted},
        flight={"enabled": True, "deadline_s": 3600.0,
                "output_dir": str(tmp_path)}))
    assert tel.tracer.enabled
    tel.tracer.span("serve.step").end()
    assert len(tel.flight_ring) == 1
    assert tel.export_trace() is None   # tracing block disabled
    bundle = tel.dump_flight("manual")
    ring_doc = json.load(open(os.path.join(bundle, "ring.json")))
    assert len(ring_doc["events"]) == 1
    tel.close()
    assert not os.path.exists(unwanted)


# ----------------------------------------------------------------------
# serving end-to-end (acceptance)
# ----------------------------------------------------------------------
def _tiny_engine(num_blocks=64, block_size=4, max_seqs=8, budget=16,
                 max_context=64):
    from deepspeed_tpu.inference.v2 import build_engine
    from deepspeed_tpu.models import get_model_config

    model = get_model_config("llama-tiny", num_layers=1)
    eng = build_engine(
        model, {"dtype": "float32",
                "state_manager": {"max_tracked_sequences": max_seqs,
                                  "max_ragged_batch_size": budget},
                "memory_config": {"num_blocks": num_blocks,
                                  "block_size": block_size},
                "max_context": max_context}, seed=0)
    return model, eng


def test_serving_trace_e2e_four_concurrent_requests(tmp_path):
    """4 concurrent requests with tracing on: the exported trace parses,
    and each request's queue→prefill→decode→finish chain shares its
    trace_id."""
    from deepspeed_tpu.runtime.config import TelemetryConfig
    from deepspeed_tpu.serving import InferenceServer, SamplingParams
    from deepspeed_tpu.telemetry import Telemetry

    trace_path = str(tmp_path / "serve.trace.json")
    tel = Telemetry(TelemetryConfig(
        enabled=True, tracing={"enabled": True, "trace_path": trace_path}))
    model, eng = _tiny_engine()
    rng = np.random.default_rng(5)
    prompts = [rng.integers(1, model.vocab_size, size=n).tolist()
               for n in (5, 9, 3, 7)]
    srv = InferenceServer(eng, telemetry=tel).start()
    try:
        outs = {}

        def run(i):
            stream = srv.submit(prompts[i],
                                SamplingParams(max_new_tokens=6))
            outs[i] = (stream.trace_id, [t for t in stream])

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
    finally:
        srv.stop()
    tel.close()  # exports the trace

    with open(trace_path) as f:
        trace = json.load(f)
    events = [e for e in trace["traceEvents"] if e["ph"] in ("X", "i")]
    # every emitted name comes from the frozen vocabulary
    assert {e["name"] for e in events} <= set(SPAN_NAMES) | set(EVENT_NAMES)
    by_trace = {}
    for e in events:
        by_trace.setdefault(e["args"].get("trace_id"), []).append(e)
    for i in range(4):
        trace_id, toks = outs[i]
        assert trace_id and len(toks) == 6
        names = [e["name"] for e in by_trace[trace_id]]
        for want in ("serve.request", "serve.queue_wait", "serve.prefill",
                     "serve.decode", "serve.enqueue", "serve.first_token",
                     "serve.finish"):
            assert want in names, (want, sorted(set(names)))
        root = next(e for e in by_trace[trace_id]
                    if e["name"] == "serve.request")
        assert root["args"]["outcome"] == "completed"
        assert root["args"]["generated"] == 6
        # phases nest inside the request span's window
        for e in by_trace[trace_id]:
            if e["ph"] == "X" and e["name"] != "serve.request":
                assert e["ts"] >= root["ts"] - 1.0
                assert e["ts"] + e["dur"] <= root["ts"] + root["dur"] + 1.0
        # one serve.emit instant per streamed token
        assert sum(1 for e in by_trace[trace_id]
                   if e["name"] == "serve.emit") == 6
    # loop-level step spans exist and engine dispatches joined the trace
    step_names = {e["name"] for e in events}
    assert "serve.step" in step_names
    assert "v2.ragged_step" in step_names


def test_serve_loop_hang_fires_watchdog_with_forensics(tmp_path):
    """Forced hang: the watchdog fires within its deadline; the bundle
    has all-thread stacks (including the wedged serve loop) and a
    non-empty span ring."""
    from deepspeed_tpu.serving import InferenceServer, SamplingParams

    model, eng = _tiny_engine()
    release = threading.Event()
    orig_step = eng.step

    def hang(*a, **kw):
        release.wait(30)
        return orig_step(*a, **kw)

    flight_dir = str(tmp_path / "flight")
    srv = InferenceServer(eng, {
        "tracing": {"enabled": True},
        "flight": {"enabled": True, "deadline_s": 0.3, "poll_s": 0.05,
                   "output_dir": flight_dir}}).start()
    try:
        rng = np.random.default_rng(0)
        prompt = rng.integers(1, model.vocab_size, size=5).tolist()
        # warm request: the process's first engine.step pays the jit
        # compile and is deliberately unmonitored — it must complete
        # without the watchdog reporting the compile as a hang
        srv.submit(prompt, SamplingParams(max_new_tokens=1)).result(
            timeout=120)
        assert srv._watchdog.fire_count == 0
        eng.step = hang
        t0 = time.monotonic()
        stream = srv.submit(prompt, SamplingParams(max_new_tokens=2))
        while srv._watchdog.fire_count == 0 \
                and time.monotonic() - t0 < 10.0:
            time.sleep(0.02)
        assert srv._watchdog.fire_count >= 1
        assert time.monotonic() - t0 < 5.0      # deadline 0.3s + slack
        bundle = srv._watchdog.bundles[0]
        manifest = json.load(open(os.path.join(bundle, "manifest.json")))
        assert manifest["reason"] == "watchdog"
        assert manifest["stalled_s"] >= 0.3
        stacks = open(os.path.join(bundle, "stacks.txt")).read()
        assert "ds-serve-loop" in stacks        # the wedged thread
        assert "hang" in stacks                 # ...inside the fake step
        ring_doc = json.load(open(os.path.join(bundle, "ring.json")))
        assert len(ring_doc["events"]) > 0      # enqueue/admit spans
        assert srv.metrics.flight_dumps >= 1
    finally:
        release.set()
        stream.result(timeout=60)
        srv.stop()


def test_first_step_kv_exhaustion_keeps_compile_skip(tmp_path):
    """A first engine.step that exits with KVCacheExhausted ran nothing
    (scheduler rolled back), so it must NOT consume the per-process
    first-compile watchdog skip — the retry is the step that actually
    pays the jit compile and still needs the watchdog disarmed."""
    from deepspeed_tpu.inference.v2.ragged import KVCacheExhausted
    from deepspeed_tpu.serving import (InferenceServer, SamplingParams,
                                       ServingError)

    model, eng = _tiny_engine()
    orig_step = eng.step
    paused_at_call = []

    def exhaust_first(*a, **kw):
        paused_at_call.append(srv._watchdog._paused)
        if len(paused_at_call) == 1:
            raise KVCacheExhausted("synthetic: no pages")
        return orig_step(*a, **kw)

    eng.step = exhaust_first
    srv = InferenceServer(eng, {
        "flight": {"enabled": True, "deadline_s": 300.0,
                   "output_dir": str(tmp_path / "flight")}}).start()
    try:
        rng = np.random.default_rng(0)
        prompt = rng.integers(1, model.vocab_size, size=4).tolist()
        # only runner + exhaustion => _preempt_one fails it fast
        with pytest.raises(ServingError):
            srv.submit(prompt, SamplingParams(max_new_tokens=2)).result(
                timeout=60)
        # the real first compile happens on this request's steps
        srv.submit(prompt, SamplingParams(max_new_tokens=2)).result(
            timeout=120)
    finally:
        eng.step = orig_step
        srv.stop()
    assert len(paused_at_call) >= 3
    assert paused_at_call[0]      # warm skip armed for the exhausted try
    assert paused_at_call[1]      # ...and STILL armed for the real compile
    assert not paused_at_call[2]  # consumed once a step actually ran
    assert srv._watchdog.fire_count == 0


def test_serve_loop_crash_writes_flight_bundle(tmp_path, monkeypatch):
    """The crash handler leaves the same forensics bundle behind."""
    from deepspeed_tpu.serving import (InferenceServer, SamplingParams,
                                       ServingError)

    model, eng = _tiny_engine()
    flight_dir = str(tmp_path / "flight")
    srv = InferenceServer(eng, {
        "tracing": {"enabled": True},
        "flight": {"enabled": True, "deadline_s": 30.0,
                   "output_dir": flight_dir}}).start()
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, model.vocab_size, size=4).tolist()

    def boom(*a, **kw):
        raise RuntimeError("injected engine failure")

    monkeypatch.setattr(eng, "step", boom)
    s = srv.submit(prompt, SamplingParams(max_new_tokens=4))
    with pytest.raises(ServingError):
        s.result(timeout=60)
    bundles = [d for d in os.listdir(flight_dir)
               if d.startswith("flight_serve_crash")]
    assert len(bundles) == 1
    manifest = json.load(
        open(os.path.join(flight_dir, bundles[0], "manifest.json")))
    assert manifest["reason"] == "serve_crash"
    assert "injected engine failure" in manifest["error"]
    with pytest.raises(RuntimeError, match="serve loop died"):
        srv.stop()
    assert srv.metrics.flight_dumps == 1
    # the crash handler paused the watchdog: the dead loop's missing
    # heartbeats must not echo the crash as a second 'watchdog' bundle
    assert srv._watchdog._paused
    assert srv._watchdog.fire_count == 0


def test_hub_flight_config_wins_over_server_blocks(tmp_path):
    """With a telemetry hub passed, the server's own tracing/flight
    blocks are ignored — a server-level flight block paired with the
    hub's disabled tracer would dump forever-empty rings."""
    from deepspeed_tpu.runtime.config import TelemetryConfig
    from deepspeed_tpu.serving import InferenceServer
    from deepspeed_tpu.telemetry import Telemetry

    tel = Telemetry(TelemetryConfig(enabled=True))   # no tracing, no flight
    model, eng = _tiny_engine()
    srv = InferenceServer(
        eng, {"flight": {"enabled": True, "deadline_s": 0.1,
                         "output_dir": str(tmp_path)}}, telemetry=tel)
    assert srv.tracer is tel.tracer
    assert srv._watchdog is None            # hub has no flight block
    tel.close()


def test_hubless_watchdog_defaults_match_hub_factory(tmp_path):
    """The hub-less server wires its watchdog through the same
    make_watchdog factory as the hub: falsy config values (deadline_s 0,
    empty output_dir) fall back instead of producing a 0-second deadline
    that fires on a healthy idle loop and dumps bundles into cwd."""
    from deepspeed_tpu.serving import InferenceServer

    _, eng = _tiny_engine()
    srv = InferenceServer(eng, {
        "flight": {"enabled": True, "deadline_s": 0, "output_dir": ""}})
    assert srv._watchdog is not None
    assert srv._watchdog.deadline_s == 60.0
    assert srv._flight_dir == "./dstpu_flight"
    assert srv._watchdog.output_dir == "./dstpu_flight"


# ----------------------------------------------------------------------
# training side (acceptance: spans ↔ StepRecords)
# ----------------------------------------------------------------------
def test_train_step_spans_match_step_records(tmp_path):
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.telemetry import read_jsonl

    jsonl = str(tmp_path / "steps.jsonl")
    trace_path = str(tmp_path / "train.trace.json")
    model = get_model_config("gpt2-tiny")
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "steps_per_print": 10_000,
        "telemetry": {
            "enabled": True, "jsonl_path": jsonl, "measure_flops": False,
            "tracing": {"enabled": True, "trace_path": trace_path},
        },
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(8, 33), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1],
             "labels": ids[:, 1:].astype(np.int32)}
    for _ in range(3):
        loss = engine.train_batch(batch)
    assert np.isfinite(float(np.asarray(loss)))
    engine.destroy()          # telemetry.close() exports the trace

    record_steps = [r["step"] for r in read_jsonl(jsonl)]
    assert record_steps == [1, 2, 3]
    with open(trace_path) as f:
        events = [e for e in json.load(f)["traceEvents"]
                  if e["ph"] in ("X", "i")]
    assert {e["name"] for e in events} <= set(SPAN_NAMES) | set(EVENT_NAMES)
    step_spans = [e for e in events if e["name"] == "train.step"]
    # cross-link: span step args == the StepRecord step ids, 1:1
    assert [e["args"]["step"] for e in step_spans] == record_steps
    # all train spans share the engine's run trace id
    trace_ids = {e["args"]["trace_id"] for e in events}
    assert len(trace_ids) == 1
    names = {e["name"] for e in events}
    assert {"train.data_ingest", "train.dispatch", "train.sync",
            "train.telemetry"} <= names
    # phase spans nest inside their step span
    for phase in (e for e in events
                  if e["ph"] == "X" and e["name"] != "train.step"):
        parent = phase["args"].get("parent_id")
        assert any(s["args"]["span_id"] == parent for s in step_spans)


def test_train_watchdog_skips_first_step_after_checkpoint_resume(
        tmp_path, monkeypatch):
    """The first ``train_batch`` of a *process* pays the full XLA compile
    even when ``global_steps`` was restored from a checkpoint — the
    watchdog must stay disarmed for it (the guard is per-process, not
    ``global_steps``), else a resume writes a spurious hang bundle."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config

    model = get_model_config("gpt2-tiny")
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "steps_per_print": 10_000,
        "telemetry": {
            "enabled": True,
            "flight": {"enabled": True, "deadline_s": 3600.0,
                       "output_dir": str(tmp_path / "flight")},
        },
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    try:
        assert engine._watchdog is not None
        engine.global_steps = 1000      # what load_checkpoint restores
        resumes = []
        orig_resume = engine._watchdog.resume
        monkeypatch.setattr(
            engine._watchdog, "resume",
            lambda: (resumes.append(1), orig_resume())[1])
        rng = np.random.default_rng(0)
        ids = rng.integers(0, model.vocab_size, size=(8, 33),
                           dtype=np.int32)
        batch = {"input_ids": ids[:, :-1],
                 "labels": ids[:, 1:].astype(np.int32)}
        engine.train_batch(batch)
        assert resumes == []            # compile step: never armed
        engine.train_batch(batch)
        assert resumes == [1]           # second step: armed as usual
    finally:
        engine.destroy()


def test_engine_destroy_during_exception_dumps_bundle(tmp_path):
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config

    flight_dir = str(tmp_path / "flight")
    model = get_model_config("gpt2-tiny")
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "steps_per_print": 10_000,
        "telemetry": {
            "enabled": True,
            "tracing": {"enabled": True},
            "flight": {"enabled": True, "deadline_s": 3600.0,
                       "output_dir": flight_dir},
        },
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    try:
        try:
            raise RuntimeError("train step blew up")
        finally:
            engine.destroy()    # the usual `finally: destroy()` pattern
    except RuntimeError:
        pass
    bundles = [d for d in os.listdir(flight_dir)
               if d.startswith("flight_engine_crash")]
    assert len(bundles) == 1
    manifest = json.load(
        open(os.path.join(flight_dir, bundles[0], "manifest.json")))
    assert "train step blew up" in manifest["error"]
