"""Static graph auditor (deepspeed_tpu/analysis; docs/STATIC_ANALYSIS.md).

Covers the frozen report schema, each planted defect class (implicit
resharding, donation miss, host callback, fp32-wire-on-quantized-path,
recompile hazard, seam violation), golden-census stability, the engine
donation-fix regression, and the tier-1 gate: every bench-row step
config audits with zero unbaselined high-severity findings on the
virtual 8-device CPU mesh.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.analysis import (AUDIT_REPORT_KEYS, Finding,
                                    GraphAuditReport, load_baseline)
from deepspeed_tpu.analysis.auditor import AuditIntent, audit
from deepspeed_tpu.analysis.seam import lint_repo, lint_source

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mesh(shape=(8,), names=("data",)):
    devs = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


# ----------------------------------------------------------------------
# report schema / baseline machinery
# ----------------------------------------------------------------------
def test_report_schema_frozen_and_sorted():
    rep = GraphAuditReport(label="x")
    d = rep.to_dict()
    assert sorted(d.keys()) == sorted(AUDIT_REPORT_KEYS)
    line = rep.to_json()
    assert list(json.loads(line).keys()) == sorted(d.keys())
    assert d["schema"] == 1


def test_finding_vocab_rejected():
    with pytest.raises(ValueError, match="unknown finding kind"):
        Finding(kind="nonsense", severity="high", message="m")
    with pytest.raises(ValueError, match="unknown severity"):
        Finding(kind="donation_miss", severity="fatal", message="m")


def test_fingerprint_stable_and_baseline_suppression(tmp_path):
    f1 = Finding(kind="donation_miss", severity="high", message="run A: "
                 "12345 bytes", where="step", detail={"key": "(4,4):f32"})
    f2 = Finding(kind="donation_miss", severity="high", message="run B: "
                 "99999 bytes", where="step", detail={"key": "(4,4):f32"})
    # messages differ (byte counts drift), fingerprints must not
    assert f1.fingerprint() == f2.fingerprint()
    rep = GraphAuditReport(label="x", findings=[f1])
    assert [f.kind for f in rep.high_findings()] == ["donation_miss"]
    assert rep.high_findings(baseline={f1.fingerprint()}) == []
    # missing baseline file = empty baseline, never an error
    assert load_baseline(str(tmp_path / "nope.json")) == frozenset()
    p = tmp_path / "b.json"
    p.write_text(json.dumps({"suppress": [f1.fingerprint()]}))
    assert rep.high_findings(load_baseline(str(p))) == []


# ----------------------------------------------------------------------
# census
# ----------------------------------------------------------------------
def test_census_detects_collectives_with_bytes():
    from deepspeed_tpu.utils.jax_compat import shard_map

    mesh = _mesh()
    fn = jax.jit(shard_map(lambda x: jax.lax.psum(x, "data"), mesh=mesh,
                           in_specs=(P("data"),), out_specs=P("data")))
    rep = audit(fn, jnp.zeros((8, 4096), jnp.float32), label="psum",
                intent=AuditIntent(expected=frozenset({"all-reduce"})))
    kinds = {c.kind: c for c in rep.census}
    assert "all-reduce" in kinds
    ar = kinds["all-reduce"]
    assert ar.count >= 1 and ar.payload_bytes > 0 and ar.wire_bytes > 0
    assert ar.group_size == 8 and "f32" in ar.dtype
    assert rep.high_findings() == []


def test_census_stable_across_jit_of_same_config():
    mesh = _mesh()
    sh = NamedSharding(mesh, P("data", None))

    def step(x):
        return (x @ x.T).sum()

    reps = [audit(jax.jit(step, in_shardings=(sh,)),
                  jnp.zeros((64, 64)), label="golden") for _ in range(2)]
    assert [c.to_dict() for c in reps[0].census] \
        == [c.to_dict() for c in reps[1].census]
    assert reps[0].census_summary() == reps[1].census_summary()


def test_planted_implicit_resharding_detected():
    mesh = _mesh((4, 2), ("data", "tensor"))

    def step(x):
        y = x * 2
        # nobody "declared" this layout flip: GSPMD must insert a
        # resharding collective to satisfy it
        y = jax.lax.with_sharding_constraint(
            y, NamedSharding(mesh, P(None, "data")))
        return y.sum()

    fn = jax.jit(step, in_shardings=(
        NamedSharding(mesh, P("data", None)),))
    x = jnp.zeros((1024, 1024))
    rep = audit(fn, x, label="planted", intent=AuditIntent())
    highs = rep.high_findings()
    assert any(f.kind == "implicit_resharding" for f in highs), \
        [f.to_dict() for f in rep.findings]
    # the same graph under an intent that EXPECTS the transition is clean
    ok = audit(fn, x, label="declared", intent=AuditIntent(
        expected=frozenset({"all-to-all", "all-reduce",
                            "collective-permute", "all-gather",
                            "reduce-scatter"})))
    assert ok.high_findings() == []


def test_wire_dtype_mismatch_on_quantized_intent():
    from deepspeed_tpu.utils.jax_compat import shard_map

    mesh = _mesh()
    fn = jax.jit(shard_map(lambda g: jax.lax.psum(g, "data"), mesh=mesh,
                           in_specs=(P(),), out_specs=P()))
    g = jnp.zeros((256, 256), jnp.float32)   # 256 KB fp32 "grad reduce"
    intent = AuditIntent(expected=frozenset({"all-reduce"}),
                         banned={"all-reduce": ("f32",)})
    rep = audit(fn, g, label="quantized_path", intent=intent)
    assert any(f.kind == "wire_dtype_mismatch" and f.severity == "high"
               for f in rep.findings), [f.to_dict() for f in rep.findings]


def test_required_collective_absent_is_mismatch():
    rep = audit(jax.jit(lambda x: x + 1), jnp.zeros((4,)), label="local",
                intent=AuditIntent(required={"collective-permute": ()}))
    assert any(f.kind == "collective_mismatch" for f in rep.findings)
    assert rep.high_findings() == []   # warning, not high


# ----------------------------------------------------------------------
# donation
# ----------------------------------------------------------------------
def test_planted_donation_miss_detected():
    def step(a, b):
        return a + 1.0, (a * b).astype(jnp.bfloat16)  # b can never alias

    fn = jax.jit(step, donate_argnums=(0, 1))
    rep = audit(fn, jnp.zeros((256, 256)), jnp.zeros((256, 256)),
                label="planted_donation")
    assert rep.donation["declared"] == 2
    assert rep.donation["aliased"] == 1
    assert rep.donation["missed_bytes"] == 256 * 256 * 4
    misses = [f for f in rep.findings if f.kind == "donation_miss"]
    assert misses and misses[0].severity == "high"
    # the honorable version is clean
    ok = audit(jax.jit(lambda a: a * 2, donate_argnums=(0,)),
               jnp.zeros((256, 256)), label="ok_donation")
    assert ok.donation["declared"] == 1 == ok.donation["aliased"]
    assert not [f for f in ok.findings if f.kind == "donation_miss"]


def test_engine_apply_step_donation_fully_aliased():
    """Regression for the donation fix: apply_step now returns the
    donated grad buffer zeroed in place, so EVERY declared donation
    aliases — the full fp32 gradient tree no longer rides the update as
    a dead buffer, and step() recycles it into the next round."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config

    model = get_model_config("gpt2-tiny", max_seq_len=64)
    engine, _, _, _ = ds.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2}, "steps_per_print": 10_000,
        "mesh": {"data": jax.device_count()}})
    try:
        grads = engine._zero_grads_jit()
        rep = audit(engine._apply_step_jit, engine.params,
                    engine.opt_state, engine.loss_scale_state, grads,
                    jnp.float32(1e-3), label="apply_step")
        assert rep.donation["declared"] == rep.donation["aliased"] > 0, \
            rep.donation
        assert not [f for f in rep.findings if f.kind == "donation_miss"]
        # trio path: the buffer comes back zeroed and seeds round 2
        rng = np.random.default_rng(0)
        ids = rng.integers(0, model.vocab_size,
                           size=(jax.device_count(), 65), dtype=np.int32)
        mb = {"input_ids": ids[:, :64],
              "labels": ids[:, 1:].astype(np.int32)}
        for _ in range(2):
            for _ in range(engine.gradient_accumulation_steps_value):
                loss = engine.forward(mb)
                engine.backward()
            engine.step()
        assert np.isfinite(float(np.asarray(loss)))
        assert engine._grad_buffer is not None
        total = sum(float(np.asarray(jnp.abs(leaf).sum()))
                    for leaf in jax.tree_util.tree_leaves(
                        engine._grad_buffer))
        assert total == 0.0
    finally:
        engine.destroy()


# ----------------------------------------------------------------------
# hot-path hygiene
# ----------------------------------------------------------------------
def test_planted_host_callback_detected():
    def step(x):
        jax.pure_callback(lambda v: v, jax.ShapeDtypeStruct((), x.dtype),
                          x.sum())
        return x * 2

    rep = audit(jax.jit(step), jnp.zeros((8,)), label="cb")
    cbs = [f for f in rep.findings if f.kind == "host_callback"]
    assert cbs and cbs[0].severity == "high"

    def dbg(x):
        jax.debug.print("x={x}", x=x.sum())
        return x * 2

    rep2 = audit(jax.jit(dbg), jnp.zeros((8,)), label="dbg")
    cbs2 = [f for f in rep2.findings if f.kind == "host_callback"]
    assert cbs2 and cbs2[0].severity == "warning"   # async, degraded


def test_recompile_hazard_python_scalar():
    rep = audit(jax.jit(lambda x, s: x * s), jnp.zeros((4,)), 2.0,
                label="scalar")
    hz = [f for f in rep.findings if f.kind == "recompile_hazard"]
    assert hz and "float" in hz[0].detail["what"]
    clean = audit(jax.jit(lambda x, s: x * s), jnp.zeros((4,)),
                  jnp.float32(2.0), label="array_scalar")
    assert not [f for f in clean.findings
                if f.kind == "recompile_hazard"]


def test_dtype_promotion_reported_in_bf16_step():
    def step(x):
        return (x.astype(jnp.float32) @ x.astype(jnp.float32).T).sum()

    rep = audit(jax.jit(step), jnp.zeros((128, 128), jnp.bfloat16),
                label="promo", intent=AuditIntent(compute_dtype="bf16"))
    promos = [f for f in rep.findings if f.kind == "dtype_promotion"]
    assert promos and promos[0].detail["bytes"] > 0
    # fp32 compute never reports promotions
    rep2 = audit(jax.jit(step), jnp.zeros((128, 128), jnp.bfloat16),
                 label="promo_fp32", intent=AuditIntent())
    assert not [f for f in rep2.findings if f.kind == "dtype_promotion"]


# ----------------------------------------------------------------------
# HLO parser units (no jax needed beyond text fixtures)
# ----------------------------------------------------------------------
def test_hlo_parsers_on_synthetic_text():
    from deepspeed_tpu.analysis.hlo import (parse_collectives,
                                            parse_input_output_alias,
                                            wire_bytes)

    hlo = """HloModule jit_step, is_scheduled=true, input_output_alias={ {0}: (0, {}, may-alias), {1}: (2, {}, may-alias) }, entry_computation_layout={(f32[8]{0})->f32[8]{0}}

ENTRY %main (p0: f32[8]) -> f32[8] {
  %p0 = f32[8]{0} parameter(0)
  %all-reduce.1 = f32[8]{0} all-reduce(f32[8]{0} %p0), channel_id=1, replica_groups=[1,8]<=[8], to_apply=%add
  %ags = (f32[128,8]{1,0}, f32[1024,8]{1,0}) all-gather-start(f32[128,8]{1,0} %x), replica_groups=[4,2]<=[8], dimensions={0}
  %agd = f32[1024,8]{1,0} all-gather-done((f32[128,8]{1,0}, f32[1024,8]{1,0}) %ags)
  ROOT %cp = bf16[16,4]{1,0} collective-permute(bf16[16,4]{1,0} %x), source_target_pairs={{0,1}}
}
"""
    ops = parse_collectives(hlo, num_partitions=8)
    # the async pair counts ONCE, priced off the -done op's RESULT type
    # (the -start tuple also contains the operand — would inflate bytes)
    # but with the -start line's replica_groups (subgroup of 2, not the
    # 8-partition fallback)
    assert [(o["kind"], o["dtype"]) for o in ops] == \
        [("all-reduce", "f32"), ("all-gather", "f32"),
         ("collective-permute", "bf16")]
    assert ops[1]["payload_bytes"] == 1024 * 8 * 4
    assert ops[1]["group_size"] == 2
    assert ops[0]["payload_bytes"] == 32
    assert ops[0]["wire_bytes"] == wire_bytes("all-reduce", 32, 8)
    assert ops[2]["payload_bytes"] == 128 and ops[2]["wire_bytes"] == 128
    assert parse_input_output_alias(hlo) == {0: "0", 2: "1"}
    assert wire_bytes("all-gather", 800, 8) == 700
    assert wire_bytes("all-reduce", 800, 1) == 0


# ----------------------------------------------------------------------
# seam lint
# ----------------------------------------------------------------------
def test_seam_lint_repo_is_clean():
    findings = lint_repo(REPO)
    assert findings == [], [f.to_dict() for f in findings]


def test_seam_lint_detects_planted_violations():
    planted = (
        "from jax.experimental.shard_map import shard_map\n"
        "import jax\n"
        "def f():\n"
        "    sp = jax.memory.Space.Host\n"
        "    from jax._src import core\n"
        "    return jax.shard_map, getattr(None, 'TPUCompilerParams')\n")
    found = lint_source(planted, "deepspeed_tpu/planted.py")
    keys = {f.detail["key"] for f in found}
    assert {"jax.experimental.shard_map.shard_map", "jax.memory",
            "jax._src.core", "jax.shard_map",
            "TPUCompilerParams"} <= keys
    assert all(f.severity == "high" for f in found)
    # the allowlist suppresses exactly the named symbol, nothing else
    allowed = lint_source(planted, "deepspeed_tpu/planted.py",
                          allow={"deepspeed_tpu/planted.py::jax.memory"})
    assert "jax.memory" not in {f.detail["key"] for f in allowed}
    assert len(allowed) == len(found) - 1
    # jax_compat itself is exempt — it IS the seam
    assert lint_source(planted, "deepspeed_tpu/utils/jax_compat.py") == []


# ----------------------------------------------------------------------
# scheduler evidence integration
# ----------------------------------------------------------------------
def test_pre_census_pinned_records_still_load():
    """Back-compat: a step_schedule pinned BEFORE static_census joined
    the frozen evidence keys must keep loading (pinned-mode
    reproducibility) — the absent census defaults to None, exactly what
    a failed audit records.  Empty evidence is still rejected."""
    from deepspeed_tpu.autotuning.overlap_scheduler import ScheduleDecision

    old = {"decision": "zero3_prefetch",
           "knobs": {"gather_prefetch_depth": 2},
           "evidence": {"dominant_collective": "all-gather",
                        "exposed_comm_ms": 1.2, "overlap_fraction": 0.3,
                        "overlap_source": "spans", "probe_step": 4}}
    d = ScheduleDecision.from_dict(old)
    assert d.evidence["static_census"] is None
    with pytest.raises(ValueError, match="missing"):
        ScheduleDecision.from_dict({"decision": "noop", "evidence": {}})


def test_scheduler_evidence_carries_static_census():
    from deepspeed_tpu.autotuning.overlap_scheduler import (EVIDENCE_KEYS,
                                                            extract_evidence)

    assert "static_census" in EVIDENCE_KEYS
    census = {"all-gather": {"count": 3, "wire_bytes": 123,
                             "dtypes": ["f32"]}}
    rep = {"devices": {"d0": {"collective_ms": 1.0}},
           "overlap_fraction": 0.4, "step": 4, "static_census": census}
    ev = extract_evidence(rep, {"zero_stage": 3})
    assert sorted(ev) == sorted(EVIDENCE_KEYS)
    assert ev["static_census"] == census
    # absent census degrades to None, never a KeyError
    rep.pop("static_census")
    assert extract_evidence(rep, {})["static_census"] is None


# ----------------------------------------------------------------------
# the tier-1 gate: every bench-row step config audits clean — the graph
# audit AND the memory-plan audit, both off ONE shared lowering
# ----------------------------------------------------------------------
@pytest.mark.parametrize("name", sorted(
    __import__("deepspeed_tpu.analysis.targets",
               fromlist=["BENCH_AUDIT_TARGETS"]).BENCH_AUDIT_TARGETS))
def test_bench_row_static_audit_clean(name):
    import jax as _jax

    from deepspeed_tpu.analysis import load_memory_baseline
    from deepspeed_tpu.analysis.targets import run_target_audits

    baseline = load_baseline(
        os.path.join(REPO, "tools", "graft_lint_baseline.json"))
    mem_base = load_memory_baseline(
        os.path.join(REPO, "tools", "memory_baseline.json"))
    budget = mem_base["budgets"].get(name, {}).get(
        _jax.default_backend())
    rep, mem = run_target_audits(name, memory=True, budget=budget)
    assert rep.to_dict()["schema"] == 1
    highs = rep.high_findings(baseline)
    assert highs == [], [f.to_dict() for f in highs]
    # donation contract: whatever a step declares, XLA aliased
    assert rep.donation["declared"] == rep.donation["aliased"], \
        rep.donation
    if name.startswith("train_"):
        assert rep.census, "a dp=8 train step with no collectives?"
    if name == "ring_attention":
        assert any(c.kind == "collective-permute" for c in rep.census)
    if name == "train_commquant":
        a2a = [c for c in rep.census if c.kind == "all-to-all"
               and "s8" in c.dtype]
        assert a2a, "int8 wire missing from the quantized reduce"
    # memory gate: zero unbaselined highs against the committed budgets
    mem_highs = mem.high_findings(baseline)
    assert mem_highs == [], [f.to_dict() for f in mem_highs]
    assert mem.totals["peak_bytes"] > 0 and mem.buffers, mem.totals
    assert budget is not None, \
        f"no frozen cpu budget for {name} — run graft_lint --memory " \
        "--write-baseline and commit tools/memory_baseline.json"


def test_graft_lint_cli_seam_only(tmp_path):
    """CLI plumbing: --seam runs AST-only (no backend churn), exits 0 on
    the clean tree, and --json writes a well-formed dump."""
    import importlib.util

    path = os.path.join(REPO, "tools", "graft_lint.py")
    spec = importlib.util.spec_from_file_location("graft_lint_mod", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = str(tmp_path / "lint.json")
    rc = mod.main(["--seam", "--json", out])
    assert rc == 0
    with open(out, "r", encoding="utf-8") as f:
        data = json.load(f)
    assert data["unbaselined_high"] == []
    assert isinstance(data["findings"], list)
