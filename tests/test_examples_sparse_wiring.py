"""Examples entry point + sparse attention wired through the model config."""

import json

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models import get_model_config, init_params
from deepspeed_tpu.models import transformer as tf


def _reset_topo():
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None


def test_train_lm_example_runs(tmp_path):
    import examples.train_lm as ex

    rc = ex.main(["--model", "gpt2-tiny", "--steps", "3", "--seq", "32",
                  "--save_dir", str(tmp_path / "ck")])
    assert rc == 0
    assert (tmp_path / "ck" / "latest").exists()
    _reset_topo()


def test_example_config_parses(tmp_path):
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    for name in ("examples/ds_config_zero3_bf16.json",
                 "examples/ds_config_offload.json"):
        with open(name) as f:
            d = json.load(f)
        d.pop("mesh", None)  # parse-only: don't need 8 devices here
        cfg = DeepSpeedConfig(d, world_size=1)
        assert cfg.train_micro_batch_size_per_gpu >= 1


def test_sparse_attention_wired_into_model():
    cfg = get_model_config("gpt2-tiny").replace(
        dtype=jnp.float32, attn_impl="sparse",
        sparse_attention={"mode": "bslongformer", "block": 8,
                          "num_sliding_window_blocks": 3,
                          "global_block_indices": [0]})
    params = init_params(cfg, jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(2, 64)), jnp.int32)
    out = tf.forward(params, ids, cfg)
    assert out.shape == (2, 64, cfg.vocab_size)
    assert np.isfinite(np.asarray(out, np.float32)).all()
    # sparse ≠ dense attention output (mask actually applied)
    dense = tf.forward(params, ids, cfg.replace(attn_impl="xla",
                                                sparse_attention=None))
    assert np.abs(np.asarray(out) - np.asarray(dense)).max() > 1e-4
    # grads flow
    g = jax.grad(lambda p: tf.loss_fn(
        p, {"input_ids": ids, "labels": ids}, cfg))(params)
    assert np.isfinite(float(jax.tree.leaves(g)[0].sum()))
