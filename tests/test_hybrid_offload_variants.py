"""Hybrid engine (RLHF train<->generate), ZenFlow, SuperOffload.

Mirrors reference coverage: tests/unit/hybrid_engine/, runtime/zenflow
tests, superoffload stage3 tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import get_model_config
from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedHybridEngine
from deepspeed_tpu.runtime.superoffload import SuperOffloadOptimizer
from deepspeed_tpu.runtime.zenflow import ZenFlowOptimizer


def _reset_topo():
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None


def test_hybrid_engine_train_generate_shared_weights():
    model = get_model_config("gpt2-tiny")
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
           "mesh": {"data": 1}}
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    he = DeepSpeedHybridEngine(engine)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, model.vocab_size, size=(2, 4), dtype=np.int32)

    he.eval()
    out1 = he.generate(prompt, max_new_tokens=3)
    assert out1.shape == (2, 7)

    # train a few steps — generation must see the UPDATED weights
    he.train()
    ids = rng.integers(0, model.vocab_size, size=(2, 9), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
    for _ in range(3):
        he.train_batch(batch)
    he.eval()
    out2 = he.generate(prompt, max_new_tokens=3)
    assert out2.shape == (2, 7)
    stats = he.stats()
    assert stats["generated_tokens"] == 12 and stats["generate_seconds"] > 0
    # weights changed → decode path reads live training params (token ids
    # may or may not differ; check the underlying logits moved)
    from deepspeed_tpu.models import transformer as tf_model

    l1 = jax.jit(lambda p, i: tf_model.forward(p, i, engine.model_config))(
        engine.params, jnp.asarray(prompt))
    assert np.isfinite(np.asarray(l1, np.float32)).all()
    _reset_topo()


def _quadratic_problem(seed=0, n=32, d=16):
    rng = np.random.default_rng(seed)
    target = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
    params = {"w": jnp.zeros((n, d), jnp.float32),
              "b": jnp.zeros((d,), jnp.float32)}

    def loss_fn(p):
        return ((p["w"] - target) ** 2).sum() + (p["b"] - 1.0).pow(2).sum() \
            if hasattr(jnp.zeros(1), "pow") else \
            ((p["w"] - target) ** 2).sum() + ((p["b"] - 1.0) ** 2).sum()

    return params, target, jax.jit(jax.value_and_grad(loss_fn))


def test_zenflow_converges_on_quadratic():
    params, target, vg = _quadratic_problem()
    opt = ZenFlowOptimizer(params, lr=0.05, topk_ratio=0.25,
                           update_interval=2, overlap=False)
    l0, _ = vg(params)
    for _ in range(60):
        _, g = vg(params)
        params = opt.step(params, g)
    params = opt.flush(params)
    l1, _ = vg(params)
    assert float(l1) < float(l0) * 0.2  # both hot and cold entries moved
    # bias (vector, all-cold) must also have moved toward 1.0
    assert float(jnp.abs(params["b"] - 1.0).mean()) < 0.9


def test_zenflow_hot_columns_update_immediately():
    params = {"w": jnp.zeros((4, 8), jnp.float32)}
    g = {"w": jnp.zeros((4, 8), jnp.float32).at[:, 2].set(5.0)}
    opt = ZenFlowOptimizer(params, lr=0.1, topk_ratio=0.125,
                           update_interval=100, overlap=False)
    new = opt.step(params, g)
    w = np.asarray(new["w"])
    assert np.abs(w[:, 2]).max() > 0  # hot column updated now
    assert np.abs(np.delete(w, 2, axis=1)).max() == 0  # cold untouched yet


def test_zenflow_overlap_thread_lands():
    params = {"w": jnp.zeros((4, 8), jnp.float32)}
    opt = ZenFlowOptimizer(params, lr=0.1, topk_ratio=0.125,
                           update_interval=1, overlap=True)
    g = {"w": jnp.ones((4, 8), jnp.float32)}
    p1 = opt.step(params, g)      # schedules async cold update
    p2 = opt.step(p1, g)          # waits + applies pending delta
    w = np.asarray(p2["w"])
    assert (np.abs(w) > 0).mean() > 0.9  # cold columns landed too


def test_zenflow_save_resume_trajectory_parity():
    """state_dict/load_state_dict mid-run (including mid-interval partial
    cold accumulator and device hot moments) must reproduce the
    uninterrupted trajectory exactly (advisor finding: state_dict dropped
    _dev_m/_dev_v and _cold_acc)."""
    params, target, vg = _quadratic_problem()
    kw = dict(lr=0.05, topk_ratio=0.25, update_interval=4, overlap=False)

    # uninterrupted reference run
    opt_ref = ZenFlowOptimizer(params, **kw)
    p_ref = params
    for _ in range(10):
        _, g = vg(p_ref)
        p_ref = opt_ref.step(p_ref, g)

    # interrupted at step 6: mid-interval (6 % 4 != 0) so _cold_acc is
    # partially filled and the device moments carry hot-column state
    opt_a = ZenFlowOptimizer(params, **kw)
    p = params
    for _ in range(6):
        _, g = vg(p)
        p = opt_a.step(p, g)
    sd = opt_a.state_dict()
    assert sd["cold_steps"] == 2  # genuinely mid-interval
    assert any(np.abs(x).sum() > 0 for x in
               jax.tree_util.tree_leaves(sd["cold_acc"]))
    # keep training opt_a past the snapshot: state_dict must be a deep
    # copy, so these steps must NOT leak into sd
    pa = p
    for _ in range(2):
        _, g = vg(pa)
        pa = opt_a.step(pa, g)

    opt_b = ZenFlowOptimizer(params, **kw)
    opt_b.load_state_dict(sd)
    for _ in range(4):
        _, g = vg(p)
        p = opt_b.step(p, g)
    np.testing.assert_array_equal(np.asarray(p["w"]), np.asarray(p_ref["w"]))
    np.testing.assert_array_equal(np.asarray(p["b"]), np.asarray(p_ref["b"]))


def test_superoffload_matches_plain_adam():
    rng = np.random.default_rng(0)
    params = {"a": jnp.asarray(rng.standard_normal((8, 8)), jnp.float32),
              "b": jnp.asarray(rng.standard_normal((8,)), jnp.float32)}
    grads = jax.tree.map(lambda x: jnp.ones_like(x) * 0.5, params)
    so = SuperOffloadOptimizer(params, lr=0.01, bucket_bytes=64)
    out = so.step(params, grads)

    import optax

    tx = optax.adam(0.01, 0.9, 0.999, 1e-8)
    state = tx.init(params)
    upd, _ = tx.update(jax.tree.map(lambda g: g, grads), state, params)
    ref = optax.apply_updates(params, upd)
    for k in params:
        np.testing.assert_allclose(np.asarray(out[k]), np.asarray(ref[k]),
                                   atol=1e-5, rtol=1e-4)


def test_superoffload_rollback():
    params = {"w": jnp.ones((4, 4), jnp.float32)}
    so = SuperOffloadOptimizer(params, lr=0.1)
    g = {"w": jnp.ones((4, 4), jnp.float32)}
    stepped = so.step(params, g)
    assert float(jnp.abs(stepped["w"] - 1.0).max()) > 0
    so.rollback()
    assert so.step_count == 0
    # master restored → re-stepping from snapshot reproduces the same result
    stepped2 = so.step(params, g)
    np.testing.assert_allclose(np.asarray(stepped2["w"]),
                               np.asarray(stepped["w"]), atol=1e-7)
    with pytest.raises(RuntimeError):
        so.rollback()
        so.rollback()  # window exhausted


def test_superoffload_state_roundtrip():
    params = {"w": jnp.ones((4, 4), jnp.float32)}
    so = SuperOffloadOptimizer(params, lr=0.1)
    g = {"w": jnp.ones((4, 4), jnp.float32)}
    so.step(params, g)
    sd = so.state_dict()
    so2 = SuperOffloadOptimizer(params, lr=0.1)
    so2.load_state_dict(sd)
    a = so.step(params, g)
    b = so2.step(params, g)
    np.testing.assert_allclose(np.asarray(a["w"]), np.asarray(b["w"]), atol=1e-7)


# ---------------------------------------------------------------------------
# SuperOffload engine integration (ref engine.py:935 super_offload +
# superoffload_stage3.py): config-selected host Adam path.
# ---------------------------------------------------------------------------
def _so_cfg(**over):
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "gradient_clipping": 0.0,
        "steps_per_print": 1000,
        "mesh": {"data": 1},
    }
    cfg.update(over)
    return cfg


def _so_train(model, cfg, batches, seed=19):
    import deepspeed_tpu as ds
    from deepspeed_tpu.parallel import topology

    engine, _, _, _ = ds.initialize(model=model, config=cfg, seed=seed)
    losses = [float(np.asarray(engine.train_batch(b))) for b in batches]
    topology._GLOBAL_TOPOLOGY = None
    return losses, engine


def test_superoffload_engine_matches_device_adam():
    """super_offload=true must reproduce the plain device-Adam trajectory
    (classic Adam, wd=0 ⇒ Adam == AdamW numerics)."""
    from deepspeed_tpu.models import get_model_config
    from tests.conftest import make_lm_batch

    model = get_model_config("gpt2-tiny")
    rng = np.random.default_rng(21)
    batches = [make_lm_batch(rng, 4, 32, model.vocab_size)] * 4
    ref, _ = _so_train(model, _so_cfg(), batches)
    so, eng = _so_train(model, _so_cfg(zero_optimization={
        "offload_optimizer": {"device": "cpu", "super_offload": True}}),
        batches)
    assert eng._super_opt is not None and eng.opt_state is None
    np.testing.assert_allclose(ref, so, rtol=2e-4, atol=2e-4)
    assert so[-1] < so[0]


def test_superoffload_engine_overflow_skip_and_rollback():
    """fp16 overflow must skip the host step (loss scale halves, params
    unchanged); engine.rollback() must undo a completed step."""
    from deepspeed_tpu.models import get_model_config
    from tests.conftest import make_lm_batch

    model = get_model_config("gpt2-tiny")
    rng = np.random.default_rng(22)
    batch = make_lm_batch(rng, 4, 32, model.vocab_size)
    cfg = _so_cfg(
        fp16={"enabled": True, "loss_scale": 0,
              "initial_scale_power": 32},  # guaranteed overflow at 2^32
        zero_optimization={
            "offload_optimizer": {"device": "cpu", "super_offload": True}})
    import deepspeed_tpu as ds
    from deepspeed_tpu.parallel import topology

    engine, _, _, _ = ds.initialize(model=model, config=cfg, seed=23)
    try:
        before = np.asarray(engine.params["final_norm"]["scale"]).copy()
        s0 = float(np.asarray(engine.loss_scale_state["scale"]))
        engine.train_batch(batch)
        s1 = float(np.asarray(engine.loss_scale_state["scale"]))
        after = np.asarray(engine.params["final_norm"]["scale"])
        assert s1 == s0 / 2  # dynamic scale halved on overflow
        np.testing.assert_array_equal(before, after)  # step skipped

        # drive the scale down until a finite step lands, then roll it back
        for _ in range(40):
            engine.train_batch(batch)
            if float(np.asarray(engine._last_metrics["grad_norm"])) > 0 \
                    and not engine._last_metrics["skipped"]:
                break
        stepped = np.asarray(engine.params["final_norm"]["scale"]).copy()
        engine.rollback()
        rolled = np.asarray(engine.params["final_norm"]["scale"])
        assert not np.array_equal(stepped, rolled)
    finally:
        topology._GLOBAL_TOPOLOGY = None


def test_superoffload_engine_checkpoint_roundtrip(tmp_path):
    """Masters/moments live on the host: save/load must round-trip them and
    reproduce the uninterrupted trajectory."""
    from deepspeed_tpu.models import get_model_config
    from tests.conftest import make_lm_batch

    model = get_model_config("gpt2-tiny")
    rng = np.random.default_rng(24)
    batches = [make_lm_batch(rng, 4, 32, model.vocab_size)] * 6
    so_cfg = _so_cfg(zero_optimization={
        "offload_optimizer": {"device": "cpu", "super_offload": True}})
    ref, _ = _so_train(model, so_cfg, batches)

    import deepspeed_tpu as ds
    from deepspeed_tpu.parallel import topology

    eng, _, _, _ = ds.initialize(model=model, config=so_cfg, seed=19)
    for b in batches[:3]:
        eng.train_batch(b)
    eng.save_checkpoint(str(tmp_path), tag="so")
    topology._GLOBAL_TOPOLOGY = None

    eng2, _, _, _ = ds.initialize(model=model, config=so_cfg, seed=99)
    eng2.load_checkpoint(str(tmp_path), tag="so")
    cont = [float(np.asarray(eng2.train_batch(b))) for b in batches[3:]]
    topology._GLOBAL_TOPOLOGY = None
    np.testing.assert_allclose(ref[3:], cont, rtol=2e-4, atol=2e-4)


def test_zenflow_overlap_long_run_matches_sync_exactly():
    """Multi-step stress of the pending-delta contract (VERDICT r3 Weak
    #7): 60 steps with the async worker racing real thread timing must be
    bit-identical to the synchronous run — any lost/duplicated delta or
    accumulator race shows up as divergence.  A mid-run state_dict
    round-trip must not perturb the trajectory either."""
    import time

    def run(overlap, jitter=False, roundtrip_at=None):
        params, _, vg = _quadratic_problem(seed=7)
        opt = ZenFlowOptimizer(params, lr=0.05, topk_ratio=0.25,
                               update_interval=3, overlap=overlap)
        for i in range(60):
            _, g = vg(params)
            params = opt.step(params, g)
            if jitter and i % 7 == 0:
                time.sleep(0.002)  # perturb worker/main interleaving
            if roundtrip_at is not None and i == roundtrip_at:
                sd = opt.state_dict()
                opt.load_state_dict(sd)
        params = opt.flush(params)
        return np.asarray(params["w"], np.float32)

    ref = run(overlap=False)
    got = run(overlap=True, jitter=True)
    np.testing.assert_array_equal(got, ref)
    got_rt = run(overlap=True, roundtrip_at=31)
    np.testing.assert_array_equal(got_rt, ref)
