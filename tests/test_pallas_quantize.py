"""Parity tests for the Pallas blockwise quantization kernels
(deepspeed_tpu/ops/pallas/quantize.py) run through the Pallas interpreter
on CPU, against the jnp reference path (ops/quantizer.py) they shadow on
TPU.  Ref kernel family: csrc/quantization/{quantize,dequantize,
fake_quantizer}.cu in the reference suite."""

import importlib

import jax.numpy as jnp
import numpy as np
import pytest

pq = importlib.import_module("deepspeed_tpu.ops.pallas.quantize")
from deepspeed_tpu.ops import quantizer as qz


@pytest.fixture(autouse=True)
def _interpret_mode():
    old = pq.INTERPRET
    pq.INTERPRET = True
    yield
    pq.INTERPRET = old


@pytest.mark.parametrize("shape,gs", [
    ((64, 512), 128),
    ((4, 8, 256), 256),
    ((300, 384), 128),          # row count not a multiple of the block
    ((1024,), 256),             # 1-D tensor
])
@pytest.mark.parametrize("bits", [8, 4])
def test_quantize_parity(shape, gs, bits):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal(shape), jnp.float32) * 3.0
    assert pq.supports(shape, gs, True, bits)
    q_p, s_p = pq.quantize(x, bits, gs)
    q_j, s_j, zp = qz.quantize_blockwise(x, bits, gs, backend="jnp")
    assert zp is None
    assert q_p.dtype == jnp.int8 and q_p.shape == x.shape
    np.testing.assert_array_equal(np.asarray(q_p), np.asarray(q_j))
    np.testing.assert_allclose(np.asarray(s_p), np.asarray(s_j),
                               rtol=1e-6, atol=1e-8)


def test_dequantize_parity():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((96, 512)), jnp.float32)
    q, s, _ = qz.quantize_blockwise(x, 8, 128, backend="jnp")
    d_p = pq.dequantize(q, s, dtype=jnp.bfloat16)
    d_j = qz.dequantize_blockwise(q, s, dtype=jnp.bfloat16, backend="jnp")
    assert d_p.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(d_p, np.float32),
                               np.asarray(d_j, np.float32),
                               rtol=1e-2, atol=1e-2)


def test_fake_quantize_one_pass_matches_roundtrip():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((64, 256)), jnp.bfloat16)
    fq_p = pq.fake_quantize(x, 8, 128)
    fq_j = qz.fake_quantize(x, 8, 128, backend="jnp")
    assert fq_p.dtype == x.dtype
    np.testing.assert_allclose(np.asarray(fq_p, np.float32),
                               np.asarray(fq_j, np.float32),
                               rtol=1e-2, atol=1e-2)


def test_facade_routes_to_pallas_under_interpret():
    """backend='auto' uses the kernel when servable (INTERPRET forces the
    TPU decision on CPU), and falls back for unservable shapes."""
    x = jnp.ones((32, 256), jnp.float32)
    q, s, zp = qz.quantize_blockwise(x, 8, 128)  # auto → pallas here
    assert zp is None and q.shape == x.shape
    # group_size not a lane multiple → jnp fallback must serve it
    assert not pq.supports((32, 96), 96, True, 8)
    q2, s2, _ = qz.quantize_blockwise(jnp.ones((32, 96)), 8, 96)
    assert q2.shape == (32, 96)
    # asymmetric → always jnp
    q3, s3, z3 = qz.quantize_blockwise(x, 8, 128, symmetric=False)
    assert z3 is not None


def test_quantization_error_bounded():
    """Round-trip error ≤ scale/2 per element (the int8 promise)."""
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((128, 512)), jnp.float32)
    q, s = pq.quantize(x, 8, 128)
    d = pq.dequantize(q, s)
    err = np.abs(np.asarray(d) - np.asarray(x))
    bound = np.repeat(np.asarray(s), 128, axis=-1) * 0.5 + 1e-7
    assert (err <= bound).all()
