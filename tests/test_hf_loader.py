"""HF checkpoint import: logits parity vs transformers reference models.

The strongest conversion test: build a tiny randomly-initialized HF model
per family, convert weights with params_from_hf, and require near-equal
logits between the torch forward and our functional forward."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp  # noqa: E402

from deepspeed_tpu.models import transformer as tf  # noqa: E402
from deepspeed_tpu.models.hf_loader import (config_from_hf,  # noqa: E402
                                            params_from_hf)


def _compare(hf_model, atol=2e-3, zero_lm_head_bias=False):
    hf_model.eval()
    if zero_lm_head_bias and getattr(hf_model, "lm_head", None) is not None \
            and getattr(hf_model.lm_head, "bias", None) is not None:
        with torch.no_grad():
            hf_model.lm_head.bias.zero_()
    cfg = config_from_hf(hf_model.config).replace(dtype=jnp.float32)
    params = params_from_hf(hf_model, cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 12), dtype=np.int64)
    with torch.no_grad():
        ref = hf_model(torch.tensor(ids)).logits.float().numpy()
    out = tf.forward(params, jnp.asarray(ids, jnp.int32), cfg)
    if isinstance(out, tuple):
        out = out[0]
    out = np.asarray(out, np.float32)
    np.testing.assert_allclose(out, ref, atol=atol, rtol=1e-3)


def test_gpt2_parity():
    from transformers import GPT2Config, GPT2LMHeadModel

    torch.manual_seed(0)
    m = GPT2LMHeadModel(GPT2Config(
        vocab_size=128, n_embd=64, n_layer=2, n_head=4, n_positions=64))
    _compare(m)


def test_llama_parity():
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(0)
    m = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=False))
    _compare(m)


def test_mistral_parity():
    from transformers import MistralConfig, MistralForCausalLM

    torch.manual_seed(0)
    m = MistralForCausalLM(MistralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, sliding_window=8,
        tie_word_embeddings=False))
    _compare(m)


def test_qwen2_parity():
    from transformers import Qwen2Config, Qwen2ForCausalLM

    torch.manual_seed(0)
    m = Qwen2ForCausalLM(Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=False))
    _compare(m)


def test_opt_parity():
    from transformers import OPTConfig, OPTForCausalLM

    torch.manual_seed(0)
    m = OPTForCausalLM(OPTConfig(
        vocab_size=128, hidden_size=64, ffn_dim=128, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=64,
        do_layer_norm_before=True, word_embed_proj_dim=64))
    _compare(m)


def test_falcon_parity():
    from transformers import FalconConfig, FalconForCausalLM

    torch.manual_seed(0)
    m = FalconForCausalLM(FalconConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, multi_query=True,
        new_decoder_architecture=False, parallel_attn=True, bias=False,
        alibi=False))
    _compare(m)


def test_falcon_sequential_parity():
    """parallel_attn=False (Falcon-RW sequential residual): ln2 must load
    from post_attention_layernorm, not input_layernorm."""
    from transformers import FalconConfig, FalconForCausalLM

    torch.manual_seed(0)
    m = FalconForCausalLM(FalconConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, multi_query=False,
        new_decoder_architecture=False, parallel_attn=False, bias=False,
        alibi=False))
    _compare(m)


def test_falcon_gqa_new_arch_parity():
    """Falcon-40B/180B layout: new_decoder_architecture with 1 < nkv < nh
    interleaves the fused QKV per KV group and uses ln_attn/ln_mlp parallel
    norms (ref GQAMegatronQKVParameter, module_inject/layers.py)."""
    from transformers import FalconConfig, FalconForCausalLM

    torch.manual_seed(0)
    m = FalconForCausalLM(FalconConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_kv_heads=2, multi_query=False,
        new_decoder_architecture=True, parallel_attn=True, bias=False,
        alibi=False))
    _compare(m)


def test_phi_parity():
    from transformers import PhiConfig, PhiForCausalLM

    torch.manual_seed(0)
    m = PhiForCausalLM(PhiConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, partial_rotary_factor=0.5))
    _compare(m, zero_lm_head_bias=True)


def test_phi3_parity():
    from transformers import Phi3Config, Phi3ForCausalLM

    torch.manual_seed(0)
    m = Phi3ForCausalLM(Phi3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=False,
        pad_token_id=0))
    _compare(m)


def test_qwen_v1_parity():
    """Qwen v1 is a remote-code model (no transformers class), but its
    math is Qwen2's (rmsnorm + biased-qkv + swiglu, no GQA) in a
    different state-dict layout: fused transformer.h.*.attn.c_attn,
    mlp.w1 (up) / w2 (gate) / c_proj, intermediate_size doubled.  Relay a
    tiny Qwen2 checkpoint into the v1 layout and require logits parity
    against the torch forward — this pins the converter's fused splits
    and gate/up mapping against real numerics."""
    from types import SimpleNamespace

    from transformers import Qwen2Config, Qwen2ForCausalLM

    torch.manual_seed(0)
    m = Qwen2ForCausalLM(Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=64, tie_word_embeddings=False))
    m.eval()
    sd2 = {k: v for k, v in m.state_dict().items()}
    sd1 = {"transformer.wte.weight": sd2["model.embed_tokens.weight"],
           "transformer.ln_f.weight": sd2["model.norm.weight"],
           "lm_head.weight": sd2["lm_head.weight"]}
    for i in range(2):
        p2, p1 = f"model.layers.{i}.", f"transformer.h.{i}."
        sd1[p1 + "attn.c_attn.weight"] = torch.cat(
            [sd2[p2 + "self_attn.q_proj.weight"],
             sd2[p2 + "self_attn.k_proj.weight"],
             sd2[p2 + "self_attn.v_proj.weight"]], dim=0)
        sd1[p1 + "attn.c_attn.bias"] = torch.cat(
            [sd2[p2 + "self_attn.q_proj.bias"],
             sd2[p2 + "self_attn.k_proj.bias"],
             sd2[p2 + "self_attn.v_proj.bias"]], dim=0)
        sd1[p1 + "attn.c_proj.weight"] = sd2[p2 + "self_attn.o_proj.weight"]
        sd1[p1 + "mlp.w2.weight"] = sd2[p2 + "mlp.gate_proj.weight"]
        sd1[p1 + "mlp.w1.weight"] = sd2[p2 + "mlp.up_proj.weight"]
        sd1[p1 + "mlp.c_proj.weight"] = sd2[p2 + "mlp.down_proj.weight"]
        sd1[p1 + "ln_1.weight"] = sd2[p2 + "input_layernorm.weight"]
        sd1[p1 + "ln_2.weight"] = sd2[p2 + "post_attention_layernorm.weight"]
    hf_cfg = SimpleNamespace(model_type="qwen", vocab_size=128,
                             hidden_size=64, intermediate_size=256,
                             num_hidden_layers=2, num_attention_heads=4,
                             seq_length=64, rotary_emb_base=10000.0,
                             layer_norm_epsilon=1e-6)
    cfg = config_from_hf(hf_cfg).replace(dtype=jnp.float32)
    params = params_from_hf(sd1, cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 12), dtype=np.int64)
    with torch.no_grad():
        ref = m(torch.tensor(ids)).logits.float().numpy()
    out = tf.forward(params, jnp.asarray(ids, jnp.int32), cfg)
    np.testing.assert_allclose(np.asarray(out, np.float32), ref,
                               atol=2e-3, rtol=1e-3)


def test_converted_model_trains():
    """End-to-end: HF GPT-2 weights → engine → loss decreases."""
    from transformers import GPT2Config, GPT2LMHeadModel

    import deepspeed_tpu as ds

    torch.manual_seed(0)
    m = GPT2LMHeadModel(GPT2Config(
        vocab_size=128, n_embd=64, n_layer=2, n_head=4, n_positions=64))
    cfg = config_from_hf(m.config)
    params = params_from_hf(m, cfg)
    engine, _, _, _ = ds.initialize(
        model=cfg, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 4,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "AdamW", "params": {"lr": 5e-3}},
                "mesh": {"data": 1}})
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(4, 17), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
    losses = [float(np.asarray(engine.train_batch(batch))) for _ in range(6)]
    assert losses[-1] < losses[0]
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None


def test_mixtral_parity():
    from transformers import MixtralConfig, MixtralForCausalLM

    torch.manual_seed(0)
    m = MixtralForCausalLM(MixtralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, num_local_experts=4,
        num_experts_per_tok=2, tie_word_embeddings=False))
    # ample capacity (set by config_from_hf) ⇒ no token drops ⇒ exact
    # top-2 routing parity with HF's dropless block
    _compare(m, atol=4e-3)


def test_qwen2_moe_parity():
    from transformers import Qwen2MoeConfig, Qwen2MoeForCausalLM

    torch.manual_seed(0)
    m = Qwen2MoeForCausalLM(Qwen2MoeConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, num_experts=4, num_experts_per_tok=2,
        moe_intermediate_size=48, shared_expert_intermediate_size=96,
        decoder_sparse_step=1, norm_topk_prob=False,
        tie_word_embeddings=False))
    _compare(m, atol=4e-3)


def test_bert_parity():
    """Encoder family: bidirectional post-LN stack + MLM head logits must
    match HF BertForMaskedLM (ref module_inject/containers/bert.py)."""
    from transformers import BertConfig, BertForMaskedLM

    torch.manual_seed(0)
    m = BertForMaskedLM(BertConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, type_vocab_size=2))
    m.eval()
    cfg = config_from_hf(m.config).replace(dtype=jnp.float32)
    assert not cfg.causal and cfg.norm_position == "post" and cfg.mlm_head
    params = params_from_hf(m, cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 12), dtype=np.int64)
    tt = rng.integers(0, 2, size=(2, 12), dtype=np.int64)
    with torch.no_grad():
        ref = m(torch.tensor(ids),
                token_type_ids=torch.tensor(tt)).logits.float().numpy()
    out = np.asarray(tf.forward(params, jnp.asarray(ids, jnp.int32), cfg,
                                token_type_ids=jnp.asarray(tt, jnp.int32)),
                     np.float32)
    np.testing.assert_allclose(out, ref, atol=2e-3, rtol=1e-3)


def test_bert_attention_mask_parity():
    """Key-padding mask: padded positions must not influence kept tokens'
    logits (matches HF attention_mask semantics)."""
    from transformers import BertConfig, BertForMaskedLM

    torch.manual_seed(1)
    m = BertForMaskedLM(BertConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, type_vocab_size=2))
    m.eval()
    cfg = config_from_hf(m.config).replace(dtype=jnp.float32)
    params = params_from_hf(m, cfg)
    rng = np.random.default_rng(1)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 12), dtype=np.int64)
    mask = np.ones((2, 12), np.int64)
    mask[:, 9:] = 0  # right padding
    with torch.no_grad():
        ref = m(torch.tensor(ids),
                attention_mask=torch.tensor(mask)).logits.float().numpy()
    out = np.asarray(
        tf.forward(params, jnp.asarray(ids, jnp.int32), cfg,
                   attention_mask=jnp.asarray(mask, jnp.int32)), np.float32)
    np.testing.assert_allclose(out[:, :9], ref[:, :9], atol=2e-3, rtol=1e-3)


def test_distilbert_parity():
    from transformers import DistilBertConfig, DistilBertForMaskedLM

    torch.manual_seed(0)
    m = DistilBertForMaskedLM(DistilBertConfig(
        vocab_size=128, dim=64, hidden_dim=128, n_layers=2, n_heads=4,
        max_position_embeddings=64))
    m.eval()
    cfg = config_from_hf(m.config).replace(dtype=jnp.float32)
    assert cfg.arch == "distilbert" and cfg.type_vocab_size == 0
    params = params_from_hf(m, cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 12), dtype=np.int64)
    with torch.no_grad():
        ref = m(torch.tensor(ids)).logits.float().numpy()
    out = np.asarray(tf.forward(params, jnp.asarray(ids, jnp.int32), cfg),
                     np.float32)
    np.testing.assert_allclose(out, ref, atol=2e-3, rtol=1e-3)


def test_bloom_parity():
    """ALiBi attention + embedding LayerNorm + headwise-fused qkv (ref
    module_inject/containers/bloom.py)."""
    from transformers import BloomConfig, BloomForCausalLM

    torch.manual_seed(0)
    m = BloomForCausalLM(BloomConfig(
        vocab_size=128, hidden_size=64, n_layer=2, n_head=4))
    _compare(m)


def test_bloom_left_padded_alibi_matches_hf():
    """LEFT-padded batches: HF build_alibi_tensor derives key positions
    from attention_mask.cumsum — the bias must shift by the padding
    offset per row, not use absolute slot indices."""
    from transformers import BloomConfig, BloomForCausalLM

    torch.manual_seed(0)
    m = BloomForCausalLM(BloomConfig(
        vocab_size=128, hidden_size=64, n_layer=2, n_head=4))
    m.eval()
    cfg = config_from_hf(m.config).replace(dtype=jnp.float32)
    params = params_from_hf(m, cfg)
    rng = np.random.default_rng(11)
    ids = rng.integers(3, cfg.vocab_size, size=(2, 12), dtype=np.int64)
    mask = np.ones((2, 12), np.int64)
    mask[0, :4] = 0   # row 0 left-padded by 4
    mask[1, :1] = 0
    with torch.no_grad():
        ref = m(torch.tensor(ids),
                attention_mask=torch.tensor(mask)).logits.float().numpy()
    out = tf.forward(params, jnp.asarray(ids, jnp.int32), cfg,
                     attention_mask=jnp.asarray(mask, jnp.int32))
    out = np.asarray(out, np.float32)
    keep = mask.astype(bool)
    np.testing.assert_allclose(out[keep], ref[keep], atol=2e-3, rtol=1e-3)


def test_gptj_parity():
    """Interleaved partial rotary + parallel block with one shared norm +
    biasless attention / biased MLP (ref containers/gptj.py).  The HF
    lm_head.bias is NOT zeroed: the converter carries it into the
    functional head's vocab-size output bias, so logits must match with a
    nonzero bias applied (the released EleutherAI weights ship one)."""
    from transformers import GPTJConfig, GPTJForCausalLM

    torch.manual_seed(0)
    m = GPTJForCausalLM(GPTJConfig(
        vocab_size=128, n_embd=64, n_layer=2, n_head=4, n_positions=64,
        rotary_dim=8))
    with torch.no_grad():
        # the random init leaves it zero — make the parity check prove the
        # bias actually reaches the logits
        m.lm_head.bias.uniform_(-0.5, 0.5)
    _compare(m)


@pytest.mark.parametrize("parallel", [True, False])
def test_gptneox_parity(parallel):
    """Partial rotate-half rotary + parallel residual with separate norms
    (and the sequential use_parallel_residual=False variant); headwise
    fused qkv (ref containers/gptneox.py)."""
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    torch.manual_seed(0)
    m = GPTNeoXForCausalLM(GPTNeoXConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, rotary_pct=0.25,
        use_parallel_residual=parallel))
    _compare(m)


def test_bloom_gptj_neox_generate_matches_hf():
    """The new v1-injection families serve through the KV-cached generate
    path: greedy continuations must match HF transformers' generate."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.parallel import topology
    from transformers import (BloomConfig, BloomForCausalLM, GPTJConfig,
                              GPTJForCausalLM, GPTNeoXConfig,
                              GPTNeoXForCausalLM)

    cases = [
        BloomForCausalLM(BloomConfig(vocab_size=128, hidden_size=64,
                                     n_layer=2, n_head=4)),
        GPTJForCausalLM(GPTJConfig(vocab_size=128, n_embd=64, n_layer=2,
                                   n_head=4, n_positions=64, rotary_dim=8)),
        GPTNeoXForCausalLM(GPTNeoXConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            max_position_embeddings=64, rotary_pct=0.25)),
    ]
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, size=(1, 10), dtype=np.int64)
    for m in cases:
        torch.manual_seed(0)
        m.eval()
        with torch.no_grad():
            # fresh LayerNorms are weight=1/bias=0, which makes ln1 == ln2
            # numerically and would mask norm-routing bugs (e.g. the v2
            # parallel_norms path) — randomize them
            for name, p in m.named_parameters():
                if "layernorm" in name.lower() or "ln_" in name.lower():
                    p.add_(torch.randn_like(p) * 0.1)
        if getattr(getattr(m, "lm_head", None), "bias", None) is not None:
            with torch.no_grad():
                m.lm_head.bias.zero_()
        cfg = config_from_hf(m.config).replace(dtype=jnp.float32)
        params = params_from_hf(m, cfg)
        with torch.no_grad():
            ref = m.generate(torch.tensor(ids), max_new_tokens=6,
                             do_sample=False).numpy()[0, 10:]
        eng = ds.init_inference(model=cfg, model_params=params,
                                dtype="float32")
        out = np.asarray(eng.generate(ids.astype(np.int32),
                                      max_new_tokens=6))[0, 10:]
        np.testing.assert_array_equal(out, ref, err_msg=cfg.arch)
        topology._GLOBAL_TOPOLOGY = None


def test_bert_sequence_classification_parity():
    """Classification checkpoints: pooler + classifier convert, and
    pooled logits match HF BertForSequenceClassification (eval mode)."""
    from transformers import BertConfig, BertForSequenceClassification

    from deepspeed_tpu.models.encoder_heads import bert_pooled_classify

    torch.manual_seed(2)
    m = BertForSequenceClassification(BertConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, type_vocab_size=2, num_labels=3))
    m.eval()
    cfg = config_from_hf(m.config).replace(dtype=jnp.float32,
                                           mlm_head=False)
    params = params_from_hf(m, cfg)
    assert "pooler" in params and "classifier" in params
    rng = np.random.default_rng(2)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 12), dtype=np.int64)
    with torch.no_grad():
        ref = m(torch.tensor(ids)).logits.float().numpy()
    hidden = tf.forward(params, jnp.asarray(ids, jnp.int32), cfg,
                        return_hidden=True)
    out = np.asarray(bert_pooled_classify(params, hidden), np.float32)
    assert out.shape == (2, 3)
    np.testing.assert_allclose(out, ref, atol=2e-3, rtol=1e-3)


def test_gptneo_parity():
    """GPT-Neo: alternating global/local attention (layer pairs with a
    static per-member window), learned positions, unscaled scores, and
    biasless q/k/v with biased out/mlp (ref containers/gptneo.py).
    window_size=8 < seq=12 so the local layer's mask is live."""
    from transformers import GPTNeoConfig, GPTNeoForCausalLM

    torch.manual_seed(0)
    m = GPTNeoForCausalLM(GPTNeoConfig(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        attention_types=[[["global", "local"], 1]], window_size=8,
        max_position_embeddings=64, intermediate_size=128))
    cfg = config_from_hf(m.config).replace(dtype=jnp.float32)
    assert cfg.alt_window and cfg.sliding_window == 8
    assert cfg.attn_scale == 1.0
    _compare(m)


def test_gptneo_generate_matches_hf():
    """GPT-Neo serves through the paged ragged path (paired alt-window
    scan + learned positions): greedy continuation equals HF generate."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.parallel import topology
    from transformers import GPTNeoConfig, GPTNeoForCausalLM

    torch.manual_seed(1)
    m = GPTNeoForCausalLM(GPTNeoConfig(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        attention_types=[[["global", "local"], 1]], window_size=8,
        max_position_embeddings=64, intermediate_size=128)).eval()
    cfg = config_from_hf(m.config).replace(dtype=jnp.float32)
    params = params_from_hf(m, cfg)
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 128, size=(1, 12), dtype=np.int64)
    with torch.no_grad():
        ref = m.generate(torch.tensor(ids), max_new_tokens=6,
                         do_sample=False).numpy()[0, 12:]
    eng = ds.init_inference(model=cfg, model_params=params,
                            dtype="float32")
    out = np.asarray(eng.generate(ids.astype(np.int32),
                                  max_new_tokens=6))[0, 12:]
    np.testing.assert_array_equal(out, ref)
    topology._GLOBAL_TOPOLOGY = None


def test_opt_generate_matches_hf():
    """Regression: the ragged embed path used to gate learned positions
    on arch == 'gpt2', silently dropping OPT's position embeddings in
    paged serving."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.parallel import topology
    from transformers import OPTConfig, OPTForCausalLM

    torch.manual_seed(2)
    m = OPTForCausalLM(OPTConfig(
        vocab_size=128, hidden_size=64, ffn_dim=128, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=64,
        do_layer_norm_before=True, word_embed_proj_dim=64)).eval()
    cfg = config_from_hf(m.config).replace(dtype=jnp.float32)
    params = params_from_hf(m, cfg)
    rng = np.random.default_rng(2)
    ids = rng.integers(4, 128, size=(1, 10), dtype=np.int64)
    with torch.no_grad():
        ref = m.generate(torch.tensor(ids), max_new_tokens=6,
                         do_sample=False).numpy()[0, 10:]
    eng = ds.init_inference(model=cfg, model_params=params,
                            dtype="float32")
    out = np.asarray(eng.generate(ids.astype(np.int32),
                                  max_new_tokens=6))[0, 10:]
    np.testing.assert_array_equal(out, ref)
    topology._GLOBAL_TOPOLOGY = None
