"""Indexed dataset + data analyzer (ref data_sampling tests)."""

import numpy as np
import pytest

from deepspeed_tpu.runtime.data_pipeline import (CurriculumScheduler,
                                                 DataAnalyzer,
                                                 DeepSpeedDataSampler,
                                                 IndexedDataset,
                                                 IndexedDatasetBuilder,
                                                 load_metric)


def _build(tmp_path, n=20, dtype=np.int32):
    rng = np.random.default_rng(0)
    seqs = [rng.integers(0, 1000, size=rng.integers(3, 40)).astype(dtype)
            for _ in range(n)]
    b = IndexedDatasetBuilder(str(tmp_path / "corpus"), dtype=dtype)
    b.add_items(seqs)
    b.finalize()
    return seqs


def test_indexed_roundtrip(tmp_path):
    seqs = _build(tmp_path)
    ds = IndexedDataset(str(tmp_path / "corpus"))
    assert len(ds) == len(seqs)
    for i in (0, 7, len(seqs) - 1, -1):
        np.testing.assert_array_equal(ds[i], seqs[i])
    np.testing.assert_array_equal(ds.sizes, [len(s) for s in seqs])
    with pytest.raises(IndexError):
        ds[len(seqs)]


def test_indexed_dtypes(tmp_path):
    for dt in (np.uint16, np.int64, np.uint8):
        _build(tmp_path / str(np.dtype(dt)), n=3, dtype=dt)
        ds = IndexedDataset(str(tmp_path / str(np.dtype(dt)) / "corpus"))
        assert ds.dtype == np.dtype(dt)


def test_analyzer_sharded_map_reduce(tmp_path):
    seqs = _build(tmp_path, n=30)
    ds = IndexedDataset(str(tmp_path / "corpus"))
    samples = [{"input_ids": ds[i]} for i in range(len(ds))]
    out_dir = str(tmp_path / "analysis")
    # 3 workers map disjoint shards, then one reduce
    for w in range(3):
        DataAnalyzer(samples, out_dir, num_workers=3, worker_id=w).run_map()
    DataAnalyzer(samples, out_dir, num_workers=3).run_reduce()
    vals = load_metric(out_dir, "seqlen")
    np.testing.assert_array_equal(vals, [len(s) for s in seqs])
    order = np.load(f"{out_dir}/seqlen_index_sorted.npy")
    assert (np.diff(vals[order]) >= 0).all()


def test_analyzer_feeds_curriculum_sampler(tmp_path):
    _build(tmp_path, n=32)
    ds = IndexedDataset(str(tmp_path / "corpus"))
    samples = [{"input_ids": ds[i]} for i in range(len(ds))]
    out_dir = str(tmp_path / "analysis")
    DataAnalyzer(samples, out_dir).run_map()
    DataAnalyzer(samples, out_dir).run_reduce()
    diffs = load_metric(out_dir)
    cs = CurriculumScheduler({
        "min_difficulty": 15, "max_difficulty": 40,
        "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 4, "difficulty_step": 5}})
    sampler = DeepSpeedDataSampler(len(samples), batch_size=2,
                                   difficulties=diffs, curriculum=cs, seed=1)
    first = next(iter(sampler))
    assert all(diffs[i] <= 15 for i in first)


def test_analyzer_missing_shard_raises(tmp_path):
    _build(tmp_path, n=10)
    ds = IndexedDataset(str(tmp_path / "corpus"))
    samples = [{"input_ids": ds[i]} for i in range(len(ds))]
    out_dir = str(tmp_path / "analysis")
    DataAnalyzer(samples, out_dir, num_workers=2, worker_id=0).run_map()
    with pytest.raises(RuntimeError):  # worker 1 never mapped
        DataAnalyzer(samples, out_dir, num_workers=2).run_reduce()
