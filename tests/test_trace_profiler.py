"""XPlane trace capture (utils/trace.py; ref utils/nvtx.py +
pytorch-profiler integration): windowed engine capture writes a trace
directory; annotations are free when no capture is active."""

import os

import numpy as np

from deepspeed_tpu.utils.trace import (TraceProfiler, instrument_w_trace,
                                       range_pop, range_push)


def test_instrument_and_ranges_no_capture():
    @instrument_w_trace
    def f(x):
        return x + 1

    assert f(1) == 2

    @instrument_w_trace(name="custom")
    def g(x):
        return x * 2

    assert g(3) == 6
    range_push("outer")
    range_push("inner")
    range_pop()
    range_pop()
    range_pop()  # underflow is a no-op


def test_engine_windowed_capture(tmp_path):
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.parallel import topology

    out = str(tmp_path / "trace")
    model = get_model_config("gpt2-tiny")
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "profiler": {"enabled": True, "output_dir": out,
                     "start_step": 2, "num_steps": 2},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(8, 33), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    for _ in range(5):
        loss = engine.train_batch(batch)
    assert np.isfinite(float(np.asarray(loss)))
    tp = engine._trace_profiler
    assert tp.done and not tp.active
    # a plugin/profile dir with at least one .xplane.pb artifact appeared
    found = [os.path.join(r, f) for r, _, fs in os.walk(out) for f in fs]
    assert any(f.endswith(".xplane.pb") for f in found), found


def test_range_pop_empty_stack_warns_not_crashes():
    """Unbalanced pop on an empty accelerator range stack: a warning,
    never an exception (dying inside a profiling annotation would turn a
    bookkeeping slip into an outage)."""
    import logging

    from deepspeed_tpu.accelerator import get_accelerator
    from deepspeed_tpu.utils.logging import logger

    acc = get_accelerator()
    while acc._ranges():                 # drain any leftover ranges
        acc._ranges().pop()
    acc._unbalanced_pop_warned = False   # other tests may have tripped it

    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    handler = Capture(level=logging.WARNING)
    logger.addHandler(handler)
    try:
        range_pop()                      # empty stack: warn + no-op
        range_pop()                      # repeat pops are throttled:
        range_pop()                      # one warning per process, not
        range_pop()                      # one per hot-loop iteration
    finally:
        logger.removeHandler(handler)
    assert sum("unbalanced" in r.getMessage() for r in records) == 1
    # balanced usage does not warn
    records.clear()
    acc._unbalanced_pop_warned = False
    logger.addHandler(handler)
    try:
        range_push("outer")
        range_pop()
    finally:
        logger.removeHandler(handler)
    assert not any("unbalanced" in r.getMessage() for r in records)


def test_resume_past_window_marks_done_without_capturing(tmp_path,
                                                         monkeypatch):
    """Checkpoint resume past the configured window: no capture, done."""
    import jax

    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda *a, **k: calls.append(a))
    tp = TraceProfiler(str(tmp_path / "t"), start_step=1, num_steps=3)
    tp.maybe_start(10)                   # resumed at step 10
    assert tp.done and not tp.active
    assert calls == []                   # start_trace never touched
    tp.maybe_start(2)                    # done is sticky
    assert calls == [] and not tp.active


def test_start_trace_failure_degrades_to_disabled(tmp_path, monkeypatch):
    """A profiler already active elsewhere must not kill the train loop:
    the window degrades to disabled and every later call is a no-op."""
    import jax

    def boom(*a, **k):
        raise RuntimeError("profiler already active")

    stops = []
    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: stops.append(1))
    tp = TraceProfiler(str(tmp_path / "t"), start_step=1, num_steps=2)
    tp.maybe_start(1)
    assert tp.done and not tp.active
    with tp.step(1):                     # degraded: nullcontext
        pass
    tp.maybe_stop(3)
    tp.close()
    assert stops == []                   # nothing was ever started


def test_close_flushes_in_window_run(tmp_path, monkeypatch):
    """A run that ends inside the capture window still writes its trace:
    close() stops exactly once, then becomes a no-op."""
    import jax

    starts, stops = [], []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda *a, **k: starts.append(a))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: stops.append(1))
    tp = TraceProfiler(str(tmp_path / "t"), start_step=2, num_steps=5)
    tp.maybe_start(2)
    assert tp.active and len(starts) == 1
    tp.close()                           # run ended at step 3 of 7
    assert stops == [1]
    assert tp.done and not tp.active
    tp.close()                           # idempotent
    tp.maybe_start(3)                    # and sticky-done
    assert stops == [1] and len(starts) == 1


def test_standalone_window_bounds(tmp_path):
    tp = TraceProfiler(str(tmp_path / "t"), start_step=3, num_steps=1)
    tp.maybe_start(1)
    assert not tp.active          # before the window
    tp.maybe_start(3)
    assert tp.active
    with tp.step(3):
        pass
    tp.maybe_stop(3)
    assert tp.active              # window not elapsed (needs step 4)
    tp.maybe_stop(4)
    assert tp.done and not tp.active
    tp.maybe_start(5)
    assert not tp.active          # one-shot