"""XPlane trace capture (utils/trace.py; ref utils/nvtx.py +
pytorch-profiler integration): windowed engine capture writes a trace
directory; annotations are free when no capture is active."""

import os

import numpy as np

from deepspeed_tpu.utils.trace import (TraceProfiler, instrument_w_trace,
                                       range_pop, range_push)


def test_instrument_and_ranges_no_capture():
    @instrument_w_trace
    def f(x):
        return x + 1

    assert f(1) == 2

    @instrument_w_trace(name="custom")
    def g(x):
        return x * 2

    assert g(3) == 6
    range_push("outer")
    range_push("inner")
    range_pop()
    range_pop()
    range_pop()  # underflow is a no-op


def test_engine_windowed_capture(tmp_path):
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.parallel import topology

    out = str(tmp_path / "trace")
    model = get_model_config("gpt2-tiny")
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "profiler": {"enabled": True, "output_dir": out,
                     "start_step": 2, "num_steps": 2},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(8, 33), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    for _ in range(5):
        loss = engine.train_batch(batch)
    assert np.isfinite(float(np.asarray(loss)))
    tp = engine._trace_profiler
    assert tp.done and not tp.active
    # a plugin/profile dir with at least one .xplane.pb artifact appeared
    found = [os.path.join(r, f) for r, _, fs in os.walk(out) for f in fs]
    assert any(f.endswith(".xplane.pb") for f in found), found


def test_standalone_window_bounds(tmp_path):
    tp = TraceProfiler(str(tmp_path / "t"), start_step=3, num_steps=1)
    tp.maybe_start(1)
    assert not tp.active          # before the window
    tp.maybe_start(3)
    assert tp.active
    with tp.step(3):
        pass
    tp.maybe_stop(3)
    assert tp.active              # window not elapsed (needs step 4)
    tp.maybe_stop(4)
    assert tp.done and not tp.active
    tp.maybe_start(5)
    assert not tp.active          # one-shot