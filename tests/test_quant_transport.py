"""ZeRO++ transport proof: the quantized collectives must MOVE int8.

Ref VERDICT r3 Missing #5 / Next #6: qwZ/qgZ promise bandwidth wins from
int8 wire traffic (ref csrc/quantization/swizzled_quantize.cu,
runtime/comm/coalesced_collectives.py:31) — these tests pin, at the
compiled-HLO level, that the all-gather (qwZ) and all-to-alls (qgZ)
transport s8 payloads and that no full-size float collective remains.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm.coalesced_collectives import all_to_all_quant_reduce
from deepspeed_tpu.parallel.topology import MeshTopology, set_topology
from deepspeed_tpu.utils.jax_compat import shard_map


def _reset():
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None


def _collective_ops(hlo: str, op: str):
    """[(dtype, total_elements)] for each `op` instruction in the HLO."""
    out = []
    for line in hlo.splitlines():
        m = re.search(rf"= \(?([a-z0-9]+)\[([0-9,]*)\][^=]*{op}\(", line)
        if m:
            dims = [int(x) for x in m.group(2).split(",") if x]
            out.append((m.group(1), int(np.prod(dims)) if dims else 1))
    return out


def test_qwz_all_gather_moves_int8():
    from deepspeed_tpu.parallel.sharding import ShardingRules
    from deepspeed_tpu.parallel.zeropp import qwz_weight_gather

    topo = MeshTopology({"data": 8})
    set_topology(topo)
    try:
        rules = ShardingRules(topo, zero_stage=3)
        L, n, h = 2, 4096, 512  # matches the mlp/wi rule (layer, embed, mlp)
        total = L * n * h
        params = {"layers": {"mlp": {"wi": jnp.ones((L, n, h),
                                               jnp.float32)}}}
        specs = rules.tree_specs(params)
        assert any(s is not None
                   for s in specs["layers"]["mlp"]["wi"]), specs
        sharded = jax.device_put(params, rules.tree_shardings(params))

        def f(p):
            g = qwz_weight_gather(p, rules)
            return g["layers"]["mlp"]["wi"].astype(jnp.float32).sum()

        hlo = jax.jit(f).lower(sharded).compile().as_text()
        ags = _collective_ops(hlo, "all-gather")
        assert any(dt == "s8" and size >= total for dt, size in ags), ags
        # no full-size float gather may remain (scales are size/group ≈
        # 1/256 of the payload; allow anything an order below full size)
        big_float = [a for a in ags
                     if a[0] in ("f32", "bf16", "f16") and a[1] >= total // 4]
        assert not big_float, ags
    finally:
        set_topology(None)
        _reset()


def test_qgz_all_to_all_moves_int8():
    devices = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = jax.sharding.Mesh(devices, ("outer", "inner"))
    grads = {"w": jnp.ones((64, 1024), jnp.float32)}

    def f(g):
        shard, _ = all_to_all_quant_reduce(g, "inner", "outer",
                                           inner_size=4, outer_size=2)
        return shard.sum()

    fn = shard_map(lambda g: (f(g),), mesh=mesh,
                       in_specs=(jax.tree.map(lambda _: P(), grads),),
                       out_specs=(P(),), check_vma=False)
    hlo = jax.jit(lambda g: fn(g)[0]).lower(grads).compile().as_text()
    a2a = _collective_ops(hlo, "all-to-all")
    assert a2a, "no all-to-all in compiled qgZ"
    s8 = [a for a in a2a if a[0] == "s8"]
    assert len(s8) >= 2, a2a  # both hierarchy levels move int8 payloads
    # float all-to-alls are only the tiny scale tensors
    total = 64 * 1024
    big_float = [a for a in a2a
                 if a[0] in ("f32", "bf16") and a[1] >= total // 4]
    assert not big_float, a2a
