"""Long-context: FPDT chunked attention, chunked FFN, ALST tiled MLP /
tiled loss, SP dataloader sharding.

Mirrors the reference's op-vs-reference test style (tests/unit/ops/) and
sequence-parallel coverage (tests/unit/sequence_parallelism/)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.sequence import (SPDataLoader, chunked_attention,
                                    chunked_ffn, sp_shard_batch,
                                    tiled_logits_loss, tiled_mlp)


def _ref_attention(q, k, v, causal):
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k)
    if causal:
        mask = np.tril(np.ones((q.shape[1], k.shape[1]), bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("chunk", [8, 16])
def test_chunked_attention_matches_full(causal, chunk):
    rng = np.random.default_rng(0)
    b, s, h, d = 2, 64, 4, 16
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    out = jax.jit(lambda q, k, v: chunked_attention(q, k, v, chunk, causal))(q, k, v)
    ref = _ref_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_chunked_attention_gqa():
    rng = np.random.default_rng(1)
    b, s, hq, hkv, d = 1, 32, 8, 2, 8
    q = jnp.asarray(rng.standard_normal((b, s, hq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)
    out = chunked_attention(q, k, v, 8, causal=True)
    ref = _ref_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_chunked_attention_grad_matches():
    rng = np.random.default_rng(2)
    b, s, h, d = 1, 32, 2, 8
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    g_chunk = jax.grad(lambda q: chunked_attention(q, k, v, 8).sum())(q)
    g_ref = jax.grad(lambda q: _ref_attention(q, k, v, True).sum())(q)
    np.testing.assert_allclose(np.asarray(g_chunk), np.asarray(g_ref),
                               atol=5e-5, rtol=5e-5)


def test_chunked_ffn_matches():
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((2, 32, 16)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((16, 16)), jnp.float32)
    fn = lambda t: jax.nn.gelu(t @ w)  # noqa: E731
    out = chunked_ffn(fn, x, num_chunks=4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(fn(x)), atol=1e-6)


def test_tiled_mlp_matches():
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((2, 24, 8)), jnp.float32)
    w1 = jnp.asarray(rng.standard_normal((8, 32)), jnp.float32)
    w2 = jnp.asarray(rng.standard_normal((32, 8)), jnp.float32)
    fn = lambda t: jax.nn.silu(t @ w1) @ w2  # noqa: E731
    out = tiled_mlp(fn, x, num_tiles=3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(fn(x)), atol=1e-6)
    # gradient flows through the scan+remat
    g = jax.grad(lambda w: tiled_mlp(lambda t: jax.nn.silu(t @ w) @ w2, x, 3).sum())(w1)
    g_ref = jax.grad(lambda w: (jax.nn.silu(x @ w) @ w2).sum())(w1)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=1e-5, rtol=1e-5)


def test_tiled_logits_loss_matches_full():
    rng = np.random.default_rng(5)
    b, s, e, v = 2, 16, 8, 32
    hidden = jnp.asarray(rng.standard_normal((b, s, e)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((v, e)), jnp.float32)
    labels = rng.integers(0, v, size=(b, s)).astype(np.int32)
    labels[0, :3] = -100  # ignore some
    labels = jnp.asarray(labels)

    loss, count = tiled_logits_loss(hidden, w, labels, num_tiles=4)
    logits = jnp.einsum("bse,ve->bsv", hidden, w)
    lse = jax.nn.logsumexp(logits, axis=-1)
    safe = jnp.where(labels == -100, 0, labels)
    gold = jnp.take_along_axis(logits, safe[..., None], -1)[..., 0]
    valid = labels != -100
    ref = jnp.where(valid, lse - gold, 0.0).sum() / valid.sum()
    assert int(count) == int(valid.sum())
    np.testing.assert_allclose(float(loss), float(ref), atol=1e-5, rtol=1e-5)


def test_tiled_logits_loss_grad():
    rng = np.random.default_rng(6)
    b, s, e, v = 1, 8, 4, 16
    hidden = jnp.asarray(rng.standard_normal((b, s, e)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((v, e)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, size=(b, s)).astype(np.int32))
    g = jax.grad(lambda h: tiled_logits_loss(h, w, labels, 2)[0])(hidden)

    def full(h):
        logits = jnp.einsum("bse,ve->bsv", h, w)
        lse = jax.nn.logsumexp(logits, -1)
        gold = jnp.take_along_axis(logits, labels[..., None], -1)[..., 0]
        return (lse - gold).mean()

    g_ref = jax.grad(full)(hidden)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=1e-5, rtol=1e-5)


def test_fpdt_attention_under_sp_mesh():
    """FPDTAttention = Ulysses a2a + chunked streaming attention, on a real
    4-way seq mesh (virtual CPU devices)."""
    from deepspeed_tpu.parallel import topology as topo_mod
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology
    from deepspeed_tpu.sequence import FPDTAttention

    rng = np.random.default_rng(7)
    b, s, h, d = 2, 32, 4, 8
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    try:
        set_topology(MeshTopology({"data": 2, "seq": 4}))
        out = FPDTAttention(chunk_size=8)(q, k, v)
    finally:
        topo_mod._GLOBAL_TOPOLOGY = None
    ref = _ref_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_sp_shard_batch():
    batch = {"input_ids": np.arange(32).reshape(2, 16),
             "labels": np.arange(32).reshape(2, 16),
             "meta": "keep"}
    s0 = sp_shard_batch(batch, 0, 4)
    s3 = sp_shard_batch(batch, 3, 4)
    assert s0["input_ids"].shape == (2, 4)
    np.testing.assert_array_equal(s0["input_ids"], batch["input_ids"][:, :4])
    np.testing.assert_array_equal(s3["labels"], batch["labels"][:, 12:])
    assert s0["meta"] == "keep"
    with pytest.raises(ValueError):
        sp_shard_batch(batch, 0, 5)


def test_sp_dataloader_iterates():
    data = [{"input_ids": np.arange(16).reshape(2, 8)} for _ in range(3)]
    dl = SPDataLoader(data, sp_rank=1, sp_size=2)
    out = list(dl)
    assert len(out) == 3 and len(dl) == 3
    np.testing.assert_array_equal(out[0]["input_ids"], data[0]["input_ids"][:, 4:])
