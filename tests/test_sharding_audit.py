"""Flagship-config sharding audit: no silent replication fallback.

Ref VERDICT r3 Weak #4: ``spec_for``'s divisibility fallback replicates a
param with only a log warning, quietly degrading ZeRO-3 to ZeRO-1 for that
tensor.  These tests pin that (a) the flagship llama3-8b / gpt2-350m
geometries shard every >1MB param under ZeRO-3 on 8 devices, and (b)
``zero_optimization.strict_sharding`` turns the fallback into a hard error.
"""

from functools import partial

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import get_model_config
from deepspeed_tpu.models.transformer import init_params
from deepspeed_tpu.parallel.sharding import ShardingRules
from deepspeed_tpu.parallel.topology import MeshTopology, set_topology


def _reset_topo():
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None


@pytest.mark.parametrize("name", ["llama3-8b", "gpt2-350m",
                                  "qwen2moe-a14b"])
@pytest.mark.parametrize("mesh", [{"data": 8}, {"data": 4, "tensor": 2}])
def test_flagship_zero3_big_params_all_sharded(name, mesh):
    cfg = get_model_config(name, num_layers=2)
    topo = MeshTopology(dict(mesh))
    set_topology(topo)
    try:
        rules = ShardingRules(topo, zero_stage=3)
        shapes = jax.eval_shape(partial(init_params, cfg),
                                jax.random.PRNGKey(0))
        offenders = rules.audit_replicated(shapes)
        assert offenders == [], offenders
        # and every >1MB param's spec names at least one mesh axis whose
        # size divides that dim (the spec is actually placeable)
        specs = rules.tree_specs(shapes)

        def check(path, leaf, spec):
            nbytes = int(np.prod(np.shape(leaf))) * leaf.dtype.itemsize
            if nbytes < (1 << 20):
                return
            assert any(s is not None for s in spec), (path, spec)
            for dim, s in zip(np.shape(leaf), spec):
                if s is None:
                    continue
                axes = s if isinstance(s, tuple) else (s,)
                world = int(np.prod([topo.axis_size(a) for a in axes]))
                assert dim % world == 0, (path, dim, s)

        jax.tree_util.tree_map_with_path(
            lambda p, l, sp: check(p, l, sp), shapes, specs)
    finally:
        set_topology(None)
        _reset_topo()


def test_strict_sharding_raises_on_indivisible_param():
    from deepspeed_tpu.runtime.config import DeepSpeedConfigError

    # vocab 4001 / hidden 252: no dim of the 4MB embed table divides the
    # 8-way fsdp world → replication fallback → strict mode must refuse
    cfg = get_model_config("gpt2-tiny", vocab_size=4001, hidden_size=252,
                           intermediate_size=1008, num_heads=4)
    conf = {"train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3, "strict_sharding": True},
            "mesh": {"data": 8}}
    with pytest.raises(DeepSpeedConfigError, match="REPLICATED"):
        ds.initialize(model=cfg, config=conf)
    _reset_topo()


def test_audit_silent_on_single_device_world():
    cfg = get_model_config("gpt2-tiny")
    topo = MeshTopology({"data": 1})
    set_topology(topo)
    try:
        rules = ShardingRules(topo, zero_stage=3)
        shapes = jax.eval_shape(partial(init_params, cfg),
                                jax.random.PRNGKey(0))
        assert rules.audit_replicated(shapes, min_bytes=0) == []
    finally:
        set_topology(None)
        _reset_topo()


def test_param_persistence_threshold_keeps_small_params_gathered():
    """ref param_persistence_threshold (runtime/zero/config.py): under
    ZeRO-3, params below the element threshold stay gathered (no per-use
    all-gather) while their optimizer state stays partitioned."""
    # full 32-layer llama3-8b depth: stacked norm scales are [32, 4096] =
    # 131,072 elements — ABOVE the threshold as a stacked array but 4,096
    # per parameter, so this catches a per-array (rather than
    # per-parameter) comparison
    cfg = get_model_config("llama3-8b")
    topo = MeshTopology({"data": 8})
    set_topology(topo)
    try:
        rules = ShardingRules(topo, zero_stage=3, persist_threshold=100_000)
        shapes = jax.eval_shape(partial(init_params, cfg),
                                jax.random.PRNGKey(0))
        specs = rules.tree_specs(shapes)
        # norms (1024 elems) persist; big matrices stay fsdp-sharded
        assert all(s is None for s in
                   specs["layers"]["ln1"]["scale"]), specs["layers"]["ln1"]
        assert any(s is not None for s in
                   specs["layers"]["mlp"]["wi"])
        # optimizer-state view still partitions the small params
        opt_specs = rules.tree_specs(shapes, param_style=False)
        assert any(s is not None for s in
                   opt_specs["layers"]["ln1"]["scale"])
    finally:
        set_topology(None)
        _reset_topo()
