"""dstpu_bench CLI (ref bin/ds_bench): runs to completion on the CPU
backend with JAX_PLATFORMS pinned — the axon plugin pins jax_platforms
via jax.config, so the CLI must re-pin from the env or a down TPU tunnel
blocks it forever (r04 regression)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dstpu_bench_cpu_pin():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "dstpu_bench"),
         "--sizes-mb", "0.25", "--trials", "1"],
        capture_output=True, text=True, timeout=240, env=env)
    assert out.returncode == 0, out.stderr[-500:]
    rows = [json.loads(l) for l in out.stdout.splitlines()
            if l.startswith("{")]
    ops = {r["op"] for r in rows}
    assert {"all_reduce", "all_gather", "reduce_scatter",
            "all_to_all"} <= ops
    assert all(r["world"] == 4 and r["time_ms"] > 0 for r in rows)
