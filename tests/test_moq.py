"""MoQ scheduled quantization (ref runtime/quantize.py + eigenvalue gate)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.quantize import MoQQuantizer, MoQScheduler


def test_scheduler_halves_bits_with_doubling_period():
    s = MoQScheduler(start_bits=16, target_bits=4, quantize_period=10)
    assert s.update(0) == 16
    assert s.update(9) == 16
    assert s.update(10) == 8   # first transition
    assert s.update(29) == 8   # period doubled → next at 10+20=30
    assert s.update(30) == 4
    assert s.update(1000) == 4  # clamped at target


def test_scheduler_deferred_transition():
    s = MoQScheduler(start_bits=16, target_bits=8, quantize_period=10)
    assert s.update(10, allow_transition=False) == 16  # gated
    assert s.update(15) == 16  # re-check scheduled at 20
    assert s.update(20) == 8


def test_moq_quantizer_applies_bits():
    rng = np.random.default_rng(0)
    params = {"w": jnp.asarray(rng.standard_normal((32, 64)), jnp.float32),
              "b": jnp.asarray(rng.standard_normal((64,)), jnp.float32)}
    q = MoQQuantizer({"quantize_training": {
        "enabled": True,
        "quantize_bits": {"start_bits": 16, "target_bits": 4},
        "schedule": {"quantize_period": 5},
        "quantize_groups": 32}})
    p0 = q.quantize(params, step=0)
    np.testing.assert_allclose(np.asarray(p0["w"]), np.asarray(params["w"]))
    p8 = q.quantize(params, step=5)   # 8-bit now
    err8 = float(jnp.abs(p8["w"] - params["w"]).max())
    assert 0 < err8 < 0.05
    p4 = q.quantize(params, step=15)  # 4-bit
    err4 = float(jnp.abs(p4["w"] - params["w"]).max())
    assert err4 > err8  # coarser quantization
    # vectors untouched
    np.testing.assert_allclose(np.asarray(p4["b"]), np.asarray(params["b"]))


def test_moq_eigenvalue_gate_defers():
    # sharply curved loss → eigenvalue above threshold → bits stay high
    A = jnp.diag(jnp.asarray([50.0, 1.0], jnp.float32))

    def loss(p):
        return 0.5 * p["x"] @ A @ p["x"]

    params = {"x": jnp.ones((2,), jnp.float32)}
    q = MoQQuantizer({"quantize_training": {
        "enabled": True,
        "quantize_bits": {"start_bits": 16, "target_bits": 8},
        "schedule": {"quantize_period": 2},
        "eigenvalue": {"enabled": True, "threshold": 10.0, "max_iter": 30}}})
    bits = q.current_bits(2, loss_fn=loss, params=params,
                          key=jax.random.PRNGKey(0))
    assert bits == 16  # deferred: eigenvalue ~50 > 10
    assert q._last_eig == pytest.approx(50.0, rel=0.05)
    # flat loss → transition allowed at the re-check step
    flat = lambda p: 0.01 * (p["x"] ** 2).sum()  # noqa: E731
    bits = q.current_bits(4, loss_fn=flat, params=params,
                          key=jax.random.PRNGKey(0))
    assert bits == 8


def test_moq_state_roundtrip():
    s = MoQScheduler(16, 4, 10)
    s.update(10)
    sd = s.state_dict()
    s2 = MoQScheduler(16, 4, 10)
    s2.load_state_dict(sd)
    assert s2.update(30) == s.update(30) == 4
