"""Overlap-driven step scheduling (autotuning/overlap_scheduler.py):
the synthetic-report decision matrix, the frozen step_schedule config
block, the CPU capture degradation that feeds it, the three knob-family
actuations in the engine, and the end-to-end probe→decide→pin loop."""

import json

import jax
import numpy as np
import pytest

from deepspeed_tpu.autotuning.overlap_scheduler import (EVIDENCE_KEYS,
                                                        OverlapScheduler,
                                                        ScheduleDecision,
                                                        decide,
                                                        ensure_schedule,
                                                        extract_evidence)
from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                          DeepSpeedConfigError)


def _base_knobs(**over):
    base = {"gather_prefetch_depth": 1,
            "param_persistence_threshold": 100_000,
            "prefetch_bucket_size": 50_000_000,
            "ring_interleave": 1,
            "weight_update": "fused"}
    base.update(over)
    return base


def _xplane_report(overlap, dominant="all-reduce.7", coll_ms=10.0, step=4):
    return {"devices": {"/device:TPU:0": {"overlap_fraction": overlap,
                                          "collective_ms": coll_ms,
                                          "compute_ms": 20.0}},
            "overlap_fraction": overlap,
            "dominant_collective": ({"name": dominant, "total_ms": coll_ms}
                                    if dominant else None),
            "top_ops": [], "spans": {}, "step": step}


# ----------------------------------------------------------------------
# decision matrix (pure, synthetic reports)
# ----------------------------------------------------------------------
def test_decide_low_overlap_zero3_deepens_prefetch():
    ctx = {"zero_stage": 3, "dp": 8, "sp": 1, "seq_impl": "ulysses",
           "base": _base_knobs()}
    updates, decisions = decide(_xplane_report(0.2, "all-gather.3"), ctx)
    names = [d.decision for d in decisions]
    assert names == ["zero3_prefetch"]
    assert updates["gather_prefetch_depth"] == 2
    assert updates["param_persistence_threshold"] == 1_000_000  # next rung
    assert updates["prefetch_bucket_size"] == 100_000_000
    # the ladder keeps climbing from wherever the base sits, and the
    # prefetch depth is capped
    ctx["base"] = _base_knobs(param_persistence_threshold=1_000_000,
                              gather_prefetch_depth=4)
    updates, _ = decide(_xplane_report(0.2, "all-gather.3"), ctx)
    assert updates["param_persistence_threshold"] == 10_000_000
    assert updates["gather_prefetch_depth"] == 4


def test_decide_reduce_dominated_picks_decomposed_update():
    ctx = {"zero_stage": 1, "dp": 8, "sp": 1, "seq_impl": "ulysses",
           "base": _base_knobs()}
    updates, decisions = decide(_xplane_report(0.15, "all-reduce.7"), ctx)
    assert [d.decision for d in decisions] == ["decomposed_update"]
    assert updates == {"weight_update": "decomposed"}
    # gather-dominated at stage 1 is NOT a decomposition signal (the
    # all-reduce it replaces isn't what is exposed) → noop
    _, decisions = decide(_xplane_report(0.15, "all-gather.3"), ctx)
    assert [d.decision for d in decisions] == ["noop"]
    # dp=1 has nothing to decompose over
    ctx_dp1 = dict(ctx, dp=1)
    _, decisions = decide(_xplane_report(0.15, "all-reduce.7"), ctx_dp1)
    assert [d.decision for d in decisions] == ["noop"]


def test_decide_ring_low_overlap_picks_interleave():
    ctx = {"zero_stage": 0, "dp": 2, "sp": 4, "seq_impl": "ring",
           "base": _base_knobs()}
    updates, decisions = decide(
        _xplane_report(0.3, "collective-permute.11"), ctx)
    assert "ring_interleave" in [d.decision for d in decisions]
    assert updates["ring_interleave"] == 2
    # already interleaved → nothing more to do on this family
    ctx["base"] = _base_knobs(ring_interleave=2)
    updates, decisions = decide(
        _xplane_report(0.3, "collective-permute.11"), ctx)
    assert "ring_interleave" not in [d.decision for d in decisions]


def test_decide_high_overlap_noop():
    ctx = {"zero_stage": 3, "dp": 8, "sp": 4, "seq_impl": "ring",
           "base": _base_knobs()}
    updates, decisions = decide(_xplane_report(0.92, "all-gather.3"), ctx)
    assert updates == {}
    assert [d.decision for d in decisions] == ["noop"]
    ev = decisions[0].evidence
    assert sorted(ev) == sorted(EVIDENCE_KEYS)
    assert ev["overlap_source"] == "xplane"
    assert ev["overlap_fraction"] == pytest.approx(0.92)
    # exposed = collective_ms * (1 - overlap)
    assert ev["exposed_comm_ms"] == pytest.approx(10.0 * 0.08, abs=1e-3)


def test_exposed_comm_is_per_device_not_world_scaled():
    """Evidence must describe one step on one chip: the per-plane
    collective times average (matching mean_overlap_fraction), they do
    not sum with the device count."""
    rep = {"devices": {f"/device:TPU:{i}": {"overlap_fraction": 0.5,
                                            "collective_ms": 10.0,
                                            "compute_ms": 20.0}
                       for i in range(8)},
           "overlap_fraction": 0.5,
           "dominant_collective": {"name": "all-reduce.1",
                                   "total_ms": 10.0},
           "spans": {}, "step": 2}
    ev = extract_evidence(rep, {})
    assert ev["exposed_comm_ms"] == pytest.approx(10.0 * 0.5, abs=1e-3)


def test_span_window_degrades_when_tracer_ring_wraps():
    """The tracer's event ring is bounded: if it wrapped during the
    capture window, the base/now diff would under-count — the spans
    estimate must be omitted, not reported wrong."""
    import types

    from deepspeed_tpu.runtime.config import TelemetryCaptureConfig
    from deepspeed_tpu.telemetry.capture import AutoCapture

    class StubTracer:
        enabled = True

        def __init__(self):
            self.dropped_events = 0
            self._totals = {}

        def summary(self):
            return {k: dict(v) for k, v in self._totals.items()}

    tr = StubTracer()
    cap = AutoCapture(TelemetryCaptureConfig(enabled=True,
                                             output_dir="unused"),
                      telemetry=types.SimpleNamespace(tracer=tr))
    tr._totals = {"train.sync": {"count": 1, "total_ms": 5.0}}
    cap._span_base = cap._span_totals()
    tr._totals = {"train.sync": {"count": 3, "total_ms": 12.0}}
    assert cap._span_window() == {"train.sync": {"count": 2,
                                                 "total_ms": 7.0}}
    tr.dropped_events = 5     # ring wrapped mid-window
    assert cap._span_window() is None


def test_schedule_decision_frozen_vocabulary():
    ev = {k: 1 for k in EVIDENCE_KEYS}
    with pytest.raises(ValueError, match="unknown schedule decision"):
        ScheduleDecision("turbo_mode", {}, ev)
    with pytest.raises(ValueError, match="missing"):
        ScheduleDecision("noop", {}, {"overlap_fraction": 0.5})
    d = ScheduleDecision("noop", {}, ev)
    assert ScheduleDecision.from_dict(d.to_dict()) == d
    # a report with neither device planes nor spans is refused
    with pytest.raises(ValueError, match="neither device planes"):
        extract_evidence({"devices": {}, "spans": {}}, {})


# ----------------------------------------------------------------------
# step_schedule config block
# ----------------------------------------------------------------------
def test_step_schedule_config_round_trip():
    block = {"mode": "pinned", "probe_steps": 2, "overlap_threshold": 0.4,
             "gather_prefetch_depth": 2,
             "param_persistence_threshold": 1_000_000,
             "prefetch_bucket_size": 100_000_000,
             "ring_interleave": 2, "weight_update": "decomposed",
             "decisions": [{"decision": "zero3_prefetch",
                            "knobs": {"gather_prefetch_depth": 2},
                            "evidence": {k: 1 for k in EVIDENCE_KEYS}}]}
    # survive a JSON round trip (what a pinned config file is)
    cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                           "step_schedule":
                           json.loads(json.dumps(block))})
    ss = cfg.step_schedule
    assert (ss.mode, ss.weight_update, ss.ring_interleave) == \
        ("pinned", "decomposed", 2)
    assert ss.param_persistence_threshold == 1_000_000
    assert ss.decisions[0]["decision"] == "zero3_prefetch"
    # the default block is static and changes nothing
    ss0 = DeepSpeedConfig(
        {"train_micro_batch_size_per_gpu": 1}).step_schedule
    assert (ss0.mode, ss0.weight_update, ss0.ring_interleave) == \
        ("static", "fused", 1)
    assert ss0.param_persistence_threshold is None


def test_step_schedule_rejects_unknown_names():
    for bad in ({"weight_update": "sharded"}, {"mode": "autodetect"},
                {"ring_interleave": 3}, {"probe_steps": 0},
                {"overlap_threshold": 1.5},
                {"decisions": [{"decision": "turbo_mode", "knobs": {},
                                "evidence": {}}]}):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                             "step_schedule": bad})


# ----------------------------------------------------------------------
# CPU capture degradation (satellite): the report carries the step and a
# spans estimate the scheduler accepts
# ----------------------------------------------------------------------
def test_cpu_capture_report_feeds_scheduler(tmp_path, rng):
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config

    model = get_model_config("gpt2-tiny")
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "steps_per_print": 10_000,
        "telemetry": {
            "enabled": True,
            "capture": {"enabled": True, "capture_step": 2,
                        "num_steps": 1, "budget": 1,
                        "output_dir": str(tmp_path)},
            "tracing": {"enabled": True},
        },
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    ids = rng.integers(0, model.vocab_size, size=(8, 33), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    for _ in range(2):
        engine.train_batch(batch)
    engine.destroy()
    assert engine.telemetry.capture.reports
    with open(engine.telemetry.capture.reports[0]) as f:
        rep = json.load(f)
    # no bare 0.0 + note: the report carries the step index and a spans
    # block with real (nonzero) decision inputs
    assert rep["step"] == 2
    spans = rep["spans"]
    assert spans["step_ms"] > 0
    assert spans["sync_ms"] >= 0
    assert 0.0 <= spans["overlap_estimate"] <= 1.0
    ctx = {"zero_stage": 0, "dp": 8, "sp": 1, "seq_impl": "ulysses",
           "base": _base_knobs()}
    ev = extract_evidence(rep, ctx)
    assert ev["overlap_source"] == "spans" or rep["devices"]
    assert ev["probe_step"] == 2
    # the scheduler accepts the report: decide() runs and returns a
    # decision whose evidence is populated
    _, decisions = decide(rep, ctx, overlap_threshold=1.0)
    assert decisions and sorted(decisions[0].evidence) == \
        sorted(EVIDENCE_KEYS)


# ----------------------------------------------------------------------
# knob family (a): ZeRO-3 gather scheduling actually actuates
# ----------------------------------------------------------------------
def _tiny_engine(config_extra, rng, model_kw=None, steps=0):
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config

    model = get_model_config("gpt2-tiny", **(model_kw or {}))
    config = {"train_micro_batch_size_per_gpu": 1,
              "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
              "steps_per_print": 10_000, **config_extra}
    engine, _, _, _ = ds.initialize(model=model, config=config)
    losses = []
    if steps:
        rows = engine.train_batch_size_value
        ids = rng.integers(0, model.vocab_size, size=(rows, 33),
                           dtype=np.int32)
        batch = {"input_ids": ids[:, :-1],
                 "labels": ids[:, 1:].astype(np.int32)}
        losses = [float(np.asarray(engine.train_batch(batch)))
                  for _ in range(steps)]
    return engine, losses


def test_persistence_threshold_actuates_param_sharding(rng):
    from deepspeed_tpu.parallel import topology as topo_mod

    # default threshold (100k): gpt2-tiny norms (128 elems/param) persist
    # — replicated despite ZeRO-3
    eng_a, _ = _tiny_engine({"zero_optimization": {"stage": 3},
                             "mesh": {"data": 8}}, rng)
    spec_a = eng_a.params["final_norm"]["scale"].sharding.spec
    topo_mod._GLOBAL_TOPOLOGY = None
    # pinned threshold 0: nothing persists, the norm is sharded — the
    # engine's physical layout changed, not just a config value
    eng_b, _ = _tiny_engine({"zero_optimization": {"stage": 3},
                             "mesh": {"data": 8},
                             "step_schedule":
                             {"mode": "pinned",
                              "param_persistence_threshold": 0}}, rng)
    spec_b = eng_b.params["final_norm"]["scale"].sharding.spec
    assert all(ax is None for ax in spec_a)
    assert any(ax is not None for ax in spec_b)


def test_gather_prefetch_depth_unrolls_layer_scan(rng):
    from deepspeed_tpu.parallel import topology as topo_mod

    ids = rng.integers(0, 512, size=(2, 17), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1],
             "labels": ids[:, 1:].astype(np.int32)}
    eng_a, _ = _tiny_engine({"zero_optimization": {"stage": 3},
                             "mesh": {"data": 8}}, rng)
    jaxpr_a = str(jax.make_jaxpr(eng_a._loss_fn)(eng_a.params, batch))
    topo_mod._GLOBAL_TOPOLOGY = None
    eng_b, _ = _tiny_engine({"zero_optimization": {"stage": 3},
                             "mesh": {"data": 8},
                             "step_schedule":
                             {"mode": "pinned",
                              "gather_prefetch_depth": 2}}, rng)
    assert eng_b.model_config.scan_unroll == 2
    jaxpr_b = str(jax.make_jaxpr(eng_b._loss_fn)(eng_b.params, batch))
    # the unrolled layer scan is a different program (fewer scan steps,
    # doubled body) — the window XLA can hoist a gather across widened
    assert jaxpr_a != jaxpr_b
    topo_mod._GLOBAL_TOPOLOGY = None
    # a depth that does not divide the layer count is clamped to the
    # largest honored divisor — never pinned as a silent no-op
    eng_c, _ = _tiny_engine({"zero_optimization": {"stage": 3},
                             "mesh": {"data": 8},
                             "step_schedule":
                             {"mode": "pinned",
                              "gather_prefetch_depth": 2}}, rng,
                            model_kw={"num_layers": 3})
    assert eng_c.model_config.scan_unroll == 1


# ----------------------------------------------------------------------
# knob family (b): ring hop interleave
# ----------------------------------------------------------------------
def test_ring_interleave_parity_and_program_change():
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology
    from deepspeed_tpu.sequence.ring import ring_attention

    topo = MeshTopology({"seq": 4, "data": 2})
    set_topology(topo)
    try:
        rng = np.random.default_rng(0)
        q = np.asarray(rng.standard_normal((2, 32, 4, 16)), np.float32)
        k = np.asarray(rng.standard_normal((2, 32, 4, 16)), np.float32)
        v = np.asarray(rng.standard_normal((2, 32, 4, 16)), np.float32)

        def fwd(i):
            return jax.jit(lambda q, k, v: ring_attention(
                q, k, v, topo, interleave=i))(q, k, v)

        o1, o2 = fwd(1), fwd(2)
        # the interleave only reorders the permute issue — same math,
        # bit-identical output
        assert np.array_equal(np.asarray(o1), np.asarray(o2))
        j1 = str(jax.make_jaxpr(lambda q, k, v: ring_attention(
            q, k, v, topo, interleave=1))(q, k, v))
        j2 = str(jax.make_jaxpr(lambda q, k, v: ring_attention(
            q, k, v, topo, interleave=2))(q, k, v))
        # ...but the issued program differs (rotate-ahead hop schedule)
        assert j1 != j2
        # gradients stay bit-identical too (the backward splits the
        # fused rotation; accumulation order is unchanged)
        def loss(i):
            f = lambda q, k, v: ring_attention(  # noqa: E731
                q, k, v, topo, interleave=i).astype(np.float32).sum()
            return jax.jit(jax.grad(f))(q, k, v)

        g1, g2 = loss(1), loss(2)
        assert np.array_equal(np.asarray(g1), np.asarray(g2))
        with pytest.raises(ValueError, match="interleave"):
            ring_attention(q, k, v, topo, interleave=3)
    finally:
        set_topology(None)


def test_ring_interleave_reaches_engine_model(rng):
    eng, losses = _tiny_engine(
        {"mesh": {"seq": 4, "data": 2},
         "sequence_parallel_size": 4,
         "step_schedule": {"mode": "pinned", "ring_interleave": 2}},
        rng, model_kw={"seq_impl": "ring"}, steps=1)
    assert eng.model_config.ring_interleave == 2
    assert np.isfinite(losses[0])


# ----------------------------------------------------------------------
# knob family (c): decomposed weight update
# ----------------------------------------------------------------------
def test_decomposed_update_shards_state_and_matches_fused(rng):
    from deepspeed_tpu.parallel import topology as topo_mod

    base = {"zero_optimization": {"stage": 1}, "mesh": {"data": 8}}
    eng_f, losses_f = _tiny_engine(dict(base), np.random.default_rng(1),
                                   steps=3)
    # stage 1 keeps the grad accumulator replicated (all-reduce layout)
    grad_spec_f = eng_f.grad_shardings["final_norm"]["scale"].spec
    assert all(ax is None for ax in grad_spec_f)
    assert not eng_f._decomposed_update
    topo_mod._GLOBAL_TOPOLOGY = None

    eng_d, losses_d = _tiny_engine(
        {**base, "step_schedule": {"mode": "pinned",
                                   "weight_update": "decomposed"}},
        np.random.default_rng(1), steps=3)
    assert eng_d._decomposed_update
    # the accumulator is physically sharded over the ZeRO axes →
    # reduce-scatter + 1/world update + params all-gather
    grad_spec_d = eng_d.grad_shardings["final_norm"]["scale"].spec
    assert any(ax is not None for ax in grad_spec_d)
    opt_leaves = [x for x in jax.tree.leaves(eng_d.opt_state)
                  if hasattr(x, "sharding") and np.ndim(x) > 0]
    assert any(any(ax is not None for ax in x.sharding.spec)
               for x in opt_leaves)
    # same data, same math — the decomposed schedule changes the
    # collective pattern, not the numerics
    np.testing.assert_allclose(losses_f, losses_d, rtol=0, atol=2e-5)
    topo_mod._GLOBAL_TOPOLOGY = None

    # stage 0 (pure DP, everything replicated) decomposes too — and the
    # optimizer build sees the sharded state (fused-kernel downgrade)
    eng_0, losses_0 = _tiny_engine(
        {"zero_optimization": {"stage": 0}, "mesh": {"data": 8},
         "step_schedule": {"mode": "pinned",
                           "weight_update": "decomposed"}},
        np.random.default_rng(1), steps=1)
    assert eng_0._decomposed_update
    assert any(ax is not None
               for ax in eng_0.grad_shardings["final_norm"]["scale"].spec)
    assert np.isfinite(losses_0[0])


def test_decomposed_update_falls_back_on_single_replica(rng):
    # no >1 ZeRO axis: warn-fallback to the native layout, engine works
    eng, losses = _tiny_engine(
        {"mesh": {"data": 1},
         "step_schedule": {"mode": "pinned",
                           "weight_update": "decomposed"}},
        rng, steps=1)
    assert not eng._decomposed_update
    assert np.isfinite(losses[0])


# ----------------------------------------------------------------------
# acceptance: probe → decide → pin end-to-end on the 8-device CPU mesh
# ----------------------------------------------------------------------
def test_probe_pin_rerun_bit_identical(tmp_path, rng):
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.parallel import topology as topo_mod

    model = get_model_config("gpt2-tiny")
    base = {"train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3},
            "mesh": {"data": 8},
            "steps_per_print": 10_000,
            "step_schedule": {"mode": "probe", "probe_steps": 1,
                              "overlap_threshold": 1.0}}
    ids = rng.integers(0, model.vocab_size, size=(8, 33), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}

    pinned, decisions = ensure_schedule(
        model, base, batch, output_dir=str(tmp_path))
    topo_mod._GLOBAL_TOPOLOGY = None
    fired = [d.decision for d in decisions]
    assert "zero3_prefetch" in fired        # ZeRO-3 + forced low overlap
    ev = decisions[0].evidence
    assert ev["exposed_comm_ms"] >= 0 and ev["probe_step"] > 0
    assert ev["dominant_collective"]
    ss = pinned["step_schedule"]
    assert ss["mode"] == "pinned"
    assert ss["gather_prefetch_depth"] == 2

    def run(config):
        import deepspeed_tpu as ds

        engine, _, _, _ = ds.initialize(model=model, config=config)
        out = [float(np.asarray(engine.train_batch(batch)))
               for _ in range(3)]
        engine.destroy()
        topo_mod._GLOBAL_TOPOLOGY = None
        return out

    # the tuned run, and a re-run from the JSON-round-tripped pinned
    # config (what a config file on disk is): bit-identical numerics
    losses_tuned = run(pinned)
    losses_rerun = run(json.loads(json.dumps(pinned)))
    assert losses_tuned == losses_rerun

    # a pinned config never re-probes: ensure_schedule must return it
    # without building an engine or touching the probe path
    def boom(self, batch):  # pragma: no cover - failing is the assert
        raise AssertionError("pinned config re-probed")

    orig = OverlapScheduler.probe
    OverlapScheduler.probe = boom
    try:
        cfg2, decisions2 = ensure_schedule(model, pinned, batch)
    finally:
        OverlapScheduler.probe = orig
    assert cfg2 is pinned
    assert [d.decision for d in decisions2] == fired
