"""ZeRO-Infinity parameter streaming (runtime/infinity.py): primitive
parity, engine training parity vs the in-HBM run, gradient accumulation,
and the NVMe param tier. Ref test model: tests/unit/runtime/zero
(offload/NVMe checkpointing) in the reference suite."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax import lax

import deepspeed_tpu as ds
from deepspeed_tpu.models import get_model_config
from deepspeed_tpu.runtime import infinity as inf
from tests.conftest import make_lm_batch


def test_streamed_scan_matches_plain_scan():
    L, H, F = 4, 32, 64
    key = jax.random.PRNGKey(0)
    params = {"wi": jax.random.normal(key, (L, H, F), jnp.float32) * 0.05,
              "wo": jax.random.normal(key, (L, F, H), jnp.float32) * 0.05}
    x = jax.random.normal(key, (8, H), jnp.float32)

    def step_fn(lp, h, extras, i):
        return jnp.tanh(h @ lp["wi"]) @ lp["wo"], jnp.zeros((), jnp.float32)

    def loss_s(ph, x):
        h, _ = inf.streamed_scan(step_fn, ph, x, extras=())
        return jnp.mean(h ** 2)

    def loss_p(p, x):
        def body(h, lp):
            return jnp.tanh(h @ lp["wi"]) @ lp["wo"], None

        h, _ = lax.scan(body, x, p)
        return jnp.mean(h ** 2)

    hp = inf.to_host(params)
    np.testing.assert_allclose(float(jax.jit(loss_s)(hp, x)),
                               float(jax.jit(loss_p)(params, x)), rtol=1e-6)
    g1 = jax.jit(jax.grad(loss_s))(hp, x)
    g2 = jax.jit(jax.grad(loss_p))(params, x)
    for k in g1:
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g2[k]),
                                   rtol=1e-5, atol=1e-7)


def test_streamed_update_matches_dense():
    L, H = 3, 16
    key = jax.random.PRNGKey(1)
    params = {"w": jax.random.normal(key, (L, H, H), jnp.float32)}
    grads = {"w": jax.random.normal(key, (L, H, H), jnp.float32)}

    def upd(g, s, p, lr):
        ns = jax.tree.map(lambda m, gg: 0.9 * m + gg, s, g)
        return jax.tree.map(lambda pp, m: pp - lr * m, p, ns), ns

    st = jax.tree.map(jnp.zeros_like, params)
    np_, ns_ = jax.jit(lambda g, s, p: inf.streamed_update(
        upd, g, s, p, 0.1, scale=0.5))(inf.to_host(grads), inf.to_host(st),
                                       inf.to_host(params))
    ref_p, ref_s = upd(jax.tree.map(lambda v: np.asarray(v) * 0.5, grads),
                       jax.tree.map(np.asarray, st),
                       jax.tree.map(np.asarray, params), 0.1)
    np.testing.assert_allclose(np.asarray(np_["w"]), ref_p["w"], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(ns_["w"]), ref_s["w"], rtol=1e-6)
    # gate=False keeps the old params
    np2, _ = jax.jit(lambda g, s, p: inf.streamed_update(
        upd, g, s, p, 0.1, gate=jnp.bool_(False)))(
        inf.to_host(grads), inf.to_host(st), inf.to_host(params))
    np.testing.assert_allclose(np.asarray(np2["w"]),
                               np.asarray(params["w"]), rtol=1e-7)


def _train(model, config, batches, seed=11):
    engine, _, _, _ = ds.initialize(model=model, config=config, seed=seed)
    losses = [float(np.asarray(engine.train_batch(b))) for b in batches]
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None
    return losses, engine


def _cfg(gas=1, **over):
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "gradient_clipping": 1.0,
        "steps_per_print": 1000,
        "mesh": {"data": 1},
    }
    cfg.update(over)
    return cfg


@pytest.mark.parametrize("gas", [1, 2])
def test_param_stream_loss_parity(gas):
    """offload_param=cpu (streamed layers, host grads, slice-wise optimizer)
    must reproduce the in-HBM training trajectory."""
    model = get_model_config("gpt2-tiny")
    rng = np.random.default_rng(0)
    batches = [make_lm_batch(rng, 4 * gas, 32, model.vocab_size)] * 3
    ref, _ = _train(model, _cfg(gas), batches)
    stream, eng = _train(model, _cfg(
        gas, zero_optimization={"stage": 0,
                                "offload_param": {"device": "cpu"}}),
        batches)
    assert eng._param_stream
    assert eng.model_config.param_stream
    np.testing.assert_allclose(ref, stream, rtol=2e-4, atol=2e-4)
    assert stream[-1] < stream[0]


def test_param_stream_nvme_tier(tmp_path):
    """offload_param=nvme: layer weights live on NVMe between steps (AIO
    store), staged through host RAM around each step; training works and a
    checkpoint round-trips."""
    model = get_model_config("gpt2-tiny")
    rng = np.random.default_rng(1)
    batches = [make_lm_batch(rng, 4, 32, model.vocab_size)] * 3
    losses, eng = _train(model, _cfg(zero_optimization={
        "stage": 0,
        "offload_param": {"device": "nvme",
                          "nvme_path": str(tmp_path / "pswap")}}), batches)
    assert eng._param_store is not None
    assert eng.params["layers"] is None  # NVMe is authoritative between steps
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]
    eng.save_checkpoint(str(tmp_path / "ckpt"))
    # trajectory parity vs plain run
    ref, _ = _train(model, _cfg(), batches)
    np.testing.assert_allclose(ref, losses, rtol=2e-4, atol=2e-4)


def test_nvme_tier_micro_api_and_eval(tmp_path):
    """The forward()/backward()/step() trio and eval_batch() must stage the
    NVMe param tier in, not just train_batch()."""
    model = get_model_config("gpt2-tiny")
    rng = np.random.default_rng(3)
    batch = make_lm_batch(rng, 4, 32, model.vocab_size)
    engine, _, _, _ = ds.initialize(model=model, config=_cfg(
        zero_optimization={"stage": 0,
                           "offload_param": {"device": "nvme",
                                             "nvme_path": str(tmp_path)}}),
        seed=5)
    try:
        assert engine.params["layers"] is None
        ev = float(np.asarray(engine.eval_batch(batch)))
        assert np.isfinite(ev)
        loss = engine.forward(batch)
        engine.backward()
        engine.step()
        assert engine.params["layers"] is None  # swapped back out
        assert np.isfinite(float(np.asarray(loss)))
    finally:
        from deepspeed_tpu.parallel import topology

        topology._GLOBAL_TOPOLOGY = None


def test_nvme_shared_mount_param_and_opt(tmp_path):
    """Param tier and optimizer tier sharing ONE nvme_path (the canonical
    DeepSpeed setup) must not clobber each other's files: the stores use
    distinct file prefixes."""
    model = get_model_config("gpt2-tiny")
    rng = np.random.default_rng(7)
    batches = [make_lm_batch(rng, 4, 32, model.vocab_size)] * 3
    shared = str(tmp_path / "mount")
    losses, eng = _train(model, _cfg(zero_optimization={
        "stage": 0,
        "offload_param": {"device": "nvme", "nvme_path": shared},
        "offload_optimizer": {"device": "nvme", "nvme_path": shared}}),
        batches)
    assert eng._param_store is not None and eng._opt_store is not None
    assert eng._param_store.prefix != eng._opt_store.prefix
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]
    ref, _ = _train(model, _cfg(), batches)
    np.testing.assert_allclose(ref, losses, rtol=2e-4, atol=2e-4)


def test_offload_reload_states_with_nvme_param_tier(tmp_path):
    """engine.offload_states()/reload_states() must not crash when the NVMe
    param tier has parked the layers off-device (params['layers'] is None
    between steps)."""
    model = get_model_config("gpt2-tiny")
    rng = np.random.default_rng(8)
    batch = make_lm_batch(rng, 4, 32, model.vocab_size)
    engine, _, _, _ = ds.initialize(model=model, config=_cfg(
        zero_optimization={"stage": 0,
                           "offload_param": {"device": "nvme",
                                             "nvme_path": str(tmp_path)}}),
        seed=9)
    try:
        assert engine.params["layers"] is None
        engine.offload_states()          # must not raise on the None leaf
        engine.reload_states()           # stages NVMe layers back in
        assert engine.params["layers"] is not None
        loss = float(np.asarray(engine.train_batch(batch)))
        assert np.isfinite(loss)
    finally:
        from deepspeed_tpu.parallel import topology

        topology._GLOBAL_TOPOLOGY = None


def test_streamed_scan_bf16_params():
    """Non-fp32 parameter trees: the custom VJP must hand back cotangents
    in the primal dtype (accumulation still runs in fp32 internally)."""
    L, H = 3, 16
    key = jax.random.PRNGKey(5)
    params = {"w": (jax.random.normal(key, (L, H, H), jnp.float32) * 0.1
                    ).astype(jnp.bfloat16)}
    x = jax.random.normal(key, (4, H), jnp.float32).astype(jnp.bfloat16)

    def step_fn(lp, h, extras, i):
        return jnp.tanh(h @ lp["w"]), jnp.zeros((), jnp.float32)

    def loss_s(ph, x):
        h, _ = inf.streamed_scan(step_fn, ph, x, extras=())
        return jnp.mean(h.astype(jnp.float32) ** 2)

    g = jax.jit(jax.grad(loss_s))(inf.to_host(params), x)
    assert g["w"].dtype == jnp.bfloat16
    assert np.all(np.isfinite(np.asarray(g["w"], dtype=np.float32)))


def test_param_stream_plus_pipeline_raises():
    """offload_param + pipeline parallelism is an explicit
    NotImplementedError, on the 1F1B path too (it must not silently bypass
    forward()'s guard)."""
    model = get_model_config("gpt2-tiny")  # 2 layers → 2 stages
    rng = np.random.default_rng(4)
    batch = make_lm_batch(rng, 8, 32, model.vocab_size)
    cfg = _cfg(mesh={"pipe": 2, "data": 4},
               train_micro_batch_size_per_gpu=2,
               zero_optimization={"stage": 0,
                                  "offload_param": {"device": "cpu"}})
    engine, _, _, _ = ds.initialize(model=model, config=cfg, seed=6)
    try:
        with pytest.raises(NotImplementedError, match="pipeline"):
            engine.train_batch(batch)
    finally:
        from deepspeed_tpu.parallel import topology

        topology._GLOBAL_TOPOLOGY = None


def test_param_stream_with_zero3_mesh():
    """Param streaming composes with a sharded mesh (ZeRO-3 specs keep
    their PartitionSpecs; only the memory kind changes)."""
    model = get_model_config("llama-tiny")
    rng = np.random.default_rng(2)
    batches = [make_lm_batch(rng, 8, 32, model.vocab_size)] * 3
    cfg = _cfg(mesh={"data": 4, "tensor": 2},
               train_micro_batch_size_per_gpu=2,
               zero_optimization={"stage": 3,
                                  "offload_param": {"device": "cpu"}})
    losses, eng = _train(model, cfg, batches)
    assert eng._param_stream
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]
