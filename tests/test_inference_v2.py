"""Inference v2 (FastGen analog): allocator, scheduler, paged decode parity.

Ref test model: tests/unit/inference/v2/ (ragged ops, kv cache, engine).
The key correctness oracle: continuous-batching paged-KV generation must
produce EXACTLY the same greedy tokens as the v1 engine's full-recompute
generation with the same weights.
"""

import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import (BlockedAllocator, DSStateManager,
                                        SplitFuseScheduler, build_engine)
from deepspeed_tpu.models import get_model_config


def test_blocked_allocator():
    a = BlockedAllocator(8)
    assert a.free_blocks == 7  # block 0 reserved
    got = a.allocate(3)
    assert len(set(got)) == 3 and 0 not in got
    with pytest.raises(RuntimeError):
        a.allocate(5)
    a.free(got)
    assert a.free_blocks == 7
    with pytest.raises(ValueError):
        a.free([0])


def test_blocked_allocator_rejects_double_free_and_bad_handles():
    """A double-freed page would be handed to two sequences and silently
    cross-write their KV — free() must reject it, plus handles that were
    never valid, without mutating the free list."""
    a = BlockedAllocator(8)
    got = a.allocate(3)
    a.free(got[:1])
    with pytest.raises(ValueError, match="double free"):
        a.free(got[:1])                    # already returned
    with pytest.raises(ValueError, match="not allocated"):
        a.free([5] if 5 not in got else [6])  # never handed out
    with pytest.raises(ValueError, match="out of range"):
        a.free([99])
    with pytest.raises(ValueError, match="out of range"):
        a.free([-1])
    with pytest.raises(ValueError, match="duplicate"):
        a.free([got[1], got[1]])
    # failed frees must not have leaked: the two live handles still free
    a.free(got[1:])
    assert a.free_blocks == 7
    from deepspeed_tpu.inference.v2 import KVCacheExhausted

    with pytest.raises(KVCacheExhausted):  # typed for the serving layer
        a.allocate(8)


def test_state_manager_slots_and_pages():
    mgr = DSStateManager(max_seqs=2, num_blocks=8, block_size=4,
                         max_blocks_per_seq=4)
    s1 = mgr.open(10, [1, 2, 3, 4, 5])
    mgr.ensure_capacity(s1, 5)
    assert len(s1.blocks) == 2
    s2 = mgr.open(11, [7])
    with pytest.raises(RuntimeError):
        mgr.open(12, [9])  # no slots
    mgr.flush(10)
    assert 10 not in mgr and mgr.allocator.free_blocks == 7
    mgr.open(12, [9])  # slot reusable
    mgr.flush(11), mgr.flush(12)


def test_splitfuse_schedule_splits_prompts():
    mgr = DSStateManager(max_seqs=4, num_blocks=64, block_size=4,
                         max_blocks_per_seq=16)
    sched = SplitFuseScheduler(mgr, token_budget=8)
    mgr.open(1, list(range(20)))  # long prompt
    sched.add(1)
    s = sched.next_schedule()
    assert [(x.uid, n) for x, n in s] == [(1, 8)]
    # simulate the engine caching those tokens
    mgr.get(1).num_cached = 8
    s = sched.next_schedule()
    assert [(x.uid, n) for x, n in s] == [(1, 8)]
    mgr.get(1).num_cached = 16
    s = sched.next_schedule()
    assert [(x.uid, n) for x, n in s] == [(1, 4)]  # final chunk → sampled
    mgr.get(1).num_cached = 20


def test_splitfuse_decode_priority():
    mgr = DSStateManager(max_seqs=4, num_blocks=64, block_size=4,
                         max_blocks_per_seq=16)
    sched = SplitFuseScheduler(mgr, token_budget=8)
    mgr.open(1, [1, 2, 3])
    sched.add(1)
    sched.next_schedule()
    mgr.get(1).num_cached = 3       # prompt done → decode set
    mgr.get(1).tokens.append(42)    # sampled token pending
    mgr.open(2, list(range(30)))
    sched.add(2)
    s = sched.next_schedule()
    # decode seq first (1 token), then prompt chunk fills the rest
    assert (s[0][0].uid, s[0][1]) == (1, 1)
    assert (s[1][0].uid, s[1][1]) == (2, 7)


@pytest.mark.parametrize("model_name", ["llama-tiny", "gpt2-tiny"])
def test_paged_generation_matches_v1(model_name):
    """Greedy continuous-batching output == full-recompute output."""
    from deepspeed_tpu.inference.engine import InferenceEngine

    model = get_model_config(model_name, num_layers=2)
    v1 = InferenceEngine(model, {"dtype": "float32"}, seed=3)
    v2 = build_engine(model, {"dtype": "float32",
                              "state_manager": {"max_tracked_sequences": 4,
                                                "max_ragged_batch_size": 16},
                              "memory_config": {"num_blocks": 64, "block_size": 4},
                              "max_context": 128},
                      model_params=v1.params, seed=3)

    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, model.vocab_size, size=n).tolist()
               for n in (5, 11, 3)]
    new = 8
    got = v2.generate(prompts, max_new_tokens=new)
    for prompt, out in zip(prompts, got):
        ref = v1.generate(np.asarray(prompt)[None], max_new_tokens=new)
        assert out == ref[0, len(prompt):].tolist()


def test_paged_generation_moe():
    model = get_model_config("mixtral-tiny", num_layers=2)
    v2 = build_engine(model, {"dtype": "float32",
                              "memory_config": {"num_blocks": 64, "block_size": 4},
                              "max_context": 64},
                      seed=0)
    out = v2.generate([[1, 2, 3], [4, 5]], max_new_tokens=4)
    assert all(len(o) == 4 for o in out)
    assert all(0 <= t < model.vocab_size for o in out for t in o)


def test_kv_pages_freed_after_generate():
    model = get_model_config("llama-tiny", num_layers=1)
    v2 = build_engine(model, {"dtype": "float32",
                              "memory_config": {"num_blocks": 32, "block_size": 4},
                              "max_context": 64}, seed=0)
    before = v2.free_blocks
    v2.generate([[1, 2, 3, 4, 5]], max_new_tokens=3)
    assert v2.free_blocks == before


def test_continuous_batching_oversubscribed():
    """More prompts than slots: engine drains in waves, all finish."""
    model = get_model_config("llama-tiny", num_layers=1)
    v2 = build_engine(model, {"dtype": "float32",
                              "state_manager": {"max_tracked_sequences": 2,
                                                "max_ragged_batch_size": 16},
                              "memory_config": {"num_blocks": 16, "block_size": 4},
                              "max_context": 32}, seed=0)
    prompts = [[1, 2, 3], [4, 5], [6], [7, 8, 9, 10]]
    out = v2.generate(prompts, max_new_tokens=3)
    assert all(len(o) == 3 for o in out)


def test_generate_raises_on_impossible_prompt():
    model = get_model_config("llama-tiny", num_layers=1)
    v2 = build_engine(model, {"dtype": "float32",
                              "memory_config": {"num_blocks": 4, "block_size": 4},
                              "max_context": 16}, seed=0)
    with pytest.raises(RuntimeError):
        v2.generate([list(range(1, 30))], max_new_tokens=8)


def test_admission_reserves_active_seq_future_blocks():
    """Tight KV cache: active sequences' future pages are reserved, so the
    second prompt waits instead of overcommitting and crashing mid-stream."""
    model = get_model_config("llama-tiny", num_layers=1)
    v2 = build_engine(model, {"dtype": "float32",
                              "state_manager": {"max_tracked_sequences": 4,
                                                "max_ragged_batch_size": 16},
                              "memory_config": {"num_blocks": 8, "block_size": 4},
                              "max_context": 32}, seed=0)
    out = v2.generate([[1, 2, 3, 4, 5], [6, 7, 8, 9, 10]], max_new_tokens=12)
    assert all(len(o) == 12 for o in out)
    assert v2.free_blocks == v2.cfg.num_blocks - 1


def test_admission_enforces_per_seq_block_cap():
    """Prompt fits the cache but exceeds max_blocks_per_seq → friendly error
    at admission, not a mid-generate crash."""
    model = get_model_config("llama-tiny", num_layers=1)
    v2 = build_engine(model, {"dtype": "float32",
                              "memory_config": {"num_blocks": 64, "block_size": 4},
                              "max_context": 16}, seed=0)
    with pytest.raises(RuntimeError, match="per sequence"):
        v2.generate([[1, 2, 3], list(range(1, 25))], max_new_tokens=4)


def test_put_validates_batch_before_mutating():
    model = get_model_config("llama-tiny", num_layers=1)
    v2 = build_engine(model, {"dtype": "float32",
                              "memory_config": {"num_blocks": 32, "block_size": 4},
                              "max_context": 32}, seed=0)
    with pytest.raises(ValueError):
        v2.put([1, 1], [[5, 6], [7, 8]])     # duplicate uid in one batch
    assert 1 not in v2.state_manager          # nothing half-admitted
    with pytest.raises(ValueError):
        v2.put([2, 3], [[5, 6]])              # mismatched lengths
    assert 2 not in v2.state_manager


def test_build_ragged_batch_checks_budget_first():
    from deepspeed_tpu.inference.v2.ragged import build_ragged_batch

    mgr = DSStateManager(max_seqs=2, num_blocks=16, block_size=4,
                         max_blocks_per_seq=4)
    seq = mgr.open(1, list(range(10)))
    with pytest.raises(RuntimeError, match="budget"):
        build_ragged_batch([(seq, 10)], mgr, token_budget=8)
    assert seq.num_cached == 0  # state untouched


def test_soak_staggered_eos_and_sampling_allocator_clean():
    """Soak: three generate() waves with eos cut-offs, varying lengths and
    nucleus sampling — the allocator must return to fully-free after every
    wave (no leaked pages/slots across waves; ref flush/retire paths)."""
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import get_model_config

    model = get_model_config("gpt2-tiny")
    eng = InferenceEngineV2(model, {"dtype": "float32",
                                    "memory_config": {"num_blocks": 64,
                                                      "block_size": 16},
                                    "max_context": 128})
    free0 = eng.free_blocks
    rng = np.random.default_rng(21)
    for wave, (n, temp, tp) in enumerate([(6, 0.0, 1.0), (4, 0.9, 0.8),
                                          (8, 0.7, 1.0)]):
        prompts = [list(map(int, rng.integers(
            1, model.vocab_size, size=(int(rng.integers(2, 24)),))))
            for _ in range(n)]
        outs = eng.generate(prompts, max_new_tokens=int(rng.integers(3, 12)),
                            temperature=temp, top_p=tp,
                            eos_token_id=7)
        assert len(outs) == n
        for o in outs:
            assert len(o) >= 1
            if 7 in o:  # eos respected: nothing after it
                assert o[o.index(7):] == [7]
        assert eng.free_blocks == free0, (wave, eng.free_blocks, free0)
        assert eng.state_manager.n_active == 0
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None


def test_compile_time_guard_for_small_block_sizes():
    """ceil(max_context/block_size) > 256 is a multi-minute TPU compile
    (observed >880 s at 512 blocks/seq on v5e, r04) — the engine refuses
    it up front unless allow_slow_compile opts in; >128 warns only."""
    import pytest

    from deepspeed_tpu.inference.v2.engine_v2 import (
        RaggedInferenceEngineConfig)

    with pytest.raises(ValueError, match="blocks per sequence"):
        RaggedInferenceEngineConfig({
            "max_context": 32768, "memory_config": {"block_size": 64}})
    cfg = RaggedInferenceEngineConfig({
        "max_context": 32768, "memory_config": {"block_size": 64},
        "allow_slow_compile": True})
    assert cfg.block_size == 64
    # the default operating point (2048 / 16 = 128) stays silent
    cfg = RaggedInferenceEngineConfig({})
    assert -(-cfg.max_context // cfg.block_size) == 128


def test_int8_kv_cache_generation():
    """memory_config.kv_dtype=int8: the cache stores int8 payload + fp32
    per-row scales (half the KV bytes), generation runs the quantize-on-
    append path, and greedy outputs match the bf16 cache closely."""
    import jax.numpy as jnp

    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.parallel import topology

    model = get_model_config("llama-tiny")
    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, model.vocab_size, size=(12,)).tolist()
               for _ in range(3)]
    outs = {}
    for kind in ("bf16", "int8"):
        eng = InferenceEngineV2(
            model, {"memory_config": {"kv_dtype": kind}}, seed=11)
        if kind == "int8":
            assert eng.cache_k["q"].dtype == jnp.int8
            assert eng.cache_k["s"].dtype == jnp.float32
            # payload bytes halve vs the bf16 cache; scales add 4/(2d)
            assert eng.cache_k["q"].nbytes * 2 == bf16_nbytes
            d = eng.cache_k["q"].shape[-1]
            assert eng.cache_k["s"].nbytes * d == eng.cache_k["q"].nbytes * 4
        else:
            assert eng.cache_k.dtype == jnp.bfloat16
            bf16_nbytes = eng.cache_k.nbytes
        outs[kind] = eng.generate(prompts, max_new_tokens=8)
        topology._GLOBAL_TOPOLOGY = None
    # greedy decode over a random tiny model: quantization noise may flip
    # an occasional argmax, but the sequences must agree on most tokens
    agree = np.mean([np.mean(np.asarray(a[:4]) == np.asarray(b[:4]))
                     for a, b in zip(outs["bf16"], outs["int8"])])
    assert agree >= 0.5, (agree, outs)
