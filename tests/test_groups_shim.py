"""deepspeed.utils.groups compat shim (utils/groups.py; ref
deepspeed/utils/groups.py getters): axis names as groups + live
topology sizes, and the names feed ds.comm collectives directly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.parallel.topology import MeshTopology, set_topology
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.jax_compat import shard_map


@pytest.fixture
def mesh():
    topo = MeshTopology({"data": 2, "expert": 2, "tensor": 2})
    set_topology(topo)
    yield topo
    set_topology(None)


def test_getters_answer_from_topology(mesh):
    assert groups.get_data_parallel_world_size() == 4  # data x expert
    assert groups.get_tensor_model_parallel_world_size() == 2
    assert groups.get_model_parallel_world_size() == 2
    assert groups.get_pipeline_model_parallel_world_size() == 1
    assert groups.get_sequence_parallel_world_size() == 1
    assert groups._get_expert_parallel_world_size("ep_size_2") == 2
    assert groups._get_expert_data_parallel_world_size() == 2
    assert groups.get_world_size() == 8
    # single-controller process: first-device coordinate is 0 everywhere
    assert groups.get_data_parallel_rank() == 0
    assert groups.get_tensor_model_parallel_rank() == 0


def test_group_names_feed_comm_collectives(mesh):
    """The returned group IS the axis name ds.comm collectives take."""
    from jax.sharding import PartitionSpec as P

    g = groups.get_tensor_model_parallel_group()

    def body(x):
        return jax.lax.psum(x, g)

    out = jax.jit(shard_map(
        body, mesh=mesh.mesh, in_specs=P("tensor"), out_specs=P()))(
        jnp.arange(2, dtype=jnp.float32))
    assert float(np.asarray(out)) == 1.0  # 0 + 1 summed over tensor axis


def test_requires_topology():
    set_topology(None)
    with pytest.raises(RuntimeError, match="no topology"):
        groups.get_data_parallel_world_size()
