"""Pipelined (overlapped) NVMe/host store swapping.

Ref VERDICT r3 Missing #4 / pipelined_optimizer_swapper.py:26: with
``offload_optimizer.pipeline_read``, the next step's store read drains on a
worker thread behind the writes while the host dispatches compute, so step
time approaches max(compute, transfer) instead of the sum.
"""

import threading
import time

import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import get_model_config
from tests.conftest import make_lm_batch


def _reset_topo():
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None


class _SlowStore:
    """Delegating store proxy that injects read latency and records which
    thread performed each read."""

    def __init__(self, inner, delay: float):
        self.inner = inner
        self.delay = delay
        self.reads = []  # (t_start, thread_name)

    def swap_in(self):
        self.reads.append((time.perf_counter(),
                           threading.current_thread().name))
        time.sleep(self.delay)
        return self.inner.swap_in()

    def swap_out(self, tree):
        self.inner.swap_out(tree)

    def wait(self):
        self.inner.wait()


def _nvme_engine(tmp_path, pipeline: bool, seed=5):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "steps_per_print": 1000,
        "mesh": {"data": 1},
        "zero_optimization": {
            "stage": 1,
            "offload_optimizer": {"device": "nvme",
                                  "nvme_path": str(tmp_path),
                                  "pipeline_read": pipeline}},
    }
    model = get_model_config("gpt2-tiny")
    engine, _, _, _ = ds.initialize(model=model, config=cfg, seed=seed)
    return engine, model


def test_pipelined_matches_serial_losses(tmp_path):
    rng = np.random.default_rng(9)
    batch = make_lm_batch(rng, 2, 32, 512)
    eng_s, _ = _nvme_engine(tmp_path / "serial", False)
    serial = [float(np.asarray(eng_s.train_batch(batch))) for _ in range(4)]
    _reset_topo()
    eng_p, _ = _nvme_engine(tmp_path / "pipe", True)
    assert eng_p._swap_pool is not None
    piped = [float(np.asarray(eng_p.train_batch(batch))) for _ in range(4)]
    np.testing.assert_allclose(serial, piped, rtol=1e-5, atol=1e-6)
    # prefetch was queued at the end of the step
    assert eng_p._opt_fut is not None
    eng_p.destroy()
    _reset_topo()


def test_step_time_is_max_not_sum(tmp_path):
    """With an artificial 0.25s transfer and an artificial 0.25s compute,
    serial steps cost ~0.5s while pipelined steps cost ~0.25s."""
    delay = 0.25
    steps = 3
    rng = np.random.default_rng(11)
    batch = make_lm_batch(rng, 2, 32, 512)

    def timed(pipeline, sub):
        engine, _ = _nvme_engine(tmp_path / sub, pipeline)
        engine._opt_store = _SlowStore(engine._opt_store, delay)
        orig = engine._grads_batch_store_jit

        def slow_grads(*a):
            out = orig(*a)
            import jax

            jax.block_until_ready(out)
            time.sleep(delay)  # stands in for device compute time
            return out

        engine._grads_batch_store_jit = slow_grads
        # serial path has no split grads fn; emulate compute latency in
        # the monolithic step the same way
        orig_mono = engine._train_step_jit

        def slow_mono(*a):
            out = orig_mono(*a)
            import jax

            jax.block_until_ready(out)
            time.sleep(delay)
            return out

        engine._train_step_jit = slow_mono
        engine.train_batch(batch)  # warmup / compile
        t0 = time.perf_counter()
        for _ in range(steps):
            engine.train_batch(batch)
        dt = time.perf_counter() - t0
        reads = list(engine._opt_store.reads)
        engine.destroy()
        _reset_topo()
        return dt, reads

    dt_serial, _ = timed(False, "serial")
    dt_piped, reads = timed(True, "pipe")
    # serial pays read+compute per step; pipelined pays ~max(read, compute).
    # Generous margins for a loaded 1-core CI box.
    assert dt_serial > steps * 2 * delay * 0.9, dt_serial
    assert dt_piped < dt_serial - steps * delay * 0.5, (dt_piped, dt_serial)
    # the overlapped reads ran on the swap worker thread, not the main one
    worker_reads = [t for _, t in reads if "dstpu-swap" in t]
    assert len(worker_reads) >= steps, reads


def test_checkpoint_save_joins_prefetch(tmp_path):
    """A checkpoint save between steps must consume the in-flight prefetch
    (single-owner AIO handle) and still serialize the current state."""
    rng = np.random.default_rng(12)
    batch = make_lm_batch(rng, 2, 32, 512)
    engine, _ = _nvme_engine(tmp_path / "ck", True)
    engine.train_batch(batch)
    assert engine._opt_fut is not None
    engine.save_checkpoint(str(tmp_path / "out"), tag="t")
    assert engine._opt_fut is None  # prefetch consumed, not raced
    loss1 = float(np.asarray(engine.train_batch(batch)))
    assert np.isfinite(loss1)
    engine.destroy()
    _reset_topo()


def test_trio_step_api_with_pipelined_store(tmp_path):
    """The manual forward/backward/step trio must work in pipelined store
    mode too (step() queues the next prefetch; the next step consumes
    it), with numerics matching train_batch."""
    rng = np.random.default_rng(13)
    batch = make_lm_batch(rng, 2, 32, 512)
    eng_a, _ = _nvme_engine(tmp_path / "a", True)
    ref = [float(np.asarray(eng_a.train_batch(batch))) for _ in range(3)]
    eng_a.destroy()
    _reset_topo()

    eng_b, _ = _nvme_engine(tmp_path / "b", True)
    got = []
    for _ in range(3):
        loss = eng_b.forward(batch)
        eng_b.backward()
        eng_b.step()
        got.append(float(np.asarray(loss)))
    assert eng_b._opt_fut is not None  # step() queued the prefetch
    eng_b.destroy()
    np.testing.assert_allclose(ref, got, rtol=1e-5, atol=1e-6)
    _reset_topo()
