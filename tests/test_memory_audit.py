"""Static memory-plan auditor (deepspeed_tpu/analysis/memory.py;
docs/STATIC_ANALYSIS.md).

Covers the frozen MemoryAuditReport schema, the budget bucketing, each
planted defect class (the pre-PR-11 unsharded-transient zero-grads
pattern, a score-shaped transient under a flash intent, a >10% budget
regression), the model-drift calibration loop into the autotuner, the
zero-grads accumulator-sharding regression pin, the capture report's
``hbm`` runtime cross-check (null-on-CPU contract), the ladder
predictor's fit gate, the scheduler's ``static_memory`` evidence, and
the graft_lint ``--memory``/``--target`` CLI plumbing.  The per-target
tier-1 gate lives in tests/test_graph_audit.py (shared lowering with
the graph audit).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.analysis import (MEMORY_CLASSES, MEMORY_REPORT_KEYS,
                                    MEMORY_TOTALS_KEYS, MemoryAuditReport,
                                    bucket_bytes, load_memory_baseline)
from deepspeed_tpu.analysis.auditor import lower_step
from deepspeed_tpu.analysis.memory import MemoryIntent, audit_memory

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mesh(shape=(8,), names=("data",)):
    devs = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


# ----------------------------------------------------------------------
# schema / bucketing
# ----------------------------------------------------------------------
def test_memory_report_schema_frozen_and_sorted():
    rep = MemoryAuditReport(label="x")
    d = rep.to_dict()
    assert sorted(d.keys()) == sorted(MEMORY_REPORT_KEYS)
    assert list(json.loads(rep.to_json()).keys()) == sorted(d.keys())
    assert d["schema"] == 1
    assert sorted(d["totals"].keys()) == sorted(MEMORY_TOTALS_KEYS)


def test_bucket_bytes_coarse_and_monotone():
    assert bucket_bytes(0) == 0
    assert bucket_bytes(1) == 1 << 12          # 4 KiB floor
    # quantization stays within ~6.25% and rounds UP
    for n in (100_000, 9_135_273, (1 << 30) + 17):
        b = bucket_bytes(n)
        assert n <= b <= int(n * 1.0626), (n, b)
        assert bucket_bytes(b) == b            # idempotent
    # a 10% regression always lands in a strictly higher bucket
    n = 9_135_273
    assert bucket_bytes(int(n * 1.11)) > bucket_bytes(n)


def test_memory_intent_rejects_unknown_classes():
    with pytest.raises(ValueError, match="unknown memory classes"):
        MemoryIntent(arg_categories=("weights",))
    with pytest.raises(ValueError, match="unknown memory classes"):
        MemoryIntent(replicated_ok=("everything",))
    assert MemoryIntent(arg_categories=MEMORY_CLASSES)


# ----------------------------------------------------------------------
# totals + buffer census
# ----------------------------------------------------------------------
def test_totals_and_census_classification():
    mesh = _mesh()
    sh = NamedSharding(mesh, P("data", None))

    def step(p, b):
        return (p * 2, (p @ b.T).sum())

    fn = jax.jit(step, in_shardings=(sh, sh), donate_argnums=(0,))
    art = lower_step(fn, jnp.zeros((256, 64)), jnp.zeros((256, 64)),
                     label="census")
    rep = audit_memory(art, intent=MemoryIntent(
        arg_categories=("params", "activations")))
    assert rep.totals["argument_bytes"] > 0
    assert rep.totals["peak_bytes"] > 0
    # donated p aliases: the alias subtraction keeps peak below arg+out+temp
    assert rep.totals["alias_bytes"] > 0
    assert rep.class_bytes["params"] > 0
    assert rep.class_bytes["activations"] > 0
    assert rep.buffers and all(
        set(b) == {"bytes", "category", "dtype", "op", "shape"}
        for b in rep.buffers)
    assert all(b["category"] in MEMORY_CLASSES for b in rep.buffers)
    # rows are sorted largest-first
    sizes = [b["bytes"] for b in rep.buffers]
    assert sizes == sorted(sizes, reverse=True)


# ----------------------------------------------------------------------
# planted defects
# ----------------------------------------------------------------------
def test_planted_unsharded_transient_detected():
    """The pre-PR-11 zero-grads pattern: a sharded layout exists for the
    tree, yet a buffer materializes at the full GLOBAL shape."""
    mesh = _mesh()
    sh = NamedSharding(mesh, P("data", None))

    def step(p):
        g = jax.lax.with_sharding_constraint(
            p * 2.0, NamedSharding(mesh, P()))     # forced replication
        return g.sum() + (p * p).sum()

    fn = jax.jit(step, in_shardings=(sh,))
    x = jnp.zeros((1024, 256))
    rep = audit_memory(fn, x, intent=MemoryIntent(
        arg_categories=("params",)), label="planted")
    hits = [f for f in rep.findings if f.kind == "unsharded_transient"]
    assert hits and hits[0].severity == "high", \
        [f.to_dict() for f in rep.findings]
    assert hits[0].detail["shard_ratio"] == 8

    # the honorable version — the transient keeps the sharded layout
    def ok(p):
        g = jax.lax.with_sharding_constraint(p * 2.0, sh)
        return g.sum() + (p * p).sum()

    clean = audit_memory(jax.jit(ok, in_shardings=(sh,)), x,
                         intent=MemoryIntent(arg_categories=("params",)),
                         label="clean")
    assert not [f for f in clean.findings
                if f.kind == "unsharded_transient"]

    # ZeRO's own full-materialization intent: the same graph audits
    # clean when the class is declared replicated_ok (per-use gathers
    # are the config's design, not a defect)
    exempt = audit_memory(fn, x, intent=MemoryIntent(
        arg_categories=("params",), replicated_ok=("params",)),
        label="exempt")
    assert not [f for f in exempt.findings
                if f.kind == "unsharded_transient"]


def test_planted_remat_miss_under_flash_intent():
    def attn(q, k):
        return jnp.einsum("bhsd,bhtd->bhst", q, k).astype(
            jnp.float32).sum()

    q = jnp.zeros((2, 4, 128, 64), jnp.bfloat16)
    rep = audit_memory(jax.jit(attn), q, q, intent=MemoryIntent(
        arg_categories=("activations", "activations"),
        seq_len=128, flash=True), label="remat")
    hits = [f for f in rep.findings if f.kind == "remat_miss"]
    assert hits and hits[0].severity == "high"
    assert hits[0].detail["seq_len"] == 128
    # the same graph without a flash declaration is legitimate
    rep2 = audit_memory(jax.jit(attn), q, q, intent=MemoryIntent(
        arg_categories=("activations", "activations"),
        seq_len=128, flash=False), label="noflash")
    assert not [f for f in rep2.findings if f.kind == "remat_miss"]


def test_planted_peak_regression_against_budget():
    fn = jax.jit(lambda x: (x @ x.T).sum())
    x = jnp.zeros((256, 256))
    base = audit_memory(fn, x, label="base")
    peak = base.totals["peak_bytes"]
    assert peak > 0
    # >10% over budget ⇒ high
    hot = audit_memory(fn, x, budget=int(peak / 1.2), label="hot")
    highs = [f for f in hot.findings if f.kind == "peak_regression"]
    assert highs and highs[0].severity == "high"
    assert highs[0].detail["budget_bytes"] == int(peak / 1.2)
    # at budget (or within tolerance) ⇒ clean
    ok = audit_memory(fn, x, budget=peak, label="ok")
    assert not [f for f in ok.findings if f.kind == "peak_regression"]
    # no budget ⇒ warning, never silent
    warn = audit_memory(fn, x, label="nobudget")
    ws = [f for f in warn.findings if f.kind == "peak_regression"]
    assert ws and ws[0].severity == "warning"
    assert warn.budget["budget_bytes"] is None


def test_model_drift_calibration_record():
    fn = jax.jit(lambda x: (x @ x.T).sum())
    x = jnp.zeros((256, 256))
    base = audit_memory(fn, x, label="b")
    peak = base.totals["peak_bytes"]
    # far-off analytic estimate ⇒ info-severity calibration record
    rep = audit_memory(fn, x, intent=MemoryIntent(
        analytic_bytes=peak * 10), label="drift")
    drifts = [f for f in rep.findings if f.kind == "model_drift"]
    assert drifts and drifts[0].severity == "info"
    assert rep.calibration["analytic_bytes"] == peak * 10
    assert rep.calibration["ratio"] == pytest.approx(0.1, abs=0.01)
    # close estimate ⇒ record only, no finding
    rep2 = audit_memory(fn, x, intent=MemoryIntent(
        analytic_bytes=peak), label="agrees")
    assert not [f for f in rep2.findings if f.kind == "model_drift"]
    assert rep2.calibration["ratio"] == pytest.approx(1.0, abs=0.01)


# ----------------------------------------------------------------------
# calibration → autotuner
# ----------------------------------------------------------------------
def test_autotuner_attaches_memory_calibration():
    from deepspeed_tpu.autotuning import (ModelInfo, generate_tuning_space,
                                          load_memory_calibration)

    mi = ModelInfo(num_params=10_000_000, hidden_size=512, num_layers=8,
                   vocab_size=32_000)
    # calibration scales the estimate: a 2x ratio halves what fits
    budget = 500 * (1 << 20)
    plain = generate_tuning_space(mi, 8, 512, budget)
    scaled = generate_tuning_space(mi, 8, 512, budget, calibration=2.0)
    assert len(scaled) < len(plain)
    assert {(c["zero_stage"], c["micro_batch"]) for c in scaled} <= \
        {(c["zero_stage"], c["micro_batch"]) for c in plain}
    # the committed baseline carries a usable cpu ratio
    ratio = load_memory_calibration(
        os.path.join(REPO, "tools", "memory_baseline.json"),
        backend="cpu")
    assert ratio > 0
    # absent file/backend degrade to 1.0, never a crash
    assert load_memory_calibration("/nonexistent.json") == 1.0
    assert load_memory_calibration(
        os.path.join(REPO, "tools", "memory_baseline.json"),
        backend="quantum") == 1.0


def test_predict_fit_gate_and_why():
    from deepspeed_tpu.autotuning import ModelInfo, predict_fit

    tiny = ModelInfo(num_params=500_000, hidden_size=128, num_layers=2,
                     vocab_size=5_000)
    fit = predict_fit(tiny, 0, 1, 1, 64, hbm_bytes=16 << 30)
    assert fit["predicted_fit"] and fit["shortfall_bytes"] == 0
    big = ModelInfo(num_params=6_700_000_000, hidden_size=4096,
                    num_layers=32, vocab_size=50_257)
    nofit = predict_fit(big, 3, 1, 1, 512, hbm_bytes=16 << 30)
    assert not nofit["predicted_fit"]
    assert nofit["shortfall_bytes"] > 0
    # 6.7B at dp=1: the un-shardable optimizer state dominates — the
    # "why" the ladder records instead of RESOURCE_EXHAUSTED
    assert nofit["dominant_class"] == "optimizer"
    assert nofit["breakdown"]["total"] >= nofit["breakdown"]["optimizer"]
    # ZeRO-Offload re-homing: the same 6.7B rung with optimizer+params
    # offloaded must NOT be priced against the device budget — the
    # offload rungs are the point of the ladder (pre-fix they were all
    # predicted unfit and silently skipped)
    nvme = predict_fit(big, 3, 1, 1, 512, hbm_bytes=16 << 30,
                       offload_param="nvme", offload_optimizer="nvme")
    assert nvme["predicted_fit"], nvme
    assert nvme["predicted_peak_bytes"] < nofit["predicted_peak_bytes"]
    # cpu-homed classes are priced against host RAM instead: a 6.7B
    # optimizer (~96GB fp32 masters+moments) cannot fit a 32GB host
    cpu = predict_fit(big, 3, 1, 1, 512, hbm_bytes=16 << 30,
                      offload_param="cpu", offload_optimizer="cpu",
                      host_bytes=32 << 30)
    assert not cpu["predicted_fit"]
    assert cpu["dominant_class"] == "optimizer"
    assert cpu["host_resident_bytes"] > (32 << 30)
    assert cpu["shortfall_bytes"] > 0


# ----------------------------------------------------------------------
# the PR-11 recycled grad accumulator stays born sharded
# ----------------------------------------------------------------------
def test_zero_grads_buffer_born_in_accumulator_sharding():
    """Memory-plan pin of the PR-11 win (2.08MB → 0.26MB/dev on the tiny
    mesh): `_zero_grads_jit`'s output is born IN the accumulator
    sharding, so its per-device footprint is the shard, not the world —
    a refactor that resurrects the unsharded transient fails here before
    it costs ~1.4GB at gpt2-350m scale."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config

    model = get_model_config("gpt2-tiny", max_seq_len=64)
    engine, _, _, _ = ds.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2}, "steps_per_print": 10_000,
        "mesh": {"data": jax.device_count()}})
    try:
        assert engine._zero_grads_jit is not None
        full_bytes = sum(
            int(np.prod(leaf.shape)) * 4 for leaf in
            jax.tree_util.tree_leaves(engine.params))
        rep = audit_memory(engine._zero_grads_jit, label="zero_grads")
        # per-device output = the accumulator SHARD (replicated small
        # leaves keep it above full/world, but far below the full tree)
        assert 0 < rep.totals["output_bytes"] < full_bytes / 2, \
            (rep.totals, full_bytes)
        assert not [f for f in rep.findings
                    if f.kind == "unsharded_transient"]
        # the regression this pins: an unsharded zeros tree costs the
        # full footprint per device
        spec = jax.eval_shape(engine._zero_grads_jit)
        unsharded = jax.jit(
            lambda: jax.tree_util.tree_map(
                lambda s: jnp.zeros(s.shape, s.dtype), spec))
        bad = audit_memory(unsharded, label="unsharded_twin")
        assert bad.totals["output_bytes"] >= full_bytes
        assert bad.totals["output_bytes"] \
            > 2 * rep.totals["output_bytes"]
    finally:
        engine.destroy()


# ----------------------------------------------------------------------
# capture report hbm cross-check (satellite: report.json `hbm` block)
# ----------------------------------------------------------------------
def test_capture_report_hbm_block_degrades_on_cpu(tmp_path):
    from deepspeed_tpu.telemetry.capture import (build_capture_report,
                                                 hbm_cross_check)

    class Rec:
        step = 4
        mfu = 0.5
        wall_time_s = 0.1
        flops_source = "measured"
        hbm = {"device_0": {"bytes_in_use": 900,
                            "peak_bytes_in_use": 1100},
               "device_1": {"bytes_in_use": 800,
                            "peak_bytes_in_use": 1000}}

    # no static plan recorded ⇒ null + note
    block, note = hbm_cross_check(None, Rec())
    assert block is None and "no static memory plan" in note
    # cpu backend ⇒ null + note (host RSS is not device HBM)
    block, note = hbm_cross_check(
        {"backend": "cpu", "peak_bytes": 1000}, Rec())
    assert block is None and "cpu" in note
    # tpu backend + watermarks ⇒ the diff
    block, note = hbm_cross_check(
        {"backend": "tpu", "peak_bytes": 1000}, Rec())
    assert note == ""
    assert block["predicted_peak_bytes"] == 1000
    assert block["measured_peak_bytes"] == 1100
    assert block["drift_ratio"] == pytest.approx(1.1)
    # e2e through build_capture_report on a CPU capture dir: hbm is
    # null and the note explains why (regression: the key must exist)
    report = build_capture_report(str(tmp_path), step_record=Rec(),
                                  static_memory={"backend": "cpu",
                                                 "peak_bytes": 1000})
    assert report["hbm"] is None
    assert "host RSS" in report["note"]


def test_engine_flops_handshake_records_static_memory():
    """profile_compiled exposes the memory totals the engine hands to
    telemetry.set_static_memory — the source of the hbm block."""
    from deepspeed_tpu.profiling.flops_profiler import profile_compiled

    prof = profile_compiled(jax.jit(lambda x: (x @ x.T).sum()),
                            jnp.zeros((128, 128)))
    assert "memory" in prof
    mem = prof["memory"]
    assert sorted(mem) == sorted(MEMORY_TOTALS_KEYS)
    assert mem["peak_bytes"] > 0

    from deepspeed_tpu.runtime.config import TelemetryConfig
    from deepspeed_tpu.telemetry import Telemetry

    tel = Telemetry(TelemetryConfig(enabled=True))
    assert tel.static_memory is None
    tel.set_static_memory({"backend": "cpu", **mem})
    assert tel.static_memory["peak_bytes"] == mem["peak_bytes"]


# ----------------------------------------------------------------------
# scheduler evidence + CLI plumbing
# ----------------------------------------------------------------------
def test_scheduler_evidence_carries_static_memory():
    from deepspeed_tpu.autotuning.overlap_scheduler import (
        EVIDENCE_KEYS, ScheduleDecision, extract_evidence)

    assert "static_memory" in EVIDENCE_KEYS
    mem = {"peak_bytes": 9135273, "temp_bytes": 6781032,
           "class_bytes": {"params": 1882112}}
    rep = {"devices": {"d0": {"collective_ms": 1.0}},
           "overlap_fraction": 0.4, "step": 4, "static_memory": mem}
    ev = extract_evidence(rep, {"zero_stage": 3})
    assert ev["static_memory"] == mem
    # records pinned before the field existed keep loading (the same
    # back-compat contract static_census has)
    old = {"decision": "noop", "knobs": {},
           "evidence": {"dominant_collective": "all-gather",
                        "exposed_comm_ms": 1.2, "overlap_fraction": 0.3,
                        "overlap_source": "spans", "probe_step": 4,
                        "static_census": None}}
    d = ScheduleDecision.from_dict(old)
    assert d.evidence["static_memory"] is None


def test_memory_summary_shape():
    rep = audit_memory(jax.jit(lambda x: x * 2), jnp.zeros((64, 64)),
                       label="sum")
    s = rep.summary()
    assert set(MEMORY_TOTALS_KEYS) <= set(s)
    assert set(s["class_bytes"]) == set(MEMORY_CLASSES)


def test_graft_lint_cli_memory_target_filter(tmp_path):
    """CLI plumbing: --memory --target runs exactly the named target's
    memory audit against the committed budget and exits 0."""
    import importlib.util

    path = os.path.join(REPO, "tools", "graft_lint.py")
    spec = importlib.util.spec_from_file_location("graft_lint_mem", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = str(tmp_path / "lint.json")
    rc = mod.main(["--memory", "--target", "ring_attention",
                   "--json", out])
    assert rc == 0
    with open(out, "r", encoding="utf-8") as f:
        data = json.load(f)
    assert data["unbaselined_high"] == []
    labels = [r["label"] for r in data["memory_reports"]]
    assert labels == ["ring_attention"]
    assert data["reports"] == []    # --memory alone runs no graph audits
    rep = data["memory_reports"][0]
    assert rep["schema"] == 1
    assert rep["budget"]["budget_bytes"] is not None
    # a misspelled --target must fail loudly (argparse exits 2), never
    # shrink the audit set to empty and return a green 0
    with pytest.raises(SystemExit) as exc:
        mod.main(["--memory", "--target", "ring_attentionx"])
    assert exc.value.code == 2