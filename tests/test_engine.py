"""End-to-end engine tests: the TPU analog of the reference's
tests/unit/runtime/zero convergence tests — train a toy model on an 8-device
mesh under each ZeRO stage and assert the loss decreases."""

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import get_model_config
from tests.conftest import make_lm_batch


def _base_config(**over):
    cfg = {
        "train_batch_size": 8,
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "bf16": {"enabled": False},
        "gradient_clipping": 1.0,
        "steps_per_print": 1000,
    }
    cfg.update(over)
    return cfg


def _fixed_batches(vocab, n_steps, global_batch, seq=16, seed=0):
    """The same batch every step — overfitting it drives the loss down, the
    standard toy-model convergence check (ref tests/unit/simple_model.py)."""
    rng = np.random.default_rng(seed)
    batch = make_lm_batch(rng, global_batch, seq, vocab)
    return [batch for _ in range(n_steps)]


def _train(engine, batches):
    losses = [float(np.asarray(engine.train_batch(b))) for b in batches]
    return losses


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_zero_stages_loss_decreases(stage):
    model = get_model_config("gpt2-tiny")
    cfg = _base_config(zero_optimization={"stage": stage}, mesh={"data": 8})
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    batches = _fixed_batches(model.vocab_size, 8, 8)
    losses = _train(engine, batches)
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0] - 0.1, f"no learning: {losses}"


def test_zero3_matches_zero0():
    """ZeRO is a memory optimization — numerics must match across stages."""
    model = get_model_config("gpt2-tiny")
    batches = _fixed_batches(model.vocab_size, 4, 8)
    losses = {}
    for stage in (0, 3):
        cfg = _base_config(zero_optimization={"stage": stage}, mesh={"data": 8})
        engine, _, _, _ = ds.initialize(model=model, config=cfg, seed=7)
        losses[stage] = _train(engine, batches)
    np.testing.assert_allclose(losses[0], losses[3], rtol=2e-4, atol=2e-4)


def test_gradient_accumulation_equivalence():
    """gas=4 with micro=1 must match gas=1 with micro=4 (same global batch)."""
    model = get_model_config("gpt2-tiny")
    batches = _fixed_batches(model.vocab_size, 3, 8)
    losses = {}
    for gas in (1, 4):
        cfg = _base_config(train_batch_size=8,
                           train_micro_batch_size_per_gpu=8 // (8 * gas) or 1,
                           gradient_accumulation_steps=gas,
                           mesh={"data": 1})
        cfg["train_micro_batch_size_per_gpu"] = 8 // gas
        engine, _, _, _ = ds.initialize(model=model, config=cfg, seed=7)
        losses[gas] = _train(engine, batches)
    np.testing.assert_allclose(losses[1], losses[4], rtol=2e-4, atol=2e-4)


def test_forward_backward_step_trio():
    model = get_model_config("gpt2-tiny")
    cfg = _base_config(train_batch_size=8, train_micro_batch_size_per_gpu=4,
                       gradient_accumulation_steps=2, mesh={"data": 1})
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    rng = np.random.default_rng(0)
    mb = make_lm_batch(rng, 4, 16, model.vocab_size)
    first = last = None
    for step in range(6):
        for _ in range(engine.gradient_accumulation_steps()):
            loss = engine.forward(mb)
            engine.backward(loss)
        engine.step()
        val = float(np.asarray(loss))
        first = val if first is None else first
        last = val
    assert engine.global_steps == 6
    assert last < first


def test_tp_mesh_runs():
    model = get_model_config("gpt2-tiny")
    cfg = _base_config(mesh={"data": 4, "tensor": 2})
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    batches = _fixed_batches(model.vocab_size, 3, 8)
    losses = _train(engine, batches)
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]


def test_fp16_dynamic_loss_scale():
    model = get_model_config("gpt2-tiny")
    cfg = _base_config(fp16={"enabled": True, "initial_scale_power": 4},
                       mesh={"data": 8})
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    assert engine.loss_scale == 16.0
    batches = _fixed_batches(model.vocab_size, 4, 8)
    losses = _train(engine, batches)
    assert all(np.isfinite(losses))


def test_checkpoint_roundtrip(tmp_path):
    model = get_model_config("gpt2-tiny")
    cfg = _base_config(zero_optimization={"stage": 2}, mesh={"data": 8})
    engine, _, _, _ = ds.initialize(model=model, config=cfg, seed=3)
    batches = _fixed_batches(model.vocab_size, 6, 8)
    losses_a = _train(engine, batches[:3])
    engine.save_checkpoint(str(tmp_path), tag="ckpt")

    engine2, _, _, _ = ds.initialize(model=model, config=cfg, seed=99)
    engine2.load_checkpoint(str(tmp_path), tag="ckpt")
    assert engine2.global_steps == 3
    cont_a = _train(engine, batches[3:])
    cont_b = _train(engine2, batches[3:])
    np.testing.assert_allclose(cont_a, cont_b, rtol=1e-5, atol=1e-5)


def test_eval_batch():
    model = get_model_config("gpt2-tiny")
    engine, _, _, _ = ds.initialize(model=model, config=_base_config(mesh={"data": 8}))
    rng = np.random.default_rng(0)
    loss = engine.eval_batch(make_lm_batch(rng, 8, 16, model.vocab_size))
    assert np.isfinite(float(np.asarray(loss)))


def test_moe_model_trains():
    model = get_model_config("mixtral-tiny")
    cfg = _base_config(mesh={"data": 4, "expert": 2})
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    batches = _fixed_batches(model.vocab_size, 6, 8)
    losses = _train(engine, batches)
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]


def test_cancel_prefetch_warning_throttled_once():
    """A failing discarded prefetch warns once per process, not once per
    checkpoint load (same pattern as the accelerator's unbalanced
    range_pop throttle) — and the futures are still joined and cleared
    on the silent repeats."""
    import types
    from concurrent.futures import Future
    from unittest import mock

    from deepspeed_tpu.runtime import engine as engine_mod

    def failing():
        f = Future()
        f.set_exception(RuntimeError("nvme read failed"))
        return f

    obj = types.SimpleNamespace(_opt_fut=None, _param_fut=None)
    engine_mod._DISCARDED_PREFETCH_WARNED = False
    try:
        with mock.patch.object(engine_mod, "logger") as lg:
            obj._opt_fut = failing()
            engine_mod.DeepSpeedEngine._cancel_prefetch(obj)
            assert lg.warning.call_count == 1
            # second and third failures: joined, cleared, silent
            obj._opt_fut = failing()
            obj._param_fut = failing()
            engine_mod.DeepSpeedEngine._cancel_prefetch(obj)
            assert lg.warning.call_count == 1
        assert obj._opt_fut is None and obj._param_fut is None
    finally:
        engine_mod._DISCARDED_PREFETCH_WARNED = False
