"""DistributedDataAnalyzer: multi-process map-reduce with merged
index-file outputs (ref data_sampling/data_analyzer.py:455
DistributedDataAnalyzer + output_index_to_sample_percentile :415)."""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _check_outputs(out, n):
    """Shared assertions: merged index files are complete and coherent."""
    mdir = os.path.join(out, "seqlen")
    s2m = np.load(os.path.join(mdir, "seqlen_sample_to_metric.npy"))
    assert s2m.shape == (n,)
    # ground truth: sample i has length 4 + i % 7
    np.testing.assert_array_equal(s2m, 4 + np.arange(n) % 7)
    uniq = np.load(os.path.join(mdir, "seqlen_index_to_metric.npy"))
    assert np.all(np.diff(uniq) > 0)
    z = np.load(os.path.join(mdir, "seqlen_index_to_sample.npz"))
    ids, offsets = z["ids"], z["offsets"]
    assert offsets[0] == 0 and offsets[-1] == n == len(ids)
    for v_idx, v in enumerate(uniq):
        row = ids[offsets[v_idx]:offsets[v_idx + 1]]
        np.testing.assert_array_equal(np.sort(row),
                                      np.where(s2m == v)[0])
    pm = np.load(os.path.join(
        mdir, "seqlen_index_to_sample_percentile_merged.npz"))
    assert pm["offsets"][-1] == n
    # sampler-compatible flat files (DataAnalyzer layout)
    vals = np.load(os.path.join(out, "seqlen_values.npy"))
    np.testing.assert_array_equal(vals, s2m)
    order = np.load(os.path.join(out, "seqlen_index_sorted.npy"))
    assert np.all(np.diff(vals[order]) >= 0)
    # accumulate metric: elementwise sum over all workers
    tok = np.load(os.path.join(out, "tokens", "tokens_metric_value.npy"))
    assert tok.shape == (16,) and tok.sum() == n


class _Ds:
    def __init__(self, n=103):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return {"input_ids": list(range(4 + i % 7)), "first": i % 16}


def _metrics():
    def tokens_hist(sample):
        h = np.zeros(16)
        h[sample["first"]] = 1
        return h

    return ({"seqlen": lambda s: len(s["input_ids"]),
             "tokens": tokens_hist},
            {"tokens": "accumulate_value_over_samples"})


def test_single_process_outputs(tmp_path):
    from deepspeed_tpu.runtime.data_pipeline import DistributedDataAnalyzer

    metrics, types = _metrics()
    a = DistributedDataAnalyzer(_Ds(), str(tmp_path), metrics=metrics,
                                metric_types=types)
    a.run_map_reduce()
    _check_outputs(str(tmp_path), 103)


def test_merge_rejects_duplicate_sample_indices(tmp_path):
    """Round-5 advisor finding: duplicate sample ids silently kept the
    last-scattered value — the merge must raise instead."""
    from deepspeed_tpu.runtime.data_pipeline import DistributedDataAnalyzer

    ds = _Ds(6)
    a = DistributedDataAnalyzer(
        ds, str(tmp_path), metrics={"seqlen": lambda s: len(s["input_ids"])},
        sample_indices=[0, 1, 2, 2, 4, 5])  # id 2 mapped twice
    with pytest.raises(ValueError, match="duplicate sample_indices"):
        a.run_map_reduce()


def test_merge_sparse_ids_nan_not_zero(tmp_path):
    """sample_indices into a larger corpus: ids absent from the gather
    must be NaN in the dense table, distinguishable from a real 0.0."""
    from deepspeed_tpu.runtime.data_pipeline import DistributedDataAnalyzer

    ds = _Ds(4)
    a = DistributedDataAnalyzer(
        ds, str(tmp_path), metrics={"seqlen": lambda s: len(s["input_ids"])},
        sample_indices=[10, 3, 7, 0])
    a.run_map_reduce()
    dense = np.load(os.path.join(str(tmp_path), "seqlen",
                                 "seqlen_sample_to_metric.npy"))
    assert dense.shape == (11,)
    present = np.asarray([10, 3, 7, 0])
    np.testing.assert_array_equal(dense[present], [4, 5, 6, 7])
    absent = np.setdiff1d(np.arange(11), present)
    assert np.all(np.isnan(dense[absent]))
    # ...but the sampler-facing flat files stay finite: NaN difficulties
    # would fail every threshold test and drop the samples silently
    vals = np.load(os.path.join(str(tmp_path), "seqlen_values.npy"))
    assert np.all(np.isfinite(vals))
    np.testing.assert_array_equal(vals[present], [4, 5, 6, 7])
    assert np.all(vals[absent] == 0.0)


def test_merge_all_empty_accumulate_metric(tmp_path):
    """Empty dataset: the accumulate merge must not collapse to a 0-d
    scalar via np.sum([], axis=0)."""
    from deepspeed_tpu.runtime.data_pipeline import DistributedDataAnalyzer

    metrics, types = _metrics()
    a = DistributedDataAnalyzer(_Ds(0), str(tmp_path), metrics=metrics,
                                metric_types=types)
    a.run_map_reduce()
    tok = np.load(os.path.join(str(tmp_path), "tokens",
                               "tokens_metric_value.npy"))
    assert tok.ndim == 1 and tok.size == 0


WORKER = textwrap.dedent("""
    import os, sys
    import numpy as np

    rank = int(sys.argv[1]); world = int(sys.argv[2])
    port = sys.argv[3]; out = sys.argv[4]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["DSTPU_COORDINATOR"] = f"localhost:{port}"
    os.environ["DSTPU_NUM_PROCS"] = str(world)
    os.environ["DSTPU_PROC_ID"] = str(rank)
    sys.path.insert(0, os.environ["DSTPU_TEST_REPO"])
    sys.path.insert(0, os.path.join(os.environ["DSTPU_TEST_REPO"], "tests"))

    import jax
    jax.config.update("jax_platforms", "cpu")
    from deepspeed_tpu.comm import comm
    comm.init_distributed(mesh_sizes={"data": 4})
    assert jax.process_count() == world

    from test_data_analyzer_dist import _Ds, _metrics
    from deepspeed_tpu.runtime.data_pipeline import DistributedDataAnalyzer

    metrics, types = _metrics()
    a = DistributedDataAnalyzer(_Ds(), out, metrics=metrics,
                                metric_types=types)
    assert a.num_workers == 2 and a.worker_id == rank
    # contiguous split (ref split_dataset): disjoint cover of the dataset
    split = a._worker_split()
    assert len(split) in (51, 52)
    a.run_map_reduce()
    print(f"analyzer worker {rank} OK", flush=True)
""")


@pytest.mark.slow
def test_two_process_map_reduce(tmp_path):
    """2 real jax.distributed processes: each maps its contiguous split,
    rank 0 writes the merged index files; outputs equal the single-process
    ground truth."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    out = str(tmp_path)
    procs, logs = [], []
    import tempfile

    files = []
    for r in range(2):
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(("DSTPU_", "XLA_", "JAX_"))}
        env["DSTPU_TEST_REPO"] = REPO
        f = tempfile.NamedTemporaryFile("w+", suffix=f"_a{r}.log",
                                        delete=False)
        files.append(f)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", WORKER, str(r), "2", str(port), out],
            stdout=f, stderr=subprocess.STDOUT, env=env))
    for p, f in zip(procs, files):
        try:
            p.wait(timeout=300)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
        f.flush()
        f.seek(0)
        logs.append(f.read())
        f.close()
        os.unlink(f.name)
    for p, log in zip(procs, logs):
        assert p.returncode == 0, f"worker failed:\n{log[-3000:]}"
    _check_outputs(out, 103)
