"""Compression (QAT/pruning/layer reduction) and OptimizedLinear/LoRA.

Mirrors the reference's tests/unit/compression/ and tests/unit/linear/."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.compression import (CompressionManager, init_compression,
                                       quantize_activation_ste,
                                       quantize_weight_ste,
                                       sparse_pruning_mask)
from deepspeed_tpu.compression.basic_layers import (channel_pruning_mask,
                                                    head_pruning_mask,
                                                    row_pruning_mask)
from deepspeed_tpu.linear import (LoRAConfig, OptimizedLinear,
                                  QuantizationConfig, QuantizedParameter,
                                  init_lora_params, lora_linear)

RNG = np.random.default_rng(0)


def _w(*shape):
    return jnp.asarray(RNG.standard_normal(shape), jnp.float32)


def test_quantize_weight_ste_value_and_grad():
    w = _w(16, 32)
    q = quantize_weight_ste(w, bits=8)
    assert float(jnp.abs(q - w).max()) < float(jnp.abs(w).max()) / 100
    # STE: gradient passes through ~identity
    g = jax.grad(lambda w: (quantize_weight_ste(w, bits=8) ** 2).sum())(w)
    g_ref = jax.grad(lambda w: (w ** 2).sum())(w)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=0.1, rtol=0.2)


def test_quantize_activation():
    x = _w(4, 64)
    for sym in (True, False):
        q = quantize_activation_ste(x, bits=8, symmetric=sym)
        assert float(jnp.abs(q - x).max()) < 0.1


def test_pruning_masks():
    w = _w(32, 64)
    m = sparse_pruning_mask(w, 0.25)
    assert abs(float(m.mean()) - 0.25) < 0.05
    rm = row_pruning_mask(w, 0.5)
    kept_rows = np.asarray(rm)[0].sum()
    assert kept_rows == 32  # half of 64 output features
    assert (np.asarray(rm).std(axis=0) == 0).all()  # whole columns
    cm = channel_pruning_mask(w, 0.5)
    assert (np.asarray(cm).std(axis=1) == 0).all()  # whole rows
    hm = head_pruning_mask(w, 0.5, num_heads=4)
    hk = np.asarray(hm).reshape(4, 8, 64)
    per_head = hk.reshape(4, -1).mean(axis=1)
    assert set(per_head.tolist()) <= {0.0, 1.0}
    assert per_head.sum() == 2  # half of 4 heads kept


def test_compression_manager_schedule_and_apply():
    cfg = {"compression_training": {
        "sparse_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 5},
            "different_groups": {"g1": {"params": {"dense_ratio": 0.5},
                                        "modules": ["mlp"]}}},
        "weight_quantization": {
            "shared_parameters": {"enabled": True, "schedule_offset": 0},
            "different_groups": {"q1": {"params": {"start_bits": 8},
                                        "modules": ["*"]}}},
    }}
    params = {"mlp": {"wi": _w(8, 16)}, "attn": {"wq": _w(8, 8)}}
    mgr = CompressionManager(cfg)
    # before offset: no pruning, but quantization active at step 0
    p0 = mgr.apply(params, step=0)
    assert float(jnp.abs(p0["mlp"]["wi"]) .min()) >= 0  # smoke
    assert (np.asarray(p0["mlp"]["wi"]) != 0).mean() > 0.9
    p5 = mgr.apply(params, step=5)
    assert abs((np.asarray(p5["mlp"]["wi"]) != 0).mean() - 0.5) < 0.1
    # attn not in pruning scope
    assert (np.asarray(p5["attn"]["wq"]) != 0).mean() > 0.9


def test_layer_reduction():
    params = {"layers": {"wi": _w(8, 4, 4)}, "embed": _w(16, 4)}
    out, mgr = init_compression(params, {"compression_training": {
        "layer_reduction": {"enabled": True, "teacher_layer": [0, 2, 5]}}})
    assert out["layers"]["wi"].shape == (3, 4, 4)
    np.testing.assert_allclose(np.asarray(out["layers"]["wi"][1]),
                               np.asarray(params["layers"]["wi"][2]))
    assert out["embed"].shape == (16, 4)  # non-layer params untouched


def test_redundancy_clean_bakes_masks():
    cfg = {"compression_training": {"sparse_pruning": {
        "shared_parameters": {"enabled": True, "schedule_offset": 100},
        "different_groups": {"g": {"params": {"dense_ratio": 0.25}}}}}}
    mgr = CompressionManager(cfg)
    params = {"w": _w(16, 16)}
    cleaned = mgr.redundancy_clean(params)
    assert abs((np.asarray(cleaned["w"]) != 0).mean() - 0.25) < 0.1


# ----------------------------------------------------------------------
def test_quantized_parameter_roundtrip():
    w = _w(64, 128)
    for bits in (8, 4):
        qp = QuantizedParameter(w, q_bits=bits, group_size=64)
        deq = qp.dequantized()
        assert deq.shape == w.shape
        err = float(jnp.abs(deq - w).max())
        assert err < (0.05 if bits == 8 else 0.6)
        assert qp.nbytes < w.size * 4  # actually compressed


def test_lora_linear_forward_and_grads():
    key = jax.random.PRNGKey(0)
    w = _w(32, 16)
    x = _w(4, 32)
    lc = LoRAConfig(lora_r=8, lora_alpha=16)
    p = init_lora_params(key, 32, 16, lc)
    # B=0 → output equals base at init
    y0 = lora_linear(x, w, p["lora_A"], p["lora_B"], lora_alpha=16, lora_r=8)
    np.testing.assert_allclose(np.asarray(y0), np.asarray(x @ w), atol=1e-5)

    def loss(p, w):
        y = lora_linear(x, w, p["lora_A"], p["lora_B"], lora_alpha=16, lora_r=8)
        return (y ** 2).sum()

    gp, gw = jax.grad(loss, argnums=(0, 1))(p, w)
    # B=0 at init → grad flows to B first (A's grad passes through B)
    assert float(jnp.abs(gp["lora_B"]).max()) > 0  # adapters train
    assert float(jnp.abs(gw).max()) == 0  # base frozen


def test_optimized_linear_quantized_base():
    w = _w(64, 32)
    x = _w(2, 64)
    ol = OptimizedLinear(w, lora_config=LoRAConfig(lora_r=4),
                         quantization_config=QuantizationConfig(q_bits=8, group_size=32),
                         key=jax.random.PRNGKey(1))
    y = ol(x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x @ w), atol=0.2, rtol=0.1)
    assert set(ol.trainable_params()) == {"lora_A", "lora_B"}


# ---------------------------------------------------------------------------
# TiledLinear (ref runtime/zero/tiling.py): feature-dim tiling with remat.
# ---------------------------------------------------------------------------
def test_tiled_linear_matches_dense():
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.runtime.tiling import TiledLinear

    key = jax.random.PRNGKey(0)
    tl = TiledLinear(12, 20, in_splits=3, out_splits=4)
    w = jax.random.normal(key, (12, 20), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(1), (20,), jnp.float32)
    params = tl.from_dense(w, b)
    x = jax.random.normal(jax.random.PRNGKey(2), (5, 12), jnp.float32)
    np.testing.assert_allclose(np.asarray(tl.apply(params, x)),
                               np.asarray(x @ w + b), rtol=1e-5, atol=1e-5)
    # layout roundtrip + gradients flow through the scanned tiles
    np.testing.assert_allclose(np.asarray(tl.to_dense(params)),
                               np.asarray(w), rtol=1e-7)

    def loss(p):
        return (tl.apply(p, x) ** 2).sum()

    g = jax.jit(jax.grad(loss))(params)
    g_dense = jax.grad(lambda wd: ((x @ wd + b) ** 2).sum())(w)
    np.testing.assert_allclose(np.asarray(tl.to_dense(g)),
                               np.asarray(g_dense), rtol=1e-4, atol=1e-4)


def test_tiled_linear_leading_dims_and_splits_validation():
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.runtime.tiling import TiledLinear

    tl = TiledLinear(8, 6, in_splits=2, out_splits=3, bias=False)
    params = tl.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 4, 8), jnp.float32)
    y = tl.apply(params, x)
    assert y.shape == (2, 4, 6)
    ref = x.reshape(-1, 8) @ tl.to_dense(params)
    np.testing.assert_allclose(np.asarray(y).reshape(-1, 6), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    with pytest.raises(ValueError):
        TiledLinear(10, 6, in_splits=3)


def test_engine_sparse_pruning_schedule_converges():
    """Engine-integrated compression (ref init_compression + scheduler):
    sparse pruning switches on mid-training at schedule_offset and the
    model keeps converging; the baked (redundancy_clean) weights carry the
    target sparsity."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config

    model = get_model_config("gpt2-tiny")
    cfg = {"train_micro_batch_size_per_gpu": 4,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
           "mesh": {"data": 1},
           "compression_training": {
               "sparse_pruning": {
                   "shared_parameters": {"enabled": True,
                                         "schedule_offset": 3},
                   "different_groups": {
                       "sp1": {"params": {"dense_ratio": 0.5},
                               "modules": ["mlp"]}}}}}
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(4, 33), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    losses = [float(np.asarray(engine.train_batch(batch)))
              for _ in range(8)]
    assert losses[-1] < losses[0] - 1.0, losses  # converges through the flip
    # masks bake in: mlp weights half-zero after redundancy_clean
    baked = engine._compression.redundancy_clean(
        jax.tree.map(np.asarray, engine.params))
    w = np.asarray(baked["layers"]["mlp"]["wi"])
    frac = (w == 0).mean()
    assert 0.45 <= frac <= 0.55, frac
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None


def test_engine_layer_reduction_and_student_init():
    """layer_reduction shrinks the engine's model; student_initialization
    maps teacher rows onto the student (ref compression/helper.py)."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.compression.compress import student_initialization
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.models import transformer as tf

    model = get_model_config("gpt2-tiny", num_layers=4)
    teacher = tf.init_params(model, jax.random.PRNGKey(1))
    cc = {"compression_training": {
        "layer_reduction": {"enabled": True, "teacher_layer": [0, 3]}}}
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
           "mesh": {"data": 1}, **cc}
    engine, _, _, _ = ds.initialize(model=model, config=cfg,
                                    model_parameters=teacher)
    assert engine.model_config.num_layers == 2
    assert engine.params["layers"]["mlp"]["wi"].shape[0] == 2
    np.testing.assert_allclose(
        np.asarray(engine.params["layers"]["mlp"]["wi"][1]),
        np.asarray(teacher["layers"]["mlp"]["wi"][3]), atol=1e-6)
    # student_initialization standalone maps the same rows
    student = tf.init_params(model.replace(num_layers=2),
                             jax.random.PRNGKey(2))
    student = student_initialization(student, teacher, cc)
    np.testing.assert_allclose(
        np.asarray(student["layers"]["attn"]["wq"][0]),
        np.asarray(teacher["layers"]["attn"]["wq"][0]), atol=1e-6)
    # and the reduced engine trains
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(2, 17), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    l0 = float(np.asarray(engine.train_batch(batch)))
    for _ in range(4):
        l1 = float(np.asarray(engine.train_batch(batch)))
    assert l1 < l0
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None
