"""Fast file writer, indexed tensor format, fast/decoupled checkpoint
engines, NVMe sweep tool.

Mirrors reference coverage: tests/unit/checkpoint/, deepspeed/io tests."""

import os

import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.io import (FastFileWriter, MockFileWriter, PyFileWriter,
                              read_tensor_file, write_tensor_file)
from deepspeed_tpu.models import get_model_config
from deepspeed_tpu.nvme import run_sweep


def _reset_topo():
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None


def test_fast_file_writer_roundtrip(tmp_path):
    path = str(tmp_path / "out.bin")
    w = FastFileWriter(path, buffer_bytes=1024)  # small → many flushes
    payload = np.random.default_rng(0).integers(0, 255, 10_000, dtype=np.uint8)
    w.write(payload.tobytes())
    stats = w.close()
    assert stats["bytes_written"] == 10_000
    assert stats["flush_count"] >= 9  # double-buffer cycled
    with open(path, "rb") as f:
        np.testing.assert_array_equal(
            np.frombuffer(f.read(), np.uint8), payload)


def test_writer_variants(tmp_path):
    arr = np.arange(100, dtype=np.float32)
    p = PyFileWriter(str(tmp_path / "py.bin"))
    p.write_array(arr)
    assert p.close()["bytes_written"] == arr.nbytes
    m = MockFileWriter("ignored")
    m.write_array(arr)
    assert m.close()["bytes_written"] == arr.nbytes
    assert not os.path.exists("ignored")


def test_tensor_file_format(tmp_path):
    tensors = {"a/w": np.random.default_rng(0).standard_normal((8, 4)).astype(np.float32),
               "b": np.arange(10, dtype=np.int32)}
    path = str(tmp_path / "t.bin")
    write_tensor_file(path, tensors, buffer_bytes=64)
    out = read_tensor_file(path)
    assert set(out) == {"a/w", "b"}
    for k in tensors:
        np.testing.assert_array_equal(out[k], tensors[k])
        assert out[k].dtype == tensors[k].dtype


@pytest.mark.parametrize("writer_type", ["fast", "decoupled"])
def test_checkpoint_engine_roundtrip(tmp_path, writer_type):
    model = get_model_config("gpt2-tiny")
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
           "mesh": {"data": 1},
           "checkpoint": {"writer": {"type": writer_type}}}
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(2, 9), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
    for _ in range(2):
        engine.train_batch(batch)
    engine.save_checkpoint(str(tmp_path), tag="t1")
    ce = engine.checkpoint_engine
    if hasattr(ce, "wait"):
        ce.wait()
    assert os.path.exists(tmp_path / "t1" / "model_states.bin")
    assert (tmp_path / "latest").read_text() == "t1"
    ref_params = {p: np.asarray(v) for p, v in
                  [("loss", engine.train_batch(batch))]}
    step_before = engine.global_steps
    _reset_topo()

    engine2, _, _, _ = ds.initialize(model=model, config=cfg)
    engine2.load_checkpoint(str(tmp_path))
    assert engine2.global_steps == step_before - 1  # saved before last step
    # params equal at load point → same next loss trajectory
    l2 = float(np.asarray(engine2.train_batch(batch)))
    assert np.isfinite(l2)
    _reset_topo()


def test_decoupled_snapshot_isolated(tmp_path):
    """Decoupled save must snapshot: mutating params after save() but
    before wait() must not change what lands on disk."""
    import jax

    from deepspeed_tpu.checkpoint.fast_engine import DecoupledCheckpointEngine

    model = get_model_config("gpt2-tiny")
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-1}},
           "mesh": {"data": 1}}
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    ce = DecoupledCheckpointEngine()
    (first_path, first_leaf), *_ = jax.tree_util.tree_flatten_with_path(
        engine.params)[0]
    first_name = "module/" + "/".join(
        str(getattr(p, "key", getattr(p, "idx", p))) for p in first_path)
    before = np.asarray(jax.device_get(first_leaf), np.float32).copy()
    ce.save(engine, str(tmp_path), "snap")
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(2, 9), dtype=np.int32)
    engine.train_batch({"input_ids": ids[:, :-1], "labels": ids[:, 1:]})
    ce.wait()
    from deepspeed_tpu.io import read_tensor_file as rtf

    flat = rtf(str(tmp_path / "snap" / "model_states.bin"))
    saved = flat[first_name].astype(np.float32)
    after = np.asarray(jax.device_get(jax.tree_util.tree_flatten_with_path(
        engine.params)[0][0][1]), np.float32)
    # on-disk leaf matches the pre-training snapshot, not the mutated params
    np.testing.assert_allclose(saved, before, atol=1e-6)
    assert np.abs(after - before).max() > 0  # training really moved them
    _reset_topo()


def test_nvme_sweep(tmp_path):
    out = run_sweep(str(tmp_path), io_bytes=1 << 20,
                    block_sizes=[256 << 10, 1 << 20], queue_depths=[4])
    assert out["results"]
    assert out["aio_config"]["block_size"] in (256 << 10, 1 << 20)
    assert all(r["write_gbps"] > 0 and r["read_gbps"] > 0
               for r in out["results"])
    assert not os.path.exists(tmp_path / "_dstpu_sweep.bin")  # cleaned up
