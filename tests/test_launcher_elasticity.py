"""Launcher CLI + elasticity tests (ref tests/unit/launcher/, elasticity/)."""

import io
import subprocess
import sys

import pytest

from deepspeed_tpu.elasticity import (ElasticityConfigError,
                                      ElasticityIncompatibleWorldSize,
                                      compute_elastic_config,
                                      get_compatible_gpus_v01, get_valid_gpus)
from deepspeed_tpu.launcher.runner import (build_parser, decode_world_info,
                                           encode_world_info, parse_hostfile,
                                           parse_resource_filter,
                                           OpenMPIRunner, PDSHRunner, SlurmRunner)
from deepspeed_tpu.launcher.launch import compute_ranks


def test_parse_hostfile():
    hosts = parse_hostfile(["worker-0 slots=4", "# comment", "",
                            "worker-1 slots=8  # trailing"])
    assert hosts == {"worker-0": 4, "worker-1": 8}


def test_parse_hostfile_rejects_bad_lines():
    with pytest.raises(ValueError):
        parse_hostfile(["worker-0 gpus=4"])
    with pytest.raises(ValueError):
        parse_hostfile(["w slots=2", "w slots=2"])


def test_resource_filter_include_exclude():
    res = parse_hostfile(["a slots=4", "b slots=4", "c slots=2"])
    inc = parse_resource_filter(res, include="a:0,1@c")
    assert inc == {"a": [0, 1], "c": [0, 1]}
    exc = parse_resource_filter(res, exclude="b@a:3")
    assert exc == {"a": [0, 1, 2], "c": [0, 1]}
    with pytest.raises(ValueError):
        parse_resource_filter(res, include="a", exclude="b")
    with pytest.raises(ValueError):
        parse_resource_filter(res, include="zzz")


def test_world_info_roundtrip_and_ranks():
    active = {"a": [0, 1], "b": [0, 1, 2]}
    blob = encode_world_info(active)
    assert decode_world_info(blob) == active
    base, slots = compute_ranks(active, 1)
    assert base == 2 and slots == [0, 1, 2]


def test_runner_cmds_contain_rendezvous():
    args = build_parser().parse_args(
        ["--master_addr", "10.0.0.1", "train.py", "--foo", "1"])
    active = {"a": [0], "b": [0]}
    env = {"DSTPU_COORDINATOR": "10.0.0.1:29500", "DSTPU_NUM_PROCS": "2"}
    blob = encode_world_info(active)
    pdsh = PDSHRunner(args, blob).get_cmd(env, active)
    assert pdsh[0] == "pdsh" and "a,b" in pdsh
    assert any("deepspeed_tpu.launcher.launch" in c for c in pdsh)
    mpi = OpenMPIRunner(args, blob).get_cmd(env, active)
    assert mpi[:3] == ["mpirun", "-n", "2"]
    srun = SlurmRunner(args, blob).get_cmd(env, active)
    assert srun[:3] == ["srun", "-n", "2"]


def test_single_node_dry_run():
    from deepspeed_tpu.launcher.runner import main
    import contextlib
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(["--hostfile", "/nonexistent", "--dry_run", "train.py"])
    assert rc == 0
    assert "deepspeed_tpu.launcher.launch" in buf.getvalue()


def test_env_report_runs():
    from deepspeed_tpu.env_report import report_lines
    lines = report_lines()
    text = "\n".join(lines)
    assert "deepspeed_tpu" in text and "op compatibility" in text


# ---------------------------------------------------------------------------
# Elasticity (ref tests/unit/elasticity/test_elastic.py)
# ---------------------------------------------------------------------------
BASE = {"elasticity": {"enabled": True, "max_train_batch_size": 10000,
                       "micro_batch_sizes": [8, 12, 16, 17], "min_gpus": 32,
                       "max_gpus": 1500, "min_time": 20, "version": 0.1}}


def test_valid_gpus():
    assert get_valid_gpus(20, [2, 4, 5], 1, 100) == [1, 2, 4, 5, 10]


def test_compatible_gpus_known_case():
    batch, gpus = get_compatible_gpus_v01([8, 12, 16, 17],
                                          max_acceptable_batch_size=10000,
                                          min_gpus=32, max_gpus=1500)
    assert batch % 8 == 0 and batch <= 10000
    assert all(32 <= g <= 1500 for g in gpus)
    # every valid gpu count must evenly produce the final batch
    for g in gpus:
        assert any(batch % (mb * g) == 0 for mb in [8, 12, 16, 17])


def test_compute_elastic_config_and_world_size():
    batch, gpus = compute_elastic_config(BASE)
    assert gpus
    ws = gpus[0]
    b2, g2, micro = compute_elastic_config(BASE, world_size=ws,
                                           return_microbatch=True)
    assert b2 == batch and micro in BASE["elasticity"]["micro_batch_sizes"]
    with pytest.raises(ElasticityIncompatibleWorldSize):
        compute_elastic_config(BASE, world_size=7919)


def test_elasticity_requires_block():
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config({})
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config({"elasticity": {"enabled": False}})


def test_elastic_v2_model_parallel():
    cfg = {"elasticity": {**BASE["elasticity"], "version": 0.2,
                          "model_parallel_size": 4, "num_gpus_per_node": 8,
                          "min_gpus": 4, "max_gpus": 256}}
    batch, gpus = compute_elastic_config(cfg)
    assert all(g % 4 == 0 for g in gpus)


def test_openmpi_rejects_filters():
    args = build_parser().parse_args(["--include", "a", "train.py"])
    active = {"a": [0]}
    with pytest.raises(ValueError):
        OpenMPIRunner(args, encode_world_info(active)).get_cmd({}, active)


def test_slurm_nodelist():
    args = build_parser().parse_args(["train.py"])
    active = {"a": [0], "b": [0]}
    cmd = SlurmRunner(args, encode_world_info(active)).get_cmd({}, active)
    assert cmd[3] == "-w" and cmd[4] == "a,b"


def test_elasticity_micro_batch_over_cap_raises():
    with pytest.raises(ElasticityConfigError):
        get_compatible_gpus_v01([7, 11], max_acceptable_batch_size=5)


def test_elastic_v2_respects_gpu_envelope():
    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 64,
                          "micro_batch_sizes": [2, 4], "min_gpus": 6,
                          "max_gpus": 256, "version": 0.2,
                          "model_parallel_size": 4, "num_gpus_per_node": 8}}
    _, gpus = compute_elastic_config(cfg)
    assert all(6 <= g <= 256 and g % 4 == 0 for g in gpus)


def test_numa_core_binding_helpers(monkeypatch):
    """get_numactl_cmd slices cores per rank and degrades to an empty
    prefix without numactl (ref utils/numa.py:104)."""
    from deepspeed_tpu.utils.numa import (get_numactl_cmd, parse_range_list,
                                          physical_cores)

    monkeypatch.delenv("KMP_AFFINITY", raising=False)
    assert parse_range_list("0-3,8") == [0, 1, 2, 3, 8]
    with pytest.raises(ValueError):
        parse_range_list("3-1")
    cmd, cores = get_numactl_cmd("0-7", num_local_procs=4, local_rank=2)
    assert list(cores) == [4, 5]
    if cmd:  # numactl present: prefix binds exactly this slice
        assert cmd[:3] == ["numactl", "-C", "4-5"]
    with pytest.raises(ValueError, match="cores cannot give"):
        get_numactl_cmd("0-1", num_local_procs=4, local_rank=0)
    # one logical CPU per physical core, and all distinct
    pc = physical_cores()
    assert pc and len(set(pc)) == len(pc)
    monkeypatch.setenv("KMP_AFFINITY", "x")
    with pytest.raises(ValueError, match="KMP_AFFINITY"):
        get_numactl_cmd(None, 1, 0)


def test_launch_bind_cores_spawns(tmp_path):
    """--bind_cores_to_rank launches children with the numactl prefix (or
    bare when numactl is absent) and an OMP_NUM_THREADS cap.

    De-flaked: the bind list is derived from the CPUs this process may
    actually use (a hardcoded "0-1" fails on 1-CPU CI boxes and boxes with
    a restricted affinity mask), nproc degrades to the available
    parallelism, and the spawn timeout scales up on small/loaded hosts
    (two interpreter boots through a loaded 1-core machine can far exceed
    the old 120 s budget)."""
    import os
    import subprocess
    import sys as _sys

    try:
        avail = sorted(os.sched_getaffinity(0))
    except AttributeError:  # non-Linux
        avail = list(range(os.cpu_count() or 1))
    nproc = 2 if len(avail) >= 2 else 1
    core_list = ",".join(str(c) for c in avail[:nproc])
    timeout_s = 120 if len(avail) >= 4 else 360

    script = tmp_path / "probe.py"
    # Single os.write (atomic for < PIPE_BUF) — concurrent ranks sharing the
    # pipe must not interleave mid-token, or the count below miscounts.
    script.write_text(
        "import os\n"
        "os.write(1, ('OMP=%s;' % os.environ.get('OMP_NUM_THREADS'))"
        ".encode())\n")
    r = subprocess.run(
        [_sys.executable, "-m", "deepspeed_tpu.launcher.launch",
         "--nproc", str(nproc), "--bind_cores_to_rank",
         "--bind_core_list", core_list,
         "--pid_dir", str(tmp_path), str(script)],
        capture_output=True, text=True, timeout=timeout_s)
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("OMP=1;") == nproc, r.stdout


# ----------------------------------------------------------------------
# DSElasticAgent restart path (ref tests for elastic_agent.py; the
# watchdog→agent story: a dead worker triggers a supervised group
# restart, max_restarts bounds the retry budget)
# ----------------------------------------------------------------------
def test_elastic_agent_restarts_dead_worker_and_recovers(tmp_path):
    """First run fails (simulated worker death), the agent restarts the
    group, the retry succeeds — run() returns 0 with one restart."""
    from deepspeed_tpu.elasticity import DSElasticAgent, WorkerSpec

    sentinel = tmp_path / "died_once"
    code = (
        "import os, sys\n"
        f"p = {str(sentinel)!r}\n"
        "if not os.path.exists(p):\n"
        "    open(p, 'w').close()\n"
        "    sys.exit(3)\n"          # first incarnation dies
        "sys.exit(0)\n")
    agent = DSElasticAgent(WorkerSpec([sys.executable, "-c", code]),
                           max_restarts=3, monitor_interval=0.05)
    assert agent.run() == 0
    assert agent.restarts == 1


def test_elastic_agent_max_restarts_honored(tmp_path):
    """A worker that always dies exhausts the restart budget: run()
    returns 1 after exactly max_restarts + 1 failed incarnations."""
    from deepspeed_tpu.elasticity import DSElasticAgent, WorkerSpec

    counter = tmp_path / "attempts"
    code = (
        "import sys\n"
        f"p = {str(counter)!r}\n"
        "with open(p, 'a') as f:\n"
        "    f.write('x')\n"
        "sys.exit(5)\n")
    agent = DSElasticAgent(WorkerSpec([sys.executable, "-c", code]),
                           max_restarts=2, monitor_interval=0.05)
    assert agent.run() == 1
    assert agent.restarts == 3           # budget exhausted (2) + final
    assert len(counter.read_text()) == 3  # initial + 2 restarts


def test_elastic_agent_group_env_layout(tmp_path):
    """Each worker sees its rank/world layout (the contract workers use
    to rebuild the mesh after a restart or resize)."""
    from deepspeed_tpu.elasticity import DSElasticAgent, WorkerSpec

    code = (
        "import os\n"
        f"d = {str(tmp_path)!r}\n"
        "rank = os.environ['RANK']\n"
        "with open(os.path.join(d, 'r' + rank), 'w') as f:\n"
        "    f.write(os.environ['WORLD_SIZE'] + ' '\n"
        "            + os.environ['DSTPU_NUM_PROCS'] + ' '\n"
        "            + os.environ['DSTPU_PROC_ID'])\n")
    agent = DSElasticAgent(
        WorkerSpec([sys.executable, "-c", code], local_world_size=2),
        max_restarts=0, monitor_interval=0.05)
    assert agent.run() == 0
    for rank in (0, 1):
        out = (tmp_path / f"r{rank}").read_text().split()
        assert out == ["2", "2", str(rank)]
