"""Fused compute-collective kernels (PR 12): quantize-into-ppermute,
gather-matmul, and the reduce-scatter grad-accumulator epilogue.

Covers the acceptance matrix:
* the wire codec the Pallas dequant epilogue applies is BITWISE the XLA
  codec (``comm/quantized.wire_decode_rows`` vs
  ``flash_mha.wire_dequant_rows``) — the two can never drift;
* quantized ring fwd+bwd parity on the 2×4 mesh, fused (interpreter
  Pallas) and XLA fallback paths, incl. exact fused-vs-XLA agreement;
* ≥3× collective-permute wire-byte reduction, census-verified;
* ``_rotate_together`` word packing survives odd-length buffers
  (satellite: no caller shape alignment);
* fused gather-matmul kernel + engine loss parity and warn-fallback;
* fused reduce-scatter engine loss parity;
* the overlap scheduler's ``fused_gather_matmul`` decision arm +
  pinned-config compatibility.
"""

import importlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.parallel.topology import MeshTopology, set_topology

# the ops.pallas package re-exports the flash_mha FUNCTION under the
# same name as its submodule — resolve the module itself
_fm = importlib.import_module("deepspeed_tpu.ops.pallas.flash_mha")


@pytest.fixture
def seq_topo():
    topo = MeshTopology({"seq": 4, "data": 2})
    set_topology(topo)
    yield topo
    set_topology(None)


@pytest.fixture
def flash_interpret():
    old = _fm.INTERPRET
    _fm.INTERPRET = True
    yield
    _fm.INTERPRET = old


def _qkv(rng, b=2, s=64, nh=4, nkv=4, d=16, dtype=jnp.float32):
    mk = lambda: jnp.asarray(rng.standard_normal((b, s, nh, d)), dtype)
    q = mk()
    k = jnp.asarray(rng.standard_normal((b, s, nkv, d)), dtype)
    v = jnp.asarray(rng.standard_normal((b, s, nkv, d)), dtype)
    return q, k, v


# ----------------------------------------------------------------------
# Codec parity: the kernel epilogue's dequant IS the XLA codec
# ----------------------------------------------------------------------
def test_wire_codec_kernel_parity_bitwise(rng):
    """flash_mha.wire_dequant_rows must reproduce
    comm/quantized.wire_decode_rows BIT-FOR-BIT on the same blocks —
    the shared-constants contract that keeps the Pallas and XLA wire
    codecs from drifting."""
    from deepspeed_tpu.comm.quantized import (wire_decode_rows,
                                              wire_encode_rows)
    from deepspeed_tpu.ops.pallas.flash_mha import wire_dequant_rows

    x = jnp.asarray(rng.standard_normal((6, 5, 32)), jnp.float32) * 3.7
    payload, scale = wire_encode_rows(x, "int8")
    ref = np.asarray(wire_decode_rows(payload, scale, "int8"))
    got = np.asarray(wire_dequant_rows(payload.reshape(-1, 32),
                                       scale.reshape(-1, 1))).reshape(
                                           ref.shape)
    assert got.dtype == np.float32
    assert np.array_equal(got, ref), "kernel dequant drifted from codec"
    # round trip bounded by the per-row symmetric int8 step
    err = np.abs(ref - np.asarray(x))
    bound = np.abs(np.asarray(x)).max(axis=-1, keepdims=True) / 127 * 0.51
    assert (err <= bound + 1e-7).all()


def test_flash_carry_quantized_matches_decoded_input(rng, flash_interpret):
    """flash_carry_block fed the int8 payload + scales must equal the
    same kernel fed the codec-decoded fp32 K/V exactly (the in-kernel
    dequant is the same arithmetic, then the same kernel body)."""
    from deepspeed_tpu.comm.quantized import (wire_decode_rows,
                                              wire_encode_rows)
    from deepspeed_tpu.ops.pallas.flash_mha import (flash_carry_block,
                                                    ring_carry_pad)

    b, h, s, d = 1, 2, 128, 32
    s_pad = ring_carry_pad(s)
    q = jnp.asarray(rng.standard_normal((b, h, s_pad, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s_pad, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s_pad, d)), jnp.float32)
    m = jnp.full((b, h, s_pad, 128), -1e30, jnp.float32)
    l = jnp.zeros((b, h, s_pad, 128), jnp.float32)
    acc = jnp.zeros((b, h, s_pad, d), jnp.float32)
    kp, ks = wire_encode_rows(k, "int8")
    vp, vs = wire_encode_rows(v, "int8")
    lanes = lambda x: jnp.broadcast_to(x, x.shape[:-1] + (128,))
    off = jnp.int32(0)
    out_q = flash_carry_block(q, kp, vp, m, l, acc, off, off, s_real=s,
                              k_scale=lanes(ks), v_scale=lanes(vs))
    out_f = flash_carry_block(
        q, wire_decode_rows(kp, ks, "int8"),
        wire_decode_rows(vp, vs, "int8"), m, l, acc, off, off, s_real=s)
    for a, b_ in zip(out_q, out_f):
        assert np.array_equal(np.asarray(a), np.asarray(b_))


# ----------------------------------------------------------------------
# Quantized ring parity (both gates, both wire dtypes)
# ----------------------------------------------------------------------
def _ring_loss_grads(topo, q, k, v, wire, interleave=1,
                     placement="contiguous"):
    from deepspeed_tpu.sequence.ring import ring_attention

    def loss(q, k, v):
        return ring_attention(q, k, v, topo, causal=True,
                              placement=placement, interleave=interleave,
                              wire_dtype=wire).astype(jnp.float32).sum()

    l, g = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))(q, k, v)
    return np.asarray(l), [np.asarray(x) for x in g]


@pytest.mark.parametrize("interleave", [1, 2])
@pytest.mark.parametrize("nkv", [4, 2])
def test_ring_quantized_wire_parity(seq_topo, rng, interleave, nkv):
    """int8 ring wire vs the fp32 wire: outputs and grads agree within
    the per-row int8 quantization budget on the XLA fallback path (the
    traveling K/V quantize once, dk/dv once per hop)."""
    q, k, v = _qkv(rng, nkv=nkv)
    l_f, g_f = _ring_loss_grads(seq_topo, q, k, v, "fp32",
                                interleave=interleave)
    l_q, g_q = _ring_loss_grads(seq_topo, q, k, v, "int8",
                                interleave=interleave)
    for a, b in zip(g_q, g_f):
        denom = np.abs(b).max() + 1e-9
        assert np.abs(a - b).max() / denom < 5e-2


def test_ring_quantized_fused_matches_xla_exactly(seq_topo, rng):
    """The fused path (int8 payload into the kernels, in-kernel dequant)
    must agree with the XLA fallback decoding the SAME payloads — both
    compute fp32 from identical decoded values."""
    q, k, v = _qkv(rng)
    old = _fm.INTERPRET
    try:
        _fm.INTERPRET = False
        l_x, g_x = _ring_loss_grads(seq_topo, q, k, v, "int8")
        _fm.INTERPRET = True
        l_p, g_p = _ring_loss_grads(seq_topo, q, k, v, "int8")
    finally:
        _fm.INTERPRET = old
    assert abs(l_x - l_p) < 1e-5
    for a, b in zip(g_p, g_x):
        assert np.abs(a - b).max() < 1e-4, np.abs(a - b).max()


def test_ring_quantized_striped_flash(seq_topo, rng, flash_interpret):
    """Quantized wire composes with striped placement on the fused
    kernels: parity vs the fp32-wire striped ring."""
    q, k, v = _qkv(rng, nkv=2)
    l_f, g_f = _ring_loss_grads(seq_topo, q, k, v, "fp32",
                                placement="striped")
    l_q, g_q = _ring_loss_grads(seq_topo, q, k, v, "int8",
                                placement="striped")
    for a, b in zip(g_q, g_f):
        assert np.abs(a - b).max() / (np.abs(b).max() + 1e-9) < 5e-2


def test_ring_fp8_wire_runs(seq_topo, rng):
    """fp8 wire (payload bitcast to u8, XLA-side decode on both gates)
    stays within its coarser budget."""
    from deepspeed_tpu.comm.quantized import fp8_supported

    if not fp8_supported():
        pytest.skip("no float8_e4m3fn on this jax build")
    q, k, v = _qkv(rng)
    l_f, g_f = _ring_loss_grads(seq_topo, q, k, v, "fp32")
    l_q, g_q = _ring_loss_grads(seq_topo, q, k, v, "fp8")
    for a, b in zip(g_q, g_f):
        assert np.abs(a - b).max() / (np.abs(b).max() + 1e-9) < 2e-1


def test_ring_rejects_unknown_wire(seq_topo, rng):
    from deepspeed_tpu.sequence.ring import ring_attention

    q, k, v = _qkv(rng)
    with pytest.raises(ValueError, match="wire dtype"):
        jax.jit(lambda a, b, c: ring_attention(
            a, b, c, seq_topo, wire_dtype="int3"))(q, k, v)


# ----------------------------------------------------------------------
# Census: the quantized wire is statically visible and ≥3× smaller
# ----------------------------------------------------------------------
def test_ring_quant_census_byte_reduction(seq_topo, rng):
    """analysis.audit on the jitted ring fwd+bwd: the quantized rotation
    moves s8 payloads (the declared fused wire), the u32 word-packing is
    gone, and total collective-permute wire bytes shrink ≥3× vs the
    fp32 wire."""
    from deepspeed_tpu.analysis.auditor import audit
    from deepspeed_tpu.sequence.ring import ring_attention

    q, k, v = _qkv(rng)

    def permute_bytes(wire):
        def fwd_bwd(q, k, v):
            def loss(q, k, v):
                return ring_attention(q, k, v, seq_topo,
                                      wire_dtype=wire).astype(
                                          jnp.float32).sum()
            return jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)

        rep = audit(jax.jit(fwd_bwd), q, k, v, label=f"ring_{wire}")
        rows = [c for c in rep.census if c.kind == "collective-permute"]
        return rows, sum(c.wire_bytes for c in rows)

    rows_f, bytes_f = permute_bytes("fp32")
    rows_q, bytes_q = permute_bytes("int8")
    dtypes_q = {d for c in rows_q for d in c.dtype.split("+")}
    assert "s8" in dtypes_q, dtypes_q
    assert "u32" not in dtypes_q, dtypes_q
    assert bytes_f / bytes_q >= 3.0, (bytes_f, bytes_q)


def test_fused_collective_rollup_in_census_summary():
    """collective_census_engine attaches the fused_collective rollup so
    pinned static_census evidence distinguishes fused from scheduled
    hops (here: a quantized-ring engine declares ring_rotation)."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.analysis.auditor import collective_census_engine
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.parallel import topology as topo_mod

    model = get_model_config("llama-tiny", max_seq_len=64, seq_impl="ring",
                             attn_impl="xla")
    engine, _, _, _ = ds.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": 2},
        "mesh": {"seq": 4},
        "comm_quantization": {"enabled": True, "ring_rotation": "int8"},
        "steps_per_print": 10_000,
    })
    try:
        summary = collective_census_engine(engine)
        fused = summary["fused_collective"]
        assert "ring_rotation" in fused
        assert fused["ring_rotation"]["wire"] == "int8"
        assert fused["ring_rotation"]["present"] is True
    finally:
        engine.destroy()
        topo_mod._GLOBAL_TOPOLOGY = None


# ----------------------------------------------------------------------
# _rotate_together word packing: arbitrary (odd) lengths
# ----------------------------------------------------------------------
@pytest.mark.parametrize("shape,dtype", [
    ((3, 17), jnp.bfloat16),       # odd element count, 2-byte dtype
    ((2, 5, 7), jnp.bfloat16),     # odd again, higher rank
    ((5, 3), jnp.int8),            # 1-byte dtype, non-multiple of 4
    ((4, 8), jnp.float32),         # word-aligned control
])
def test_rotate_together_odd_shapes(seq_topo, rng, shape, dtype):
    """The packed single-permute rotation pads sub-word tails instead of
    relying on callers to keep shapes pair-aligned (regression: an odd
    head_dim used to silently fall back to per-buffer permutes)."""
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.sequence.ring import _rotate_together
    from deepspeed_tpu.utils.jax_compat import shard_map

    sp = seq_topo.sp_size
    vals = rng.standard_normal((sp,) + shape) * 10
    odd = jnp.asarray(vals, dtype)
    extra = jnp.asarray(rng.standard_normal((sp, 4, 8)), jnp.float32)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def body(a, b):
        ra, rb = _rotate_together(perm, a, b)
        return ra, rb

    f = shard_map(body, mesh=seq_topo.mesh,
                  in_specs=(P("seq"), P("seq")),
                  out_specs=(P("seq"), P("seq")),
                  axis_names={"seq"}, check_vma=False)
    ra, rb = jax.jit(f)(odd, extra)
    # shard i receives shard i-1's buffer, byte-exact
    assert np.array_equal(np.asarray(ra), np.asarray(jnp.roll(odd, 1, 0)))
    assert np.array_equal(np.asarray(rb),
                          np.asarray(jnp.roll(extra, 1, 0)))


def test_ring_odd_head_dim(seq_topo, rng):
    """End-to-end ring attention with an odd head_dim (the shapes the
    packing fix unlocks) matches the full-attention reference."""
    from deepspeed_tpu.sequence.ring import (_block_attend_single,
                                             ring_attention)

    b, s, nh, d = 2, 32, 2, 17
    q = jnp.asarray(rng.standard_normal((b, s, nh, d)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, s, nh, d)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, s, nh, d)), jnp.bfloat16)
    out = jax.jit(lambda a, b_, c: ring_attention(a, b_, c, seq_topo))(
        q, k, v)
    ref = _block_attend_single(q, k, v, d ** -0.5, True, None)
    assert np.abs(np.asarray(out, np.float32)
                  - np.asarray(ref, np.float32)).max() < 2e-1


# ----------------------------------------------------------------------
# Fused gather-matmul
# ----------------------------------------------------------------------
@pytest.mark.parametrize("m,k,n", [(64, 64, 256), (130, 96, 72),
                                   (8, 300, 128)])
def test_pallas_matmul_parity(m, k, n, rng):
    import deepspeed_tpu.ops.pallas.gather_matmul as gm

    x = jnp.asarray(rng.standard_normal((m, k)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((k, n)), jnp.float32)
    old = gm.INTERPRET
    try:
        gm.INTERPRET = True
        got = gm.pallas_matmul(x, w)
        # grads flow through the hand-written VJP
        g = jax.grad(lambda a, b: gm.pallas_matmul(a, b).sum(),
                     argnums=(0, 1))(x, w)
    finally:
        gm.INTERPRET = old
    ref = x @ w
    assert np.abs(np.asarray(got) - np.asarray(ref)).max() < 1e-4
    gx_ref, gw_ref = jax.grad(lambda a, b: (a @ b).sum(),
                              argnums=(0, 1))(x, w)
    assert np.abs(np.asarray(g[0]) - np.asarray(gx_ref)).max() < 1e-4
    assert np.abs(np.asarray(g[1]) - np.asarray(gw_ref)).max() < 1e-4


def _train_losses(model_name, config, steps=2, rows=16, seq=64, seed=0):
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.parallel import topology as topo_mod

    model = get_model_config(model_name, max_seq_len=seq)
    engine, _, _, _ = ds.initialize(model=model, config=config)
    try:
        rng = np.random.default_rng(seed)
        ids = rng.integers(0, model.vocab_size, size=(rows, seq + 1),
                           dtype=np.int32)
        batch = {"input_ids": ids[:, :-1],
                 "labels": ids[:, 1:].astype(np.int32)}
        losses = [float(engine.train_batch(batch)) for _ in range(steps)]
        return engine, losses
    finally:
        engine.destroy()
        topo_mod._GLOBAL_TOPOLOGY = None


def _z3_config(**ss):
    cfg = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": 3, "param_persistence_threshold": 0},
        "gradient_clipping": 1.0,
        "mesh": {"data": 8},
        "steps_per_print": 10_000,
    }
    if ss:
        cfg["step_schedule"] = ss
    return cfg


def test_fused_gather_matmul_engine_parity():
    """stage-3 engine with the fused gather-matmul MLP trains to the
    same losses as the GSPMD-scheduled path (gpt2's biased gelu MLP —
    bi rides the fused region)."""
    _, base = _train_losses("gpt2-tiny", _z3_config())
    eng, fused = _train_losses("gpt2-tiny",
                               _z3_config(fused_gather_matmul=True))
    assert eng.model_config.fused_gather_matmul
    assert eng.model_config.fused_gather_axes == ("data",)
    for a, b in zip(base, fused):
        assert abs(a - b) < 1e-5, (base, fused)


def test_fused_gather_matmul_swiglu_interpreter_parity():
    """swiglu (llama) MLP through the interpreted Pallas matmul kernel —
    the real fused path, forward and backward."""
    import deepspeed_tpu.ops.pallas.gather_matmul as gm

    _, base = _train_losses("llama-tiny", _z3_config())
    old = gm.INTERPRET
    try:
        gm.INTERPRET = True
        eng, fused = _train_losses("llama-tiny",
                                   _z3_config(fused_gather_matmul=True))
    finally:
        gm.INTERPRET = old
    assert eng.model_config.fused_gather_matmul
    for a, b in zip(base, fused):
        assert abs(a - b) < 1e-5, (base, fused)


def test_fused_gather_matmul_fallback_on_indivisible_bias():
    """An MLP bias whose dim cannot shard over the fsdp world (here
    intermediate_size=100 on 8 devices) must warn-fallback — the fused
    region's bias in_spec would otherwise crash at trace time."""
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.parallel import topology as topo_mod

    import deepspeed_tpu as ds

    model = get_model_config("gpt2-tiny", max_seq_len=64,
                             intermediate_size=100)
    engine, _, _, _ = ds.initialize(model=model,
                                    config=_z3_config(
                                        fused_gather_matmul=True))
    try:
        assert not engine.model_config.fused_gather_matmul
        rng = np.random.default_rng(0)
        ids = rng.integers(0, model.vocab_size, size=(16, 65),
                           dtype=np.int32)
        loss = float(engine.train_batch(
            {"input_ids": ids[:, :-1],
             "labels": ids[:, 1:].astype(np.int32)}))
        assert np.isfinite(loss)
    finally:
        engine.destroy()
        topo_mod._GLOBAL_TOPOLOGY = None


def test_fused_gather_matmul_fallback_when_persistent():
    """The default param-persistence threshold keeps tiny MLP weights
    gathered — the gate must warn-fallback, not shard_map over
    unsharded weights."""
    cfg = _z3_config(fused_gather_matmul=True)
    cfg["zero_optimization"] = {"stage": 3}   # default persistence
    eng, losses = _train_losses("gpt2-tiny", cfg)
    assert not eng.model_config.fused_gather_matmul
    assert all(np.isfinite(losses))


# ----------------------------------------------------------------------
# Fused reduce-scatter epilogue
# ----------------------------------------------------------------------
def _z1_config(**ss):
    cfg = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": 1},
        "gradient_clipping": 1.0,
        "mesh": {"data": 8},
        "steps_per_print": 10_000,
        "step_schedule": ss,
    }
    return cfg


def test_fused_reduce_scatter_parity():
    eng0, base = _train_losses(
        "gpt2-tiny", _z1_config(weight_update="decomposed"), steps=3)
    eng1, fused = _train_losses(
        "gpt2-tiny", _z1_config(weight_update="decomposed",
                                fused_reduce_scatter=True), steps=3)
    assert not getattr(eng0, "_fused_rs", False)
    assert eng1._fused_rs
    for a, b in zip(base, fused):
        assert abs(a - b) < 1e-5, (base, fused)


def test_fused_reduce_scatter_fallback_without_decomposed():
    eng, losses = _train_losses(
        "gpt2-tiny", _z1_config(fused_reduce_scatter=True), steps=2)
    assert not eng._fused_rs
    assert all(np.isfinite(losses))


# ----------------------------------------------------------------------
# Scheduler decision arm + config compatibility
# ----------------------------------------------------------------------
def _report(overlap=0.1, dom="all-gather.1"):
    return {"step": 5, "devices": {"d0": {"collective_ms": 4.0}},
            "overlap_fraction": overlap,
            "dominant_collective": {"name": dom}}


def test_scheduler_fused_gather_arm_fires_after_prefetch_exhausted():
    from deepspeed_tpu.autotuning.overlap_scheduler import decide

    ctx = {"zero_stage": 3, "dp": 8, "sp": 1, "seq_impl": "",
           "base": {"gather_prefetch_depth": 2,
                    "param_persistence_threshold": 0,
                    "prefetch_bucket_size": 50_000_000,
                    "ring_interleave": 1, "weight_update": "fused",
                    "fused_gather_matmul": False}}
    updates, decisions = decide(_report(), ctx)
    names = {d.decision for d in decisions}
    assert "fused_gather_matmul" in names
    assert updates["fused_gather_matmul"] is True
    # the scheduled arm keeps deepening in the same pass
    assert "zero3_prefetch" in names


def test_scheduler_fused_gather_arm_waits_for_depth():
    """First low-overlap probe at depth 1 only deepens prefetch — the
    fused arm waits until the scheduled arm is exhausted."""
    from deepspeed_tpu.autotuning.overlap_scheduler import decide

    ctx = {"zero_stage": 3, "dp": 8, "sp": 1, "seq_impl": "",
           "base": {"gather_prefetch_depth": 1,
                    "param_persistence_threshold": 0,
                    "prefetch_bucket_size": 50_000_000,
                    "ring_interleave": 1, "weight_update": "fused",
                    "fused_gather_matmul": False}}
    updates, decisions = decide(_report(), ctx)
    names = {d.decision for d in decisions}
    assert "fused_gather_matmul" not in names
    assert "zero3_prefetch" in names


def test_scheduler_fused_gather_arm_not_on_reduce_dominated():
    from deepspeed_tpu.autotuning.overlap_scheduler import decide

    ctx = {"zero_stage": 3, "dp": 8, "sp": 1, "seq_impl": "",
           "base": {"gather_prefetch_depth": 2,
                    "param_persistence_threshold": 0,
                    "prefetch_bucket_size": 50_000_000,
                    "ring_interleave": 1, "weight_update": "fused",
                    "fused_gather_matmul": False}}
    _, decisions = decide(_report(dom="all-reduce.3"), ctx)
    assert "fused_gather_matmul" not in {d.decision for d in decisions}


def test_pre_existing_pinned_configs_still_load():
    """A step_schedule block pinned BEFORE the fused knobs existed (no
    fused_gather_matmul / fused_reduce_scatter keys, pre-census decision
    records) must keep loading; unknown decisions stay rejected."""
    from deepspeed_tpu.autotuning.overlap_scheduler import ScheduleDecision
    from deepspeed_tpu.runtime.config import (DeepSpeedConfigError,
                                              StepScheduleConfig)

    old_pinned = {
        "mode": "pinned", "probe_steps": 3, "overlap_threshold": 0.5,
        "gather_prefetch_depth": 2,
        "decisions": [{"decision": "zero3_prefetch",
                       "knobs": {"gather_prefetch_depth": 2},
                       "evidence": {"dominant_collective": "all-gather",
                                    "exposed_comm_ms": 3.0,
                                    "overlap_fraction": 0.2,
                                    "overlap_source": "spans",
                                    "probe_step": 4}}],
    }
    ss = StepScheduleConfig(**old_pinned)
    assert ss.fused_gather_matmul is False
    assert ss.fused_reduce_scatter is False
    d = ScheduleDecision.from_dict(old_pinned["decisions"][0])
    assert d.evidence["static_census"] is None
    # new fused records round-trip too
    d2 = ScheduleDecision.from_dict(
        {"decision": "fused_gather_matmul",
         "knobs": {"fused_gather_matmul": True},
         "evidence": dict(d.evidence)})
    assert d2.decision == "fused_gather_matmul"
    with pytest.raises(ValueError):
        ScheduleDecision.from_dict(
            {"decision": "warp_drive", "knobs": {},
             "evidence": dict(d.evidence)})
    with pytest.raises(DeepSpeedConfigError):
        StepScheduleConfig(decisions=[{"decision": "warp_drive",
                                       "knobs": {}, "evidence": {}}])
