"""Per-op autocast policy (ref runtime/torch_autocast.py): the
"torch_autocast" config block's fp32_ops / lower_precision_safe_modules
reach the model and change which ops run in the low dtype."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models import get_model_config
from deepspeed_tpu.models import transformer as tf
from deepspeed_tpu.runtime.config import DeepSpeedConfig, DeepSpeedConfigError


def _batch(cfg, b=2, s=32, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, cfg.vocab_size, size=(b, s + 1), dtype=np.int32)
    return {"input_ids": jnp.asarray(ids[:, :-1]),
            "labels": jnp.asarray(ids[:, 1:])}


def _loss(cfg, batch):
    params = tf.init_params(cfg, jax.random.PRNGKey(0))
    return float(np.asarray(tf.loss_fn(params, batch, cfg)))


def test_default_policy_is_current_behavior():
    cfg = get_model_config("gpt2-tiny")
    assert cfg.fp32_ops is None
    for op in ("layernorm", "softmax", "rope", "router", "loss"):
        assert tf.op_fp32(cfg, op)
    cfg2 = cfg.replace(fp32_ops=("layernorm",))
    assert tf.op_fp32(cfg2, "layernorm") and not tf.op_fp32(cfg2, "softmax")


def test_aggressive_policy_trains_and_diverges_in_low_precision():
    """Dropping every fp32 island still yields a finite loss, and the
    result differs from the safe policy (proof the gates are live)."""
    base = get_model_config("gpt2-tiny", attn_impl="xla")
    batch = _batch(base)
    safe = _loss(base, batch)
    aggressive = _loss(base.replace(fp32_ops=()), batch)
    assert np.isfinite(aggressive)
    assert abs(safe - aggressive) > 1e-7  # bf16 softmax/norm shifts numerics


def _matmul_dtypes(fn, *args):
    jaxpr = jax.make_jaxpr(fn)(*args)
    dts = set()
    for eqn in jaxpr.jaxpr.eqns:
        if eqn.primitive.name == "dot_general":
            dts.add(str(eqn.invars[0].aval.dtype))
    return dts


def test_safe_modules_promote_unlisted_to_fp32():
    """With an empty safe list the mlp matmuls run on fp32 operands; with
    "mlp" listed (or no list) they stay in the compute dtype.  The block
    restores the residual-stream dtype at its boundary either way."""
    cfg = get_model_config("gpt2-tiny", attn_impl="xla")
    params = tf.init_params(cfg, jax.random.PRNGKey(0))
    mlp_p = jax.tree.map(lambda x: x, params["layers"]["mlp"])
    mlp_p = {k: v[0] for k, v in mlp_p.items() if v is not None}
    x = jnp.ones((2, 8, cfg.hidden_size), jnp.bfloat16)

    promoted = cfg.replace(autocast_safe_modules=())
    dts = _matmul_dtypes(lambda t: tf._mlp_block(t, mlp_p, promoted), x)
    assert dts == {"float32"}
    assert tf._mlp_block(x, mlp_p, promoted).dtype == jnp.bfloat16

    listed = cfg.replace(autocast_safe_modules=("mlp",))
    dts = _matmul_dtypes(lambda t: tf._mlp_block(t, mlp_p, listed), x)
    assert dts == {"bfloat16"}


def test_lm_head_promotion_honored():
    """Omitting lm_head from the safe list promotes the logits matmul to
    fp32 (the documented 'unlisted modules are promoted' contract); listing
    it keeps the low dtype.  Covers both tied and untied heads."""
    for tie in (True, False):
        cfg = get_model_config("gpt2-tiny", attn_impl="xla").replace(
            dtype=jnp.bfloat16, tie_embeddings=tie)
        params = tf.init_params(cfg, jax.random.PRNGKey(0))
        ids = jnp.zeros((1, 8), jnp.int32)

        promoted = cfg.replace(
            autocast_safe_modules=("attn", "mlp", "embed"))
        dts = _matmul_dtypes(
            lambda p: tf.forward(p, ids, promoted), params)
        assert "float32" in dts, (tie, dts)

        listed = cfg.replace(
            autocast_safe_modules=("attn", "mlp", "embed", "lm_head"))
        dts = _matmul_dtypes(lambda p: tf.forward(p, ids, listed), params)
        assert dts == {"bfloat16"}, (tie, dts)


def test_config_block_reaches_model():
    import deepspeed_tpu as ds
    from deepspeed_tpu.parallel import topology

    model = get_model_config("gpt2-tiny")
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "torch_autocast": {"enabled": True, "dtype": "bfloat16",
                           "fp32_ops": ["layernorm", "loss"],
                           "lower_precision_safe_modules": ["attn", "mlp"]},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    mc = engine.model_config
    assert mc.dtype == jnp.bfloat16
    assert mc.fp32_ops == ("layernorm", "loss")
    assert mc.autocast_safe_modules == ("attn", "mlp")
    topology._GLOBAL_TOPOLOGY = None


def test_autocast_conflicts_with_explicit_bf16():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                         "bf16": {"enabled": True},
                         "torch_autocast": {"enabled": True}})
