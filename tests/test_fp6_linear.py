"""FP6 (e3m2) packed-weight linear: real 6-bit storage + packed-read GEMM
(deepspeed_tpu/ops/pallas/fp6_linear.py).  Ref: the reference's FP6-LLM
weight-only path, inference/v2/kernels/core_ops/cuda_linear/
cuda_linear.py:167 (packed storage + split-K GEMM)."""

import importlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

f6 = importlib.import_module("deepspeed_tpu.ops.pallas.fp6_linear")


@pytest.fixture(autouse=True)
def _interpret_mode():
    old = f6.INTERPRET
    f6.INTERPRET = True
    yield
    f6.INTERPRET = old


def test_decode_table_is_e3m2():
    t = f6.DECODE_TABLE
    assert t.shape == (64,)
    assert t[0] == 0.0 and t.max() == 28.0 and t.min() == -28.0
    # subnormal step
    assert np.isclose(np.abs(t[t != 0]).min(), 2.0 ** -4)
    # all magnitudes distinct per sign half
    assert len(np.unique(t)) == 63  # +0 and -0 collapse


def test_quantize_roundtrip_nearest():
    rng = np.random.default_rng(0)
    w = rng.standard_normal((64, 256)).astype(np.float32)
    packed, scale = f6.fp6_quantize(w)
    assert packed.dtype == jnp.uint8 and packed.shape == (3, 16, 256)
    deq = np.asarray(f6.fp6_dequantize(packed, scale, jnp.float32))
    # every dequantized value is the NEAREST representable: error bounded
    # by half the local grid step (max normal step at |x|~14 is 2)
    scaled_err = np.abs(deq - w) / np.asarray(scale)[None, :]
    step = np.maximum(2.0 ** np.floor(np.log2(
        np.maximum(np.abs(w / np.asarray(scale)[None, :]), 2 ** -4))) * 0.25,
        2.0 ** -4)
    assert (scaled_err <= step / 2 + 1e-6).all()
    # storage really is 6 bits + one fp32 scale per column
    assert packed.nbytes == w.size * 3 // 4


def test_packed_matmul_matches_dequant():
    """The Pallas packed-read GEMM equals dequantize-then-dot exactly."""
    rng = np.random.default_rng(1)
    m, k, n = 16, 64, 256
    w = rng.standard_normal((k, n)).astype(np.float32) * 0.1
    x = jnp.asarray(rng.standard_normal((m, k)), jnp.float32)
    packed, scale = f6.fp6_quantize(w)
    ref = x @ f6.fp6_dequantize(packed, scale, jnp.float32)
    out = f6.fp6_matmul.__wrapped__(x, packed, scale, block_m=16,
                                    block_n=128, block_k4=8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)
    # K-grid accumulation: a single-step K grid (bk4=16 covers K/4) must
    # equal the two-step bk4=8 run above
    out2 = f6.fp6_matmul.__wrapped__(x, packed, scale, block_m=16,
                                     block_n=128, block_k4=16)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(out),
                               rtol=1e-5, atol=1e-5)


def test_quantized_parameter_fp6():
    """linear.QuantizedParameter q_bits=6: packed bytes, matmul() path,
    and the memory claim (0.75 B/value + fp32/column)."""
    from deepspeed_tpu.linear import QuantizedParameter

    rng = np.random.default_rng(2)
    w = rng.standard_normal((128, 256)).astype(np.float32)
    qp = QuantizedParameter(w, q_bits=6)
    assert qp.nbytes == w.size * 3 // 4 + 256 * 4
    assert qp.nbytes < w.astype(np.float16).nbytes  # beats fp16 storage
    deq = np.asarray(qp.dequantized())
    assert np.abs(deq - w).max() < np.abs(w).max() * 0.2
    x = jnp.asarray(rng.standard_normal((8, 128)), jnp.float32)
    out = np.asarray(qp.matmul(x))
    ref = np.asarray(x) @ deq
    np.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-2)


def test_fp6_rejects_bad_shapes():
    from deepspeed_tpu.linear import QuantizedParameter

    with pytest.raises(ValueError, match="2-D"):
        QuantizedParameter(np.zeros((4, 4, 4), np.float32), q_bits=6)
    with pytest.raises(ValueError, match="divisible by 4"):
        f6.fp6_quantize(np.zeros((6, 8), np.float32))


def test_lora_over_fp6_base_grads_flow():
    """OptimizedLinear with an FP6 base: forward routes through the
    packed matmul, LoRA A/B get gradients, and dx flows to upstream
    layers via the custom VJP (dequantized backward)."""
    from deepspeed_tpu.linear import (LoRAConfig, OptimizedLinear,
                                      QuantizationConfig)

    rng = np.random.default_rng(3)
    w = rng.standard_normal((64, 256)).astype(np.float32) * 0.1
    lin = OptimizedLinear(jnp.asarray(w),
                          lora_config=LoRAConfig(lora_r=8),
                          quantization_config=QuantizationConfig(q_bits=6),
                          key=jax.random.PRNGKey(0))
    assert lin.base.q_bits == 6
    x = jnp.asarray(rng.standard_normal((4, 64)), jnp.float32)

    def loss(x, a, b):
        return jnp.sum(lin(x, lora_A=a, lora_B=b) ** 2)

    # a nonzero B (the zero LoRA init makes dL/dA identically zero)
    b_rand = jnp.asarray(rng.standard_normal(lin.lora_B.shape) * 0.1,
                         jnp.float32)
    gx, ga, gb = jax.grad(loss, argnums=(0, 1, 2))(x, lin.lora_A, b_rand)
    assert float(jnp.abs(gx).sum()) > 0      # dx flows upstream
    assert float(jnp.abs(ga).sum()) > 0      # adapters train
    assert float(jnp.abs(gb).sum()) > 0
    # dx equals the dequantized-weight product's dx
    deq = lin.base.dequantized()

    def loss_ref(x, a, b):
        y = x @ deq + (16.0 / 8) * ((x @ a) @ b)
        return jnp.sum(y ** 2)

    gx_ref = jax.grad(loss_ref)(x, lin.lora_A, b_rand)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                               rtol=1e-4, atol=1e-4)


def test_fp6_matmul_batched_activations():
    """[B, S, H] activations flatten through the packed path and restore
    their leading shape (transformer-shaped callers)."""
    rng = np.random.default_rng(4)
    w = rng.standard_normal((64, 256)).astype(np.float32) * 0.1
    packed, scale = f6.fp6_quantize(w)
    x = jnp.asarray(rng.standard_normal((2, 5, 64)), jnp.float32)
    out = f6.fp6_matmul.__wrapped__(x, packed, scale)
    assert out.shape == (2, 5, 256)
    ref = x.reshape(-1, 64) @ f6.fp6_dequantize(packed, scale, jnp.float32)
    np.testing.assert_allclose(np.asarray(out).reshape(-1, 256),
                               np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_fp6_matmul_awkward_m_pads_not_falls_back(monkeypatch):
    """Prime / 2*prime M pads to the sublane and KEEPS the packed-read
    kernel (serving is weight-bandwidth-bound; dequant fallback would
    re-read the full bf16 weight)."""
    calls = {}
    orig = f6.pl.pallas_call

    def spy(*a, **kw):
        calls["kernel"] = True
        return orig(*a, **kw)

    monkeypatch.setattr(f6.pl, "pallas_call", spy)
    rng = np.random.default_rng(5)
    w = rng.standard_normal((64, 256)).astype(np.float32) * 0.1
    packed, scale = f6.fp6_quantize(w)
    for m in (7, 514):  # prime; 2*257
        calls.clear()
        x = jnp.asarray(rng.standard_normal((m, 64)), jnp.float32)
        out = f6.fp6_matmul.__wrapped__(x, packed, scale)
        assert calls.get("kernel"), f"M={m} fell back to dequant"
        assert out.shape == (m, 256)
        ref = x @ f6.fp6_dequantize(packed, scale, jnp.float32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
