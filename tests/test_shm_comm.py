"""Shared-memory host collectives across real processes (ref
csrc/cpu/comm/shm.cpp coverage via CCLBackend tests)."""

import multiprocessing as mp
import os

import numpy as np
import pytest

from deepspeed_tpu.comm.shm import ShmComm, shm_available


def _worker(rank, world, name, q):
    # workers must not initialize jax/TPU: keep imports cheap
    try:
        comm = ShmComm(name, rank, world, max_elems=1024)
        x = np.full(16, float(rank + 1), np.float32)
        red = comm.allreduce(x.copy())
        gat = comm.allgather(np.array([float(rank)], np.float32))
        b = np.array([42.0 if rank == 0 else 0.0], np.float32)
        bc = comm.broadcast(b, root=0)
        comm.barrier()
        comm.close(unlink=(rank == 0))
        q.put((rank, red[0], gat.ravel().tolist(), bc[0]))
    except Exception as e:  # surface worker failures to the test
        q.put((rank, "ERR", str(e), ""))


def test_native_builds():
    assert shm_available()


@pytest.mark.parametrize("world", [2, 4])
def test_shm_collectives_across_processes(world):
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    name = f"test{os.getpid()}_{world}"
    procs = [ctx.Process(target=_worker, args=(r, world, name, q))
             for r in range(world)]
    for p in procs:
        p.start()
    results = [q.get(timeout=60) for _ in range(world)]
    for p in procs:
        p.join(timeout=30)
        assert p.exitcode == 0
    expected_sum = sum(range(1, world + 1))
    for rank, red, gat, bc in results:
        assert red != "ERR", gat
        assert red == expected_sum  # sum of rank+1
        assert sorted(gat) == [float(r) for r in range(world)]
        assert bc == 42.0


def test_payload_too_large():
    comm = ShmComm(f"big{os.getpid()}", 0, 1, max_elems=8)
    comm.allreduce(np.ones(8, np.float32))  # fits
    with pytest.raises(ValueError):
        comm.allreduce(np.ones(9, np.float32))
    comm.close(unlink=True)
