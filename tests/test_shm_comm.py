"""Shared-memory host collectives across real processes (ref
csrc/cpu/comm/shm.cpp coverage via CCLBackend tests)."""

import multiprocessing as mp
import os

import numpy as np
import pytest

from deepspeed_tpu.comm.shm import ShmComm, shm_available


def _worker(rank, world, name, q):
    # workers must not initialize jax/TPU: keep imports cheap
    try:
        comm = ShmComm(name, rank, world, max_elems=1024)
        x = np.full(16, float(rank + 1), np.float32)
        red = comm.allreduce(x.copy())
        gat = comm.allgather(np.array([float(rank)], np.float32))
        b = np.array([42.0 if rank == 0 else 0.0], np.float32)
        bc = comm.broadcast(b, root=0)
        comm.barrier()
        comm.close(unlink=(rank == 0))
        q.put((rank, red[0], gat.ravel().tolist(), bc[0]))
    except Exception as e:  # surface worker failures to the test
        q.put((rank, "ERR", str(e), ""))


def test_native_builds():
    assert shm_available()


@pytest.mark.parametrize("world", [2, 4])
def test_shm_collectives_across_processes(world):
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    name = f"test{os.getpid()}_{world}"
    procs = [ctx.Process(target=_worker, args=(r, world, name, q))
             for r in range(world)]
    for p in procs:
        p.start()
    results = [q.get(timeout=60) for _ in range(world)]
    for p in procs:
        p.join(timeout=30)
        assert p.exitcode == 0
    expected_sum = sum(range(1, world + 1))
    for rank, red, gat, bc in results:
        assert red != "ERR", gat
        assert red == expected_sum  # sum of rank+1
        assert sorted(gat) == [float(r) for r in range(world)]
        assert bc == 42.0


def _stale_worker(rank, world, name, start_delay, q):
    """Second-run worker: the shm region already holds a crashed previous
    run's header (old nonce, world=1 barrier). Ranks must wait for THIS
    run's nonce instead of racing into the stale barrier."""
    import time

    try:
        time.sleep(start_delay)
        comm = ShmComm(name, rank, world, max_elems=64, nonce=0xBEEF)
        red = comm.allreduce(np.full(4, float(rank + 1), np.float32))
        comm.close(unlink=(rank == 0))
        q.put((rank, float(red[0])))
    except Exception as e:
        q.put((rank, f"ERR {e}"))


def test_stale_region_relaunch():
    """A crashed run leaves an initialized header behind; a relaunch with a
    new nonce must re-initialize instead of racing into the stale barrier
    (advisor finding: stale init_done race)."""
    name = f"stale{os.getpid()}"
    # "previous run": world=1, initializes the region, exits WITHOUT unlink
    prev = ShmComm(name, 0, 1, max_elems=64, nonce=0xDEAD)
    prev.allreduce(np.ones(4, np.float32))
    prev.close(unlink=False)  # simulate crash: region persists, nonce=0xDEAD

    world = 2
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    # non-root starts FIRST: with the old init_done flag it would have run
    # straight into the stale world=1 barrier; with the nonce it waits
    procs = [ctx.Process(target=_stale_worker,
                         args=(r, world, name, 0.0 if r else 0.5, q))
             for r in range(world)]
    for p in procs:
        p.start()
    results = [q.get(timeout=60) for _ in range(world)]
    for p in procs:
        p.join(timeout=30)
        assert p.exitcode == 0
    for rank, red in results:
        assert red == 3.0, results  # 1 + 2


def _bitwise_worker(rank, world, name, q):
    try:
        comm = ShmComm(name, rank, world, max_elems=64)
        # values whose FP sum is order-sensitive: catastrophic cancellation
        vals = np.array([1e8, 1.0, -1e8, 1e-8], np.float32) * (rank + 1)
        red = comm.allreduce(vals.copy())
        comm.close(unlink=(rank == 0))
        q.put((rank, red.tobytes().hex()))
    except Exception as e:
        q.put((rank, f"ERR {e}"))


def test_allreduce_bitwise_identical_across_ranks():
    """All ranks must produce bitwise-identical allreduce results (fixed
    summation order) — the grad-norm-agreement use case (advisor finding:
    per-rank FP order divergence)."""
    world = 4
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    name = f"bw{os.getpid()}"
    procs = [ctx.Process(target=_bitwise_worker, args=(r, world, name, q))
             for r in range(world)]
    for p in procs:
        p.start()
    results = [q.get(timeout=60) for _ in range(world)]
    for p in procs:
        p.join(timeout=30)
        assert p.exitcode == 0
    hexes = {h for _, h in results}
    assert not any(str(h).startswith("ERR") for h in hexes), results
    assert len(hexes) == 1, f"rank results differ bitwise: {results}"


def test_payload_too_large():
    comm = ShmComm(f"big{os.getpid()}", 0, 1, max_elems=8)
    comm.allreduce(np.ones(8, np.float32))  # fits
    with pytest.raises(ValueError):
        comm.allreduce(np.ones(9, np.float32))
    comm.close(unlink=True)
