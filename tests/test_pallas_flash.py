"""Parity tests for the repo-owned Pallas flash attention kernel
(deepspeed_tpu/ops/pallas/flash_mha.py) run through the Pallas interpreter
on the CPU mesh. Ref test model: tests/unit/ops/transformer/inference
attention parity in the reference suite."""

import importlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# the package re-exports the flash_mha *function* under the same name as the
# submodule; import the module itself for INTERPRET toggling
fm = importlib.import_module("deepspeed_tpu.ops.pallas.flash_mha")


@pytest.fixture(autouse=True)
def _interpret_mode():
    old = fm.INTERPRET
    fm.INTERPRET = True
    yield
    fm.INTERPRET = old


def _ref_attn(q, k, v, causal, scale):
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    hq, hkv = q.shape[1], k.shape[1]
    if hq != hkv:
        kf = jnp.repeat(kf, hq // hkv, axis=1)
        vf = jnp.repeat(vf, hq // hkv, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
    if causal:
        S = q.shape[2]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, vf)


CASES = [
    # b, hq, hkv, s, d, causal
    (1, 2, 2, 256, 64, True),     # MHA
    (1, 4, 2, 256, 64, True),     # GQA 2x
    (1, 4, 1, 128, 64, True),     # MQA
    (1, 2, 2, 200, 64, True),     # odd length (pad + mask path)
    (1, 2, 2, 256, 64, False),    # non-causal
]


@pytest.mark.parametrize("b,hq,hkv,s,d,causal", CASES)
def test_forward_parity(b, hq, hkv, s, d, causal):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, hq, s, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, hkv, s, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, hkv, s, d), jnp.bfloat16)
    out = fm.flash_mha(q, k, v, causal)
    ref = _ref_attn(q, k, v, causal, 1.0 / np.sqrt(d))
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref)))
    assert err < 0.05, err


@pytest.mark.parametrize("b,hq,hkv,s,d,causal", [CASES[1], CASES[3]])
def test_grad_parity(b, hq, hkv, s, d, causal):
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (b, hq, s, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, hkv, s, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, hkv, s, d), jnp.bfloat16)
    w = jnp.linspace(0.0, 1.0, d)

    def loss(fn):
        return lambda q, k, v: (fn(q, k, v).astype(jnp.float32) * w).sum()

    scale = 1.0 / np.sqrt(d)
    g1 = jax.grad(loss(lambda q, k, v: fm.flash_mha(q, k, v, causal)),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss(lambda q, k, v: _ref_attn(q, k, v, causal, scale)),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        a32 = a.astype(jnp.float32)
        b32 = b_.astype(jnp.float32)
        rel = float(jnp.linalg.norm((a32 - b32).ravel())
                    / (jnp.linalg.norm(b32.ravel()) + 1e-9))
        assert rel < 0.02, rel


def test_supports_budget():
    assert fm.supports(1024, 64)
    assert fm.supports(8192, 128)
    assert fm.supports(65536, 128)          # KV-blocked long-context path
    assert fm.supports(262144, 128)
    assert not fm.supports(1 << 20, 128)
    assert fm._supports_resident(1024, 64)
    assert fm._supports_resident(2048, 128)
    # past _RESIDENT_MAX_SEQ the blocked kernels are measured faster
    # (r04 crossover study) even though 8192x64 fits the VMEM budget
    assert not fm._supports_resident(8192, 64)
    assert not fm._supports_resident(16384, 128)


def test_resident_bwd_vmem_budget():
    """The grouped resident dkv kernel holds group× the q-side in VMEM;
    Llama-3 geometry (group=4, S=1024, D=128) measured 17.55M against the
    16M scoped-vmem limit on a real v5e (r04), so the backward must route
    to the KV-blocked path there while the r02-tuned MHA d=64 config
    keeps the resident fast path."""
    assert not fm._resident_bwd_fits(1024, 128, 4, fm._choose_bq(1024))
    assert fm._resident_bwd_fits(1024, 64, 1, fm._choose_bq(1024))


def test_gqa_d128_grad_parity_blocked_fallback():
    """Grad parity through the footprint-driven blocked-backward fallback
    (forward stays resident, backward goes KV-blocked): the exact
    llama3-8b head geometry that VMEM-OOMed on hardware in r04."""
    b, hq, hkv, s, d = 1, 8, 2, 1024, 128
    assert fm._supports_resident(s, d)  # fwd resident...
    assert not fm._resident_bwd_fits(   # ...bwd must fall back
        s, d, hq // hkv, fm._choose_bq(s))
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(ks[0], (b, hq, s, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, hkv, s, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, hkv, s, d), jnp.bfloat16)
    w = jnp.linspace(0.0, 1.0, d)

    def loss(fn):
        return lambda q, k, v: (fn(q, k, v).astype(jnp.float32) * w).sum()

    scale = 1.0 / np.sqrt(d)
    g1 = jax.grad(loss(lambda q, k, v: fm.flash_mha(q, k, v, True)),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss(lambda q, k, v: _ref_attn(q, k, v, True, scale)),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        a32, b32 = a.astype(jnp.float32), b_.astype(jnp.float32)
        rel = float(jnp.linalg.norm((a32 - b32).ravel())
                    / (jnp.linalg.norm(b32.ravel()) + 1e-9))
        assert rel < 0.02, rel


BLOCKED_CASES = [
    # b, hq, hkv, s, d, causal
    (1, 4, 2, 1024, 64, True),    # GQA, 2x2 blocks
    (1, 2, 2, 1280, 64, True),    # pad path (s_pad = 1536, ragged tail)
    (1, 4, 1, 1024, 64, False),   # MQA, non-causal
]


@pytest.fixture
def _force_blocked(monkeypatch):
    monkeypatch.setattr(fm, "_supports_resident", lambda s, d: False)


@pytest.mark.parametrize("b,hq,hkv,s,d,causal", BLOCKED_CASES)
def test_blocked_forward_parity(b, hq, hkv, s, d, causal, _force_blocked):
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (b, hq, s, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, hkv, s, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, hkv, s, d), jnp.bfloat16)
    out = fm.flash_mha(q, k, v, causal)
    ref = _ref_attn(q, k, v, causal, 1.0 / np.sqrt(d))
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref)))
    assert err < 0.05, err


@pytest.mark.parametrize("b,hq,hkv,s,d,causal",
                         [BLOCKED_CASES[0], BLOCKED_CASES[1]])
def test_blocked_grad_parity(b, hq, hkv, s, d, causal, _force_blocked):
    ks = jax.random.split(jax.random.PRNGKey(4), 3)
    q = jax.random.normal(ks[0], (b, hq, s, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, hkv, s, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, hkv, s, d), jnp.bfloat16)
    w = jnp.linspace(0.0, 1.0, d)

    def loss(fn):
        return lambda q, k, v: (fn(q, k, v).astype(jnp.float32) * w).sum()

    scale = 1.0 / np.sqrt(d)
    g1 = jax.grad(loss(lambda q, k, v: fm.flash_mha(q, k, v, causal)),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss(lambda q, k, v: _ref_attn(q, k, v, causal, scale)),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        a32 = a.astype(jnp.float32)
        b32 = b_.astype(jnp.float32)
        rel = float(jnp.linalg.norm((a32 - b32).ravel())
                    / (jnp.linalg.norm(b32.ravel()) + 1e-9))
        assert rel < 0.02, rel


def test_long_context_16k_forward():
    """S=16K naturally routes to the KV-blocked path (resident budget is
    8K at d=128 / 128·s_pad score cap); oracle is the independently-written
    FPDT chunked online-softmax attention (O(chunk) memory — a full [S,S]
    reference would need multi-GB scores on the CPU runner)."""
    from deepspeed_tpu.sequence.fpdt import chunked_attention

    s, d = 16384, 64
    assert not fm._supports_resident(s, d)
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(ks[0], (1, 2, s, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (1, 1, s, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (1, 1, s, d), jnp.bfloat16)
    out = fm.flash_mha(q, k, v, True)
    ref = chunked_attention(q.swapaxes(1, 2), k.swapaxes(1, 2),
                            v.swapaxes(1, 2), chunk_size=2048,
                            causal=True).swapaxes(1, 2)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    assert err < 0.05, err


def test_any_length_no_fallback(monkeypatch):
    """flash_attention dispatches s % 128 != 0 through the repo kernel
    (pad+mask), not the O(S²) XLA path — verified by pretending to be on
    TPU (interpret mode) and asserting the repo kernel actually ran."""
    from deepspeed_tpu.ops import flash_attention as fa

    monkeypatch.setattr(fa, "_on_tpu", lambda: True)
    calls = {"n": 0}
    real = fm._fwd

    def counting_fwd(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(fm, "_fwd", counting_fwd)

    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(ks[0], (1, 200, 4, 64), jnp.bfloat16)
    k = jax.random.normal(ks[1], (1, 200, 2, 64), jnp.bfloat16)
    v = jax.random.normal(ks[2], (1, 200, 2, 64), jnp.bfloat16)
    out = fa.flash_attention.__wrapped__(q, k, v, causal=True, sm_scale=None,
                                         impl="auto")
    assert calls["n"] == 1, "repo kernel was not used for s % 128 != 0"
    ref = _ref_attn(q.swapaxes(1, 2), k.swapaxes(1, 2), v.swapaxes(1, 2),
                    True, 1.0 / np.sqrt(64)).swapaxes(1, 2)
    assert float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref))) < 0.05


def _ref_attn_window(q, k, v, causal, scale, window):
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    hq, hkv = q.shape[1], k.shape[1]
    if hq != hkv:
        kf = jnp.repeat(kf, hq // hkv, axis=1)
        vf = jnp.repeat(vf, hq // hkv, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
    S = q.shape[2]
    rows = jax.lax.broadcasted_iota(jnp.int32, (S, S), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (S, S), 1)
    mask = rows - cols < window
    if causal:
        mask &= cols <= rows
    s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, vf)


@pytest.mark.parametrize("b,hq,hkv,s,d,window",
                         [(1, 2, 2, 256, 64, 96),   # window < S
                          (1, 4, 2, 256, 64, 128),  # GQA
                          (1, 2, 2, 200, 64, 64)])  # ragged tail
def test_sliding_window_forward_parity(b, hq, hkv, s, d, window):
    """Mistral sliding-window masking in the resident kernel (ref
    transformer.py _attention_scores window semantics: q - k < window)."""
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(ks[0], (b, hq, s, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, hkv, s, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, hkv, s, d), jnp.float32)
    out = fm.flash_mha(q, k, v, True, None, window)
    ref = _ref_attn_window(q, k, v, True, 1.0 / np.sqrt(d), window)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref)))
    assert err < 5e-5, err


def test_sliding_window_blocked_grads(_force_blocked):
    """Window masking + grid skip in the KV-blocked path, fwd and bwd
    (grid-level skip must not drop in-window tiles)."""
    ks = jax.random.split(jax.random.PRNGKey(8), 3)
    b, hq, hkv, s, d, window = 1, 2, 1, 1536, 64, 700
    q = jax.random.normal(ks[0], (b, hq, s, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, hkv, s, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, hkv, s, d), jnp.float32)
    w = jnp.linspace(0.0, 1.0, d)

    def loss(fn):
        return lambda q, k, v: (fn(q, k, v).astype(jnp.float32) * w).sum()

    scale = 1.0 / np.sqrt(d)
    out = fm.flash_mha(q, k, v, True, None, window)
    ref = _ref_attn_window(q, k, v, True, scale, window)
    assert float(jnp.max(jnp.abs(out - ref))) < 5e-5
    g1 = jax.grad(loss(lambda q, k, v: fm.flash_mha(q, k, v, True, None,
                                                    window)),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss(lambda q, k, v: _ref_attn_window(q, k, v, True,
                                                        scale, window)),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        rel = float(jnp.linalg.norm((a - b_).ravel())
                    / (jnp.linalg.norm(b_.ravel()) + 1e-9))
        assert rel < 1e-4, rel


def test_sliding_window_resident_grads():
    """Window gradients on the RESIDENT path (the default at training
    lengths) — fwd-only coverage there would ship untested dq/dkv
    masking."""
    ks = jax.random.split(jax.random.PRNGKey(9), 3)
    b, hq, hkv, s, d, window = 1, 2, 1, 256, 64, 96
    q = jax.random.normal(ks[0], (b, hq, s, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, hkv, s, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, hkv, s, d), jnp.float32)
    assert fm._supports_resident(s, d)  # really the resident path
    w = jnp.linspace(0.0, 1.0, d)
    scale = 1.0 / np.sqrt(d)

    def loss(fn):
        return lambda q, k, v: (fn(q, k, v).astype(jnp.float32) * w).sum()

    g1 = jax.grad(loss(lambda q, k, v: fm.flash_mha(q, k, v, True, None,
                                                    window)),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss(lambda q, k, v: _ref_attn_window(q, k, v, True,
                                                        scale, window)),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        rel = float(jnp.linalg.norm((a - b_).ravel())
                    / (jnp.linalg.norm(b_.ravel()) + 1e-9))
        assert rel < 1e-4, rel


@pytest.mark.parametrize("bq,bk", [(256, 512), (512, 256)])
def test_blocked_asymmetric_blocks_parity(bq, bk, _force_blocked,
                                          monkeypatch):
    """bq != bk exercises the generalized diagonal clamps
    (_clamped_kv_index / the dkv q-side clamp use block-unit division,
    not equality) — fwd and grads must match the dense reference."""
    monkeypatch.setattr(fm, "_BLK_Q", bq)
    monkeypatch.setattr(fm, "_BLK_K", bk)
    ks = jax.random.split(jax.random.PRNGKey(9), 3)
    b, hq, hkv, s, d = 1, 2, 1, 1280, 64  # ragged tail vs 512-step pad
    q = jax.random.normal(ks[0], (b, hq, s, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, hkv, s, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, hkv, s, d), jnp.float32)
    out = fm.flash_mha(q, k, v, True)
    ref = _ref_attn(q, k, v, True, 1.0 / np.sqrt(d))
    assert float(jnp.max(jnp.abs(out - ref))) < 5e-5
    w = jnp.linspace(0.0, 1.0, d)

    def loss(fn):
        return lambda q, k, v: (fn(q, k, v).astype(jnp.float32) * w).sum()

    g1 = jax.grad(loss(lambda q, k, v: fm.flash_mha(q, k, v, True)),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss(lambda q, k, v: _ref_attn(q, k, v, True,
                                                 1.0 / np.sqrt(d))),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        rel = float(jnp.linalg.norm((a - b_).ravel())
                    / (jnp.linalg.norm(b_.ravel()) + 1e-9))
        assert rel < 1e-4, rel


@pytest.mark.parametrize("bq,bk", [(256, 512), (512, 256)])
def test_blocked_asymmetric_window_parity(bq, bk, _force_blocked,
                                          monkeypatch):
    """Sliding window + asymmetric blocks: the window clamp's lo/hi block
    arithmetic must not drop live tiles."""
    monkeypatch.setattr(fm, "_BLK_Q", bq)
    monkeypatch.setattr(fm, "_BLK_K", bk)
    ks = jax.random.split(jax.random.PRNGKey(10), 3)
    b, hq, hkv, s, d, window = 1, 2, 1, 1536, 64, 700
    q = jax.random.normal(ks[0], (b, hq, s, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, hkv, s, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, hkv, s, d), jnp.float32)
    out = fm.flash_mha(q, k, v, True, None, window)
    ref = _ref_attn_window(q, k, v, True, 1.0 / np.sqrt(d), window)
    assert float(jnp.max(jnp.abs(out - ref))) < 5e-5
    g = jax.grad(lambda q, k, v: fm.flash_mha(
        q, k, v, True, None, window).astype(jnp.float32).sum(),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: _ref_attn_window(
        q, k, v, True, 1.0 / np.sqrt(d), window)
        .astype(jnp.float32).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g, gr):
        rel = float(jnp.linalg.norm((a - b_).ravel())
                    / (jnp.linalg.norm(b_.ravel()) + 1e-9))
        assert rel < 1e-4, rel


@pytest.mark.parametrize("causal,stride", [(True, 1), (False, 1), (True, 4)])
def test_carry_kernel_chains_to_full_attention(causal, stride):
    """flash_carry_block (the ring-hop kernel): chaining the online-softmax
    carry over key blocks fed in ARBITRARY hop order must reproduce dense
    attention.  stride=4 exercises the striped-placement position
    arithmetic (block positions off + stride*i)."""
    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    b, h, s_l, d, hops = 1, 2, 128, 32, 4
    s = s_l * hops
    scale = 1.0 / np.sqrt(d)
    q = jax.random.normal(ks[0], (b, h, s, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, h, s, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, h, s, d), jnp.float32)

    # reference over GLOBAL positions (identity layout: position == index)
    pos = np.arange(s)
    sc = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    valid = np.ones((s, s), bool)
    if causal:
        valid = pos[:, None] >= pos[None, :]
    sc = jnp.where(jnp.asarray(valid)[None, None], sc, -1e30)
    ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(sc, -1), v)

    # hop decomposition: block j holds positions off_j + stride*i.  For
    # stride=1 that is contiguous chunks; for stride=s_l... use the striped
    # interleave (off_j = j, stride = hops) and gather the matching rows.
    if stride == 1:
        blocks = [(j * s_l, k[:, :, j * s_l:(j + 1) * s_l],
                   v[:, :, j * s_l:(j + 1) * s_l]) for j in range(hops)]
        q_off, q_stride = 0, 1
        qk = q[:, :, :s_l]
        ref_rows = slice(0, s_l)
    else:
        blocks = [(j, k[:, :, j::hops], v[:, :, j::hops])
                  for j in range(hops)]
        q_off, q_stride = 0, hops
        qk = q[:, :, 0::hops]
        ref_rows = slice(0, s, hops)

    m = jnp.full((b, h, s_l, 128), -1e30, jnp.float32)
    l = jnp.zeros((b, h, s_l, 128), jnp.float32)
    acc = jnp.zeros((b, h, s_l, d), jnp.float32)
    for k_off, kc, vc in reversed(blocks):   # arbitrary order on purpose
        m, l, acc = fm.flash_carry_block(
            qk, kc, vc, m, l, acc, jnp.int32(q_off), jnp.int32(k_off),
            q_stride=q_stride, k_stride=stride if stride > 1 else 1,
            s_real=s_l, sm_scale=scale, causal=causal)
    out = acc / jnp.maximum(l[..., 0:1], 1e-20)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(ref[:, :, ref_rows]),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal,stride", [(True, 1), (False, 1), (True, 4)])
def test_ring_bwd_kernels_chain_to_reference_grads(causal, stride):
    """flash_ring_dq_block / flash_ring_dkv_block (the fused ring
    backward): accumulating per-block grads over key blocks fed in
    ARBITRARY hop order must reproduce the dense-attention gradients —
    dq for the local query shard (aliased accumulator across hops) and
    dk/dv per visiting block.  stride=4 exercises the striped-placement
    position arithmetic shared with the forward carry kernel."""
    ks = jax.random.split(jax.random.PRNGKey(12), 4)
    b, h, s_l, d, hops = 1, 2, 128, 32, 4
    s = s_l * hops
    scale = 1.0 / np.sqrt(d)
    k = jax.random.normal(ks[1], (b, h, s, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, h, s, d), jnp.float32)
    qk = jax.random.normal(ks[0], (b, h, s_l, d), jnp.float32)
    do = jax.random.normal(ks[3], (b, h, s_l, d), jnp.float32)

    # one query shard at global positions q_stride*i against the FULL
    # key sequence (dq is row-independent; dk/dv from a single shard are
    # exactly this reference's dk/dv)
    if stride == 1:
        q_stride, qpos = 1, np.arange(s_l)
        blocks = [(j * s_l, k[:, :, j * s_l:(j + 1) * s_l],
                   v[:, :, j * s_l:(j + 1) * s_l]) for j in range(hops)]
        merge = lambda parts: jnp.concatenate(  # noqa: E731
            [p for _, p in sorted(parts.items())], axis=2)
    else:
        q_stride, qpos = hops, np.arange(0, s, hops)
        blocks = [(j, k[:, :, j::hops], v[:, :, j::hops])
                  for j in range(hops)]

        def merge(parts):
            out = np.zeros((b, h, s, d), np.float32)
            for j, p in parts.items():
                out[:, :, j::hops] = np.asarray(p)
            return jnp.asarray(out)

    kpos = np.arange(s)
    valid = np.ones((s_l, s), bool)
    if causal:
        valid = qpos[:, None] >= kpos[None, :]
    vmask = jnp.asarray(valid)[None, None]

    def ref_out(qk, k, v):
        sc = jnp.einsum("bhqd,bhkd->bhqk", qk, k) * scale
        sc = jnp.where(vmask, sc, -1e30)
        return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(sc, -1), v)

    dq_ref, dk_ref, dv_ref = jax.grad(
        lambda qk, k, v: jnp.sum(ref_out(qk, k, v) * do),
        argnums=(0, 1, 2))(qk, k, v)

    # the kernels consume the saved forward residuals: o, lse, delta
    sc = jnp.einsum("bhqd,bhkd->bhqk", qk, k) * scale
    sc = jnp.where(vmask, sc, -1e30)
    lse = jax.scipy.special.logsumexp(sc, axis=-1)        # [b, h, s_l]
    o = ref_out(qk, k, v)
    lsep, deltap = fm.bwd_lane_residuals(o, do, lse, s_l)

    dq = jnp.zeros((b, h, s_l, d), jnp.float32)
    dk_parts, dv_parts = {}, {}
    for k_off, kc, vc in reversed(blocks):   # arbitrary order on purpose
        kw = dict(q_stride=q_stride, k_stride=stride if stride > 1 else 1,
                  s_real=s_l, sm_scale=scale, causal=causal)
        dq = fm.flash_ring_dq_block(qk, kc, vc, do, lsep, deltap, dq,
                                    jnp.int32(0), jnp.int32(k_off), **kw)
        zk = jnp.zeros((b, h, s_l, d), jnp.float32)
        zv = jnp.zeros((b, h, s_l, d), jnp.float32)
        dk_b, dv_b = fm.flash_ring_dkv_block(
            qk, kc, vc, do, lsep, deltap, zk, zv,
            jnp.int32(0), jnp.int32(k_off), **kw)
        dk_parts[k_off], dv_parts[k_off] = dk_b, dv_b

    np.testing.assert_allclose(np.asarray(dq), np.asarray(dq_ref),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(merge(dk_parts)),
                               np.asarray(dk_ref), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(merge(dv_parts)),
                               np.asarray(dv_ref), rtol=2e-4, atol=2e-4)
