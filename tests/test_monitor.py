"""Monitor fan-out (monitor/monitor.py): CSV round-trip, rank gating,
and MonitorMaster degrading a failing backend to disabled instead of
raising into the train loop."""

import csv
import os

import jax
import pytest

from deepspeed_tpu.monitor.monitor import CSVMonitor, MonitorMaster
from deepspeed_tpu.runtime.config import DeepSpeedConfig


def _cfg(tmp_path, **monitor_blocks):
    return DeepSpeedConfig({"train_batch_size": 8, **monitor_blocks})


def _csv_block(tmp_path):
    return {"enabled": True, "output_path": str(tmp_path),
            "job_name": "job"}


def test_csv_monitor_rows_round_trip(tmp_path):
    cfg = _cfg(tmp_path, csv_monitor=_csv_block(tmp_path)).csv_monitor
    mon = CSVMonitor(cfg)
    events = [("Train/loss", 1.5, 1), ("Train/loss", 1.25, 2),
              ("Train/lr", 1e-3, 1)]
    mon.write_events(events)
    loss_file = os.path.join(str(tmp_path), "job", "Train_loss.csv")
    with open(loss_file, newline="") as f:
        rows = [(int(s), float(v)) for s, v in csv.reader(f)]
    assert rows == [(1, 1.5), (2, 1.25)]
    assert os.path.exists(os.path.join(str(tmp_path), "job",
                                       "Train_lr.csv"))


def test_non_rank0_writers_stay_silent(tmp_path, monkeypatch):
    cfg = _cfg(tmp_path, csv_monitor=_csv_block(tmp_path)).csv_monitor
    mon = CSVMonitor(cfg)          # constructed as rank 0 (makedirs ok)
    monkeypatch.setattr(jax, "process_index", lambda *a: 1)
    mon.write_events([("Train/loss", 1.0, 1)])
    assert not os.path.exists(os.path.join(str(tmp_path), "job",
                                           "Train_loss.csv"))


def test_master_fans_out_only_to_enabled_backends(tmp_path):
    ds_config = _cfg(tmp_path, csv_monitor=_csv_block(tmp_path))
    master = MonitorMaster(ds_config)
    assert master.enabled
    assert len(master.monitors) == 1   # only the csv block was enabled
    master.write_events([("Train/loss", 2.0, 1)])
    with open(os.path.join(str(tmp_path), "job", "Train_loss.csv"),
              newline="") as f:
        assert list(csv.reader(f)) == [["1", "2.0"]]


def test_master_all_disabled_is_inert(tmp_path):
    master = MonitorMaster(_cfg(tmp_path))
    assert not master.enabled
    master.write_events([("Train/loss", 1.0, 1)])  # no-op, no crash


class _ExplodingBackend:
    enabled = True

    def write_events(self, events):
        raise RuntimeError("disk full")


def test_master_degrades_failing_backend_to_disabled(tmp_path):
    ds_config = _cfg(tmp_path, csv_monitor=_csv_block(tmp_path))
    master = MonitorMaster(ds_config)
    bad = _ExplodingBackend()
    master.monitors.insert(0, bad)     # fails BEFORE the healthy backend
    master.write_events([("Train/loss", 3.0, 7)])
    # the failing backend is now off, the healthy one still wrote
    assert bad.enabled is False
    assert master.enabled              # csv survives
    with open(os.path.join(str(tmp_path), "job", "Train_loss.csv"),
              newline="") as f:
        assert list(csv.reader(f)) == [["7", "3.0"]]
    # a second write is clean (the dead backend is skipped)
    master.write_events([("Train/loss", 4.0, 8)])
    # all backends dead → master reports disabled
    master2 = MonitorMaster(_cfg(tmp_path))
    bad2 = _ExplodingBackend()
    master2.monitors.append(bad2)
    master2.enabled = True
    master2.write_events([("x", 1.0, 1)])
    assert master2.enabled is False


def test_unknown_outcome_keys_rejected_by_csv_path(tmp_path):
    """Tags with path separators must be sanitized into one file name,
    not create directories."""
    cfg = _cfg(tmp_path, csv_monitor=_csv_block(tmp_path)).csv_monitor
    mon = CSVMonitor(cfg)
    mon.write_events([("a/b/c", 1.0, 1)])
    assert os.path.exists(os.path.join(str(tmp_path), "job", "a_b_c.csv"))
