"""DeepCompile-analog pass pipeline, evoformer attention, spatial ops.

Mirrors reference coverage: tests/unit/compile/, ops/deepspeed4science,
spatial kernel tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.compile import CompileReport, deepspeed_compile
from deepspeed_tpu.ops.evoformer_attn import evoformer_attention
from deepspeed_tpu.ops.spatial import (bias_add_nhwc, conv2d_nhwc,
                                       group_norm_nhwc, upsample_nearest_nhwc)


def _mlp_factory(knobs):
    w1 = jnp.ones((64, 256), jnp.float32) * 0.01
    w2 = jnp.ones((256, 64), jnp.float32) * 0.01

    def fn(x):
        def block(h):
            return jax.nn.gelu(h @ w1) @ w2

        if knobs.get("remat_policy") == "nothing_saveable":
            block = jax.checkpoint(block)
        elif knobs.get("remat_policy") == "dots_saveable":
            block = jax.checkpoint(
                block, policy=jax.checkpoint_policies.checkpoint_dots)
        h = x
        for _ in range(4):
            h = block(h)
        return h.sum()

    return fn


def test_compile_no_budget_no_changes():
    x = jnp.ones((8, 64), jnp.float32)
    fn, report = deepspeed_compile(_mlp_factory, (x,), {})
    assert report.knobs["remat_policy"] == "none"
    assert np.isfinite(float(fn(x)))
    assert any("profile" in d for d in report.decisions)


def test_compile_escalates_remat_under_budget():
    x = jnp.ones((8, 64), jnp.float32)
    # absurdly small budget → ladder escalates to nothing_saveable and
    # finally flips optimizer offload
    fn, report = deepspeed_compile(_mlp_factory, (x,),
                                   {"memory_budget_bytes": 1})
    assert report.knobs["remat_policy"] == "nothing_saveable"
    assert report.knobs.get("offload_optimizer") is True
    assert any("remat" in d for d in report.decisions)
    # result identical regardless of remat
    base, _ = deepspeed_compile(_mlp_factory, (x,), {})
    np.testing.assert_allclose(float(fn(x)), float(base(x)), rtol=1e-6)


def test_compile_prefetch_widens_stream_window():
    """With streaming active and memory headroom, the prefetch pass
    raises scan_unroll (the H2D overlap window); without streaming it
    never fires (ref passes/prefetch.py)."""
    x = jnp.ones((8, 64), jnp.float32)
    budget = {"memory_budget_bytes": 1 << 40, "param_stream": True}
    fn, report = deepspeed_compile(_mlp_factory, (x,), budget)
    assert report.knobs.get("scan_unroll") == 4  # 1 → 2 → 4, ladder top
    assert any("prefetch" in d for d in report.decisions)
    _, no_stream = deepspeed_compile(_mlp_factory, (x,),
                                     {"memory_budget_bytes": 1 << 40})
    assert "scan_unroll" not in no_stream.knobs
    assert np.isfinite(float(fn(x)))


def test_evoformer_attention_matches_reference():
    rng = np.random.default_rng(0)
    b, s, h, d = 2, 16, 4, 8
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    bias2 = jnp.asarray(rng.standard_normal((b, h, s, s)), jnp.float32)
    out = evoformer_attention(q, k, v, bias2=bias2)
    scale = d ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k) + bias2
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(scores, -1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_evoformer_mask_bias_excludes_keys():
    rng = np.random.default_rng(1)
    s, h, d = 8, 2, 4
    q = jnp.asarray(rng.standard_normal((1, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, s, h, d)), jnp.float32)
    mask = jnp.zeros((1, 1, 1, s)).at[..., -1].set(-1e9)  # kill last key
    out = evoformer_attention(q, k, v, bias1=mask)
    v2 = v.at[:, -1].set(v[:, -1] + 50.0)
    out2 = evoformer_attention(q, k, v2, bias1=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2), atol=1e-5)


def test_evoformer_5d_alphafold_shapes():
    rng = np.random.default_rng(2)
    n, r, s, h, d = 2, 3, 8, 2, 4  # batch, MSA rows, seq, heads, dim
    q = jnp.asarray(rng.standard_normal((n, r, s, h, d)), jnp.float32)
    out = evoformer_attention(q, q, q)
    assert out.shape == (n, r, s, h, d)
    assert np.isfinite(np.asarray(out)).all()


def test_conv2d_nhwc_and_epilogues():
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((2, 8, 8, 3)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((3, 3, 3, 16)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((16,)), jnp.float32)
    out = conv2d_nhwc(x, w, b, activation="silu")
    assert out.shape == (2, 8, 8, 16)
    ref = jax.nn.silu(jax.lax.conv_general_dilated(
        x, w, (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC")) + b)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    np.testing.assert_allclose(np.asarray(bias_add_nhwc(x, jnp.ones(3))),
                               np.asarray(x + 1), atol=1e-6)


def test_group_norm_and_upsample():
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((1, 4, 4, 8)), jnp.float32)
    out = group_norm_nhwc(x, jnp.ones(8), jnp.zeros(8), num_groups=4)
    grp = np.asarray(out).reshape(1, 4, 4, 4, 2)
    assert abs(grp[0, :, :, 0].mean()) < 1e-4  # normalized per group
    with pytest.raises(ValueError):
        group_norm_nhwc(x, jnp.ones(8), jnp.zeros(8), num_groups=3)
    up = upsample_nearest_nhwc(x, 2)
    assert up.shape == (1, 8, 8, 8)
    np.testing.assert_allclose(np.asarray(up[0, 0, 0]), np.asarray(up[0, 1, 1]))


def test_compile_selective_unshard_with_headroom():
    """With peak well under budget, the selective-unshard pass climbs the
    persist-threshold ladder (ref DeepCompile selective gather): spare HBM
    buys fewer ZeRO-3 all-gathers."""
    import numpy as np

    x = jnp.ones((8, 64), jnp.float32)
    seen = []

    def factory(knobs):
        seen.append(dict(knobs))
        return _mlp_factory(knobs)

    fn, report = deepspeed_compile(
        factory, (x,), {"memory_budget_bytes": int(1e12)})
    assert report.knobs.get("persist_threshold", 0) > 0, report.knobs
    assert any("selective_unshard" in d for d in report.decisions)
    np.testing.assert_allclose(np.asarray(fn(x)),
                               np.asarray(_mlp_factory({})(x)), atol=1e-6)
