"""Native CPU optimizers vs torch/optax references.

Mirrors the reference's tests/unit/ops/adam/ (kernel vs torch.optim)."""

import numpy as np
import pytest

from deepspeed_tpu.ops.cpu_optimizer import (DeepSpeedCPUAdagrad,
                                             DeepSpeedCPUAdam,
                                             DeepSpeedCPULion,
                                             adam_step_numpy,
                                             cpu_optimizer_available)

RNG = np.random.default_rng(0)


def _params(shapes):
    return [np.ascontiguousarray(RNG.standard_normal(s), np.float32)
            for s in shapes]


def test_native_builds():
    # the toolchain is baked into the image — the native path must build
    assert cpu_optimizer_available()


@pytest.mark.parametrize("adamw", [False, True])
def test_cpu_adam_matches_torch(adamw):
    import torch

    shapes = [(64, 32), (129,)]  # odd size exercises vector tail
    params = _params(shapes)
    t_params = [torch.nn.Parameter(torch.tensor(p)) for p in params]
    opt_cls = torch.optim.AdamW if adamw else torch.optim.Adam
    t_opt = opt_cls(t_params, lr=1e-2, betas=(0.9, 0.999), eps=1e-8,
                    weight_decay=0.01)
    ds_opt = DeepSpeedCPUAdam(params, lr=1e-2, betas=(0.9, 0.999), eps=1e-8,
                              weight_decay=0.01, adamw_mode=adamw)
    for step in range(5):
        grads = [np.ascontiguousarray(RNG.standard_normal(s), np.float32)
                 for s in shapes]
        for tp, g in zip(t_params, grads):
            tp.grad = torch.tensor(g)
        t_opt.step()
        ds_opt.step(grads)
    for p, tp in zip(params, t_params):
        np.testing.assert_allclose(p, tp.detach().numpy(), atol=2e-5,
                                   rtol=2e-4)


def test_cpu_adam_native_matches_numpy():
    if not cpu_optimizer_available():
        pytest.skip("no native lib")
    shapes = [(1000,)]
    p_nat = _params(shapes)
    p_np = [p.copy() for p in p_nat]
    nat = DeepSpeedCPUAdam(p_nat, lr=0.1)
    m = [np.zeros_like(p) for p in p_np]
    v = [np.zeros_like(p) for p in p_np]
    for step in range(1, 4):
        g = [np.ascontiguousarray(RNG.standard_normal(s), np.float32)
             for s in shapes]
        nat.step(g)
        for pp, gg, mm, vv in zip(p_np, g, m, v):
            adam_step_numpy(pp, gg, mm, vv, 0.1, 0.9, 0.999, 1e-8, 0.0,
                            step, adamw=True)
    np.testing.assert_allclose(p_nat[0], p_np[0], atol=1e-6, rtol=1e-5)


def test_cpu_adagrad():
    import torch

    shapes = [(40, 10)]
    params = _params(shapes)
    t_params = [torch.nn.Parameter(torch.tensor(p)) for p in params]
    t_opt = torch.optim.Adagrad(t_params, lr=1e-2, eps=1e-10)
    ds_opt = DeepSpeedCPUAdagrad(params, lr=1e-2, eps=1e-10)
    for _ in range(3):
        grads = [np.ascontiguousarray(RNG.standard_normal(s), np.float32)
                 for s in shapes]
        for tp, g in zip(t_params, grads):
            tp.grad = torch.tensor(g)
        t_opt.step()
        ds_opt.step(grads)
    np.testing.assert_allclose(params[0], t_params[0].detach().numpy(),
                               atol=1e-5, rtol=1e-4)


def test_cpu_lion_sign_update():
    params = _params([(32,)])
    before = params[0].copy()
    opt = DeepSpeedCPULion(params, lr=0.1, betas=(0.9, 0.99))
    g = [np.ones((32,), np.float32)]
    opt.step(g)
    # first step: c = 0.1*g (m=0) → sign=+1 → p -= lr
    np.testing.assert_allclose(params[0], before - 0.1, atol=1e-6)
    # momentum accumulated
    np.testing.assert_allclose(opt.exp_avg[0], 0.01 * np.ones(32), atol=1e-6)


def test_superoffload_uses_native_path():
    import jax.numpy as jnp

    from deepspeed_tpu.runtime.superoffload import SuperOffloadOptimizer

    params = {"w": jnp.asarray(RNG.standard_normal((16, 16)), jnp.float32)}
    grads = {"w": jnp.ones((16, 16), jnp.float32)}
    so = SuperOffloadOptimizer(params, lr=0.01)
    out = so.step(params, grads)
    import optax

    tx = optax.adam(0.01, 0.9, 0.999, 1e-8)
    st = tx.init(params)
    upd, _ = tx.update(grads, st, params)
    ref = optax.apply_updates(params, upd)
    np.testing.assert_allclose(np.asarray(out["w"]), np.asarray(ref["w"]),
                               atol=1e-5, rtol=1e-4)
