"""Engine integration: random-LTD schedule and progressive layer drop
driven from the JSON config (ref tests/unit/runtime data-efficiency +
PLD coverage)."""

import jax.numpy as jnp
import numpy as np

import deepspeed_tpu as ds
from deepspeed_tpu.models import get_model_config, init_params
from deepspeed_tpu.models import transformer as tf


def _reset_topo():
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None


def _batch(model, n=4, s=33, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, model.vocab_size, size=(n, s), dtype=np.int32)
    return {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}


def test_forward_ltd_band_matches_shape_and_differs():
    cfg = get_model_config("gpt2-tiny").replace(
        dtype=jnp.float32, num_layers=4, ltd_kept=8, ltd_start=1, ltd_end=3)
    import jax

    params = init_params(cfg, jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(2, 16)), jnp.int32)
    out = tf.forward(params, ids, cfg)
    assert out.shape == (2, 16, cfg.vocab_size)
    full = tf.forward(params, ids, cfg.replace(ltd_kept=0))
    # dropping tokens in the band must change the result
    assert np.abs(np.asarray(out) - np.asarray(full)).max() > 1e-5


def test_engine_random_ltd_schedule_rejits():
    model = get_model_config("gpt2-tiny").replace(num_layers=4)
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "mesh": {"data": 1},
        "data_efficiency": {
            "enabled": True,
            "data_routing": {"random_ltd": {
                "enabled": True, "ltd_start": 1, "ltd_end": 3,
                "random_ltd_schedule": {
                    "min_value": 16, "max_value": 32,
                    "schedule_config": {"require_steps": 2,
                                        "seq_per_step": 16}}}}},
    }
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    batch = _batch(model)
    losses = []
    for _ in range(4):
        losses.append(float(np.asarray(engine.train_batch(batch))))
    assert all(np.isfinite(losses))
    # step 0-1: kept=16 < seq 32 → LTD active; by step 2 kept=32 ≥ seq → off
    assert engine.model_config.ltd_kept == 0
    _reset_topo()


def test_engine_pld_theta_rides_batch():
    model = get_model_config("gpt2-tiny").replace(num_layers=4)
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "mesh": {"data": 1},
        "progressive_layer_drop": {"enabled": True, "theta": 0.5,
                                   "gamma": 0.01},
    }
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    assert engine.progressive_layer_drop is not None
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(8, 33), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
    l0 = float(np.asarray(engine.train_batch(batch)))
    for _ in range(3):
        l1 = float(np.asarray(engine.train_batch(batch)))
    assert np.isfinite(l0) and np.isfinite(l1)
    # theta decayed from 1.0 toward 0.5
    assert engine.progressive_layer_drop.current_theta < 1.0
    _reset_topo()


def test_pld_theta_one_is_identity():
    import jax

    cfg = get_model_config("gpt2-tiny").replace(dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(2, 8)), jnp.int32)
    base = tf.forward(params, ids, cfg)
    pld1 = tf.forward(params, ids, cfg, pld_theta=jnp.float32(1.0))
    np.testing.assert_allclose(np.asarray(base), np.asarray(pld1), atol=1e-6)
