"""Quantized ZeRO collectives (comm/quantized.py + the engine's explicit
grad-reduce path; docs/QUANTIZED_COMM.md).

Covers the ISSUE-6 acceptance set: round-trip quant/dequant error bounds,
reduce-scatter == all-reduce-then-slice equivalence, error-feedback
residual behaviour, config plumbing rejection, the qgZ
all_to_all_quant_reduce numerics bound, pack_signs arbitrary-length
padding, and the tier-1 loss-parity + byte-reduction check of the
comm-quant train step on the 8-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import deepspeed_tpu as ds
from deepspeed_tpu.comm.quantized import (QUANT_COMM_OPS, _wire_decode,
                                          _wire_encode, fp8_supported,
                                          quantized_all_reduce,
                                          quantized_reduce_scatter,
                                          validate_wire_dtype)
from deepspeed_tpu.models import get_model_config
from deepspeed_tpu.utils.comms_logging import get_comms_logger
from deepspeed_tpu.utils.jax_compat import shard_map
from tests.conftest import make_lm_batch

WORLD = 8


def _mesh():
    return Mesh(np.array(jax.devices()[:WORLD]), ("data",))


def _per_rank(fn, x, out_rows=True):
    """Run ``fn`` per-rank over a [WORLD, n] stack of rank-local buffers."""
    mesh = _mesh()
    mapped = shard_map(fn, mesh=mesh, in_specs=(P("data", None),),
                      out_specs=P("data", None), check_vma=False)
    return np.asarray(jax.jit(mapped)(x))


# ----------------------------------------------------------------------
# round-trip quant/dequant error bounds
# ----------------------------------------------------------------------
@pytest.mark.parametrize("wire,bound", [("fp32", 0.0), ("int8", 1 / 127.0),
                                        ("fp8", 0.13)])
def test_wire_roundtrip_error_bound(rng, wire, bound):
    """Per-block round-trip error is bounded by the wire dtype's step:
    int8 absmax/127 per block; fp8-e4m3 has 3 mantissa bits (relative
    step 2^-3, i.e. elementwise |err| <= x/8 <= absmax/8 — documented in
    docs/QUANTIZED_COMM.md's trade-off table)."""
    if wire == "fp8" and not fp8_supported():
        pytest.skip("no fp8 on this jax")
    x = jnp.asarray(rng.standard_normal((4, 512)), jnp.float32)
    payload, scale = _wire_encode(x, wire, group_size=128)
    back = _wire_decode(payload, scale, wire)
    err = np.abs(np.asarray(back) - np.asarray(x))
    g = np.asarray(x).reshape(4, 4, 128)
    absmax = np.abs(g).max(axis=-1, keepdims=True)
    tol = np.broadcast_to(absmax * bound + 1e-7, g.shape).reshape(4, 512)
    assert (err <= tol).all(), (err.max(), tol.min())


def test_wire_dtype_validation():
    validate_wire_dtype("int8")
    with pytest.raises(ValueError, match="wire dtype"):
        validate_wire_dtype("int4")


# ----------------------------------------------------------------------
# reduce-scatter == all-reduce-then-slice
# ----------------------------------------------------------------------
@pytest.mark.parametrize("wire", ["fp32", "int8"])
def test_reduce_scatter_matches_all_reduce_slice(rng, wire):
    n = WORLD * 256
    X = jnp.asarray(rng.standard_normal((WORLD, n)), jnp.float32)

    def rs(x):
        sh, _ = quantized_reduce_scatter(x.reshape(-1), "data", WORLD,
                                         wire_dtype=wire, group_size=64)
        return sh[None]

    def ar(x):
        out, _ = quantized_all_reduce(x.reshape(-1), "data", WORLD,
                                      wire_dtype=wire, group_size=64)
        return out[None]

    shards = _per_rank(rs, X).reshape(-1)          # rank r's [n/WORLD] chunk
    full = _per_rank(ar, X)                        # every rank's full [n]
    # every rank's all-reduce output is identical; its slice r equals the
    # reduce-scatter shard up to the gather-phase requantize
    for r in range(WORLD):
        got = full[r].reshape(-1)
        if wire == "fp32":
            np.testing.assert_array_equal(got, shards)
        else:
            m = n // WORLD
            g = shards.reshape(WORLD, m // 64, 64)
            tol = np.abs(g).max(axis=-1, keepdims=True) / 127.0 + 1e-7
            assert (np.abs(got - shards).reshape(WORLD, m // 64, 64)
                    <= tol).all()


def test_all_reduce_matches_fp32_mean(rng):
    """The documented relative error bounds of the two-phase quantized
    all-reduce vs the exact fp32 mean."""
    n = WORLD * 512
    X = jnp.asarray(rng.standard_normal((WORLD, n)), jnp.float32)
    ref = np.mean(np.asarray(X), axis=0)
    scale = np.max(np.abs(ref)) + 1e-9
    for wire, bound in [("fp32", 1e-6), ("int8", 0.03), ("fp8", 0.10)]:
        if wire == "fp8" and not fp8_supported():
            continue

        def ar(x):
            out, _ = quantized_all_reduce(x.reshape(-1), "data", WORLD,
                                          wire_dtype=wire, group_size=256)
            return out[None]

        got = _per_rank(ar, X)[0]
        rel = np.max(np.abs(got - ref)) / scale
        assert rel <= bound, (wire, rel)


# ----------------------------------------------------------------------
# error feedback
# ----------------------------------------------------------------------
def test_error_feedback_average_error_shrinks(rng):
    """With a constant input, the residual telescopes: the time-averaged
    quantized all-reduce output converges to the true mean (sum_k Q_k =
    k·x + r_0 − r_k), so the running-mean error shrinks ~1/k and the
    residual itself stays bounded by the quantization step."""
    n = WORLD * 256
    X = jnp.asarray(rng.standard_normal((WORLD, n)), jnp.float32)
    ref = np.mean(np.asarray(X), axis=0)
    steps = 8

    def run(x):
        x = x.reshape(-1)
        res = jnp.zeros_like(x)
        outs = []
        for _ in range(steps):
            out, res = quantized_all_reduce(x, "data", WORLD,
                                            wire_dtype="int8",
                                            group_size=64, residual=res)
            outs.append(out)
        return jnp.stack(outs)[None], res[None]

    mesh = _mesh()
    mapped = shard_map(lambda x: run(x), mesh=mesh,
                       in_specs=(P("data", None),),
                       out_specs=(P("data", None, None), P("data", None)),
                       check_vma=False)
    outs, res = jax.jit(mapped)(X)
    outs = np.asarray(outs)[0]  # rank-0's per-step outputs [steps, n]
    err1 = np.abs(outs[0] - ref).mean()
    err_avg = np.abs(outs.mean(axis=0) - ref).mean()
    assert err_avg < err1 / 2, (err_avg, err1)
    # the carried residual never exceeds the one-send quantization step
    x0 = np.asarray(X)[0]
    step = np.abs(x0.reshape(WORLD, -1, 64)).max(axis=-1).max() / 127.0
    assert np.abs(np.asarray(res)).max() <= 2 * step + 1e-6


def test_fp32_wire_has_zero_residual(rng):
    X = jnp.asarray(rng.standard_normal((WORLD, 64)), jnp.float32)

    def f(x):
        x = x.reshape(-1)
        out, res = quantized_all_reduce(x, "data", WORLD, wire_dtype="fp32",
                                        residual=jnp.zeros_like(x))
        return res[None]

    res = _per_rank(f, X)
    assert np.abs(res).max() == 0.0


# ----------------------------------------------------------------------
# config plumbing
# ----------------------------------------------------------------------
def test_config_rejects_bad_dtype():
    from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                              DeepSpeedConfigError)

    with pytest.raises(DeepSpeedConfigError, match="grad_reduce"):
        DeepSpeedConfig({"train_batch_size": 8,
                         "comm_quantization": {"enabled": True,
                                               "grad_reduce": "int4"}},
                        world_size=8)


def test_config_rejects_bad_collective_name():
    from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                              DeepSpeedConfigError)

    with pytest.raises(DeepSpeedConfigError, match="unknown collective"):
        DeepSpeedConfig({"train_batch_size": 8,
                         "comm_quantization": {
                             "enabled": True,
                             "collectives": {"param_gather": "int8"}}},
                        world_size=8)


def test_config_collectives_dict_form_and_group_size():
    from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                              DeepSpeedConfigError)

    cfg = DeepSpeedConfig({"train_batch_size": 8,
                           "comm_quantization": {
                               "enabled": True,
                               "collectives": {"grad_reduce": "int8",
                                               "zero3_gather": "fp8"}}},
                          world_size=8)
    assert cfg.comm_quantization.grad_reduce == "int8"
    assert cfg.comm_quantization.zero3_gather == "fp8"
    with pytest.raises(DeepSpeedConfigError, match="group_size"):
        DeepSpeedConfig({"train_batch_size": 8,
                         "comm_quantization": {"enabled": True,
                                               "group_size": 0}},
                        world_size=8)


# ----------------------------------------------------------------------
# engine: explicit quantized grad reduce — loss parity + byte reduction
# ----------------------------------------------------------------------
def _train_commquant(rng_seed, cq, steps=5):
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None
    cl = get_comms_logger()
    cl.reset()
    prev = cl.enabled
    cl.enabled = True
    try:
        model = get_model_config("gpt2-tiny", num_layers=2)
        cfg = {"train_micro_batch_size_per_gpu": 1,
               "gradient_accumulation_steps": 2,
               "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
               "zero_optimization": {"stage": 1},
               "mesh": {"data": 8}, "steps_per_print": 1000}
        if cq:
            cfg["comm_quantization"] = cq
        engine, *_ = ds.initialize(model=model, config=cfg, seed=0)
        rng = np.random.default_rng(rng_seed)
        batch = make_lm_batch(rng, 16, 16, model.vocab_size)
        losses = [float(np.asarray(engine.train_batch(batch)))
                  for _ in range(steps)]
        comm = {k: v for k, v in cl.totals().items()
                if k in QUANT_COMM_OPS}
        return losses, comm, engine
    finally:
        cl.enabled = prev


def test_commquant_loss_parity_and_byte_reduction(rng):
    """The ISSUE-6 acceptance check, tier-1 edition of the
    gpt2_350m_commquant bench row: N-step loss parity of the int8 wire
    vs both the implicit fp32 reduce and the explicit fp32-wire control,
    and >= 3x grad-reduce byte reduction in the per-collective comm
    telemetry."""
    base, comm0, _ = _train_commquant(0, None)
    assert comm0 == {}  # implicit GSPMD reduce records no explicit ops

    f32, comm_f, ef32 = _train_commquant(
        0, {"enabled": True, "grad_reduce": "fp32"})
    assert ef32._comm_quant is not None
    assert ef32._comm_quant_state is None  # fp32 wire carries no residual
    # the explicit fp32 collective is numerically the implicit reduce
    np.testing.assert_allclose(f32, base, rtol=1e-4, atol=1e-4)

    i8, comm_q, ei8 = _train_commquant(
        0, {"enabled": True, "grad_reduce": "int8"})
    assert ei8._comm_quant_state is not None  # error feedback engaged
    # N-step loss parity: int8 wire tracks the fp32 curve
    assert max(abs(a - b) for a, b in zip(i8, base)) < 0.02, (i8, base)

    for op in QUANT_COMM_OPS:
        assert comm_f[op]["bytes"] > 0 and comm_q[op]["bytes"] > 0
    reduction = (sum(v["bytes"] for v in comm_f.values())
                 / sum(v["bytes"] for v in comm_q.values()))
    assert reduction >= 3.0, reduction


@pytest.mark.parametrize("wire", ["fp8"])
def test_commquant_fp8_trains(rng, wire):
    if not fp8_supported():
        pytest.skip("no fp8 on this jax")
    losses, comm, engine = _train_commquant(
        0, {"enabled": True, "grad_reduce": wire}, steps=4)
    assert engine._comm_quant is not None
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_commquant_falls_back_on_single_device(rng):
    """dp == 1: no explicit path (warn + implicit reduce)."""
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None
    model = get_model_config("gpt2-tiny", num_layers=1)
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
           "comm_quantization": {"enabled": True, "grad_reduce": "int8"},
           "mesh": {"data": 1}}
    engine, *_ = ds.initialize(model=model, config=cfg, seed=0)
    assert engine._comm_quant is None
    batch = make_lm_batch(np.random.default_rng(0), 2, 8, model.vocab_size)
    assert np.isfinite(float(np.asarray(engine.train_batch(batch))))


def test_zero3_gather_fp8_trains(rng):
    """comm_quantization.zero3_gather='fp8': the stage-3 qwZ
    straight-through gather moves fp8 payloads and still converges."""
    if not fp8_supported():
        pytest.skip("no fp8 on this jax")
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None
    model = get_model_config("gpt2-tiny", num_layers=2)
    cfg = {"train_micro_batch_size_per_gpu": 1,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": 3},
           "comm_quantization": {"enabled": True, "zero3_gather": "fp8"},
           "mesh": {"data": 8}, "steps_per_print": 1000}
    engine, *_ = ds.initialize(model=model, config=cfg, seed=0)
    batch = make_lm_batch(np.random.default_rng(0), 8, 16, model.vocab_size)
    losses = [float(np.asarray(engine.train_batch(batch))) for _ in range(4)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


# ----------------------------------------------------------------------
# satellite: existing qgZ all_to_all_quant_reduce numerics
# ----------------------------------------------------------------------
def test_all_to_all_quant_reduce_numerics(rng):
    """int8 two-level qgZ reduce vs the fp32 reference mean on a 2x4
    mesh: documented bound — two cascaded int8 block quantizations, each
    with per-block error <= absmax/127, keep the reduced gradient within
    5% of the reference (relative to the buffer's absmax)."""
    from deepspeed_tpu.comm.coalesced_collectives import \
        all_to_all_quant_reduce

    devices = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devices, ("outer", "inner"))
    n = 8 * 512
    X = jnp.asarray(rng.standard_normal((8, n)), jnp.float32)

    def f(x):
        shard, _ = all_to_all_quant_reduce(
            {"g": x.reshape(-1)}, "inner", "outer",
            inner_size=4, outer_size=2)
        return shard[None]

    mapped = shard_map(f, mesh=mesh,
                       in_specs=(P(("outer", "inner"), None),),
                       out_specs=P(("outer", "inner"), None),
                       check_vma=False)
    m = n // 8
    shards = np.asarray(jax.jit(mapped)(X)).reshape(2, 4, m)
    ref = np.mean(np.asarray(X), axis=0)
    # rank (o, i) holds level-1 chunk i's level-2 sub-chunk o: its
    # reference segment starts at i*(n/inner) + o*(n/(inner*outer))
    recon = np.zeros(n, np.float32)
    for o in range(2):
        for i in range(4):
            start = i * (n // 4) + o * m
            recon[start:start + m] = shards[o, i]
    rel = np.max(np.abs(recon - ref)) / (np.max(np.abs(ref)) + 1e-9)
    assert rel <= 0.05, rel


# ----------------------------------------------------------------------
# satellite: pack_signs arbitrary-length padding
# ----------------------------------------------------------------------
@pytest.mark.parametrize("n", [13, 8, 1, 24, 100])
def test_pack_signs_pads_internally(rng, n):
    from deepspeed_tpu.comm.compressed import pack_signs, unpack_signs

    bits = jnp.asarray(rng.integers(0, 2, size=(n,)), jnp.uint8)
    packed = pack_signs(bits)
    assert packed.shape[-1] == -(-n // 8)
    back = unpack_signs(packed)[:n]
    np.testing.assert_array_equal(np.asarray(back), np.asarray(bits))


def test_compressed_allreduce_arbitrary_chunk_length(rng):
    """compressed_allreduce with per-rank chunks NOT divisible by 8 (the
    old pack_signs raised; an intermediate state reshape-crashed deep in
    the jit): N = world*12 runs end to end and stays an unbiased-ish
    sign-compressed mean."""
    from deepspeed_tpu.comm.compressed import compressed_allreduce

    n = WORLD * 12
    X = jnp.asarray(rng.standard_normal((WORLD, n)), jnp.float32)

    def f(x):
        x = x.reshape(-1)
        out, werr, serr = compressed_allreduce(
            x, jnp.zeros_like(x), jnp.zeros((n // WORLD,), jnp.float32),
            "data", WORLD)
        return out[None]

    out = _per_rank(f, X)
    assert out.shape == (WORLD, n)
    assert np.isfinite(out).all()
    # 1-bit compression preserves only sign x magnitude-mean structure;
    # the decompressed average must correlate with the true mean
    ref = np.mean(np.asarray(X), axis=0)
    assert np.corrcoef(out[0], ref)[0, 1] > 0.3


def test_error_feedback_survives_fp16_overflow(rng):
    """Review regression: an overflow-skipped fp16 step must not poison
    the carried error-feedback residual with inf/NaN — the residual rolls
    back with params/opt state and training recovers once the loss scale
    halves down."""
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None
    model = get_model_config("gpt2-tiny", num_layers=1)
    cfg = {"train_micro_batch_size_per_gpu": 1,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
           # scale 2^20: loss*scale overflows fp16 -> first steps skip
           "fp16": {"enabled": True, "initial_scale_power": 20},
           "comm_quantization": {"enabled": True, "grad_reduce": "int8"},
           "mesh": {"data": 8}, "steps_per_print": 1000}
    engine, *_ = ds.initialize(model=model, config=cfg, seed=0)
    assert engine._comm_quant_state is not None
    batch = make_lm_batch(np.random.default_rng(0), 8, 8, model.vocab_size)
    losses = [float(np.asarray(engine.train_batch(batch)))
              for _ in range(10)]
    assert engine.skipped_steps >= 1  # the big scale really overflowed
    res = np.asarray(engine._comm_quant_state["residual"])
    assert np.isfinite(res).all()  # residual never poisoned
    finite_losses = [l for l in losses if np.isfinite(l)]
    assert finite_losses, losses   # training recovered after rescale
    assert np.isfinite(float(engine.loss_scale))


def test_compress_roundtrip_arbitrary_length(rng):
    """_compress/_decompress track the true length through the padded
    sign bytes — arbitrary flat buffers compress (the old pack_signs
    raised on lengths not divisible by 8)."""
    from deepspeed_tpu.comm.compressed import _compress, _decompress

    for n in (13, 21, 64):
        x = jnp.asarray(rng.standard_normal((2, n)), jnp.float32)
        bits, scale = _compress(x)
        back = _decompress(bits, scale, n)
        assert back.shape == (2, n)
        # sign-compressed: sign pattern preserved, magnitude = L1 mean
        np.testing.assert_array_equal(np.sign(np.asarray(back)),
                                      np.where(np.asarray(x) >= 0, 1.0, -1.0))
