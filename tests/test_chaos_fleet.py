"""Chaos harness + fleet supervisor + graceful-degradation ladder.

Three layers, bottom-up: the seeded fault-injection vocabulary
(resilience/chaos.py) must be deterministic and exactly-once; the
FleetSupervisor's health state machine must walk the frozen states —
quarantine, respawn within budget, tier collapse/restore — against
scripted replica failures; and the brownout ladder must be monotone
with hysteresis, shedding STRICTLY the lowest-priority class while
accepted requests keep their exact greedy outputs.  Supervisor tests
run against fake replicas (the supervisor only touches public probe
surfaces); the shedding tests drive a real serve loop.
"""

import json
import time
import types

import numpy as np
import pytest

from deepspeed_tpu.resilience.chaos import (CHAOS_SENTINEL, FAULT_KINDS,
                                            INJECTION_POINTS, ChaosError,
                                            ChaosInjector, FaultPlan,
                                            FaultSpec, TrainChaos,
                                            attach_chaos)
from deepspeed_tpu.serving import (BROWNOUT_LEVELS, HEALTH_STATES,
                                   BrownoutConfig, BrownoutController,
                                   FleetHealFailed, FleetSupervisor,
                                   RequestShed, ServingError,
                                   brownout_index)

ENG_CFG = {"dtype": "float32",
           "memory_config": {"num_blocks": 64, "block_size": 4},
           "max_context": 64}


# ---------------------------------------------------------------------------
# chaos module: plans, injectors, the training contract
# ---------------------------------------------------------------------------

def test_fault_spec_validation():
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultSpec(kind="meteor_strike")
    with pytest.raises(ValueError, match="unknown injection point"):
        FaultSpec(kind="replica_crash", point="kitchen.sink")
    # every kind resolves to a legal default point
    for kind in FAULT_KINDS:
        assert FaultSpec(kind=kind).point in INJECTION_POINTS


def test_fault_plan_sorted_and_targeted():
    plan = FaultPlan([
        {"kind": "replica_hang", "at": 2.0, "target": "r1"},
        {"kind": "replica_crash", "at": 0.5, "target": "r0"},
        {"kind": "slow_replica", "at": 1.0},          # broadcast
    ], seed=3)
    assert [f.at for f in plan.faults] == [0.5, 1.0, 2.0]
    # a target sees its own specs plus the broadcast ones, in order
    assert [f.kind for f in plan.for_target("r1")] == ["slow_replica",
                                                       "replica_hang"]
    assert len(plan.for_target(None)) == 1


def test_injector_one_shot_fires_exactly_once():
    plan = FaultPlan([{"kind": "replica_crash", "at": 0.5,
                       "target": "r0"}])
    inj = ChaosInjector(plan, target="r0").arm(now=100.0)
    assert inj.fire("server.step", now=100.4) == []
    due = inj.fire("server.step", now=100.6)
    assert [f.kind for f in due] == ["replica_crash"]
    # consumed: never again, regardless of how often the loop polls
    assert inj.fire("server.step", now=100.7) == []
    assert inj.fire("server.step", now=200.0) == []
    assert inj.injected == 1 and inj.fired_kinds == {"replica_crash"}
    # the wrong point never sees it
    assert inj.fire("engine.step", now=100.6) == []


def test_injector_durational_window_and_delay():
    plan = FaultPlan([{"kind": "slow_replica", "at": 0.0,
                       "duration_s": 1.0, "params": {"delay_ms": 20.0}}])
    inj = ChaosInjector(plan, target="r0").arm(now=50.0)
    assert len(inj.fire("server.step", now=50.2)) == 1
    due = inj.fire("server.step", now=50.9)     # re-fires inside window
    assert len(due) == 1
    assert inj.delay_s(due) == pytest.approx(0.02)
    assert inj.fire("server.step", now=51.5) == []     # window closed
    assert inj.injected == 1        # ONE activation (one instant), many fires


def test_injector_unarmed_is_free():
    plan = FaultPlan([{"kind": "replica_crash", "at": 0.0}])
    inj = ChaosInjector(plan)
    assert not inj.armed and inj.fire("server.step") == []


def test_attach_chaos_wires_fleet_against_one_origin():
    reps = [types.SimpleNamespace(name=f"r{i}",
                                  server=types.SimpleNamespace(tracer=None),
                                  engine=types.SimpleNamespace())
            for i in range(2)]
    router = types.SimpleNamespace(tracer=None)
    plan = FaultPlan([{"kind": "replica_crash", "at": 1.0}])
    injs = attach_chaos(reps, plan, router=router)
    assert set(injs) == {"r0", "r1", "router"}
    assert all(i.armed for i in injs.values())
    assert len({i._t0 for i in injs.values()}) == 1    # shared clock
    for rep in reps:
        assert rep.server._chaos is injs[rep.name]
        assert rep.engine.chaos is injs[rep.name]
    assert router._chaos is injs["router"]


def test_chaos_error_is_not_a_typed_serving_outcome():
    # a ChaosError must ride the "unexpected crash" paths, not the typed
    # request-outcome taxonomy
    assert issubclass(ChaosError, RuntimeError)
    assert not issubclass(ChaosError, ServingError)


def test_train_chaos_env_contract(tmp_path):
    env = {"DSTPU_CHAOS": json.dumps({"rank": 1, "die_at": 3})}
    ckpt = str(tmp_path)
    assert TrainChaos.from_env(0, ckpt, env=env) is None   # other rank
    tc = TrainChaos.from_env(1, ckpt, env=env)
    assert tc is not None and tc.cfg["die_at"] == 3
    # the sentinel disarms every later incarnation (exactly-once)
    (tmp_path / CHAOS_SENTINEL).write_text("999")
    assert TrainChaos.from_env(1, ckpt, env=env) is None
    assert TrainChaos.from_env(1, ckpt, env={}) is None    # chaos off


# ---------------------------------------------------------------------------
# fleet supervisor state machine (fake replicas: public probe surface only)
# ---------------------------------------------------------------------------

class _FakeAdmission:
    def __init__(self):
        self.depth = 0
        self.cfg = types.SimpleNamespace(max_queue_size=8)

    def __len__(self):
        return self.depth


class _FakeServer:
    def __init__(self):
        self.loop_beat_t = time.monotonic()
        self.step_ema_s = 0.0
        self.admission = _FakeAdmission()
        self.brownout_level = "normal"

    def set_brownout(self, level):
        self.brownout_level = level


class _FakeReplica:
    def __init__(self, index, tier="unified"):
        self.index = index
        self.name = f"r{index}"
        self.tier = tier
        self.alive = True
        self.killed = False
        self.queue_load = 0
        self.kv_headroom = 1.0
        self.server = _FakeServer()

    def kill(self):
        self.alive = False
        self.killed = True


class _FakeSet(list):
    def __init__(self, reps, fail_respawn=False):
        super().__init__(reps)
        self.respawns = []
        self.fail_respawn = fail_respawn

    def respawn(self, index):
        if self.fail_respawn:
            raise RuntimeError("no capacity")
        if self[index].alive:
            raise RuntimeError(f"replica {index} still alive")
        fresh = _FakeReplica(index, self[index].tier)
        self[index] = fresh
        self.respawns.append(index)
        return fresh


class _FakeRouter:
    # no collapse_tiers: a plain (non-disagg) router has no tiers, and
    # the supervisor keys tier management off that attribute
    def __init__(self):
        self._mask = {}
        self.brownout = None

    def mask(self, index, cooldown_s=None):
        self._mask[index] = cooldown_s

    def unmask(self, index):
        self._mask.pop(index, None)

    def masked_indices(self):
        return set(self._mask)

    def set_brownout(self, level):
        self.brownout = level


class _FakeDisaggRouter(_FakeRouter):
    def __init__(self):
        super().__init__()
        self.collapsed = False
        self.collapse_calls = 0
        self.restore_calls = 0

    def collapse_tiers(self):
        self.collapsed = True
        self.collapse_calls += 1

    def restore_tiers(self):
        self.collapsed = False
        self.restore_calls += 1


def _sup(reps, router=None, **cfg):
    cfg.setdefault("suspect_ticks", 1)
    cfg.setdefault("manage_brownout", False)
    return FleetSupervisor(reps, router=router, config=cfg)


def test_supervisor_dead_replica_quarantined_and_respawned():
    reps = _FakeSet([_FakeReplica(0), _FakeReplica(1)])
    router = _FakeRouter()
    sup = _sup(reps, router, suspect_ticks=2)
    assert sup.tick() == {"r0": "healthy", "r1": "healthy"}
    reps[0].kill()
    assert sup.tick()["r0"] == "suspect"      # one miss is a race...
    states = sup.tick()                        # ...two is a corpse
    assert states["r0"] == "respawned"         # dead→quarantined→respawned
    seq = [e["state"] for e in sup.events if e["replica"] == "r0"]
    assert seq == ["suspect", "dead", "quarantined", "respawned"]
    assert all(s in HEALTH_STATES for s in seq)
    assert reps.respawns == [0] and reps[0].alive
    assert router.masked_indices() == set()    # unmasked after the heal
    assert sup.tick()["r0"] == "healthy"       # one clean tick closes it
    assert sup.heals == 1
    heal = next(e for e in sup.events if e["state"] == "respawned")
    assert heal["heal_s"] <= heal["deadline_s"]


def test_supervisor_stuck_probe_needs_queued_work():
    reps = _FakeSet([_FakeReplica(0), _FakeReplica(1)])
    sup = _sup(reps, stuck_after_s=5.0)
    now = time.monotonic()
    # idle replica with an ancient beat is NOT stuck (blocked in
    # wait_for_work is legitimate)...
    reps[0].server.loop_beat_t = now - 60.0
    assert sup.tick(now=now)["r0"] == "healthy"
    # ...but a stale beat WITH queued work is a wedge
    reps[0].queue_load = 3
    assert sup.tick(now=now)["r0"] == "respawned"
    assert [e["state"] for e in sup.events] == ["stuck", "quarantined",
                                                "respawned"]
    # the quarantine killed the hung thread before respawning
    assert reps.respawns == [0]


def test_supervisor_straggler_needs_sustained_evidence_and_peers():
    reps = _FakeSet([_FakeReplica(i) for i in range(4)])
    for r in reps:
        r.server.step_ema_s = 0.1
    reps[0].server.step_ema_s = 1.0            # 10x the peer median
    sup = _sup(reps, straggler_factor=4.0, straggler_ticks=2)
    assert sup.tick()["r0"] == "healthy"       # tick 1: evidence, no verdict
    assert sup.tick()["r0"] == "respawned"     # tick 2: sustained
    assert any(e["state"] == "straggler" for e in sup.events)


def test_supervisor_max_heals_fails_loudly():
    reps = _FakeSet([_FakeReplica(0), _FakeReplica(1)])
    sup = _sup(reps, max_heals=1)
    reps[0].kill()
    sup.tick()                                  # heal 1: within budget
    reps[1].kill()
    with pytest.raises(FleetHealFailed, match="budget exhausted"):
        sup.tick()
    with pytest.raises(FleetHealFailed):
        sup.check()                             # sticky, caller-visible
    assert any(e["state"] == "retired" for e in sup.events)


def test_supervisor_respawn_failure_retires():
    reps = _FakeSet([_FakeReplica(0), _FakeReplica(1)], fail_respawn=True)
    sup = _sup(reps)
    reps[0].kill()
    assert sup.tick()["r0"] == "retired"
    sup.check()                                 # retirement is not a raise


def test_supervisor_tier_collapse_and_restore():
    reps = _FakeSet([_FakeReplica(0, "prefill"), _FakeReplica(1, "prefill"),
                     _FakeReplica(2, "decode"), _FakeReplica(3, "decode")])
    router = _FakeDisaggRouter()
    sup = _sup(reps, router)
    reps[2].kill()
    reps[3].kill()
    states = sup.tick()
    # the tick that emptied the decode pool collapsed BEFORE healing
    # (the degraded window is real), then healing restored the tiers
    assert router.collapse_calls == 1 and router.restore_calls == 1
    assert sup.collapses == 1 and sup.restores == 1
    assert not router.collapsed
    assert states["r2"] == states["r3"] == "respawned"
    # one casualty does NOT collapse a tier that still has a survivor
    reps[0].kill()
    sup.tick()
    assert router.collapse_calls == 1


def test_supervisor_brownout_actuation_and_pressure():
    reps = _FakeSet([_FakeReplica(0), _FakeReplica(1)])
    router = _FakeRouter()
    sup = FleetSupervisor(reps, router=router, config={
        "suspect_ticks": 1,
        "brownout": {"enter": 0.8, "exit": 0.3, "dwell_s": 0.0}})
    assert sup.fleet_pressure() == 0.0
    reps[0].server.admission.depth = 8          # queue fraction 1.0
    assert sup.fleet_pressure() == 1.0
    sup.tick()
    assert router.brownout == "shed_speculation"    # one level per tick
    sup.tick()
    assert router.brownout == "cap_decode"
    # inside the hysteresis band the ladder holds
    reps[0].server.admission.depth = 4          # pressure 0.5
    sup.tick()
    assert router.brownout == "cap_decode"
    reps[0].server.admission.depth = 0
    sup.tick()
    assert router.brownout == "shed_speculation"
    sup.tick()
    assert router.brownout == "normal"
    levels = [e["level"] for e in sup.events if e["state"] == "brownout"]
    assert levels == ["shed_speculation", "cap_decode",
                      "shed_speculation", "normal"]


def test_supervisor_snapshot_shape():
    reps = _FakeSet([_FakeReplica(0)])
    sup = _sup(reps)
    sup.tick()
    snap = sup.snapshot()
    assert snap["states"] == {"r0": "healthy"}
    assert snap["brownout_level"] == "normal" and not snap["failed"]


# ---------------------------------------------------------------------------
# brownout ladder: monotone, hysteresis, no flapping
# ---------------------------------------------------------------------------

def test_brownout_controller_walks_one_level_with_dwell():
    bc = BrownoutController(BrownoutConfig(enter=0.8, exit=0.3,
                                           dwell_s=1.0))
    assert bc.level == "normal"
    assert bc.observe(0.95, now=0.0) == "shed_speculation"
    assert bc.observe(0.95, now=0.5) is None        # dwell holds
    assert bc.observe(0.95, now=1.1) == "cap_decode"
    assert bc.observe(0.95, now=2.2) == "shed_low_priority"
    assert bc.observe(0.95, now=3.3) == "reject_new"
    assert bc.observe(0.95, now=4.4) is None        # top of the ladder
    assert bc.level == "reject_new"
    # descent: one level per dwell once pressure clears the EXIT line
    assert bc.observe(0.5, now=5.5) is None          # hysteresis band
    for i, want in enumerate(["shed_low_priority", "cap_decode",
                              "shed_speculation", "normal"]):
        assert bc.observe(0.1, now=6.6 + i * 1.1) == want
    assert bc.observe(0.1, now=20.0) is None         # floor


def test_brownout_no_flap_around_one_threshold():
    bc = BrownoutController(BrownoutConfig(enter=0.8, exit=0.3,
                                           dwell_s=0.0))
    bc.observe(0.9, now=0.0)
    # pressure oscillating around the ENTER threshold inside the band
    # must not move the ladder in either direction
    for i in range(20):
        assert bc.observe(0.79 if i % 2 else 0.31, now=1.0 + i) is None
    assert bc.level == "shed_speculation"


def test_brownout_config_validates_band():
    with pytest.raises(ValueError, match="exit"):
        BrownoutConfig(enter=0.5, exit=0.6)
    assert [brownout_index(l) for l in BROWNOUT_LEVELS] == [0, 1, 2, 3, 4]


# ---------------------------------------------------------------------------
# shedding on a real serve loop: strictly the lowest-priority class
# ---------------------------------------------------------------------------

def _server(srv_cfg=None):
    from deepspeed_tpu.inference.v2 import build_engine
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.serving import InferenceServer

    model = get_model_config("llama-tiny", num_layers=1)
    eng = build_engine(model, ENG_CFG, seed=0)
    return model, InferenceServer(eng, srv_cfg or {})


def test_shed_low_priority_sheds_strictly_below_floor():
    from deepspeed_tpu.serving import SamplingParams

    model, srv = _server({"brownout": {"priority_floor": 0}})
    rng = np.random.default_rng(5)
    p = rng.integers(1, model.vocab_size, size=8).tolist()
    with srv:
        want = srv.generate([p], max_new_tokens=4)[0]
        srv.set_brownout("shed_low_priority")
        with pytest.raises(RequestShed):
            srv.submit(p, SamplingParams(max_new_tokens=4), priority=-1)
        # AT the floor is accepted — and the accepted request's greedy
        # output is exactly the fault-free one (degradation never
        # touches correctness)
        s = srv.submit(p, SamplingParams(max_new_tokens=4), priority=0)
        assert s.result(timeout=300) == want
        srv.set_brownout("reject_new")
        with pytest.raises(RequestShed):       # even high priority
            srv.submit(p, SamplingParams(max_new_tokens=4), priority=99)
        srv.set_brownout("normal")
        s = srv.submit(p, SamplingParams(max_new_tokens=4), priority=-1)
        assert s.result(timeout=300) == want
        m = srv.metrics.snapshot()
        assert m["shed"] == 2 and m["completed"] == 3


def test_queue_sweep_sheds_only_below_floor():
    from deepspeed_tpu.serving import SamplingParams

    model, srv = _server({"brownout": {"priority_floor": 0,
                                       "decode_cap": 1}})
    rng = np.random.default_rng(6)
    p = rng.integers(1, model.vocab_size, size=8).tolist()
    with srv:
        srv.generate([p], max_new_tokens=2)     # pay the compile
        # cap_decode holds admissions behind the filler, so the two
        # probes sit IN QUEUE when the ladder reaches shed_low_priority
        srv.set_brownout("cap_decode")
        filler = srv.submit(p, SamplingParams(max_new_tokens=24))
        deadline = time.monotonic() + 60
        while not srv._active and time.monotonic() < deadline:
            time.sleep(0.01)
        keep = srv.submit(p, SamplingParams(max_new_tokens=4), priority=0)
        low = srv.submit(p, SamplingParams(max_new_tokens=4), priority=-1)
        srv.set_brownout("shed_low_priority")
        with pytest.raises(RequestShed):        # swept from the queue
            low.result(timeout=300)
        srv.set_brownout("normal")
        assert len(filler.result(timeout=300)) == 24
        assert len(keep.result(timeout=300)) == 4    # survived the sweep
