"""Model family tests: shapes, loss sanity, determinism."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models import get_model_config, init_params, forward, loss_fn, count_params
from tests.conftest import make_lm_batch


@pytest.mark.parametrize("name", ["gpt2-tiny", "llama-tiny", "mixtral-tiny"])
def test_forward_shapes(name, rng):
    cfg = get_model_config(name, dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = make_lm_batch(rng, 2, 16, cfg.vocab_size)
    out = forward(params, jnp.asarray(batch["input_ids"]), cfg)
    logits = out[0] if isinstance(out, tuple) else out
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert np.all(np.isfinite(np.asarray(logits, dtype=np.float32)))


@pytest.mark.parametrize("name", ["gpt2-tiny", "llama-tiny", "mixtral-tiny"])
def test_loss_reasonable(name, rng):
    cfg = get_model_config(name, dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = {k: jnp.asarray(v) for k, v in make_lm_batch(rng, 2, 16, cfg.vocab_size).items()}
    loss = loss_fn(params, batch, cfg)
    # random init → loss ≈ ln(vocab)
    expected = np.log(cfg.vocab_size)
    assert abs(float(loss) - expected) < 2.0


def test_label_ignore_index(rng):
    cfg = get_model_config("gpt2-tiny", dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = make_lm_batch(rng, 2, 16, cfg.vocab_size)
    all_ignored = {"input_ids": jnp.asarray(batch["input_ids"]),
                   "labels": jnp.full_like(jnp.asarray(batch["labels"]), -100)}
    loss = loss_fn(params, all_ignored, cfg)
    assert float(loss) == 0.0


def test_param_count_gpt2_125m():
    cfg = get_model_config("gpt2-125m")
    shapes = jax.eval_shape(lambda k: init_params(cfg, k), jax.random.PRNGKey(0))
    n = sum(int(np.prod(s.shape)) for s in jax.tree.leaves(shapes))
    # 124M-class model (padded vocab)
    assert 110e6 < n < 140e6


def test_causality(rng):
    """Changing a future token must not affect earlier logits."""
    cfg = get_model_config("gpt2-tiny", dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(1, 16), dtype=np.int32))
    ids2 = ids.at[0, -1].set((ids[0, -1] + 1) % cfg.vocab_size)
    l1 = forward(params, ids, cfg)
    l2 = forward(params, ids2, cfg)
    np.testing.assert_allclose(np.asarray(l1[:, :-1]), np.asarray(l2[:, :-1]),
                               rtol=1e-5, atol=1e-5)


def test_gqa_heads():
    cfg = get_model_config("llama-tiny")
    assert cfg.kv_heads == 2 and cfg.num_heads == 4
    params = init_params(cfg, jax.random.PRNGKey(0))
    # wk second dim is kv_heads * head_dim
    assert params["layers"]["attn"]["wk"].shape == (cfg.num_layers, cfg.hidden_size,
                                                    cfg.kv_heads * cfg.dim_per_head)
