"""Encoder (BERT-class) training: bidirectional attention, post-LN stack,
MLM objective through the engine.  Ref: the reference's fused transformer
kernel exists to train BERT-class encoders
(ops/transformer/transformer.py:296 DeepSpeedTransformerLayer) and v1
injection serves bert/distil_bert (module_inject/containers)."""

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu as ds
from deepspeed_tpu.models import get_model_config
from deepspeed_tpu.models import transformer as tf
from deepspeed_tpu.parallel import topology


def _mlm_batch(cfg, rng, b=16, s=32, mask_frac=0.15, mask_id=3):
    """BERT-style MLM batch: 15% positions masked, labels = original ids
    at masked positions, -100 elsewhere (unshifted)."""
    ids = rng.integers(4, cfg.vocab_size, size=(b, s), dtype=np.int32)
    mask = rng.random((b, s)) < mask_frac
    mask[:, 0] = True  # ensure at least one target per row
    labels = np.where(mask, ids, -100).astype(np.int32)
    inputs = np.where(mask, mask_id, ids).astype(np.int32)
    return {"input_ids": inputs, "labels": labels}


def test_attention_is_bidirectional():
    cfg = get_model_config("bert-tiny", dtype=jnp.float32)
    params = tf.init_params(cfg, jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 512, (2, 16)),
                      jnp.int32)
    base = tf.forward(params, ids, cfg)
    flipped = tf.forward(params, ids.at[:, -1].set((ids[:, -1] + 1) % 512),
                         cfg)
    # flipping the LAST token must change the FIRST position's logits
    assert float(jnp.abs(flipped[:, 0] - base[:, 0]).max()) > 1e-6


def test_mlm_training_through_engine():
    """bert-tiny MLM training: loss drops, segment ids accepted, eval
    (no dropout key) deterministic."""
    model = get_model_config("bert-tiny", dropout=0.1)
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config, seed=2)
    rng = np.random.default_rng(0)
    batch = _mlm_batch(model, rng)
    batch["token_type_ids"] = np.zeros_like(batch["input_ids"])
    losses = [float(np.asarray(engine.train_batch(batch)))
              for _ in range(6)]
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], losses
    e1 = np.asarray(tf.forward(engine.params, batch["input_ids"][:2],
                               engine.model_config))
    e2 = np.asarray(tf.forward(engine.params, batch["input_ids"][:2],
                               engine.model_config))
    np.testing.assert_array_equal(e1, e2)
    topology._GLOBAL_TOPOLOGY = None


def test_mlm_training_zero3_tensor():
    """Encoder composes with ZeRO-3 + tensor parallelism."""
    model = get_model_config("bert-tiny")
    config = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3},
        "mesh": {"data": 4, "tensor": 2},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config, seed=3)
    rng = np.random.default_rng(1)
    batch = _mlm_batch(model, rng)
    losses = [float(np.asarray(engine.train_batch(batch)))
              for _ in range(4)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0], losses
    topology._GLOBAL_TOPOLOGY = None


def test_mlm_training_pipeline():
    """Encoder + pipeline parallelism: post-LN/MLM-head models route to
    the AD-differentiated GPipe path (the 1F1B tail assumes the decoder
    head) and still train."""
    model = get_model_config("bert-tiny", dropout=0.1)
    config = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "mesh": {"pipe": 2, "data": 4},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config, seed=4)
    rng = np.random.default_rng(2)
    batch = _mlm_batch(model, rng)
    losses = [float(np.asarray(engine.train_batch(batch)))
              for _ in range(3)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0], losses
    topology._GLOBAL_TOPOLOGY = None


def test_padding_mask_excludes_pad_tokens():
    """attention_mask=0 keys cannot influence kept positions."""
    cfg = get_model_config("bert-tiny", dtype=jnp.float32)
    params = tf.init_params(cfg, jax.random.PRNGKey(1))
    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(0, 512, (2, 16)), jnp.int32)
    mask = np.ones((2, 16), np.int32)
    mask[:, 12:] = 0
    out1 = tf.forward(params, ids, cfg, attention_mask=jnp.asarray(mask))
    # change the PAD region's ids: kept positions must be unaffected
    ids2 = ids.at[:, 12:].set(7)
    out2 = tf.forward(params, ids2, cfg, attention_mask=jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(out1[:, :12]),
                               np.asarray(out2[:, :12]), atol=1e-6)
