"""Flops profiler (XLA cost analysis + analytic) and autotuner.

Mirrors reference coverage in tests/unit/profiling/ and
tests/unit/autotuning/."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.autotuning import (Autotuner, ModelInfo,
                                      estimate_memory_per_device,
                                      generate_tuning_space)
from deepspeed_tpu.models import get_model_config
from deepspeed_tpu.profiling import get_model_profile, mfu, profile_compiled


def test_profile_compiled_reports_flops():
    n = 64

    @jax.jit
    def f(a, b):
        return a @ b

    a = jnp.ones((n, n), jnp.float32)
    prof = profile_compiled(f, a, a)
    # 2*n^3 matmul flops (cost model may add epsilon elementwise)
    assert prof.get("flops", 0) >= 2 * n ** 3 * 0.9


def test_analytic_model_profile():
    cfg = get_model_config("gpt2-125m")
    prof = get_model_profile(cfg, batch_size=1, seq_len=1024)
    # GPT-2 125M: ~124M params
    assert 100e6 < prof["params"] < 165e6
    # ~6*N flops per token fwd+bwd (within 2x, attention adds seq term)
    per_tok = prof["total_flops_per_step"] / 1024
    assert 4 * prof["params"] < per_tok < 12 * prof["params"]
    assert prof["breakdown_per_layer"]["mlp"] > 0
    assert mfu(prof["total_flops_per_step"], 1.0, 1e15) > 0


def test_engine_flops_profiler_integration():
    model = get_model_config("gpt2-tiny")
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
           "mesh": {"data": 1},
           "flops_profiler": {"enabled": True, "profile_step": 1}}
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(2, 17), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
    engine.train_batch(batch)
    prof = engine._last_flops_profile
    assert prof is not None and prof.get("flops", 0) > 0
    assert "analytic" in prof
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None


def test_memory_estimates_monotone_in_stage():
    mi = ModelInfo(num_params=10**9, hidden_size=2048, num_layers=24,
                   vocab_size=50000)
    sizes = [estimate_memory_per_device(mi, s, dp_size=8, micro_batch=1,
                                        seq_len=1024) for s in (0, 1, 2, 3)]
    assert sizes[0] > sizes[1] > sizes[2] > sizes[3]
    # stage 3 with dp=8 shards everything
    assert sizes[3] < sizes[0] / 4


def test_tuning_space_respects_budget():
    mi = ModelInfo(num_params=10**8, hidden_size=512, num_layers=8,
                   vocab_size=32000)
    space = generate_tuning_space(mi, dp_size=4, seq_len=512,
                                  hbm_bytes=4 << 30)
    assert space
    assert all(c["est_bytes"] <= 4 << 30 for c in space)
    # tight budget shrinks the space
    tight = generate_tuning_space(mi, dp_size=4, seq_len=512,
                                  hbm_bytes=1 << 28)
    assert len(tight) < len(space)


@pytest.mark.slow
def test_autotuner_end_to_end():
    model = get_model_config("gpt2-tiny")
    # in-process trials: subprocess isolation is covered by its own test;
    # under full-suite load a fresh jax-loading subprocess per trial can
    # starve on a single-core box and time out spuriously
    tuner = Autotuner(model, {"optimizer": {"type": "AdamW",
                                            "params": {"lr": 1e-3}},
                              "mesh": {"data": 1}},
                      seq_len=16, mode="model_based", max_trials=2,
                      steps_per_trial=1, isolate_trials=False)
    best, results = tuner.tune()
    assert results and any(r.throughput > 0 for r in results)
    assert "train_micro_batch_size_per_gpu" in best
    assert best["zero_optimization"]["stage"] in (0, 1, 2, 3)


def test_enumerate_meshes_validity():
    """Mesh sweep candidates must respect model divisibility (ref
    autotuner.py:278 tuning-space generation extended with tp/pp/sp/ep)."""
    from deepspeed_tpu.autotuning.autotuner import enumerate_meshes
    from deepspeed_tpu.models import get_model_config

    model = get_model_config("llama-tiny")  # 4 heads, 2 kv, 2 layers
    meshes = enumerate_meshes(8, model)
    assert {"data": 8} in meshes
    for m in meshes:
        n = 1
        for v in m.values():
            n *= v
        assert n == 8
        assert model.num_heads % m.get("tensor", 1) == 0
        assert model.num_kv_heads % m.get("tensor", 1) == 0
        assert model.num_layers % m.get("pipe", 1) == 0
        assert model.num_heads % m.get("seq", 1) == 0
        assert "expert" not in m  # dense model: no expert axis
    # tp=2 and pipe=2 variants must be present (divisible), tp=8 absent
    assert any(m.get("tensor") == 2 for m in meshes)
    assert any(m.get("pipe") == 2 for m in meshes)
    assert not any(m.get("tensor", 1) == 8 for m in meshes)
    # MoE model gets expert factorizations
    moe = get_model_config("mixtral-tiny")  # 4 experts
    assert any(m.get("expert", 1) > 1 for m in enumerate_meshes(8, moe))


@pytest.mark.slow
def test_autotuner_mesh_sweep_runs_trials():
    """tune_mesh=True sweeps mesh shapes (the highest-leverage TPU knobs)
    and lands on a runnable config; non-data axes appear in the space."""
    from deepspeed_tpu.autotuning.autotuner import Autotuner
    from deepspeed_tpu.models import get_model_config

    model = get_model_config("llama-tiny")
    base = {"optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "steps_per_print": 1000}
    tuner = Autotuner(model, base, seq_len=32, mode="random", max_trials=3,
                      steps_per_trial=1, tune_mesh=True, n_devices=8, seed=3,
                      isolate_trials=False)
    space = tuner._space()
    assert any(c["mesh"] != {"data": 8} for c in space)
    best_cfg, results = tuner.tune()
    assert any(r.throughput > 0 for r in results)
    assert "mesh" in best_cfg and "zero_optimization" in best_cfg


def test_autotuner_subprocess_isolation_contains_crash():
    """A candidate whose trial subprocess dies (here: config error at
    engine init) must score 0 without killing the tuner — the property
    that matters for hard XLA aborts (ref: experiments as separate jobs,
    autotuner.py:404)."""
    from deepspeed_tpu.autotuning.autotuner import Autotuner
    from deepspeed_tpu.models import get_model_config

    model = get_model_config("gpt2-tiny")
    # super_offload + a non-Adam optimizer raises DeepSpeedConfigError in
    # the subprocess before any compilation: a fast, deterministic death
    base = {"optimizer": {"type": "lamb", "params": {"lr": 1e-3}},
            "mesh": {"data": 1},
            "zero_optimization": {"offload_optimizer": {
                "device": "cpu", "super_offload": True}}}
    tuner = Autotuner(model, base, seq_len=16, mode="grid", max_trials=1,
                      steps_per_trial=1, isolate_trials=True)
    cand = tuner._space()[0]
    res = tuner.run_trial(cand)
    assert res.throughput == 0.0 and res.error


def test_enumerate_meshes_divisor_enumeration():
    """Satellite: exhaustive divisor enumeration — every candidate
    factorizes the device count exactly, no duplicates, every
    model-admissible tensor divisor appears, and pruned axes never leak
    a size-1 entry."""
    from types import SimpleNamespace

    from deepspeed_tpu.autotuning.autotuner import enumerate_meshes

    permissive = SimpleNamespace(num_heads=24, num_kv_heads=24,
                                 num_layers=24, num_experts=0)
    meshes = enumerate_meshes(8, permissive)
    seen = set()
    for m in meshes:
        n = 1
        for v in m.values():
            n *= v
        assert n == 8, m
        # only "data" may carry 1 (it is always present); the sweep never
        # emits tensor/pipe/seq/expert entries of size 1
        assert all(v > 1 for k, v in m.items() if k != "data"), m
        key = tuple(sorted(m.items()))
        assert key not in seen, f"duplicate mesh {m}"
        seen.add(key)
    # a fully-divisible model admits every divisor of n on each axis
    assert sorted({m.get("tensor", 1) for m in meshes}) == [1, 2, 4, 8]
    assert sorted({m.get("pipe", 1) for m in meshes}) == [1, 2, 4, 8]
    # non-power-of-two device counts enumerate their true divisors
    assert sorted({m.get("tensor", 1) for m in
                   enumerate_meshes(6, permissive)}) == [1, 2, 3, 6]
    # degenerate world: exactly the pure-data mesh
    assert enumerate_meshes(1, permissive) == [{"data": 1}]


def test_memory_estimate_stage_monotonicity_edges():
    """Satellite: estimate_memory_per_device is monotone non-increasing
    in zero_stage at any dp, EQUAL across stages at dp=1 (nothing to
    shard), and each stage increment shrinks exactly its own term."""
    from deepspeed_tpu.autotuning.autotuner import (
        BYTES_PER_PARAM, estimate_memory_per_device)

    mi = ModelInfo(num_params=10**8, hidden_size=1024, num_layers=12,
                   vocab_size=32000)
    kw = dict(micro_batch=2, seq_len=256)
    # dp=1: stages are indistinguishable
    at_dp1 = [estimate_memory_per_device(mi, s, dp_size=1, **kw)
              for s in (0, 1, 2, 3)]
    assert len(set(at_dp1)) == 1
    # dp=8: strictly decreasing, and each step removes (dp-1)/dp of the
    # corresponding state term
    at_dp8 = [estimate_memory_per_device(mi, s, dp_size=8, **kw)
              for s in (0, 1, 2, 3)]
    assert at_dp8[0] > at_dp8[1] > at_dp8[2] > at_dp8[3]
    opt_full = mi.num_params * 12
    assert at_dp8[0] - at_dp8[1] == opt_full - opt_full // 8
    grads_full = mi.num_params * BYTES_PER_PARAM["bf16"]
    assert at_dp8[1] - at_dp8[2] == grads_full - grads_full // 8
    assert at_dp8[2] - at_dp8[3] == grads_full - grads_full // 8  # params


def test_generate_tuning_space_enumeration_rules():
    """Satellite: candidate micro-batches are the power-of-two ladder up
    to the cap, pipeline meshes prune stages >= 2, and the seq axis
    prunes non-divisible sequence lengths."""
    mi = ModelInfo(num_params=10**6, hidden_size=64, num_layers=4,
                   vocab_size=1000)
    space = generate_tuning_space(mi, dp_size=2, seq_len=64,
                                  hbm_bytes=1 << 40, max_micro_batch=8)
    mbs = sorted({c["micro_batch"] for c in space})
    assert mbs == [1, 2, 4, 8]  # the cap itself is included (no
    #                             off-by-one at the ladder top)
    assert {c["zero_stage"] for c in space} == {0, 1, 2, 3}
    # pipeline composes with ZeRO-0/1 only
    pp_space = generate_tuning_space(
        mi, dp_size=1, seq_len=64, hbm_bytes=1 << 40, max_micro_batch=2,
        meshes=[{"data": 1, "pipe": 2}])
    assert pp_space and {c["zero_stage"] for c in pp_space} == {0, 1}
    # a seq mesh that does not divide the sequence length yields nothing
    assert generate_tuning_space(
        mi, dp_size=1, seq_len=63, hbm_bytes=1 << 40,
        meshes=[{"data": 1, "seq": 2}]) == []
