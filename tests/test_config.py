"""Config system tests (ref test model: tests/unit/runtime/test_ds_config_dict.py)."""

import pytest

from deepspeed_tpu.runtime.config import DeepSpeedConfig, DeepSpeedConfigError


def test_batch_resolution_infer_gas():
    cfg = DeepSpeedConfig({"train_batch_size": 32, "train_micro_batch_size_per_gpu": 4},
                          world_size=2)
    assert cfg.gradient_accumulation_steps == 4
    assert cfg.train_batch_size == 32


def test_batch_resolution_infer_train():
    cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 2,
                           "gradient_accumulation_steps": 3}, world_size=4)
    assert cfg.train_batch_size == 24


def test_batch_resolution_infer_micro():
    cfg = DeepSpeedConfig({"train_batch_size": 16, "gradient_accumulation_steps": 2},
                          world_size=4)
    assert cfg.train_micro_batch_size_per_gpu == 2


def test_batch_inconsistent_raises():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 10, "train_micro_batch_size_per_gpu": 4},
                        world_size=2)


def test_no_batch_raises():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({}, world_size=1)


def test_zero_config():
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "zero_optimization": {
            "stage": 3,
            "offload_optimizer": {"device": "cpu", "pin_memory": True},
            "reduce_bucket_size": 1000,
        },
    })
    assert cfg.zero_config.stage == 3
    assert cfg.zero_config.offload_optimizer_device == "cpu"
    assert cfg.zero_enabled


def test_zero_stage_range():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 8, "zero_optimization": {"stage": 4}})


def test_fp16_bf16_conflict():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 8, "fp16": {"enabled": True},
                         "bf16": {"enabled": True}})


def test_fp16_dynamic_scale():
    cfg = DeepSpeedConfig({"train_batch_size": 8,
                           "fp16": {"enabled": True, "initial_scale_power": 12}})
    assert cfg.fp16.dynamic
    assert cfg.fp16.initial_scale_power == 12


def test_optimizer_scheduler_blocks():
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 3e-4, "betas": [0.9, 0.95]}},
        "scheduler": {"type": "WarmupLR", "params": {"warmup_num_steps": 100}},
    })
    assert cfg.optimizer.type == "adamw"
    assert cfg.optimizer.lr == 3e-4
    assert cfg.scheduler.type == "WarmupLR"


def test_mesh_resolution():
    cfg = DeepSpeedConfig({"train_batch_size": 8, "mesh": {"tensor": 2, "data": -1}})
    sizes = cfg.mesh.resolved(8)
    assert sizes["tensor"] == 2 and sizes["data"] == 4


def test_mesh_from_tp_config():
    cfg = DeepSpeedConfig({"train_batch_size": 8, "tensor_parallel": {"autotp_size": 4}})
    assert cfg.mesh.tensor == 4


def test_unknown_keys_ignored():
    cfg = DeepSpeedConfig({"train_batch_size": 8, "fp16": {"enabled": True, "bogus": 1}})
    assert cfg.fp16.enabled


def test_deprecated_cpu_offload_alias():
    cfg = DeepSpeedConfig({"train_batch_size": 8,
                           "zero_optimization": {"stage": 2, "cpu_offload": True}})
    assert cfg.zero_config.offload_optimizer_device == "cpu"


def test_torch_autocast_selects_compute_dtype():
    """ref runtime/torch_autocast.py config surface: enabling autocast
    picks the compute dtype; per-op fp32 islands (norms/softmax/router)
    are the built-in model policy."""
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    c = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                         "torch_autocast": {"enabled": True,
                                            "dtype": "bfloat16"}})
    assert c.bf16.enabled and not c.fp16.enabled
    c2 = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                          "torch_autocast": {"enabled": True,
                                             "dtype": "float16"}})
    assert c2.fp16.enabled and not c2.bf16.enabled


def test_config_fuzz_never_crashes():
    """Malformed-but-dict-shaped configs must produce DeepSpeedConfigError
    or parse with warnings — never an unhandled exception (the reference's
    pydantic layer gives the same guarantee)."""
    import random

    from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                              DeepSpeedConfigError)

    rng = random.Random(0)
    blocks = ["optimizer", "scheduler", "fp16", "bf16", "zero_optimization",
              "torch_autocast", "profiler", "activation_checkpointing",
              "flops_profiler", "pipeline", "tensor_parallel", "mesh"]
    junk_values = [None, 0, -3, 1.5, "x", [], [1, 2], {}, {"bogus": 1},
                   {"enabled": "yes"}, {"stage": 99}]
    for trial in range(60):
        cfg = {"train_micro_batch_size_per_gpu": 1}
        for b in rng.sample(blocks, rng.randint(1, 4)):
            cfg[b] = rng.choice(junk_values)
        try:
            DeepSpeedConfig(cfg)
        except DeepSpeedConfigError:
            pass  # typed rejection is the contract
        except (TypeError, ValueError) as e:
            # dataclass coercion failures are acceptable only when they
            # carry the offending context in the message
            assert str(e), f"silent {type(e).__name__} for {cfg}"
