"""Offload tests: host-RAM optimizer/param offload via memory kinds, partial
(TwinFlow) ratio, NVMe swapping via the native AIO engine, offload_states
API, and raw AIO round-trips (ref test model: tests/unit/runtime/zero/
test_offload_states & tests/unit/ops/aio)."""

import os

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import get_model_config
from tests.conftest import make_lm_batch


def _cfg(**over):
    cfg = {
        "train_batch_size": 8,
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "steps_per_print": 1000,
        "mesh": {"data": 8},
    }
    for k, v in over.items():
        if k == "zero_optimization":
            cfg["zero_optimization"].update(v)
        else:
            cfg[k] = v
    return cfg


def _mk(model, cfg, seed=3):
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None
    engine, _, _, _ = ds.initialize(model=model, config=cfg, seed=seed)
    return engine


def _train(engine, batches):
    return [float(np.asarray(engine.train_batch(b))) for b in batches]


def _batches(model, n=4):
    rng = np.random.default_rng(0)
    return [make_lm_batch(rng, 8, 16, model.vocab_size)] * n


def _memory_kinds(tree):
    return {x.sharding.memory_kind for x in jax.tree.leaves(tree)
            if hasattr(x, "sharding")}


def test_cpu_offload_matches_baseline():
    """Offload is placement only — numerics must equal the non-offload run.
    On the CPU test backend the engine takes the host-store fallback (memory
    kinds under SPMD are unimplemented there); on TPU it streams via
    pinned_host memory kinds. Both paths are numerics-preserving."""
    model = get_model_config("gpt2-tiny")
    batches = _batches(model)
    base = _train(_mk(model, _cfg()), batches)
    eng = _mk(model, _cfg(zero_optimization={"offload_optimizer": {"device": "cpu"}}))
    if eng._opt_stream_offload:
        assert "pinned_host" in _memory_kinds(eng.opt_state)
    else:
        assert eng.opt_state is None and eng._opt_store is not None
    off = _train(eng, batches)
    np.testing.assert_allclose(base, off, rtol=1e-5, atol=1e-5)


def test_partial_offload_shardings_split():
    """The TwinFlow ratio splits leaves host/device by size (unit-level; the
    streaming mode that consumes these shardings is TPU-only)."""
    import jax
    from deepspeed_tpu.runtime.offload import partial_offload_shardings
    from deepspeed_tpu.parallel.topology import MeshTopology
    from jax.sharding import NamedSharding, PartitionSpec as P

    topo = MeshTopology({"data": 8})
    dev = {"big": NamedSharding(topo.mesh, P()), "small": NamedSharding(topo.mesh, P()),
           "count": NamedSharding(topo.mesh, P())}
    shapes = {"big": jax.ShapeDtypeStruct((1024, 64), np.float32),
              "small": jax.ShapeDtypeStruct((8,), np.float32),
              "count": jax.ShapeDtypeStruct((), np.int32)}
    # jax builds where the CPU backend has no pinned_host memory space
    # degrade the placement to a no-op (with_memory_kind guards it) — the
    # size-ordered split itself is what this test pins down
    try:
        NamedSharding(topo.mesh, P()).with_memory_kind("pinned_host")
        host_kind = "pinned_host"
    except ValueError:
        host_kind = dev["big"].memory_kind
    out = partial_offload_shardings(shapes, dev, 0.5)
    assert out["big"].memory_kind == host_kind
    assert out["small"].memory_kind != "pinned_host"
    assert out["count"].memory_kind != "pinned_host"  # scalars never offload
    full = partial_offload_shardings(shapes, dev, 1.0)
    assert full["small"].memory_kind == host_kind
    assert full["count"].memory_kind != "pinned_host"


def test_param_offload():
    model = get_model_config("gpt2-tiny")
    eng = _mk(model, _cfg(zero_optimization={
        "stage": 3, "offload_param": {"device": "cpu"}}))
    from deepspeed_tpu.runtime.offload import host_offload_supported

    if host_offload_supported(eng.topology):
        assert _memory_kinds(eng.params) == {"pinned_host"}
    losses = _train(eng, _batches(model, 2))
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]


def test_nvme_offload_matches_baseline(tmp_path):
    model = get_model_config("gpt2-tiny")
    batches = _batches(model)
    base = _train(_mk(model, _cfg()), batches)
    eng = _mk(model, _cfg(zero_optimization={
        "offload_optimizer": {"device": "nvme", "nvme_path": str(tmp_path)}}))
    assert eng.opt_state is None  # NVMe is authoritative between steps
    assert os.listdir(str(tmp_path))  # swap files exist
    off = _train(eng, batches)
    np.testing.assert_allclose(base, off, rtol=1e-5, atol=1e-5)


def test_nvme_checkpoint(tmp_path):
    model = get_model_config("gpt2-tiny")
    swap = tmp_path / "swap"
    eng = _mk(model, _cfg(zero_optimization={
        "offload_optimizer": {"device": "nvme", "nvme_path": str(swap)}}))
    _train(eng, _batches(model, 2))
    eng.save_checkpoint(str(tmp_path / "ck"), tag="t")
    eng2 = _mk(model, _cfg(), seed=9)
    eng2.load_checkpoint(str(tmp_path / "ck"), tag="t")
    assert eng2.global_steps == 2


def test_store_mode_checkpoint_roundtrip_restores_optimizer(tmp_path):
    """Loading a checkpoint into an offload-store engine must push the loaded
    optimizer state into the store — continuation numerics must match a
    non-offload engine continuing from the same checkpoint."""
    model = get_model_config("gpt2-tiny")
    batches = _batches(model, 6)
    off_cfg = _cfg(zero_optimization={
        "offload_optimizer": {"device": "nvme", "nvme_path": str(tmp_path / "sw")}})
    eng = _mk(model, off_cfg)
    _train(eng, batches[:3])
    eng.save_checkpoint(str(tmp_path / "ck"), tag="t")

    # plain engine continues from checkpoint
    ref = _mk(model, _cfg(), seed=11)
    ref.load_checkpoint(str(tmp_path / "ck"), tag="t")
    ref_cont = _train(ref, batches[3:])

    # offload-store engine continues from the same checkpoint
    eng2 = _mk(model, _cfg(zero_optimization={
        "offload_optimizer": {"device": "nvme", "nvme_path": str(tmp_path / "sw2")}}),
        seed=22)
    eng2.load_checkpoint(str(tmp_path / "ck"), tag="t")
    off_cont = _train(eng2, batches[3:])
    np.testing.assert_allclose(ref_cont, off_cont, rtol=1e-5, atol=1e-5)


def test_offload_states_api():
    from deepspeed_tpu.runtime.offload import host_offload_supported

    model = get_model_config("gpt2-tiny")
    eng = _mk(model, _cfg())
    if not host_offload_supported(eng.topology):
        pytest.skip("memory-kind offload unsupported on this backend")
    eng.offload_states()
    assert _memory_kinds(eng.params) == {"pinned_host"}
    eng.reload_states()
    assert _memory_kinds(eng.params) == {"device"}
    losses = _train(eng, _batches(model, 2))
    assert all(np.isfinite(losses))


def test_aio_roundtrip(tmp_path):
    from deepspeed_tpu.ops.aio import AsyncIOHandle

    h = AsyncIOHandle(block_size=1 << 16, queue_depth=4, thread_count=2)
    x = np.random.default_rng(0).standard_normal((1 << 18,)).astype(np.float32)
    path = str(tmp_path / "t.bin")
    h.pwrite(x, path)
    y = np.empty_like(x)
    h.pread(y, path)
    np.testing.assert_array_equal(x, y)


@pytest.mark.parametrize("nbytes", [1 << 18, (1 << 18) + 100, 4096, 100])
def test_aio_direct_roundtrip(tmp_path, nbytes):
    """O_DIRECT path (page-cache bypass): aligned body + buffered tail,
    incl. sub-block and unaligned sizes; falls back transparently where the
    FS rejects O_DIRECT (ref csrc/aio O_DIRECT discipline)."""
    from deepspeed_tpu.ops.aio import AsyncIOHandle

    h = AsyncIOHandle(block_size=1 << 16, queue_depth=4, thread_count=2,
                      use_direct=True)
    x = np.random.default_rng(1).integers(
        0, 255, size=nbytes, dtype=np.uint8)
    path = str(tmp_path / "d.bin")
    h.pwrite(x, path)
    assert os.path.getsize(path) == nbytes
    y = np.empty_like(x)
    h.pread(y, path)
    np.testing.assert_array_equal(x, y)
    # buffered handle reads back the O_DIRECT-written file identically
    hb = AsyncIOHandle(block_size=1 << 16, queue_depth=4, thread_count=2)
    z = np.empty_like(x)
    hb.pread(z, path)
    np.testing.assert_array_equal(x, z)


def test_aio_async_overlap(tmp_path):
    from deepspeed_tpu.ops.aio import AsyncIOHandle

    h = AsyncIOHandle(thread_count=4)
    arrays = [np.full((1 << 16,), i, np.float32) for i in range(8)]
    for i, a in enumerate(arrays):
        h.async_pwrite(a, str(tmp_path / f"f{i}.bin"))
    assert h.wait() == 0
    outs = [np.empty_like(a) for a in arrays]
    for i, o in enumerate(outs):
        h.async_pread(o, str(tmp_path / f"f{i}.bin"))
    assert h.wait() == 0
    for a, o in zip(arrays, outs):
        np.testing.assert_array_equal(a, o)


def test_aio_missing_file_reports_error(tmp_path):
    from deepspeed_tpu.ops.aio import AsyncIOHandle

    h = AsyncIOHandle()
    buf = np.empty((1024,), np.float32)
    with pytest.raises(IOError):
        h.pread(buf, str(tmp_path / "missing.bin"))
