"""Offload tests: host-RAM optimizer/param offload via memory kinds, partial
(TwinFlow) ratio, NVMe swapping via the native AIO engine, offload_states
API, and raw AIO round-trips (ref test model: tests/unit/runtime/zero/
test_offload_states & tests/unit/ops/aio)."""

import os

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import get_model_config
from tests.conftest import make_lm_batch


def _cfg(**over):
    cfg = {
        "train_batch_size": 8,
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "steps_per_print": 1000,
        "mesh": {"data": 8},
    }
    for k, v in over.items():
        if k == "zero_optimization":
            cfg["zero_optimization"].update(v)
        else:
            cfg[k] = v
    return cfg


def _mk(model, cfg, seed=3):
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None
    engine, _, _, _ = ds.initialize(model=model, config=cfg, seed=seed)
    return engine


def _train(engine, batches):
    return [float(np.asarray(engine.train_batch(b))) for b in batches]


def _batches(model, n=4):
    rng = np.random.default_rng(0)
    return [make_lm_batch(rng, 8, 16, model.vocab_size)] * n


def _memory_kinds(tree):
    return {x.sharding.memory_kind for x in jax.tree.leaves(tree)
            if hasattr(x, "sharding")}


def test_cpu_offload_matches_baseline():
    """Offload is placement only — numerics must equal the non-offload run.
    On the CPU test backend the engine takes the host-store fallback (memory
    kinds under SPMD are unimplemented there); on TPU it streams via
    pinned_host memory kinds. Both paths are numerics-preserving."""
    model = get_model_config("gpt2-tiny")
    batches = _batches(model)
    base = _train(_mk(model, _cfg()), batches)
    eng = _mk(model, _cfg(zero_optimization={"offload_optimizer": {"device": "cpu"}}))
    if eng._opt_stream_offload:
        assert "pinned_host" in _memory_kinds(eng.opt_state)
    else:
        assert eng.opt_state is None and eng._opt_store is not None
    off = _train(eng, batches)
    np.testing.assert_allclose(base, off, rtol=1e-5, atol=1e-5)


def test_partial_offload_shardings_split():
    """The TwinFlow ratio splits leaves host/device by size (unit-level; the
    streaming mode that consumes these shardings is TPU-only)."""
    import jax
    from deepspeed_tpu.runtime.offload import partial_offload_shardings
    from deepspeed_tpu.parallel.topology import MeshTopology
    from jax.sharding import NamedSharding, PartitionSpec as P

    topo = MeshTopology({"data": 8})
    dev = {"big": NamedSharding(topo.mesh, P()), "small": NamedSharding(topo.mesh, P()),
           "count": NamedSharding(topo.mesh, P())}
    shapes = {"big": jax.ShapeDtypeStruct((1024, 64), np.float32),
              "small": jax.ShapeDtypeStruct((8,), np.float32),
              "count": jax.ShapeDtypeStruct((), np.int32)}
    # jax builds where the CPU backend has no pinned_host memory space
    # degrade the placement to a no-op (with_memory_kind guards it) — the
    # size-ordered split itself is what this test pins down
    try:
        NamedSharding(topo.mesh, P()).with_memory_kind("pinned_host")
        host_kind = "pinned_host"
    except ValueError:
        host_kind = dev["big"].memory_kind
    out = partial_offload_shardings(shapes, dev, 0.5)
    assert out["big"].memory_kind == host_kind
    assert out["small"].memory_kind != "pinned_host"
    assert out["count"].memory_kind != "pinned_host"  # scalars never offload
    full = partial_offload_shardings(shapes, dev, 1.0)
    assert full["small"].memory_kind == host_kind
    assert full["count"].memory_kind != "pinned_host"


def test_param_offload():
    model = get_model_config("gpt2-tiny")
    eng = _mk(model, _cfg(zero_optimization={
        "stage": 3, "offload_param": {"device": "cpu"}}))
    from deepspeed_tpu.runtime.offload import host_offload_supported

    if host_offload_supported(eng.topology):
        assert _memory_kinds(eng.params) == {"pinned_host"}
    losses = _train(eng, _batches(model, 2))
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]


def test_nvme_offload_matches_baseline(tmp_path):
    model = get_model_config("gpt2-tiny")
    batches = _batches(model)
    base = _train(_mk(model, _cfg()), batches)
    eng = _mk(model, _cfg(zero_optimization={
        "offload_optimizer": {"device": "nvme", "nvme_path": str(tmp_path)}}))
    assert eng.opt_state is None  # NVMe is authoritative between steps
    assert os.listdir(str(tmp_path))  # swap files exist
    off = _train(eng, batches)
    np.testing.assert_allclose(base, off, rtol=1e-5, atol=1e-5)


def test_nvme_checkpoint(tmp_path):
    model = get_model_config("gpt2-tiny")
    swap = tmp_path / "swap"
    eng = _mk(model, _cfg(zero_optimization={
        "offload_optimizer": {"device": "nvme", "nvme_path": str(swap)}}))
    _train(eng, _batches(model, 2))
    eng.save_checkpoint(str(tmp_path / "ck"), tag="t")
    eng2 = _mk(model, _cfg(), seed=9)
    eng2.load_checkpoint(str(tmp_path / "ck"), tag="t")
    assert eng2.global_steps == 2


def test_store_mode_checkpoint_roundtrip_restores_optimizer(tmp_path):
    """Loading a checkpoint into an offload-store engine must push the loaded
    optimizer state into the store — continuation numerics must match a
    non-offload engine continuing from the same checkpoint."""
    model = get_model_config("gpt2-tiny")
    batches = _batches(model, 6)
    off_cfg = _cfg(zero_optimization={
        "offload_optimizer": {"device": "nvme", "nvme_path": str(tmp_path / "sw")}})
    eng = _mk(model, off_cfg)
    _train(eng, batches[:3])
    eng.save_checkpoint(str(tmp_path / "ck"), tag="t")

    # plain engine continues from checkpoint
    ref = _mk(model, _cfg(), seed=11)
    ref.load_checkpoint(str(tmp_path / "ck"), tag="t")
    ref_cont = _train(ref, batches[3:])

    # offload-store engine continues from the same checkpoint
    eng2 = _mk(model, _cfg(zero_optimization={
        "offload_optimizer": {"device": "nvme", "nvme_path": str(tmp_path / "sw2")}}),
        seed=22)
    eng2.load_checkpoint(str(tmp_path / "ck"), tag="t")
    off_cont = _train(eng2, batches[3:])
    np.testing.assert_allclose(ref_cont, off_cont, rtol=1e-5, atol=1e-5)


def test_offload_states_api():
    from deepspeed_tpu.runtime.offload import host_offload_supported

    model = get_model_config("gpt2-tiny")
    eng = _mk(model, _cfg())
    if not host_offload_supported(eng.topology):
        pytest.skip("memory-kind offload unsupported on this backend")
    eng.offload_states()
    assert _memory_kinds(eng.params) == {"pinned_host"}
    eng.reload_states()
    assert _memory_kinds(eng.params) == {"device"}
    losses = _train(eng, _batches(model, 2))
    assert all(np.isfinite(losses))


def test_with_memory_kind_degrades_with_one_warning():
    """Where the backend has no such memory space, with_memory_kind must
    degrade to the original sharding AND flip the once-per-process warn
    throttle — a TPU run that unexpectedly loses pinned_host placement
    should say so (once), not silently keep everything device-resident."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deepspeed_tpu.parallel import topology
    from deepspeed_tpu.parallel.topology import MeshTopology
    from deepspeed_tpu.runtime import offload as off_mod

    topology._GLOBAL_TOPOLOGY = None
    sh = NamedSharding(MeshTopology({"data": 8}).mesh, P())
    try:
        sh.with_memory_kind("pinned_host")
        pytest.skip("backend supports pinned_host — nothing degrades")
    except ValueError:
        pass
    saved = off_mod._MEMORY_KIND_DEGRADE_WARNED
    try:
        off_mod._MEMORY_KIND_DEGRADE_WARNED = False
        out = off_mod.with_memory_kind({"w": sh}, "pinned_host")
        assert out["w"] is sh  # degraded to the original placement
        assert off_mod._MEMORY_KIND_DEGRADE_WARNED  # warned + throttled
        out = off_mod.with_memory_kind({"w": sh}, "pinned_host")
        assert out["w"] is sh
    finally:
        off_mod._MEMORY_KIND_DEGRADE_WARNED = saved


def test_offload_states_roundtrip_values_bit_identical():
    """offload_states/reload_states is placement only — after a full
    device→host→device round trip every param and optimizer leaf must be
    BIT-identical and training must still run.  Unlike
    test_offload_states_api this never skips: where memory kinds are
    unsupported the placement degrades (warned once) and the round trip
    must still be value-preserving."""
    model = get_model_config("gpt2-tiny")
    eng = _mk(model, _cfg())
    _train(eng, _batches(model, 1))  # non-trivial moments before the trip
    p_before = [np.asarray(x) for x in jax.tree.leaves(eng.params)]
    o_before = [np.asarray(x) for x in jax.tree.leaves(eng.opt_state)]
    eng.offload_states()
    eng.reload_states()
    for b, a in zip(p_before, jax.tree.leaves(eng.params)):
        np.testing.assert_array_equal(b, np.asarray(a))
    for b, a in zip(o_before, jax.tree.leaves(eng.opt_state)):
        np.testing.assert_array_equal(b, np.asarray(a))
    losses = _train(eng, _batches(model, 2))
    assert all(np.isfinite(losses))


@pytest.mark.parametrize("chunk_bytes", [1 << 14, 12_004])
def test_chunked_adam_unit_parity_with_fused(chunk_bytes):
    """Chunked-vs-fused Adam parity on IDENTICAL grads: the chunked host
    step (DeepSpeed denom form, native kernel or numpy fallback) must
    equal the fused optax AdamW update to ≤1e-6 on the fp32 masters over
    3 steps — for a chunk size that divides nothing evenly (12_004 B →
    3001-element chunks), so the tail chunk and every leaf-straddling
    segment boundary are exercised."""
    import jax.numpy as jnp
    import optax

    from deepspeed_tpu.runtime.offload import ChunkedHostOptimizer

    rng = np.random.default_rng(7)
    params = {
        "w": jnp.asarray(rng.standard_normal((300, 17)), jnp.float32),
        "b": jnp.asarray(rng.standard_normal((4099,)), jnp.float32),
        "s": jnp.asarray(rng.standard_normal(()), jnp.float32),
    }
    lr, wd = 1e-3, 0.01
    opt = ChunkedHostOptimizer(params, lr=lr, betas=(0.9, 0.999),
                               eps=1e-8, weight_decay=wd,
                               chunk_bytes=chunk_bytes, adamw=True)
    try:
        assert opt.num_chunks > 1
        assert opt.total_numel % opt.chunk_numel != 0  # tail chunk real
        tx = optax.adamw(lr, b1=0.9, b2=0.999, eps=1e-8,
                         weight_decay=wd)
        state = tx.init(params)
        # fixed grads sequence (independent of the evolving params) so
        # both optimizers consume bit-identical inputs every step
        grad_seq = [jax.tree.map(lambda x, k=k: jnp.cos(x * (k + 1)),
                                 params) for k in range(3)]
        cur, ref = params, params
        for grads in grad_seq:
            cur = opt.step(cur, grads)
            upd, state = tx.update(grads, state, ref)
            ref = optax.apply_updates(ref, upd)
        masters = opt.state_dict()["master"]
        ref_leaves = jax.tree.leaves(ref)
        assert len(masters) == len(ref_leaves)
        for r, m in zip(ref_leaves, masters):
            np.testing.assert_allclose(np.asarray(r), m, rtol=0,
                                       atol=1e-6)
        # the pushed device params are the masters in the working dtype
        for r, c in zip(ref_leaves, jax.tree.leaves(cur)):
            np.testing.assert_allclose(np.asarray(r), np.asarray(c),
                                       rtol=0, atol=1e-6)
    finally:
        opt.close()


@pytest.fixture(scope="module")
def baseline6():
    """One plain-engine baseline shared by the chunked engine-level
    tests (each engine build pays a full jit compile on the 8-device
    mesh, so the family shares a single reference run).  `_batches`
    repeats one identical batch, so shorter runs are prefixes of this
    one.  Returns (batches, losses over 6 steps, fp32 param leaves
    snapshotted after step 3)."""
    model = get_model_config("gpt2-tiny")
    batches = _batches(model, 6)
    eng = _mk(model, _cfg())
    losses = _train(eng, batches[:3])
    params3 = [np.asarray(x, np.float32)
               for x in jax.tree.leaves(eng.params)]
    losses += _train(eng, batches[3:])
    return batches, losses, params3


def test_chunked_host_adam_matches_fused(baseline6):
    """Engine-level chunked-vs-fused parity: losses track the baseline
    to 1e-5 and the fp32 masters the baseline params.  The exact ≤1e-6
    Adam parity is pinned by test_chunked_adam_unit_parity_with_fused on
    identical grads (both chunk geometries); HERE the two engines
    compile different grad programs, and Adam amplifies their ulp-level
    grad differences wherever the true gradient is ~0 (e.g. the
    attention key bias: softmax is invariant to q·bk, so its grad is
    pure reduction noise that m/√v normalizes to ±1-scale updates) — so
    the master check is a loose gross-error tripwire (leaf order,
    scaling, missed chunks), not a numerics bound."""
    from deepspeed_tpu.runtime.offload import ChunkedHostOptimizer

    model = get_model_config("gpt2-tiny")
    batches, base, base_leaves = baseline6
    eng = _mk(model, _cfg(zero_optimization={"offload_optimizer": {
        "device": "cpu", "working_set_bytes": 1,
        "chunk_bytes": 12_004}}))  # divides nothing evenly: real tail chunk
    assert isinstance(eng._super_opt, ChunkedHostOptimizer)
    assert eng._super_opt.num_chunks > 1  # the pipeline actually chunks
    off = _train(eng, batches[:3])
    np.testing.assert_allclose(base[:3], off, rtol=1e-5, atol=1e-5)
    masters = eng._super_opt.state_dict()["master"]
    assert len(masters) == len(base_leaves)
    for b, m in zip(base_leaves, masters):
        np.testing.assert_allclose(b, m, rtol=0, atol=1e-3)


def test_nvme_chunked_matches_baseline(tmp_path, baseline6):
    """The NVMe chunk store behind the chunked host Adam: per-chunk
    files exist (one per chunk — the state is ON DISK between steps) and
    numerics match the non-offload baseline."""
    batches, base, _ = baseline6
    model = get_model_config("gpt2-tiny")
    eng = _mk(model, _cfg(zero_optimization={"offload_optimizer": {
        "device": "nvme", "nvme_path": str(tmp_path),
        "working_set_bytes": 1, "chunk_bytes": 1 << 14}}))
    off = _train(eng, batches[:3])
    chunks = [f for f in os.listdir(str(tmp_path))
              if f.startswith("opt_chunk_")]
    assert len(chunks) == eng._super_opt.num_chunks
    np.testing.assert_allclose(base[:3], off, rtol=1e-5, atol=1e-5)


def test_chunked_checkpoint_roundtrip(tmp_path, baseline6):
    """Chunked engines checkpoint through the superoffload state_dict
    path — save at step 3, resume in a FRESH chunked engine, and the
    continuation must match a baseline engine that trained straight
    through (parity + exact state round-trip composed)."""
    batches, base_all, _ = baseline6
    model = get_model_config("gpt2-tiny")
    chunk_zero = {"offload_optimizer": {"device": "cpu",
                                        "working_set_bytes": 1,
                                        "chunk_bytes": 1 << 14}}
    eng = _mk(model, _cfg(zero_optimization=chunk_zero))
    _train(eng, batches[:3])
    eng.save_checkpoint(str(tmp_path / "ck"), tag="t")
    eng2 = _mk(model, _cfg(zero_optimization=chunk_zero), seed=22)
    eng2.load_checkpoint(str(tmp_path / "ck"), tag="t")
    assert eng2.global_steps == 3
    cont = _train(eng2, batches[3:])
    np.testing.assert_allclose(base_all[3:], cont, rtol=1e-5, atol=1e-5)


def test_aio_roundtrip(tmp_path):
    from deepspeed_tpu.ops.aio import AsyncIOHandle

    h = AsyncIOHandle(block_size=1 << 16, queue_depth=4, thread_count=2)
    x = np.random.default_rng(0).standard_normal((1 << 18,)).astype(np.float32)
    path = str(tmp_path / "t.bin")
    h.pwrite(x, path)
    y = np.empty_like(x)
    h.pread(y, path)
    np.testing.assert_array_equal(x, y)


@pytest.mark.parametrize("nbytes", [1 << 18, (1 << 18) + 100, 4096, 100])
def test_aio_direct_roundtrip(tmp_path, nbytes):
    """O_DIRECT path (page-cache bypass): aligned body + buffered tail,
    incl. sub-block and unaligned sizes; falls back transparently where the
    FS rejects O_DIRECT (ref csrc/aio O_DIRECT discipline)."""
    from deepspeed_tpu.ops.aio import AsyncIOHandle

    h = AsyncIOHandle(block_size=1 << 16, queue_depth=4, thread_count=2,
                      use_direct=True)
    x = np.random.default_rng(1).integers(
        0, 255, size=nbytes, dtype=np.uint8)
    path = str(tmp_path / "d.bin")
    h.pwrite(x, path)
    assert os.path.getsize(path) == nbytes
    y = np.empty_like(x)
    h.pread(y, path)
    np.testing.assert_array_equal(x, y)
    # buffered handle reads back the O_DIRECT-written file identically
    hb = AsyncIOHandle(block_size=1 << 16, queue_depth=4, thread_count=2)
    z = np.empty_like(x)
    hb.pread(z, path)
    np.testing.assert_array_equal(x, z)


def test_aio_async_overlap(tmp_path):
    from deepspeed_tpu.ops.aio import AsyncIOHandle

    h = AsyncIOHandle(thread_count=4)
    arrays = [np.full((1 << 16,), i, np.float32) for i in range(8)]
    for i, a in enumerate(arrays):
        h.async_pwrite(a, str(tmp_path / f"f{i}.bin"))
    assert h.wait() == 0
    outs = [np.empty_like(a) for a in arrays]
    for i, o in enumerate(outs):
        h.async_pread(o, str(tmp_path / f"f{i}.bin"))
    assert h.wait() == 0
    for a, o in zip(arrays, outs):
        np.testing.assert_array_equal(a, o)


def test_aio_missing_file_reports_error(tmp_path):
    from deepspeed_tpu.ops.aio import AsyncIOHandle

    h = AsyncIOHandle()
    buf = np.empty((1024,), np.float32)
    with pytest.raises(IOError):
        h.pread(buf, str(tmp_path / "missing.bin"))
