"""Mesh topology + collectives facade tests (ref: tests/unit/comm/test_dist.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from deepspeed_tpu.utils.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm import comm
from deepspeed_tpu.parallel.topology import (DATA_AXIS, TENSOR_AXIS, MeshTopology,
                                             set_topology)


def test_topology_resolution():
    topo = MeshTopology({"data": -1, "tensor": 2})
    assert topo.tp_size == 2
    assert topo.dp_size == 4
    assert topo.world_size == 8


def test_topology_all_axes():
    topo = MeshTopology({"pipe": 2, "data": 2, "seq": 2, "tensor": 1})
    assert topo.pp_size == 2 and topo.sp_size == 2
    assert topo.zero_size == 4  # data * expert * seq


def test_topology_bad_product():
    with pytest.raises(ValueError):
        MeshTopology({"data": 5, "tensor": 2})  # 10 > 8 devices


def test_topology_submesh():
    topo = MeshTopology({"data": 3, "tensor": 2})  # 6 of 8 devices
    assert topo.world_size == 6


def test_all_reduce_in_shard_map():
    topo = MeshTopology({"data": 8})
    set_topology(topo)
    x = jnp.arange(8.0)

    def f(shard):
        return comm.all_reduce(shard, group=DATA_AXIS)

    out = shard_map(f, mesh=topo.mesh, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS))(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))


def test_reduce_scatter_all_gather_roundtrip():
    topo = MeshTopology({"data": 4, "tensor": 2})
    set_topology(topo)
    x = jnp.arange(32.0).reshape(4, 8)

    def f(shard):
        rs = comm.reduce_scatter(shard, group=DATA_AXIS, axis=0)
        return comm.all_gather(rs, group=DATA_AXIS, axis=0)

    out = shard_map(f, mesh=topo.mesh, in_specs=P(None, TENSOR_AXIS),
                    out_specs=P(None, TENSOR_AXIS), check_vma=False)(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) * 4)


def test_all_to_all():
    topo = MeshTopology({"data": 4, "tensor": 2})
    set_topology(topo)
    x = jnp.arange(16.0).reshape(4, 4)

    def f(shard):
        return comm.all_to_all(shard, group=DATA_AXIS, split_axis=1, concat_axis=0)

    out = shard_map(f, mesh=topo.mesh, in_specs=P(DATA_AXIS, None),
                    out_specs=P(DATA_AXIS, None))(x)
    # tiled all_to_all redistributes: global result is the block transpose
    np.testing.assert_allclose(np.asarray(out), np.asarray(x).T.reshape(16, 1))


def test_eager_all_reduce():
    topo = MeshTopology({"data": 8})
    set_topology(topo)
    x = jnp.ones((8, 4))
    out = comm.all_reduce_eager(x, group=DATA_AXIS, shard_dim=0)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 4), 8.0))


def test_world_size_queries():
    topo = MeshTopology({"data": 4, "tensor": 2})
    set_topology(topo)
    assert comm.get_world_size() == 8
    assert comm.get_world_size("tensor") == 2
    assert comm.get_world_size(("data", "tensor")) == 8
    assert comm.get_rank() == 0
    # group-scoped rank: single-process holds device (0, 0) of the mesh
    assert comm.get_rank("tensor") == 0
    assert comm.get_rank(("data", "tensor")) == 0


def test_broadcast_value_and_no_all_gather():
    """broadcast must deliver src's value to every rank WITHOUT lowering to
    an all-gather (VERDICT round-1: the old impl materialised world_size
    copies)."""
    topo = MeshTopology({"data": 8})
    set_topology(topo)
    x = jnp.arange(8.0).reshape(8, 1)

    def f(shard):
        return comm.broadcast(shard, src=3, group=DATA_AXIS)

    mapped = shard_map(f, mesh=topo.mesh, in_specs=P(DATA_AXIS, None),
                       out_specs=P(DATA_AXIS, None), check_vma=False)
    out = mapped(x)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 3.0))
    hlo = jax.jit(mapped).lower(x).compile().as_text()
    assert "all-gather" not in hlo, "broadcast lowered to all-gather"
