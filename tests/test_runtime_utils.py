"""Runtime utility surface (ref deepspeed/runtime/utils.py):
see_memory_usage, global norms, clip_grad_norm_, and the
memory_breakdown engine flag."""

import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.utils import (clip_grad_norm_, get_global_norm,
                                         get_global_norm_of_tensors,
                                         see_memory_usage)


def test_see_memory_usage_returns_stats(caplog):
    stats = see_memory_usage("unit-test", force=True)
    assert set(stats) >= {"bytes_in_use", "peak_bytes_in_use",
                          "host_peak_rss"}
    assert stats["host_peak_rss"] > 0  # POSIX RSS always available here


def test_global_norms_match_numpy():
    tree = {"a": jnp.asarray([[3.0, 4.0]]), "b": jnp.asarray([12.0])}
    n2 = float(get_global_norm_of_tensors(tree))
    np.testing.assert_allclose(n2, np.sqrt(9 + 16 + 144), rtol=1e-6)
    ninf = float(get_global_norm_of_tensors(tree, float("inf")))
    assert ninf == 12.0
    assert abs(get_global_norm([3.0, 4.0]) - 5.0) < 1e-12


def test_clip_grad_norm_scales_and_reports():
    tree = {"w": jnp.asarray([6.0, 8.0])}  # norm 10
    clipped, pre = clip_grad_norm_(tree, max_norm=5.0)
    np.testing.assert_allclose(float(pre), 10.0, rtol=1e-5)
    np.testing.assert_allclose(
        float(get_global_norm_of_tensors(clipped)), 5.0, rtol=1e-4)
    # under the max: unchanged
    same, pre2 = clip_grad_norm_(tree, max_norm=100.0)
    np.testing.assert_allclose(np.asarray(same["w"]),
                               np.asarray(tree["w"]), rtol=1e-6)


def test_engine_memory_breakdown_calls_see_memory_usage(monkeypatch):
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.parallel import topology
    from deepspeed_tpu.runtime import utils as rt_utils

    calls = []
    monkeypatch.setattr(rt_utils, "see_memory_usage",
                        lambda msg, force=False: calls.append((msg, force)))
    model = get_model_config("gpt2-tiny")
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "memory_breakdown": True,
        "steps_per_print": 1,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(8, 17), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    engine.train_batch(batch)
    assert calls == [("after step 1", True)]
    topology._GLOBAL_TOPOLOGY = None
