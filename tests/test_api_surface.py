"""Top-level API parity: zero.Init/GatheredParameters, checkpointing,
OnDevice, mpu adapter (ref deepspeed.zero / deepspeed.checkpointing /
utils/init_on_device / Megatron mpu consumption)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import get_model_config, init_params


def _reset_topo():
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None


def test_zero_init_materializes_sharded():
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology

    set_topology(MeshTopology({"data": 8}))
    cfg = get_model_config("gpt2-tiny").replace(dtype=jnp.float32)
    try:
        with ds.zero.Init(zero_stage=3) as zinit:
            params = zinit.materialize(
                lambda k: init_params(cfg, k), jax.random.PRNGKey(0))
        leaves = jax.tree.leaves(params)
        assert leaves
        # at least the big matrices must be sharded (not fully replicated)
        sharded = [l for l in leaves
                   if not l.sharding.is_fully_replicated and l.ndim >= 2]
        assert sharded, "zero.Init produced only replicated params"
    finally:
        _reset_topo()


def test_zero_init_needs_context():
    z = ds.zero.Init()
    with pytest.raises(RuntimeError):
        z.materialize(lambda k: {"w": jnp.ones(4)}, jax.random.PRNGKey(0))


def test_gathered_parameters_roundtrip():
    params = {"w": jnp.ones((4, 4), jnp.float32)}
    ctx = ds.zero.GatheredParameters(params)
    with ctx as host:
        host["w"][0, 0] = 5.0
    assert float(ctx.updated["w"][0, 0]) == 5.0
    assert float(params["w"][0, 0]) == 1.0  # original untouched (functional)
    out = ds.zero.gathered_update(
        params, lambda t: {"w": t["w"] * 2})
    np.testing.assert_allclose(np.asarray(out["w"]), 2.0)


def test_zero_memory_estimators():
    g3, h3 = ds.zero.estimate_zero3_model_states_mem_needs(
        10**9, num_gpus_per_node=8, cpu_offload=False)
    g2, h2 = ds.zero.estimate_zero2_model_states_mem_needs(
        10**9, num_gpus_per_node=8, cpu_offload=False)
    assert g3 < g2  # stage 3 shards params too
    assert h3 == 0 or h3 > 0  # smoke


def test_checkpointing_api():
    ds.checkpointing.configure(partition_activations=True,
                               checkpoint_in_cpu=False)
    w = jnp.ones((8, 8), jnp.float32)
    out = ds.checkpointing.checkpoint(lambda x: jnp.tanh(x @ w), w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(jnp.tanh(w @ w)),
                               atol=1e-6)
    # grad flows through the remat wrapper
    g = jax.grad(lambda x: ds.checkpointing.CheckpointFunction.apply(
        lambda y: (y @ w).sum(), x))(w)
    assert np.isfinite(np.asarray(g)).all()
    cfgd = ds.checkpointing.get_config()
    assert cfgd["partition_activations"] is True
    ds.checkpointing.reset()
    assert ds.checkpointing.get_config()["partition_activations"] is False


def test_on_device_meta_and_real():
    cfg = get_model_config("gpt2-tiny")
    with ds.OnDevice(dtype=jnp.bfloat16, device="meta") as ctx:
        shapes = ctx.init(lambda k: init_params(cfg, k), jax.random.PRNGKey(0))
    leaf = jax.tree.leaves(shapes)[0]
    assert isinstance(leaf, jax.ShapeDtypeStruct)
    assert leaf.dtype == jnp.bfloat16
    with ds.OnDevice(dtype=jnp.float32) as ctx:
        params = ctx.init(lambda k: {"w": jnp.ones((2, 2), jnp.bfloat16)},
                          jax.random.PRNGKey(0))
    assert params["w"].dtype == jnp.float32


def test_mpu_adapter_and_initialize():
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology

    set_topology(MeshTopology({"data": 2, "tensor": 2, "pipe": 2}))
    try:
        mpu = ds.MpuAdapter()
        assert mpu.get_tensor_model_parallel_world_size() == 2
        assert mpu.get_data_parallel_world_size() == 2
        assert mpu.get_pipeline_model_parallel_world_size() == 2
        from deepspeed_tpu.utils.mpu_adapter import topology_from_mpu

        topo = topology_from_mpu(mpu)
        assert topo.tp_size == 2 and topo.pp_size == 2
    finally:
        _reset_topo()


def test_round4_api_surface_importable():
    """Round-4 additions are part of the public surface: converter
    registry, sampling helpers, block-sparse kernel, KV generator,
    compression student init, pipelined-swap engine hooks."""
    from deepspeed_tpu.compression.compress import student_initialization
    from deepspeed_tpu.inference.kv_generate import KVCachedGenerator
    from deepspeed_tpu.inference.v2.model import (check_sampling_params,
                                                  sample_tokens)
    from deepspeed_tpu.models.hf_loader import register_converter
    from deepspeed_tpu.ops.pallas.block_sparse_mha import block_sparse_mha
    from deepspeed_tpu.ops.pallas.paged_attention import (
        paged_decode_attention)

    assert all(callable(f) for f in (
        student_initialization, KVCachedGenerator, check_sampling_params,
        sample_tokens, register_converter, block_sparse_mha,
        paged_decode_attention))
    # config keys of the round parse cleanly
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    c = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "zero_optimization": {
            "stage": 3, "strict_sharding": False,
            "param_persistence_threshold": 50_000,
            "offload_optimizer": {"device": "nvme", "pipeline_read": True,
                                  "nvme_path": "/tmp/x"}},
        "compression_training": {"sparse_pruning": {
            "shared_parameters": {"enabled": True}}},
    })
    assert c.zero_config.param_persistence_threshold == 50_000
    assert c.zero_config.offload_optimizer.pipeline_read
