"""Optimizer factory.

Analog of the reference's optimizer zoo (``_configure_basic_optimizer``,
runtime/engine.py:1536 — FusedAdam/CPUAdam/Lamb/Lion/Adagrad/Muon/1-bit).
On TPU there is no fused-vs-unfused split: every optimizer below is a pure
pytree transform that XLA fuses into the (sharded) update step, which *is*
the fused multi-tensor kernel — applied to ZeRO-partitioned state when the
engine shards opt state (ZeRO-1).

The learning rate is NOT baked into the transform chain: ``update_fn`` takes
``lr`` as a traced scalar so host-side LR schedules never retrigger
compilation.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.utils.logging import logger


@dataclass
class Optimizer:
    """init/update pair over param pytrees."""
    name: str
    init_fn: Callable[[Any], Any]
    update_fn: Callable[..., Tuple[Any, Any]]  # (grads, state, params, lr) -> (params, state)
    defaults: Dict[str, Any]

    def init(self, params):
        return self.init_fn(params)

    def update(self, grads, state, params, lr):
        return self.update_fn(grads, state, params, lr)


def _chain_to_optimizer(name: str, tx: optax.GradientTransformation,
                        defaults: Dict[str, Any]) -> Optimizer:
    def update_fn(grads, state, params, lr):
        updates, new_state = tx.update(grads, state, params)
        updates = jax.tree.map(lambda u: (-lr * u).astype(u.dtype), updates)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_state

    return Optimizer(name=name, init_fn=tx.init, update_fn=update_fn, defaults=defaults)


def _adam(params_cfg: Dict[str, Any], adam_w_mode: bool) -> Optimizer:
    betas = params_cfg.get("betas", (0.9, 0.999))
    eps = float(params_cfg.get("eps", 1e-8))
    wd = float(params_cfg.get("weight_decay", 0.01 if adam_w_mode else 0.0))
    txs = [optax.scale_by_adam(b1=float(betas[0]), b2=float(betas[1]), eps=eps)]
    if wd:
        if adam_w_mode:
            txs.append(optax.add_decayed_weights(wd))
        else:
            # plain Adam + L2: decay folded into grads happens pre-moment in
            # torch Adam; approximate with decoupled decay is NOT identical,
            # so add L2 term up front instead.
            txs.insert(0, optax.add_decayed_weights(wd))
    name = "adamw" if adam_w_mode else "adam"
    return _chain_to_optimizer(name, optax.chain(*txs),
                               dict(betas=betas, eps=eps, weight_decay=wd))


def _lion(params_cfg: Dict[str, Any]) -> Optimizer:
    betas = params_cfg.get("betas", (0.9, 0.99))
    wd = float(params_cfg.get("weight_decay", 0.0))
    txs = [optax.scale_by_lion(b1=float(betas[0]), b2=float(betas[1]))]
    if wd:
        txs.append(optax.add_decayed_weights(wd))
    return _chain_to_optimizer("lion", optax.chain(*txs), dict(betas=betas, weight_decay=wd))


def _lamb(params_cfg: Dict[str, Any]) -> Optimizer:
    betas = params_cfg.get("betas", (0.9, 0.999))
    eps = float(params_cfg.get("eps", 1e-6))
    wd = float(params_cfg.get("weight_decay", 0.0))
    txs = [optax.scale_by_adam(b1=float(betas[0]), b2=float(betas[1]), eps=eps)]
    if wd:
        txs.append(optax.add_decayed_weights(wd))
    txs.append(optax.scale_by_trust_ratio())
    return _chain_to_optimizer("lamb", optax.chain(*txs),
                               dict(betas=betas, eps=eps, weight_decay=wd))


def _adagrad(params_cfg: Dict[str, Any]) -> Optimizer:
    eps = float(params_cfg.get("eps", 1e-10))
    wd = float(params_cfg.get("weight_decay", 0.0))
    txs = [optax.scale_by_rss(initial_accumulator_value=0.0, eps=eps)]
    if wd:
        txs.insert(0, optax.add_decayed_weights(wd))
    return _chain_to_optimizer("adagrad", optax.chain(*txs), dict(eps=eps, weight_decay=wd))


def _sgd(params_cfg: Dict[str, Any]) -> Optimizer:
    momentum = float(params_cfg.get("momentum", 0.0))
    wd = float(params_cfg.get("weight_decay", 0.0))
    txs = []
    if wd:
        txs.append(optax.add_decayed_weights(wd))
    if momentum:
        txs.append(optax.trace(decay=momentum, nesterov=bool(params_cfg.get("nesterov", False))))
    tx = optax.chain(*txs) if txs else optax.identity()
    return _chain_to_optimizer("sgd", tx, dict(momentum=momentum, weight_decay=wd))


def _muon(params_cfg: Dict[str, Any]) -> Optimizer:
    """Muon: momentum + Newton–Schulz orthogonalisation for 2-D params
    (ref runtime/zero/muon/original_muon.py:36); non-2D params fall back to
    Adam, matching the reference's use_muon split."""
    from deepspeed_tpu.ops.muon import build_muon

    return build_muon(params_cfg)


def build_optimizer(opt_type: str, params_cfg: Optional[Dict[str, Any]] = None) -> Optimizer:
    params_cfg = dict(params_cfg or {})
    params_cfg.pop("lr", None)  # lr flows through update_fn
    t = opt_type.lower()
    if t in (C.ADAM_OPTIMIZER, C.FUSED_ADAM_OPTIMIZER):
        adam_w_mode = bool(params_cfg.pop("adam_w_mode", True))
        return _adam(params_cfg, adam_w_mode)
    if t == C.ADAMW_OPTIMIZER:
        params_cfg.pop("adam_w_mode", None)
        return _adam(params_cfg, True)
    if t in (C.LION_OPTIMIZER, "fusedlion"):
        return _lion(params_cfg)
    if t in (C.LAMB_OPTIMIZER, "fusedlamb"):
        return _lamb(params_cfg)
    if t == C.ADAGRAD_OPTIMIZER:
        return _adagrad(params_cfg)
    if t == C.SGD_OPTIMIZER:
        return _sgd(params_cfg)
    if t == C.MUON_OPTIMIZER:
        return _muon(params_cfg)
    if t in (C.ONEBIT_ADAM_OPTIMIZER, C.ONEBIT_LAMB_OPTIMIZER, C.ZERO_ONE_ADAM_OPTIMIZER):
        # Compressed-communication optimizers: on TPU gradient reduction is
        # compiled; the compression variant lives in ops/compressed_optimizer.
        logger.warning(f"{opt_type}: using uncompressed TPU variant (XLA-reduced grads)")
        return _adam(params_cfg, bool(params_cfg.pop("adam_w_mode", True)))
    raise ValueError(f"unknown optimizer type '{opt_type}'")
