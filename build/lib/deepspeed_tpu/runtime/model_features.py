"""Training-dynamics model features: progressive layer drop, block
eigenvalue estimation, tiled linear, sparse gradients.

Analogs of ``deepspeed/runtime/progressive_layer_drop.py:10``,
``runtime/eigenvalue.py:13``, ``runtime/zero/tiling.py`` (TiledLinear) and
``runtime/sparse_tensor.py``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class ProgressiveLayerDrop:
    """Keep-probability schedule for stochastic depth (ref
    ProgressiveLayerDrop: theta(t) = (1-theta)·exp(-gamma·t) + theta)."""

    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0

    def get_theta(self, global_step: Optional[int] = None) -> float:
        if global_step is None:
            return self.current_theta
        return (1.0 - self.theta) * float(np.exp(-self.gamma * global_step)) \
            + self.theta

    def update_state(self, global_step: int) -> float:
        self.current_theta = self.get_theta(global_step)
        return self.current_theta

    def get_state(self) -> Dict[str, float]:
        return {"progressive_layer_drop": True, "pld_theta": self.current_theta}


def layer_drop(layer_fn: Callable, x, keep_prob: float, key,
               layer_idx: int = 0, num_layers: int = 1, *args, **kwargs):
    """Stochastic-depth wrapper: skip the layer (identity) with prob
    1 - keep_prob·scale, where deeper layers drop more (PLD's linear depth
    scaling).  Output is rescaled at train time like dropout."""
    p = keep_prob * (1.0 - layer_idx / max(1, num_layers) * (1.0 - keep_prob))
    p = jnp.clip(p, 0.0, 1.0)
    coin = jax.random.bernoulli(key, p)
    out = layer_fn(x, *args, **kwargs)
    y = out[0] if isinstance(out, tuple) else out
    kept = jnp.where(coin, y, x)
    return (kept,) + tuple(out[1:]) if isinstance(out, tuple) else kept


# ----------------------------------------------------------------------
class Eigenvalue:
    """Power-iteration max-eigenvalue of the loss Hessian per param block
    (ref Eigenvalue, runtime/eigenvalue.py:13 — used by MoQ to schedule
    precision switching).  Hessian-vector products come from
    ``jax.jvp(jax.grad(loss))`` — no Hessian materialisation.
    """

    def __init__(self, max_iter: int = 10, tol: float = 1e-2,
                 stability: float = 1e-6):
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability

    def compute(self, loss_fn: Callable[[Any], jnp.ndarray], params: Any,
                key) -> Dict[str, float]:
        """→ {leaf_path: max |eigenvalue| estimate}."""
        grad_fn = jax.grad(loss_fn)

        def hvp(v):
            return jax.jvp(grad_fn, (params,), (v,))[1]

        # random unit start per leaf
        leaves, treedef = jax.tree_util.tree_flatten(params)
        keys = jax.random.split(key, len(leaves))
        v = jax.tree_util.tree_unflatten(
            treedef, [jax.random.normal(k, l.shape, jnp.float32)
                      for k, l in zip(keys, leaves)])
        v = _normalize_tree(v, self.stability)
        eig = 0.0
        for _ in range(self.max_iter):
            hv = hvp(v)
            new_eig = float(_tree_dot(v, hv))
            v = _normalize_tree(hv, self.stability)
            if abs(new_eig - eig) <= self.tol * max(1.0, abs(new_eig)):
                eig = new_eig
                break
            eig = new_eig
        # per-leaf contribution: ||Hv_leaf|| as block estimate
        hv = hvp(v)
        out = {}
        for (path, leaf) in jax.tree_util.tree_flatten_with_path(hv)[0]:
            name = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                            for p in path)
            out[name] = float(jnp.linalg.norm(leaf.astype(jnp.float32)))
        out["__global__"] = abs(eig)
        return out


def _tree_dot(a, b) -> jnp.ndarray:
    return sum(jnp.vdot(x.astype(jnp.float32), y.astype(jnp.float32))
               for x, y in zip(jax.tree_util.tree_leaves(a),
                               jax.tree_util.tree_leaves(b)))


def _normalize_tree(t, eps: float):
    norm = jnp.sqrt(sum((x.astype(jnp.float32) ** 2).sum()
                        for x in jax.tree_util.tree_leaves(t)))
    return jax.tree.map(lambda x: (x / (norm + eps)).astype(x.dtype), t)


# ----------------------------------------------------------------------
def tiled_linear(x, w, bias=None, in_splits: int = 1, out_splits: int = 1,
                 activation: Optional[Callable] = None):
    """TiledLinear (ref runtime/zero/tiling.py): evaluate a large linear as
    an in_splits × out_splits grid of sub-matmuls, accumulating over input
    tiles.  Under jit XLA sees smaller live intermediates, which is the
    memory effect the reference gets from sequential sub-layers."""
    in_dim, out_dim = w.shape[-2], w.shape[-1]
    if in_dim % in_splits or out_dim % out_splits:
        raise ValueError(f"dims {w.shape} not divisible by splits "
                         f"({in_splits}, {out_splits})")
    it, ot = in_dim // in_splits, out_dim // out_splits
    outs = []
    for j in range(out_splits):
        acc = None
        for i in range(in_splits):
            xi = x[..., i * it:(i + 1) * it]
            wij = w[i * it:(i + 1) * it, j * ot:(j + 1) * ot]
            part = xi @ wij
            acc = part if acc is None else acc + part
        if bias is not None:
            acc = acc + bias[j * ot:(j + 1) * ot]
        if activation is not None:
            acc = activation(acc)
        outs.append(acc)
    return jnp.concatenate(outs, axis=-1)


# ----------------------------------------------------------------------
class SparseTensor:
    """COO sparse gradient carrier (ref runtime/sparse_tensor.py) for
    embedding-style row-sparse grads; allreduce concatenates (indices,
    values) across ranks like the reference's sparse allreduce
    (engine.py:145 split_half_float_double_sparse)."""

    def __init__(self, indices, values, dense_shape: Tuple[int, ...]):
        self.indices = jnp.asarray(indices)
        self.values = jnp.asarray(values)
        self.dense_shape = tuple(dense_shape)

    @classmethod
    def from_dense(cls, dense, threshold: float = 0.0) -> "SparseTensor":
        rows = jnp.where(jnp.abs(dense).sum(axis=tuple(range(1, dense.ndim)))
                         > threshold)[0]
        return cls(rows, dense[rows], dense.shape)

    def to_dense(self) -> jnp.ndarray:
        out = jnp.zeros(self.dense_shape, self.values.dtype)
        return out.at[self.indices].add(self.values)

    def sparse_size(self) -> int:
        return int(self.indices.size + self.values.size)

    @staticmethod
    def add(a: "SparseTensor", b: "SparseTensor") -> "SparseTensor":
        if a.dense_shape != b.dense_shape:
            raise ValueError("shape mismatch")
        return SparseTensor(jnp.concatenate([a.indices, b.indices]),
                            jnp.concatenate([a.values, b.values]),
                            a.dense_shape)
