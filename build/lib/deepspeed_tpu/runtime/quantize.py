"""MoQ — Mixture-of-Quantization: scheduled precision reduction during
training.

Analog of ``deepspeed/runtime/quantize.py`` (``Quantizer`` :180, MoQ): start
training at high bit-width, halve the quantization period's target bits on
a schedule (``quantize_period`` doubling per transition), optionally gating
each transition on the loss-landscape curvature (block eigenvalue — a high
top-eigenvalue layer is still moving, so its precision drop is deferred).

The quantization itself reuses the compression suite's STE fake-quant; MoQ
is the *scheduler* that decides per-step, per-group target bits.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from deepspeed_tpu.compression.basic_layers import quantize_weight_ste
from deepspeed_tpu.runtime.model_features import Eigenvalue
from deepspeed_tpu.utils.logging import logger


class MoQScheduler:
    """Per-step target bit-width (ref Quantizer schedule fields:
    start_bits → target_bits, quantize_period doubling)."""

    def __init__(self, start_bits: int = 16, target_bits: int = 8,
                 quantize_period: int = 100, period_factor: int = 2):
        if target_bits > start_bits:
            raise ValueError("target_bits must be <= start_bits")
        self.start_bits = start_bits
        self.target_bits = target_bits
        self.quantize_period = quantize_period
        self.period_factor = period_factor
        self.current_bits = start_bits
        self._next_transition = quantize_period
        self._period = quantize_period

    def update(self, step: int, allow_transition: bool = True) -> int:
        """Advance to ``step``; one bit-halving per elapsed period (gated
        by ``allow_transition`` — the eigenvalue hook)."""
        while (step >= self._next_transition
               and self.current_bits > self.target_bits):
            if not allow_transition:
                # defer: re-check after the same period
                self._next_transition = step + self._period
                return self.current_bits
            self.current_bits = max(self.target_bits, self.current_bits // 2)
            self._period *= self.period_factor
            self._next_transition += self._period
            logger.info(f"MoQ: step {step} → {self.current_bits}-bit")
        return self.current_bits

    def state_dict(self) -> Dict[str, Any]:
        return {"current_bits": self.current_bits,
                "next_transition": self._next_transition,
                "period": self._period}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.current_bits = int(sd["current_bits"])
        self._next_transition = int(sd["next_transition"])
        self._period = int(sd["period"])


class MoQQuantizer:
    """Config-driven MoQ over a param tree (ref Quantizer.quantize).

    config (mirroring the reference's ``quantize_training`` block)::

        {"enabled": true, "quantize_bits": {"start_bits": 16,
         "target_bits": 8}, "schedule": {"quantize_period": 100,
         "schedule_offset": 0}, "quantize_groups": 64,
         "eigenvalue": {"enabled": false, "max_iter": 10, "tol": 1e-2,
                        "stability": 1e-6}}
    """

    def __init__(self, config: Dict[str, Any]):
        qt = config.get("quantize_training", config) or {}
        self.enabled = bool(qt.get("enabled", False))
        bits = qt.get("quantize_bits", {})
        sched = qt.get("schedule", {})
        self.schedule_offset = int(sched.get("schedule_offset", 0))
        self.scheduler = MoQScheduler(
            start_bits=int(bits.get("start_bits", 16)),
            target_bits=int(bits.get("target_bits", 8)),
            quantize_period=int(sched.get("quantize_period", 100)))
        self.quantize_groups = int(qt.get("quantize_groups", 64))
        ev = qt.get("eigenvalue", {}) or {}
        self.eigenvalue_enabled = bool(ev.get("enabled", False))
        self._eig = Eigenvalue(max_iter=int(ev.get("max_iter", 10)),
                               tol=float(ev.get("tol", 1e-2)),
                               stability=float(ev.get("stability", 1e-6))) \
            if self.eigenvalue_enabled else None
        self._last_eig: Optional[float] = None
        self._eig_threshold = float(ev.get("threshold", 1.0))

    # ------------------------------------------------------------------
    def check_eigenvalue(self, loss_fn: Callable, params: Any, key) -> bool:
        """Transition gate: allow the bit drop only once curvature settled
        below threshold (ref eigenvalue-based MoQ precision switching)."""
        if self._eig is None:
            return True
        out = self._eig.compute(loss_fn, params, key)
        self._last_eig = out["__global__"]
        ok = self._last_eig <= self._eig_threshold
        if not ok:
            logger.info(f"MoQ: eigenvalue {self._last_eig:.3g} > "
                        f"{self._eig_threshold:.3g}; deferring bit drop")
        return ok

    def current_bits(self, step: int, loss_fn: Optional[Callable] = None,
                     params: Any = None, key=None) -> int:
        if not self.enabled or step < self.schedule_offset:
            return self.scheduler.start_bits
        allow = True
        if (self.eigenvalue_enabled and loss_fn is not None
                and step >= self.scheduler._next_transition
                and self.scheduler.current_bits > self.scheduler.target_bits):
            allow = self.check_eigenvalue(
                loss_fn, params,
                key if key is not None else jax.random.PRNGKey(step))
        return self.scheduler.update(step - self.schedule_offset, allow)

    def quantize(self, params: Any, step: int, **gate_kw) -> Any:
        """Fake-quantize ≥2-D weights at the current bit-width (apply
        inside the jitted loss like the compression manager)."""
        bits = self.current_bits(step, **gate_kw)
        if not self.enabled or bits >= 16:
            return params
        return jax.tree.map(
            lambda w: quantize_weight_ste(w, bits=bits,
                                          group_size=self.quantize_groups)
            if np.ndim(w) >= 2 else w, params)
