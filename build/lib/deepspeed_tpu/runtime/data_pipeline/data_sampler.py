"""Curriculum-capable deterministic data sampler.

Analog of ``deepspeed/runtime/data_pipeline/data_sampling/data_sampler.py``
(``DeepSpeedDataSampler`` :36): yields per-step index batches, optionally
filtered by a per-sample difficulty metric so only samples at or below the
curriculum's current difficulty are drawn.  Deterministic in
(seed, epoch, step) so every DP rank computes the same global order and
takes its own disjoint slice — the TPU-native replacement for a
torch.distributed sampler.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import CurriculumScheduler


class DeepSpeedDataSampler:
    """Yields lists of dataset indices, one list per *global* batch.

    difficulties: optional per-sample difficulty values (e.g. sequence
    lengths).  When both ``difficulties`` and ``curriculum`` are given, each
    batch draws only from samples with difficulty ≤ the schedule's current
    value (ref CL-enabled DeepSpeedDataSampler).
    """

    def __init__(self, total_samples: int, batch_size: int,
                 difficulties: Optional[Sequence] = None,
                 curriculum: Optional[CurriculumScheduler] = None,
                 dp_rank: int = 0, dp_size: int = 1,
                 shuffle: bool = True, seed: int = 1234,
                 drop_last: bool = True):
        if batch_size % dp_size != 0:
            raise ValueError(f"global batch {batch_size} not divisible by dp={dp_size}")
        self.total_samples = total_samples
        self.batch_size = batch_size
        self.micro_batch = batch_size // dp_size
        self.difficulties = (np.asarray(difficulties)
                             if difficulties is not None else None)
        if self.difficulties is not None and len(self.difficulties) != total_samples:
            raise ValueError("difficulties must have one entry per sample")
        self.curriculum = curriculum
        self.dp_rank = dp_rank
        self.dp_size = dp_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        self.consumed_batches = 0  # global steps served (for resume)

    # ------------------------------------------------------------------
    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def _order(self) -> np.ndarray:
        order = np.arange(self.total_samples)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(order)
        return order

    def __len__(self) -> int:
        n = self.total_samples // self.batch_size
        if not self.drop_last and self.total_samples % self.batch_size:
            n += 1
        return n

    def __iter__(self) -> Iterator[List[int]]:
        order = self._order()
        if self.curriculum is not None and self.difficulties is not None:
            # stable-sort eligibility per step: draw sequentially from the
            # shuffled order, skipping too-hard samples (they become
            # eligible as difficulty rises) — same sample-once-per-epoch
            # guarantee as the reference.
            pos = 0
            for _ in range(len(self)):
                diff = self.curriculum.update_difficulty(self.consumed_batches)
                batch: List[int] = []
                scan = pos
                deferred: List[int] = []
                while len(batch) < self.batch_size and scan < len(order):
                    idx = int(order[scan])
                    if self.difficulties[idx] <= diff:
                        batch.append(idx)
                    else:
                        deferred.append(idx)
                    scan += 1
                # keep deferred (too hard now) at the front for later steps
                order = np.concatenate([
                    np.asarray(deferred, dtype=order.dtype),
                    order[scan:]])
                pos = 0
                if len(batch) < self.batch_size and self.drop_last:
                    return
                if not batch:
                    return
                self.consumed_batches += 1
                yield self._rank_slice(batch)
        else:
            for start in range(0, self.total_samples, self.batch_size):
                batch = [int(i) for i in order[start:start + self.batch_size]]
                if len(batch) < self.batch_size and self.drop_last:
                    return
                self.consumed_batches += 1
                yield self._rank_slice(batch)
        self.epoch += 1

    def _rank_slice(self, batch: List[int]) -> List[int]:
        if self.dp_size == 1:
            return batch
        per = max(1, len(batch) // self.dp_size)
        return batch[self.dp_rank * per:(self.dp_rank + 1) * per]

    # -- resume ---------------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        state = {"epoch": self.epoch, "consumed_batches": self.consumed_batches}
        if self.curriculum is not None:
            state["curriculum"] = self.curriculum.state_dict()
        return state

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.epoch = int(state["epoch"])
        self.consumed_batches = int(state["consumed_batches"])
        if self.curriculum is not None and "curriculum" in state:
            self.curriculum.load_state_dict(state["curriculum"])
