"""Variable batch size and LR.

Analog of ``deepspeed/runtime/data_pipeline/data_sampling/
variable_batch_size_and_lr.py``: pack variable-length samples into batches
of roughly constant *token* count (so step cost is uniform even when seq
lengths vary wildly), and scale the LR for each batch's effective size so
the optimization trajectory matches fixed-batch training.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np


def batch_by_token_budget(seqlens: Sequence[int], token_budget: int,
                          max_batch_size: int = 0,
                          shuffle_seed: int = -1,
                          sort_by_length: bool = True) -> List[List[int]]:
    """Plan index batches with ≤ ``token_budget`` total tokens each.

    Sorting by length first (the reference's default) minimises padding
    waste; a fixed seed shuffles the *batches* afterwards so step order is
    still random.  ``max_batch_size`` (0 = unlimited) caps rows per batch.
    """
    seqlens = np.asarray(seqlens)
    order = np.argsort(seqlens, kind="stable") if sort_by_length \
        else np.arange(len(seqlens))
    batches: List[List[int]] = []
    cur: List[int] = []
    cur_max = 0
    for idx in order:
        sl = int(seqlens[idx])
        if sl > token_budget:
            raise ValueError(f"sample {idx} ({sl} tokens) exceeds budget "
                             f"{token_budget}")
        new_max = max(cur_max, sl)
        # padded cost = rows * max_len (padding counts against the budget)
        if cur and ((len(cur) + 1) * new_max > token_budget
                    or (max_batch_size and len(cur) >= max_batch_size)):
            batches.append(cur)
            cur, cur_max = [], 0
            new_max = sl
        cur.append(int(idx))
        cur_max = new_max
    if cur:
        batches.append(cur)
    if shuffle_seed >= 0:
        rng = np.random.default_rng(shuffle_seed)
        rng.shuffle(batches)
    return batches


def scale_lr_by_batch_size(base_lr: float, batch_size: int,
                           base_batch_size: int,
                           method: str = "linear") -> float:
    """LR scaling for a variable batch (ref scale_lr in
    variable_batch_size_and_lr.py): ``linear`` (Goyal et al.) or ``sqrt``
    (Hoffer et al.) scaling; ``none`` disables."""
    if method == "none" or batch_size == base_batch_size:
        return base_lr
    ratio = batch_size / base_batch_size
    if method == "linear":
        return base_lr * ratio
    if method == "sqrt":
        return base_lr * ratio ** 0.5
    raise ValueError(f"unknown lr scaling method {method!r}")
