"""Random-LTD — random layerwise token dropping.

Analog of ``deepspeed/runtime/data_pipeline/data_routing/``
(``basic_layer.py`` RandomLayerTokenDrop, ``scheduler.py:38`` the kept-
seqlen schedule) and the gather/scatter kernels in ``csrc/random_ltd/``.

A band of middle layers runs on a random subset of tokens; the untouched
tokens bypass those layers and are scattered back afterwards.  On TPU the
gather/scatter are `jnp.take_along_axis`/``.at[].set`` — XLA lowers them to
dynamic-gather/scatter HLOs, the role the reference's CUDA kernels play —
and the random subset is drawn with a jax PRNG key so the whole step stays
jittable (the kept count is a *static* python int per compile, exactly like
the reference where the schedule changes the tensor shape between steps).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp


class RandomLTDScheduler:
    """Kept-sequence-length schedule (ref data_routing/scheduler.py:38).

    Linearly increases the kept seqlen from ``min_value`` to ``max_value``
    over ``total_steps``, rounded down to a multiple of ``step_size``.
    """

    def __init__(self, min_value: int, max_value: int, total_steps: int,
                 step_size: int = 8):
        self.min_value = int(min_value)
        self.max_value = int(max_value)
        self.total_steps = int(total_steps)
        self.step_size = int(step_size)
        self.current_seqlen = self.min_value

    def get_seqlen(self, global_step: int) -> int:
        frac = min(1.0, max(0.0, global_step / max(1, self.total_steps)))
        val = self.min_value + (self.max_value - self.min_value) * frac
        val = int(val // self.step_size) * self.step_size
        return min(self.max_value, max(self.min_value, val))

    def update(self, global_step: int) -> int:
        self.current_seqlen = self.get_seqlen(global_step)
        return self.current_seqlen

    def state_dict(self) -> Dict[str, Any]:
        return {"current_seqlen": self.current_seqlen}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.current_seqlen = int(state["current_seqlen"])


def random_ltd_indices(key, seq_len: int, kept: int, batch: int):
    """Per-sample sorted random subset of token positions → [B, kept].
    Sorted order preserves causality within the kept subsequence (ref
    token_sort_ kernels, csrc/random_ltd/)."""
    keys = jax.random.split(key, batch)

    def one(k):
        perm = jax.random.permutation(k, seq_len)[:kept]
        return jnp.sort(perm)

    return jax.vmap(one)(keys)


def random_ltd_drop(x, indices):
    """Gather kept tokens: x [B, S, ...] + indices [B, K] → [B, K, ...]
    (ref gather kernel, csrc/random_ltd/gather_scatter.cu analog)."""
    idx = indices.reshape(indices.shape + (1,) * (x.ndim - 2))
    return jnp.take_along_axis(x, idx, axis=1)


def random_ltd_restore(x_full, x_kept, indices):
    """Scatter processed tokens back into the full sequence; dropped tokens
    keep their (bypassed) values from ``x_full`` (ref scatter kernel)."""
    rows = jnp.arange(x_full.shape[0])[:, None]
    return x_full.at[rows, indices].set(x_kept)


class RandomLTDLayerWrapper:
    """Apply a layer stack on a random token subset (ref RandomLayerTokenDrop,
    data_routing/basic_layer.py).

    ``layer_fn(x, positions) -> x`` runs on the kept tokens only; dropped
    tokens bypass via identity.  ``kept`` must be static per compile.
    """

    def __init__(self, layer_fn, scheduler: RandomLTDScheduler):
        self.layer_fn = layer_fn
        self.scheduler = scheduler

    def __call__(self, x, positions, key, kept: int):
        b, s = x.shape[0], x.shape[1]
        if kept >= s:
            return self.layer_fn(x, positions)
        idx = random_ltd_indices(key, s, kept, b)
        x_kept = random_ltd_drop(x, idx)
        pos_kept = jnp.take_along_axis(positions, idx, axis=1) \
            if positions is not None and positions.ndim == 2 else positions
        y_kept = self.layer_fn(x_kept, pos_kept)
        return random_ltd_restore(x, y_kept, idx)
