"""Dataset analysis → per-sample curriculum metric files.

Analog of ``deepspeed/runtime/data_pipeline/data_sampling/data_analyzer.py``
(``DataAnalyzer``): maps a dataset once (parallelizable by worker shards),
computing per-sample difficulty metrics (seqlen, vocab rarity, custom fns),
writes them as ``.npy`` metric files plus a sorted index-by-metric, which
``DeepSpeedDataSampler`` consumes as its ``difficulties``.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np


def metric_seqlen(sample) -> float:
    return float(len(sample["input_ids"] if isinstance(sample, dict)
                     else sample))


def metric_vocab_rarity(sample, token_freq: Optional[np.ndarray] = None) -> float:
    """Mean negative log-frequency of the sample's tokens (rarer = harder)."""
    toks = np.asarray(sample["input_ids"] if isinstance(sample, dict)
                      else sample)
    if token_freq is None:
        return float(len(toks))
    f = token_freq[np.clip(toks, 0, len(token_freq) - 1)]
    return float(-np.log(np.maximum(f, 1e-12)).mean())


class DataAnalyzer:
    """Map a dataset to metric files (ref DataAnalyzer.run_map/run_reduce).

    ``metrics``: {name: fn(sample) -> float}.  ``num_workers``/``worker_id``
    shard the map phase; ``run_reduce`` merges shard files.
    """

    def __init__(self, dataset, output_dir: str,
                 metrics: Optional[Dict[str, Callable]] = None,
                 num_workers: int = 1, worker_id: int = 0):
        self.dataset = dataset
        self.output_dir = output_dir
        self.metrics = metrics or {"seqlen": metric_seqlen}
        self.num_workers = num_workers
        self.worker_id = worker_id
        os.makedirs(output_dir, exist_ok=True)

    # ------------------------------------------------------------------
    def _shard_indices(self) -> np.ndarray:
        n = len(self.dataset)
        return np.arange(self.worker_id, n, self.num_workers)

    def run_map(self) -> Dict[str, str]:
        """Compute this worker's metric shard → file paths."""
        idx = self._shard_indices()
        out = {}
        for name, fn in self.metrics.items():
            vals = np.asarray([fn(self.dataset[int(i)]) for i in idx],
                              np.float64)
            path = os.path.join(self.output_dir,
                                f"{name}.worker{self.worker_id}.npy")
            np.save(path, np.stack([idx.astype(np.float64), vals], axis=1))
            out[name] = path
        return out

    def run_reduce(self) -> Dict[str, str]:
        """Merge all worker shards into ``<metric>_values.npy`` (dense,
        index-aligned) + ``<metric>_index_sorted.npy`` (sample indices
        sorted by metric) + a JSON summary."""
        n = len(self.dataset)
        results = {}
        for name in self.metrics:
            dense = np.zeros(n, np.float64)
            seen = np.zeros(n, bool)
            for w in range(self.num_workers):
                path = os.path.join(self.output_dir, f"{name}.worker{w}.npy")
                if not os.path.exists(path):
                    raise RuntimeError(
                        f"metric {name}: worker {w} shard missing ({path}) — "
                        "did every worker run_map?")
                pairs = np.load(path)
                ii = pairs[:, 0].astype(np.int64)
                dense[ii] = pairs[:, 1]
                seen[ii] = True
            if not seen.all():
                raise RuntimeError(
                    f"metric {name}: {int((~seen).sum())} samples missing — "
                    "did every worker run_map?")
            vpath = os.path.join(self.output_dir, f"{name}_values.npy")
            spath = os.path.join(self.output_dir, f"{name}_index_sorted.npy")
            np.save(vpath, dense)
            np.save(spath, np.argsort(dense, kind="stable"))
            results[name] = vpath
        summary = {name: {"min": float(np.load(p).min()),
                          "max": float(np.load(p).max()),
                          "mean": float(np.load(p).mean())}
                   for name, p in results.items()}
        with open(os.path.join(self.output_dir, "analysis_summary.json"),
                  "w") as f:
            json.dump(summary, f, indent=2)
        return results


def load_metric(output_dir: str, name: str = "seqlen") -> np.ndarray:
    """Load a reduced metric as the sampler's ``difficulties`` array."""
    return np.load(os.path.join(output_dir, f"{name}_values.npy"))
