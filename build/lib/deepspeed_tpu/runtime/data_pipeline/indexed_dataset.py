"""Memory-mapped indexed token dataset.

Analog of ``deepspeed/runtime/data_pipeline/data_sampling/
indexed_dataset.py`` (the Megatron MMapIndexedDataset lineage): token
sequences live in one flat binary file plus an index of (offset, length)
pairs, read back through ``np.memmap`` so multi-million-document corpora
cost no resident RAM.  Builder + reader + on-disk format:

``<path>.bin``  — concatenated token arrays
``<path>.idx``  — header (magic, version, dtype code, count) then
                  int64 offsets[count+1] (prefix sums, in elements)
"""

from __future__ import annotations

import os
import struct
from typing import Iterable, List, Sequence

import numpy as np

_MAGIC = b"DSTPUIDX"
_VERSION = 1
_DTYPES = {1: np.uint16, 2: np.int32, 3: np.int64, 4: np.uint8}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


class IndexedDatasetBuilder:
    """Stream sequences to disk (ref MMapIndexedDatasetBuilder)."""

    def __init__(self, path_prefix: str, dtype=np.int32):
        self.path_prefix = path_prefix
        self.dtype = np.dtype(dtype)
        if self.dtype not in _DTYPE_CODES:
            raise ValueError(f"unsupported dtype {dtype}")
        os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
        self._bin = open(path_prefix + ".bin", "wb")
        self._offsets: List[int] = [0]

    def add_item(self, tokens: Sequence[int]) -> None:
        arr = np.asarray(tokens, dtype=self.dtype)
        self._bin.write(arr.tobytes())
        self._offsets.append(self._offsets[-1] + arr.size)

    def add_items(self, seqs: Iterable[Sequence[int]]) -> None:
        for s in seqs:
            self.add_item(s)

    def finalize(self) -> None:
        self._bin.close()
        with open(self.path_prefix + ".idx", "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<HHQ", _VERSION,
                                _DTYPE_CODES[self.dtype],
                                len(self._offsets) - 1))
            f.write(np.asarray(self._offsets, np.int64).tobytes())


class IndexedDataset:
    """Read-only memory-mapped view (ref MMapIndexedDataset)."""

    def __init__(self, path_prefix: str):
        with open(path_prefix + ".idx", "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError(f"{path_prefix}.idx: bad magic {magic!r}")
            version, dcode, count = struct.unpack("<HHQ", f.read(12))
            if version != _VERSION:
                raise ValueError(f"unsupported index version {version}")
            self.dtype = np.dtype(_DTYPES[dcode])
            self._offsets = np.frombuffer(f.read(8 * (count + 1)), np.int64)
        self._data = np.memmap(path_prefix + ".bin", dtype=self.dtype,
                               mode="r")
        self._count = int(count)

    def __len__(self) -> int:
        return self._count

    def __getitem__(self, idx: int) -> np.ndarray:
        if idx < 0:
            idx += self._count
        if not 0 <= idx < self._count:
            raise IndexError(idx)
        return np.asarray(
            self._data[self._offsets[idx]:self._offsets[idx + 1]])

    @property
    def sizes(self) -> np.ndarray:
        """Per-sequence lengths — the default curriculum difficulty metric."""
        return np.diff(self._offsets)
