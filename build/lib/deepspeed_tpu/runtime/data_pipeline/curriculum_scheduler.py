"""Curriculum learning difficulty schedules.

Analog of ``deepspeed/runtime/data_pipeline/curriculum_scheduler.py``
(``CurriculumScheduler`` :11): maps the global step to a "difficulty" (for
seqlen-based curricula: the current max sequence length).  Schedule types
match the reference: ``fixed_linear`` / ``fixed_root`` / ``fixed_discrete``
/ ``custom``.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional


class CurriculumScheduler:
    """step → difficulty.

    config keys (matching the reference's JSON schema)::

        {"curriculum_type": "seqlen",
         "min_difficulty": 8, "max_difficulty": 1024,
         "schedule_type": "fixed_linear",
         "schedule_config": {"total_curriculum_step": 10000,
                             "difficulty_step": 8,
                             # fixed_root only:
                             "root_degree": 2,
                             # fixed_discrete only:
                             "difficulty": [...], "max_step": [...]}}
    """

    def __init__(self, config: Dict[str, Any],
                 custom_get_difficulty: Optional[Callable[[int], int]] = None):
        self.config = config
        self.min_difficulty = int(config.get("min_difficulty", 1))
        self.max_difficulty = int(config.get("max_difficulty", self.min_difficulty))
        self.schedule_type = config.get("schedule_type", "fixed_linear")
        sc = config.get("schedule_config", {}) or {}
        self.schedule_config = sc
        self.current_difficulty = self.min_difficulty
        self.first_step = True
        self._custom = custom_get_difficulty

        if self.schedule_type in ("fixed_linear", "fixed_root"):
            if "total_curriculum_step" not in sc:
                raise ValueError(
                    f"{self.schedule_type} schedule requires schedule_config"
                    "['total_curriculum_step']")
            self.total_step = int(sc["total_curriculum_step"])
            self.difficulty_step = int(sc.get("difficulty_step", 1))
            self.root_degree = int(sc.get("root_degree", 2))
        elif self.schedule_type == "fixed_discrete":
            if "difficulty" not in sc or "max_step" not in sc:
                raise ValueError(
                    "fixed_discrete schedule requires schedule_config"
                    "['difficulty'] and ['max_step']")
            self.discrete_difficulty = [int(x) for x in sc["difficulty"]]
            self.discrete_max_step = [int(x) for x in sc["max_step"]]
            if len(self.discrete_max_step) != len(self.discrete_difficulty) - 1:
                raise ValueError("max_step must have len(difficulty)-1 entries")
        elif self.schedule_type == "custom":
            if custom_get_difficulty is None:
                raise ValueError("custom schedule requires custom_get_difficulty")
        else:
            raise ValueError(f"unknown schedule_type {self.schedule_type!r}")

    # ------------------------------------------------------------------
    def _root_difficulty(self, step: int, degree: int) -> int:
        frac = min(1.0, max(0.0, step / self.total_step))
        next_diff = self.min_difficulty + (
            (self.max_difficulty - self.min_difficulty) * frac ** (1.0 / degree))
        next_diff = int(next_diff / self.difficulty_step) * self.difficulty_step
        return min(self.max_difficulty, max(self.min_difficulty, next_diff))

    def get_difficulty(self, global_steps: int) -> int:
        if self.schedule_type == "fixed_linear":
            return self._root_difficulty(global_steps, 1)
        if self.schedule_type == "fixed_root":
            return self._root_difficulty(global_steps, self.root_degree)
        if self.schedule_type == "fixed_discrete":
            for diff, boundary in zip(self.discrete_difficulty, self.discrete_max_step):
                if global_steps <= boundary:
                    return diff
            return self.discrete_difficulty[-1]
        return int(self._custom(global_steps))

    def update_difficulty(self, global_steps: int) -> int:
        self.current_difficulty = self.get_difficulty(global_steps)
        return self.current_difficulty

    def get_current_difficulty(self) -> int:
        return self.current_difficulty

    def state_dict(self) -> Dict[str, Any]:
        return {"current_difficulty": self.current_difficulty}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.current_difficulty = int(state["current_difficulty"])
