"""Data loading.

Analog of ``runtime/dataloader.py`` (DeepSpeedDataLoader) — batches a
map-style or iterable dataset into numpy dict batches sized for
``engine.train_batch``.  Works with torch Datasets, HF datasets, lists of
dicts, or dicts of arrays.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, Optional

import numpy as np


def default_collate(samples) -> Dict[str, np.ndarray]:
    if isinstance(samples[0], dict):
        return {k: np.stack([np.asarray(s[k]) for s in samples]) for k in samples[0]}
    arr = np.stack([np.asarray(s) for s in samples])
    return {"input_ids": arr, "labels": arr}


class DeepSpeedDataLoader:
    def __init__(self, dataset: Any, batch_size: int,
                 collate_fn: Optional[Callable] = None,
                 drop_last: bool = False, shuffle: bool = False, seed: int = 0):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or default_collate
        self.drop_last = drop_last
        self.shuffle = shuffle
        self.seed = seed
        self._epoch = 0

    def __len__(self) -> int:
        n = len(self.dataset)
        return n // self.batch_size if self.drop_last else (n + self.batch_size - 1) // self.batch_size

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        n = len(self.dataset)
        order = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self._epoch)
            rng.shuffle(order)
        self._epoch += 1
        for start in range(0, n, self.batch_size):
            idx = order[start:start + self.batch_size]
            if len(idx) < self.batch_size and self.drop_last:
                break
            yield self.collate_fn([self.dataset[int(i)] for i in idx])


class RepeatingLoader:
    """Wraps an iterator to repeat forever (ref: runtime/dataloader.py)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)
