"""LR schedules — same names/params as the reference ``runtime/lr_schedules.py``
(LRRangeTest :277, OneCycle :364, WarmupLR :612, WarmupDecayLR :712,
WarmupCosineLR :781).

Schedules are host-side callables ``step -> lr``; the engine passes the
scalar into the jitted train step each iteration so schedule changes never
trigger recompiles.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

VALID_LR_SCHEDULES = ["LRRangeTest", "OneCycle", "WarmupLR", "WarmupDecayLR", "WarmupCosineLR"]


class LRSchedule:
    """Minimal scheduler object with the reference's step/get_lr surface."""

    def __init__(self, fn: Callable[[int], float], name: str = "custom"):
        self._fn = fn
        self.name = name
        self.last_batch_iteration = -1
        self._last_lr = [fn(0)]

    def step(self, last_batch_iteration: Optional[int] = None) -> None:
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        self._last_lr = [self._fn(max(0, last_batch_iteration))]

    def get_lr(self):
        return list(self._last_lr)

    def get_last_lr(self):
        return list(self._last_lr)

    def state_dict(self) -> Dict[str, Any]:
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.last_batch_iteration = int(sd["last_batch_iteration"])
        self._last_lr = [self._fn(max(0, self.last_batch_iteration))]

    def __call__(self, step: int) -> float:
        return self._fn(step)


def _warmup(step: int, warmup_min_lr: float, warmup_max_lr: float,
            warmup_num_steps: int, warmup_type: str = "log") -> float:
    if warmup_num_steps <= 0 or step >= warmup_num_steps:
        return warmup_max_lr
    if warmup_type == "log":
        # ref WarmupLR: min + (max-min) * log(1+step)/log(1+warmup)
        gamma = math.log(1 + step) / math.log(1 + warmup_num_steps)
    else:
        gamma = step / warmup_num_steps
    return warmup_min_lr + (warmup_max_lr - warmup_min_lr) * gamma


def warmup_lr(warmup_min_lr: float = 0.0, warmup_max_lr: float = 0.001,
              warmup_num_steps: int = 1000, warmup_type: str = "log", **_) -> LRSchedule:
    return LRSchedule(
        lambda s: _warmup(s, warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type),
        "WarmupLR")


def warmup_decay_lr(total_num_steps: int, warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                    warmup_type: str = "log", **_) -> LRSchedule:
    def fn(s: int) -> float:
        if s < warmup_num_steps:
            return _warmup(s, warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)
        frac = max(0.0, (total_num_steps - s) / max(1.0, total_num_steps - warmup_num_steps))
        return warmup_max_lr * frac

    return LRSchedule(fn, "WarmupDecayLR")


def warmup_cosine_lr(total_num_steps: int, warmup_min_ratio: float = 0.0,
                     warmup_num_steps: int = 1000, cos_min_ratio: float = 0.0001,
                     warmup_type: str = "log", lr: float = 0.001, **_) -> LRSchedule:
    def fn(s: int) -> float:
        if s < warmup_num_steps:
            ratio = _warmup(s, warmup_min_ratio, 1.0, warmup_num_steps, warmup_type)
        else:
            progress = min(1.0, (s - warmup_num_steps) / max(1, total_num_steps - warmup_num_steps))
            ratio = cos_min_ratio + (1 - cos_min_ratio) * 0.5 * (1 + math.cos(math.pi * progress))
        return lr * ratio

    return LRSchedule(fn, "WarmupCosineLR")


def lr_range_test(lr_range_test_min_lr: float = 1e-3, lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0,
                  lr_range_test_staircase: bool = False, **_) -> LRSchedule:
    def fn(s: int) -> float:
        interval = s / lr_range_test_step_size
        if lr_range_test_staircase:
            interval = math.floor(interval)
        return lr_range_test_min_lr * (1 + interval * lr_range_test_step_rate)

    return LRSchedule(fn, "LRRangeTest")


def one_cycle(cycle_min_lr: float = 1e-3, cycle_max_lr: float = 1e-2,
              cycle_first_step_size: int = 2000, cycle_second_step_size: Optional[int] = None,
              decay_step_size: int = 0, decay_lr_rate: float = 0.0, **_) -> LRSchedule:
    second = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size

    def fn(s: int) -> float:
        if s <= cycle_first_step_size:
            frac = s / cycle_first_step_size
            return cycle_min_lr + (cycle_max_lr - cycle_min_lr) * frac
        if s <= cycle_first_step_size + second:
            frac = (s - cycle_first_step_size) / second
            return cycle_max_lr - (cycle_max_lr - cycle_min_lr) * frac
        if decay_step_size > 0:
            decay_steps = (s - cycle_first_step_size - second) / decay_step_size
            return cycle_min_lr / (1 + decay_steps * decay_lr_rate)
        return cycle_min_lr

    return LRSchedule(fn, "OneCycle")


_FACTORIES = {
    "WarmupLR": warmup_lr,
    "WarmupDecayLR": warmup_decay_lr,
    "WarmupCosineLR": warmup_cosine_lr,
    "LRRangeTest": lr_range_test,
    "OneCycle": one_cycle,
}


def build_lr_schedule(sched_type: str, params: Dict[str, Any],
                      base_lr: Optional[float] = None) -> LRSchedule:
    if sched_type not in _FACTORIES:
        raise ValueError(f"unknown scheduler '{sched_type}'; valid: {VALID_LR_SCHEDULES}")
    params = dict(params)
    if sched_type == "WarmupCosineLR" and "lr" not in params and base_lr is not None:
        params["lr"] = base_lr
    return _FACTORIES[sched_type](**params)


def constant_lr(lr: float) -> LRSchedule:
    return LRSchedule(lambda s: lr, "Constant")
