"""ZenFlow — stall-free optimizer offload via importance-split updates.

Analog of ``deepspeed/runtime/zenflow/`` (+ ``ops/adam/zenflow_torch_adam.py
:43``): plain ZeRO-Offload stalls the accelerator while the CPU runs the
full optimizer step.  ZenFlow splits gradients by importance: the top-k
columns of each weight (by squared norm) are updated *immediately* with
device-resident Adam state, while the cold remainder accumulates on the
host and is applied asynchronously every ``update_interval`` steps — the
device never waits on the host path.

TPU realisation: the hot update is a jitted gather→adam→scatter on a
fixed-k column set (``jax.lax.top_k`` keeps shapes static), so XLA fuses it
into the step.  Hot columns are zeroed out of the gradient before it joins
the host accumulator, so hot and cold partitions never double-apply; the
async host Adam produces a *pending delta* that is added to the device
params at the start of the next step after the worker lands — the same
eventual-consistency contract as the reference's async CPU AdamW.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


def _hot_update(param, grad, m, v, idx, lr, beta1, beta2, eps, step):
    """Adam on the selected columns only (gather → update → scatter).
    Returns (new_param, new_m, new_v, cold_grad) where cold_grad has the
    hot columns zeroed."""
    gf = grad.astype(jnp.float32)
    g_hot = jnp.take(gf, idx, axis=-1)
    m_hot = beta1 * jnp.take(m, idx, axis=-1) + (1 - beta1) * g_hot
    v_hot = beta2 * jnp.take(v, idx, axis=-1) + (1 - beta2) * g_hot ** 2
    mh = m_hot / (1 - beta1 ** step)
    vh = v_hot / (1 - beta2 ** step)
    delta = lr * mh / (jnp.sqrt(vh) + eps)
    p32 = param.astype(jnp.float32)
    new_p = p32.at[..., idx].set(jnp.take(p32, idx, axis=-1) - delta)
    cold = gf.at[..., idx].set(0.0)
    return (new_p.astype(param.dtype), m.at[..., idx].set(m_hot),
            v.at[..., idx].set(v_hot), cold)


def _topk_columns(g, k: int):
    norms = (g.astype(jnp.float32) ** 2).reshape(-1, g.shape[-1]).sum(axis=0)
    return jax.lax.top_k(norms, k)[1]


class ZenFlowOptimizer:
    """Importance-split Adam over a param pytree.

    ``topk_ratio``: fraction of columns updated on device each step.
    ``update_interval``: cold (host) update cadence in steps.
    ``overlap``: run the host Adam on a worker thread (stall-free mode).
    """

    def __init__(self, params: Any, lr: float = 1e-3, betas=(0.9, 0.999),
                 eps: float = 1e-8, topk_ratio: float = 0.1,
                 update_interval: int = 4, overlap: bool = True):
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.topk_ratio = topk_ratio
        self.update_interval = update_interval
        self.overlap = overlap
        self.step_count = 0
        self.cold_updates = 0
        is_mat = lambda x: x.ndim >= 2  # noqa: E731
        # device Adam moments, touched only on hot columns
        self._dev_m = jax.tree.map(
            lambda x: jnp.zeros(x.shape, jnp.float32) if is_mat(x) else None, params)
        self._dev_v = jax.tree.map(
            lambda x: jnp.zeros(x.shape, jnp.float32) if is_mat(x) else None, params)
        # host Adam state, touched only on cold entries
        self._host_m = jax.tree.map(
            lambda x: np.zeros(x.shape, np.float32), params)
        self._host_v = jax.tree.map(
            lambda x: np.zeros(x.shape, np.float32), params)
        self._cold_acc = jax.tree.map(
            lambda x: np.zeros(x.shape, np.float32), params)
        self._cold_steps = 0
        self._pending_delta: Optional[Any] = None
        self._worker: Optional[threading.Thread] = None
        self._hot_jit = jax.jit(_hot_update)
        self._apply_delta_jit = jax.jit(
            lambda p, d: jax.tree.map(
                lambda a, b: (a.astype(jnp.float32) + b).astype(a.dtype), p, d))

    def _k(self, width: int) -> int:
        return max(1, int(round(width * self.topk_ratio)))

    # ------------------------------------------------------------------
    def step(self, params: Any, grads: Any) -> Any:
        """One ZenFlow step → new params."""
        self.wait()
        if self._pending_delta is not None:  # land the async cold update
            params = self._apply_delta_jit(
                params, jax.device_put(self._pending_delta))
            self._pending_delta = None
        self.step_count += 1
        step = self.step_count

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = jax.tree_util.tree_flatten(grads)[0]
        none_leaf = lambda x: x is None  # noqa: E731
        flat_m = jax.tree_util.tree_flatten(self._dev_m, is_leaf=none_leaf)[0]
        flat_v = jax.tree_util.tree_flatten(self._dev_v, is_leaf=none_leaf)[0]
        out_p, out_m, out_v, cold_g = [], [], [], []
        for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v):
            if m is None:  # vectors/scalars: all-cold
                out_p.append(p)
                out_m.append(None)
                out_v.append(None)
                cold_g.append(g.astype(jnp.float32))
                continue
            idx = _topk_columns(g, self._k(p.shape[-1]))
            # step as a traced array so per-step calls hit the jit cache
            p2, m2, v2, cg = self._hot_jit(p, g, m, v, idx,
                                           jnp.float32(self.lr), self.beta1,
                                           self.beta2, self.eps,
                                           jnp.float32(step))
            out_p.append(p2)
            out_m.append(m2)
            out_v.append(v2)
            cold_g.append(cg)
        self._dev_m = jax.tree_util.tree_unflatten(treedef, out_m)
        self._dev_v = jax.tree_util.tree_unflatten(treedef, out_v)
        new_params = jax.tree_util.tree_unflatten(treedef, out_p)

        host_cold = [np.asarray(jax.device_get(g), np.float32) for g in cold_g]
        flat_acc = jax.tree_util.tree_flatten(self._cold_acc)[0]
        for acc, g in zip(flat_acc, host_cold):
            acc += g
        self._cold_steps += 1
        if self._cold_steps >= self.update_interval:
            n = self._cold_steps
            self._cold_steps = 0
            if self.overlap:
                self._worker = threading.Thread(target=self._cold_update,
                                                args=(n,), daemon=True)
                self._worker.start()
            else:
                self._cold_update(n)
        return new_params

    def _cold_update(self, n_accum: int) -> None:
        """Host Adam on the averaged cold grads → pending delta.  Entries
        with zero accumulated grad (the hot columns) see only moment decay,
        matching the reference's disjoint partitions."""
        self.cold_updates += 1
        step = self.cold_updates

        def upd(m, v, acc):
            g = acc / max(1, n_accum)
            m *= self.beta1
            m += (1 - self.beta1) * g
            v *= self.beta2
            v += (1 - self.beta2) * g * g
            mh = m / (1 - self.beta1 ** step)
            vh = v / (1 - self.beta2 ** step)
            delta = (-self.lr * mh / (np.sqrt(vh) + self.eps)).astype(np.float32)
            # hot columns contributed no grad this round: suppress their
            # decay-only drift so only cold entries move
            delta[acc == 0] = 0.0
            acc[:] = 0
            return delta

        self._pending_delta = jax.tree.map(upd, self._host_m, self._host_v,
                                           self._cold_acc)

    def wait(self) -> None:
        if self._worker is not None:
            self._worker.join()
            self._worker = None

    def flush(self, params: Any) -> Any:
        """Force any pending/partial cold state to land (checkpoint
        boundary)."""
        self.wait()
        if self._cold_steps:
            self._cold_update(self._cold_steps)
            self._cold_steps = 0
        if self._pending_delta is not None:
            params = self._apply_delta_jit(params,
                                           jax.device_put(self._pending_delta))
            self._pending_delta = None
        return params

    def state_dict(self) -> Dict[str, Any]:
        """Complete optimizer state: host AND device moments, the partial
        cold accumulator, and any un-landed pending delta — so a
        save/resume continues the exact trajectory (hot-column Adam state
        and in-flight cold work included)."""
        self.wait()
        none_leaf = lambda x: x is None  # noqa: E731
        to_np = lambda t: jax.tree.map(  # noqa: E731
            lambda x: None if x is None else np.asarray(jax.device_get(x)),
            t, is_leaf=none_leaf)
        # host state is mutated IN PLACE by _cold_update — snapshot copies
        # so later steps can't corrupt a saved checkpoint
        copy_np = lambda t: jax.tree.map(np.copy, t)  # noqa: E731
        return {"step": self.step_count, "cold_updates": self.cold_updates,
                "cold_steps": self._cold_steps,
                "host_m": copy_np(self._host_m),
                "host_v": copy_np(self._host_v),
                "cold_acc": copy_np(self._cold_acc),
                "dev_m": to_np(self._dev_m), "dev_v": to_np(self._dev_v),
                "pending_delta": None if self._pending_delta is None
                else copy_np(self._pending_delta)}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.wait()
        self.step_count = int(state["step"])
        self.cold_updates = int(state["cold_updates"])
        self._cold_steps = int(state.get("cold_steps", 0))
        copy_np = lambda t: jax.tree.map(np.copy, t)  # noqa: E731
        self._host_m = copy_np(state["host_m"])
        self._host_v = copy_np(state["host_v"])
        if "cold_acc" in state:
            self._cold_acc = copy_np(state["cold_acc"])
        none_leaf = lambda x: x is None  # noqa: E731
        if "dev_m" in state:
            to_dev = lambda t: jax.tree.map(  # noqa: E731
                lambda x: None if x is None else jnp.asarray(x),
                t, is_leaf=none_leaf)
            self._dev_m = to_dev(state["dev_m"])
            self._dev_v = to_dev(state["dev_v"])
        pend = state.get("pending_delta")
        self._pending_delta = None if pend is None else copy_np(pend)
