"""OnDevice — meta/abstract model construction.

Analog of ``deepspeed/utils/init_on_device.py`` (``OnDevice``): build a
model without allocating real storage ("meta" device) or directly on a
target device/dtype.  Functionally: ``device="meta"`` evaluates the init
shape-only (``jax.eval_shape``); a real device jits the init with placement.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp


class OnDevice:
    """Usage::

        with OnDevice(dtype=jnp.bfloat16, device="meta") as ctx:
            shapes = ctx.init(init_fn, rng)      # ShapeDtypeStructs only

        with OnDevice(dtype=jnp.bfloat16) as ctx:  # default device
            params = ctx.init(init_fn, rng)
    """

    def __init__(self, dtype=None, device: Optional[str] = None,
                 enabled: bool = True):
        self.dtype = dtype
        self.device = device
        self.enabled = enabled

    def __enter__(self) -> "OnDevice":
        return self

    def __exit__(self, *exc) -> None:
        return None

    def init(self, init_fn: Callable, *args) -> Any:
        fn = init_fn
        if self.dtype is not None:
            base = init_fn

            def fn(*a):
                return jax.tree.map(
                    lambda x: x.astype(self.dtype)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x, base(*a))

        if not self.enabled:
            return fn(*args)
        if self.device == "meta":
            return jax.eval_shape(fn, *args)
        if self.device is None:
            return jax.jit(fn)(*args)
        dev = jax.devices(self.device)[0] if isinstance(self.device, str) \
            else self.device
        return jax.device_put(jax.jit(fn)(*args), dev)
