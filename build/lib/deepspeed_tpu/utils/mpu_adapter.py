"""Megatron ``mpu`` interface adapter.

The reference accepts an ``mpu`` object (Megatron's model-parallel unit)
everywhere group information is needed (``deepspeed.initialize(mpu=...)``,
``groups.initialize(mpu=mpu)``).  :class:`MpuAdapter` exposes that
interface backed by the mesh topology, so ported Megatron-style callers
keep their ``mpu.get_*`` call sites; conversely :func:`topology_from_mpu`
builds a mesh from a foreign mpu's sizes.
"""

from __future__ import annotations

from typing import Optional

from deepspeed_tpu.parallel.topology import MeshTopology, get_topology


class MpuAdapter:
    """Megatron mpu surface over a MeshTopology (ref utils/groups.py mpu
    consumption: get_model_parallel_world_size/rank, get_data_parallel_*,
    get_tensor_model_parallel_*, get_pipeline_model_parallel_*)."""

    def __init__(self, topology: Optional[MeshTopology] = None):
        self._topo = topology

    @property
    def topo(self) -> MeshTopology:
        t = self._topo or get_topology()
        if t is None:
            raise RuntimeError("mpu adapter needs an initialized topology")
        return t

    # -- tensor/model parallel -----------------------------------------
    def get_model_parallel_world_size(self) -> int:
        return self.topo.tp_size

    get_tensor_model_parallel_world_size = get_model_parallel_world_size

    def get_model_parallel_rank(self) -> int:
        # single-controller SPMD: rank-dependent code paths don't exist;
        # report the process's first local device's coordinate
        return 0

    get_tensor_model_parallel_rank = get_model_parallel_rank

    def get_model_parallel_group(self):
        return ("tensor",)  # mesh-axis handle usable with shard_map

    get_tensor_model_parallel_group = get_model_parallel_group

    # -- data parallel --------------------------------------------------
    def get_data_parallel_world_size(self) -> int:
        return self.topo.dp_size

    def get_data_parallel_rank(self) -> int:
        return 0

    def get_data_parallel_group(self):
        return ("data",)

    # -- pipeline parallel ----------------------------------------------
    def get_pipeline_model_parallel_world_size(self) -> int:
        return self.topo.pp_size

    def get_pipeline_model_parallel_rank(self) -> int:
        return 0

    def get_pipeline_model_parallel_group(self):
        return ("pipe",)

    # -- sequence parallel (ALST parallel_state_sp parity) ---------------
    def get_sequence_parallel_world_size(self) -> int:
        return self.topo.sp_size

    def get_sequence_parallel_group(self):
        return ("seq",)


def topology_from_mpu(mpu) -> MeshTopology:
    """Build a mesh from a foreign Megatron mpu's sizes (ref
    engine._configure_distributed_model mpu path)."""
    sizes = {}
    tp = getattr(mpu, "get_tensor_model_parallel_world_size",
                 getattr(mpu, "get_model_parallel_world_size", lambda: 1))()
    pp = getattr(mpu, "get_pipeline_model_parallel_world_size", lambda: 1)()
    dp = getattr(mpu, "get_data_parallel_world_size", lambda: 1)()
    if tp > 1:
        sizes["tensor"] = tp
    if pp > 1:
        sizes["pipe"] = pp
    if dp > 1:
        sizes["data"] = dp
    return MeshTopology(sizes or None)
