"""Elastic agent: supervise workers, restart on failure, re-shard on resize.

Analog of the reference's ``DSElasticAgent`` (elasticity/elastic_agent.py:32,
built on torch-elastic): monitors the local worker processes
(ref _invoke_run :127), restarts the group up to ``max_restarts`` times, and
on a world-size change relaunches with new DSTPU_NUM_PROCS so workers
re-shard from the universal checkpoint.

TPU differences: there is no rendezvous store to re-join — the launcher
recomputes the world layout and workers rebuild the mesh; parameter state
travels through the atomic universal checkpoint rather than NCCL broadcast.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger


class WorkerSpec:
    def __init__(self, cmd: List[str], env: Optional[Dict[str, str]] = None,
                 local_world_size: int = 1):
        self.cmd = list(cmd)
        self.env = dict(env or {})
        self.local_world_size = int(local_world_size)


class DSElasticAgent:
    """Run a worker group, restarting on failure (ref elastic_agent.py:32)."""

    def __init__(self, spec: WorkerSpec, max_restarts: int = 3,
                 monitor_interval: float = 1.0,
                 world_size_fn: Optional[Callable[[], int]] = None):
        self.spec = spec
        self.max_restarts = int(max_restarts)
        self.monitor_interval = float(monitor_interval)
        self._world_size_fn = world_size_fn or (lambda: spec.local_world_size)
        self.restarts = 0

    def _start_group(self, world_size: int) -> List[subprocess.Popen]:
        procs = []
        for rank in range(world_size):
            env = {**os.environ, **self.spec.env,
                   "DSTPU_NUM_PROCS": str(world_size),
                   "DSTPU_PROC_ID": str(rank),
                   "LOCAL_RANK": str(rank),
                   "RANK": str(rank),
                   "WORLD_SIZE": str(world_size)}
            procs.append(subprocess.Popen(self.spec.cmd, env=env))
        return procs

    def _stop_group(self, procs: List[subprocess.Popen]) -> None:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:  # pragma: no cover
                p.kill()

    def run(self) -> int:
        """Monitor loop (ref _invoke_run :127): HEALTHY → poll; a failed
        worker triggers a group restart; world-size change re-launches."""
        world = self._world_size_fn()
        procs = self._start_group(world)
        while True:
            time.sleep(self.monitor_interval)
            codes = [p.poll() for p in procs]
            if all(c == 0 for c in codes):
                return 0
            if any(c not in (None, 0) for c in codes):
                self.restarts += 1
                if self.restarts > self.max_restarts:
                    logger.error("elastic agent: max_restarts exceeded")
                    self._stop_group(procs)
                    return 1
                logger.warning(f"elastic agent: worker failed (codes={codes}); "
                               f"restart {self.restarts}/{self.max_restarts}")
                self._stop_group(procs)
                world = self._world_size_fn()
                procs = self._start_group(world)
                continue
            new_world = self._world_size_fn()
            if new_world != world:
                logger.warning(f"elastic agent: world size {world} → {new_world}; "
                               "restarting group to re-shard")
                self._stop_group(procs)
                world = new_world
                procs = self._start_group(world)
