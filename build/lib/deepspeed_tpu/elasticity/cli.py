"""`dstpu_elastic` — elastic-config checker CLI (ref bin/ds_elastic)."""

from __future__ import annotations

import argparse
import json
import sys

from deepspeed_tpu.elasticity.elasticity import compute_elastic_config


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="dstpu_elastic")
    p.add_argument("-c", "--config", required=True, help="ds config JSON path")
    p.add_argument("-w", "--world-size", type=int, default=0)
    args = p.parse_args(argv)
    with open(args.config) as f:
        ds_config = json.load(f)
    if args.world_size > 0:
        batch, gpus, micro = compute_elastic_config(
            ds_config, world_size=args.world_size, return_microbatch=True)
        print(f"world size {args.world_size} is valid; "
              f"micro batch per chip = {micro}")
    else:
        batch, gpus = compute_elastic_config(ds_config)
    print(f"final effective batch size: {batch}")
    print(f"valid chip counts: {gpus}")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
