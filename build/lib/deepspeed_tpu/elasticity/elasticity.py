"""Elastic training configuration.

Analog of the reference's elasticity v1 (``elasticity/elasticity.py``):
pre-compute the set of chip counts at which a job can (re)start while
keeping the SAME effective batch size — so a preempted TPU slice can resume
on fewer/more chips with identical optimization behavior
(ref _get_compatible_gpus_v01 :83, compute_elastic_config :233).

On TPU the "restart at a new world size" step is: reload the universal
checkpoint (deepspeed_tpu/checkpoint/universal.py) under a new mesh — XLA
recompiles, the atomic per-param checkpoint re-shards automatically.
"""

from __future__ import annotations

import json
import math
from typing import Any, Dict, List, Tuple

LATEST_ELASTICITY_VERSION = 0.2
MINIMUM_DEEPSPEED_VERSION = "0.1.0"


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


class ElasticityConfig:
    """Parsed `elasticity` config block (ref elasticity/config.py).

    Keys: enabled, max_train_batch_size, micro_batch_sizes, min_gpus,
    max_gpus, min_time, prefer_larger_batch, ignore_non_elastic_batch_info,
    version; v2 adds model_parallel_size / num_gpus_per_node.
    """

    def __init__(self, d: Dict[str, Any]):
        self.enabled = bool(d.get("enabled", False))
        self.max_train_batch_size = int(d.get("max_train_batch_size", 2000))
        self.micro_batches = [int(m) for m in d.get("micro_batch_sizes", [2, 4, 6])]
        if any(m <= 0 for m in self.micro_batches):
            raise ElasticityConfigError("micro_batch_sizes must be positive")
        self.min_gpus = int(d.get("min_gpus", 1))
        self.max_gpus = int(d.get("max_gpus", 10000))
        if self.min_gpus < 1 or self.max_gpus < self.min_gpus:
            raise ElasticityConfigError(
                f"invalid gpu range [{self.min_gpus}, {self.max_gpus}]")
        self.min_time = int(d.get("min_time", 0))
        self.version = float(d.get("version", LATEST_ELASTICITY_VERSION))
        self.prefer_larger_batch = bool(d.get("prefer_larger_batch", True))
        self.ignore_non_elastic_batch_info = bool(
            d.get("ignore_non_elastic_batch_info", False))
        self.model_parallel_size = int(d.get("model_parallel_size", 1))
        self.num_gpus_per_node = int(d.get("num_gpus_per_node", 1))


def get_valid_gpus(batch_size: int, micro_batches: List[int],
                   min_valid_gpus: int, max_valid_gpus: int) -> List[int]:
    """All chip counts that evenly tile `batch_size` with some micro batch.

    Ref: _get_valid_gpus (elasticity.py:63).
    """
    valid = set()
    for mb in micro_batches:
        if batch_size % mb != 0:
            continue
        max_gpus = batch_size // mb
        for i in range(1, max_gpus + 1):
            if max_gpus % i == 0 and min_valid_gpus <= i <= max_valid_gpus:
                valid.add(i)
    return sorted(valid)


def get_compatible_gpus_v01(micro_batches: List[int],
                            max_acceptable_batch_size: int,
                            min_gpus: int = 1,
                            max_gpus: int = 10000,
                            prefer_larger: bool = True
                            ) -> Tuple[int, List[int]]:
    """Pick the final batch size ≤ max with the largest set of valid chip
    counts. Ref: _get_compatible_gpus_v01 (elasticity.py:83)."""
    if not micro_batches:
        raise ElasticityConfigError("micro_batch_sizes is empty")
    if max(micro_batches) > max_acceptable_batch_size:
        raise ElasticityConfigError(
            f"micro batch {max(micro_batches)} exceeds "
            f"max_train_batch_size {max_acceptable_batch_size}")
    base = math.lcm(*micro_batches)
    if base <= max_acceptable_batch_size:
        candidate_batches = list(range(base, max_acceptable_batch_size + 1, base))
    else:
        # No batch is a multiple of every micro batch; fall back to multiples
        # of each micro batch individually, still under the cap.
        candidate_batches = sorted({m * i for m in micro_batches
                                    for i in range(1, max_acceptable_batch_size // m + 1)})

    best_batch, best_gpus = 0, []
    for b in candidate_batches:
        gpus = get_valid_gpus(b, micro_batches, min_gpus, max_gpus)
        better = (len(gpus), b if prefer_larger else -b) > \
                 (len(best_gpus), best_batch if prefer_larger else -best_batch)
        if better:
            best_batch, best_gpus = b, gpus
    if not best_gpus:
        raise ElasticityConfigError(
            f"no valid chip count in [{min_gpus},{max_gpus}] for "
            f"batch ≤ {max_acceptable_batch_size} with micro batches {micro_batches}")
    return best_batch, best_gpus


def get_compatible_gpus_v02(micro_batches, max_acceptable_batch_size,
                            current_num_gpus, min_gpus, max_gpus,
                            prefer_larger, num_gpus_per_node,
                            model_parallel_size) -> Tuple[int, List[int]]:
    """v2: chip counts must also be multiples of mp_size (whole model
    replicas). Ref: _get_compatible_gpus_v02 (elasticity.py:129)."""
    if model_parallel_size > 1:
        if num_gpus_per_node % model_parallel_size != 0:
            raise ElasticityConfigError(
                f"model_parallel_size {model_parallel_size} must divide "
                f"chips per node {num_gpus_per_node}")
    if max_gpus < model_parallel_size:
        raise ElasticityConfigError(
            f"max_gpus {max_gpus} < model_parallel_size {model_parallel_size}")
    dp_min = -(-min_gpus // model_parallel_size)  # ceil: stay ≥ min_gpus
    dp_max = max_gpus // model_parallel_size      # floor: stay ≤ max_gpus
    batch, dp_counts = get_compatible_gpus_v01(
        micro_batches, max_acceptable_batch_size, dp_min, dp_max, prefer_larger)
    return batch, [c * model_parallel_size for c in dp_counts]


def compute_elastic_config(ds_config: Dict[str, Any], target_deepspeed_version: str = "",
                           world_size: int = 0, return_microbatch: bool = False):
    """(final_batch_size, valid_gpus[, micro_batch]) for this config.

    Ref: compute_elastic_config (elasticity.py:233).  When `world_size` > 0
    also validates it and resolves the per-chip micro batch.
    """
    if "elasticity" not in ds_config:
        raise ElasticityConfigError("'elasticity' block missing from config")
    cfg = ElasticityConfig(ds_config["elasticity"])
    if not cfg.enabled:
        raise ElasticityConfigError("elasticity is not enabled")

    if cfg.version >= 0.2 and cfg.model_parallel_size > 1:
        final_batch, valid_gpus = get_compatible_gpus_v02(
            cfg.micro_batches, cfg.max_train_batch_size, world_size,
            cfg.min_gpus, cfg.max_gpus, cfg.prefer_larger_batch,
            cfg.num_gpus_per_node, cfg.model_parallel_size)
    else:
        final_batch, valid_gpus = get_compatible_gpus_v01(
            cfg.micro_batches, cfg.max_train_batch_size,
            cfg.min_gpus, cfg.max_gpus, cfg.prefer_larger_batch)

    if world_size > 0:
        dp = world_size // cfg.model_parallel_size if cfg.version >= 0.2 else world_size
        if world_size not in valid_gpus:
            raise ElasticityIncompatibleWorldSize(
                f"world size {world_size} not in valid set {valid_gpus}")
        micro = _resolve_micro_batch(final_batch, dp, cfg.micro_batches,
                                     cfg.prefer_larger_batch)
        if return_microbatch:
            return final_batch, valid_gpus, micro
    if return_microbatch:
        return final_batch, valid_gpus, None
    return final_batch, valid_gpus


def _resolve_micro_batch(batch: int, dp: int, micro_batches: List[int],
                         prefer_larger: bool) -> int:
    per_rank = batch // dp
    candidates = [m for m in sorted(micro_batches, reverse=prefer_larger)
                  if per_rank % m == 0]
    if not candidates:
        raise ElasticityIncompatibleWorldSize(
            f"no micro batch in {micro_batches} divides per-rank batch {per_rank}")
    return candidates[0]


def elasticity_enabled(ds_config: Dict[str, Any]) -> bool:
    return bool(ds_config.get("elasticity", {}).get("enabled", False))


def ensure_immutable_elastic_config(runtime_elastic_config_dict,
                                    stored_elastic_config_dict) -> None:
    """A resumed job must not silently change its elastic envelope.

    Ref: ensure_immutable_elastic_config (elasticity.py:202).
    """
    if json.dumps(runtime_elastic_config_dict, sort_keys=True) != \
            json.dumps(stored_elastic_config_dict, sort_keys=True):
        raise ElasticityConfigError(
            "elasticity config changed across restarts; set "
            "ignore_elastic_config_changes to override")
