"""Accelerator selection: ``get_accelerator()`` / ``set_accelerator()``.

Analog of ``accelerator/real_accelerator.py:51``.  Selection order:
1. ``DS_ACCELERATOR`` env var ("tpu" | "gpu" | "cpu") — explicit override,
   mirroring the reference's env-based selection.
2. Probe JAX platforms: tpu > gpu > cpu (the reference probes module
   imports; here a platform probe plays that role).
"""

from __future__ import annotations

import os
from typing import Optional

from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator
from deepspeed_tpu.utils.logging import logger

_ACCELERATOR: Optional[DeepSpeedAccelerator] = None

_KNOWN = ("tpu", "gpu", "cuda", "cpu")


def _probe_platform() -> str:
    import jax

    for platform in ("tpu", "gpu"):
        try:
            if jax.devices(platform):
                return platform
        except RuntimeError:
            continue
    return "cpu"


def _make(name: str) -> DeepSpeedAccelerator:
    if name == "cpu":
        from deepspeed_tpu.accelerator.cpu_accelerator import CPU_Accelerator

        return CPU_Accelerator()
    from deepspeed_tpu.accelerator.tpu_accelerator import TPU_Accelerator

    return TPU_Accelerator(platform="gpu" if name in ("gpu", "cuda") else "tpu")


def get_accelerator() -> DeepSpeedAccelerator:
    global _ACCELERATOR
    if _ACCELERATOR is None:
        name = os.environ.get("DS_ACCELERATOR", "").lower()
        if name and name not in _KNOWN:
            raise ValueError(f"DS_ACCELERATOR={name!r} not in {_KNOWN}")
        if not name:
            name = _probe_platform()
        _ACCELERATOR = _make(name)
        logger.info(f"accelerator: {_ACCELERATOR.device_name()} "
                    f"(comm backend {_ACCELERATOR.communication_backend_name()})")
    return _ACCELERATOR


def set_accelerator(accel: DeepSpeedAccelerator) -> None:
    global _ACCELERATOR
    _ACCELERATOR = accel


def is_current_accelerator_supported() -> bool:
    return get_accelerator().device_name().split(":")[0] in _KNOWN
