from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator
from deepspeed_tpu.accelerator.real_accelerator import (get_accelerator,
                                                        is_current_accelerator_supported,
                                                        set_accelerator)

__all__ = ["DeepSpeedAccelerator", "get_accelerator", "set_accelerator",
           "is_current_accelerator_supported"]
