"""TPU accelerator backend (drives any PJRT device: tpu, gpu, cpu).

TPU-native analog of ``accelerator/cuda_accelerator.py``: device handles are
JAX devices, memory stats come from PJRT, RNG state is a functional PRNG key
held in a mutable slot for API parity with the torch-style surface.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np

from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator


class TPU_Accelerator(DeepSpeedAccelerator):

    def __init__(self, platform: str = "tpu"):
        super().__init__()
        self._name = platform
        self._communication_backend_name = "xla"
        self._compile_backend = "jax.jit"
        self._current = 0
        self._seed = 0
        self._key = jax.random.PRNGKey(0)

    # -- identification -------------------------------------------------
    def is_synchronized_device(self) -> bool:
        return False  # async dispatch queue, like CUDA streams

    # -- devices --------------------------------------------------------
    def _devices(self):
        try:
            return jax.devices(self._name)
        except RuntimeError:
            return jax.devices()

    def device(self, device_index: Optional[int] = None):
        devs = self._devices()
        return devs[device_index if device_index is not None else self._current]

    def device_count(self) -> int:
        return len(self._devices())

    def set_device(self, device_index: int) -> None:
        self._current = device_index

    def current_device(self) -> int:
        return self._current

    def is_available(self) -> bool:
        try:
            return len(jax.devices(self._name)) > 0
        except RuntimeError:
            return False

    # -- RNG ------------------------------------------------------------
    def random(self):
        return jax.random

    def set_rng_state(self, new_state, device_index: Optional[int] = None) -> None:
        self._key = jax.numpy.asarray(np.asarray(new_state, dtype=np.uint32))

    def get_rng_state(self, device_index: Optional[int] = None):
        return np.asarray(self._key)

    def manual_seed(self, seed: int) -> None:
        self._seed = int(seed)
        self._key = jax.random.PRNGKey(self._seed)

    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        """Split and return a fresh PRNG key (functional RNG convenience)."""
        self._key, sub = jax.random.split(self._key)
        return sub

    # -- sync -----------------------------------------------------------
    def synchronize(self, device_index: Optional[int] = None) -> None:
        for d in self._devices():
            try:
                d.synchronize_all_activity()
            except Exception:
                pass
        jax.effects_barrier()

    # -- memory ---------------------------------------------------------
    def memory_stats(self, device_index: Optional[int] = None) -> Dict[str, int]:
        try:
            stats = self.device(device_index).memory_stats() or {}
        except Exception:
            stats = {}
        return {k: int(v) for k, v in stats.items()}

    def total_memory(self, device_index: Optional[int] = None) -> int:
        stats = self.memory_stats(device_index)
        return stats.get("bytes_limit", stats.get("bytes_reservable_limit", 0))

    # -- dtypes ---------------------------------------------------------
    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        return True  # emulated via f32 accumulate on MXU

    # -- misc -----------------------------------------------------------
    def communication_backend_name(self) -> str:
        return self._communication_backend_name
