"""Inference engine (v1-equivalent).

Analog of ``deepspeed.init_inference`` → ``InferenceEngine``
(ref inference/engine.py:40): wraps a model config + params, applies TP
sharding via the same ShardingRules as training (AutoTP-equivalent), and
serves greedy/sampled generation with a static KV cache that keeps shapes
fixed for XLA.  The FastGen-equivalent ragged/continuous-batching engine
lives in ``inference/v2`` (blocked KV cache + scheduler).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deepspeed_tpu.models import transformer as tf_model
from deepspeed_tpu.models.transformer import TransformerConfig
from deepspeed_tpu.parallel.sharding import ShardingRules
from deepspeed_tpu.parallel.topology import MeshTopology, set_topology
from deepspeed_tpu.utils.logging import log_dist


class InferenceConfig:
    def __init__(self, d: Optional[Dict[str, Any]] = None, **kw):
        d = dict(d or {})
        d.update(kw)
        self.tensor_parallel = d.get("tensor_parallel", {})
        if isinstance(self.tensor_parallel, dict):
            self.tp_size = int(self.tensor_parallel.get("tp_size", 1))
        else:
            self.tp_size = int(self.tensor_parallel)
        self.dtype = d.get("dtype", "bfloat16")
        self.max_tokens = int(d.get("max_tokens", d.get("max_out_tokens", 1024)))
        self.max_batch = int(d.get("max_batch", 8))
        self.replace_with_kernel_inject = bool(d.get("replace_with_kernel_inject", True))


class InferenceEngine:
    """Greedy/temperature generation over the functional model zoo."""

    def __init__(self, model: TransformerConfig, config=None,
                 model_params: Optional[Any] = None, seed: int = 0, **kwargs):
        self.cfg = InferenceConfig(config if isinstance(config, dict) else None, **kwargs)
        dt = jnp.bfloat16 if "bf" in str(self.cfg.dtype) else jnp.float32
        self.model_config = model.replace(dtype=dt)
        mesh_sizes = {"tensor": self.cfg.tp_size} if self.cfg.tp_size > 1 else None
        self.topology = MeshTopology(mesh_sizes)
        set_topology(self.topology)
        self.rules = ShardingRules(self.topology, zero_stage=0)
        if model_params is None:
            shapes = jax.eval_shape(partial(tf_model.init_params, self.model_config),
                                    jax.random.PRNGKey(seed))
            shardings = self.rules.tree_shardings(shapes)
            self.params = jax.jit(partial(tf_model.init_params, self.model_config),
                                  out_shardings=shardings)(jax.random.PRNGKey(seed))
        else:
            self.params = jax.device_put(
                model_params, self.rules.tree_shardings(model_params))
        self._decode_jit = None
        log_dist(f"InferenceEngine: tp={self.cfg.tp_size} dtype={dt.__name__}")

    # ------------------------------------------------------------------
    def forward(self, input_ids) -> jnp.ndarray:
        out = tf_model.forward(self.params, jnp.asarray(input_ids), self.model_config)
        return out[0] if isinstance(out, tuple) else out

    __call__ = forward

    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0, seed: int = 0) -> np.ndarray:
        """Simple full-recompute generation loop (the KV-cached decode path
        lives in inference/v2). Greedy when temperature == 0."""
        ids = np.asarray(input_ids)
        if ids.ndim == 1:
            ids = ids[None, :]
        total = ids.shape[1] + max_new_tokens
        if total > self.model_config.max_seq_len:
            raise ValueError(
                f"prompt ({ids.shape[1]}) + max_new_tokens ({max_new_tokens}) "
                f"= {total} exceeds max_seq_len {self.model_config.max_seq_len}")
        key = jax.random.PRNGKey(seed)
        for _ in range(max_new_tokens):
            logits = self.forward(jnp.asarray(ids))
            next_logits = logits[:, -1, :].astype(jnp.float32)
            if temperature > 0:
                key, sub = jax.random.split(key)
                nxt = jax.random.categorical(sub, next_logits / temperature, axis=-1)
            else:
                nxt = jnp.argmax(next_logits, axis=-1)
            ids = np.concatenate([ids, np.asarray(nxt)[:, None]], axis=1)
        return ids
