"""Dynamic SplitFuse scheduler.

Analog of the reference's FastGen scheduling
(ref inference/v2/scheduling_utils.py + the Dynamic SplitFuse policy,
blogs/deepspeed-fastgen): every engine step runs a FIXED token budget;
running (decode) sequences contribute one token each, and waiting prompts
fill the remaining budget — long prompts are *split* across steps, short
prompts *fuse* into one step. This keeps every forward the same shape
(compiled once) and latency flat.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, List, Tuple

from deepspeed_tpu.inference.v2.ragged import DSStateManager, SequenceDescriptor


class SplitFuseScheduler:
    def __init__(self, mgr: DSStateManager, token_budget: int = 256):
        self.mgr = mgr
        self.token_budget = token_budget
        self._decode: List[int] = []          # uids generating tokens
        self._prefill: Deque[int] = deque()   # uids with uncached prompt tokens

    def add(self, uid: int) -> None:
        self._prefill.append(uid)

    def retire(self, uid: int) -> None:
        if uid in self._decode:
            self._decode.remove(uid)
        if uid in self._prefill:
            self._prefill.remove(uid)

    @property
    def has_work(self) -> bool:
        return bool(self._decode or self._prefill)

    def next_schedule(self) -> List[Tuple[SequenceDescriptor, int]]:
        """(sequence, n_tokens) items for one step, ≤ token_budget total.

        Decode sequences first (1 token each — they bound latency), then
        prompt chunks. A prompt whose remaining tokens exceed the leftover
        budget is split; its unsampled chunk stays queued.
        """
        budget = self.token_budget
        schedule: List[Tuple[SequenceDescriptor, int]] = []
        for uid in list(self._decode):
            if budget == 0:
                break
            seq = self.mgr.get(uid)
            if seq.uncached <= 0:
                continue
            schedule.append((seq, 1))
            budget -= 1

        finished_prefill = []
        for uid in list(self._prefill):
            if budget == 0:
                break
            seq = self.mgr.get(uid)
            n = min(seq.uncached, budget)
            if n <= 0:
                finished_prefill.append(uid)
                continue
            schedule.append((seq, n))
            budget -= n
            if n == seq.uncached:
                finished_prefill.append(uid)
        for uid in finished_prefill:
            self._prefill.remove(uid)
            if uid not in self._decode:
                self._decode.append(uid)
        return schedule
