"""`dstpu` CLI — multi-host job launcher.

TPU-native analog of the reference `deepspeed` CLI
(ref launcher/runner.py:436 `main`): parses a hostfile ("host slots=N"),
applies --include/--exclude resource filters (ref runner.py:310), encodes
the world layout as base64 JSON (ref runner.py:401), then either spawns the
per-node launcher locally or builds a multinode command (pdsh / mpirun /
srun — ref launcher/multinode_runner.py).

On TPU the unit of a "slot" is one host *process* (PJRT owns all local
chips per process); rendezvous is JAX's coordinator service instead of the
torch MASTER_ADDR store.  We export both the DSTPU_* names our comm layer
reads and the MASTER_ADDR/RANK names so ported user scripts keep working.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import re
import shlex
import subprocess
import sys
from collections import OrderedDict
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

DEFAULT_COORD_PORT = 29500


def parse_hostfile(lines) -> "OrderedDict[str, int]":
    """Parse `hostname slots=N` lines. Ref: _parse_hostfile (runner.py:243)."""
    resources: "OrderedDict[str, int]" = OrderedDict()
    for line in lines:
        line = line.split("#")[0].strip()
        if not line:
            continue
        m = re.match(r"^(\S+)\s+slots=(\d+)\s*$", line)
        if m is None:
            raise ValueError(f"malformed hostfile line: {line!r} "
                             "(expected '<host> slots=<n>')")
        host, slots = m.group(1), int(m.group(2))
        if host in resources:
            raise ValueError(f"duplicate host {host} in hostfile")
        resources[host] = slots
    return resources


def fetch_hostfile(path: Optional[str]) -> "OrderedDict[str, int]":
    """Ref: fetch_hostfile (runner.py:230). Missing file → single-node."""
    if not path or not os.path.isfile(path):
        return OrderedDict()
    with open(path) as f:
        return parse_hostfile(f)


def _parse_device_filter(spec: str) -> Dict[str, Optional[List[int]]]:
    """Parse 'host1:0,1@host2' style include/exclude strings.

    Returns {host: [slot ids] or None (= all slots)}.
    Ref: parse_resource_filter (runner.py:310).
    """
    out: Dict[str, Optional[List[int]]] = OrderedDict()
    for part in spec.split("@"):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            host, ids = part.split(":", 1)
            out[host] = [int(i) for i in ids.split(",") if i != ""]
        else:
            out[part] = None
    return out


def parse_resource_filter(resources: "OrderedDict[str, int]",
                          include: str = "",
                          exclude: str = "") -> "OrderedDict[str, List[int]]":
    """Apply --include/--exclude to {host: slots} → {host: [slot ids]}.

    Ref: parse_resource_filter (runner.py:310): include and exclude are
    mutually exclusive; 'host:ids' limits to specific slots.
    """
    if include and exclude:
        raise ValueError("--include and --exclude are mutually exclusive")
    full = OrderedDict((h, list(range(n))) for h, n in resources.items())
    if include:
        filt = _parse_device_filter(include)
        out = OrderedDict()
        for host, ids in filt.items():
            if host not in full:
                raise ValueError(f"include host {host} not in hostfile")
            use = full[host] if ids is None else ids
            bad = set(use) - set(full[host])
            if bad:
                raise ValueError(f"include slots {sorted(bad)} out of range for {host}")
            out[host] = sorted(use)
        return out
    if exclude:
        filt = _parse_device_filter(exclude)
        out = OrderedDict()
        for host, ids in full.items():
            if host in filt:
                if filt[host] is None:
                    continue
                keep = sorted(set(ids) - set(filt[host]))
                if keep:
                    out[host] = keep
            else:
                out[host] = ids
        return out
    return full


def encode_world_info(active: "OrderedDict[str, List[int]]") -> str:
    """base64(JSON {host: [slot ids]}). Ref: encode_world_info (runner.py:401)."""
    return base64.urlsafe_b64encode(json.dumps(active).encode()).decode()


def decode_world_info(blob: str) -> "OrderedDict[str, List[int]]":
    return OrderedDict(json.loads(base64.urlsafe_b64decode(blob.encode()).decode()))


# ----------------------------------------------------------------------
# Multinode runners (ref launcher/multinode_runner.py:19-393)
# ----------------------------------------------------------------------
class MultiNodeRunner:
    name = "base"

    def __init__(self, args, world_info_b64: str):
        self.args = args
        self.world_info_b64 = world_info_b64
        self.user_cmd = [args.user_script] + list(args.user_args)

    def backend_exists(self) -> bool:  # pragma: no cover - env dependent
        return False

    def get_cmd(self, environment: Dict[str, str],
                active: "OrderedDict[str, List[int]]") -> List[str]:
        raise NotImplementedError

    @property
    def exports(self) -> Dict[str, str]:
        ex = {}
        for kv in self.args.export or []:
            k, _, v = kv.partition("=")
            ex[k] = v
        return ex


class PDSHRunner(MultiNodeRunner):
    """Ref: PDSHRunner (multinode_runner.py:19) — pdsh fan-out over ssh."""
    name = "pdsh"

    def backend_exists(self) -> bool:
        return _which("pdsh")

    def get_cmd(self, environment, active):
        environment = dict(environment)
        environment["PDSH_RCMD_TYPE"] = "ssh"
        hosts = ",".join(active.keys())
        exports = "".join(f"export {k}={shlex.quote(str(v))}; "
                          for k, v in {**environment, **self.exports}.items())
        node_cmd = (f"{exports}cd {shlex.quote(os.getcwd())}; "
                    f"{sys.executable} -m deepspeed_tpu.launcher.launch "
                    f"--world_info={self.world_info_b64} "
                    f"--node_rank=%n "
                    f"--coordinator_addr={self.args.master_addr} "
                    f"--coordinator_port={self.args.master_port} "
                    + " ".join(map(shlex.quote, self.user_cmd)))
        return ["pdsh", "-S", "-f", "1024", "-w", hosts, node_cmd]


class OpenMPIRunner(MultiNodeRunner):
    """Ref: OpenMPIRunner (multinode_runner.py:142) — one rank per slot."""
    name = "openmpi"

    def backend_exists(self) -> bool:
        return _which("mpirun")

    def get_cmd(self, environment, active):
        # mpirun fills slots from the hostfile itself, so slot-level
        # include/exclude cannot be honored (ref multinode_runner.py:159
        # raises the same way).
        if self.args.include or self.args.exclude:
            raise ValueError("--include/--exclude are not supported with the "
                             "openmpi launcher; use pdsh or edit the hostfile")
        total = sum(len(v) for v in active.values())
        hostfile_args = ["--hostfile", self.args.hostfile] if self.args.hostfile else []
        exports = []
        for k, v in {**environment, **self.exports}.items():
            exports += ["-x", f"{k}={v}"]
        return (["mpirun", "-n", str(total), "--allow-run-as-root",
                 "--tag-output"] + hostfile_args + exports +
                [sys.executable, "-u"] + self.user_cmd)


class SlurmRunner(MultiNodeRunner):
    """Ref: SlurmRunner (multinode_runner.py:304) — srun launch."""
    name = "slurm"

    def backend_exists(self) -> bool:
        return _which("srun")

    def get_cmd(self, environment, active):
        total = sum(len(v) for v in active.values())
        srun = ["srun", "-n", str(total), "-w", ",".join(active.keys())]
        if getattr(self.args, "comment", ""):
            srun += ["--comment", self.args.comment]
        exports = ",".join(f"{k}={v}" for k, v in {**environment, **self.exports}.items())
        if exports:
            srun += [f"--export=ALL,{exports}"]
        return srun + [sys.executable, "-u"] + self.user_cmd


RUNNERS = {r.name: r for r in (PDSHRunner, OpenMPIRunner, SlurmRunner)}


def _which(prog: str) -> bool:
    from shutil import which
    return which(prog) is not None


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dstpu",
                                description="deepspeed_tpu multi-host launcher")
    p.add_argument("-H", "--hostfile", type=str, default="/job/hostfile",
                   help="'host slots=N' file; absent → single node")
    p.add_argument("-i", "--include", type=str, default="",
                   help="host[:slot,...] list to include, @-separated")
    p.add_argument("-e", "--exclude", type=str, default="",
                   help="host[:slot,...] list to exclude, @-separated")
    p.add_argument("--num_nodes", type=int, default=-1)
    p.add_argument("--num_procs", type=int, default=-1,
                   help="processes per node (default: slots, or 1)")
    p.add_argument("--master_addr", type=str, default="")
    p.add_argument("--master_port", type=int, default=DEFAULT_COORD_PORT)
    p.add_argument("--launcher", type=str, default="pdsh",
                   choices=sorted(RUNNERS))
    p.add_argument("--export", action="append", default=[],
                   metavar="KEY=VAL", help="extra env to export to all ranks")
    p.add_argument("--dry_run", action="store_true",
                   help="print the command instead of executing")
    p.add_argument("--comment", type=str, default="", help="slurm comment")
    p.add_argument("user_script", type=str)
    p.add_argument("user_args", nargs=argparse.REMAINDER)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    """Ref: launcher/runner.py:436 main."""
    args = build_parser().parse_args(argv)
    resources = fetch_hostfile(args.hostfile)

    if not resources:
        # Single node: exec the per-node launcher directly.
        if args.include or args.exclude:
            raise ValueError("--include/--exclude require a hostfile")
        nprocs = args.num_procs if args.num_procs > 0 else 1
        cmd = [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
               "--nproc", str(nprocs),
               "--coordinator_addr", args.master_addr or "127.0.0.1",
               "--coordinator_port", str(args.master_port),
               args.user_script] + args.user_args
        env = dict(os.environ)
        for kv in args.export or []:
            k, _, v = kv.partition("=")
            env[k] = v
        if args.dry_run:
            print(shlex.join(cmd))
            return 0
        return subprocess.call(cmd, env=env)

    active = parse_resource_filter(resources, args.include, args.exclude)
    if args.num_nodes > 0:
        active = OrderedDict(list(active.items())[:args.num_nodes])
    if args.num_procs > 0:
        active = OrderedDict((h, list(range(args.num_procs))) for h in active)
    if not active:
        raise ValueError("no hosts left after filtering")

    master_addr = args.master_addr or next(iter(active))
    args.master_addr = master_addr
    world_info = encode_world_info(active)
    env = {
        "DSTPU_COORDINATOR": f"{master_addr}:{args.master_port}",
        "DSTPU_NUM_PROCS": str(sum(len(v) for v in active.values())),
        "MASTER_ADDR": master_addr,
        "MASTER_PORT": str(args.master_port),
    }
    runner = RUNNERS[args.launcher](args, world_info)
    cmd = runner.get_cmd(env, active)
    if args.dry_run:
        print(shlex.join(cmd))
        return 0
    if not runner.backend_exists():  # pragma: no cover - env dependent
        raise RuntimeError(f"launcher backend '{args.launcher}' not found in PATH")
    logger.info(f"launching: {shlex.join(cmd)}")
    return subprocess.call(cmd, env={**os.environ, **env})


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
