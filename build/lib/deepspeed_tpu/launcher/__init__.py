from deepspeed_tpu.launcher.runner import (fetch_hostfile, parse_hostfile,
                                           parse_resource_filter,
                                           encode_world_info, decode_world_info,
                                           MultiNodeRunner, PDSHRunner,
                                           OpenMPIRunner, SlurmRunner)
