"""Universal checkpoint: per-parameter atomic format + any-topology reload.

Re-design of the reference's UCP (``deepspeed/checkpoint/ds_to_universal.py``
:112/:152/:232, loader ``universal_checkpoint.py:22``, offline consolidation
``utils/zero_to_fp32.py``): the reference must merge per-rank ZeRO shards and
TP slices into atomic per-param files; here global arrays are already
logical wholes (single-controller JAX), so the converter writes one ``.npy``
per parameter path and reload simply re-shards onto whatever mesh the new
engine has — world-size elasticity falls out of the sharding system.

Layout:
    <dir>/universal/
        meta.json                 # step counters, config, param manifest
        params/<path>.npy         # fp32 master weights
        optimizer/<path>.npy      # flattened optimizer state leaves
"""

from __future__ import annotations

import json
import os
import pickle
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger


def _flatten_with_paths(tree) -> Dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        from deepspeed_tpu.parallel.sharding import path_str

        if hasattr(leaf, "is_fully_addressable") and not leaf.is_fully_addressable:
            # ds_to_universal runs on process 0 only, so a cross-process
            # gather here would hang — the converter's inputs must already
            # be host-complete (the pickle engine allgathers at save time)
            raise ValueError(
                "universal converter got a non-fully-addressable array; "
                "convert from a saved checkpoint (engine.save_checkpoint), "
                "not from live multi-host state")
        flat[path_str(path)] = np.asarray(leaf)
    return flat


def _save_flat(flat: Dict[str, np.ndarray], root: str) -> None:
    for path, arr in flat.items():
        fname = os.path.join(root, path.replace("/", "__") + ".npy")
        np.save(fname, arr)


def _load_flat(root: str) -> Dict[str, np.ndarray]:
    out = {}
    for fname in sorted(os.listdir(root)):
        if fname.endswith(".npy"):
            out[fname[:-4].replace("__", "/")] = np.load(os.path.join(root, fname))
    return out


def ds_to_universal(ckpt_dir: str, tag: Optional[str] = None,
                    output_dir: Optional[str] = None) -> str:
    """Convert a saved checkpoint to the universal per-param format.
    Ref: ds_to_universal.py main flow (extract shards → merge → per-param)."""
    from deepspeed_tpu.checkpoint.engine import LATEST_FILE, _ckpt_path

    if tag is None:
        with open(os.path.join(ckpt_dir, LATEST_FILE)) as f:
            tag = f.read().strip()

    out = output_dir or os.path.join(ckpt_dir, str(tag), "universal")
    if jax.process_count() > 1 and jax.process_index() != 0:
        # each process's pickle holds the full (allgathered) state; one
        # writer suffices on a shared FS — wait for process 0 to finish,
        # and surface its failure instead of returning a broken dir
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(np.array([1], np.int32))
        if not bool(flags.min()):
            raise RuntimeError("universal conversion failed on process 0")
        return out

    ok = False
    try:
        with open(_ckpt_path(ckpt_dir, tag), "rb") as f:
            state = pickle.load(f)

        os.makedirs(os.path.join(out, "params"), exist_ok=True)
        os.makedirs(os.path.join(out, "optimizer"), exist_ok=True)

        params_flat = _flatten_with_paths(state["module"])
        _save_flat(params_flat, os.path.join(out, "params"))
        opt_flat = _flatten_with_paths(state["optimizer"])
        _save_flat(opt_flat, os.path.join(out, "optimizer"))

        meta = {
            "global_steps": state.get("global_steps", 0),
            "micro_steps": state.get("micro_steps", 0),
            "lr_scheduler": state.get("lr_scheduler"),
            "loss_scale_state": {k: float(np.asarray(v))
                                 for k, v in state.get("loss_scale_state",
                                                       {}).items()},
            "param_manifest": {k: list(v.shape)
                               for k, v in params_flat.items()},
            "opt_treedef_leaves": len(opt_flat),
            "ds_config": state.get("ds_config", {}),
            "source_mesh": state.get("mesh_sizes", {}),
        }
        with open(os.path.join(out, "meta.json"), "w") as f:
            json.dump(meta, f, indent=2)
        ok = True
    finally:
        if jax.process_count() > 1:
            # ALWAYS release the non-writer processes — a writer exception
            # must raise on process 0, not hang processes 1..N — and tell
            # them whether the conversion actually succeeded
            from jax.experimental import multihost_utils

            multihost_utils.process_allgather(
                np.array([1 if ok else 0], np.int32))
    log_dist(f"universal checkpoint written: {out}")
    return out


def resolve_universal_dir(load_dir: str, tag: Optional[str] = None) -> str:
    """Accept either the universal dir itself, a checkpoint root (+tag), or a
    checkpoint root with a ``latest`` file."""
    if os.path.exists(os.path.join(load_dir, "meta.json")):
        return load_dir
    if tag is None:
        latest = os.path.join(load_dir, "latest")
        if os.path.exists(latest):
            with open(latest) as f:
                tag = f.read().strip()
    if tag is not None:
        cand = os.path.join(load_dir, str(tag), "universal")
        if os.path.exists(os.path.join(cand, "meta.json")):
            return cand
    raise FileNotFoundError(f"no universal checkpoint under {load_dir} (tag={tag})")


def load_universal(engine, universal_dir: str) -> None:
    """Load a universal checkpoint into an engine with ANY mesh topology
    (ref load_hp_checkpoint_state, universal_checkpoint.py:22).  Arrays are
    device_put with the engine's current shardings, so dp/tp/pp/sp changes
    between save and load "just work"."""
    with open(os.path.join(universal_dir, "meta.json")) as f:
        meta = json.load(f)

    params_flat = _load_flat(os.path.join(universal_dir, "params"))
    params = _unflatten_like(engine.params, params_flat)
    engine.params = jax.device_put(params, engine.param_shardings)

    opt_flat = _load_flat(os.path.join(universal_dir, "optimizer"))
    template = engine._opt_state_template()
    if opt_flat and template is not None:
        opt_state = _unflatten_like(template, opt_flat)
        # store mode: device placement is transient (engine pushes to the
        # store right after); stream mode: resident (possibly host) shardings
        target = (engine._opt_device_shardings if engine._opt_store is not None
                  else engine.opt_shardings)
        engine.opt_state = jax.device_put(opt_state, target)

    if meta.get("loss_scale_state"):
        import jax.numpy as jnp

        ls = meta["loss_scale_state"]
        engine.loss_scale_state = jax.device_put(
            {"scale": jnp.float32(ls.get("scale", 1.0)),
             "good_steps": jnp.int32(int(ls.get("good_steps", 0))),
             "skipped": jnp.int32(int(ls.get("skipped", 0)))},
            engine._replicated)
    if meta.get("lr_scheduler"):
        engine.lr_scheduler.load_state_dict(meta["lr_scheduler"])
    engine.global_steps = int(meta.get("global_steps", 0))
    engine.micro_steps = int(meta.get("micro_steps", 0))
    log_dist(f"universal checkpoint loaded from {universal_dir} "
             f"(source mesh {meta.get('source_mesh')} → {engine.topology.sizes})")


def _unflatten_like(template, flat: Dict[str, np.ndarray]):
    """Rebuild a pytree with ``template``'s structure from path→array dict."""
    from deepspeed_tpu.parallel.sharding import path_str

    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    for path, leaf in leaves_with_paths:
        key = path_str(path)
        if key not in flat:
            raise KeyError(f"universal checkpoint missing entry '{key}'")
        arr = flat[key]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise ValueError(f"shape mismatch for '{key}': "
                             f"checkpoint {arr.shape} vs model {np.shape(leaf)}")
        new_leaves.append(arr.astype(np.asarray(leaf).dtype
                                     if hasattr(leaf, "dtype") else arr.dtype))
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def zero_to_fp32(ckpt_dir: str, output_file: str, tag: Optional[str] = None) -> str:
    """Offline consolidation to a single fp32 state dict file
    (ref utils/zero_to_fp32.py). Master params are fp32 already; this writes
    a flat ``{path: np.float32 array}`` pickle loadable without the engine."""
    from deepspeed_tpu.checkpoint.engine import LATEST_FILE, _ckpt_path

    if tag is None:
        with open(os.path.join(ckpt_dir, LATEST_FILE)) as f:
            tag = f.read().strip()
    with open(_ckpt_path(ckpt_dir, tag), "rb") as f:
        state = pickle.load(f)
    flat = {k: v.astype(np.float32)
            for k, v in _flatten_with_paths(state["module"]).items()}
    with open(output_file, "wb") as f:
        pickle.dump(flat, f, protocol=pickle.HIGHEST_PROTOCOL)
    log_dist(f"fp32 consolidated state dict: {output_file} ({len(flat)} tensors)")
    return output_file
