"""Compression config parsing.

Analog of ``deepspeed/compression/config.py`` + ``constants.py``: the
``compression_training`` JSON block with per-technique
``shared_parameters`` / ``different_groups`` and a ``layer_reduction``
block.  Technique keys match the reference schema so DeepSpeed configs port
unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

TECHNIQUES = ("weight_quantization", "activation_quantization",
              "sparse_pruning", "row_pruning", "head_pruning",
              "channel_pruning")


@dataclass
class CompressionGroup:
    """One ``different_groups`` entry: param-path patterns + technique
    params (ref DIFFERENT_GROUPS_* constants)."""
    name: str
    params: Dict[str, Any]
    modules: List[str] = field(default_factory=lambda: ["*"])
    related_modules: Optional[List[str]] = None


@dataclass
class TechniqueConfig:
    enabled: bool = False
    schedule_offset: int = 0
    schedule_offset_end: Optional[int] = None
    shared: Dict[str, Any] = field(default_factory=dict)
    groups: List[CompressionGroup] = field(default_factory=list)


@dataclass
class LayerReductionConfig:
    enabled: bool = False
    keep_number_layer: Optional[int] = None
    teacher_layer: Optional[List[int]] = None
    module_name_prefix: str = ""
    other_module_name: Optional[List[str]] = None


def parse_compression_config(d: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """compression_training dict → {technique: TechniqueConfig,
    "layer_reduction": LayerReductionConfig}."""
    d = d or {}
    out: Dict[str, Any] = {}
    for tech in TECHNIQUES:
        td = d.get(tech, {}) or {}
        shared = td.get("shared_parameters", {}) or {}
        groups = []
        for gname, gd in (td.get("different_groups", {}) or {}).items():
            groups.append(CompressionGroup(
                name=gname,
                params=gd.get("params", {}) or {},
                modules=gd.get("modules", ["*"]),
                related_modules=gd.get("related_modules")))
        out[tech] = TechniqueConfig(
            enabled=bool(shared.get("enabled", False)),
            schedule_offset=int(shared.get("schedule_offset", 0)),
            schedule_offset_end=(int(shared["schedule_offset_end"])
                                 if "schedule_offset_end" in shared else None),
            shared=shared, groups=groups)
    lr = d.get("layer_reduction", {}) or {}
    out["layer_reduction"] = LayerReductionConfig(
        enabled=bool(lr.get("enabled", False)),
        keep_number_layer=lr.get("keep_number_layer"),
        teacher_layer=lr.get("teacher_layer"),
        module_name_prefix=lr.get("module_name_prefix", ""),
        other_module_name=lr.get("other_module_name"))
    return out
