"""Compression primitives: QAT fake-quant with STE, structured/unstructured
pruning masks.

Analog of ``deepspeed/compression/basic_layer.py`` (LinearLayer_Compress
and friends).  The reference wraps nn.Linear modules; here every technique
is a pure function over a weight array, applied inside the jitted forward —
masks and quantization fuse into the surrounding matmul, so "compressed
training" costs one elementwise op per weight instead of a module swap.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def ste_round(x):
    """Round with straight-through gradient (QAT backward rule)."""
    return x + jax.lax.stop_gradient(jnp.round(x) - x)


def quantize_weight_ste(w, bits: int = 8, symmetric: bool = True,
                        group_size: int = 0):
    """Fake-quantize a weight for QAT (ref WEIGHT_QUANTIZE_*: symmetric /
    asymmetric, per-tensor or grouped).  Differentiable via STE."""
    orig_shape = w.shape
    wf = w.astype(jnp.float32)
    if group_size and w.size % group_size == 0:
        wf = wf.reshape(-1, group_size)
    qmax = 2.0 ** (bits - 1) - 1
    if symmetric:
        scale = jnp.max(jnp.abs(wf), axis=-1, keepdims=True) / qmax
        scale = jnp.maximum(scale, 1e-8)
        q = ste_round(wf / scale).clip(-qmax - 1, qmax)
        out = q * scale
    else:
        mn = wf.min(axis=-1, keepdims=True)
        mx = wf.max(axis=-1, keepdims=True)
        scale = jnp.maximum((mx - mn) / (2.0 ** bits - 1), 1e-8)
        q = ste_round((wf - mn) / scale).clip(0, 2.0 ** bits - 1)
        out = q * scale + mn
    return out.reshape(orig_shape).astype(w.dtype)


def quantize_activation_ste(x, bits: int = 8, symmetric: bool = False,
                            range_calibration: str = "dynamic"):
    """Activation fake-quant (ref ACTIVATION_QUANTIZATION_*): dynamic
    per-token range by default."""
    xf = x.astype(jnp.float32)
    if symmetric:
        qmax = 2.0 ** (bits - 1) - 1
        scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1, keepdims=True) / qmax, 1e-8)
        out = ste_round(xf / scale).clip(-qmax - 1, qmax) * scale
    else:
        mn = xf.min(axis=-1, keepdims=True)
        mx = xf.max(axis=-1, keepdims=True)
        scale = jnp.maximum((mx - mn) / (2.0 ** bits - 1), 1e-8)
        out = ste_round((xf - mn) / scale).clip(0, 2.0 ** bits - 1) * scale + mn
    return out.astype(x.dtype)


# ----------------------------------------------------------------------
# Pruning masks. All return a {0,1} mask with w's shape; masks are
# magnitude-based like the reference's TopK defaults.
# ----------------------------------------------------------------------

def sparse_pruning_mask(w, dense_ratio: float, method: str = "topk"):
    """Unstructured magnitude pruning (ref SPARSE_PRUNING_*): keep the
    top ``dense_ratio`` fraction by |w|. method 'l1' == 'topk' magnitude."""
    if dense_ratio >= 1.0:
        return jnp.ones_like(w)
    k = max(1, int(round(w.size * dense_ratio)))
    flat = jnp.abs(w.reshape(-1))
    thresh = jax.lax.top_k(flat, k)[0][-1]
    return (jnp.abs(w) >= thresh).astype(w.dtype)


def row_pruning_mask(w, dense_ratio: float):
    """Structured row pruning (ref ROW_PRUNING_*): score rows (output
    features, last dim of [in, out]) by L1 norm, keep top fraction."""
    if dense_ratio >= 1.0:
        return jnp.ones_like(w)
    scores = jnp.abs(w).sum(axis=tuple(range(w.ndim - 1)))  # [out]
    k = max(1, int(round(scores.shape[0] * dense_ratio)))
    thresh = jax.lax.top_k(scores, k)[0][-1]
    keep = (scores >= thresh).astype(w.dtype)
    return jnp.broadcast_to(keep, w.shape)


def channel_pruning_mask(w, dense_ratio: float):
    """Structured input-channel pruning (ref CHANNEL_PRUNING_*): scores the
    second-to-last (input) dim."""
    if dense_ratio >= 1.0:
        return jnp.ones_like(w)
    axes = tuple(i for i in range(w.ndim) if i != w.ndim - 2)
    scores = jnp.abs(w).sum(axis=axes)  # [in]
    k = max(1, int(round(scores.shape[0] * dense_ratio)))
    thresh = jax.lax.top_k(scores, k)[0][-1]
    keep = (scores >= thresh).astype(w.dtype)
    return jnp.broadcast_to(keep[:, None], w.shape)


def head_pruning_mask(w, dense_ratio: float, num_heads: int):
    """Attention head pruning (ref HEAD_PRUNING_*): w is an output
    projection [..., H*D, out]; score each head's slab, keep top fraction."""
    if dense_ratio >= 1.0:
        return jnp.ones_like(w)
    in_dim = w.shape[-2]
    if in_dim % num_heads != 0:
        raise ValueError(f"in dim {in_dim} not divisible by {num_heads} heads")
    hd = in_dim // num_heads
    wh = w.reshape(w.shape[:-2] + (num_heads, hd, w.shape[-1]))
    axes = tuple(i for i in range(wh.ndim) if i != wh.ndim - 3)
    scores = jnp.abs(wh).sum(axis=axes)  # [H]
    k = max(1, int(round(num_heads * dense_ratio)))
    thresh = jax.lax.top_k(scores, k)[0][-1]
    keep = (scores >= thresh).astype(w.dtype)
    mask = jnp.broadcast_to(keep[:, None, None], wh.shape[-3:])
    return jnp.broadcast_to(mask.reshape((in_dim, w.shape[-1])), w.shape)
