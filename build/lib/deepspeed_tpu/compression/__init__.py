"""Compression: QAT, structured/unstructured pruning, layer reduction.

Analog of ``deepspeed/compression/``."""

from deepspeed_tpu.compression.compress import (CompressionManager,
                                                CompressionScheduler,
                                                init_compression)
from deepspeed_tpu.compression.basic_layers import (channel_pruning_mask,
                                                    head_pruning_mask,
                                                    quantize_activation_ste,
                                                    quantize_weight_ste,
                                                    row_pruning_mask,
                                                    sparse_pruning_mask)

__all__ = [
    "CompressionManager", "CompressionScheduler", "init_compression",
    "quantize_weight_ste", "quantize_activation_ste", "sparse_pruning_mask",
    "row_pruning_mask", "channel_pruning_mask", "head_pruning_mask",
]
