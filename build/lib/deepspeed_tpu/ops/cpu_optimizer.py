"""Native host optimizers (ZeRO-Offload step path).

API mirrors the reference's ``DeepSpeedCPUAdam`` (ops/adam/cpu_adam.py:13),
``DeepSpeedCPUAdagrad`` and ``DeepSpeedCPULion``: fused, vectorized
optimizer steps over fp32 host arrays, backed by
``csrc/cpu_optimizer/cpu_optimizer.cpp`` (the analog of
csrc/adam/cpu_adam_impl.cpp's AVX kernels) with a numpy fallback when no
compiler is available.
"""

from __future__ import annotations

import ctypes
from typing import Dict, List, Optional

import numpy as np

from deepspeed_tpu.ops.op_builder import OpBuilderError, load_op
from deepspeed_tpu.utils.logging import logger

_LIB = None
_LIB_FAILED = False


def _lib():
    global _LIB, _LIB_FAILED
    if _LIB is None and not _LIB_FAILED:
        try:
            lib = load_op("ds_cpu_optimizer",
                          ["cpu_optimizer/cpu_optimizer.cpp"],
                          extra_flags=["-fopenmp"])
            f32 = ctypes.POINTER(ctypes.c_float)
            lib.ds_adam_step.argtypes = [f32, f32, f32, f32, ctypes.c_int64,
                                         ctypes.c_float, ctypes.c_float,
                                         ctypes.c_float, ctypes.c_float,
                                         ctypes.c_float, ctypes.c_int,
                                         ctypes.c_int]
            lib.ds_adagrad_step.argtypes = [f32, f32, f32, ctypes.c_int64,
                                            ctypes.c_float, ctypes.c_float,
                                            ctypes.c_float]
            lib.ds_lion_step.argtypes = [f32, f32, f32, ctypes.c_int64,
                                         ctypes.c_float, ctypes.c_float,
                                         ctypes.c_float, ctypes.c_float]
            _LIB = lib
        except OpBuilderError as e:
            logger.warning(f"native cpu optimizer unavailable ({e}); "
                           "using numpy fallback")
            _LIB_FAILED = True
    return _LIB


def cpu_optimizer_available() -> bool:
    return _lib() is not None


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _check(*arrays: np.ndarray) -> None:
    for a in arrays:
        if a.dtype != np.float32 or not a.flags["C_CONTIGUOUS"]:
            raise ValueError("cpu optimizer needs contiguous fp32 arrays")


class DeepSpeedCPUAdam:
    """Fused host Adam/AdamW over a list of fp32 numpy params (in-place)."""

    def __init__(self, params: List[np.ndarray], lr: float = 1e-3,
                 betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True):
        self.params = params
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self.step_count = 0
        self.exp_avg = [np.zeros_like(p) for p in params]
        self.exp_avg_sq = [np.zeros_like(p) for p in params]

    def step(self, grads: List[np.ndarray],
             lr: Optional[float] = None) -> None:
        self.step_count += 1
        lr = self.lr if lr is None else lr
        lib = _lib()
        for p, g, m, v in zip(self.params, grads, self.exp_avg,
                              self.exp_avg_sq):
            g = np.ascontiguousarray(g, np.float32)
            if lib is not None:
                _check(p, m, v)
                lib.ds_adam_step(_ptr(p), _ptr(g), _ptr(m), _ptr(v), p.size,
                                 lr, self.beta1, self.beta2, self.eps,
                                 self.weight_decay, self.step_count,
                                 int(self.adamw_mode))
            else:
                adam_step_numpy(p, g, m, v, lr, self.beta1, self.beta2,
                                self.eps, self.weight_decay, self.step_count,
                                self.adamw_mode)

    def state_dict(self) -> Dict:
        return {"step": self.step_count, "exp_avg": self.exp_avg,
                "exp_avg_sq": self.exp_avg_sq}

    def load_state_dict(self, sd: Dict) -> None:
        self.step_count = int(sd["step"])
        self.exp_avg = [np.array(x, np.float32) for x in sd["exp_avg"]]
        self.exp_avg_sq = [np.array(x, np.float32) for x in sd["exp_avg_sq"]]


class DeepSpeedCPUAdagrad:
    def __init__(self, params: List[np.ndarray], lr: float = 1e-2,
                 eps: float = 1e-10, weight_decay: float = 0.0):
        self.params = params
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self.exp_avg_sq = [np.zeros_like(p) for p in params]

    def step(self, grads: List[np.ndarray],
             lr: Optional[float] = None) -> None:
        lr = self.lr if lr is None else lr
        lib = _lib()
        for p, g, v in zip(self.params, grads, self.exp_avg_sq):
            g = np.ascontiguousarray(g, np.float32)
            if lib is not None:
                _check(p, v)
                lib.ds_adagrad_step(_ptr(p), _ptr(g), _ptr(v), p.size, lr,
                                    self.eps, self.weight_decay)
            else:
                if self.weight_decay:
                    g = g + self.weight_decay * p
                v += g * g
                p -= lr * g / (np.sqrt(v) + self.eps)


class DeepSpeedCPULion:
    def __init__(self, params: List[np.ndarray], lr: float = 1e-4,
                 betas=(0.9, 0.99), weight_decay: float = 0.0):
        self.params = params
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.weight_decay = weight_decay
        self.exp_avg = [np.zeros_like(p) for p in params]

    def step(self, grads: List[np.ndarray],
             lr: Optional[float] = None) -> None:
        lr = self.lr if lr is None else lr
        lib = _lib()
        for p, g, m in zip(self.params, grads, self.exp_avg):
            g = np.ascontiguousarray(g, np.float32)
            if lib is not None:
                _check(p, m)
                lib.ds_lion_step(_ptr(p), _ptr(g), _ptr(m), p.size, lr,
                                 self.beta1, self.beta2, self.weight_decay)
            else:
                c = self.beta1 * m + (1 - self.beta1) * g
                upd = np.sign(c)
                if self.weight_decay:
                    upd = upd + self.weight_decay * p
                p -= lr * upd
                m[:] = self.beta2 * m + (1 - self.beta2) * g


def adam_step_numpy(p, g, m, v, lr, beta1, beta2, eps, weight_decay, step,
                    adamw) -> None:
    """Reference/fallback implementation (in-place)."""
    if not adamw and weight_decay:
        g = g + weight_decay * p
    m *= beta1
    m += (1 - beta1) * g
    v *= beta2
    v += (1 - beta2) * g * g
    bc1 = 1 - beta1 ** step
    bc2 = 1 - beta2 ** step
    denom = np.sqrt(v) / np.sqrt(bc2) + eps
    if adamw and weight_decay:
        p -= lr * weight_decay * p
    p -= (lr / bc1) * m / denom
