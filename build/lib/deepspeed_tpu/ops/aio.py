"""Python wrapper for the native async-IO engine (DeepNVMe equivalent).

API mirrors the reference's ``aio_handle`` (ops/aio, csrc/aio/py_lib/
py_ds_aio.cpp): ``AsyncIOHandle(block_size, queue_depth, thread_count)``
with ``async_pread/async_pwrite`` over numpy buffers + ``wait()``.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from deepspeed_tpu.ops.op_builder import load_op


def _lib():
    lib = load_op("ds_aio", ["aio/ds_aio.cpp"])
    lib.ds_aio_create.restype = ctypes.c_void_p
    lib.ds_aio_create.argtypes = [ctypes.c_long, ctypes.c_int, ctypes.c_int,
                                  ctypes.c_int]
    lib.ds_aio_destroy.argtypes = [ctypes.c_void_p]
    lib.ds_aio_pread.restype = ctypes.c_int
    lib.ds_aio_pread.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_long,
                                 ctypes.c_char_p, ctypes.c_long]
    lib.ds_aio_pwrite.restype = ctypes.c_int
    lib.ds_aio_pwrite.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_long,
                                  ctypes.c_char_p, ctypes.c_long]
    lib.ds_aio_wait.restype = ctypes.c_long
    lib.ds_aio_wait.argtypes = [ctypes.c_void_p]
    lib.ds_aio_pending.restype = ctypes.c_long
    lib.ds_aio_pending.argtypes = [ctypes.c_void_p]
    lib.ds_aio_direct_fallbacks.restype = ctypes.c_long
    lib.ds_aio_direct_fallbacks.argtypes = [ctypes.c_void_p]
    return lib


class AsyncIOHandle:
    """Async pread/pwrite of numpy arrays through the native thread pool."""

    def __init__(self, block_size: int = 1 << 20, queue_depth: int = 8,
                 thread_count: int = 4, use_direct: bool = False):
        self._lib = _lib()
        self._h = self._lib.ds_aio_create(block_size, queue_depth, thread_count,
                                          1 if use_direct else 0)
        self.block_size = block_size
        self.queue_depth = queue_depth
        self.thread_count = thread_count
        self.use_direct = use_direct
        # keep buffers alive while IO is in flight
        self._inflight_bufs = []

    def async_pwrite(self, array: np.ndarray, path: str, offset: int = 0) -> int:
        arr = np.ascontiguousarray(array)
        self._inflight_bufs.append(arr)
        return self._lib.ds_aio_pwrite(
            self._h, arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes,
            path.encode(), offset)

    def async_pread(self, array: np.ndarray, path: str, offset: int = 0) -> int:
        if not array.flags["C_CONTIGUOUS"] or not array.flags["WRITEABLE"]:
            raise ValueError("read target must be a writable contiguous array")
        self._inflight_bufs.append(array)
        return self._lib.ds_aio_pread(
            self._h, array.ctypes.data_as(ctypes.c_void_p), array.nbytes,
            path.encode(), offset)

    def wait(self) -> int:
        """Block until all submitted ops finish. Returns failed chunk count."""
        errors = int(self._lib.ds_aio_wait(self._h))
        self._inflight_bufs.clear()
        return errors

    def pending(self) -> int:
        return int(self._lib.ds_aio_pending(self._h))

    def direct_fallbacks(self) -> int:
        """O_DIRECT chunks that fell back to buffered I/O since last call
        (non-zero means 'direct' timings measured the page cache)."""
        return int(self._lib.ds_aio_direct_fallbacks(self._h))

    # sync conveniences (ref: aio_handle.read/write)
    def pwrite(self, array: np.ndarray, path: str, offset: int = 0) -> None:
        self.async_pwrite(array, path, offset)
        errs = self.wait()
        if errs:
            raise IOError(f"aio pwrite to {path}: {errs} failed chunks")

    def pread(self, array: np.ndarray, path: str, offset: int = 0) -> None:
        self.async_pread(array, path, offset)
        errs = self.wait()
        if errs:
            raise IOError(f"aio pread from {path}: {errs} failed chunks")

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.ds_aio_wait(self._h)
                self._lib.ds_aio_destroy(self._h)
                self._h = None
        except Exception:
            pass


def aio_available() -> bool:
    """True when the native csrc/aio library builds/loads on this host."""
    try:
        _lib()
        return True
    except Exception:
        return False
