"""Floating-point quantization: FP8 / FP6 / FP12 quantize-dequantize.

TPU-native analog of the reference's FP quantizer
(``csrc/fp_quantizer/fp_quantize.{cpp,cu}``, ``ops/fp_quantizer/`` — SURVEY
§2.6): used for FP8 gradient/weight compression and FP6 weight-only
inference (cuda_linear).  FP8 uses the hardware-backed
``float8_e4m3fn``/``float8_e5m2`` dtypes (XLA lowers conversions natively);
FP6 (e3m2) and FP12 (e4m7) are emulated by mantissa truncation + exponent
clamping on f32 bit patterns — the same numerics the CUDA kernel computes,
expressed as vectorizable integer ops XLA fuses.

Layout note: the CUDA path stores FP6 in packed 6-bit lanes for the
weight-only GEMM; on TPU the MXU consumes bf16, so quantized values are kept
in byte lanes and dequantized to bf16 at the matmul boundary (XLA fuses the
dequant into the matmul's operand load).

Scaled variants group the last axis (``group_size``) with one f32 scale per
group, mirroring ``quantize()``'s q_range scaling (ref fp_quantize.cu).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

_FORMATS = {
    # name: (exp_bits, man_bits, jnp dtype or None → emulated)
    "fp8_e4m3": (4, 3, jnp.float8_e4m3fn),
    "fp8_e5m2": (5, 2, jnp.float8_e5m2),
    "fp6_e3m2": (3, 2, None),
    "fp12_e4m7": (4, 7, None),
}


def _emulate_round(x: jnp.ndarray, exp_bits: int, man_bits: int) -> jnp.ndarray:
    """Round f32 to a small float format by mantissa truncation (round to
    nearest even) and exponent clamping, returning f32 holding representable
    values."""
    xf = x.astype(jnp.float32)
    bits = jnp.asarray(xf).view(jnp.uint32)
    drop = 23 - man_bits
    # round-to-nearest-even on the dropped mantissa bits
    round_bit = jnp.uint32(1) << (drop - 1)
    sticky_mask = round_bit - 1
    lsb = (bits >> drop) & 1
    rounded = bits + round_bit - 1 + lsb
    bits = (rounded >> drop) << drop
    y = bits.view(jnp.float32)
    # clamp exponent range: bias = 2^(e-1)-1; max normal exponent
    bias = 2 ** (exp_bits - 1) - 1
    max_exp = bias
    max_val = (2.0 - 2.0 ** (-man_bits)) * (2.0 ** max_exp)
    min_normal = 2.0 ** (1 - bias)
    y = jnp.clip(y, -max_val, max_val)
    # subnormals: fixed-point grid of 2^(1-bias-man) below the normal range
    sub_step = min_normal * 2.0 ** (-man_bits)
    y_sub = jnp.round(xf / sub_step) * sub_step
    y = jnp.where(jnp.abs(xf) < min_normal, y_sub, y)
    return jnp.where(x == 0, 0.0, y).astype(jnp.float32)


def fp_quantize(x: jnp.ndarray, fmt: str = "fp8_e4m3",
                group_size: int = 0) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Quantize to a low-bit float format with optional per-group scaling.

    Returns ``(q, scales)``; ``q`` is the format's dtype (or f32 holding
    representable values for emulated formats). Ref: fp_quantize.cu
    quantize().
    """
    if fmt not in _FORMATS:
        raise ValueError(f"unknown fp format {fmt}; have {list(_FORMATS)}")
    exp_bits, man_bits, dtype = _FORMATS[fmt]
    xf = x.astype(jnp.float32)
    if group_size and group_size < xf.shape[-1]:
        if xf.shape[-1] % group_size != 0:
            raise ValueError(f"last dim {xf.shape[-1]} % group {group_size} != 0")
        g = xf.reshape(xf.shape[:-1] + (-1, group_size))
        bias = 2 ** (exp_bits - 1) - 1
        max_val = (2.0 - 2.0 ** (-man_bits)) * (2.0 ** bias)
        absmax = jnp.max(jnp.abs(g), axis=-1, keepdims=True)
        scale = jnp.where(absmax == 0, 1.0, absmax / max_val)
        g = g / scale
        xf = g.reshape(xf.shape)
        scales = scale.squeeze(-1)
    else:
        scales = jnp.ones(xf.shape[:-1] + (1,), jnp.float32)
        group_size = xf.shape[-1]
    if dtype is not None:
        q = xf.astype(dtype)
    else:
        q = _emulate_round(xf, exp_bits, man_bits)
    return q, scales


def fp_dequantize(q: jnp.ndarray, scales: jnp.ndarray, fmt: str = "fp8_e4m3",
                  dtype=jnp.float32) -> jnp.ndarray:
    """Inverse of :func:`fp_quantize` (ref fp_quantize.cu dequantize)."""
    xf = q.astype(jnp.float32)
    group_size = xf.shape[-1] // scales.shape[-1]
    g = xf.reshape(xf.shape[:-1] + (scales.shape[-1], group_size))
    out = g * scales[..., None]
    return out.reshape(xf.shape).astype(dtype)


def fp_fake_quantize(x: jnp.ndarray, fmt: str = "fp8_e4m3",
                     group_size: int = 0) -> jnp.ndarray:
    """Quantize-dequantize roundtrip (selective_dequant analog)."""
    q, s = fp_quantize(x, fmt, group_size)
    return fp_dequantize(q, s, fmt, dtype=x.dtype)
