"""Spatial (diffusion) ops — NHWC channels-last conv helpers.

Analog of ``csrc/spatial/`` (channels-last conv + fused bias kernels used
by the stable-diffusion path).  TPU convolutions are natively NHWC, so the
"channels-last" transform the reference implements in CUDA is simply the
default layout here; the fused bias/activation epilogues fold into the
conv under XLA.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def conv2d_nhwc(x, w, bias=None, stride: Tuple[int, int] = (1, 1),
                padding: str = "SAME", activation: Optional[str] = None):
    """x [B, H, W, Cin], w [KH, KW, Cin, Cout] → [B, H', W', Cout]
    (ref spatial conv wrappers; bias+silu fused epilogue)."""
    out = lax.conv_general_dilated(
        x, w.astype(x.dtype), window_strides=stride, padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    if bias is not None:
        out = out + bias.astype(out.dtype)
    if activation == "silu":
        out = jax.nn.silu(out)
    elif activation == "gelu":
        out = jax.nn.gelu(out)
    return out


def bias_add_nhwc(x, bias):
    """Fused channel bias add (ref csrc/spatial bias_add)."""
    return x + bias.astype(x.dtype)


def group_norm_nhwc(x, scale, bias, num_groups: int = 32,
                    eps: float = 1e-5):
    """GroupNorm over NHWC (diffusion UNet blocks)."""
    b, h, w, c = x.shape
    if c % num_groups != 0:
        raise ValueError(f"channels {c} not divisible by groups {num_groups}")
    xf = x.astype(jnp.float32).reshape(b, h, w, num_groups, c // num_groups)
    mean = xf.mean(axis=(1, 2, 4), keepdims=True)
    var = xf.var(axis=(1, 2, 4), keepdims=True)
    xf = (xf - mean) * lax.rsqrt(var + eps)
    out = xf.reshape(b, h, w, c) * scale.astype(jnp.float32) \
        + bias.astype(jnp.float32)
    return out.astype(x.dtype)


def upsample_nearest_nhwc(x, factor: int = 2):
    """Nearest-neighbour upsample (diffusion decoder)."""
    b, h, w, c = x.shape
    x = jnp.broadcast_to(x[:, :, None, :, None, :],
                         (b, h, factor, w, factor, c))
    return x.reshape(b, h * factor, w * factor, c)
