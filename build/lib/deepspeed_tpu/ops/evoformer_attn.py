"""Evoformer attention (DeepSpeed4Science).

Analog of ``csrc/deepspeed4science/evoformer_attn/`` (CUTLASS fused MSA/
triangle attention) and its wrapper ``deepspeed/ops/deepspeed4science/``.
AlphaFold-style attention takes up to two additive biases — the mask bias
broadcast over rows and the learned pair bias — fused into the softmax.
On TPU the einsum-softmax-einsum chain compiles to fused MXU ops; fp32
softmax accumulation matches the reference kernel's numerics.

Shapes (AlphaFold convention): q/k/v [*, S, H, D] with arbitrary leading
batch dims; bias1 [*, 1, 1, 1, S] row mask; bias2 [*, 1, H, S, S] pair
bias (either may be None).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def evoformer_attention(q, k, v, bias1: Optional[jnp.ndarray] = None,
                        bias2: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """softmax(q·kᵀ/√d + bias1 + bias2)·v over the last three dims
    [S, H, D] (ref EvoformerAttnBuilder attention fwd)."""
    d = q.shape[-1]
    scale = d ** -0.5
    qf = q.astype(jnp.float32) * scale
    kf = k.astype(jnp.float32)
    # [..., H, Sq, Sk]
    scores = jnp.einsum("...qhd,...khd->...hqk", qf, kf)
    if bias1 is not None:
        scores = scores + _align_bias(bias1, scores)
    if bias2 is not None:
        scores = scores + _align_bias(bias2, scores)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("...hqk,...khd->...qhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _align_bias(bias, scores):
    """Broadcast a reference-layout bias onto [..., H, Sq, Sk]."""
    b = bias.astype(jnp.float32)
    while b.ndim < scores.ndim:
        b = b[None]
    # squeeze stray singleton layout dims beyond scores' rank
    while b.ndim > scores.ndim:
        axis = next(i for i, s in enumerate(b.shape) if s == 1)
        b = jnp.squeeze(b, axis=axis)
    return b


def evoformer_attention_bwd_reference(q, k, v, bias1=None, bias2=None):
    """Autodiff handles backward; exposed for kernel-parity tests (the
    reference ships explicit bwd kernels)."""
    return jax.grad(
        lambda q_: evoformer_attention(q_, k, v, bias1, bias2).sum())(q)
