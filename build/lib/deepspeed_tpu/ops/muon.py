"""Muon optimizer: momentum + Newton–Schulz orthogonalised update.

TPU-native port of the reference's Muon integration
(``runtime/zero/muon/original_muon.py:36`` — ``zeropower_via_newtonschulz5``).
The quintic Newton–Schulz iteration is 5 matmuls per step per 2-D param —
pure MXU work, so a plain jnp implementation compiles to optimal code; 1-D
params (norms, biases) fall back to Adam exactly like the reference's
``use_muon`` split (deepspeed/__init__.py:69).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax


def newton_schulz(g: jnp.ndarray, steps: int = 5, eps: float = 1e-7) -> jnp.ndarray:
    """Quintic Newton–Schulz iteration to approximate the orthogonal factor
    of g. Runs in bf16 like the reference implementation."""
    a, b, c = (3.4445, -4.7750, 2.0315)
    x = g.astype(jnp.bfloat16)
    transposed = g.shape[-2] > g.shape[-1]
    if transposed:
        x = x.swapaxes(-2, -1)
    x = x / (jnp.linalg.norm(x, axis=(-2, -1), keepdims=True) + eps)
    for _ in range(steps):
        xxt = x @ x.swapaxes(-2, -1)
        bxxt = b * xxt + c * (xxt @ xxt)
        x = a * x + bxxt @ x
    if transposed:
        x = x.swapaxes(-2, -1)
    return x.astype(g.dtype)


def _is_matrix(x) -> bool:
    return x.ndim == 2 or (x.ndim == 3 and min(x.shape[1:]) > 1)  # stacked layers [L,m,n]


def build_muon(params_cfg: Dict[str, Any]):
    """Muon for ≥2-D params (per stacked layer), AdamW for the rest."""
    from deepspeed_tpu.runtime.optimizers import Optimizer

    momentum = float(params_cfg.get("momentum", 0.95))
    nesterov = bool(params_cfg.get("nesterov", True))
    ns_steps = int(params_cfg.get("ns_steps", 5))
    wd = float(params_cfg.get("weight_decay", 0.0))
    betas = params_cfg.get("betas", (0.9, 0.95))
    eps = float(params_cfg.get("eps", 1e-8))
    adam_tx = optax.scale_by_adam(b1=float(betas[0]), b2=float(betas[1]), eps=eps)

    def init_fn(params):
        mom = jax.tree.map(jnp.zeros_like, params)
        adam_state = adam_tx.init(params)
        return {"momentum": mom, "adam": adam_state}

    def update_fn(grads, state, params, lr):
        new_mom = jax.tree.map(lambda m, g: momentum * m + g, state["momentum"], grads)
        adam_updates, new_adam = adam_tx.update(grads, state["adam"], params)

        def leaf_update(path, p, g, m, au):
            if _is_matrix(p):
                eff = momentum * m + g if nesterov else m
                if eff.ndim == 3:  # stacked layer axis → vmap the orthogonalisation
                    o = jax.vmap(lambda e: newton_schulz(e, ns_steps))(eff)
                    scale = jnp.sqrt(jnp.maximum(1.0, eff.shape[-2] / eff.shape[-1]))
                else:
                    o = newton_schulz(eff, ns_steps)
                    scale = jnp.sqrt(jnp.maximum(1.0, p.shape[-2] / p.shape[-1]))
                upd = o * scale * 0.2  # ref muon lr adjustment
            else:
                upd = au
            new_p = p - lr * upd - lr * wd * p
            return new_p.astype(p.dtype)

        new_params = jax.tree_util.tree_map_with_path(
            leaf_update, params, grads, new_mom, adam_updates)
        return new_params, {"momentum": new_mom, "adam": new_adam}

    return Optimizer(name="muon", init_fn=init_fn, update_fn=update_fn,
                     defaults=dict(momentum=momentum, ns_steps=ns_steps, weight_decay=wd))
