"""Repo-owned Pallas TPU kernels.

These are the hand-written kernels backing the hot ops (training flash
attention, paged decode attention) — the TPU equivalents of the reference's
``csrc/`` CUDA kernels. Everything here degrades to a numerically equivalent
XLA path on non-TPU backends.
"""

from deepspeed_tpu.ops.pallas.flash_mha import flash_mha  # noqa: F401
