"""Autotuner — memory-model-driven search over ZeRO stage & micro-batch.

Analog of ``deepspeed/autotuning/autotuner.py`` (``Autotuner`` :42,
``model_info_profile_run`` :663, ``get_instantiation_memory_required_per_gpu``
:278) and the grid/random/model-based tuners (``autotuning/tuner/``).  The
reference launches whole subprocess experiment jobs; on TPU a trial is just
building an engine and timing a few compiled steps in-process — rendezvous
and relaunch overhead don't exist under single-controller JAX.

Flow (mirrors Autotuner.tune): estimate per-device memory for each ZeRO
stage → prune stages that can't fit → sweep micro-batch sizes (power-of-2
"model-based" ordering) → run short timed trials → pick best throughput.
"""

from __future__ import annotations

import copy
import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger

BYTES_PER_PARAM = {"bf16": 2, "fp16": 2, "fp32": 4}


@dataclass
class ModelInfo:
    """Ref model_info_profile_run: num_params + activation footprint."""
    num_params: int
    hidden_size: int = 0
    num_layers: int = 0
    vocab_size: int = 0


def estimate_memory_per_device(model_info: ModelInfo, zero_stage: int,
                               dp_size: int, micro_batch: int, seq_len: int,
                               dtype: str = "bf16",
                               optimizer_factor: int = 12) -> int:
    """Bytes per device for params+grads+optimizer+activations.

    Ref get_instantiation_memory_required_per_gpu (autotuner.py:278):
    optimizer_factor=12 ≈ fp32 master + two Adam moments + fp16 param/grad
    bookkeeping, partitioned by stage:
      stage 0: all replicated; 1: optimizer/dp; 2: +grads/dp; 3: +params/dp.
    """
    p = model_info.num_params
    b = BYTES_PER_PARAM.get(dtype, 2)
    params_mem = p * b
    grads_mem = p * b
    opt_mem = p * optimizer_factor
    if zero_stage >= 1:
        opt_mem //= dp_size
    if zero_stage >= 2:
        grads_mem //= dp_size
    if zero_stage >= 3:
        params_mem //= dp_size
    # activation estimate: ~ layers * micro_batch * seq * hidden * c bytes
    act = (model_info.num_layers * micro_batch * seq_len
           * max(1, model_info.hidden_size) * 2 * 16)
    return int(params_mem + grads_mem + opt_mem + act)


def generate_tuning_space(model_info: ModelInfo, dp_size: int, seq_len: int,
                          hbm_bytes: int, dtype: str = "bf16",
                          stages=(0, 1, 2, 3),
                          max_micro_batch: int = 64) -> List[Dict[str, Any]]:
    """Candidate (zero_stage, micro_batch) configs that fit the memory
    budget (ref tuning-space templates, autotuning/config_templates/)."""
    space = []
    for stage in stages:
        mb = 1
        while mb <= max_micro_batch:
            need = estimate_memory_per_device(model_info, stage, dp_size, mb,
                                              seq_len, dtype)
            if need <= hbm_bytes:
                space.append({"zero_stage": stage, "micro_batch": mb,
                              "est_bytes": need})
            mb *= 2
    return space


@dataclass
class TrialResult:
    config: Dict[str, Any]
    throughput: float  # samples/sec
    step_seconds: float
    error: Optional[str] = None


class Autotuner:
    """Ref Autotuner (autotuning/autotuner.py:42).

    ``tune`` returns (best_ds_config, results).  ``mode``: "grid" tries the
    whole space; "random" samples ``max_trials``; "model_based" orders by
    estimated memory headroom (bigger batch first) and early-stops after
    ``patience`` non-improving trials.
    """

    def __init__(self, model_cfg, base_config: Dict[str, Any],
                 seq_len: int = 64, mode: str = "model_based",
                 max_trials: int = 8, steps_per_trial: int = 3,
                 hbm_bytes: Optional[int] = None, seed: int = 0):
        self.model_cfg = model_cfg
        self.base_config = base_config
        self.seq_len = seq_len
        self.mode = mode
        self.max_trials = max_trials
        self.steps_per_trial = steps_per_trial
        self.hbm_bytes = hbm_bytes or (16 << 30)
        self.seed = seed
        self.results: List[TrialResult] = []

    # ------------------------------------------------------------------
    def model_info(self) -> ModelInfo:
        from deepspeed_tpu.profiling import get_model_profile

        prof = get_model_profile(self.model_cfg, 1, self.seq_len)
        return ModelInfo(num_params=prof["params"],
                         hidden_size=self.model_cfg.hidden_size,
                         num_layers=self.model_cfg.num_layers,
                         vocab_size=self.model_cfg.vocab_size)

    def _space(self) -> List[Dict[str, Any]]:
        mesh = self.base_config.get("mesh") or {}
        dp = int(mesh.get("data", 1)) * int(mesh.get("expert", 1))
        space = generate_tuning_space(self.model_info(), max(1, dp),
                                      self.seq_len, self.hbm_bytes)
        if self.mode == "random":
            rng = np.random.default_rng(self.seed)
            rng.shuffle(space)
            return space[:self.max_trials]
        if self.mode == "model_based":
            space.sort(key=lambda c: (-c["micro_batch"], -c["zero_stage"]))
            return space[:self.max_trials]
        return space  # grid

    def _trial_config(self, cand: Dict[str, Any]) -> Dict[str, Any]:
        cfg = copy.deepcopy(self.base_config)
        cfg["train_micro_batch_size_per_gpu"] = cand["micro_batch"]
        cfg.setdefault("gradient_accumulation_steps", 1)
        cfg.pop("train_batch_size", None)
        cfg.setdefault("zero_optimization", {})["stage"] = cand["zero_stage"]
        return cfg

    def run_trial(self, cand: Dict[str, Any]) -> TrialResult:
        import deepspeed_tpu as ds
        from deepspeed_tpu.parallel import topology

        cfg = self._trial_config(cand)
        try:
            engine, _, _, _ = ds.initialize(model=self.model_cfg, config=cfg)
            rng = np.random.default_rng(0)
            rows = (engine.train_batch_size_value
                    * 1)
            ids = rng.integers(0, self.model_cfg.vocab_size,
                               size=(rows, self.seq_len + 1), dtype=np.int32)
            batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
            loss = engine.train_batch(batch)  # compile step (excluded)
            float(np.asarray(loss))
            t0 = time.perf_counter()
            for _ in range(self.steps_per_trial):
                loss = engine.train_batch(batch)
            float(np.asarray(loss))  # sync
            dt = (time.perf_counter() - t0) / self.steps_per_trial
            tput = engine.train_batch_size_value / dt
            return TrialResult(cand, throughput=tput, step_seconds=dt)
        except Exception as e:  # OOM / compile failure → score 0
            logger.warning(f"autotuner trial {cand} failed: {e}")
            return TrialResult(cand, throughput=0.0, step_seconds=float("inf"),
                               error=str(e))
        finally:
            topology._GLOBAL_TOPOLOGY = None

    def tune(self, patience: int = 3):
        """→ (best_config_dict, [TrialResult...])."""
        best: Optional[TrialResult] = None
        stale = 0
        for cand in self._space():
            res = self.run_trial(cand)
            self.results.append(res)
            logger.info(f"autotuner: {cand} → "
                        f"{res.throughput:.2f} samples/s")
            if best is None or res.throughput > best.throughput:
                best, stale = res, 0
            else:
                stale += 1
                if self.mode == "model_based" and stale >= patience:
                    break
        if best is None or best.throughput <= 0:
            raise RuntimeError("autotuning found no runnable config")
        return self._trial_config(best.config), self.results
