"""Autotuning (ref deepspeed/autotuning/)."""

from deepspeed_tpu.autotuning.autotuner import (Autotuner, ModelInfo,
                                                TrialResult,
                                                estimate_memory_per_device,
                                                generate_tuning_space)

__all__ = ["Autotuner", "ModelInfo", "TrialResult",
           "estimate_memory_per_device", "generate_tuning_space"]
