from deepspeed_tpu.module_inject.auto_tp import AutoTP, tp_model_init
from deepspeed_tpu.module_inject.layers import (column_parallel_linear,
                                                linear_allreduce, linear_layer,
                                                row_parallel_linear,
                                                vocab_parallel_logits)

__all__ = ["AutoTP", "tp_model_init", "column_parallel_linear",
           "row_parallel_linear", "linear_allreduce", "linear_layer",
           "vocab_parallel_logits"]
