"""Tensor-parallel linear layer functions.

TPU-native analog of ``module_inject/layers.py`` (``LinearAllreduce``:388,
``LinearLayer``:465, ``ColumnParallel``:125, ``RowParallel``:64).  The
reference wraps nn.Linear with eager NCCL calls; here each is a pure
function used inside ``shard_map`` (explicit mode, tests/bench) — under
plain ``jit`` + sharded weights the same collectives appear automatically
via AutoTP's PartitionSpecs, so models never need to call these directly.
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.parallel.topology import TENSOR_AXIS


def column_parallel_linear(x, w_shard, b_shard=None, *,
                           gather_output: bool = False,
                           axis: str = TENSOR_AXIS):
    """Y_local = X @ W[:, shard] (ref ColumnParallel, layers.py:125).

    Output is head/ffn-sharded; with ``gather_output`` the shards are
    all-gathered (rarely wanted — keep activations sharded between the
    column→row pair, the Megatron pattern).
    """
    y = x @ w_shard
    if b_shard is not None:
        y = y + b_shard
    if gather_output:
        y = lax.all_gather(y, axis, axis=y.ndim - 1, tiled=True)
    return y


def row_parallel_linear(x_shard, w_shard, b=None, *, axis: str = TENSOR_AXIS):
    """Y = psum_tp(X[:, shard] @ W[shard, :]) (ref RowParallel, layers.py:64;
    LinearAllreduce:388). Bias is added AFTER the reduce, once."""
    y = lax.psum(x_shard @ w_shard, axis)
    if b is not None:
        y = y + b
    return y


def linear_allreduce(x_shard, w_shard, b=None, *, axis: str = TENSOR_AXIS):
    """Alias matching the reference's class name (LinearAllreduce:388)."""
    return row_parallel_linear(x_shard, w_shard, b, axis=axis)


def linear_layer(x, w_shard, b_shard=None, *, axis: str = TENSOR_AXIS):
    """Alias matching the reference's LinearLayer (column split, :465)."""
    return column_parallel_linear(x, w_shard, b_shard, axis=axis)


def vocab_parallel_logits(x, embed_shard, *, axis: str = TENSOR_AXIS):
    """lm-head over a vocab-sharded embedding: local partial logits are
    all-gathered on the vocab dim (ref VocabParallelEmbedding path)."""
    logits_local = x @ embed_shard.T
    return lax.all_gather(logits_local, axis, axis=logits_local.ndim - 1,
                          tiled=True)
