"""AutoTP — automatic tensor-parallel sharding of arbitrary param trees.

TPU-native analog of the reference's AutoTP (``module_inject/auto_tp.py:193``)
and ``deepspeed.tp_model_init`` (deepspeed/__init__.py:380).  The reference
walks an nn.Module graph, classifies each Linear as all-reduce (row
parallel) or split (column parallel) by name/policy, and swaps in
``LinearAllreduce``/``LinearLayer`` wrappers (module_inject/layers.py:388/465).

Here a model is a param pytree; AutoTP classifies each weight by its *path*
(the same layer-name heuristics the reference's ``tp_parser`` applies to HF
module names) and emits a ``PartitionSpec`` tree.  ``jax.device_put`` +
``jit`` then realise Megatron-style TP: XLA inserts the row-parallel output
all-reduce that ``LinearAllreduce`` performs eagerly in the reference.

Classification (mirroring the reference's policy lists):
* row-parallel (shard INPUT dim, output psum): attention output and MLP
  down projections — ``o_proj, out_proj, dense (in attention), down_proj,
  dense_4h_to_h, wo, w2, fc2, c_proj``.
* column-parallel (shard OUTPUT dim): q/k/v/gate/up and fused projections —
  everything else 2-D that is divisible.
* replicated: norms, small vectors, anything indivisible (with a warning —
  ref ``tp_grain_size`` rounding).
* embeddings: vocab dim sharded (ref VocabParallelEmbedding path).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.sharding import path_str
from deepspeed_tpu.parallel.topology import TENSOR_AXIS, MeshTopology, get_topology
from deepspeed_tpu.utils.logging import logger

# name fragments → row parallel (output needs the allreduce). Mirrors the
# reference's all-reduce linear lists (auto_tp.py tp_parser / policy files).
ROW_PARALLEL_PATTERNS = [
    r"o_proj$", r"out_proj$", r"down_proj$", r"dense_4h_to_h$", r"c_proj$",
    r"attn/wo$", r"attention/wo$", r"mlp/wo$", r"moe/wo$", r"/w2$", r"fc2$",
    r"attention/dense$", r"self_attention/dense$", r"wo$",
]
# name fragments → column parallel explicitly (fused qkv etc.)
COLUMN_PARALLEL_PATTERNS = [
    r"q_proj$", r"k_proj$", r"v_proj$", r"gate_proj$", r"up_proj$",
    r"query_key_value$", r"c_attn$", r"dense_h_to_4h$", r"fc1$",
    r"attn/w[qkv]$", r"mlp/w[ig]$", r"moe/w[ig]$", r"/w[13]$",
    r"lm_head$", r"embed_out$",
]
EMBEDDING_PATTERNS = [r"embed[^/]*/tokens$", r"embed_tokens", r"wte$", r"word_embeddings$"]


class AutoTP:
    """Classify params and emit TP PartitionSpecs (ref AutoTP class)."""

    def __init__(self, topology: Optional[MeshTopology] = None,
                 tp_grain_size: int = 1):
        self.topo = topology or get_topology()
        if self.topo is None:
            raise RuntimeError("AutoTP needs an initialized topology "
                               "(call deepspeed_tpu.comm.init_distributed)")
        self.tp_size = self.topo.tp_size
        self.tp_grain_size = tp_grain_size
        self._row = [re.compile(p) for p in ROW_PARALLEL_PATTERNS]
        self._col = [re.compile(p) for p in COLUMN_PARALLEL_PATTERNS]
        self._emb = [re.compile(p) for p in EMBEDDING_PATTERNS]

    # ------------------------------------------------------------------
    def classify(self, path: str, shape: Tuple[int, ...]) -> str:
        """→ "row" | "column" | "embedding" | "replicate"."""
        if any(p.search(path) for p in self._emb):
            return "embedding"
        # Biases follow their matrix: column-parallel biases shard their
        # feature (last) dim, row-parallel biases replicate (they are added
        # once, after the psum). Detected by name, not ndim — stacked
        # per-layer biases are [L, dim] and must still classify as biases.
        leaf = path.rsplit("/", 1)[-1]
        if leaf == "bias" or (len(leaf) == 2 and leaf[0] == "b"):
            parent = path[:-(len(leaf) + 1)] if "/" in path else ""
            cands = [parent]
            if leaf != "bias":
                cands.append(f"{parent}/w{leaf[1:]}" if parent else f"w{leaf[1:]}")
            if any(p.search(c) for p in self._row for c in cands):
                return "replicate"
            if any(p.search(c) for p in self._col for c in cands):
                return "column_bias"
            return "replicate"  # norm biases & unknowns: safe under GSPMD
        if len(shape) < 2:
            return "replicate"
        if any(p.search(path) for p in self._row):
            return "row"
        if any(p.search(path) for p in self._col):
            return "column"
        return "column"  # default Linear → split output (ref LinearLayer)

    def _divisible(self, n: int) -> bool:
        return (n % (self.tp_size * max(1, self.tp_grain_size))) == 0 or \
            (n % self.tp_size == 0 and self.tp_grain_size <= 1)

    def spec_for(self, path: str, shape: Tuple[int, ...]) -> P:
        if self.tp_size <= 1:
            return P()
        kind = self.classify(path, shape)
        ndim = len(shape)
        spec: List[Any] = [None] * ndim
        if kind == "replicate":
            return P()
        if kind == "embedding":
            # vocab (dim 0 of [V, H]) sharded; leading stacked dims skipped
            dim = ndim - 2
            if self._divisible(shape[dim]):
                spec[dim] = TENSOR_AXIS
            return P(*spec)
        if kind == "column_bias":
            if self._divisible(shape[-1]):
                spec[-1] = TENSOR_AXIS
            return P(*spec)
        if kind == "row":
            dim = ndim - 2  # input dim of [..., in, out]
            if self._divisible(shape[dim]):
                spec[dim] = TENSOR_AXIS
            else:
                logger.warning(f"AutoTP: {path} dim {shape[dim]} not divisible "
                               f"by tp={self.tp_size}; replicating")
            return P(*spec)
        # column
        if self._divisible(shape[-1]):
            spec[-1] = TENSOR_AXIS
        else:
            logger.warning(f"AutoTP: {path} dim {shape[-1]} not divisible "
                           f"by tp={self.tp_size}; replicating")
        return P(*spec)

    # ------------------------------------------------------------------
    def tree_specs(self, params: Any):
        def leaf(path, x):
            return self.spec_for(path_str(path), np.shape(x))

        return jax.tree_util.tree_map_with_path(leaf, params)

    def tree_shardings(self, params: Any):
        return jax.tree.map(lambda s: NamedSharding(self.topo.mesh, s),
                            self.tree_specs(params),
                            is_leaf=lambda x: isinstance(x, P))


def tp_model_init(params: Any, topology: Optional[MeshTopology] = None,
                  tp_grain_size: int = 1) -> Any:
    """Shard a param tree tensor-parallel over the mesh "tensor" axis.

    Ref: ``deepspeed.tp_model_init`` (deepspeed/__init__.py:380) +
    ``TpTrainingManager`` (runtime/tensor_parallel/tp_manager.py) — AutoTP
    for *training*.  Returns the resharded tree; subsequent jitted steps
    see TP-sharded weights and XLA inserts the Megatron collectives.
    """
    tp = AutoTP(topology, tp_grain_size=tp_grain_size)
    return jax.device_put(params, tp.tree_shardings(params))
